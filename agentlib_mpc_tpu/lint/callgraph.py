"""Package index + jit-reachability call graph (pure ``ast``).

The jit-hygiene passes need to know which functions execute *under a JAX
trace*. That set is built here from intra-package call edges:

* **Trace roots** — functions decorated ``@jax.jit`` /
  ``@partial(jax.jit, ...)``; functions passed by name to ``jax.jit`` /
  ``jax.vmap`` / ``jax.grad`` / ``jax.lax.while_loop`` / ``scan`` /
  ``cond`` / ``fori_loop`` etc.; and, for the build-then-jit idiom
  (``jax.jit(self._build_step())``), the functions *returned by* the
  called builder.
* **Propagation** — a call inside a reachable function marks its callee
  reachable when the callee resolves inside the package: lexically nested
  defs and sibling closures, module top-level functions, ``self.method``
  within the class, imported package functions
  (``from agentlib_mpc_tpu.ops.admm import consensus_update``), module
  aliases (``from agentlib_mpc_tpu.ops import admm as admm_ops``), and —
  as a deliberate over-approximation — ``<expr>.method()`` calls whose
  method name is defined by at most :data:`METHOD_FANOUT_CAP` classes
  package-wide (the ``ocp.trajectories(...)`` pattern, where the receiver
  type is not statically known).

Resolution is last-definition-wins (Python semantics), taint-free and
flow-insensitive; cycles are fine (BFS). External roots (``jax``,
``numpy``, stdlib) never resolve into the package.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque

#: jax transforms whose function-valued arguments trace under jit (or are
#: themselves tracing): positions are which args are trace targets; None
#: means "every argument"
_TRACING_CALLS = {
    "jit": None,
    "vmap": (0,),
    "pmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "jacfwd": (0,),
    "jacrev": (0,),
    "hessian": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "custom_jvp": (0,),
    "custom_vjp": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "scan": (0,),
    "cond": (1, 2),
    "switch": None,
    "associative_scan": (0,),
}

#: method-name fan-out cap for receiver-unknown attribute calls
METHOD_FANOUT_CAP = 4

#: generic method names excluded from receiver-unknown fan-out — these
#: collide with dict/list/socket/threading vocabulary and would drag
#: runtime classes into the "jit-reachable" set on every ``d.pop(...)``
_FANOUT_SKIP = {
    "pop", "get", "put", "update", "append", "clear", "copy", "items",
    "keys", "values", "send", "broadcast", "reset", "close", "read",
    "write", "run", "start", "stop", "join", "set", "wait", "notify",
    "inc", "observe", "record", "add", "remove", "extend", "insert",
    "setdefault", "publish", "connect", "subscribe",
}

#: import roots that never resolve into the package
_EXTERNAL_ROOTS = {
    "jax", "jnp", "np", "numpy", "lax", "functools", "math", "time",
    "datetime", "os", "sys", "itertools", "collections", "logging",
    "threading", "json", "struct", "socket", "random", "re", "dataclasses",
}


@dataclasses.dataclass
class FunctionInfo:
    module: str                     # package-relative posix path
    qualname: str                   # dotted, no <locals>
    node: ast.AST                   # FunctionDef/AsyncFunctionDef/Lambda
    parent: "FunctionInfo | None"
    cls: "str | None"               # innermost enclosing class name
    is_root: bool = False
    #: names of nested defs, for lexical resolution
    nested: "dict[str, FunctionInfo]" = dataclasses.field(
        default_factory=dict)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


class ModuleInfo:
    def __init__(self, path: str, tree: ast.Module, source: str):
        self.path = path
        self.tree = tree
        self.source = source
        self.functions: list[FunctionInfo] = []
        #: top-level function name -> info (last def wins)
        self.top_level: dict[str, FunctionInfo] = {}
        #: (class name, method name) -> info
        self.methods: dict[tuple, FunctionInfo] = {}
        #: import alias -> package-relative module path ("ops/admm.py")
        self.module_aliases: dict[str, str] = {}
        #: imported name -> (module path, remote name)
        self.imported: dict[str, tuple] = {}
        #: module-level simple aliases: name -> name
        self.name_aliases: dict[str, str] = {}
        #: names bound from the jax family (jnp, lax, jax, ...)
        self.jax_names: set[str] = set()
        #: names bound from numpy
        self.numpy_names: set[str] = set()


def _mod_to_path(dotted: str, package: str) -> "str | None":
    """'agentlib_mpc_tpu.ops.admm' -> 'ops/admm.py' (None if external)."""
    if dotted == package:
        return "__init__.py"
    prefix = package + "."
    if not dotted.startswith(prefix):
        return None
    return dotted[len(prefix):].replace(".", "/") + ".py"


class _Collector(ast.NodeVisitor):
    """One pass over a module: functions, imports, trace-root marks."""

    def __init__(self, info: ModuleInfo, package: str):
        self.info = info
        self.package = package
        self._func_stack: list[FunctionInfo] = []
        self._class_stack: list[str] = []
        #: deferred root requests: (kind, payload)
        self.root_requests: list[tuple] = []

    # -- imports ---------------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            if alias.name.split(".")[0] == "jax":
                self.info.jax_names.add(name)
            if alias.name.split(".")[0] == "numpy":
                self.info.numpy_names.add(name)
            path = _mod_to_path(alias.name, self.package)
            if path is not None:
                self.info.module_aliases[alias.asname or alias.name] = path

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        if node.level:      # relative import — resolve against the package
            mod = self.package + ("." + mod if mod else "")
        root = mod.split(".")[0]
        for alias in node.names:
            name = alias.asname or alias.name
            if root == "jax":
                self.info.jax_names.add(name)
            if root == "numpy":
                self.info.numpy_names.add(name)
            sub = _mod_to_path(f"{mod}.{alias.name}", self.package)
            if sub is not None:
                # ``from agentlib_mpc_tpu.ops import admm`` — module alias
                self.info.module_aliases[name] = sub
            path = _mod_to_path(mod, self.package)
            if path is not None:
                self.info.imported[name] = (path, alias.name)

    # -- scopes ----------------------------------------------------------------

    def _qualname(self, name: str) -> str:
        parts = []
        if self._func_stack:
            parts.append(self._func_stack[-1].qualname)
        elif self._class_stack:
            parts.append(".".join(self._class_stack))
        parts.append(name)
        return ".".join(parts)

    def _add_function(self, name: str, node) -> FunctionInfo:
        parent = self._func_stack[-1] if self._func_stack else None
        qual = self._qualname(name)
        # duplicate defs (the decorated/wrapper shadow pattern): keep both
        # infos, disambiguate the qualname of the earlier one is NOT needed
        # — last-wins resolution matches Python
        fn = FunctionInfo(module=self.info.path, qualname=qual, node=node,
                          parent=parent,
                          cls=self._class_stack[-1] if self._class_stack
                          else None)
        self.info.functions.append(fn)
        if parent is not None:
            parent.nested[name] = fn
        elif self._class_stack:
            self.info.methods[(self._class_stack[-1], name)] = fn
        else:
            self.info.top_level[name] = fn
        return fn

    def _visit_func(self, node, name: str) -> None:
        fn = self._add_function(name, node)
        for dec in getattr(node, "decorator_list", []):
            if self._is_tracing_expr(dec):
                fn.is_root = True
        self._func_stack.append(fn)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self._func_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node, node.name)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_func(node, node.name)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        fn = self._add_function(f"<lambda:{node.lineno}>", node)
        self._func_stack.append(fn)
        self.visit(node.body)
        self._func_stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self._class_stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        # name = lambda ...: treat as a def under that name
        if isinstance(node.value, ast.Lambda) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            fn = self._add_function(node.targets[0].id, node.value)
            self._func_stack.append(fn)
            self.visit(node.value.body)
            self._func_stack.pop()
            return
        # simple alias: name = other_name (module or function scope)
        if isinstance(node.value, ast.Name) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if not self._func_stack and not self._class_stack:
                self.info.name_aliases[node.targets[0].id] = node.value.id
        self.generic_visit(node)

    # -- trace-root detection --------------------------------------------------

    def _jax_attr_name(self, func: ast.AST) -> "str | None":
        """Terminal attribute name of a call into the jax family
        (``jax.jit`` -> 'jit', ``jax.lax.while_loop`` -> 'while_loop',
        bare ``jit``/``vmap`` if imported from jax), else None."""
        if isinstance(func, ast.Attribute):
            root = func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and \
                    root.id in self.info.jax_names | {"jax", "lax"}:
                return func.attr
            return None
        if isinstance(func, ast.Name) and func.id in self.info.jax_names:
            return func.id
        return None

    def _is_tracing_expr(self, expr: ast.AST) -> bool:
        """Decorator forms: jax.jit / jit / partial(jax.jit, ...) /
        jax.vmap / functools.partial(jax.jit, ...)."""
        if self._jax_attr_name(expr) in _TRACING_CALLS:
            return True
        if isinstance(expr, ast.Call):
            fname = expr.func
            is_partial = (isinstance(fname, ast.Name)
                          and fname.id == "partial") or (
                isinstance(fname, ast.Attribute)
                and fname.attr == "partial")
            if is_partial and expr.args:
                return self._jax_attr_name(expr.args[0]) in _TRACING_CALLS
            # jax.jit(fn, static_argnums=...) used as decorator factory
            return self._jax_attr_name(expr.func) in _TRACING_CALLS
        return False

    def visit_Call(self, node: ast.Call) -> None:
        name = self._jax_attr_name(node.func)
        if name in _TRACING_CALLS:
            positions = _TRACING_CALLS[name]
            args = node.args if positions is None else [
                node.args[i] for i in positions if i < len(node.args)]
            scope = self._func_stack[-1] if self._func_stack else None
            for arg in args:
                self.root_requests.append((scope, arg))
        self.generic_visit(node)


class PackageIndex:
    """All modules of one package + the jit-reachable set."""

    def __init__(self, package: str = "agentlib_mpc_tpu"):
        self.package = package
        self.modules: dict[str, ModuleInfo] = {}
        #: method name -> [FunctionInfo] across every class in the package
        self.methods_by_name: dict[str, list] = {}
        self._root_requests: list[tuple] = []

    # -- construction ----------------------------------------------------------

    def add_module(self, path: str, source: str) -> "ModuleInfo | None":
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return None
        info = ModuleInfo(path, tree, source)
        collector = _Collector(info, self.package)
        collector.visit(tree)
        self.modules[path] = info
        for fn in info.functions:
            if fn.cls is not None and fn.parent is None:
                self.methods_by_name.setdefault(fn.name, []).append(fn)
        self._root_requests.extend(
            (info, scope, arg) for scope, arg in collector.root_requests)
        return info

    # -- resolution ------------------------------------------------------------

    def resolve_name(self, info: ModuleInfo, scope: "FunctionInfo | None",
                     name: str, _depth: int = 0):
        """Resolve a bare name to a FunctionInfo: lexical nested defs,
        module top level, imports, simple aliases."""
        if _depth > 4:
            return None
        s = scope
        while s is not None:
            if name in s.nested:
                return s.nested[name]
            # sibling closures: the parent's nested defs are visible
            s = s.parent
        if name in info.top_level:
            return info.top_level[name]
        if name in info.name_aliases and info.name_aliases[name] != name:
            return self.resolve_name(info, scope, info.name_aliases[name],
                                     _depth + 1)
        if name in info.imported:
            mod_path, remote = info.imported[name]
            target = self.modules.get(mod_path)
            if target is not None:
                if remote in target.top_level:
                    return target.top_level[remote]
                # ``from pkg import name`` re-exported via __init__
                if remote in target.imported:
                    m2, r2 = target.imported[remote]
                    t2 = self.modules.get(m2)
                    if t2 is not None and r2 in t2.top_level:
                        return t2.top_level[r2]
        return None

    def resolve_call(self, info: ModuleInfo, scope: "FunctionInfo | None",
                     func: ast.AST) -> list:
        """FunctionInfos a call expression may reach (possibly empty)."""
        if isinstance(func, ast.Name):
            target = self.resolve_name(info, scope, func.id)
            return [target] if target is not None else []
        if isinstance(func, ast.Attribute):
            base = func.value
            # self.method() / cls.method()
            if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                    and scope is not None:
                s, cls = scope, None
                while s is not None and cls is None:
                    cls, s = s.cls, s.parent
                if cls is not None:
                    m = info.methods.get((cls, func.attr))
                    if m is not None:
                        return [m]
            # module_alias.func()
            if isinstance(base, ast.Name):
                if base.id in _EXTERNAL_ROOTS or \
                        base.id in info.jax_names or \
                        base.id in info.numpy_names:
                    return []
                mod_path = info.module_aliases.get(base.id)
                if mod_path is not None:
                    target = self.modules.get(mod_path)
                    if target is not None and \
                            func.attr in target.top_level:
                        return [target.top_level[func.attr]]
            # receiver of unknown type: fan out across same-named methods
            # when the name is package-rare (the ocp.bounds(...) pattern)
            if func.attr not in _FANOUT_SKIP:
                candidates = self.methods_by_name.get(func.attr, [])
                if 0 < len(candidates) <= METHOD_FANOUT_CAP:
                    return list(candidates)
        return []

    # -- reachability ----------------------------------------------------------

    def _returned_functions(self, fn: FunctionInfo) -> list:
        """Nested functions returned by ``fn`` (the build-then-jit idiom)."""
        out = []
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Return) and \
                    isinstance(node.value, ast.Name):
                if node.value.id in fn.nested:
                    out.append(fn.nested[node.value.id])
        return out

    def compute_reachable(self) -> "set[int]":
        """ids of FunctionInfos reachable from any trace root."""
        roots: list[FunctionInfo] = []
        for info in self.modules.values():
            roots.extend(f for f in info.functions if f.is_root)
        # deferred root requests: arguments of tracing calls
        for info, scope, arg in self._root_requests:
            if isinstance(arg, (ast.Name,)):
                t = self.resolve_name(info, scope, arg.id)
                if t is not None:
                    roots.append(t)
            elif isinstance(arg, ast.Lambda):
                # the collector registered the lambda as a nested def
                for fn in info.functions:
                    if fn.node is arg:
                        roots.append(fn)
            elif isinstance(arg, ast.Call):
                # jax.jit(self._build_step()) — root the functions the
                # builder returns
                for builder in self.resolve_call(
                        info, scope, arg.func):
                    roots.extend(self._returned_functions(builder))

        reachable: set[int] = set()
        by_id = {}
        queue = deque()
        for fn in roots:
            if id(fn) not in reachable:
                reachable.add(id(fn))
                by_id[id(fn)] = fn
                queue.append(fn)
        while queue:
            fn = queue.popleft()
            info = self.modules[fn.module]
            for node in ast.walk(fn.node):
                targets = []
                if isinstance(node, ast.Call):
                    targets = self.resolve_call(info, fn, node.func)
                # a nested def that is itself decorated with a tracer
                # transform inside a reachable builder is a root already;
                # plain nested defs only join via calls/returns
                for t in targets:
                    if id(t) not in reachable:
                        reachable.add(id(t))
                        by_id[id(t)] = t
                        queue.append(t)
        self._reachable_infos = [by_id[i] for i in reachable]
        return reachable

    def reachable_functions(self) -> list:
        if not hasattr(self, "_reachable_infos"):
            self.compute_reachable()
        return list(self._reachable_infos)
