"""ML-model simulator: drives a hybrid NARX model, hot-swaps surrogates.

Counterpart of the reference's ``MLModelSimulator``
(``modules/ml_model_simulator.py:51-71``: an agentlib Simulator subclass
whose ``_update_ml_model_callback`` receives serialized models over the
broker and rebuilds the CasADi predict function while keeping past
values). Here the history pytree carries the NARX state across steps and a
received model document becomes new predictor parameters — same-shape
swaps keep the compiled step function.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu.backends.ml_backend import load_ml_model
from agentlib_mpc_tpu.ml.serialized import load_serialized_model
from agentlib_mpc_tpu.runtime.module import BaseModule, register_module
from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source

logger = logging.getLogger(__name__)


@register_module("ml_simulator")
class MLSimulator(BaseModule):
    """Plant stand-in for learned dynamics."""

    variable_groups = ("inputs", "outputs", "states", "parameters")
    shared_groups = ("outputs", "states")

    def __init__(self, config: dict, agent):
        super().__init__(config, agent)
        self.t_sample = float(config.get("t_sample", 1.0))
        self.model = load_ml_model(config["model"], dt=self.t_sample)
        self.ml_model_variable = config.get("ml_model_variable", "MLModel")
        init = {}
        for var in self.variables_in_group("states"):
            if var.value is not None:
                init[var.name] = float(var.value)
        self.hist = self.model.init_history(init)
        self._rows: list[dict] = []
        self._build_step()

    def _build_step(self) -> None:
        model = self.model

        @jax.jit
        def sim_step(hist, p, ml_params):
            nxt, outs = model.ml_step(hist, p, ml_params=ml_params)
            hist_next = model.advance_history(hist, dict(nxt))
            return hist_next, nxt, outs

        self._sim_step = sim_step
        # compile at construction (real-time schedules must not pause on
        # the first step); hot-swaps with matching shapes hit the jit cache
        out = sim_step(self.hist,
                       jnp.asarray(model.default_vector("parameters")),
                       model.ml_params)
        jax.block_until_ready(out)

    def register_callbacks(self) -> None:
        super().register_callbacks()
        self.agent.data_broker.register_callback(
            self.ml_model_variable, Source(), self._update_ml_model_callback)

    def _update_ml_model_callback(self, incoming: AgentVariable) -> None:
        """Hot-swap a retrained surrogate (reference
        ``_update_ml_model_callback``, ``ml_model_simulator.py:51-71``)."""
        try:
            serialized = load_serialized_model(incoming.value)
            self.model.update_ml_models(serialized)
            self._build_step()  # cheap; jit cache hits when shapes match
            self.logger.info("hot-swapped ML model for %s at t=%s",
                             list(serialized.output), self.env.now)
        except (ValueError, KeyError, TypeError) as exc:
            self.logger.error("rejected ML model update: %s", exc)

    def process(self):
        while True:
            updates = self._current_inputs()
            yield self.t_sample
            self.do_step(updates)

    def _current_inputs(self) -> dict:
        updates = {}
        for name in self.model.input_names:
            if name in self.vars and self.vars[name].value is not None:
                updates[name] = float(self.vars[name].value)
        return updates

    def do_step(self, updates: dict | None = None) -> None:
        model = self.model
        if updates is None:
            updates = self._current_inputs()
        hist = dict(self.hist)
        for n, v in updates.items():
            if n in hist:
                hist[n] = hist[n].at[0].set(v)
        p = np.array(model.default_vector("parameters"))
        for i, name in enumerate(model.parameter_names):
            if name in self.vars and self.vars[name].value is not None:
                p[i] = float(self.vars[name].value)
        hist_next, nxt, outs = self._sim_step(hist, jnp.asarray(p),
                                              model.ml_params)
        self.hist = hist_next
        row = {"time": float(self.env.now)}
        for n, v in updates.items():
            row[n] = v
        for n in (*nxt, *outs):
            val = float((nxt.get(n) if n in nxt else outs[n]))
            row[n] = val
            if n in self.vars:
                self.set(n, val)
        self._rows.append(row)

    def results(self):
        import pandas as pd

        if not self._rows:
            return None
        return pd.DataFrame(self._rows).set_index("time")

    def cleanup_results(self) -> None:
        self._rows.clear()
