"""Convex-QP fast path: structure detection + Mehrotra predictor-corrector.

The reference's solver menu routes QP-structured problems (linear model,
quadratic objective — the standard linear-MPC case) to dedicated QP codes:
qpoases / osqp / proxqp (``data_structures/casadi_utils.py:52-61,127-161``).
The general interior-point NLP solver (:mod:`ops.solver`) subsumes them
functionally, but pays for generality every iteration: a Lagrangian-Hessian
evaluation, a batched line-search model sweep, and one value+Jacobian pass.

For an LQ program all of that is constant structure:

    min ½ wᵀH w + cᵀw   s.t.  A w + g₀ = 0,  C w + h₀ ≥ 0,  lb ≤ w ≤ ub

so this module

- certifies the structure ONCE at setup — primarily by the *sound*
  jaxpr-level proof :func:`agentlib_mpc_tpu.lint.jaxpr.certify_lq`
  (a polynomial-degree lattice over the traced functions, valid for
  ALL theta), with :func:`is_lq` (probabilistic probe: constant
  Hessian/Jacobians at random points, exact quadratic model match)
  demoted to a cross-check and to the fallback for problems the
  interpreter cannot see through (opaque primitives) — and
- solves with :func:`solve_qp`, a Mehrotra predictor-corrector QP IPM
  that extracts (H, c, A, C) per solve with three AD passes, then runs
  pure linear algebra: no model evaluations, no line search (convex ⇒
  fraction-to-boundary steps suffice), one KKT factorization + two
  back-substitutions per iteration. The KKT system is the same reduced
  symmetric quasi-definite form as the NLP solver's, so it reuses the
  identical factorization kernels (lanes-batched Pallas LDLᵀ on TPU,
  pivoted LU elsewhere, ``ops/kkt.py``).

``solve_qp`` mirrors ``solve_nlp``'s signature and ``SolverResult``
contract (same dual conventions, scaling, and stats), so backends swap it
in without touching warm-start plumbing; on a non-LQ problem it converges
to the wrong point — gate it behind :func:`is_lq` (the backends do).
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp

from agentlib_mpc_tpu.ops import stagejac as sjac
from agentlib_mpc_tpu.ops import stagewise as stage_ops
from agentlib_mpc_tpu.ops.solver import (
    JAC_PATHS,
    KKT_PATHS,
    PRECISION_PATHS,
    NLPFunctions,
    SolverOptions,
    SolverResult,
    SolverStats,
    _factor_kkt,
    _max_step,
    _resolve_jacobian,
    _resolve_kkt,
    _resolve_method,
    _resolve_precision,
    _row_scaling,
    _safe_max,
)

__all__ = ["is_lq", "resolve_qp_routing", "solve_qp"]


def resolve_qp_routing(mode: str, probe, logger=None,
                       label: str = "problem", certifier=None) -> bool:
    """Shared auto/on/off routing decision for every QP-fast-path seam
    (central backend, MHE backend, ADMM backend, MINLP via the central
    seam, fused groups — one definition so mode validation, certificate
    and probe semantics cannot drift).

    ``certifier`` is a zero-arg callable returning an
    :class:`agentlib_mpc_tpu.lint.jaxpr.LQCertificate`; ``probe`` a
    zero-arg callable returning the :func:`is_lq` verdict. Neither runs
    except for ``"auto"``. Routing authority (the VERDICT r5 medium —
    a theta-gated nonlinearity falsely certified by the default-theta
    probe — is closed here):

    * certificate ``"lq"`` — proof for all theta; the probe runs as a
      cross-check only (a probe refutation is concrete evidence of an
      interpreter bug, so it wins and the fast path stays off);
    * certificate ``"not_lq"`` — never route; the probe is skipped (it
      can only produce the false positive the certificate just ruled
      out);
    * certificate ``"unknown"`` (opaque primitives) or no certifier —
      fall back to the sampled probe, loudly.
    """
    if mode == "on":
        return True
    if mode == "off":
        return False
    if mode != "auto":
        raise ValueError(
            f"qp_fast_path must be 'auto', 'on' or 'off', got {mode!r}")
    cert = None
    if certifier is not None:
        try:
            cert = certifier()
        except Exception:  # noqa: BLE001 — certification must never
            cert = None    # block a backend setup; the probe still routes
            if logger is not None:
                logger.warning(
                    "LQ certification raised for %s; falling back to the "
                    "sampled probe", label, exc_info=True)
    if cert is not None and cert.status == "not_lq":
        if logger is not None:
            # INFO like the symmetric "proved" line: skipping the probe
            # and forcing the NLP path is a consequential routing
            # decision operators grep for (verify recipe)
            logger.info(
                "LQ structure refuted for %s (%s): staying on the "
                "general NLP path", label, cert.describe())
        return False
    if cert is not None and cert.status == "lq":
        if not bool(probe()):
            if logger is not None:
                logger.warning(
                    "LQ certificate and sampled probe DISAGREE for %s "
                    "(%s, probe says non-LQ) — not routing to the QP "
                    "fast path; please report this as a certifier bug",
                    label, cert.describe())
            return False
        if logger is not None:
            logger.info("LQ structure proved for %s (%s; probe "
                        "cross-check passed): dispatching to the "
                        "Mehrotra QP fast path", label, cert.describe())
        return True
    use = bool(probe())
    if cert is not None and logger is not None:
        logger.warning(
            "LQ certificate inconclusive for %s (%s): routing on the "
            "sampled probe (%s) — the probe only sees default-theta "
            "structure", label, cert.describe(),
            "LQ" if use else "non-LQ")
    elif use and logger is not None:
        logger.info("LQ structure certified for %s: dispatching to the "
                    "Mehrotra QP fast path", label)
    return use


def is_lq(nlp: NLPFunctions, theta, n: int, *, seed: int = 0,
          n_probes: int = 2, rtol: float = 1e-5, atol: float = 1e-7) -> bool:
    """Probabilistic certificate that the NLP is linear-quadratic in ``w``.

    Checks, at ``n_probes`` pairs of random points, with a random probe
    direction: the objective's Hessian-vector product is constant, the
    g/h vector-Jacobian products are constant, and the objective equals
    its own second-order Taylor model exactly between the two points —
    all O(1) model evaluations (no full Hessians/Jacobians: this runs
    eagerly at every backend/engine build, so it must be cheap).
    Polynomials of higher degree fail at random points with probability
    1; transcendental nonlinearities fail outright. Structure in ``w``
    does not change with ``theta``."""
    key = jax.random.PRNGKey(seed)
    f = lambda w: nlp.f(w, theta)
    g = lambda w: nlp.g(w, theta)
    h = lambda w: nlp.h(w, theta)
    probe0 = g(jnp.zeros((n,)))
    m_e = probe0.shape[0]
    m_h = h(jnp.zeros((n,))).shape[0]
    # dtype-aware tolerances: in f32 (the TPU regime) an exactly-quadratic
    # function still shows O(eps·scale) differences between its HVPs at
    # two points; a bilinear/nonlinear term shows O(1) — keep the gate
    # far above the former, far below the latter
    eps = float(jnp.finfo(jnp.zeros(0).dtype).eps)
    rtol = max(rtol, 2e4 * eps)
    atol = max(atol, 1e3 * eps)

    def close(a, b):
        return bool(jnp.all(jnp.isfinite(a)) and jnp.all(jnp.isfinite(b))
                    and jnp.allclose(a, b, rtol=rtol, atol=atol))

    def hvp(w, v):
        return jax.grad(lambda ww: jax.grad(f)(ww) @ v)(w)

    for _ in range(n_probes):
        key, k1, k2, k3, k4 = jax.random.split(key, 5)
        w1 = jax.random.normal(k1, (n,))
        w2 = 2.0 * jax.random.normal(k2, (n,)) + 0.5
        d = w2 - w1
        # Hessian constancy along d AND a random direction v
        v = jax.random.normal(k3, (n,))
        if not (close(hvp(w1, d), hvp(w2, d))
                and close(hvp(w1, v), hvp(w2, v))):
            return False
        # exact quadratic model between the two probe points
        df = f(w2) - f(w1)
        model = jax.grad(f)(w1) @ d + 0.5 * d @ hvp(w1, d)
        scale = jnp.maximum(jnp.abs(df), 1.0)
        if not close(df / scale, model / scale):
            return False
        # constraint affineness: constant VJP against a random cotangent
        # plus the exact linear model g(w2) − g(w1) = J·d
        for fn, m in ((g, m_e), (h, m_h)):
            if not m:
                continue
            ct = jax.random.normal(k4, (m,))
            _, pb1 = jax.vjp(fn, w1)
            _, pb2 = jax.vjp(fn, w2)
            if not close(pb1(ct)[0], pb2(ct)[0]):
                return False
            _, jd = jax.jvp(fn, (w1,), (d,))
            if not close(fn(w2) - fn(w1), jd):
                return False
    return True


@functools.partial(jax.jit, static_argnums=(0, 5))
def solve_qp(
    nlp: NLPFunctions,
    w0: jnp.ndarray,
    theta,
    w_lb: jnp.ndarray,
    w_ub: jnp.ndarray,
    options: SolverOptions = SolverOptions(),
    y0: jnp.ndarray | None = None,
    z0: jnp.ndarray | None = None,
    mu0: jnp.ndarray | None = None,
    max_iter: jnp.ndarray | None = None,
) -> SolverResult:
    """Solve an LQ program with a Mehrotra predictor-corrector IPM.

    Same signature/result contract as :func:`ops.solver.solve_nlp` (so it
    vmaps and swaps in transparently); ``mu0`` is accepted for signature
    compatibility but ignored — Mehrotra's σ heuristic sets the barrier
    from the iterate's own complementarity, which is what makes warm
    starts effective without a tuned barrier schedule. Correctness
    requires the problem to BE LQ (certify with :func:`is_lq`).
    """
    with jax.default_matmul_precision("highest"):
        return _solve_qp_impl(nlp, w0, theta, w_lb, w_ub, options,
                              y0, z0, max_iter)


def _solve_qp_impl(nlp, w0, theta, w_lb, w_ub, opts, y0, z0, max_iter_arg):
    dtype = w0.dtype
    eps = jnp.finfo(dtype).eps
    n = w0.shape[0]
    m_e = nlp.g(w0, theta).shape[0]
    m_h = nlp.h(w0, theta).shape[0]

    # derivative pipeline + factor path resolved once at trace time
    # (constant structure: the QP KKT has the same stage-banded form as
    # the NLP solver's, so both stage paths drop in here — no refactor
    # churn). On the sparse path the constant (H, A, C) are extracted
    # ONCE as banded rows and the dense matrices never exist.
    kkt_size = n + m_e if m_e else n
    jac_path = _resolve_jacobian(opts, kkt_size)
    plan = opts.stage_jacobian_plan if jac_path == "sparse" else None
    if plan is not None:
        kkt_path = "stage"
    else:
        kkt_path = _resolve_method(opts.kkt_method, kkt_size,
                                   opts.stage_partition, opts.stage_min_size)
    kkt_path_code = jnp.asarray(KKT_PATHS.index(kkt_path))
    jac_path_code = jnp.asarray(JAC_PATHS.index(jac_path))
    # precision routing (same contract as solve_nlp): the QP's only
    # certified-narrow work is the one-time structure extraction — the
    # three AD passes that contract the constant (H, A, C). The
    # per-iteration factor/resolve stays under the entry point's
    # "highest" context. No phase_scope names here: the fast path is a
    # leaf the fleet engines embed whole, and naming its interior would
    # splinter the enclosing step's phase attribution (the observatory
    # attributes the embedded QP to the surrounding phase).
    precision_path = _resolve_precision(opts)
    precision_path_code = jnp.asarray(PRECISION_PATHS.index(precision_path))
    if precision_path == "mixed":
        mixed_mm = lambda: jax.default_matmul_precision("bfloat16")
        narrow_store = lambda t: jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16).astype(x.dtype), t)
    else:
        mixed_mm = lambda: contextlib.nullcontext()
        narrow_store = lambda t: t

    # dtype-aware feasibility target, shared definition with solve_nlp:
    # the f32 noise floor of O(1)-scaled constraints sits near 1e3·eps,
    # and a gate below it starves every acceptance test (VERDICT r5 #4)
    viol_tol = jnp.maximum(opts.constr_viol_tol, 1e3 * eps)

    f_raw = lambda w: nlp.f(w, theta)
    g_raw = lambda w: nlp.g(w, theta)
    h_raw = lambda w: nlp.h(w, theta)

    # ---- scaling (same scheme as solve_nlp, so duals transfer) -------------
    if opts.scale_variables:
        d_w = jnp.maximum(1.0, jnp.abs(w0))
    else:
        d_w = jnp.ones((n,), dtype)
    gmax = opts.scaling_grad_max
    s_f, s_g, s_h = _row_scaling(f_raw, g_raw, h_raw, w0, d_w, gmax,
                                 dtype, m_e, m_h, plan)

    f = lambda w: s_f * f_raw(w * d_w)
    g = lambda w: s_g * g_raw(w * d_w)
    h = lambda w: s_h * h_raw(w * d_w)
    lb = w_lb / d_w
    ub = w_ub / d_w

    # ---- one-time structure extraction (3 AD passes, exact for LQ) ---------
    # under the mixed routing this is the QP's certified-narrow region:
    # the extraction matmuls run bf16-input/f32-accumulate and the
    # constant Hessian is rounded through bf16 storage; the linear
    # constraint rows (A, C) stay exact — feasibility is the
    # compensator-free part of the residual
    wz = jnp.zeros((n,), dtype)
    f0 = f(wz)
    with mixed_mm():
        if plan is not None:
            # banded extraction: compressed pullbacks give (c, A, C) as
            # row windows, compressed forward seeds give H as banded
            # columns — O(N) storage and FLOPs for all four
            def fgh_scaled(w):
                return jnp.concatenate([f(w)[None], g(w), h(w)])

            vals_z, c, A_rows, C_rows = sjac.banded_fgh_jac(
                plan, fgh_scaled, wz)
            g0 = vals_z[1:1 + m_e]
            h0 = vals_z[1 + m_e:]
            CH = narrow_store(
                sjac.banded_lagrangian_hessian(plan, jax.grad(f), wz))
            H_rows = sjac.hessian_rows(plan, CH)
            h_mv = lambda x: sjac.band_matvec(H_rows,
                                              plan.hrow_cols_safe, x)
            a_mv = lambda x: sjac.band_matvec(A_rows, plan.g_cols_safe,
                                              x)
            a_t_mv = lambda v: sjac.band_rmatvec(A_rows,
                                                 plan.g_cols_safe, v, n)
            c_mv = lambda x: sjac.band_matvec(C_rows, plan.h_cols_safe,
                                              x)
            c_t_mv = lambda v: sjac.band_rmatvec(C_rows,
                                                 plan.h_cols_safe, v, n)
        else:
            c = jax.grad(f)(wz)                   # ∇f(0)
            H = narrow_store(jax.hessian(f)(wz))  # constant
            if m_e:
                A = jax.jacrev(g)(wz)
                g0 = g(wz)                        # g(w) = A w + g0
            else:
                A = jnp.zeros((0, n), dtype)
                g0 = jnp.zeros((0,), dtype)
            if m_h:
                C = jax.jacrev(h)(wz)
                h0 = h(wz)                        # h(w) = C w + h0
            else:
                C = jnp.zeros((0, n), dtype)
                h0 = jnp.zeros((0,), dtype)
            h_mv = lambda x: H @ x
            a_mv = lambda x: A @ x
            a_t_mv = lambda v: A.T @ v
            c_mv = lambda x: C @ x
            c_t_mv = lambda v: C.T @ v

    def f_val(w):
        return f0 + c @ w + 0.5 * w @ h_mv(w)

    # ---- initial point ------------------------------------------------------
    span = jnp.maximum(ub - lb, 1e-8)
    push = opts.bound_push * jnp.minimum(1.0, span)
    w = jnp.clip(w0 / d_w, lb + push, ub - push)
    hv = c_mv(w) + h0 if m_h else h0
    s = jnp.maximum(hv, 1e-2) if m_h else h0
    z = jnp.clip(0.1 / s, 1e-8, 1e8) if m_h else s
    if z0 is not None and m_h:
        z = jnp.maximum(s_f * z0 / jnp.maximum(s_h, 1e-12), 1e-8)
    if y0 is not None and m_e:
        y = s_f * y0 / jnp.maximum(s_g, 1e-12)
    else:
        y = jnp.zeros((m_e,), dtype)
    zL = jnp.clip(0.1 / (w - lb), 1e-12, 1e8)
    zU = jnp.clip(0.1 / (ub - w), 1e-12, 1e8)

    def kkt_error(w, s, y, z, zL, zU):
        """Scaled optimality error at mu=0 (same scaling as solve_nlp)."""
        r_w = c + h_mv(w) - zL + zU
        if m_e:
            r_w = r_w + a_t_mv(y)
        if m_h:
            r_w = r_w - c_t_mv(z)
        r_g = a_mv(w) + g0 if m_e else g0
        r_h = (c_mv(w) + h0 - s) if m_h else h0
        comp = jnp.concatenate([
            s * z if m_h else h0,
            (w - lb) * zL,
            (ub - w) * zU,
        ])
        s_max = 100.0
        dual_sum = (jnp.sum(jnp.abs(y)) + jnp.sum(jnp.abs(z))
                    + jnp.sum(jnp.abs(zL)) + jnp.sum(jnp.abs(zU)))
        s_d = jnp.maximum(s_max, dual_sum / (m_e + m_h + 2 * n)) / s_max
        dual_inf = _safe_max(jnp.abs(r_w)) / s_d
        viol = jnp.maximum(_safe_max(jnp.abs(r_g)), _safe_max(jnp.abs(r_h)))
        compl_inf = _safe_max(jnp.abs(comp)) / s_d
        return jnp.maximum(jnp.maximum(dual_inf, viol), compl_inf), \
            viol, dual_inf, compl_inf

    n_comp = m_h + 2 * n    # complementarity pairs

    def body(carry):
        (w, s, y, z, zL, zU, it, done, err, best, stall, delta,
         frozen) = carry

        dL = jnp.maximum(w - lb, 1e-12)
        dU = jnp.maximum(ub - w, 1e-12)
        sigma_s = z / jnp.maximum(s, 1e-12) if m_h else s
        sigma_L = zL / dL
        sigma_U = zU / dU

        gv = a_mv(w) + g0 if m_e else g0
        hv = c_mv(w) + h0 if m_h else h0
        r_h = hv - s
        r_w = c + h_mv(w) - zL + zU
        if m_e:
            r_w = r_w + a_t_mv(y)
        if m_h:
            r_w = r_w - c_t_mv(z)

        # current duality measure
        mu_now = (jnp.sum(s * z) + jnp.sum((w - lb) * zL)
                  + jnp.sum((ub - w) * zU)) / n_comp

        # adaptive Levenberg regularization, the NLP solver's self-healing
        # loop ported here: ``delta`` grows when a direction is rejected
        # (the pivot-free factorizations can break down at the extreme
        # barrier conditioning near convergence — for a convex QP the
        # damped system is always solvable once delta is large enough)
        # and decays back toward ``delta_init`` while steps are healthy,
        # so the converged solution is unperturbed
        reg = delta + sigma_L + sigma_U
        if plan is not None:
            with mixed_mm():
                D, E = sjac.assemble_kkt_banded(
                    plan, CH, A_rows, C_rows,
                    sigma_s if m_h else jnp.zeros((0,), dtype), reg,
                    opts.delta_c)
            factor = ("stage_banded",
                      (stage_ops.factor_kkt_stage_banded(D, E),
                       plan.partition))
        else:
            with mixed_mm():
                W = H + reg * jnp.eye(n, dtype=dtype)
                if m_h:
                    W = W + C.T @ (sigma_s[:, None] * C)
                if m_e:
                    K = jnp.block([
                        [W, A.T],
                        [A, -opts.delta_c * jnp.eye(m_e, dtype=dtype)],
                    ])
                else:
                    K = W
            factor = _factor_kkt(K, kkt_path, opts.stage_partition)

        def newton_dir(mu_s, mu_L, mu_U):
            """Direction for per-entry complementarity targets (same
            elimination as solve_nlp: bound duals + slacks folded into
            the reduced system). Also returns the relative residual of
            the reduced linear solve, computed through the same
            operators that built the system — the health signal of the
            factorization at this iterate's conditioning."""
            rhs = -r_w + (mu_L / dL - zL) - (mu_U / dU - zU)
            if m_h:
                corr = mu_s / jnp.maximum(s, 1e-12) - z - sigma_s * r_h
                rhs = rhs + c_t_mv(corr)
            if m_e:
                sol = _resolve_kkt(factor, jnp.concatenate([rhs, -gv]))
                dw, dy = sol[:n], sol[n:]
            else:
                dw = _resolve_kkt(factor, rhs)
                dy = jnp.zeros((0,), dtype)
            # residual of K [dw; dy] = [rhs; -gv] with
            # K = [[H + diag(reg) + Cᵀ Σ C, Aᵀ], [A, -δ_c I]] — a few
            # matvecs (banded on the sparse path). The pivot-free stage
            # LDLᵀ can break down (NaN or garbage, refinement
            # non-contractive) at the extreme near-convergence
            # conditioning that pivoted LU survives; a direction from a
            # broken factor must be rejected like a non-finite one, or
            # the iterate runs away and the solve stalls its budget out
            # (the N=8 forced-stage hang this guard closes).
            r_top = h_mv(dw) + reg * dw - rhs
            if m_h:
                r_top = r_top + c_t_mv(sigma_s * c_mv(dw))
            if m_e:
                r_top = r_top + a_t_mv(dy)
                r_bot = a_mv(dw) - opts.delta_c * dy + gv
            else:
                r_bot = jnp.zeros((0,), dtype)
            scale = jnp.maximum(
                jnp.maximum(_safe_max(jnp.abs(rhs)),
                            _safe_max(jnp.abs(gv))), 1.0)
            resid = jnp.maximum(_safe_max(jnp.abs(r_top)),
                                _safe_max(jnp.abs(r_bot))) / scale
            ds = (c_mv(dw) + r_h) if m_h else s
            dz = (mu_s / jnp.maximum(s, 1e-12) - z - sigma_s * ds) \
                if m_h else z
            dzL = mu_L / dL - zL - sigma_L * dw
            dzU = mu_U / dU - zU + sigma_U * dw
            return dw, dy, ds, dz, dzL, dzU, resid

        def steps(dw, ds, dz, dzL, dzU, tau):
            a_p = jnp.minimum(_max_step(dL, dw, tau),
                              _max_step(dU, -dw, tau))
            a_d = jnp.minimum(_max_step(zL, dzL, tau),
                              _max_step(zU, dzU, tau))
            if m_h:
                a_p = jnp.minimum(a_p, _max_step(s, ds, tau))
                a_d = jnp.minimum(a_d, _max_step(z, dz, tau))
            return a_p, a_d

        # ---- affine predictor (mu target 0) --------------------------------
        zero = jnp.zeros(())
        dw_a, dy_a, ds_a, dz_a, dzL_a, dzU_a, _res_a = newton_dir(
            zero, zero, zero)
        a_p, a_d = steps(dw_a, ds_a, dz_a, dzL_a, dzU_a, 1.0)
        w_aff = w + a_p * dw_a
        s_aff = s + a_p * ds_a if m_h else s
        z_aff = z + a_d * dz_a if m_h else z
        zL_aff = zL + a_d * dzL_a
        zU_aff = zU + a_d * dzU_a
        mu_aff = (jnp.sum(s_aff * z_aff)
                  + jnp.sum((w_aff - lb) * zL_aff)
                  + jnp.sum((ub - w_aff) * zU_aff)) / n_comp
        sigma = jnp.clip((mu_aff / jnp.maximum(mu_now, 1e-30)) ** 3,
                         1e-4, 1.0)
        mu_t = sigma * mu_now

        # ---- corrector: fold the predictor's Δ∘Δ into the targets ----------
        # (Gondzio-clipped so a wild predictor cannot poison the step)
        cap = 10.0 * jnp.maximum(mu_t, mu_now)
        mu_L = jnp.clip(mu_t - dw_a * dzL_a, 0.0, cap)
        mu_U = jnp.clip(mu_t + dw_a * dzU_a, 0.0, cap)
        mu_s = jnp.clip(mu_t - ds_a * dz_a, 0.0, cap) if m_h else zero
        dw, dy, ds, dz, dzL, dzU, resid = newton_dir(mu_s, mu_L, mu_U)

        tau = jnp.maximum(opts.tau_min, 1.0 - mu_now)
        a_p, a_d = steps(dw, ds, dz, dzL, dzU, tau)
        # direction-health guard: a failed factorization (non-finite
        # direction, or a finite one whose linear-solve residual shows
        # the factor broke down) must not poison the iterate — keep it;
        # the stall counter then accumulates and the acceptance/stall
        # exits below judge the held point instead of a runaway one.
        # 1e-2 sits orders of magnitude above a healthy f32 solve
        # (~1e-5 relative) and below a broken factor's O(1)+.
        finite = (jnp.all(jnp.isfinite(dw)) & jnp.all(jnp.isfinite(dy))
                  & jnp.all(jnp.isfinite(ds)) & jnp.all(jnp.isfinite(dz))
                  & (resid < 1e-2))
        pick = lambda v, dv, a: jnp.where(finite, v + a * dv, v)
        w_n = pick(w, dw, a_p)
        s_n = pick(s, ds, a_p)
        y_n = pick(y, dy, a_d)
        z_n = pick(z, dz, a_d)
        zL_n = pick(zL, dzL, a_d)
        zU_n = pick(zU, dzU, a_d)
        delta_n = jnp.where(finite,
                            jnp.maximum(opts.delta_init, delta / 3.0),
                            jnp.minimum(delta * 10.0 + 1e-6,
                                        opts.delta_max))
        # consecutive REJECTED directions (the factorization-breakdown
        # signal; an accepted step resets it — slow-but-real convergence
        # must never trip the wedge exit below)
        frozen_n = jnp.where(finite, 0, frozen + 1)

        err_n, viol_n, dual_n, compl_n = kkt_error(
            w_n, s_n, y_n, z_n, zL_n, zU_n)
        # stall-acceptance (same spirit as solve_nlp): when the error has
        # stopped improving — the f32 precision floor, typically — accept
        # a point that is feasible with loose-tolerance complementarity
        # and stationarity instead of burning the budget on noise
        improved = err_n < 0.95 * best
        stall_n = jnp.where(improved, 0, stall + 1)
        best_n = jnp.minimum(best, err_n)
        acceptable = ((viol_n <= viol_tol)
                      & (dual_n <= opts.dual_inf_tol)
                      & (compl_n <= jnp.maximum(opts.tol, 1e3 * eps)))
        # the complementarity gate scales with the REQUESTED tolerance
        # (100×tol) and the dtype floor — a loose config-level gate
        # (compl_inf_tol=1e-2) would let a tol=1e-8 solve accept a
        # genuinely unconverged warm iterate after 4 flat iterations
        stalled_ok = ((stall_n >= 4)
                      & (viol_n <= viol_tol)
                      & (dual_n <= opts.dual_inf_tol)
                      & (compl_n <= jnp.minimum(
                          opts.compl_inf_tol,
                          jnp.maximum(100.0 * opts.tol, 1e4 * eps))))
        done_n = (err_n <= opts.tol) | acceptable | stalled_ok
        return (w_n, s_n, y_n, z_n, zL_n, zU_n, it + 1, done_n, err_n,
                best_n, stall_n, delta_n, frozen_n)

    budget = jnp.asarray(opts.max_iter if max_iter_arg is None
                         else max_iter_arg)

    def cond(carry):
        it, done, frozen = carry[6], carry[7], carry[12]
        # wedge exit: 8 consecutive REJECTED directions even with the
        # Levenberg delta escalating toward delta_max means the
        # factorization cannot produce a usable step at this iterate's
        # conditioning — burning the rest of a large budget cannot
        # change the verdict, so stop and let the final acceptance test
        # judge the held point. Slow-but-converging solves (directions
        # accepted, error creeping down) never trip this: an accepted
        # step resets the counter.
        return (~done) & (it < budget) & (frozen < 8)

    err0, _, _, _ = kkt_error(w, s, y, z, zL, zU)
    carry = (w, s, y, z, zL, zU, jnp.asarray(0), err0 <= opts.tol, err0,
             err0, jnp.asarray(0), jnp.asarray(opts.delta_init, dtype),
             jnp.asarray(0))
    (w, s, y, z, zL, zU, it, done, err, _best,
     _stall, _delta, _frozen) = jax.lax.while_loop(cond, body, carry)

    err_f, viol_f, dual_f, compl_f = kkt_error(w, s, y, z, zL, zU)
    acceptable_f = ((viol_f <= viol_tol)
                    & (dual_f <= opts.dual_inf_tol)
                    & (compl_f <= opts.compl_inf_tol))

    # ---- unscale ------------------------------------------------------------
    gv_f = a_mv(w) + g0 if m_e else g0
    hv_f = c_mv(w) + h0 if m_h else h0
    g_raw_v = gv_f / jnp.maximum(s_g, 1e-12) if m_e else gv_f
    h_raw_v = hv_f / jnp.maximum(s_h, 1e-12) if m_h else hv_f
    viol_raw = jnp.maximum(
        _safe_max(jnp.abs(g_raw_v)),
        _safe_max(jnp.maximum(-h_raw_v, 0.0)))
    mu_f = (jnp.sum(s * z) + jnp.sum((w - lb) * zL)
            + jnp.sum((ub - w) * zU)) / n_comp
    stats = SolverStats(
        iterations=it,
        kkt_error=err,
        success=done | acceptable_f,
        objective=f_val(w) / s_f,
        mu=mu_f,
        constraint_violation=viol_raw,
        kkt_path=kkt_path_code,
        jac_path=jac_path_code,
        precision_path=precision_path_code,
    )
    return SolverResult(
        w=w * d_w,
        y=(s_g * y / s_f) if m_e else y,
        z=(s_h * z / s_f) if m_h else z,
        s=s / jnp.maximum(s_h, 1e-12) if m_h else s,
        stats=stats)
