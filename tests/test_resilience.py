"""Guarded actuation cascade (ISSUE 2 tentpole) + auto-checkpointing.

The acceptance contract: with the chaos harness injecting a 100%-failure
solver window, a running BaseMPC never actuates a non-finite or
out-of-bounds control, degrades to FallbackPID within the configured
budget, and re-engages MPC after the recovery hysteresis — pinned here
end-to-end on the one-room MAS, plus pure-host unit coverage of the
ladder itself and the crash/restart warm-start round-trip.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.resilience import install_chaos
from agentlib_mpc_tpu.resilience.guard import (
    LEVEL_FALLBACK,
    LEVEL_HOLD,
    LEVEL_MPC,
    LEVEL_REPLAY,
    ActuationGuard,
    DegradationPolicy,
    check_result,
)


def _result(u0=0.02, success=True, with_plan=True, n=5):
    traj = {"u": np.full((n, 1), float(u0) if np.isfinite(u0) else u0)}
    if with_plan:
        traj["u"] = np.linspace(u0, u0, n).reshape(n, 1) \
            if np.isfinite(u0) else np.full((n, 1), u0)
    return {"u0": {"mDot": u0}, "traj": traj,
            "stats": {"success": success}}


BOUNDS = {"mDot": (0.0, 0.05)}


class TestCheckResult:
    def test_healthy(self):
        ok, reasons = check_result(_result(), BOUNDS)
        assert ok and reasons == ()

    def test_solver_failure(self):
        ok, reasons = check_result(_result(success=False), BOUNDS)
        assert not ok and "solver_failure" in reasons

    def test_nonfinite_control_and_trajectory(self):
        ok, reasons = check_result(_result(u0=float("nan")), BOUNDS)
        assert not ok
        assert "nonfinite_control" in reasons
        assert "nonfinite_trajectory" in reasons

    def test_out_of_bounds(self):
        ok, reasons = check_result(_result(u0=0.2), BOUNDS)
        assert not ok and reasons == ("control_out_of_bounds",)

    def test_bounds_are_the_module_layer(self):
        # without bounds, an in-range-unknown control passes; the module
        # supplies the live lb/ub (backend.health_check is a pure
        # backend-specific hook on top)
        ok, _ = check_result(_result(u0=0.2), bounds=None)
        assert ok

    def test_backend_precheck_merges_into_assessment(self):
        guard = ActuationGuard(DegradationPolicy(recovery_steps=1),
                               agent="a", module="m")
        d = guard.assess(_result(), BOUNDS,
                         precheck=(False, ("surrogate_off_manifold",)))
        assert not d.healthy
        assert "surrogate_off_manifold" in d.reasons


class TestLadder:
    def _guard(self, **kw):
        policy = DegradationPolicy(replay_steps=2, hold_steps=1,
                                   recovery_steps=2, **kw)
        return ActuationGuard(policy, agent="a", module="m")

    def test_replay_hold_fallback_then_hysteretic_recovery(self):
        guard = self._guard()
        plan = {"u0": {"mDot": 0.01},
                "traj": {"u": np.arange(5, dtype=float).reshape(5, 1) / 100},
                "stats": {"success": True}}
        d = guard.assess(plan, BOUNDS)
        assert d.action == "actuate" and guard.level == LEVEL_MPC

        bad = _result(success=False)
        d1 = guard.assess(bad, BOUNDS)          # failure 1 → replay row 1
        assert d1.action == "replay"
        assert d1.controls == {"mDot": 0.01}
        assert guard.level == LEVEL_REPLAY
        d2 = guard.assess(bad, BOUNDS)          # failure 2 → replay row 2
        assert d2.action == "replay" and d2.controls == {"mDot": 0.02}
        d3 = guard.assess(bad, BOUNDS)          # budget (2+1) not yet hit
        assert d3.action == "hold"
        assert d3.controls == {"mDot": 0.02}    # holds the last actuated
        assert guard.level == LEVEL_HOLD
        d4 = guard.assess(bad, BOUNDS)          # budget exhausted
        assert d4.action == "fallback" and d4.entered_fallback
        assert guard.level == LEVEL_FALLBACK
        d5 = guard.assess(bad, BOUNDS)          # stays in fallback
        assert d5.action == "fallback" and not d5.entered_fallback

        ok = _result()
        d6 = guard.assess(ok, BOUNDS)           # healthy probe 1: hysteresis
        assert d6.action == "fallback" and not d6.reengaged
        assert guard.in_fallback
        d7 = guard.assess(ok, BOUNDS)           # healthy probe 2: re-engage
        assert d7.action == "actuate" and d7.reengaged
        assert guard.level == LEVEL_MPC

    def test_one_healthy_solve_resets_the_streak(self):
        guard = self._guard()
        guard.assess(_result(), BOUNDS)
        bad = _result(success=False)
        guard.assess(bad, BOUNDS)
        guard.assess(_result(), BOUNDS)         # replay-level recovery is
        assert guard.level == LEVEL_MPC         # immediate (plant never
        d = guard.assess(bad, BOUNDS)           # left MPC)
        assert d.action == "replay"             # streak restarted at 1

    def test_no_plan_no_last_control_goes_straight_to_fallback(self):
        guard = self._guard()
        d = guard.assess(_result(success=False), BOUNDS)
        assert d.action == "fallback" and d.entered_fallback

    def test_fallback_after_caps_the_budget(self):
        guard = ActuationGuard(DegradationPolicy(
            replay_steps=3, hold_steps=3, fallback_after=1,
            recovery_steps=1), agent="a", module="m")
        guard.assess(_result(), BOUNDS)
        d1 = guard.assess(_result(success=False), BOUNDS)
        assert d1.action == "replay"            # within the hard budget
        d2 = guard.assess(_result(success=False), BOUNDS)
        assert d2.action == "fallback"          # budget 1 exhausted

    def test_degradation_level_gauge_exported(self):
        telemetry.configure(enabled=True)
        guard = self._guard()
        guard.assess(_result(success=False), BOUNDS)
        level = telemetry.metrics().get("mpc_degradation_level",
                                        agent="a", module="m")
        assert level == float(LEVEL_FALLBACK)

    def test_minlp_shaped_plan_replays_binaries_too(self):
        """MINLP results keep binaries in the top-level binary_schedule
        (traj['u'] holds only the continuous columns) — the replay rung
        must still engage, with name-mapped columns (review finding)."""
        guard = ActuationGuard(DegradationPolicy(replay_steps=2,
                                                 hold_steps=1),
                               agent="a", module="m")
        guard.plan_columns = ["mDot"]            # continuous traj columns
        guard.binary_plan_columns = ["valve"]
        result = {
            "u0": {"mDot": 0.0, "valve": 1.0},
            "traj": {"u": np.arange(4, dtype=float).reshape(4, 1) / 100},
            "binary_schedule": np.array([[1.0], [1.0], [0.0], [0.0]]),
            "stats": {"success": True},
        }
        bounds = {"mDot": (0.0, 0.05), "valve": (0.0, 1.0)}
        guard.assess(result, bounds)
        bad = {"u0": {"mDot": float("nan"), "valve": float("nan")},
               "traj": {}, "stats": {"success": False}}
        d1 = guard.assess(bad, bounds)
        assert d1.action == "replay"
        assert d1.controls == {"mDot": 0.01, "valve": 1.0}
        d2 = guard.assess(bad, bounds)
        assert d2.action == "replay"
        assert d2.controls == {"mDot": 0.02, "valve": 0.0}

    def test_rejects_unknown_policy_keys(self):
        with pytest.raises(ValueError, match="unknown resilience option"):
            DegradationPolicy.from_config({"replays": 3})


# -- end-to-end: chaos solver window → FallbackPID hand-over → recovery ------

UB = 295.15
TIME_STEP = 300.0


def _mas_configs():
    from examples.one_room_mpc import OneRoom

    agent_mpc = {
        "id": "ctrl",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {
                "module_id": "mpc",
                "type": "mpc",
                "enable_deactivation": True,
                "resilience": {"replay_steps": 1, "hold_steps": 1,
                               "recovery_steps": 2},
                "optimization_backend": {
                    "type": "jax",
                    "model": {"class": OneRoom},
                    "discretization_options": {
                        "collocation_order": 2,
                        "collocation_method": "legendre",
                    },
                    "solver": {"max_iter": 60},
                },
                "time_step": TIME_STEP,
                "prediction_horizon": 6,
                "parameters": [
                    {"name": "s_T", "value": 0.001},
                    {"name": "r_mDot", "value": 0.01},
                ],
                "inputs": [
                    {"name": "T_in", "value": 290.15},
                    {"name": "load", "value": 150},
                    {"name": "T_upper", "value": UB},
                ],
                "controls": [{"name": "mDot", "value": 0.02,
                              "ub": 0.05, "lb": 0}],
                "outputs": [{"name": "T_out"}],
                "states": [
                    {"name": "T", "value": 298.16, "ub": 303.15,
                     "lb": 288.15, "alias": "T", "source": "plant"},
                ],
            },
            {
                "module_id": "pid",
                "type": "fallback_pid",
                "input": {"name": "T", "alias": "T", "source": "plant"},
                "output": {"name": "mDot_pid", "alias": "mDot"},
                "setpoint": UB,
                "Kp": 0.005, "reverse_acting": True,
                "lb": 0.0, "ub": 0.05,
            },
        ],
    }
    agent_sim = {
        "id": "plant",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {
                "module_id": "room",
                "type": "simulator",
                "model": {"class": OneRoom,
                          "states": [{"name": "T", "value": 298.16}]},
                "t_sample": 50,
                "outputs": [{"name": "T_out", "value": 298.16,
                             "alias": "T"}],
                "inputs": [{"name": "mDot", "value": 0.02,
                            "alias": "mDot"}],
            },
        ],
    }
    return agent_mpc, agent_sim


@pytest.fixture(scope="module")
def outage_run():
    """Run the closed loop through a 4-step 100%-failure solver window
    (solve calls 3..6 NaN-poisoned) and record everything the plant and
    the flag subscribers saw."""
    from agentlib_mpc_tpu.runtime.mas import LocalMAS
    from agentlib_mpc_tpu.runtime.module import BaseModule, register_module
    import agentlib_mpc_tpu.modules  # noqa: F401

    telemetry.configure(enabled=True)
    received_mdot = []     # (t, value, source module) — external probe
    flag_events = []       # (t, value) — listener INSIDE the ctrl agent
    ext_flags = []         # flag events leaking to OTHER agents: none
    #                        allowed (the guard flag is agent-local)

    @register_module("_resilience_probe")
    class Probe(BaseModule):
        def register_callbacks(self):
            self.agent.data_broker.register_callback(
                "mDot", None,
                lambda v: received_mdot.append(
                    (v.timestamp, v.value, v.source.module_id)))
            self.agent.data_broker.register_callback(
                "mpc_active", None,
                lambda v: ext_flags.append((v.timestamp, v.value)))

    @register_module("_flag_listener")
    class FlagListener(BaseModule):
        def register_callbacks(self):
            self.agent.data_broker.register_callback(
                "mpc_active", None,
                lambda v: flag_events.append((v.timestamp, v.value)))

    agent_mpc, agent_sim = _mas_configs()
    agent_mpc["modules"].append(
        {"module_id": "flags", "type": "_flag_listener"})
    probe = {"id": "probe",
             "modules": [{"module_id": "p", "type": "_resilience_probe"}]}
    mas = LocalMAS([agent_mpc, agent_sim, probe], env={"rt": False})
    ctl = install_chaos(mas, {
        "seed": 1,
        "solver": [{"target": "ctrl/mpc", "mode": "nan",
                    "every": 1, "start_call": 3, "n_calls": 4}],
    })
    mas.run(until=3600)
    module = mas.agents["ctrl"].get_module("mpc")
    return {"mas": mas, "ctl": ctl, "module": module,
            "mdot": received_mdot, "flags": flag_events,
            "ext_flags": ext_flags}


@pytest.mark.chaos
class TestFallbackHandover:
    def test_window_actually_injected(self, outage_run):
        assert outage_run["ctl"].count("solver_nan") == 4

    def test_plant_only_ever_receives_bounded_controls(self, outage_run):
        values = np.array([v for _, v, _ in outage_run["mdot"]], dtype=float)
        assert len(values) > 0
        assert np.isfinite(values).all()
        assert (values >= -1e-9).all() and (values <= 0.05 + 1e-9).all()

    def test_flag_flips_within_the_budget_and_recovers(self, outage_run):
        flags = outage_run["flags"]
        offs = [t for t, v in flags if v is False]
        ons = [t for t, v in flags if v is True]
        # window starts at solve call 3 (t=900); budget replay+hold = 2
        # → fallback at the 3rd failed call, t=1500
        assert offs and min(offs) == pytest.approx(1500.0)
        # recovery: first healthy probe t=2100, hysteresis 2 → re-engage
        # at t=2400
        assert any(t == pytest.approx(2400.0) for t in ons)

    def test_pid_served_the_plant_during_the_outage(self, outage_run):
        pid_msgs = [(t, v) for t, v, src in outage_run["mdot"]
                    if src == "pid" and 1500.0 <= t <= 2400.0]
        assert pid_msgs, "FallbackPID never actuated during the outage"
        assert all(0.0 <= v <= 0.05 for _, v in pid_msgs)

    def test_mpc_back_in_charge_after_recovery(self, outage_run):
        assert outage_run["module"].guard.level == LEVEL_MPC
        mpc_after = [t for t, _, src in outage_run["mdot"]
                     if src == "mpc" and t > 2400.0]
        assert mpc_after, "MPC never actuated again after re-engaging"

    def test_degraded_steps_not_recorded_as_results(self, outage_run):
        df = outage_run["module"].results()
        times = set(df.index.get_level_values("time").unique())
        # neither the 4 poisoned solves (t=900..1800) nor the healthy
        # but never-actuated recovery probe (t=2100) may pollute the
        # results: recorded rows are exactly what drove the plant
        assert times == {0.0, 300.0, 600.0, 2400.0,
                         2700.0, 3000.0, 3300.0, 3600.0}
        # dropna: u is N entries on the N+1 results grid — the terminal
        # node is layout padding, not data
        assert np.isfinite(
            df[("variable", "mDot")].dropna().to_numpy(dtype=float)).all()

    def test_recovery_does_not_override_operator_deactivation(
            self, outage_run):
        """If an operator (MPCOnOff / skip-interval window) set the flag
        False, guard recovery must NOT flip it back on — the plant stays
        with the operator's choice (review finding). Runs last: it
        drives the already-finished module by hand."""
        module = outage_run["module"]
        flags_before = list(outage_run["flags"])
        # put the guard one healthy solve away from re-engagement while
        # an external deactivation is in force
        module.guard.level = LEVEL_FALLBACK
        module.guard._healthy_streak = \
            module.guard.policy.recovery_steps - 1
        module._external_flag = False
        module.do_step()
        assert module.guard.level == LEVEL_MPC      # guard DID recover
        assert outage_run["flags"] == flags_before  # but stayed silent

        # with no external deactivation, the same recovery flips the flag
        module.guard.level = LEVEL_FALLBACK
        module.guard._healthy_streak = \
            module.guard.policy.recovery_steps - 1
        module._external_flag = True
        module.do_step()
        assert outage_run["flags"][-1][1] is True

    def test_fallback_flag_stays_agent_local(self, outage_run):
        """The guard's flag flips must not leak onto the bus: a shared
        broadcast would switch every OTHER healthy MPC agent in the
        fleet to its fallback (review finding). Opt in with
        resilience.share_fallback_flag for a remote fallback
        controller."""
        assert outage_run["flags"], "ctrl-local listener saw no flips"
        assert outage_run["ext_flags"] == []

    def test_guarded_actuation_is_the_shared_seam(self, outage_run):
        """The decentralized/coordinated ADMM loops route through
        guarded_actuation — pin the seam directly: a NaN result never
        reaches set_actuation; a finite degraded substitute does."""
        module = outage_run["module"]
        n_before = len(outage_run["mdot"])
        bad = {"u0": {"mDot": float("nan")},
               "traj": {"u": np.full((6, 1), np.nan)},
               "stats": {"success": False}}
        decision = module.guarded_actuation(bad)
        assert decision.action in ("replay", "hold")
        new = [v for _, v, _ in outage_run["mdot"][n_before:]]
        assert new and all(np.isfinite(v) for v in new)

    def test_guard_telemetry_counters(self, outage_run):
        reg = telemetry.metrics()
        assert reg.get("mpc_fallback_engagements_total",
                       agent="ctrl", module="mpc") >= 1
        assert reg.get("mpc_recoveries_total",
                       agent="ctrl", module="mpc") >= 1
        assert reg.get("mpc_unhealthy_solves_total", agent="ctrl",
                       module="mpc", reason="solver_failure") >= 4


# -- crash/restart warm-start round-trip (checkpoint_every satellite) --------

def _checkpoint_agent(path):
    from examples.one_room_mpc import OneRoom

    return {
        "id": "solo",
        "modules": [{
            "module_id": "mpc",
            "type": "mpc",
            "checkpoint_path": str(path),
            "checkpoint_every": 1,
            "optimization_backend": {
                "type": "jax",
                "model": {"class": OneRoom},
                "discretization_options": {"collocation_order": 2,
                                           "collocation_method": "legendre"},
                "solver": {"max_iter": 60},
            },
            "time_step": TIME_STEP,
            "prediction_horizon": 6,
            "parameters": [{"name": "s_T", "value": 0.001},
                           {"name": "r_mDot", "value": 0.01}],
            "inputs": [{"name": "T_in", "value": 290.15},
                       {"name": "load", "value": 150},
                       {"name": "T_upper", "value": UB}],
            "controls": [{"name": "mDot", "value": 0.02,
                          "ub": 0.05, "lb": 0}],
            "outputs": [{"name": "T_out"}],
            "states": [{"name": "T", "value": 298.16,
                        "ub": 303.15, "lb": 288.15}],
        }],
    }


class TestAutoCheckpoint:
    def test_crash_restart_round_trip(self, tmp_path):
        """checkpoint_every writes after every step; a 'crashed' process
        rebuilt from the same config restores on construct and its next
        solve matches the uninterrupted controller exactly."""
        pytest.importorskip("orbax.checkpoint")
        from agentlib_mpc_tpu.runtime.mas import LocalMAS
        import agentlib_mpc_tpu.modules  # noqa: F401

        path = tmp_path / "warm"
        mas_a = LocalMAS([_checkpoint_agent(path)], env={"rt": False})
        mas_a.run(until=650)                    # solves at t=0, 300, 600
        mod_a = mas_a.agents["solo"].get_module("mpc")
        assert path.is_dir(), "auto-checkpoint never wrote"

        # "restart": a fresh process builds the same module and restores
        mas_b = LocalMAS([_checkpoint_agent(path)], env={"rt": False})
        mod_b = mas_b.agents["solo"].get_module("mpc")
        assert mod_b.backend._cold is False     # restored, not cold
        a_state = mod_a.backend.warm_state()
        b_state = mod_b.backend.warm_state()
        for key in ("w", "y", "z"):
            np.testing.assert_array_equal(np.asarray(a_state[key]),
                                          np.asarray(b_state[key]))

        res_a = mod_a.backend.solve(900.0, {"T": 296.5})
        res_b = mod_b.backend.solve(900.0, {"T": 296.5})
        np.testing.assert_array_equal(np.asarray(res_a["traj"]["u"]),
                                      np.asarray(res_b["traj"]["u"]))
        assert res_a["stats"]["iterations"] == res_b["stats"]["iterations"]

    def test_missing_checkpoint_starts_cold(self, tmp_path):
        from agentlib_mpc_tpu.utils.checkpoint import has_checkpoint

        assert not has_checkpoint(str(tmp_path / "nothing_here"))

    def test_checkpointing_rides_the_guarded_actuation_seam(
            self, outage_run, tmp_path):
        """Auto-checkpointing lives on guarded_actuation — the seam the
        ADMM modes (which own their step loops, never do_step) route
        through — so they checkpoint too (review finding)."""
        pytest.importorskip("orbax.checkpoint")
        from agentlib_mpc_tpu.utils.checkpoint import has_checkpoint

        module = outage_run["module"]
        module.checkpoint_path = str(tmp_path / "warm")
        module.checkpoint_every = 1
        module._steps_since_checkpoint = 0
        try:
            healthy = module.backend.solve(3900.0, {})
            module.guarded_actuation(healthy)
            assert has_checkpoint(module.checkpoint_path)
        finally:
            module.checkpoint_path = None
