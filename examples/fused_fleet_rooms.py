"""Config-driven fused fleet: 16 rooms negotiate one shared supply on the
data plane.

The same ``admm_local``-style agent configs the module path consumes
(``examples/admm_cooled_room.py``) are compiled by
:class:`~agentlib_mpc_tpu.parallel.config_bridge.FusedFleet` into ONE
jitted ADMM program — every room's interior-point solve, the consensus
mean and the dual updates fused (docs/DISTRIBUTED.md, "data plane").
Closed loop: each control interval the fused round plans, the plant
models integrate one step, measurements feed back via ``update_agent``,
and the warm start shifts. This is the cluster-simulation workflow the
reference runs as N CasADi processes around a coordinator agent
(``examples/4_Room_ADMM_Coordinator/admm_4rooms_coord_main.py``), here
one XLA computation per round.

Mid-run the loop checkpoints the fleet's control state and (under
``testing``) proves a restarted fleet restored from it produces the
identical next round — the durable-resume workflow a real building
controller needs across restarts (the reference cannot do this; its
warm starts die with the process).

Run directly for a report, or call ``run_example`` (examples-as-tests,
SURVEY.md §4).
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu.models.zoo import CooledRoom
from agentlib_mpc_tpu.parallel.config_bridge import FusedFleet

N_ROOMS = 16
TIME_STEP = 300.0
HORIZON = 6
MAX_ITERATIONS = 8
UB = 295.15
T_IN = 290.15
START_TEMP = 298.16


def room_config(i: int, load: float) -> dict:
    return {
        "id": f"Room_{i}",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "admm", "type": "admm_local",
             "optimization_backend": {
                 "type": "jax_admm",
                 "model": {"class": CooledRoom},
                 "discretization_options": {"collocation_order": 2,
                                            "collocation_method": "legendre"},
                 "solver": {"max_iter": 30},
             },
             "time_step": TIME_STEP,
             "prediction_horizon": HORIZON,
             "max_iterations": MAX_ITERATIONS,
             "penalty_factor": 20.0,
             "parameters": [{"name": "s_T", "value": 1.0}],
             "inputs": [
                 {"name": "load", "value": load},
                 {"name": "T_in", "value": T_IN},
                 {"name": "T_upper", "value": UB},
             ],
             "states": [{"name": "T", "value": START_TEMP}],
             "couplings": [
                 {"name": "mDot", "alias": "mDotShared", "value": 0.02,
                  "lb": 0.0, "ub": 0.05},
             ]},
        ],
    }


def run_example(until: float = 3600.0, n_rooms: int = N_ROOMS,
                testing: bool = False, verbose: bool = True,
                checkpoint_dir: "str | None" = None) -> dict:
    import tempfile

    loads = np.linspace(80.0, 220.0, n_rooms)
    configs = [room_config(i, float(loads[i])) for i in range(n_rooms)]
    fleet = FusedFleet.from_configs(configs)

    plant = CooledRoom()
    p_plant = plant.default_vector("parameters")
    temps = {f"Room_{i}": START_TEMP for i in range(n_rooms)}
    iter_trail: list[int] = []
    # checkpoint only when someone will consume it (the testing resume
    # proof, or a caller-supplied directory) — not dead I/O per run
    ckpt_dir = checkpoint_dir
    if ckpt_dir is None and testing:
        ckpt_dir = tempfile.mkdtemp(prefix="fleet_ckpt_")

    n_steps = int(until // TIME_STEP)
    out_round2 = None
    for k in range(n_steps):
        if k == 1 and ckpt_dir is not None:
            # durable resume point: warm starts + the round-1 plant
            # measurements (update_agent ran before this) are all inside
            ckpt_path = fleet.save_checkpoint(f"{ckpt_dir}/fleet")
        out = fleet.step()
        if k == 1:
            out_round2 = {f"Room_{i}": np.asarray(
                out[f"Room_{i}"]["u"]["mDot"]) for i in range(n_rooms)}
        iter_trail.append(out["Room_0"]["iterations"])
        for i in range(n_rooms):
            aid = f"Room_{i}"
            mdot = float(out[aid]["u"]["mDot"][0])
            u = jnp.array([mdot, float(loads[i]), T_IN, UB])
            x_next, _ = plant.simulate_step(
                jnp.array([temps[aid]]), u, p_plant, TIME_STEP)
            temps[aid] = float(x_next[0])
            fleet.update_agent(aid, x0=[temps[aid]])
        fleet.advance()

    t = np.array([temps[f"Room_{i}"] for i in range(n_rooms)])
    if verbose:
        print(f"{n_rooms} rooms, {n_steps} control steps "
              f"({len(fleet.engine.groups)} fused group(s))")
        print(f"temperatures: start {START_TEMP:.2f} K -> "
              f"[{t.min():.2f}, {t.max():.2f}] K (band {UB} K)")
        print(f"ADMM iterations per round: {iter_trail}")
    if testing:
        assert len(fleet.engine.groups) == 1, "identical rooms must batch"
        assert np.all(t < START_TEMP), "every room must cool"
        # warm starts pay off: some later round beats the cold round
        # (meaningful only when there are later rounds and the cold round
        # did not already saturate the iteration cap)
        if len(iter_trail) >= 2 and iter_trail[0] < MAX_ITERATIONS:
            assert min(iter_trail[1:]) <= iter_trail[0]
        if out_round2 is not None:
            # durable resume: a "restarted controller" restored from the
            # mid-run checkpoint must reproduce round 2 bit-identically
            resumed = FusedFleet.from_configs(configs)
            resumed.restore_checkpoint(ckpt_path)
            out_resumed = resumed.step()
            for aid, u_ref in out_round2.items():
                np.testing.assert_array_equal(
                    np.asarray(out_resumed[aid]["u"]["mDot"]), u_ref)
            if checkpoint_dir is None:   # auto-created temp dir
                import shutil

                shutil.rmtree(ckpt_dir, ignore_errors=True)
    return {"temps": temps, "iterations": iter_trail}


if __name__ == "__main__":
    run_example(until=7200.0, testing=True)
