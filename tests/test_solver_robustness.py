"""Solver robustness campaign: degenerate and adversarial programs.

VERDICT r4 #6: the random-QP corpus (test_solver_random.py) certifies the
happy path; this file certifies HONESTY on the unhappy ones — the stats
taxonomy (success / kkt_error / constraint_violation) must tell the truth
for LICQ failure, infeasibility, active-set degeneracy and brutal
scaling, and a control module must keep stepping after failed solves (the
reference's tolerance: ``modules/mpc/mpc.py:389-404`` logs and carries
on). The QP fast path faces the same corpus where its structure
assumption holds.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.ops.qp import solve_qp
from agentlib_mpc_tpu.ops.solver import (
    NLPFunctions,
    SolverOptions,
    solve_nlp,
)

OPTS = SolverOptions(tol=1e-8, max_iter=120)
SOLVERS = [("ipm", solve_nlp), ("qp", solve_qp)]


def _qp_nlp(Q, c, Aeq=None, beq=None):
    Qj, cj = jnp.asarray(Q), jnp.asarray(c)
    if Aeq is None:
        g = lambda w, t: jnp.zeros((0,))
    else:
        Aj, bj = jnp.asarray(Aeq), jnp.asarray(beq)
        g = lambda w, t: Aj @ w - bj
    return NLPFunctions(f=lambda w, t: 0.5 * w @ Qj @ w + cj @ w,
                        g=g, h=lambda w, t: jnp.zeros((0,)))


@pytest.mark.parametrize("name,solver", SOLVERS)
class TestDegenerateButSolvable:
    def test_licq_failure_duplicated_constraints(self, name, solver):
        """The same equality row three times: the constraint Jacobian is
        rank-deficient everywhere (LICQ fails), but the feasible set and
        optimum are unchanged — the quasi-definite regularization must
        still deliver the right point, honestly flagged a success."""
        n = 6
        rng = np.random.default_rng(0)
        M = rng.normal(size=(n, n))
        Q = M @ M.T + n * np.eye(n)
        c = rng.normal(size=n)
        a = rng.normal(size=(1, n))
        Aeq = np.vstack([a, a, a])          # rank 1, three rows
        beq = np.array([1.0, 1.0, 1.0])
        nlp = _qp_nlp(Q, c, Aeq, beq)
        lb, ub = jnp.full(n, -10.0), jnp.full(n, 10.0)
        res = solver(nlp, jnp.zeros(n), None, lb, ub, OPTS)
        assert bool(res.stats.success)
        # KKT conditions of the unduplicated problem hold
        w = np.asarray(res.w)
        assert abs(float((a @ w)[0]) - 1.0) < 1e-5
        # stationarity: Qw + c + A^T y ⊥ (multipliers may split any way
        # across the duplicated rows — check the residual directly)
        y = np.asarray(res.y)
        grad = Q @ w + c + Aeq.T @ y
        assert np.max(np.abs(grad)) < 1e-4

    def test_weakly_active_bound(self, name, solver):
        """Optimum exactly ON a bound with a vanishing multiplier (the
        active-set-flip degeneracy): min (w0)^2 s.t. w0 >= 0 — both the
        constraint and its dual are zero at the solution."""
        n = 3
        Q = np.eye(n)
        c = np.zeros(n)
        nlp = _qp_nlp(Q, c)
        lb = jnp.asarray([0.0, -1.0, -1.0])
        ub = jnp.full(n, 1.0)
        res = solver(nlp, jnp.full(n, 0.5), None, lb, ub, OPTS)
        assert bool(res.stats.success)
        # the barrier parks the weakly-active coordinate at O(sqrt(mu));
        # 1e-4 is zero to the solver's own mu floor, not a miss
        np.testing.assert_allclose(np.asarray(res.w), np.zeros(n),
                                   atol=1e-4)

    def test_solution_pinned_at_bound_with_active_gradient(self, name,
                                                           solver):
        """Strictly active bound: min -w0 on [0, 1] — the optimum sits at
        ub with a genuinely nonzero bound dual."""
        nlp = NLPFunctions(f=lambda w, t: -w[0] + 0.5 * w[1] ** 2,
                           g=lambda w, t: jnp.zeros((0,)),
                           h=lambda w, t: jnp.zeros((0,)))
        res = solver(nlp, jnp.asarray([0.5, 0.5]), None,
                     jnp.zeros(2), jnp.ones(2), OPTS)
        assert bool(res.stats.success)
        assert abs(float(res.w[0]) - 1.0) < 1e-6

    def test_brutal_scaling(self, name, solver):
        """Curvatures spanning 8 orders of magnitude: the automatic
        scaling layer has to carry this (the stiff-dynamics analogue at
        the pure-QP level)."""
        scales = np.array([1e-4, 1.0, 1e4])
        Q = np.diag(scales)
        c = -scales * np.array([1.0, 2.0, 3.0])   # optimum [1, 2, 3]
        nlp = _qp_nlp(Q, c)
        lb, ub = jnp.full(3, -10.0), jnp.full(3, 10.0)
        res = solver(nlp, jnp.asarray([0.1, 0.1, 0.1]), None, lb, ub,
                     OPTS)
        assert bool(res.stats.success)
        # the 1e-4-curvature coordinate is only determined to the SCALED
        # tolerance (its gradient is invisible next to the 1e4 one —
        # IPOPT behaves identically); the honest gate is the objective
        w = np.asarray(res.w)
        w_star = np.array([1.0, 2.0, 3.0])
        np.testing.assert_allclose(w[1:], w_star[1:], rtol=1e-4)
        f = 0.5 * w @ (Q @ w) + c @ w
        f_star = 0.5 * w_star @ (Q @ w_star) + c @ w_star
        assert f - f_star < 1e-4


@pytest.mark.parametrize("name,solver", SOLVERS)
class TestInfeasible:
    def test_contradictory_equalities_not_a_success(self, name, solver):
        """w0 + w1 = 0 AND w0 + w1 = 1: no feasible point exists. The
        solver must not claim success, and constraint_violation must
        report a genuinely non-vanishing number."""
        Aeq = np.array([[1.0, 1.0], [1.0, 1.0]])
        beq = np.array([0.0, 1.0])
        nlp = _qp_nlp(np.eye(2), np.zeros(2), Aeq, beq)
        res = solver(nlp, jnp.zeros(2), None, jnp.full(2, -5.0),
                     jnp.full(2, 5.0), OPTS)
        assert not bool(res.stats.success)
        assert float(res.stats.constraint_violation) > 0.05

    def test_equality_outside_box_not_a_success(self, name, solver):
        """w0 = 3 with box [-1, 1]: feasibility blocked by the bounds."""
        Aeq = np.array([[1.0, 0.0]])
        beq = np.array([3.0])
        nlp = _qp_nlp(np.eye(2), np.zeros(2), Aeq, beq)
        res = solver(nlp, jnp.zeros(2), None, jnp.full(2, -1.0),
                     jnp.ones(2), OPTS)
        assert not bool(res.stats.success)
        assert float(res.stats.constraint_violation) > 0.5


class TestStiffOCP:
    def test_stiff_badly_scaled_dynamics_mpc(self):
        """A stiff two-time-scale plant (rate constants 1 vs 1e4) with
        badly scaled parameters through the full transcription: the MPC
        backend must converge and the trajectory stay finite."""
        from agentlib_mpc_tpu.models.model import Model, ModelEquations
        from agentlib_mpc_tpu.models.objective import SubObjective
        from agentlib_mpc_tpu.models.variables import (
            control_input,
            parameter,
            state,
        )
        from agentlib_mpc_tpu.backends.backend import (
            VariableReference,
            create_backend,
        )

        class StiffPlant(Model):
            inputs = [control_input("u", 0.0, lb=0.0, ub=1.0)]
            states = [state("x_slow", 1.0, lb=-100.0, ub=100.0),
                      state("x_fast", 0.5, lb=-100.0, ub=100.0)]
            parameters = [parameter("k_slow", 1.0),
                          parameter("k_fast", 1e4),
                          parameter("w_track", 1e6)]

            def setup(self, v):
                eq = ModelEquations()
                eq.ode("x_slow", -v.k_slow * v.x_slow + v.u)
                # fast mode relaxes to x_slow at rate 1e4
                eq.ode("x_fast", -v.k_fast * (v.x_fast - v.x_slow))
                eq.objective = (
                    SubObjective((v.x_slow - 0.2) ** 2, weight=v.w_track,
                                 name="track")
                    + SubObjective(v.u ** 2, weight=1e-3, name="effort"))
                return eq

        backend = create_backend({
            "type": "jax",
            "model": {"class": StiffPlant},
            "discretization_options": {"collocation_order": 3,
                                       "collocation_method": "radau"},
            "solver": {"max_iter": 120},
        })
        backend.setup_optimization(
            VariableReference(states=["x_slow", "x_fast"], controls=["u"],
                              parameters=["k_slow", "k_fast", "w_track"]),
            time_step=0.1, prediction_horizon=6)
        res = backend.solve(0.0, {"x_slow": 1.0, "x_fast": 0.5})
        assert res["stats"]["success"], res["stats"]
        x = np.asarray(res["traj"]["x"])
        assert np.all(np.isfinite(x))
        # the slow mode moved toward its setpoint at its O(1) rate ...
        assert float(x[-1, 0]) < 0.6 < float(x[0, 0])
        # ... and the 1e4-rate fast mode collapsed onto it (the stiff
        # relaxation the collocation must resolve without oscillating)
        assert abs(float(x[-1, 1]) - float(x[-1, 0])) < 1e-3


class TestModuleSurvivesFailedSolves:
    def test_do_step_keeps_running_after_infeasible_solves(self, caplog):
        """The reference logs a warning and keeps the loop alive when a
        solve fails (``modules/mpc/mpc.py:389-404``); the module path
        here must do the same at scale: an MPC whose state bound makes
        the problem infeasible completes every step, logs the failures,
        actuates the (clipped) best effort, and records honest stats."""
        import logging

        import agentlib_mpc_tpu.modules  # noqa: F401
        from agentlib_mpc_tpu.runtime.mas import LocalMAS

        cfg = {
            "id": "Doomed",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {
                    "module_id": "mpc",
                    "type": "mpc",
                    "optimization_backend": {
                        "type": "jax",
                        "model": {"class": "OneRoom"},
                        "discretization_options": {"collocation_order": 2},
                        "solver": {"max_iter": 15},
                    },
                    "time_step": 300.0,
                    "prediction_horizon": 4,
                    "inputs": [
                        {"name": "load", "value": 150.0},
                        {"name": "T_in", "value": 290.15},
                        {"name": "T_upper", "value": 295.15},
                    ],
                    # infeasible by construction: the state must stay
                    # BELOW a bound the plant starts far above, with the
                    # hard bound leaving no slack headroom
                    "states": [
                        {"name": "T", "value": 305.15, "ub": 296.15,
                         "lb": 288.15},
                        {"name": "T_slack", "value": 0.0, "ub": 0.0,
                         "lb": 0.0},
                    ],
                    "controls": [
                        {"name": "mDot", "value": 0.02, "ub": 0.05,
                         "lb": 0.0},
                    ],
                    "parameters": [
                        {"name": "s_T", "value": 1.0},
                        {"name": "r_mDot", "value": 0.01},
                    ],
                },
            ],
        }
        mas = LocalMAS([cfg], env={"rt": False})
        with caplog.at_level(logging.WARNING):
            mas.run(until=1500.0)           # steps at t = 0, 300, ..., 1500
        mpc = mas.agents["Doomed"].get_module("mpc")
        stats = mpc.solver_stats()
        assert len(stats) == 6, "a failed solve must not stall the loop"
        failed = (~stats["success"]).sum()
        assert failed >= 1, "expected at least one honestly-failed solve"
        assert any("did not converge" in r.message for r in caplog.records)
        # actuation stayed in bounds every step (clipped best effort)
        u = float(mpc.vars["mDot"].value)
        assert 0.0 <= u <= 0.05
