from agentlib_mpc_tpu.models.variables import (
    Var,
    state,
    control_input,
    parameter,
    output,
)
from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.objective import (
    Objective,
    SubObjective,
    ChangePenaltyObjective,
    ConditionalObjective,
    CombinedObjective,
)
