"""Telemetry subsystem tests (ISSUE 1).

Covers: registry counter/gauge/histogram semantics, Prometheus text
golden rendering, JSONL export, span nesting + ring-buffer overflow, the
retrace-counter hooks (a deliberate static-shape change must increment the
retrace metric), broker unmatched counting with the rate-limited warning,
the solver-failure telemetry path, the ``MPCBackend.stats_history``
back-compat schema, and the dashboard telemetry data layer.
"""

import json
import logging

import numpy as np
import pytest

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.telemetry.registry import MetricsRegistry
from agentlib_mpc_tpu.telemetry.spans import SpanRecorder


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Each test starts from an enabled, empty default registry."""
    telemetry.configure(enabled=True)
    telemetry.reset()
    yield
    telemetry.configure(enabled=True)
    telemetry.reset()


class TestRegistry:
    def test_counter_labels_and_totals(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs_total", "requests")
        c.inc(agent="a")
        c.inc(2.0, agent="a")
        c.inc(agent="b")
        assert reg.get("reqs_total", agent="a") == 3.0
        assert reg.get("reqs_total", agent="b") == 1.0
        assert reg.get("reqs_total", agent="missing") is None
        assert c.total() == 4.0

    def test_counter_rejects_decrease(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="cannot decrease"):
            reg.counter("c_total").inc(-1.0)

    def test_gauge_set_and_inc(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth")
        g.set(4.0, q="x")
        g.set(2.5, q="x")
        g.inc(0.5, q="x")
        assert reg.get("depth", q="x") == 3.0

    def test_histogram_buckets_cumulative(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        (sample,) = h.samples()
        assert sample["count"] == 5
        assert sample["sum"] == pytest.approx(56.05)
        assert sample["buckets"] == {"0.1": 1, "1": 3, "10": 4, "+Inf": 5}

    def test_histogram_boundary_is_inclusive(self):
        # Prometheus `le` semantics: an observation equal to the bound
        # lands in that bucket
        reg = MetricsRegistry()
        h = reg.histogram("b", buckets=(1.0, 2.0))
        h.observe(1.0)
        (sample,) = h.samples()
        assert sample["buckets"]["1"] == 1

    def test_kind_conflict_raises_and_redeclare_is_idempotent(self):
        reg = MetricsRegistry()
        c = reg.counter("x_total", "first help")
        assert reg.counter("x_total", "other help") is c
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_reset_keeps_families(self):
        reg = MetricsRegistry()
        reg.counter("kept_total").inc()
        reg.reset()
        names = [f["name"] for f in reg.snapshot()]
        assert names == ["kept_total"]
        assert reg.snapshot()[0]["samples"] == []
        assert reg.snapshot()[0]["total"] == 0.0

    def test_disabled_registry_records_nothing(self):
        reg = MetricsRegistry(enabled=False)
        reg.counter("c_total").inc()
        reg.gauge("g").set(1.0)
        reg.histogram("h").observe(1.0)
        assert all(f["samples"] == [] for f in reg.snapshot())

    def test_bound_labels_child(self):
        reg = MetricsRegistry()
        child = reg.counter("c_total").labels(agent="a1")
        child.inc()
        child.inc(2.0)
        assert reg.get("c_total", agent="a1") == 3.0

    def test_kind_inappropriate_writes_raise(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="does not support"):
            reg.counter("kc_total").labels(a="1").set(5.0)
        with pytest.raises(ValueError, match="does not support"):
            reg.histogram("kh").labels(a="1").inc()
        with pytest.raises(ValueError, match="does not support"):
            reg.histogram("kh").labels(a="1").set(1.0)
        # gauges legitimately support both set and inc
        g = reg.gauge("kg").labels(a="1")
        g.set(1.0)
        g.inc(1.0)
        assert reg.get("kg", a="1") == 2.0


class TestPrometheusText:
    def test_golden_rendering(self):
        reg = MetricsRegistry()
        c = reg.counter("solves_total", "solver calls")
        c.inc(2, backend="jax")
        c.inc(backend="mhe")
        reg.gauge("kkt", "last kkt").set(1.5e-3, backend="jax")
        h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        expected = "\n".join([
            '# HELP kkt last kkt',
            '# TYPE kkt gauge',
            'kkt{backend="jax"} 0.0015',
            '# HELP lat_seconds latency',
            '# TYPE lat_seconds histogram',
            'lat_seconds_bucket{le="0.1"} 1',
            'lat_seconds_bucket{le="1"} 2',
            'lat_seconds_bucket{le="+Inf"} 2',
            'lat_seconds_sum 0.55',
            'lat_seconds_count 2',
            '# HELP solves_total solver calls',
            '# TYPE solves_total counter',
            'solves_total{backend="jax"} 2',
            'solves_total{backend="mhe"} 1',
        ]) + "\n"
        assert reg.prometheus_text() == expected

    def test_label_value_escaping(self):
        reg = MetricsRegistry()
        reg.counter("c_total").inc(path='a"b\\c\nd')
        text = reg.prometheus_text()
        assert 'path="a\\"b\\\\c\\nd"' in text


class TestJsonlExport:
    def test_round_trip(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c_total", "help").inc(3, agent="a")
        reg.histogram("h_seconds", buckets=(1.0,)).observe(0.5)
        path = tmp_path / "metrics.jsonl"
        reg.write_jsonl(str(path))
        lines = [json.loads(ln) for ln in path.read_text().splitlines()]
        by_name = {ln["name"]: ln for ln in lines}
        assert by_name["c_total"]["kind"] == "counter"
        assert by_name["c_total"]["samples"] == [
            {"labels": {"agent": "a"}, "value": 3.0}]
        assert by_name["h_seconds"]["samples"][0]["count"] == 1


class TestSpans:
    def test_nesting_depth_and_parent(self):
        with telemetry.span("outer") as outer:
            with telemetry.span("inner", k="v") as inner:
                assert telemetry.current_span() is inner
            assert telemetry.current_span() is outer
        assert telemetry.current_span() is None
        assert outer.depth == 0 and outer.parent is None
        assert inner.depth == 1 and inner.parent == "outer"
        assert inner.duration is not None and inner.duration >= 0.0
        assert outer.duration >= inner.duration
        names = [s.name for s in telemetry.recorder().spans()]
        # inner exits (and records) first
        assert names[-2:] == ["inner", "outer"]

    def test_ring_buffer_overflow(self):
        rec = SpanRecorder(capacity=4)
        dropped0 = telemetry.metrics().counter(
            "telemetry_spans_dropped_total").total()
        for i in range(10):
            with telemetry.span(f"s{i}") as sp:
                pass
            rec.record(sp)
        assert rec.total_recorded == 10
        # records evict oldest-first...
        assert [s.name for s in rec.spans()] == ["s6", "s7", "s8", "s9"]
        # ...but the running aggregates survive eviction
        agg = rec.aggregate()
        assert set(agg) == {f"s{i}" for i in range(10)} | {
            "_dropped_spans"}
        assert agg["s0"]["count"] == 1 and agg["s9"]["count"] == 1
        # the overflow is ACCOUNTED, not silent (ISSUE 15 satellite):
        # 10 records through 4 slots evict 6, reported both on the
        # recorder and in the metric the dashboards scrape
        assert rec.dropped == 6
        assert agg["_dropped_spans"]["count"] == 6
        assert telemetry.metrics().counter(
            "telemetry_spans_dropped_total").total() - dropped0 == 6

    def test_ring_buffer_within_capacity_reports_no_drops(self):
        rec = SpanRecorder(capacity=16)
        for i in range(5):
            with telemetry.span(f"k{i}") as sp:
                pass
            rec.record(sp)
        assert rec.dropped == 0
        assert "_dropped_spans" not in rec.aggregate()

    def test_disabled_spans_are_shared_noop(self):
        telemetry.configure(enabled=False)
        a = telemetry.span("a")
        b = telemetry.span("b", with_label="x")
        assert a is b is telemetry.NOOP_SPAN
        with a:
            assert telemetry.current_span() is None
        assert telemetry.recorder().spans() == []

    def test_span_dict_export(self):
        with telemetry.span("x", agent="a") as sp:
            pass
        d = sp.as_dict()
        assert d["name"] == "x" and d["labels"] == {"agent": "a"}
        assert d["duration_s"] == sp.duration


class TestJaxCompileHooks:
    def test_retrace_counter_increments_on_shape_change(self):
        import jax
        import jax.numpy as jnp

        telemetry.install_jax_hooks()

        @jax.jit
        def fn(x):
            return x * 2.0 + 1.0

        def get(name):
            return telemetry.metrics().get(
                name, entry_point="test.retrace") or 0.0

        with telemetry.span("test.retrace"):
            fn(jnp.ones((3,)))
        assert get("jax_traces_total") >= 1
        assert get("jax_compiles_total") >= 1
        assert get("jax_retraces_total") == 0
        assert get("jax_compile_seconds_total") > 0

        with telemetry.span("test.retrace"):
            fn(jnp.ones((3,)))          # cache hit: nothing fires
        assert get("jax_retraces_total") == 0

        with telemetry.span("test.retrace"):
            fn(jnp.ones((5,)))          # static shape change -> retrace
        assert get("jax_retraces_total") == 1

    def test_hooks_silent_when_disabled(self):
        import jax
        import jax.numpy as jnp

        telemetry.install_jax_hooks()
        telemetry.configure(enabled=False)

        @jax.jit
        def fn(x):
            return x + 1.0

        with telemetry.span("test.disabled"):
            fn(jnp.ones((2,)))
        telemetry.configure(enabled=True)
        assert telemetry.metrics().get(
            "jax_traces_total", entry_point="test.disabled") is None


class TestBrokerTelemetry:
    def _broker(self):
        from agentlib_mpc_tpu.runtime.broker import DataBroker

        return DataBroker("agent_t")

    def _var(self, alias):
        from agentlib_mpc_tpu.runtime.variables import AgentVariable

        return AgentVariable(name=alias, alias=alias, value=1.0)

    def test_unmatched_counter_and_single_warning(self, caplog):
        broker = self._broker()
        seen = []
        broker.register_callback("known", None, seen.append)
        with caplog.at_level(logging.WARNING,
                             logger="agentlib_mpc_tpu.runtime.broker"):
            broker.send_variable(self._var("known"))
            broker.send_variable(self._var("typo_alias"))
            broker.send_variable(self._var("typo_alias"))
            broker.send_variable(self._var("typo_alias"))
        get = telemetry.metrics().get
        assert get("broker_messages_total", agent="agent_t") == 4.0
        assert get("broker_callbacks_total", agent="agent_t") == 1.0
        assert get("broker_unmatched_total", agent="agent_t",
                   alias="typo_alias") == 3.0
        warnings = [r for r in caplog.records
                    if "typo_alias" in r.getMessage()]
        assert len(warnings) == 1   # rate-limited: once per alias
        assert len(seen) == 1

    def test_forwarded_shared_variable_does_not_warn(self, caplog):
        from agentlib_mpc_tpu.runtime.broker import BroadcastBus, DataBroker
        from agentlib_mpc_tpu.runtime.variables import AgentVariable

        bus = BroadcastBus()
        a, b = DataBroker("a"), DataBroker("b")
        bus.join(a)
        bus.join(b)
        got = []
        b.register_callback("x", None, got.append)
        with caplog.at_level(logging.WARNING,
                             logger="agentlib_mpc_tpu.runtime.broker"):
            a.send_variable(AgentVariable(name="x", alias="x", value=2.0,
                                          shared=True))
        assert len(got) == 1
        # unmatched on a's *local* table, but forwarded — not a drop:
        # neither warned nor counted (normal broadcast fan-out must not
        # drown the misconfiguration signal)
        assert not [r for r in caplog.records if "dropped" in r.getMessage()]
        assert telemetry.metrics().get("broker_unmatched_total",
                                       agent="a", alias="x") is None
        # ...and the receiving side's external non-match does not count
        # either
        assert telemetry.metrics().get("broker_unmatched_total",
                                       agent="b", alias="x") is None


class TestSolveRecording:
    def _bare_backend(self):
        from agentlib_mpc_tpu.backends.backend import OptimizationBackend

        return OptimizationBackend({})

    def _row(self, success, time=0.0):
        return {"time": time, "iterations": 7, "success": success,
                "kkt_error": 3e-3, "objective": 1.25,
                "constraint_violation": 0.0, "solve_wall_time": 0.01}

    def test_metrics_and_history(self):
        be = self._bare_backend()
        be._record_solve(self._row(True))
        be._record_solve(self._row(True, time=300.0))
        get = telemetry.metrics().get
        assert get("solver_solves_total",
                   backend="OptimizationBackend") == 2.0
        assert get("solver_failures_total",
                   backend="OptimizationBackend") is None
        assert get("solver_iterations",
                   backend="OptimizationBackend") == 2.0  # observation count
        assert be.stats_history == [self._row(True),
                                    self._row(True, time=300.0)]
        be.stats_history.clear()     # back-compat mutation still works
        assert be.stats_history == []

    def test_failure_warns_with_full_stats_row(self, caplog):
        be = self._bare_backend()
        with caplog.at_level(logging.WARNING):
            be._record_solve(self._row(False, time=42.0))
        assert telemetry.metrics().get(
            "solver_failures_total", backend="OptimizationBackend") == 1.0
        msg = " ".join(r.getMessage() for r in caplog.records)
        # the full stats row rides in the warning: iterations AND
        # objective, not just the kkt error (ISSUE 1 satellite)
        for fragment in ("iterations", "objective", "kkt_error", "42.0"):
            assert fragment in msg


class TestStatsHistoryBackCompat:
    """The pre-telemetry `stats_history` contract survives the migration:
    same key schema, same mutability (ISSUE 1 satellite)."""

    EXPECTED_KEYS = {"time", "iterations", "success", "kkt_error",
                     "objective", "constraint_violation", "solve_wall_time",
                     "kkt_path", "jac_path", "init_point_source"}

    @pytest.fixture(scope="class")
    def backend(self):
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent))
        from conftest import make_tracker_model

        from agentlib_mpc_tpu.backends.backend import (
            VariableReference,
            create_backend,
        )

        Tracker = make_tracker_model()
        be = create_backend({
            "type": "jax",
            "model": {"class": Tracker},
            "discretization_options": {"method": "multiple_shooting"},
            "solver": {"max_iter": 30},
        })
        be.setup_optimization(
            VariableReference(controls=["u"], parameters=["a"]),
            time_step=300.0, prediction_horizon=3)
        return be

    def test_solve_row_schema_unchanged(self, backend):
        result = backend.solve(0.0, {})
        assert set(result["stats"].keys()) == self.EXPECTED_KEYS
        assert len(backend.stats_history) == 1
        row = backend.stats_history[0]
        assert set(row.keys()) == self.EXPECTED_KEYS
        assert isinstance(row["iterations"], int)
        assert isinstance(row["success"], bool)
        assert isinstance(row["kkt_error"], float)
        assert isinstance(row["solve_wall_time"], float)
        # per-solve factor-path attribution (lu on CPU for this tiny OCP)
        assert row["kkt_path"] in ("lu", "ldl", "stage")
        # derivative-pipeline attribution (dense: tiny OCP, no plan)
        assert row["jac_path"] in ("dense", "sparse")
        # initial-point provenance (no predictor installed here)
        assert row["init_point_source"] == "plain"

    def test_history_is_mutable_list(self, backend):
        hist = backend.stats_history
        hist.append({"time": -1.0})
        assert backend.stats_history[-1] == {"time": -1.0}
        hist.clear()
        assert backend.stats_history == []


class TestAdmmResidualRecording:
    def test_record_residuals_gauges(self):
        from agentlib_mpc_tpu.ops.admm import record_residuals

        record_residuals(0.5, 0.25, iteration=0, fleet="f")
        record_residuals(0.1, 0.05, iteration=1, fleet="f")
        get = telemetry.metrics().get
        assert get("admm_primal_residual", fleet="f", iteration="0") == 0.5
        assert get("admm_dual_residual", fleet="f", iteration="1") == 0.05
        assert get("admm_iterations_total", fleet="f") == 2.0

    def test_noop_when_disabled(self):
        from agentlib_mpc_tpu.ops.admm import record_residuals

        telemetry.configure(enabled=False)
        record_residuals(1.0, 1.0, iteration=0)
        telemetry.configure(enabled=True)
        assert telemetry.metrics().get("admm_primal_residual",
                                       iteration="0") is None

    def test_trim_removes_stale_round_tail(self):
        from agentlib_mpc_tpu.ops.admm import (
            record_residuals,
            trim_residuals,
        )

        # round 1: 4 iterations; round 2: 2 iterations + trim of the tail
        for k in range(4):
            record_residuals(1.0 / (k + 1), 0.5 / (k + 1), iteration=k,
                             fleet="f")
        for k in range(2):
            record_residuals(0.1 / (k + 1), 0.05 / (k + 1), iteration=k,
                             fleet="f")
        trim_residuals(2, 4, fleet="f")
        get = telemetry.metrics().get
        assert get("admm_primal_residual", fleet="f", iteration="1") == 0.05
        assert get("admm_primal_residual", fleet="f", iteration="2") is None
        assert get("admm_dual_residual", fleet="f", iteration="3") is None


class TestDashboardTelemetryLayer:
    def _snapshot(self):
        reg = MetricsRegistry()
        t = reg.counter("jax_traces_total")
        r = reg.counter("jax_retraces_total")
        c = reg.counter("jax_compiles_total")
        s = reg.counter("jax_compile_seconds_total")
        for ep, n in (("backend.solve", 4), ("admm.fused_step", 2)):
            t.inc(n, entry_point=ep)
            c.inc(n, entry_point=ep)
            s.inc(0.5 * n, entry_point=ep)
        r.inc(entry_point="backend.solve")
        reg.gauge("admm_primal_residual").set(0.5, iteration="0", fleet="f")
        reg.gauge("admm_primal_residual").set(0.2, iteration="1", fleet="f")
        reg.gauge("admm_dual_residual").set(0.4, iteration="0", fleet="f")
        reg.gauge("admm_dual_residual").set(0.1, iteration="1", fleet="f")
        reg.counter("broker_messages_total").inc(5, agent="a")
        return reg.snapshot()

    def test_compile_table(self):
        from agentlib_mpc_tpu.utils.plotting.dashboard import compile_table

        rows = compile_table(self._snapshot())
        assert rows[0]["entry_point"] == "backend.solve"   # heaviest first
        assert rows[0]["compiles"] == 4 and rows[0]["retraces"] == 1
        assert rows[1]["entry_point"] == "admm.fused_step"
        assert rows[1]["compile_seconds"] == pytest.approx(1.0)

    def test_residual_gauge_table(self):
        from agentlib_mpc_tpu.utils.plotting.dashboard import (
            residual_gauge_table,
        )

        rows = residual_gauge_table(self._snapshot())
        assert [(r[0], r[1], r[2]) for r in rows] == [
            (0, 0.5, 0.4), (1, 0.2, 0.1)]

    def test_scalar_rows_prefix_filter(self):
        from agentlib_mpc_tpu.utils.plotting.dashboard import scalar_rows

        rows = scalar_rows(self._snapshot(), prefix="broker_")
        assert rows == [("broker_messages_total", "agent=a", 5.0)]

    def test_span_summary_sorted(self):
        from agentlib_mpc_tpu.utils.plotting.dashboard import span_summary

        rec = SpanRecorder(capacity=8)
        for name, dur in (("fast", 0.01), ("slow", 0.5), ("fast", 0.02)):
            with telemetry.span(name) as sp:
                pass
            sp.duration = dur      # deterministic totals
            rec.record(sp)
        rows = span_summary(rec)
        assert rows[0][0] == "slow" and rows[0][1] == 1
        assert rows[1][0] == "fast" and rows[1][1] == 2
        assert rows[1][2] == pytest.approx(0.03)
