"""Central-MPC backend: one jitted transcribe+solve pipeline.

The counterpart of the reference's CasADi backend core
(``optimization_backends/casadi_/core/casadi_backend.py``: setup :108-131,
solve :133-139, per-solve input sampling :141-253) and its basic/full
system variants (``casadi_/basic.py``, ``casadi_/full.py`` — the Δu change
penalty arrives here via the model's ``v.du``). Where the reference drives
a C++ IPOPT process per solve, this backend compiles the whole step — input
splicing, warm start, interior-point solve, trajectory extraction, shift —
into a single XLA computation held hot across the closed loop.

Accepts the reference's config keys (``discretization_options``,
``solver``, ``results_file``/``save_results``) with native equivalents.
"""

from __future__ import annotations

import time as _time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.backends.backend import (
    OptimizationBackend,
    VariableReference,
    load_model,
    register_backend,
)
from agentlib_mpc_tpu.ops.solver import SolverOptions, solve_nlp
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.utils.sampling import InterpolationMethods, sample


def transcription_kwargs_from_config(disc: dict) -> dict:
    """Translate reference-style ``discretization_options`` into `transcribe`
    keyword arguments (shared by the MPC, MHE and MINLP backends)."""
    disc = dict(disc or {})
    if disc.get("method", "collocation") == "multiple_shooting":
        return dict(
            method="multiple_shooting",
            integrator=disc.get("integrator", "rk4"),
            integrator_substeps=int(disc.get("integrator_substeps", 3)),
        )
    return dict(
        method="collocation",
        collocation_degree=int(disc.get("collocation_order", 3)),
        collocation_method=disc.get("collocation_method", "radau"),
    )


def solver_options_from_config(cfg: dict) -> SolverOptions:
    """Translate a reference-style solver config into SolverOptions.
    Unknown keys (e.g. the reference's ipopt-specific options) are ignored
    so existing configs keep working."""
    cfg = dict(cfg or {})
    cfg.pop("name", None)  # reference: solver name (ipopt/fatrop/...)
    cfg.pop("options", None)
    # derived, not config-expressible: the backends attach these from the
    # transcribed OCP (attach_stage_partition / attach_derivative_plan)
    # after transcription
    cfg.pop("stage_partition", None)
    cfg.pop("stage_jacobian_plan", None)
    known = SolverOptions._fields
    return SolverOptions(**{k: v for k, v in cfg.items() if k in known})


def attach_stage_partition(options: SolverOptions, ocp) -> SolverOptions:
    """Wire a transcribed OCP's stage partition into solver options (the
    fatrop-role plumbing, shared by the MPC/MHE/ADMM/MINLP backends;
    the fused fleet routes through the same underlying rule):
    ``kkt_method="auto"`` then routes long-horizon KKT systems to the
    block-tridiagonal stage sweep, and ``"stage"`` can be forced from
    config. A config dict cannot express the partition itself — it is
    derived structure, not a knob."""
    from agentlib_mpc_tpu.ops.solver import attach_stage_partition as attach

    return attach(options, getattr(ocp, "stage_partition", None))


def attach_derivative_plan(options: SolverOptions, ocp, nlp=None,
                           theta=None, logger=None,
                           label: "str | None" = None) -> SolverOptions:
    """Wire the stage-sparse derivative plan (``ops/stagejac.py``) into
    solver options — the derivative-side sibling of
    :func:`attach_stage_partition`, shared by every backend seam.

    The plan is built from the jaxpr stage-structure certificate of the
    functions ACTUALLY SOLVED: pass ``nlp``/``theta`` for augmented
    problems (the ADMM backends certify their consensus-augmented
    objective, mirroring their LQ routing); by default the OCP's own
    ``nlp`` is certified. Skipped entirely (no certifier cost) when
    ``plan_worthwhile`` says the solve could never route sparse —
    ``jacobian="dense"``, no partition, a problem below the crossover
    floors, or a platform where "auto" never reaches the stage factor.
    Thin ocp-aware wrapper over ``stagejac.attach_plan_if_worthwhile``
    (the one gate+certify+attach seam; the fused fleet calls it
    directly)."""
    from agentlib_mpc_tpu.ops import stagejac

    return stagejac.attach_plan_if_worthwhile(
        options, getattr(ocp, "stage_partition", None),
        ocp.nlp if nlp is None else nlp,
        ocp.default_params() if theta is None else theta,
        ocp.n_w, log=logger, label=label or "the transcribed OCP")


@register_backend("jax", "jax_full", "casadi", "casadi_basic")
class JAXBackend(OptimizationBackend):
    """Central MPC: states/controls/inputs/params against one model."""

    def setup_optimization(self, var_ref: VariableReference,
                           time_step: float, prediction_horizon: int) -> None:
        if var_ref.binary_controls:
            raise NotImplementedError(
                "this backend ignores binary_controls; use the MINLP "
                "backend (type 'jax_minlp') for mixed-integer problems")
        self.var_ref = var_ref
        self.time_step = float(time_step)
        self.N = int(prediction_horizon)
        self.model = load_model(self.config["model"])
        trans_kwargs = transcription_kwargs_from_config(
            self.config.get("discretization_options"))
        self.ocp = transcribe(self.model, var_ref.controls, N=self.N,
                              dt=self.time_step, **trans_kwargs)
        self.solver_options = attach_derivative_plan(
            attach_stage_partition(
                solver_options_from_config(self.config.get("solver")),
                self.ocp),
            self.ocp, logger=self.logger,
            label=f"the {type(self).__name__} OCP")
        self._exo_names = list(self.ocp.exo_names)
        self._resolve_qp_fast_path()
        self._build_step_fn()
        self._reset_warm_start()
        if self.config.get("precompile"):
            self._precompile()

    def _resolve_qp_fast_path(self) -> None:
        """Route LQ problems (linear model, quadratic objective) to the
        structure-exploiting Mehrotra QP solver — the role qpoases/osqp/
        proxqp play in the reference's solver menu
        (``data_structures/casadi_utils.py:52-61,127-161``). Config key
        ``solver.qp_fast_path``: ``"auto"`` (default — the jaxpr-level
        LQ certificate decides at setup, sound for every theta, with the
        sampled probe as cross-check/fallback), ``"on"`` (force; the
        caller asserts LQ-ness), ``"off"``."""
        from agentlib_mpc_tpu.ops.qp import is_lq, resolve_qp_routing

        theta0 = self.ocp.default_params()
        n = int(self.ocp.initial_guess(theta0).shape[0])

        def certifier():
            from agentlib_mpc_tpu.lint.jaxpr import certify_lq

            return certify_lq(self.ocp.nlp, theta0, n)

        def probe():
            return is_lq(self.ocp.nlp, theta0, n)

        self.uses_qp_fast_path = resolve_qp_routing(
            str((self.config.get("solver") or {})
                .get("qp_fast_path", "auto")),
            probe, logger=self.logger,
            label=f"the {type(self).__name__} OCP",
            certifier=certifier)

    def _precompile(self) -> None:
        """Trigger XLA compilation at setup with default inputs so the first
        real-time control step meets its wall-clock budget (the reference
        pays this cost to CasADi codegen/DLL compilation instead,
        ``casadi_utils.py:313-369``; here it is one throwaway solve).
        Telemetry recording is suppressed for the throwaway solve (the
        compile still attributes to the ``backend.solve`` span)."""
        self._suppress_record = True
        try:
            self.solve(0.0, {})
        finally:
            self._suppress_record = False
        self.stats_history.clear()
        self._reset_warm_start()

    # -- compiled pipeline ----------------------------------------------------

    def _build_step_fn(self) -> None:
        ocp = self.ocp
        opts = self.solver_options
        if getattr(self, "uses_qp_fast_path", False):
            from agentlib_mpc_tpu.ops.qp import solve_qp as solver_fn
        else:
            solver_fn = solve_nlp

        @jax.jit
        def step(x0, u_prev, d_traj, p, x_lb, x_ub, u_lb, u_ub,
                 w_guess, y_guess, z_guess, mu0, t0):
            theta = ocp.default_params(
                x0=x0, u_prev=u_prev, d_traj=d_traj, p=p,
                x_lb=x_lb, x_ub=x_ub, u_lb=u_lb, u_ub=u_ub, t0=t0)
            lb, ub = ocp.bounds(theta)
            res = solver_fn(ocp.nlp, w_guess, theta, lb, ub, opts,
                            y0=y_guess, z0=z_guess, mu0=mu0)
            traj = ocp.trajectories(res.w, theta)
            u0 = jnp.clip(traj["u"][0], theta.u_lb[0], theta.u_ub[0])
            w_next = ocp.shift_guess(res.w, theta)
            return u0, traj, w_next, res.y, res.z, res.stats

        self._step = step

    def _reset_warm_start(self) -> None:
        theta0 = self.ocp.default_params()
        self._w_guess = self.ocp.initial_guess(theta0)
        self._y_guess = jnp.zeros((self.ocp.n_g,))
        self._z_guess = jnp.full((self.ocp.n_h,), 0.1).astype(
            self._w_guess.dtype)
        self._cold = True

    # -- per-solve input assembly (host side) ---------------------------------

    def _collect(self, now: float, variables: dict[str, Any]):
        model = self.model
        vr = self.var_ref
        N = self.N
        grid_u = np.arange(N) * self.time_step

        def val_of(name, default):
            v = variables.get(name)
            return default if v is None else v

        x0 = np.array([
            float(np.asarray(val_of(n, model.get_var(n).value)).reshape(-1)[0])
            for n in model.diff_state_names])
        u_prev = np.array([
            float(np.asarray(val_of(n, model.get_var(n).value)).reshape(-1)[0])
            for n in vr.controls]) if vr.controls else np.zeros(0)

        d_traj = np.zeros((N, len(self._exo_names)))
        for j, name in enumerate(self._exo_names):
            d_traj[:, j] = sample(val_of(name, model.get_var(name).value),
                                  grid_u, current=now)

        p = np.array([float(val_of(n, model.get_var(n).value))
                      for n in model.parameter_names])

        def bound_traj(names, grid, kind):
            out = np.zeros((len(grid), len(names)))
            for j, n in enumerate(names):
                b = variables.get(f"{n}__{kind}")
                if b is None:
                    b = getattr(model.get_var(n), kind)
                out[:, j] = sample(b, grid, current=now)
            return out

        grid_x = np.arange(N + 1) * self.time_step
        x_lb = bound_traj(model.diff_state_names, grid_x, "lb")
        x_ub = bound_traj(model.diff_state_names, grid_x, "ub")
        u_lb = bound_traj(vr.controls, grid_u, "lb")
        u_ub = bound_traj(vr.controls, grid_u, "ub")
        return x0, u_prev, d_traj, p, x_lb, x_ub, u_lb, u_ub

    def solve(self, now: float, variables: dict[str, Any]) -> dict:
        x0, u_prev, d_traj, p, x_lb, x_ub, u_lb, u_ub = \
            self._collect(now, variables)
        mu0 = jnp.asarray(self.solver_options.mu_init if self._cold else 1e-2,
                          dtype=self._w_guess.dtype)
        t_start = _time.perf_counter()
        with telemetry.span("backend.solve", backend=type(self).__name__,
                            instance=f"{id(self):x}"):
            u0, traj, w_next, y_next, z_next, stats = self._step(
                x0, u_prev, d_traj, p, x_lb, x_ub, u_lb, u_ub,
                self._w_guess, self._y_guess, self._z_guess, mu0,
                jnp.asarray(float(now)))
            u0.block_until_ready()
        wall = _time.perf_counter() - t_start
        self._carry_warm_start(w_next, y_next, z_next, now=now)

        stats_row = self.solver_stats_row(stats, now, wall)
        self._record_solve(stats_row)
        return {
            "u0": {n: float(u0[i]) for i, n in enumerate(self.var_ref.controls)},
            "traj": {k: np.asarray(v) for k, v in traj.items()},
            "stats": stats_row,
        }


# -- scenario-tree robust solve (ISSUE 12 backend seam) -----------------------

_SCENARIO_ENGINES: dict = {}
_SCENARIO_ENGINES_MAX = 8


def scenario_engine(ocp, tree, solver_options: SolverOptions,
                    fleet_options=None):
    """One cached single-agent scenario engine per (OCP, tree, options)
    structure: the backend-level entry to scenario-tree robust MPC. A
    single agent with no consensus aliases leaves exactly the
    non-anticipativity coupling — the robust solve proper — so a
    backend can evaluate S disturbance branches in one fused call
    instead of the reference's S serial solves. Engines are memoized
    (bounded, oldest-out) because a ScenarioFleet build pays a solver
    trace; steady-state calls reuse the compiled round."""
    from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup
    from agentlib_mpc_tpu.scenario import (
        ScenarioFleet,
        ScenarioFleetOptions,
    )

    fleet_options = fleet_options or ScenarioFleetOptions()
    key = (id(ocp), tree, solver_options, fleet_options)
    hit = _SCENARIO_ENGINES.get(key)
    if hit is not None:
        return hit[0]
    group = AgentGroup(name="scenario-backend", ocp=ocp, n_agents=1,
                       solver_options=solver_options)
    fleet = ScenarioFleet(group, tree, fleet_options)
    while len(_SCENARIO_ENGINES) >= _SCENARIO_ENGINES_MAX:
        _SCENARIO_ENGINES.pop(next(iter(_SCENARIO_ENGINES)))
    # pin the ocp so a recycled id() can never alias a different
    # structure (the FusedADMM certificate-memo pattern)
    _SCENARIO_ENGINES[key] = (fleet, ocp)
    return fleet


def robust_scenario_controls(ocp, theta, tree,
                             solver_options: SolverOptions = SolverOptions(),
                             fleet_options=None, state=None):
    """Solve one agent's scenario tree and return the robust controls:
    ``(u0 (n_u,), state, stats)`` where ``u0`` is the
    non-anticipativity projection's first-interval group mean —
    identical across every branch by construction, the scenario-tree
    analogue of the nominal backend's ``u[0]``. ``theta`` is a
    scenario-stacked (S, ...) OCPParams batch
    (:func:`agentlib_mpc_tpu.scenario.generate.ensemble_thetas` builds
    it from a nominal theta + seed); pass the returned ``state`` back
    in for warm-started re-solves."""
    fleet = scenario_engine(ocp, tree, solver_options, fleet_options)
    theta_batch = jax.tree.map(lambda leaf: leaf[None], theta)
    if state is None:
        state = fleet.init_state(theta_batch)
    state, _trajs, stats = fleet.step(state, theta_batch)
    u0 = np.asarray(fleet.actuated_u0(state))[0, 0]
    return u0, fleet.shift_state(state), stats
