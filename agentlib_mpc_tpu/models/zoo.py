"""Built-in example models ("model zoo").

Native re-designs of the dynamics used across the reference's example
families (``examples/one_room_mpc/physical/simple_mpc.py:27-138``,
``examples/admm/models/{ca_room_model,ca_cooler_model}.py``): single-zone
cooling, the cooled-room / cooler pair coupled through an air mass flow
(the consensus-ADMM benchmark topology), and a synthetic N-zone building
for scale-out benchmarks. The physics is the standard 1R1C air-volume
energy balance:

    dT/dt = cp * mDot / C * (T_in - T) + load / C

All models are plain :class:`~agentlib_mpc_tpu.models.model.Model`
subclasses — pure JAX, jit/vmap/grad-safe.
"""

from __future__ import annotations

from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.objective import SubObjective
from agentlib_mpc_tpu.models.variables import (
    control_input,
    output,
    parameter,
    state,
)


class OneRoom(Model):
    """Flagship single-zone cooling model (central MPC).

    Air-volume zone with soft comfort constraint ``T + s <= T_upper`` and
    cost ``r_mDot * mDot + s_T * s**2`` — the reference's one-room example
    (``examples/one_room_mpc/physical/simple_mpc.py:27-138``).
    """

    inputs = [
        control_input("mDot", 0.0225, lb=0.0, ub=0.05, unit="m^3/s",
                      description="cooling air mass flow (control)"),
        control_input("load", 150.0, unit="W", description="heat load"),
        control_input("T_in", 290.15, unit="K",
                      description="inflow air temperature"),
        control_input("T_upper", 294.15, unit="K",
                      description="soft upper comfort bound"),
    ]
    states = [
        state("T", 293.15, lb=288.15, ub=303.15, unit="K",
              description="zone temperature"),
        state("T_slack", 0.0, unit="K", description="comfort slack"),
    ]
    parameters = [
        parameter("cp", 1000.0, unit="J/kg*K"),
        parameter("C", 100000.0, unit="J/K"),
        parameter("s_T", 1.0, description="slack weight"),
        parameter("r_mDot", 1.0, description="air flow cost weight"),
    ]
    outputs = [output("T_out", unit="K")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", v.cp * v.mDot / v.C * (v.T_in - v.T) + v.load / v.C)
        eq.alg("T_out", v.T)
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        eq.objective = (
            SubObjective(v.mDot, weight=v.r_mDot, name="control_costs")
            + SubObjective(v.T_slack ** 2, weight=v.s_T, name="temp_slack")
        )
        return eq


class CooledRoom(Model):
    """Room half of the ADMM pair: ``mDot`` is a *coupling* input the room
    optimizes locally but must agree on with the cooler (reference
    ``examples/admm/models/ca_room_model.py``). The room pays only for
    comfort (slack), not for the air it requests.
    """

    inputs = [
        control_input("mDot", 0.0225, lb=0.0, ub=0.05, unit="m^3/s",
                      description="air mass flow into the zone (coupling)"),
        control_input("load", 150.0, unit="W"),
        control_input("T_in", 290.15, unit="K"),
        control_input("T_upper", 294.15, unit="K"),
    ]
    states = [
        state("T", 293.15, lb=288.15, ub=303.15, unit="K"),
        state("T_slack", 0.0, unit="K"),
    ]
    parameters = [
        parameter("cp", 1000.0),
        parameter("C", 100000.0),
        parameter("s_T", 1.0),
    ]
    outputs = [output("T_out", unit="K")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", v.cp * v.mDot / v.C * (v.T_in - v.T) + v.load / v.C)
        eq.alg("T_out", v.T)
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        eq.objective = SubObjective(v.T_slack ** 2, weight=v.s_T,
                                    name="temp_slack")
        return eq


class Cooler(Model):
    """Cooler half of the ADMM pair: purely static, supplies ``mDot`` at
    cost ``r_mDot * mDot`` (reference ``ca_cooler_model.py``)."""

    inputs = [
        control_input("mDot", 0.0225, lb=0.0, ub=0.05, unit="m^3/s",
                      description="air mass flow out of the cooler"),
    ]
    parameters = [parameter("r_mDot", 1.0)]
    outputs = [output("mDot_out", 0.0225, unit="m^3/s")]

    def setup(self, v):
        eq = ModelEquations()
        eq.alg("mDot_out", v.mDot)
        eq.objective = SubObjective(v.mDot, weight=v.r_mDot,
                                    name="control_costs")
        return eq


class ZoneWithSupply(Model):
    """Synthetic scale-out zone: a cooled room that also pays for its air
    request — the per-zone subproblem of the N-zone exchange-ADMM benchmark
    (BASELINE.json "synthetic 256-zone building"). Zones differ only in
    their ``load``/``C`` parameters, so N of them vmap into one batch.
    """

    inputs = [
        control_input("mDot", 0.0225, lb=0.0, ub=0.05, unit="m^3/s",
                      description="air mass flow (exchange coupling)"),
        control_input("load", 150.0, unit="W"),
        control_input("T_in", 290.15, unit="K"),
        control_input("T_upper", 294.15, unit="K"),
    ]
    states = [
        state("T", 293.15, lb=288.15, ub=303.15, unit="K"),
        state("T_slack", 0.0, unit="K"),
    ]
    parameters = [
        parameter("cp", 1000.0),
        parameter("C", 100000.0),
        parameter("s_T", 1.0),
        parameter("r_mDot", 0.01),
    ]
    outputs = [output("T_out", unit="K")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", v.cp * v.mDot / v.C * (v.T_in - v.T) + v.load / v.C)
        eq.alg("T_out", v.T)
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        eq.objective = (
            SubObjective(v.mDot, weight=v.r_mDot, name="control_costs")
            + SubObjective(v.T_slack ** 2, weight=v.s_T, name="temp_slack")
        )
        return eq


class LinearRCZone(Model):
    """Linear 1R1C zone with DIRECT thermal-power actuation — the
    canonical *linear* MPC formulation of building control (the problem
    class the reference hands to its QP solvers qpoases/osqp/proxqp,
    ``data_structures/casadi_utils.py:52-61``). Where :class:`OneRoom`
    actuates an air mass flow (bilinear ``mDot·(T_in − T)`` term ⇒ a
    genuine NLP), here the control is the cooling power ``Q`` itself:

        dT/dt = (load − Q) / C + (T_amb − T) / (R·C)

    — affine dynamics, quadratic objective, affine constraints: an LQ
    program end to end, which the ``jax`` backend's structure probe
    certifies and routes to the Mehrotra QP fast path (``ops/qp.py``).
    """

    inputs = [
        control_input("Q", 0.0, lb=0.0, ub=500.0, unit="W",
                      description="cooling power extracted from the zone"),
        control_input("load", 150.0, unit="W"),
        control_input("T_amb", 303.15, unit="K"),
        control_input("T_upper", 295.15, unit="K"),
    ]
    states = [
        state("T", 293.15, lb=288.15, ub=310.15, unit="K"),
        state("T_slack", 0.0, unit="K"),
    ]
    parameters = [
        parameter("C", 100000.0, description="thermal capacity J/K"),
        parameter("R", 0.05, description="envelope resistance K/W"),
        parameter("s_T", 1.0),
        parameter("r_Q", 1e-3),
    ]
    outputs = [output("T_out", unit="K")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", (v.load - v.Q) / v.C + (v.T_amb - v.T) / (v.R * v.C))
        eq.alg("T_out", v.T)
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        eq.objective = (
            SubObjective(v.Q, weight=v.r_Q, name="energy")
            + SubObjective(v.T_slack ** 2, weight=v.s_T, name="temp_slack")
        )
        return eq


class AirHandlingUnit(Model):
    """Central air-handling unit serving four zones — the supplier half of
    the 4-room coordinated-ADMM benchmark (reference
    ``examples/4_Room_ADMM_Coordinator/models/rlt_model.py``): four air
    mass flows, one shared capacity constraint ``sum(mDot_i) <= mDot_max``,
    flow production cost. Each ``mDot_out_i`` couples to room ``i``'s
    requested flow via consensus-ADMM.
    """

    inputs = [
        control_input(f"mDot_{i}", 0.0225, lb=0.0, ub=0.05, unit="m^3/s",
                      description=f"air mass flow to zone {i}")
        for i in range(1, 5)
    ]
    parameters = [
        parameter("mDot_max", 0.075, unit="m^3/s",
                  description="total AHU capacity"),
        parameter("r_mDot", 1.0, description="flow production cost weight"),
    ]
    outputs = [output(f"mDot_out_{i}", 0.0225, unit="m^3/s")
               for i in range(1, 5)]

    def setup(self, v):
        eq = ModelEquations()
        total = v.mDot_1 + v.mDot_2 + v.mDot_3 + v.mDot_4
        for i in range(1, 5):
            eq.alg(f"mDot_out_{i}", getattr(v, f"mDot_{i}"))
        eq.constraint(0.0, total, v.mDot_max)
        eq.objective = SubObjective(total, weight=v.r_mDot,
                                    name="flow_costs")
        return eq


class ExchangeRoom(Model):
    """Zone for the exchange-ADMM benchmark (reference
    ``examples/exchange_admm/models/room_model.py``): the room optimizes
    its own air request ``mDot`` (actuated per-room) and mirrors it into
    the exchange variable ``mDot_out = mDot``; the exchange mean-zero
    condition across all zones + the supplier balances total consumption
    against supply.
    """

    inputs = [
        control_input("mDot", 0.0225, lb=0.0, ub=0.05, unit="m^3/s",
                      description="air mass flow into the zone"),
        control_input("load", 150.0, unit="W"),
        control_input("T_in", 290.15, unit="K"),
        control_input("T_upper", 294.15, unit="K"),
    ]
    states = [
        state("T", 293.15, lb=288.15, ub=303.15, unit="K"),
        state("T_slack", 0.0, unit="K"),
    ]
    parameters = [
        parameter("cp", 1000.0),
        parameter("C", 100000.0),
        parameter("s_T", 1.0),
    ]
    outputs = [
        output("T_out", unit="K"),
        output("mDot_out", 0.0225, unit="m^3/s",
               description="net flow (positive = consumption)"),
    ]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", v.cp * v.mDot / v.C * (v.T_in - v.T) + v.load / v.C)
        eq.alg("T_out", v.T)
        eq.alg("mDot_out", v.mDot)
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        eq.objective = SubObjective(v.T_slack ** 2, weight=v.s_T,
                                    name="temp_slack")
        return eq


class AirSupplier(Model):
    """Supplier half of the exchange-ADMM benchmark (reference
    ``examples/exchange_admm/models/rlt_model.py``): produces air flow at
    cost; its *negative* net flow ``mDot_net = -mDot`` enters the exchange
    coupling so that the exchange mean-zero condition enforces
    supply = total zone consumption.
    """

    inputs = [
        control_input("mDot", 0.05, lb=0.0, ub=0.2, unit="m^3/s",
                      description="total air mass flow produced"),
    ]
    parameters = [parameter("r_mDot", 1.0)]
    outputs = [output("mDot_net", -0.05, unit="m^3/s",
                      description="net flow (negative = supply)")]

    def setup(self, v):
        eq = ModelEquations()
        eq.alg("mDot_net", -v.mDot)
        eq.objective = SubObjective(v.mDot, weight=v.r_mDot,
                                    name="flow_costs")
        return eq


class SwitchedRoom(Model):
    """Single zone with an on/off chiller — the mixed-integer benchmark
    (reference ``examples/one_room_mpc/mixed_integer``: a binary cooling
    stage enters the energy balance; the MPC must schedule it). The binary
    control ``on`` is declared as an ordinary [0,1] input; the MINLP/CIA
    backends enforce integrality (``backends/minlp_backend.py``).
    """

    inputs = [
        control_input("on", 0.0, lb=0.0, ub=1.0,
                      description="chiller stage on/off (binary control)"),
        control_input("load", 180.0, unit="W", description="heat load"),
        control_input("T_upper", 295.15, unit="K",
                      description="soft upper comfort bound"),
    ]
    states = [
        state("T", 294.15, lb=288.15, ub=303.15, unit="K"),
        state("T_slack", 0.0, unit="K"),
    ]
    parameters = [
        parameter("C", 100000.0, unit="J/K"),
        parameter("Q_cool", 500.0, unit="W", description="chiller capacity"),
        parameter("s_T", 10.0, description="comfort slack weight"),
        parameter("r_on", 0.01, description="chiller run cost"),
    ]
    outputs = [output("T_out", unit="K")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", (v.load - v.on * v.Q_cool) / v.C)
        eq.alg("T_out", v.T)
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        eq.objective = (
            SubObjective(v.on, weight=v.r_on, name="chiller_costs")
            + SubObjective(v.T_slack ** 2, weight=v.s_T, name="temp_slack")
        )
        return eq
