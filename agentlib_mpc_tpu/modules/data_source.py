"""Data source: replay a table onto the broker on a sample grid.

Counterpart of the reference's ``DataSource``
(``modules/data_source.py``: config :15-75, replay loop :170-182,
interpolated lookup :134-168): a CSV file / DataFrame / dict of columns is
normalized to a numeric seconds index and each configured output column is
published every ``t_sample`` with linear or zero-order-hold interpolation,
with an optional ``data_offset`` shifting the table's time axis.
"""

from __future__ import annotations

import logging
from pathlib import Path

import numpy as np

from agentlib_mpc_tpu.runtime.module import BaseModule, register_module
from agentlib_mpc_tpu.utils.sampling import interpolate_to_previous

logger = logging.getLogger(__name__)


@register_module("data_source")
class DataSource(BaseModule):
    """Config keys: ``data`` (csv path | DataFrame | {col: {t: v}}),
    ``t_sample``, ``data_offset`` (seconds added to lookup time),
    ``interpolation_method`` ("linear" | "previous"), ``outputs`` (the
    columns to publish; empty = all columns)."""

    variable_groups = ("outputs",)
    shared_groups = ("outputs",)

    def __init__(self, config: dict, agent):
        super().__init__(config, agent)
        self.t_sample = float(config.get("t_sample", 1.0))
        self.data_offset = float(config.get("data_offset", 0.0))
        self.method = config.get("interpolation_method", "linear")
        if self.method not in ("linear", "previous"):
            raise ValueError(
                f"interpolation_method must be 'linear' or 'previous', got "
                f"{self.method!r}")
        self.data = self._load_table(config["data"])
        cols = self._groups.get("outputs") or list(self.data)
        missing = [c for c in cols if c not in self.data]
        if missing:
            raise ValueError(f"data source columns not in table: {missing}")
        self.columns = cols
        # columns that were not declared as outputs are still published
        # under their own name (reference publishes every column)
        from agentlib_mpc_tpu.runtime.variables import AgentVariable

        for c in cols:
            if c not in self.vars:
                var = AgentVariable(name=c, shared=True)
                self._declare(var, "outputs")
                self._groups["outputs"].append(c)

    @staticmethod
    def _normalize_index(index) -> np.ndarray:
        """datetime → seconds since start; numeric stays (reference
        datetime normalization, ``data_source.py:96-132``)."""
        import pandas as pd

        idx = pd.Index(index)
        if isinstance(idx, pd.DatetimeIndex):
            return (idx - idx[0]).total_seconds().to_numpy()
        return idx.to_numpy(dtype=float)

    def _load_table(self, data) -> dict[str, tuple[np.ndarray, np.ndarray]]:
        import pandas as pd

        if isinstance(data, (str, Path)):
            from agentlib_mpc_tpu.utils.try_format import (
                is_try_file,
                read_try_file,
            )

            if is_try_file(data):
                # German TRY weather dataset (the reference's TRYPredictor
                # input format, ``modules/InputPrediction/try_predictor.py``)
                df = read_try_file(data)
            else:
                df = pd.read_csv(data, index_col=0)
                try:
                    df.index = pd.to_datetime(df.index)
                except (ValueError, TypeError):
                    pass
        elif isinstance(data, pd.DataFrame):
            df = data
        elif isinstance(data, dict):
            df = pd.DataFrame(data)
        else:
            raise TypeError(f"unsupported data source type {type(data)}")
        if df.empty:
            raise ValueError("data source table is empty")
        times = self._normalize_index(df.index)
        order = np.argsort(times)
        return {
            str(c): (times[order],
                     df[c].to_numpy(dtype=float)[order])
            for c in df.columns}

    def get_data_at_time(self, t: float) -> dict[str, float]:
        t = t + self.data_offset
        out = {}
        for c in self.columns:
            times, vals = self.data[c]
            if self.method == "previous":
                out[c] = float(interpolate_to_previous([t], times, vals)[0])
            else:
                out[c] = float(np.interp(t, times, vals))
        return out

    def process(self):
        while True:
            for name, value in self.get_data_at_time(
                    float(self.env.now)).items():
                self.set(name, value)
            yield self.t_sample
