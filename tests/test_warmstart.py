"""Learned warm starts (ml/warmstart.py): fingerprint-keyed initial-point
prediction trained from the journal tape.

Covers the PR's acceptance surface end to end on the CPU tracker model:

- the serialized predictor round-trips through the EngineStore artifact
  path bitwise (same prediction before and after revive);
- structural-fingerprint drift REFUSES the artifact (plain starts, never
  a mis-matched prediction);
- the in-graph KKT gate selects the plain start bitwise when the
  predictor is corrupted (NaN weights), and counts the rejection;
- the chaos ``WarmstartPoisonRule`` degrades latency, never actuation:
  zero failed actuations, and the injection -> rejection -> recovery
  chain is reconstructible from the journal alone;
- the dataset CLI is deterministic: two extractions of the same journal
  are byte-identical.
"""

import json
import os
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax.numpy as jnp

from conftest import make_tracker_model  # noqa: E402

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.ml.training import fit_warmstart
from agentlib_mpc_tpu.ml.warmstart import (
    WarmstartDriftError,
    build_warmstart,
    flatten_theta,
    load_warmstart,
    make_gated_init,
    plain_init,
    save_warmstart,
    theta_flat_size,
)
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
from agentlib_mpc_tpu.resilience import install_serving_chaos
from agentlib_mpc_tpu.serving import ServingPlane, TenantSpec
from agentlib_mpc_tpu.serving.fingerprint import tenant_fingerprint
from agentlib_mpc_tpu.serving.store import EngineStore
from agentlib_mpc_tpu.telemetry.journal import read_events

ADMM = FusedADMMOptions(max_iterations=6, rho=2.0)
SOL = SolverOptions(max_iter=30)


@pytest.fixture(scope="module")
def tracker_ocp():
    Tracker = make_tracker_model(lb=-5.0, ub=5.0)
    return transcribe(Tracker(), ["u"], N=5, dt=300.0,
                      method="multiple_shooting")


def _spec(ocp, tid, a):
    return TenantSpec(tenant_id=tid, ocp=ocp,
                      theta=ocp.default_params(p=jnp.array([float(a)])),
                      couplings={"shared_u": "u"}, solver_options=SOL)


@pytest.fixture(scope="module")
def tape(tracker_ocp, tmp_path_factory):
    """One served tape: journal + EngineStore dir + a model trained from
    the journal replay (never a live hook)."""
    tmp = tmp_path_factory.mktemp("warmstart")
    journal = str(tmp / "journal.jsonl")
    store = str(tmp / "store")
    telemetry.configure(enabled=True)
    telemetry.enable_journal(journal)
    try:
        plane = ServingPlane(ADMM, slot_multiple=1, initial_capacity=4,
                             engine_store=store, warmstart_tape=True)
        for i, a in enumerate([0.5, 1.5, 2.5]):
            plane.join(_spec(tracker_ocp, f"s{i}", a))
        for _ in range(4):
            for i in range(3):
                plane.submit(f"s{i}")
            plane.serve_round()
    finally:
        telemetry.disable_journal()
    from agentlib_mpc_tpu.telemetry.__main__ import dataset_from_events

    data, _meta = dataset_from_events(read_events(journal))
    fp = tenant_fingerprint(tracker_ocp).digest
    model = fit_warmstart(data, fingerprint=fp, aliases=["shared_u"],
                          trainer_config={"hidden": (16,), "epochs": 150,
                                          "seed": 0})
    return {"journal": journal, "store": store, "model": model, "fp": fp}


# -- serialization round-trip via EngineStore --------------------------------

def test_roundtrip_bitwise_via_store(tracker_ocp, tape):
    model = tape["model"]
    store = EngineStore(tape["store"])
    save_warmstart(store, model)
    revived = load_warmstart(store, tape["fp"])
    assert revived is not None
    assert revived.fingerprint == model.fingerprint
    assert revived.heads == model.heads

    b0 = build_warmstart(model, ocp=tracker_ocp)
    b1 = build_warmstart(revived, ocp=tracker_ocp)
    theta = tracker_ocp.default_params(p=jnp.array([1.25]))
    x = flatten_theta(theta)
    out0 = np.asarray(b0.apply(b0.params, x))
    out1 = np.asarray(b1.apply(b1.params, x))
    # bitwise: the artifact is content-addressed, a revive must not
    # perturb the prediction by even one ulp
    assert out0.tobytes() == out1.tobytes()


def test_load_warmstart_absent_is_plain(tape):
    store = EngineStore(tape["store"])
    assert load_warmstart(store, "no-such-fingerprint") is None


# -- fingerprint drift = refuse ----------------------------------------------

def test_fingerprint_drift_refused(tracker_ocp, tape):
    import dataclasses

    model = tape["model"]
    drifted = dataclasses.replace(model, fingerprint="f" * 16)
    with pytest.raises(WarmstartDriftError, match="drift"):
        build_warmstart(drifted, ocp=tracker_ocp)
    with pytest.raises(WarmstartDriftError):
        build_warmstart(dataclasses.replace(model, fingerprint=""),
                        ocp=tracker_ocp)
    # matching digest passes
    assert build_warmstart(model, fingerprint=tape["fp"]) is not None


def test_trainer_config_configures_trainer(tracker_ocp, tape):
    n_theta = theta_flat_size(tracker_ocp)
    rng = np.random.default_rng(0)
    data = {"theta": rng.normal(size=(6, n_theta)),
            "w": rng.normal(size=(6, int(tracker_ocp.n_w))),
            "iterations": np.full(6, 3)}
    model = fit_warmstart(data, fingerprint=tape["fp"], val_share=0.0,
                          trainer_config={"hidden": (4,), "epochs": 2,
                                          "seed": 0})
    # hidden=(4,) must actually shape the net, not just ride as metadata
    assert np.asarray(model.weights[0]).shape == (n_theta, 4)


# -- in-graph gate: corrupted predictor => plain start bitwise ---------------

def test_gate_selects_plain_on_poisoned_weights(tracker_ocp, tape):
    import jax

    bundle = build_warmstart(tape["model"], ocp=tracker_ocp)
    gated = make_gated_init(tracker_ocp, bundle)
    plain = plain_init(tracker_ocp)
    theta = tracker_ocp.default_params(p=jnp.array([1.0]))

    poisoned = jax.tree.map(lambda leaf: jnp.full_like(leaf, jnp.nan),
                            bundle.params)
    w_g, y_g, z_g, lam_g, src = gated(poisoned, jnp.asarray(True), theta)
    w_p, y_p, z_p, _lam, src_p = plain(None, jnp.asarray(False), theta)
    assert int(src) == 2          # predicted_rejected
    assert int(src_p) == 0        # plain
    for got, want in ((w_g, w_p), (y_g, y_p), (z_g, z_p)):
        assert np.asarray(got).tobytes() == np.asarray(want).tobytes()
    assert np.all(np.isfinite(np.asarray(lam_g)))

    # disabled predictor: src=plain even with healthy weights
    _w, _y, _z, _l, src_off = gated(bundle.params, jnp.asarray(False),
                                    theta)
    assert int(src_off) == 0


# -- chaos: poisoned predictor degrades to plain, never actuation ------------

def test_chaos_poison_recovery_from_journal(tracker_ocp, tape,
                                            tmp_path):
    journal = str(tmp_path / "chaos.jsonl")
    telemetry.configure(enabled=True)
    telemetry.enable_journal(journal)
    try:
        plane = ServingPlane(ADMM, slot_multiple=1, initial_capacity=4,
                             engine_store=tape["store"])
        plane.join(_spec(tracker_ocp, "t0", 1.0))
        plane.join(_spec(tracker_ocp, "t1", 2.0))
        ctrl = install_serving_chaos(plane, {"warmstart_poison": [
            {"start_round": 1, "n_rounds": 2}]})
        bad = 0
        for r in range(5):
            for t in ("t0", "t1"):
                plane.submit(t)
            out = plane.serve_round()
            # churn one tenant so cold joins keep exercising the gate
            plane.leave("t1")
            plane.join(_spec(tracker_ocp, "t1", 2.0 + 0.1 * r))
            for res in (out or {}).values():
                if res.action != "actuate" or not res.healthy:
                    bad += 1
        ctrl.uninstall()
    finally:
        telemetry.disable_journal()
    assert bad == 0, "poisoned predictor must never cost an actuation"

    # the full chain from the journal ALONE: injection -> in-window
    # rejections -> lift -> accepted predictions again
    evs = read_events(journal)
    inj = [e for e in evs if e.get("etype") == "chaos.injected"
           and "warmstart" in e.get("rule", "")]
    adm = [e for e in evs if e.get("etype") == "warmstart.admission"]
    rej = [e for e in adm if e.get("source") == "predicted_rejected"]
    acc = [e for e in adm if e.get("source") == "predicted"]
    assert any(e["rule"] == "warmstart_poison" for e in inj)
    assert any(e["rule"] == "warmstart_poison_lifted" for e in inj)
    assert rej and acc

    seq = lambda e: e.get("seq", 0)  # noqa: E731
    inj_seq = min(seq(e) for e in inj if e["rule"] == "warmstart_poison")
    lift_seq = min(seq(e) for e in inj
                   if e["rule"] == "warmstart_poison_lifted")
    assert inj_seq < lift_seq
    assert [e for e in rej if inj_seq < seq(e) < lift_seq], \
        "no rejection between injection and lift"
    assert [e for e in acc if seq(e) > lift_seq], \
        "predictor did not recover after the rule lifted"


# -- dataset CLI determinism -------------------------------------------------

def test_dataset_cli_deterministic(tape, tmp_path):
    from agentlib_mpc_tpu.telemetry.__main__ import main as tcli

    outs = []
    for tag in ("a", "b"):
        csv = str(tmp_path / f"ds_{tag}.csv")
        npz = str(tmp_path / f"ds_{tag}.npz")
        tcli(["--dataset", tape["journal"], "--out", csv])
        tcli(["--dataset", tape["journal"], "--out", npz])
        outs.append((Path(csv).read_bytes(), Path(npz).read_bytes()))
    assert outs[0][0] == outs[1][0], "CSV extraction not deterministic"
    a = np.load(str(tmp_path / "ds_a.npz"))
    b = np.load(str(tmp_path / "ds_b.npz"))
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        assert np.asarray(a[k]).tobytes() == np.asarray(b[k]).tobytes()


def test_dataset_cli_no_jax():
    """The extraction CLI stays jax-free: offline tooling replaying the
    journal must not touch the accelerator stack (the package root may
    import jax, the CLI module's own code must not)."""
    import ast

    import agentlib_mpc_tpu.telemetry.__main__ as tmod

    tree = ast.parse(Path(tmod.__file__).read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            names = [node.module or ""]
        else:
            continue
        for name in names:
            assert not name.startswith("jax"), \
                f"dataset CLI imports {name} at {node.lineno}"
