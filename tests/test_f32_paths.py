"""f32 tier: the TPU-native precision, exercised explicitly on CPU.

The suite runs in f64 (conftest enables x64 for tight tolerances); the
TPU data plane runs f32. These tests re-trace the hot paths under
``jax.experimental.enable_x64(False)`` and pin the f32-specific behavior the solver
was engineered for (scaling, stall acceptance, barrier floor —
``ops/solver.py`` docstring): solves still succeed and land on the f64
answer to f32-appropriate tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.models.zoo import OneRoom
from agentlib_mpc_tpu.ops.solver import (
    NLPFunctions,
    SolverOptions,
    solve_nlp,
)
from agentlib_mpc_tpu.ops.transcription import transcribe


@pytest.fixture()
def f32():
    # jax >= 0.4.3x removed the jax.enable_x64 alias; the context manager
    # lives in jax.experimental (this fixture errored on every tier-1 run
    # since the image's jax moved — fixed in the jaxlint PR)
    from jax.experimental import enable_x64

    with enable_x64(False):
        yield


class TestSolverF32:
    def test_hs071_f32(self, f32):
        nlp = NLPFunctions(
            f=lambda w, t: w[0] * w[3] * (w[0] + w[1] + w[2]) + w[2],
            g=lambda w, t: jnp.array([jnp.sum(w**2) - 40.0]),
            h=lambda w, t: jnp.array([w[0] * w[1] * w[2] * w[3] - 25.0]),
        )
        res = solve_nlp(nlp, jnp.array([1.0, 5.0, 5.0, 1.0]), None,
                        jnp.ones(4), 5.0 * jnp.ones(4),
                        SolverOptions(tol=1e-4, max_iter=60))
        assert res.w.dtype == jnp.float32
        assert bool(res.stats.success)
        np.testing.assert_allclose(
            np.asarray(res.w), [1.0, 4.743, 3.8211, 1.3794], atol=2e-3)

    @pytest.mark.slow
    def test_one_room_ocp_f32_matches_f64_objective(self, f32):
        """The benchmark-shaped OCP: f32 solve succeeds and the optimal
        cost matches the f64 solve to well under a percent (the
        closed-loop-cost parity claim of BASELINE.md rests on this)."""
        model = OneRoom(overrides={"s_T": 0.001, "r_mDot": 0.01})
        ocp = transcribe(model, ["mDot"], N=8, dt=300.0,
                         method="collocation", collocation_degree=2)
        theta = ocp.default_params(x0=jnp.array([298.16]))
        lb, ub = ocp.bounds(theta)
        res32 = solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta, lb,
                          ub, SolverOptions(tol=1e-4, max_iter=60))
        assert res32.w.dtype == jnp.float32
        assert bool(res32.stats.success)
        obj32 = float(res32.stats.objective)

        from jax.experimental import enable_x64

        with enable_x64(True):
            ocp64 = transcribe(model, ["mDot"], N=8, dt=300.0,
                               method="collocation", collocation_degree=2)
            theta64 = ocp64.default_params(x0=jnp.array([298.16]))
            lb64, ub64 = ocp64.bounds(theta64)
            res64 = solve_nlp(ocp64.nlp, ocp64.initial_guess(theta64),
                              theta64, lb64, ub64,
                              SolverOptions(tol=1e-7, max_iter=80))
        assert bool(res64.stats.success)
        obj64 = float(res64.stats.objective)
        assert obj32 == pytest.approx(obj64, rel=5e-3)


class TestFusedEngineF32:
    def test_consensus_fixed_point_f32(self, f32):
        from conftest import make_tracker_model

        from agentlib_mpc_tpu.parallel.fused_admm import (
            AgentGroup,
            FusedADMM,
            FusedADMMOptions,
            stack_params,
        )

        Tracker = make_tracker_model()
        ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                         method="multiple_shooting")
        group = AgentGroup(
            name="trackers", ocp=ocp, n_agents=3,
            couplings={"shared": "u"},
            solver_options=SolverOptions(tol=1e-5, max_iter=30))
        engine = FusedADMM(
            [group], FusedADMMOptions(max_iterations=40, rho=2.0,
                                      abs_tol=1e-4, rel_tol=1e-3))
        thetas = stack_params([
            ocp.default_params(p=jnp.array([float(a)]))
            for a in (0.0, 2.0, 4.0)])
        state = engine.init_state([thetas])
        state, _trajs, stats = engine.step(state, [thetas])
        assert state.zbar["shared"].dtype == jnp.float32
        assert bool(stats.converged)
        np.testing.assert_allclose(
            np.asarray(state.zbar["shared"]), 2.0, atol=5e-3)
