"""Durable control-state checkpointing (orbax).

The reference has NO checkpoint/resume for process state — warm starts
live in memory and die with the process (SURVEY §5: "Checkpoint/resume:
none for process state"; its only durable artifacts are results CSVs
and serialized ML models). For long-running building fleets that is a
real gap: a controller restart loses every warm start, dual variable
and consensus state, and the next control step pays cold-start
iteration counts under a real-time deadline.

Here the whole control state is a pytree by construction (JAX), so
checkpointing is one orbax call. :class:`~agentlib_mpc_tpu.parallel.
config_bridge.FusedFleet` wires these into ``save_checkpoint`` /
``restore_checkpoint``; for hand-built :class:`FusedADMM` states (also
NamedTuple pytrees) call :func:`save_pytree` / :func:`load_pytree`
directly with the state as its own template.
"""

from __future__ import annotations

import os
import shutil
from typing import Any

__all__ = ["save_pytree", "load_pytree"]


def save_pytree(path: str, tree: Any) -> str:
    """Write a pytree of arrays/scalars to ``path`` (a directory),
    replacing any existing checkpoint WITHOUT a window where none
    exists: the new checkpoint is fully written to a sibling temp
    directory first, then swapped in — a crash mid-save leaves the
    previous checkpoint intact (periodic checkpointing must survive
    being killed mid-save; that is its whole purpose).

    Returns the absolute path."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(tmp, tree)
    ckptr.wait_until_finished()
    if os.path.isdir(path):
        old = f"{path}.old-{os.getpid()}"
        os.rename(path, old)
        os.rename(tmp, path)
        shutil.rmtree(old)
    else:
        os.rename(tmp, path)
    return path


def load_pytree(path: str, template: Any) -> Any:
    """Restore a pytree written by :func:`save_pytree`.

    ``template`` supplies the tree structure, container types (incl.
    NamedTuples) and array shapes/dtypes — pass a freshly-initialized
    state of the same problem; its VALUES are ignored."""
    import jax
    import orbax.checkpoint as ocp

    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    return ocp.StandardCheckpointer().restore(
        os.path.abspath(path), abstract)
