"""Per-phase performance baselines + the continuous regression gate.

The third layer of the performance observatory: persist what
:mod:`.profiler` measured, estimate how noisy the machine is, and fail
``bench.py --perf-gate`` when a phase drifts out of band — the
automated defense PERF.md's manual tables never were.

Honesty rules carry over verbatim from the bench (ISSUE 2/9/14):
baseline keys are qualified by :func:`qualified_metric` — unqualified
names are reserved for TPU, everything else gets ``_<platform>``, a
mesh run gets ``_d<n>`` or the full ``_d<A>x<S>`` 2-D shape, a degraded
round ``_degraded`` — so a CPU-fallback baseline can never gate (or be
gated by) a silicon run: they are different experiments under different
keys, and a key with no baseline is a SKIP with a note, never a pass
invented from the wrong platform's numbers.

Noise bands come from repeated samples at baseline-update time: band =
max(observed spread across update captures, ``rel_floor`` of the mean,
``abs_floor_ms``) — a shared-CI-runner's scheduler jitter is absorbed
by the floors, a real slowdown is not. The gate verdict is one-sided:
only slower-than-band fails (an improvement is recorded as a note so a
suspicious speedup is still visible in the report). Both outcomes land
on the flight recorder: ``perf.gate`` (status pass/fail) always, plus
one ``perf.regression`` event per offending phase — which the incident
CLI renders in its timeline, so performance drift shows up next to the
faults it often explains.
"""

from __future__ import annotations

import json
import os

from agentlib_mpc_tpu.telemetry import journal as _journal_mod
from agentlib_mpc_tpu.telemetry.profiler import (
    UNATTRIBUTED as _UNATTRIBUTED,
)

__all__ = [
    "check_regression", "load_baselines", "qualified_metric",
    "update_baseline",
]

#: default noise-band floors: relative to the phase mean, and absolute
#: (sub-0.05 ms phases are pure scheduler noise on every platform)
REL_FLOOR = 0.25
ABS_FLOOR_MS = 0.05
#: phases thinner than this never gate — a 20 µs row's "regression" is
#: timer granularity, not performance
MIN_GATE_MS = 0.02


def qualified_metric(base: str, platform: str, n_devices: int = 1,
                     degraded: bool = False,
                     mesh_shape: "tuple | None" = None,
                     quality_level: int = 0,
                     precision: str = "full") -> str:
    """The ONE metric-qualification rule (shared with ``bench.py``,
    which delegates here): unqualified names are reserved for TPU; any
    other platform gets a ``_<platform>`` suffix; a measurement spanning
    a device mesh gains ``_d<n>`` — or the full ``_d<A>x<S>`` shape for
    a 2-D grid — a run the SLO autopilot held at reduced quality gains
    ``_q<level>`` (the deepest ladder level reached, ISSUE 17: a
    quality-reduced round must never read as a full-quality headline),
    a run on a non-full precision path gains ``_<precision>``
    (``_mixed``/``_bf16`` — ISSUE 20: a mixed-precision solve must
    never publish under a full-precision headline key) and a degraded
    round ``_degraded``. Two qualified keys are comparable iff they
    are equal; the baseline store and the gate both key on this."""
    name = base if platform == "tpu" else f"{base}_{platform}"
    if mesh_shape is not None:
        name = f"{name}_d{'x'.join(str(int(s)) for s in mesh_shape)}"
    elif n_devices > 1:
        name = f"{name}_d{n_devices}"
    if quality_level:
        name = f"{name}_q{int(quality_level)}"
    if precision not in ("", "full", None):
        name = f"{name}_{precision}"
    return f"{name}_degraded" if degraded else name


def load_baselines(path: str) -> dict:
    """The committed baseline store: ``{metric_key: entry}`` with
    ``entry = {"phases": {phase: {"mean_ms", "band_ms", "n"}},
    "total_device_ms", "platform", "rounds"}``. Missing file → empty
    store (every key skips with a note)."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def _band(samples: "list[float]", rel_floor: float,
          abs_floor_ms: float) -> float:
    mean = sum(samples) / max(len(samples), 1)
    spread = (max(samples) - min(samples)) if len(samples) > 1 else 0.0
    return max(spread, rel_floor * mean, abs_floor_ms)


def update_baseline(path: str, profiles: list, *,
                    rel_floor: float = REL_FLOOR,
                    abs_floor_ms: float = ABS_FLOOR_MS) -> dict:
    """Fold repeated :class:`~.profiler.PhaseProfile` samples (same
    ``metric_key``) into the baseline store at ``path`` and write it
    back. Multiple samples estimate the noise band per phase; a single
    sample gets the floors. Other keys in the store are preserved —
    a CPU update never touches a TPU row."""
    if not profiles:
        raise ValueError("update_baseline needs at least one profile")
    keys = {p.metric_key for p in profiles}
    if len(keys) != 1:
        raise ValueError(
            f"profiles span multiple metric keys {sorted(keys)} — "
            f"baselines are per qualified key (different platforms/"
            f"meshes are different experiments)")
    key = profiles[0].metric_key
    phases: dict = {}
    names: set = set()
    for p in profiles:
        names |= set(p.device_ms)
    for ph in sorted(names):
        samples = [p.device_ms.get(ph, 0.0) for p in profiles]
        phases[ph] = {
            "mean_ms": round(sum(samples) / len(samples), 4),
            "band_ms": round(_band(samples, rel_floor, abs_floor_ms), 4),
            "n": len(samples),
        }
    store = load_baselines(path)
    store[key] = {
        "phases": phases,
        "total_device_ms": round(
            sum(p.total_device_ms for p in profiles) / len(profiles), 4),
        "platform": profiles[0].platform,
        "rounds": profiles[0].rounds,
        "coverage": round(
            sum(p.coverage for p in profiles) / len(profiles), 4),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(store, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return store[key]


def check_regression(baselines: "dict | str", profile, *,
                     journal: bool = True) -> dict:
    """Gate one measured profile against its baseline row.

    Returns ``{"status": "pass"|"fail"|"skip", "metric_key", ...,
    "violations": [...], "improvements": [...], "notes": [...]}``.
    ``skip`` (no baseline under this qualified key) is explicit — the
    caller decides whether a missing baseline is an error (CI on the
    pinned platform) or expected (first run on new silicon). Journals
    ``perf.gate`` with the verdict and one ``perf.regression`` per
    out-of-band phase."""
    store = load_baselines(baselines) if isinstance(baselines, str) \
        else baselines
    key = profile.metric_key
    entry = store.get(key)
    report: dict = {"metric_key": key, "platform": profile.platform,
                    "violations": [], "improvements": [], "notes": []}
    if entry is None:
        report["status"] = "skip"
        report["notes"].append(
            f"no baseline under key {key!r} (keys present: "
            f"{sorted(store)}) — record one with --perf-gate --update")
        if journal:
            _journal_event("perf.gate", status="skip", metric_key=key)
        return report
    for ph, base in sorted(entry.get("phases", {}).items()):
        measured = profile.device_ms.get(ph, 0.0)
        mean, band = float(base["mean_ms"]), float(base["band_ms"])
        if max(measured, mean) < MIN_GATE_MS:
            continue
        if ph == _UNATTRIBUTED and measured > mean + band:
            # the residual row is attribution quality, not a workload
            # phase — its excursions are surfaced, never CI-failing
            # (its scale is noise-level: a few-µs excess would flake
            # an otherwise-green A/A)
            report["notes"].append(
                f"unattributed residual above band "
                f"({measured:.3f} ms vs {mean}±{band} ms) — "
                f"attribution drift, check coverage")
            continue
        if measured > mean + band:
            report["violations"].append({
                "phase": ph, "measured_ms": round(measured, 4),
                "baseline_ms": mean, "band_ms": band,
                "excess_ms": round(measured - mean - band, 4)})
        elif measured < mean - band:
            report["improvements"].append({
                "phase": ph, "measured_ms": round(measured, 4),
                "baseline_ms": mean, "band_ms": band})
    for ph in sorted(profile.device_ms):
        if ph not in entry.get("phases", {}) \
                and profile.device_ms[ph] >= MIN_GATE_MS:
            report["notes"].append(
                f"phase {ph!r} has no baseline row "
                f"({profile.device_ms[ph]:.3f} ms measured) — "
                f"re-record the baseline")
    report["status"] = "fail" if report["violations"] else "pass"
    if journal:
        if report["violations"]:
            for v in report["violations"]:
                _journal_event("perf.regression", metric_key=key, **v)
        _journal_event(
            "perf.gate", status=report["status"], metric_key=key,
            violations=len(report["violations"]),
            improvements=len(report["improvements"]))
    return report


def _journal_event(etype: str, **fields) -> None:
    if _journal_mod._GLOBAL is not None:
        _journal_mod.record(etype, **fields)
