"""Stage-sparse derivative pipeline tests (``ops/stagejac.py``).

The CasADi-coloring-role coverage: the compressed-pullback eval+jac and
compressed-seed Hessian must (a) reproduce the dense ``jacrev`` /
``jax.hessian`` results EXACTLY (the compression is loss-free on a
certified-banded problem — golden equivalence over the example menu:
collocation d1/d2, multiple shooting, ± ``fix_initial_state``, linear
and bilinear models), (b) assemble the SAME banded blocks the dense
``_stage_blocks`` extraction produces, (c) carry solutions through
``solve_nlp``/``solve_qp`` that agree with the dense pipeline, (d)
route on the jaxpr certificate's authority — a refuted certificate
keeps the dense path, forcing ``jacobian="sparse"`` without a proof
raises — and (e) stay vmap-transparent for the fused fleet.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.ops import stagejac as sj
from agentlib_mpc_tpu.ops import stagewise as sw
from agentlib_mpc_tpu.ops.solver import (
    JAC_PATHS,
    KKT_PATHS,
    NLPFunctions,
    SolverOptions,
    attach_jacobian_plan,
    attach_stage_partition,
    solve_nlp,
)


def _transcribed(model_cls, controls, N=5, **kw):
    from agentlib_mpc_tpu.ops.transcription import transcribe

    return transcribe(model_cls(), controls, N=N, dt=60.0, **kw)


_PLANS: dict = {}


def _plan_for(ocp, key=None):
    """Certificate-backed plan, memoized per transcription config so the
    abstract interpreter runs once per configuration, not once per test
    (the production seams memoize the same way via the plan cache)."""
    if key is not None and key in _PLANS:
        return _PLANS[key]
    plan = sj.plan_from_certificate(ocp.nlp, ocp.default_params(),
                                    ocp.n_w, ocp.stage_partition)
    assert plan is not None, "menu entry must certify banded"
    if key is not None:
        _PLANS[key] = plan
    return plan


def _expand(rows, cols, m, n):
    """Banded row windows -> dense (m, n) matrix (test-side inverse)."""
    out = np.zeros((m, n))
    rows = np.asarray(rows)
    for r in range(m):
        for k, c in enumerate(np.asarray(cols)[r]):
            if c >= 0:
                out[r, c] += rows[r, k]
    return out


def _sparse_opts(ocp, plan, **kw):
    return attach_jacobian_plan(attach_stage_partition(
        SolverOptions(jacobian="sparse", **kw), ocp.stage_partition), plan)


# quick tier: one entry per structural family (collocation with interior
# states, shooting without); the full menu sweep (d1/d2, shooting,
# ±fix_initial_state, bilinear CooledRoom) rides the slow tier like the
# certifier's own menu sweep does
MENU_QUICK = [
    ("OneRoom", ["mDot"], dict(method="collocation",
                               collocation_degree=2)),
    ("LinearRCZone", ["Q"], dict(method="multiple_shooting",
                                 fix_initial_state=False)),
]
MENU_SLOW = [
    ("OneRoom", ["mDot"], dict(method="collocation",
                               collocation_degree=1)),
    ("OneRoom", ["mDot"], dict(method="multiple_shooting")),
    ("OneRoom", ["mDot"], dict(method="collocation", collocation_degree=2,
                               fix_initial_state=False)),
    ("CooledRoom", ["mDot"], dict(method="collocation",
                                  collocation_degree=1)),
]
MENU = MENU_QUICK + [pytest.param(*e, marks=pytest.mark.slow)
                     for e in MENU_SLOW]


# --------------------------------------------------------------------------
# golden equivalence: banded eval+jac == dense jacrev on the full menu
# --------------------------------------------------------------------------

@pytest.mark.parametrize("model_name,controls,kw", MENU)
def test_banded_eval_jac_matches_dense(model_name, controls, kw):
    from agentlib_mpc_tpu.models import zoo

    ocp = _transcribed(getattr(zoo, model_name), controls, **kw)
    theta = ocp.default_params()
    plan = _plan_for(ocp, key=(model_name, str(kw)))
    n, m_e, m_h = ocp.n_w, ocp.n_g, ocp.n_h

    fgh = sj.stacked_fgh(ocp.nlp, theta)
    w = ocp.initial_guess(theta) + 0.1 * jax.random.normal(
        jax.random.PRNGKey(0), (n,))

    @jax.jit
    def dense(w):
        vals, pullback = jax.vjp(fgh, w)
        return vals, jax.vmap(lambda ct: pullback(ct)[0])(
            jnp.eye(1 + m_e + m_h))

    vals_d, J = dense(w)
    vals_s, gf, Jg_rows, Jh_rows = jax.jit(
        lambda w: sj.banded_fgh_jac(plan, fgh, w))(w)

    assert jnp.allclose(vals_d, vals_s)
    assert jnp.allclose(J[0], gf)
    # the compression is loss-free: EXACT agreement, not tolerance
    np.testing.assert_array_equal(
        _expand(Jg_rows, plan.g_cols, m_e, n), np.asarray(J[1:1 + m_e]))
    np.testing.assert_array_equal(
        _expand(Jh_rows, plan.h_cols, m_h, n), np.asarray(J[1 + m_e:]))


@pytest.mark.parametrize("model_name,controls,kw", MENU_QUICK[:1]
                         + [pytest.param(*e, marks=pytest.mark.slow)
                            for e in MENU_SLOW[:2]])
def test_banded_hessian_matches_dense(model_name, controls, kw):
    from agentlib_mpc_tpu.models import zoo

    ocp = _transcribed(getattr(zoo, model_name), controls, **kw)
    theta = ocp.default_params()
    plan = _plan_for(ocp, key=(model_name, str(kw)))
    n, m_e, m_h = ocp.n_w, ocp.n_g, ocp.n_h
    rng = np.random.default_rng(1)
    y = jnp.asarray(rng.normal(size=m_e))
    z = jnp.asarray(np.abs(rng.normal(size=m_h)))
    w = jnp.asarray(rng.normal(size=n))

    def lagr(ww):
        val = ocp.nlp.f(ww, theta) + y @ ocp.nlp.g(ww, theta)
        if m_h:
            val = val - z @ ocp.nlp.h(ww, theta)
        return val

    H = jax.jit(jax.hessian(lagr))(w)

    @jax.jit
    def banded(w):
        CH = sj.banded_lagrangian_hessian(plan, jax.grad(lagr), w)
        return sj.hessian_rows(plan, CH)

    H_rows = banded(w)
    np.testing.assert_allclose(
        _expand(H_rows, plan.hrow_cols, n, n), np.asarray(H),
        rtol=0, atol=5e-5 * max(1.0, float(jnp.max(jnp.abs(H)))))


def test_assembly_matches_dense_stage_blocks():
    """assemble_kkt_banded must produce the same (D, E) blocks the dense
    path's ``_stage_blocks`` extracts from the materialized KKT matrix
    (up to f32 symmetrization noise)."""
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], method="collocation",
                       collocation_degree=2)
    theta = ocp.default_params()
    p = ocp.stage_partition
    plan = _plan_for(ocp, key="site1")
    n, m_e, m_h = ocp.n_w, ocp.n_g, ocp.n_h
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=n))
    y = jnp.asarray(rng.normal(size=m_e))
    z = jnp.asarray(np.abs(rng.normal(size=m_h)))
    sigma_s = jnp.asarray(np.abs(rng.normal(size=m_h)) + 0.1)
    w_diag = jnp.asarray(np.abs(rng.normal(size=n)) + 1e-4)
    delta_c = 1e-8

    def lagr(ww):
        return (ocp.nlp.f(ww, theta) + y @ ocp.nlp.g(ww, theta)
                - z @ ocp.nlp.h(ww, theta))

    @jax.jit
    def dense_blocks(w):
        H = jax.hessian(lagr)(w)
        Jg = jax.jacrev(lambda ww: ocp.nlp.g(ww, theta))(w)
        Jh = jax.jacrev(lambda ww: ocp.nlp.h(ww, theta))(w)
        W = H + jnp.diag(w_diag) + Jh.T @ (sigma_s[:, None] * Jh)
        K = jnp.block([[W, Jg.T], [Jg, -delta_c * jnp.eye(m_e)]])
        return sw._stage_blocks(K, p)

    D_ref, E_ref = dense_blocks(w)

    @jax.jit
    def banded_blocks(w):
        fgh = sj.stacked_fgh(ocp.nlp, theta)
        _, _, Jg_rows, Jh_rows = sj.banded_fgh_jac(plan, fgh, w)
        CH = sj.banded_lagrangian_hessian(plan, jax.grad(lagr), w)
        return sj.assemble_kkt_banded(plan, CH, Jg_rows, Jh_rows,
                                      sigma_s, w_diag, delta_c)

    D, E = banded_blocks(w)
    scale = max(1.0, float(jnp.max(jnp.abs(D_ref))))
    np.testing.assert_allclose(np.asarray(D), np.asarray(D_ref),
                               rtol=0, atol=5e-5 * scale)
    np.testing.assert_allclose(np.asarray(E), np.asarray(E_ref),
                               rtol=0, atol=5e-5 * scale)


def test_banded_factor_solves_like_dense_stage():
    """factor/resolve_kkt_stage_banded from assembled blocks must agree
    with the dense-input stage sweep AND satisfy the dense residual."""
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], method="collocation",
                       collocation_degree=2)
    p = ocp.stage_partition
    K, rhs = sw.synthetic_stage_kkt(p, seed=3, dtype=np.float32)
    Kj, rj = jnp.asarray(K), jnp.asarray(rhs)
    x_ref = sw.solve_kkt_stage(Kj, rj, p)
    D, E = sw._stage_blocks(Kj, p)
    x_b = sw.resolve_kkt_stage_banded(sw.factor_kkt_stage_banded(D, E),
                                      rj, p)
    res = float(jnp.max(jnp.abs(Kj @ x_b - rj)))
    assert res < 1e-3
    np.testing.assert_allclose(np.asarray(x_b), np.asarray(x_ref),
                               rtol=0, atol=1e-3)


# --------------------------------------------------------------------------
# end to end: solve_nlp / solve_qp with each pipeline agree
# --------------------------------------------------------------------------

def test_solve_nlp_sparse_matches_dense():
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=8, method="collocation",
                       collocation_degree=2)
    theta = ocp.default_params()
    w0 = ocp.initial_guess(theta)
    lb, ub = ocp.bounds(theta)
    plan = _plan_for(ocp, key="site2")
    base = SolverOptions(tol=1e-4, max_iter=30)
    opts_d = attach_stage_partition(
        base._replace(jacobian="dense", kkt_method="stage"),
        ocp.stage_partition)
    opts_s = attach_jacobian_plan(attach_stage_partition(
        base._replace(jacobian="sparse"), ocp.stage_partition), plan)
    rd = solve_nlp(ocp.nlp, w0, theta, lb, ub, opts_d)
    rs = solve_nlp(ocp.nlp, w0, theta, lb, ub, opts_s)
    assert bool(rd.stats.success) and bool(rs.stats.success)
    assert int(rd.stats.jac_path) == JAC_PATHS.index("dense")
    assert int(rs.stats.jac_path) == JAC_PATHS.index("sparse")
    assert int(rs.stats.kkt_path) == KKT_PATHS.index("stage")
    # same tolerance the stage sweep met in its dense-vs-stage identity
    assert float(jnp.max(jnp.abs(rd.w - rs.w))) < 1e-5 * (
        1.0 + float(jnp.max(jnp.abs(rd.w))))


def test_solve_qp_sparse_matches_lu():
    """The QP fast path with the sparse pipeline must reach the same
    optimum as the production dense-LU QP path (objective + feasibility;
    the f32 stall points differ slightly between factorizations)."""
    from agentlib_mpc_tpu.models.zoo import LinearRCZone
    from agentlib_mpc_tpu.ops.qp import solve_qp

    ocp = _transcribed(LinearRCZone, ["Q"], N=8,
                       method="collocation", collocation_degree=2)
    theta = ocp.default_params()
    w0 = ocp.initial_guess(theta)
    lb, ub = ocp.bounds(theta)
    plan = _plan_for(ocp, key="site3")
    base = SolverOptions(tol=1e-4, max_iter=60)
    r_lu = solve_qp(ocp.nlp, w0, theta, lb, ub,
                    base._replace(kkt_method="lu"))
    r_sp = solve_qp(ocp.nlp, w0, theta, lb, ub, _sparse_opts(
        ocp, plan, tol=1e-4, max_iter=60))
    assert bool(r_lu.stats.success) and bool(r_sp.stats.success)
    assert int(r_sp.stats.jac_path) == JAC_PATHS.index("sparse")
    assert float(r_sp.stats.constraint_violation) < 1e-2
    obj_lu, obj_sp = float(r_lu.stats.objective), float(r_sp.stats.objective)
    assert abs(obj_sp - obj_lu) < 5e-3 * max(1.0, abs(obj_lu))


@pytest.mark.slow
def test_vmap_sparse_matches_single_lane():
    """Fused-fleet transparency: the sparse pipeline under vmap (the
    agent axis) must equal the per-lane solves exactly."""
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=5, method="collocation",
                       collocation_degree=1)
    theta = ocp.default_params()
    w0 = ocp.initial_guess(theta)
    lb, ub = ocp.bounds(theta)
    plan = _plan_for(ocp, key="site4")
    opts = _sparse_opts(ocp, plan, tol=1e-4, max_iter=20)
    wb = jnp.stack([w0, w0 * 1.01, w0 * 0.98])
    rb = jax.vmap(lambda w: solve_nlp(ocp.nlp, w, theta, lb, ub, opts))(wb)
    r0 = solve_nlp(ocp.nlp, wb[0], theta, lb, ub, opts)
    assert float(jnp.max(jnp.abs(rb.w[0] - r0.w))) == 0.0


# --------------------------------------------------------------------------
# routing: the certificate is the authority
# --------------------------------------------------------------------------

def _out_of_band_nlp(ocp):
    """Adversarial wrapper: a first-stage × last-stage objective coupling
    the certificate must refute (the sparse assembly would DROP it)."""
    base = ocp.nlp

    def f_bad(w, theta):
        return base.f(w, theta) + 1e-6 * w[0] * w[-1]

    return NLPFunctions(f=f_bad, g=base.g, h=base.h)


def test_refuted_certificate_yields_no_plan_and_dense_routing(caplog):
    import logging

    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=5, method="collocation",
                       collocation_degree=1)
    theta = ocp.default_params()
    bad = _out_of_band_nlp(ocp)
    with caplog.at_level(logging.WARNING,
                         logger="agentlib_mpc_tpu.ops.stagejac"):
        plan = sj.plan_from_certificate(bad, theta, ocp.n_w,
                                        ocp.stage_partition)
    assert plan is None
    assert any("not proved" in r.message for r in caplog.records), \
        "the dense fallback must be loud"

    # jacobian="auto" without a plan: solves, stays dense — even with the
    # stage factorization forced (banded FACTOR is fine, the dense matrix
    # still materializes every out-of-band entry)
    w0 = ocp.initial_guess(theta)
    lb, ub = ocp.bounds(theta)
    opts = attach_stage_partition(
        SolverOptions(tol=1e-4, max_iter=20, kkt_method="stage"),
        ocp.stage_partition)
    res = solve_nlp(bad, w0, theta, lb, ub, opts)
    assert int(res.stats.jac_path) == JAC_PATHS.index("dense")


def test_forced_sparse_without_plan_raises():
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=5, method="collocation",
                       collocation_degree=1)
    theta = ocp.default_params()
    w0 = ocp.initial_guess(theta)
    lb, ub = ocp.bounds(theta)
    opts = attach_stage_partition(
        SolverOptions(jacobian="sparse"), ocp.stage_partition)
    with pytest.raises(ValueError, match="stage_jacobian_plan"):
        solve_nlp(ocp.nlp, w0, theta, lb, ub, opts)


def test_forced_sparse_contradicting_kkt_method_raises():
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=5, method="collocation",
                       collocation_degree=1)
    theta = ocp.default_params()
    plan = _plan_for(ocp, key="site5")
    w0 = ocp.initial_guess(theta)
    lb, ub = ocp.bounds(theta)
    opts = attach_jacobian_plan(
        SolverOptions(jacobian="sparse", kkt_method="lu"), plan)
    with pytest.raises(ValueError, match="contradicts"):
        solve_nlp(ocp.nlp, w0, theta, lb, ub, opts)


def test_auto_routing_is_size_aware():
    """auto routes sparse exactly where the stage factor path runs: below
    stage_min_size the whole pipeline stays dense; lowering the floor
    flips BOTH paths together; jacobian_min_size adds a sparse-only
    floor on top. Exercised at the trace-time resolver (pure — the
    end-to-end stats codes are pinned by
    test_solve_nlp_sparse_matches_dense)."""
    from agentlib_mpc_tpu.models.zoo import OneRoom
    from agentlib_mpc_tpu.ops.solver import _resolve_jacobian

    ocp = _transcribed(OneRoom, ["mDot"], N=6, method="collocation",
                       collocation_degree=2)
    plan = _plan_for(ocp, key="site6")
    size = ocp.stage_partition.n_total

    def resolve(**kw):
        return _resolve_jacobian(attach_jacobian_plan(
            attach_stage_partition(SolverOptions(**kw),
                                   ocp.stage_partition), plan), size)

    assert resolve() == "dense"                    # default floor 192
    assert resolve(stage_min_size=8, jacobian_min_size=8) == "sparse"
    # the sparse-only floor (default 384, the measured whole-solve
    # crossover) keeps small stage-factored problems on dense derivatives
    assert resolve(stage_min_size=8) == "dense"
    assert resolve(kkt_method="stage", jacobian_min_size=8) == "sparse"
    # forced sparse ignores every floor
    assert resolve(jacobian="sparse") == "sparse"


def test_plan_cache_and_equality():
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=5, method="collocation",
                       collocation_degree=1)
    cert_stages = _plan_for(ocp, key="site7").h_row_stages
    p1 = sj.build_stage_jacobian_plan(ocp.stage_partition, cert_stages)
    p2 = sj.build_stage_jacobian_plan(ocp.stage_partition, cert_stages)
    assert p1 is p2                      # memoized: one object per key
    assert hash(p1) == hash(p2) and p1 == p2


def test_certificate_reports_h_row_stages():
    from agentlib_mpc_tpu.lint.jaxpr import certify_stage_structure
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=5, method="collocation",
                       collocation_degree=2)
    cert = certify_stage_structure(ocp.nlp, ocp.default_params(),
                                   ocp.n_w, ocp.stage_partition)
    assert cert.ok
    assert cert.h_row_stages is not None
    assert len(cert.h_row_stages) == ocp.n_h
    assert all(0 <= s < ocp.stage_partition.n_stages
               for s in cert.h_row_stages)


def test_backend_attaches_plan_only_when_worthwhile():
    """plan_worthwhile gates the certifier cost away from small setups:
    the default config at bench sizes must not build a plan, forcing
    jacobian='sparse' must."""
    from agentlib_mpc_tpu.models.zoo import OneRoom
    from agentlib_mpc_tpu.ops.solver import plan_worthwhile

    ocp = _transcribed(OneRoom, ["mDot"], N=5, method="collocation",
                       collocation_degree=1)
    part = ocp.stage_partition
    assert not plan_worthwhile(SolverOptions(), part)
    assert plan_worthwhile(SolverOptions(jacobian="sparse"), part)
    # forced stage below the sparse floor: auto jacobian would still
    # resolve dense, so the certificate would be dead weight
    assert not plan_worthwhile(SolverOptions(kkt_method="stage"), part)
    assert not plan_worthwhile(SolverOptions(jacobian="dense",
                                             kkt_method="stage"), part)
    # a REAL above-crossover partition (the worthwhile gate now consults
    # the stage sweep's availability probe, which a mutated/mock
    # partition would fail)
    big = sw.build_stage_partition(N=80, n_x=1, n_u=1, n_z=1, d=1,
                                   method="collocation")
    assert big.n_total >= 384
    assert plan_worthwhile(SolverOptions(kkt_method="stage"), big)
    # CPU: auto resolves LU -> stage above the crossover, so the plan
    # pays for itself; where the Pallas LDL probe passes (TPU) auto
    # never reaches stage and this returns False instead
    assert plan_worthwhile(SolverOptions(), big)
