"""Config→fused-engine bridge (parallel/config_bridge.py).

The bridge compiles reference-shaped ``admm_local`` agent configs into
one FusedADMM program: same config dialect as the module path
(`modules/admm.py`), data-plane execution (docs/DISTRIBUTED.md).
"""

import numpy as np
import pytest

from agentlib_mpc_tpu.models.zoo import CooledRoom
from agentlib_mpc_tpu.parallel.config_bridge import FusedFleet
from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions

UB = 295.15
START = 298.16


def _room_cfg(i: int, load: float, alias: str = "mDotShared") -> dict:
    return {
        "id": f"Room_{i}",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "admm", "type": "admm_local",
             "optimization_backend": {
                 "type": "jax_admm",
                 "model": {"class": CooledRoom},
                 "discretization_options": {"collocation_order": 2,
                                            "collocation_method": "legendre"},
                 "solver": {"max_iter": 30},
             },
             "time_step": 300.0,
             "prediction_horizon": 6,
             "max_iterations": 8,
             "penalty_factor": 20.0,
             "parameters": [{"name": "s_T", "value": 1.0}],
             "inputs": [
                 {"name": "load", "value": load},
                 {"name": "T_in", "value": 290.15},
                 {"name": "T_upper", "value": UB},
             ],
             "states": [{"name": "T", "value": START}],
             "couplings": [
                 {"name": "mDot", "alias": alias, "value": 0.02,
                  "lb": 0.0, "ub": 0.05},
             ]},
        ],
    }


def _sim_cfg() -> dict:
    return {
        "id": "Simulation",
        "modules": [
            {"module_id": "sim", "type": "simulator",
             "model": {"class": CooledRoom}, "t_sample": 60},
        ],
    }


class TestCheckpointResume:
    def test_restored_fleet_continues_identically(self, tmp_path):
        """Checkpoint/resume beyond the reference (SURVEY §5: it has no
        process-state checkpointing): a fleet rebuilt in a 'new process'
        and restored from the checkpoint must produce bit-identical next
        steps to the uninterrupted original."""
        configs = [_room_cfg(i, 80.0 + 30 * i) for i in range(3)]
        fleet = FusedFleet.from_configs(configs)
        fleet.step()
        fleet.advance()
        path = fleet.save_checkpoint(str(tmp_path / "ckpt"))

        out_continued = fleet.step()

        fleet2 = FusedFleet.from_configs(configs)   # "restarted process"
        fleet2.restore_checkpoint(path)
        assert fleet2.time == fleet.dt              # clock restored
        out_resumed = fleet2.step()

        assert set(out_continued) == set(out_resumed)
        for aid in out_continued:
            np.testing.assert_array_equal(
                out_continued[aid]["u"]["mDot"],
                out_resumed[aid]["u"]["mDot"])
            assert out_continued[aid]["iterations"] == \
                out_resumed[aid]["iterations"]

    def test_restore_rejects_structural_mismatch(self, tmp_path):
        fleet = FusedFleet.from_configs(
            [_room_cfg(i, 80.0 + 30 * i) for i in range(3)])
        path = fleet.save_checkpoint(str(tmp_path / "ckpt"))
        other = FusedFleet.from_configs(
            [_room_cfg(i, 80.0 + 30 * i) for i in range(4)])
        # orbax rejects the agent-axis shape mismatch (4 vs 3 stored)
        with pytest.raises(ValueError, match="not compatible"):
            other.restore_checkpoint(path)


class TestFromConfigs:
    def test_identical_agents_bucket_into_one_group(self):
        fleet = FusedFleet.from_configs(
            [_room_cfg(i, 80.0 + 30 * i) for i in range(4)] + [_sim_cfg()])
        assert len(fleet.engine.groups) == 1
        assert fleet.engine.groups[0].n_agents == 4
        # module-level knobs made it into the engine options
        assert fleet.engine.options.max_iterations == 8
        assert {float(np.asarray(v))
                for v in fleet.state.rho.values()} == {20.0}

    def test_step_reaches_consensus_and_cools(self):
        fleet = FusedFleet.from_configs(
            [_room_cfg(i, 80.0 + 30 * i) for i in range(4)])
        out = fleet.step()
        assert set(out) == {f"Room_{i}" for i in range(4)}
        u = np.stack([out[f"Room_{i}"]["u"]["mDot"] for i in range(4)])
        # consensus: all rooms agree on the shared trajectory
        assert np.max(np.abs(u - u.mean(axis=0))) < 5e-3
        # warm rooms request cooling air within bounds
        assert u.max() <= 0.05 + 1e-6 and u[:, 0].mean() > 1e-3
        # temperatures head down across the horizon
        x = out["Room_3"]["x"]
        assert x[-1, 0] < x[0, 0]

    def test_update_agent_feeds_back_plant_state(self):
        fleet = FusedFleet.from_configs(
            [_room_cfg(i, 100.0) for i in range(2)])
        fleet.step()
        fleet.advance()
        fleet.update_agent("Room_1", x0=[294.0], inputs={"load": 250.0})
        out = fleet.step()
        assert out["Room_1"]["x"][0, 0] == pytest.approx(294.0, abs=0.2)

    def test_output_coupling_raises_pointed_error(self):
        cfg = _room_cfg(0, 100.0)
        cfg["modules"][1]["couplings"] = [
            {"name": "not_a_control", "alias": "x"}]
        with pytest.raises(NotImplementedError, match="module path"):
            FusedFleet.from_configs([cfg])

    def test_mixed_horizons_rejected(self):
        a, b = _room_cfg(0, 100.0), _room_cfg(1, 100.0)
        b["modules"][1]["prediction_horizon"] = 9
        with pytest.raises(ValueError, match="horizon"):
            FusedFleet.from_configs([a, b])

    def test_no_admm_modules_rejected(self):
        with pytest.raises(ValueError, match="no ADMM"):
            FusedFleet.from_configs([_sim_cfg()])

    def test_partial_bounds_merge_across_lists(self):
        """ub from the controls list + lb from the couplings list for the
        same variable must BOTH survive into the OCP bounds."""
        cfg = _room_cfg(0, 100.0)
        mod = cfg["modules"][1]
        mod["controls"] = [{"name": "mDot", "ub": 0.03}]
        mod["couplings"] = [{"name": "mDot", "alias": "mDotShared",
                             "lb": 0.01}]
        fleet = FusedFleet.from_configs([cfg])
        theta = fleet._agents[0].theta(fleet.N)
        assert float(np.asarray(theta.u_lb).max()) == pytest.approx(0.01)
        assert float(np.asarray(theta.u_ub).min()) == pytest.approx(0.03)

    def test_conflicting_penalty_factor_rejected(self):
        a, b = _room_cfg(0, 100.0), _room_cfg(1, 100.0)
        b["modules"][1]["penalty_factor"] = 50.0
        with pytest.raises(ValueError, match="penalty_factor"):
            FusedFleet.from_configs([a, b])

    def test_unknown_input_feedback_rejected(self):
        fleet = FusedFleet.from_configs([_room_cfg(0, 100.0)])
        with pytest.raises(KeyError, match="exogenous"):
            fleet.update_agent("Room_0", inputs={"Load": 250.0})


class TestExchangeBridge:
    def test_exchange_configs_balance_to_zero(self):
        """'exchange' entries ride the bridge too: trackers exchanging on
        their control settle at u_i = a_i - mean(a) (sum-zero condition,
        the analytic exchange-ADMM fixed point)."""
        from conftest import make_tracker_model

        Tracker = make_tracker_model(lb=-10.0, ub=10.0)

        def cfg(i, a):
            return {"id": f"T_{i}", "modules": [
                {"module_id": "admm", "type": "admm_local",
                 "optimization_backend": {
                     "type": "jax_admm",
                     "model": {"class": Tracker},
                     "discretization_options": {
                         "method": "multiple_shooting"},
                     "solver": {"max_iter": 40, "tol": 1e-8},
                 },
                 "time_step": 300.0, "prediction_horizon": 4,
                 "max_iterations": 50, "penalty_factor": 1.0,
                 "parameters": [{"name": "a", "value": a}],
                 "exchange": [{"name": "u", "alias": "power"}]}]}

        targets = (2.0, -1.0, 5.0)
        fleet = FusedFleet.from_configs(
            [cfg(i, a) for i, a in enumerate(targets)],
            options=FusedADMMOptions(max_iterations=50, rho=1.0,
                                     abs_tol=1e-6, rel_tol=1e-5))
        out = fleet.step()
        u = np.stack([out[f"T_{i}"]["u"]["u"] for i in range(3)])
        np.testing.assert_allclose(u.sum(axis=0), 0.0, atol=5e-3)
        mean_a = np.mean(targets)
        for i, a in enumerate(targets):
            np.testing.assert_allclose(u[i], a - mean_a, atol=5e-3)


class TestFleetResults:
    @pytest.mark.slow
    def test_results_roundtrip_through_analysis_loader(self, tmp_path):
        """Fused-fleet history writes/loads as the reference MPC CSV
        layout (utils/analysis.load_mpc) — the module path's format."""
        import pandas as pd
        from agentlib_mpc_tpu.utils.analysis import load_mpc

        fleet = FusedFleet.from_configs(
            [_room_cfg(i, 100.0 + 40 * i) for i in range(2)])
        for _ in range(3):
            fleet.step()
            fleet.advance()
        df = fleet.results("Room_1")
        assert df.index.names == ["time", "grid"]
        times = df.index.get_level_values("time").unique()
        assert list(times) == [0.0, 300.0, 600.0]
        assert ("variable", "T") in df.columns
        assert ("variable", "mDot") in df.columns
        path = tmp_path / "room1.csv"
        df.to_csv(path)
        loaded = load_mpc(path)
        assert loaded.shape[0] == df.shape[0]

    @pytest.mark.slow
    def test_iteration_stats_trail(self):
        fleet = FusedFleet.from_configs(
            [_room_cfg(i, 120.0) for i in range(2)])
        fleet.step()
        fleet.advance()
        fleet.step()
        st = fleet.iteration_stats()
        assert st.index.names == ["time", "iteration"]
        # coordinator column names: plot_admm_residuals consumes directly
        assert set(st.columns) == {"primal_residual", "dual_residual",
                                   "penalty_parameter"}
        assert np.all(np.isfinite(st["primal_residual"].to_numpy()))
        import matplotlib

        matplotlib.use("Agg")
        from agentlib_mpc_tpu.utils.plotting.admm import (
            plot_admm_residuals,
        )

        ax = plot_admm_residuals(st.loc[0.0])
        assert ax.get_xlabel() == "ADMM iteration"


class TestHeterogeneousBridge:
    @pytest.mark.slow
    def test_room_cooler_pair_as_two_groups(self):
        """Different model classes bucket into separate vmapped groups
        that consensus-couple ACROSS groups — the reference's
        room/cooler ADMM pair (examples/admm/) through the bridge."""
        from agentlib_mpc_tpu.models.zoo import Cooler

        room = _room_cfg(0, 150.0, alias="mDotCoolAir")
        cooler = {
            "id": "Cooler",
            "modules": [
                {"module_id": "admm", "type": "admm_local",
                 "optimization_backend": {
                     "type": "jax_admm",
                     "model": {"class": Cooler},
                     "discretization_options": {
                         "method": "multiple_shooting"},
                     "solver": {"max_iter": 30},
                 },
                 "time_step": 300.0, "prediction_horizon": 6,
                 "max_iterations": 8, "penalty_factor": 20.0,
                 "parameters": [{"name": "r_mDot", "value": 0.01}],
                 "couplings": [
                     {"name": "mDot", "alias": "mDotCoolAir",
                      "lb": 0.0, "ub": 0.05},
                 ]},
            ],
        }
        fleet = FusedFleet.from_configs([room, cooler])
        assert len(fleet.engine.groups) == 2
        out = fleet.step()
        u_room = out["Room_0"]["u"]["mDot"]
        u_cooler = out["Cooler"]["u"]["mDot"]
        # cross-group consensus on the shared air flow
        np.testing.assert_allclose(u_room, u_cooler, atol=2e-3)
        # warm room requests cooling; cooler supplies it
        assert u_room[0] > 1e-3


class TestAdmmIterationRecord:
    def test_engine_coupling_locals_match_final_trajectories(self):
        """The last recorded iteration's locals must equal the final
        returned control trajectories (the history is the real data, not
        a separate computation)."""
        fleet = FusedFleet.from_configs(
            [_room_cfg(i, 90.0 + 50 * i) for i in range(3)])
        out = fleet.step()
        stats = fleet.last_stats
        it = int(stats.iterations)
        hist = np.asarray(stats.coupling_locals["mDotShared"])  # (mx,n,T)
        assert np.all(np.isfinite(hist[:it]))
        assert np.all(np.isnan(hist[it:]))
        for i in range(3):
            np.testing.assert_allclose(
                hist[it - 1, i], out[f"Room_{i}"]["u"]["mDot"],
                rtol=0, atol=0)

    @pytest.mark.slow
    def test_admm_results_roundtrip_and_shades(self, tmp_path):
        """(time, iteration, grid) frames load via analysis.load_admm and
        feed plot_consensus_shades / the convergence animation — the
        last analysis tools that needed module-path data."""
        import matplotlib

        matplotlib.use("Agg")
        from agentlib_mpc_tpu.utils.analysis import (
            admm_at_time_step,
            load_admm,
        )
        from agentlib_mpc_tpu.utils.plotting.admm import (
            plot_consensus_shades,
        )

        fleet = FusedFleet.from_configs(
            [_room_cfg(i, 100.0 + 60 * i) for i in range(2)])
        for _ in range(3):
            fleet.step()
            fleet.advance()
        df = fleet.admm_results("Room_1")
        assert df.index.names == ["time", "iteration", "grid"]
        assert ("variable", "mDotShared") in df.columns
        path = tmp_path / "room1_admm.csv"
        df.to_csv(path)
        loaded = load_admm(path)
        assert loaded.shape == df.shape
        # slicing API works: all iterations of the second control step
        sl = admm_at_time_step(loaded, 300.0)
        assert len(sl) > 0
        ax = plot_consensus_shades({"Room_1": loaded}, "mDotShared",
                                   final_iteration_only=False)
        assert ax.get_xlabel() == "time / s"

    def test_record_false_compiles_without_history(self):
        """record=False builds the engine without the per-iteration
        buffers: stats fields None, accessors empty, step still works."""
        agents = FusedFleet.from_configs(
            [_room_cfg(i, 110.0) for i in range(2)])._agents
        from agentlib_mpc_tpu.parallel.config_bridge import FusedFleet as F
        fleet = F(agents, N=6,
                  options=FusedADMMOptions(max_iterations=6, rho=20.0),
                  record=False)
        out = fleet.step()
        assert out["Room_0"]["converged"] in (True, False)
        assert fleet.last_stats.coupling_locals is None
        assert fleet.admm_results("Room_0") is None
        assert fleet.iteration_stats() is None
