from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
)
