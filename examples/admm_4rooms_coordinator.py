"""4-zone coordinated consensus-ADMM: four cooled rooms share one AHU.

Native re-design of the reference's 4-room coordinator benchmark
(``examples/4_Room_ADMM_Coordinator/admm_4rooms_coord_main.py``): four room
agents each negotiate their air mass flow with a central air-handling unit
that has a shared capacity constraint ``sum(mDot_i) <= mDot_max``; an
``admm_coordinator`` agent drives the iteration (registration →
start-iteration → optimization rounds, Boyd residual convergence, adaptive
penalty). A simulator agent per room closes the loop.

This is one of the four BASELINE.md benchmark configs. Run directly for a
report, or call ``run_example`` (examples-as-tests, SURVEY.md §4).
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

import agentlib_mpc_tpu.modules  # noqa: F401 - registers module types
from agentlib_mpc_tpu.models.zoo import AirHandlingUnit, CooledRoom
from agentlib_mpc_tpu.runtime.mas import LocalMAS

N_ROOMS = 4
TIME_STEP = 300.0
HORIZON = 8
UB = 295.15
START_TEMP = 298.16
#: per-room heat loads [W] — rooms differ so the AHU must arbitrate; the
#: total (500 W) needs ~0.1 m^3/s to hold every room at the band, above the
#: AHU capacity of 0.075, so the allocation trade-off is active
LOADS = (80.0, 110.0, 140.0, 170.0)


def _backend(model_cls):
    return {
        "type": "jax_admm",
        "model": {"class": model_cls},
        "discretization_options": {"collocation_order": 2,
                                   "collocation_method": "legendre"},
        "solver": {"max_iter": 60},
    }


def agent_configs(admm_iter_max: int = 15, penalty_factor: float = 10.0):
    coordinator = {
        "id": "Coordinator",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "coordinator", "type": "admm_coordinator",
             "time_step": TIME_STEP,
             "prediction_horizon": HORIZON,
             "admm_iter_max": admm_iter_max,
             "penalty_factor": penalty_factor,
             "abs_tol": 1e-4, "rel_tol": 1e-3,
             "penalty_change_threshold": 10.0},
        ],
    }

    rooms = []
    sims = []
    for i in range(1, N_ROOMS + 1):
        rooms.append({
            "id": f"Room_{i}",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "admm", "type": "admm_coordinated",
                 "coordinator": "Coordinator",
                 "registration_interval": 30.0,
                 "optimization_backend": _backend(CooledRoom),
                 "time_step": TIME_STEP,
                 "prediction_horizon": HORIZON,
                 "parameters": [{"name": "s_T", "value": 1.0}],
                 "inputs": [
                     {"name": "load", "value": LOADS[i - 1]},
                     {"name": "T_in", "value": 290.15},
                     {"name": "T_upper", "value": UB},
                 ],
                 "states": [
                     {"name": "T", "value": START_TEMP, "ub": 303.15,
                      "lb": 288.15, "alias": f"T_{i}",
                      "source": f"Simulation_{i}"},
                 ],
                 "controls": [],
                 "couplings": [
                     {"name": "mDot", "alias": f"mDotCoolAir_{i}",
                      "value": 0.02, "ub": 0.05, "lb": 0.0},
                 ]},
            ],
        })
        sims.append({
            "id": f"Simulation_{i}",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "simulator", "type": "simulator",
                 "model": {"class": CooledRoom,
                           "states": [{"name": "T", "value": START_TEMP}],
                           "inputs": [{"name": "load",
                                       "value": LOADS[i - 1]}]},
                 "t_sample": 60,
                 "outputs": [{"name": "T_out", "value": START_TEMP,
                              "alias": f"T_{i}"}],
                 "inputs": [{"name": "mDot", "value": 0.02,
                             "alias": f"mDot_{i}"}]},
            ],
        })

    ahu = {
        "id": "AHU",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "admm", "type": "admm_coordinated",
             "coordinator": "Coordinator",
             "registration_interval": 30.0,
             "optimization_backend": _backend(AirHandlingUnit),
             "time_step": TIME_STEP,
             "prediction_horizon": HORIZON,
             "parameters": [{"name": "r_mDot", "value": 1.0},
                            {"name": "mDot_max", "value": 0.075}],
             "controls": [
                 {"name": f"mDot_{i}", "value": 0.02, "ub": 0.05,
                  "lb": 0.0, "alias": f"mDot_{i}"}
                 for i in range(1, N_ROOMS + 1)
             ],
             "couplings": [
                 {"name": f"mDot_out_{i}", "alias": f"mDotCoolAir_{i}",
                  "value": 0.02}
                 for i in range(1, N_ROOMS + 1)
             ]},
        ],
    }
    return [coordinator, *rooms, ahu, *sims]


def run_example(until: float = 3600.0, testing: bool = False,
                verbose: bool = True) -> dict:
    mas = LocalMAS(agent_configs(), env={"rt": False})
    mas.run(until=until)
    results = mas.get_results()

    temps = {}
    flows = {}
    for i in range(1, N_ROOMS + 1):
        sim_df = results[f"Simulation_{i}"]["simulator"]
        temps[i] = sim_df["T_out"]
        flows[i] = sim_df["mDot"]
    total_flow = sum(np.asarray(flows[i], dtype=float)
                     for i in range(1, N_ROOMS + 1))

    if verbose:
        for i in range(1, N_ROOMS + 1):
            print(f"room {i}: {temps[i].iloc[0]:.2f} K -> "
                  f"{temps[i].iloc[-1]:.2f} K  (load {LOADS[i - 1]:.0f} W)")
        print(f"peak total flow: {total_flow.max():.4f} m^3/s "
              f"(capacity 0.075)")

    if testing:
        # building-average temperature moves toward the band even though
        # capacity scarcity may keep individual high-load rooms warm
        mean_start = np.mean([float(temps[i].iloc[0])
                              for i in range(1, N_ROOMS + 1)])
        mean_end = np.mean([float(temps[i].iloc[-1])
                            for i in range(1, N_ROOMS + 1)])
        assert mean_end < mean_start, "building must cool on average"
        # shared AHU capacity respected in closed loop (small consensus
        # tolerance: rooms actuate their own agreed flows)
        assert float(total_flow.max()) <= 0.075 * 1.10 + 1e-9
        # scarce air is allocated by need: hottest-load room gets more air
        # than the coolest-load room on average
        mean_flow = {i: float(np.mean(np.asarray(flows[i], dtype=float)))
                     for i in range(1, N_ROOMS + 1)}
        assert mean_flow[N_ROOMS] > mean_flow[1]
        coord = mas.agents["Coordinator"].get_module("coordinator")
        assert len(coord.agent_dict) == N_ROOMS + 1
    return results


if __name__ == "__main__":
    run_example(until=3600.0, testing=True)
