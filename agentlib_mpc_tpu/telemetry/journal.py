"""Flight recorder: a durable, append-only causal event journal.

Everything above this module answers "how much / how fast" (the
metrics registry) and "where did the time go" (spans). The journal
answers the question production incidents actually ask: **what
happened, in what order, and why** — watchdog condemnations, supervisor
degrade/readmit transitions, health-ladder moves, admission sheds,
compile-cache outcomes, checkpoint saves/restores/rejections, certifier
refusals, and every chaos injection, each as one typed JSONL line with
correlation keys (tenant id, bucket digest, mesh shape, chaos
seed/rule, engine/schedule digests) and a monotonic sequence number +
wall/round stamps. ``docs/telemetry.md`` ("Flight recorder & SLOs")
tabulates the event vocabulary.

Durability contract:

* **Atomic line appends** — every event is one ``write()`` of one
  complete line; a crash mid-write leaves at most one truncated TAIL
  line, which :func:`read_events` tolerates (skipped, never fatal).
* **Size-based rotation** — the active segment rotates to
  ``<path>.<k>`` once it exceeds ``max_bytes``; ``max_segments`` bounds
  disk (oldest rotated segments are dropped, counted in ``stats()``).
* **Monotonic sequence numbers** — strictly increasing per journal,
  resumed across process restarts by scanning the existing segments, so
  event ORDER is recoverable even when wall clocks jump.

Emit sites go through :func:`record` (or the
``telemetry.journal_event`` convenience) which is a no-op when no
journal is enabled — instrumentation stays unconditional, like every
metric write. Journaling is pure host-side Python: nothing here may
ever enter a jit trace (the ``[telemetry.journal]`` zero-retrace budget
pins this).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Iterable, Optional

from agentlib_mpc_tpu.telemetry import registry as _registry_mod

#: default active-segment size before rotation (events are ~200 B, so
#: one segment holds ~40k events)
DEFAULT_MAX_BYTES = 8 * 1024 * 1024
#: default bound on retained rotated segments (None = keep all)
DEFAULT_MAX_SEGMENTS = 16


def _segment_index(path: str, base: str) -> Optional[int]:
    m = re.fullmatch(re.escape(os.path.basename(base)) + r"\.(\d+)",
                     os.path.basename(path))
    return int(m.group(1)) if m else None


def journal_segments(path: str) -> list:
    """Every segment of the journal at ``path``, replay order (oldest
    rotated segment first, the active file last)."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    rotated = []
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            idx = _segment_index(name, base)
            if idx is not None:
                rotated.append((idx, os.path.join(directory, name)))
    out = [p for _idx, p in sorted(rotated)]
    if os.path.isfile(path):
        out.append(path)
    return out


def _read_segment(path: str) -> list:
    """Parse one segment's events; a truncated/garbled tail line (the
    crash-mid-append signature) is skipped, never fatal. A bad line in
    the MIDDLE is skipped too (torn filesystem) — replay is best-effort
    by design, and the monotonic ``seq`` makes any gap visible."""
    events = []
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if isinstance(ev, dict) and "etype" in ev:
                    events.append(ev)
    except OSError:
        return []
    return events


def read_events(path: str) -> list:
    """Replay a journal: every parseable event across all segments, in
    sequence order. Tolerates truncated tails and missing segments."""
    events: list = []
    for seg in journal_segments(path):
        events.extend(_read_segment(seg))
    events.sort(key=lambda e: int(e.get("seq", 0)))
    return events


class Journal:
    """One append-only event journal (module docstring for the
    contract). Thread-safe; one instance per file path."""

    def __init__(self, path: str,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 max_segments: "int | None" = DEFAULT_MAX_SEGMENTS,
                 fsync: bool = False):
        if int(max_bytes) < 1024:
            raise ValueError(f"max_bytes must be >= 1024, "
                             f"got {max_bytes}")
        self.path = os.path.abspath(path)
        self.max_bytes = int(max_bytes)
        self.max_segments = (None if max_segments is None
                             else max(1, int(max_segments)))
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        self._counts: dict = {}
        self._round: "int | None" = None
        self.rotations = 0
        self.segments_dropped = 0
        self.bytes_written = 0
        self.events_written = 0
        #: events lost to write failures (disk full, file closed by a
        #: concurrent disable) — counted, never raised: an emit site
        #: must not be able to crash the code path it observes
        self.write_errors = 0
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # resume: continue the sequence past whatever an earlier process
        # (or an earlier enable in this one) left behind — order across
        # restarts must stay recoverable from seq alone. Only the LAST
        # non-empty segment needs parsing (seq is monotonic across
        # segments); scanning the whole journal would make enable_journal
        # O(total tape size) on exactly the crash-recovery path where
        # MTTR is being measured.
        segments = journal_segments(self.path)
        self._seq = 0
        for seg in reversed(segments):
            tail = _read_segment(seg)
            if tail:
                self._seq = max(int(e.get("seq", 0)) for e in tail)
                break
        # rotation indices resume past the MAX existing index — resuming
        # from the segment COUNT would, after max_segments pruning
        # dropped low indices, hand out indices BELOW the retained ones
        # and make the pruner evict the newest segments first (or rename
        # over an old one)
        self._existing_rotated = max(
            (idx for idx in (_segment_index(seg, self.path)
                             for seg in segments) if idx is not None),
            default=0)
        # heal a torn tail before appending: a crash mid-write leaves a
        # newline-less partial line, and appending straight onto it
        # would corrupt the NEXT event too (one torn line is tolerated;
        # two fused ones would silently drop a real event)
        try:
            with open(self.path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() > 0:
                    fh.seek(-1, os.SEEK_END)
                    torn = fh.read(1) != b"\n"
                else:
                    torn = False
        except OSError:
            torn = False
        self._fh = open(self.path, "a", encoding="utf-8")
        if torn:
            self._fh.write("\n")
            self._fh.flush()

    # -- write path -----------------------------------------------------------

    def set_round(self, round_: "int | None") -> None:
        """Stamp subsequent events with this control-round index (emit
        sites that know their round pass it explicitly instead)."""
        self._round = None if round_ is None else int(round_)

    @property
    def current_round(self) -> "int | None":
        return self._round

    def record(self, etype: str, **fields) -> int:
        """Append one typed event; returns its sequence number. Reserved
        keys (seq, t) are journal-owned; ``round`` defaults to the
        :meth:`set_round` stamp. Non-JSON field values are stringified —
        an emit site must never be able to crash the code path it
        observes."""
        rnd = fields.pop("round", None)
        # journal-owned stamps: a field that collides (an emit site
        # forwarding user-supplied labels) must not overwrite them —
        # replay order is seq-sorted
        fields.pop("seq", None)
        fields.pop("t", None)
        with self._lock:
            self._seq += 1
            ev = {"seq": self._seq, "t": time.time(),
                  "round": self._round if rnd is None else int(rnd),
                  "etype": str(etype)}
            ev.update(fields)
            try:
                line = json.dumps(ev, default=str)
            except (TypeError, ValueError):
                line = json.dumps({k: str(v) for k, v in ev.items()})
            try:
                # ONE write of one complete line: a crash can truncate
                # the tail but never interleave two events
                self._fh.write(line + "\n")
                self._fh.flush()
                if self.fsync:
                    os.fsync(self._fh.fileno())
                self.bytes_written += len(line) + 1
                if self._fh.tell() >= self.max_bytes:
                    self._rotate_locked()
            except (OSError, ValueError):
                # disk full, or the file was closed under us (a
                # concurrent disable() while a worker thread emits):
                # the tape loses this event — count the loss, never
                # crash the serving/fleet path being observed
                self.write_errors += 1
                return self._seq
            self.events_written += 1
            self._counts[etype] = self._counts.get(etype, 0) + 1
            seq = self._seq
        if _registry_mod.DEFAULT._enabled:
            _registry_mod.DEFAULT.counter(
                "telemetry_journal_events_total",
                "events appended to the flight-recorder journal"
                ).inc(etype=etype)
        return seq

    def _rotate_locked(self) -> None:
        self._fh.close()
        try:
            self.rotations += 1
            idx = self._existing_rotated + self.rotations
            os.rename(self.path, f"{self.path}.{idx}")
            if self.max_segments is not None:
                rotated = [seg for seg in journal_segments(self.path)
                           if seg != self.path]
                while len(rotated) > self.max_segments:
                    try:
                        os.remove(rotated.pop(0))
                    except OSError:
                        break
                    self.segments_dropped += 1
        finally:
            # reopen the active file even when the rename failed — a
            # rotation failure must cost at worst an oversized segment,
            # never every subsequent event
            self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                self._fh.close()

    # -- introspection --------------------------------------------------------

    def read(self) -> list:
        """Replay this journal's events (all segments, seq order)."""
        with self._lock:
            self._fh.flush()
        return read_events(self.path)

    def stats(self) -> dict:
        """The journal's own loss/volume accounting — embedded by
        ``bench.py --emit-metrics`` next to the certificate sections."""
        with self._lock:
            return {
                "path": self.path,
                "events": self.events_written,
                "events_by_type": dict(sorted(self._counts.items())),
                "bytes_written": self.bytes_written,
                "rotations": self.rotations,
                "segments_dropped": self.segments_dropped,
                "write_errors": self.write_errors,
                "last_seq": self._seq,
            }


# -- the process-global journal (enable/record like the registry) -------------

_GLOBAL: "Journal | None" = None
_GLOBAL_LOCK = threading.Lock()


def enable(path: str, **kwargs) -> Journal:
    """Install the process-global journal at ``path`` (closing any
    previous one). Every built-in emit site starts recording."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.close()
        _GLOBAL = Journal(path, **kwargs)
        return _GLOBAL


def disable() -> None:
    """Close and uninstall the process-global journal (the file
    stays — a flight recorder's tape survives the flight)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.close()
        _GLOBAL = None


def active() -> "Journal | None":
    return _GLOBAL


def record(etype: str, **fields) -> "int | None":
    """Emit one event into the global journal; no-op (None) when no
    journal is enabled — THE seam every instrumented site calls."""
    j = _GLOBAL
    if j is None:
        return None
    return j.record(etype, **fields)


def set_round(round_: "int | None") -> None:
    j = _GLOBAL
    if j is not None:
        j.set_round(round_)


def events_of(events: Iterable, *etypes: str) -> list:
    """Filter helper: the events whose etype is in ``etypes``."""
    wanted = set(etypes)
    return [e for e in events if e.get("etype") in wanted]
