"""Per-tenant health ledger: the quarantine → probation → evict ladder.

The fused engine's in-jit quarantine (``docs/robustness.md``) keeps a
NaN-ing lane from poisoning its bucket's consensus math — but it keeps
the lane *occupied*: the substituted iterate comes back finite, the guard
sees a healthy solve, and a persistently sick tenant occupies its slot
(and degrades its bucket's batch) forever. The ledger closes that gap.

Inputs, per served round and tenant (fed by
``ServingPlane._assess_bucket``):

* the guard verdict (``healthy`` + reasons) — catches NaN/failed/
  out-of-bounds results that reach the decode,
* ``stats.quarantined_iters`` — the per-lane
  :class:`~agentlib_mpc_tpu.parallel.fused_admm.IterationStats`
  attribution; a lane quarantined through the WHOLE round is sick even
  though its decoded trajectory is finite (the substitution did the
  work). This is the signal the guard alone cannot see.

The ladder (all thresholds on :class:`HealthPolicy`):

1. **healthy** — the steady state; any healthy round resets the strike
   count.
2. **quarantined** — ``quarantine_after`` consecutive sick rounds. An
   observability state: the tenant still serves (the engine-level
   quarantine is already containing it), but it is flagged
   (``serving_health_state`` gauge) and one more ladder rung from
   eviction.
3. **evicted** — ``evict_after`` consecutive sick rounds. The plane
   masks the tenant's lane out (slot freed, spec and guard retained);
   its submissions shed straight into its PR 2 ``ActuationGuard``
   ladder (replay → hold → fallback), so the tenant's plant is
   commanded by its degradation policy while the bucket's batch is
   clean again.
4. **probation** — after ``readmit_after`` evicted rounds the plane
   re-admits the tenant (fresh warm start into a free slot — a splice,
   zero retraces, gate-enforced). ``probation_rounds`` consecutive
   healthy rounds promote it back to healthy; ONE sick round during
   probation re-evicts immediately (hysteresis: a tenant must prove
   itself, one lucky round must not bounce it back into the batch).

Everything is counted: ``serving_health_state{tenant=}`` gauge
(0=healthy, 1=quarantined, 2=probation, 3=evicted),
``serving_evictions_total{bucket=}``,
``serving_readmissions_total{bucket=}``.
"""

from __future__ import annotations

import dataclasses
import logging

from agentlib_mpc_tpu import telemetry

logger = logging.getLogger(__name__)

#: ledger states, exported as the gauge value
HEALTHY = "healthy"
QUARANTINED = "quarantined"
PROBATION = "probation"
EVICTED = "evicted"

_STATE_LEVEL = {HEALTHY: 0, QUARANTINED: 1, PROBATION: 2, EVICTED: 3}


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Knobs of the tenant-health ladder (plane config key
    ``health_policy``)."""

    #: consecutive sick rounds before a tenant is flagged quarantined
    quarantine_after: int = 2
    #: consecutive sick rounds before the tenant's lane is masked out
    evict_after: int = 4
    #: evicted rounds before the plane attempts a probation re-admission
    readmit_after: int = 6
    #: consecutive healthy rounds in probation before full promotion
    probation_rounds: int = 3
    #: a round whose lane spent >= this fraction of its iterations in
    #: the engine quarantine counts as sick even when the decoded
    #: trajectory is finite (the substitution made it so)
    quarantine_sick_fraction: float = 1.0

    def __post_init__(self):
        if not (0 < self.quarantine_after <= self.evict_after):
            raise ValueError(
                "need 0 < quarantine_after <= evict_after, got "
                f"{self.quarantine_after} / {self.evict_after}")
        if self.readmit_after < 1 or self.probation_rounds < 1:
            raise ValueError("readmit_after and probation_rounds must "
                             "be >= 1")

    @classmethod
    def from_config(cls, cfg: dict) -> "HealthPolicy":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown health option(s) {sorted(unknown)}; "
                f"known: {sorted(known)}")
        return cls(**cfg)


@dataclasses.dataclass
class TenantHealth:
    """One tenant's ledger row."""

    state: str = HEALTHY
    sick_streak: int = 0
    healthy_streak: int = 0
    #: rounds spent evicted since the (latest) eviction
    evicted_rounds: int = 0
    evictions: int = 0


class HealthLedger:
    """The per-tenant state machine; owns no plane mechanics — it only
    decides transitions, the plane executes them."""

    def __init__(self, policy: HealthPolicy = HealthPolicy()):
        self.policy = policy
        self._rows: "dict[str, TenantHealth]" = {}

    def __contains__(self, tenant_id: str) -> bool:
        return tenant_id in self._rows

    def row(self, tenant_id: str) -> TenantHealth:
        return self._rows.setdefault(tenant_id, TenantHealth())

    def state(self, tenant_id: str) -> str:
        return self.row(tenant_id).state

    def forget(self, tenant_id: str) -> None:
        self._rows.pop(tenant_id, None)
        if telemetry.enabled():
            # leave the gauge at its last value? No: a departed tenant
            # must not read as eternally sick on the dashboard
            telemetry.gauge(
                "serving_health_state",
                "tenant-health ladder position (0=healthy, "
                "1=quarantined, 2=probation, 3=evicted)").set(
                0.0, tenant=tenant_id)

    def _export(self, tenant_id: str, row: TenantHealth) -> None:
        if telemetry.enabled():
            telemetry.gauge(
                "serving_health_state",
                "tenant-health ladder position (0=healthy, "
                "1=quarantined, 2=probation, 3=evicted)").set(
                float(_STATE_LEVEL[row.state]), tenant=tenant_id)

    def _journal_move(self, tenant_id: str, row: TenantHealth,
                      state_from: str) -> None:
        if row.state != state_from:
            telemetry.journal_event(
                "health.transition", tenant=tenant_id,
                state=row.state, state_from=state_from,
                sick_streak=row.sick_streak,
                healthy_streak=row.healthy_streak,
                evictions=row.evictions)

    def is_sick_result(self, healthy: bool, stats: "dict | None") -> bool:
        """Merge the guard verdict with the per-lane quarantine
        attribution into one sick/healthy bit for the ledger."""
        if not healthy:
            return True
        stats = stats or {}
        iters = int(stats.get("iterations") or 0)
        q = int(stats.get("quarantined_iters") or 0)
        if iters <= 0 or q <= 0:
            return False
        return q >= self.policy.quarantine_sick_fraction * iters

    def observe(self, tenant_id: str, sick: bool) -> "str | None":
        """Record one served round's verdict. Returns the transition the
        plane must execute: ``"evict"`` (mask the lane out), ``"clear"``
        (probation completed), or None."""
        row = self.row(tenant_id)
        if row.state == EVICTED:
            # an evicted tenant has no served rounds; ignore strays
            # (e.g. a pipelined round launched before the eviction)
            return None
        state_before = row.state
        transition = None
        if sick:
            row.healthy_streak = 0
            row.sick_streak += 1
            if row.state == PROBATION:
                # hysteresis: one sick probation round re-evicts
                transition = "evict"
            elif row.sick_streak >= self.policy.evict_after:
                transition = "evict"
            elif row.sick_streak >= self.policy.quarantine_after \
                    and row.state == HEALTHY:
                row.state = QUARANTINED
                logger.warning(
                    "tenant %s quarantined after %d consecutive sick "
                    "rounds (evict at %d)", tenant_id, row.sick_streak,
                    self.policy.evict_after)
        else:
            row.sick_streak = 0
            row.healthy_streak += 1
            if row.state == PROBATION:
                if row.healthy_streak >= self.policy.probation_rounds:
                    row.state = HEALTHY
                    transition = "clear"
                    logger.info(
                        "tenant %s promoted from probation after %d "
                        "healthy rounds", tenant_id, row.healthy_streak)
            elif row.state == QUARANTINED:
                row.state = HEALTHY
                logger.info("tenant %s left quarantine", tenant_id)
        if transition == "evict":
            row.state = EVICTED
            row.sick_streak = 0
            row.healthy_streak = 0
            row.evicted_rounds = 0
            row.evictions += 1
        self._export(tenant_id, row)
        self._journal_move(tenant_id, row, state_before)
        return transition

    def force_evict(self, tenant_id: str) -> None:
        """Record an eviction decided OUTSIDE observe() — the plane's
        public ``evict_tenant`` (operator action, chaos drills, the
        ``[serving.health]`` gate). Idempotent."""
        row = self.row(tenant_id)
        if row.state == EVICTED:
            return
        state_before = row.state
        row.state = EVICTED
        row.sick_streak = 0
        row.healthy_streak = 0
        row.evicted_rounds = 0
        row.evictions += 1
        self._export(tenant_id, row)
        self._journal_move(tenant_id, row, state_before)

    def tick_evicted(self) -> "list[str]":
        """Advance every evicted tenant's clock by one served round;
        returns the tenants whose re-admission window opened."""
        due = []
        for tenant_id, row in self._rows.items():
            if row.state == EVICTED:
                row.evicted_rounds += 1
                if row.evicted_rounds >= self.policy.readmit_after:
                    due.append(tenant_id)
        return due

    def readmitted(self, tenant_id: str) -> None:
        """The plane re-admitted a tenant: start probation."""
        row = self.row(tenant_id)
        state_before = row.state
        row.state = PROBATION
        row.sick_streak = 0
        row.healthy_streak = 0
        row.evicted_rounds = 0
        self._export(tenant_id, row)
        self._journal_move(tenant_id, row, state_before)

    # -- checkpoint seam ------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able ledger state for the plane checkpoint."""
        return {tid: dataclasses.asdict(row)
                for tid, row in self._rows.items()}

    def restore(self, snap: dict) -> None:
        for tid, row in (snap or {}).items():
            self._rows[tid] = TenantHealth(**row)
            self._export(tid, self._rows[tid])
