"""SLO autopilot: the feedback controller that spends the error budget.

The tentpole contracts (ISSUE 17 / docs/serving.md "SLO autopilot"):

* hysteresis: ``degrade_after`` hot rounds per down-move,
  ``restore_after`` cool rounds per up-move, probation re-degrades on
  ONE hot round — the controller never flaps on alternating rounds;
* L1 caps the warm iteration budget by RE-BUCKETING through the
  compile cache (cache hit after first use, deterministic digests);
* L2 relaxes admission deadlines host-side — explicit deadlines too;
* L3 shrinks a robust tenant's tree to its highest-probability
  branches (flat-bucket squeeze at S=1) and restores it on the way up;
* controller state (levels AND counters) rides the plane checkpoint —
  a restore resumes mid-incident at the same quality level without
  re-growing the tree, and restoring autopilot state into a plane
  without a controller fails loudly;
* ``SLOTracker.forget`` tombstones instead of dropping — membership
  churn cannot launder a burn rate;
* quality-reduced metrics publish under the ``_q<level>`` key;
* the incident builder joins overload → down-move → up-move chains.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
from agentlib_mpc_tpu.scenario.tree import fan_tree
from agentlib_mpc_tpu.serving import (
    AutopilotPolicy,
    CompileCache,
    ServingPlane,
    TenantSpec,
)
from agentlib_mpc_tpu.serving.autopilot import LEVERS, SLOAutopilot
from agentlib_mpc_tpu.telemetry.slo import SLOPolicy, SLOTracker

ADMM_OPTS = FusedADMMOptions(max_iterations=4, rho=2.0)
SOLVER_OPTS = SolverOptions(max_iter=30)
#: fast 2-round window + 80% availability target: one missed round in
#: the window is burn 2.5 (hot), one clean window is burn 0 (cool)
SLO = SLOPolicy(availability_target=0.8, windows=(2, 4))
PILOT = AutopilotPolicy(degrade_after=2, restore_after=2,
                        probation_rounds=2)


@pytest.fixture(scope="module")
def ocp():
    return tracker_ocp()


@pytest.fixture(scope="module")
def cache():
    """Shared across the module's planes: identical structures build
    once (the bucket digests are content-addressed, tenant-id-free)."""
    return CompileCache()


def flat_spec(ocp, tid, a=1.0, **kw):
    return TenantSpec(
        tenant_id=tid, ocp=ocp,
        theta=ocp.default_params(p=jnp.array([float(a)])),
        couplings={"shared_u": "u"},
        solver_options=SOLVER_OPTS, **kw)


def robust_spec(ocp, tid):
    """2-branch fan with skewed probabilities: L3 at keep_fraction 0.5
    must keep exactly branch 0 (p=1.0), and the collapsed S=1 spec
    must squeeze into the flat bucket."""
    theta = jax.tree.map(
        lambda leaf: jnp.broadcast_to(jnp.asarray(leaf),
                                      (2,) + np.shape(leaf)),
        ocp.default_params())
    theta = theta._replace(
        p=jnp.stack([jnp.array([1.0]), jnp.array([2.0])]))
    return TenantSpec(
        tenant_id=tid, ocp=ocp, theta=theta,
        couplings={"shared_u": "u"}, solver_options=SOLVER_OPTS,
        scenario_tree=fan_tree(2, probabilities=(0.7, 0.3)))


def make_plane(cache, **kw):
    kw.setdefault("slo_policy", SLO)
    kw.setdefault("autopilot", PILOT)
    return ServingPlane(ADMM_OPTS, slot_multiple=1, initial_capacity=2,
                        pipelined=False, donate=False, cache=cache,
                        **kw)


class Clock:
    """Virtual round clock: a bad round's request expires at the drain
    (submitted with a deadline shorter than the round), a good round's
    does not — burn is driven entirely by ``now`` arithmetic."""

    def __init__(self, plane):
        self.plane = plane
        self.t = 0.0

    def bad(self, *tids):
        for tid in tids:
            self.plane.submit(tid, deadline_s=0.1, now=self.t)
        self.t += 1.0
        out = self.plane.serve_round(now=self.t)
        self.t += 1.0
        return out

    def good(self, *tids):
        for tid in tids:
            self.plane.submit(tid, now=self.t)
        out = self.plane.serve_round(now=self.t)
        self.t += 1.0
        return out


class TestPolicy:
    def test_validation(self):
        with pytest.raises(ValueError, match="burn_threshold"):
            AutopilotPolicy(burn_threshold=0.0)
        with pytest.raises(ValueError, match="dead band"):
            AutopilotPolicy(restore_threshold=1.5, burn_threshold=1.0)
        with pytest.raises(ValueError, match="max_level"):
            AutopilotPolicy(max_level=7)
        with pytest.raises(ValueError, match="TIGHTENS"):
            AutopilotPolicy(l2_deadline_factor=0.5)
        with pytest.raises(ValueError, match="keep_fraction"):
            AutopilotPolicy(l3_keep_fraction=0.0)
        with pytest.raises(ValueError, match="unknown autopilot"):
            AutopilotPolicy.from_config({"warp_factor": 9})

    def test_plane_wiring(self, cache):
        with pytest.raises(TypeError, match="autopilot"):
            make_plane(cache, autopilot=object())
        plane = make_plane(cache, autopilot=None)
        assert plane.autopilot is None
        plane = make_plane(cache)
        assert isinstance(plane.autopilot, SLOAutopilot)
        # no mesh hook: the effective ladder tops out at L3
        assert plane.autopilot.effective_max_level == 3


class TestHysteresis:
    def test_burn_walks_the_ladder_both_ways(self, ocp, cache):
        plane = make_plane(cache)
        plane.join(flat_spec(ocp, "t0"))
        auto = plane.autopilot
        key0 = plane._tenant_bucket["t0"]
        clk = Clock(plane)

        # warm-up: one clean round stores an actuation plan, so the
        # deadline storm below degrades through replay/hold instead of
        # falling straight through to the fallback controller
        assert clk.good("t0")["t0"].action == "actuate"
        # ONE hot round does not move (degrade_after=2): no flapping
        clk.bad("t0")
        assert auto.level("t0") == 0
        assert auto.row("t0").hot_streak == 1
        # the second consecutive hot round buys the L1 down-move
        clk.bad("t0")
        assert auto.level("t0") == 1
        spec = plane._specs["t0"]
        assert spec.warm_solver_options is not None
        assert spec.warm_solver_options.max_iter == \
            PILOT.l1_warm_max_iter
        key1 = plane._tenant_bucket["t0"]
        assert key1 != key0, "L1 must re-bucket (warm budget is a key " \
                             "field)"
        # L1 does not touch deadlines
        assert auto.relaxed_deadline("t0", 0.1) == 0.1
        # two more hot rounds walk to L2 — which relaxes deadlines
        clk.bad("t0")
        assert auto.level("t0") == 1
        clk.bad("t0")
        assert auto.level("t0") == 2
        assert auto.relaxed_deadline("t0", 0.1) == pytest.approx(
            0.1 * PILOT.l2_deadline_factor)
        # L2 is host-side: same bucket as L1
        assert plane._tenant_bucket["t0"] == key1

        # recovery is hysteretic: the fast window still carries the
        # last miss on the first good round — no up-move until
        # restore_after CLEAN windows
        clk.good("t0")
        assert auto.level("t0") == 2
        clk.good("t0")
        assert auto.level("t0") == 2
        clk.good("t0")
        assert auto.level("t0") == 1, "2 cool rounds buy ONE up-move"
        assert auto.row("t0").probation == PILOT.probation_rounds
        # probation: a SINGLE hot round re-degrades immediately
        clk.bad("t0")
        assert auto.level("t0") == 2
        assert auto.row("t0").probation == 0

    def test_idle_rounds_never_earn_restore(self, ocp, cache):
        plane = make_plane(cache)
        plane.join(flat_spec(ocp, "t0"))
        auto = plane.autopilot
        clk = Clock(plane)
        clk.good("t0")
        clk.bad("t0")
        assert auto.row("t0").hot_streak == 1
        # the window is ROUND-based: one idle round later the fast
        # window still spans the miss, so the streak keeps building
        # and buys the L1 move...
        plane.serve_round(now=clk.t)
        assert auto.level("t0") == 1
        # ...but once the miss ages out, idle rounds read burn=None and
        # are NEUTRAL: no cool credit, no restore — a silent tenant
        # cannot buy its quality back without delivering clean traffic
        for _ in range(6):
            plane.serve_round(now=clk.t)
        assert auto.level("t0") == 1
        assert auto.row("t0").cool_streak == 0


class TestLevers:
    def test_l2_relaxes_explicit_deadline_at_submit(self, ocp, cache):
        plane = make_plane(cache)
        plane.join(flat_spec(ocp, "t0"))
        assert plane.autopilot.force_level(plane, "t0", 2)
        # deadline 0.5 would expire at now=1.0; the x4 relaxation
        # (applied to the EXPLICIT deadline) keeps it admissible
        plane.submit("t0", deadline_s=0.5, now=0.0)
        res = plane.serve_round(now=1.0)
        assert res["t0"].action == "actuate"

    def test_l3_shrinks_tree_and_restores_it(self, ocp, cache):
        plane = make_plane(cache)
        plane.join(robust_spec(ocp, "r0"))
        assert plane._specs["r0"].scenario_tree.n_scenarios == 2
        assert plane.autopilot.force_level(plane, "r0", 3)
        spec = plane._specs["r0"]
        # keep_fraction 0.5 keeps the high-probability branch only —
        # the S=1 degenerate squeezes into the FLAT bucket
        assert spec.scenario_tree is None
        assert spec.theta.p.shape == (1,)
        assert float(spec.theta.p[0]) == pytest.approx(1.0)
        assert plane.autopilot.force_level(plane, "r0", 0)
        spec = plane._specs["r0"]
        assert spec.scenario_tree is not None
        assert spec.scenario_tree.n_scenarios == 2
        assert spec.theta.p.shape == (2, 1)
        assert spec.warm_solver_options is None

    def test_ladder_cycle_is_cache_hit_after_first_use(self, ocp,
                                                       cache):
        plane = make_plane(cache)
        plane.join(robust_spec(ocp, "r0"))
        digests = {}

        def cycle(record):
            for lvl in (1, 2, 3, 2, 1, 0):
                assert plane.autopilot.force_level(plane, "r0", lvl)
                d = plane._tenant_bucket["r0"].digest
                if record:
                    digests[lvl] = d
                else:
                    assert digests[lvl] == d, \
                        "effective bucket digests must be " \
                        "deterministic across cycles"

        cycle(record=True)          # pays each level's build once
        misses = plane.cache.misses
        hits = plane.cache.hits
        cycle(record=False)         # every rung comes out of the cache
        assert plane.cache.misses == misses, \
            "repeat ladder cycle caused a cold engine build"
        assert plane.cache.hits > hits


class TestCheckpoint:
    def test_mid_incident_restore_keeps_level_and_counters(
            self, ocp, cache, tmp_path):
        plane = make_plane(cache)
        plane.join(robust_spec(ocp, "r0"))
        assert plane.autopilot.force_level(plane, "r0", 3)
        row = plane.autopilot.row("r0")
        row.hot_streak = 1
        row.cool_streak = 0
        row.probation = 1
        shrunk = plane._tenant_bucket["r0"].digest
        path = plane.save_checkpoint(str(tmp_path / "plane"))

        fresh = make_plane(cache)
        misses = fresh.cache.misses
        report = fresh.restore_checkpoint(path, {"r0": robust_spec(
            ocp, "r0")})
        # the restore resumes mid-incident: same level, same counters,
        # same SHRUNK effective bucket — through the cache, not a build
        assert report.cold_builds == 0
        assert fresh.cache.misses == misses
        assert fresh.autopilot.level("r0") == 3
        restored = fresh.autopilot.row("r0")
        assert (restored.hot_streak, restored.cool_streak,
                restored.probation, restored.moves) == \
            (row.hot_streak, row.cool_streak, row.probation, row.moves)
        assert fresh._tenant_bucket["r0"].digest == shrunk
        assert fresh._specs["r0"].scenario_tree is None
        # the first post-restore round must NOT re-grow the tree (one
        # cool round is still below restore_after)
        fresh.submit("r0", now=0.0)
        res = fresh.serve_round(now=0.0)
        assert res["r0"].action == "actuate"
        assert fresh.autopilot.level("r0") == 3
        assert fresh._specs["r0"].scenario_tree is None
        assert fresh.cache.misses == misses

    def test_autopilot_state_without_controller_is_rejected(
            self, ocp, cache, tmp_path):
        plane = make_plane(cache)
        plane.join(robust_spec(ocp, "r0"))
        assert plane.autopilot.force_level(plane, "r0", 1)
        path = plane.save_checkpoint(str(tmp_path / "plane"))
        bare = make_plane(cache, autopilot=None)
        with pytest.raises(ValueError,
                           match="no autopilot= configured"):
            bare.restore_checkpoint(path, {"r0": robust_spec(ocp,
                                                             "r0")})


class TestForgetTombstone:
    def test_rejoin_resumes_burn_inside_window(self):
        slo = SLOTracker(SLOPolicy(availability_target=0.8,
                                   windows=(2, 4)))
        for r in range(2):
            slo.record_result("a", "hold")
            slo.tick_round(r)
        assert slo.burn_rates()["a"][2] == pytest.approx(5.0)
        slo.forget("a")
        # tombstoned: out of the report's tenant section...
        assert "a" not in slo.report()["tenants"]
        # ...but a rejoin INSIDE max_window resumes the old windows —
        # a fresh row would read burn 0 here, laundering the burn
        slo.record_result("a", "actuate")
        slo.tick_round(2)
        assert slo.burn_rates()["a"][2] == pytest.approx(2.5)
        assert "a" in slo.report()["tenants"]

    def test_row_really_goes_after_window_ages_out(self):
        slo = SLOTracker(SLOPolicy(availability_target=0.8,
                                   windows=(2, 4)))
        slo.record_result("a", "hold")
        slo.tick_round(0)
        slo.forget("a")
        snap = slo.snapshot()
        assert snap["tombstones"] == {"a": 4}
        # restore round-trips the tombstone
        slo2 = SLOTracker(SLOPolicy(availability_target=0.8,
                                    windows=(2, 4)))
        slo2.restore(snap)
        assert "a" not in slo2.report()["tenants"]
        for r in range(1, 5):
            slo2.tick_round(r)
        assert "a" not in slo2.burn_rates()
        assert "a" not in slo2.snapshot()["tenants"]


class TestQualifiedMetric:
    def test_quality_level_suffix(self):
        from agentlib_mpc_tpu.telemetry.regression import (
            qualified_metric,
        )

        assert qualified_metric("m", "tpu") == "m"
        assert qualified_metric("m", "cpu", quality_level=3) == \
            "m_cpu_q3"
        assert qualified_metric("m", "tpu", quality_level=1) == "m_q1"
        assert qualified_metric("m", "tpu", n_devices=4,
                                quality_level=2) == "m_d4_q2"
        assert qualified_metric("m", "cpu", degraded=True,
                                quality_level=2) == "m_cpu_q2_degraded"
        # level 0 = full quality = no suffix
        assert qualified_metric("m", "cpu", quality_level=0) == "m_cpu"


class TestIncidentChain:
    EVENTS = [
        {"seq": 1, "round": 4, "etype": "chaos.injected",
         "rule": "serve_overload", "target": "round4", "seed": 0},
        {"seq": 2, "round": 5, "etype": "autopilot.move",
         "tenant": "t0", "level_from": 0, "level_to": 1,
         "direction": "down", "lever": LEVERS[1], "trigger": "burn",
         "burn": 2.5, "window": 2, "probation_strike": False},
        {"seq": 3, "round": 9, "etype": "autopilot.move",
         "tenant": "t0", "level_from": 1, "level_to": 0,
         "direction": "up", "lever": LEVERS[1], "trigger": "burn",
         "burn": 0.0, "window": 2, "probation_strike": False},
    ]

    def test_overload_chain_joins_down_then_up(self):
        from agentlib_mpc_tpu.telemetry.incident import build_incident

        report = build_incident(list(self.EVENTS))
        assert report["complete_chains"] == 1
        chain = report["chains"][0]
        assert chain["symptom"]["direction"] == "down"
        assert chain["recovery"]["direction"] == "up"

    def test_down_move_alone_is_incomplete(self):
        from agentlib_mpc_tpu.telemetry.incident import build_incident

        report = build_incident(list(self.EVENTS[:2]))
        assert report["complete_chains"] == 0
        assert report["chains"][0]["status"] == "incomplete"

    def test_markdown_renders_the_ladder_transition(self):
        from agentlib_mpc_tpu.telemetry.incident import (
            build_incident,
            render_markdown,
        )

        md = render_markdown(build_incident(list(self.EVENTS)))
        assert "autopilot.move" in md
        assert "L0→L1" in md
        assert "warm_iters" in md
        assert "burn=2.5 over 2-round window" in md
