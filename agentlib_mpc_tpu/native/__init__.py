"""Native (C++) components, loaded through ctypes.

The reference outsources its combinatorial heavy lifting to third-party
C++ binaries (pycombina's branch-and-bound, SURVEY.md §2.8). This package
holds the framework's own native sources, compiled on demand with the
system toolchain into a per-version shared library next to the sources.
Every native entry point has a pure-Python fallback at its call site, so a
missing compiler degrades performance, never capability.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import sysconfig
import tempfile
from pathlib import Path

logger = logging.getLogger(__name__)

_DIR = Path(__file__).parent
_LIB_CACHE: dict[str, ctypes.CDLL | None] = {}


def _so_path(name: str, src: Path) -> Path:
    # the source hash is part of the filename: a changed .cpp can never be
    # satisfied by a stale binary (mtime comparisons break across clones)
    digest = hashlib.sha256(src.read_bytes()).hexdigest()[:12]
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return _DIR / f"_{name}-{digest}{suffix}"


def _compile(name: str) -> Path | None:
    src = _DIR / f"{name}.cpp"
    out = _so_path(name, src)
    if out.exists():
        return out
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           str(src), "-o", str(out)]
    try:
        # build into a temp file then rename: concurrent test workers must
        # never dlopen a half-written .so
        with tempfile.NamedTemporaryFile(
                dir=_DIR, suffix=".so.tmp", delete=False) as tmp:
            tmp_path = Path(tmp.name)
        cmd[-1] = str(tmp_path)
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, out)
        return out
    except (OSError, subprocess.SubprocessError) as exc:
        logger.warning("native build of %s failed (%s); using the Python "
                       "fallback", name, exc)
        try:
            tmp_path.unlink(missing_ok=True)
        except (OSError, NameError):
            pass
        return None


def load(name: str) -> ctypes.CDLL | None:
    """Compile (if needed) and dlopen native/<name>.cpp. None on failure."""
    if name not in _LIB_CACHE:
        path = _compile(name)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(str(path))
            except OSError as exc:  # pragma: no cover - load after build
                logger.warning("cannot load %s: %s", path, exc)
        _LIB_CACHE[name] = lib
    return _LIB_CACHE[name]
