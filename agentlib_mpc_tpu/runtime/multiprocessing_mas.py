"""Process-per-agent MAS with a localhost broadcast broker.

Counterpart of the reference's ``MultiProcessingMAS`` +
``multiprocessing_broadcast`` communicator (SURVEY.md §2.9;
``examples/admm/admm_example_multiprocessing.py:28-36``): every agent runs
in its own OS process with a real-time(-scaled) clock, linked through a
central TCP relay on localhost. The relay forwards length-prefixed JSON
frames from each connection to every other — the same star topology as
the reference's ``MultiProcessingBroker``.

The per-agent wiring mirrors the in-process ``BroadcastBus`` seam: shared
variables leaving an agent's DataBroker are framed onto the socket; a
reader thread injects received variables with ``from_external=True``.
Everything device-side (jit caches, warm starts) stays process-local.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import socket
import threading
import time as _time
from typing import Optional

from agentlib_mpc_tpu.runtime.wire import (
    FramedSocket,
    var_from_wire,
    var_to_wire,
)

logger = logging.getLogger(__name__)


class MultiProcessingBroker:
    """Central localhost relay (reference ``MultiProcessingBroker``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen()
        self.host, self.port = self._server.getsockname()
        self._clients: list[FramedSocket] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                raw, _ = self._server.accept()
            except OSError:
                return
            conn = FramedSocket(raw)
            with self._lock:
                self._clients.append(conn)
            t = threading.Thread(target=self._relay_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _relay_loop(self, conn: FramedSocket) -> None:
        while not self._stop.is_set():
            try:
                frame = conn.recv_frame()
            except OSError:
                break
            if frame is None:
                break
            with self._lock:
                targets = [c for c in self._clients if c is not conn]
            for c in targets:
                try:
                    c.send_frame(frame)
                except OSError:
                    pass
        with self._lock:
            if conn in self._clients:
                self._clients.remove(conn)
        conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            for c in self._clients:
                try:
                    c.close()
                except OSError:
                    pass
            self._clients.clear()


class SocketBus:
    """Drop-in for BroadcastBus backed by the relay socket."""

    def __init__(self, sock: socket.socket, broker):
        self._sock = FramedSocket(sock)
        self._broker = broker
        self._stop = threading.Event()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)

    def start(self) -> None:
        self._reader.start()

    def broadcast(self, from_agent: str, var) -> None:
        try:
            self._sock.send_frame(var_to_wire(var))
        except OSError as exc:
            logger.warning("broadcast failed: %s", exc)

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            try:
                frame = self._sock.recv_frame()
            except OSError:
                return
            if frame is None:
                return
            try:
                var = var_from_wire(frame)
            except (ValueError, KeyError) as exc:
                logger.warning("dropping malformed frame: %s", exc)
                continue
            self._broker.send_variable(var, from_external=True)

    def stop(self) -> None:
        self._stop.set()
        self._sock.close()


def _agent_process_main(agent_config: dict, env_config: dict,
                        host: str, port: int, until: float,
                        result_queue: mp.Queue,
                        bootstrap=None, barrier=None) -> None:
    """Child entry: build the agent, bridge its broker to the relay, run.

    ``bootstrap``: optional callable executed first in the fresh process —
    the per-process runtime hook (device selection, jax platform pinning,
    logging setup). Spawned children inherit no parent runtime state.

    ``barrier``: start barrier across all agent processes. Without it,
    import/compile skew means one agent's real-time clock can run out
    before another is even connected — the same reason the reference opens
    a registration window before each round (``admm.py:249-261``)."""
    if bootstrap is not None:
        bootstrap()
    import agentlib_mpc_tpu.modules  # noqa: F401 - register module types
    from agentlib_mpc_tpu.runtime.agent import Agent
    from agentlib_mpc_tpu.runtime.environment import Environment

    sock = socket.create_connection((host, port), timeout=10.0)
    env = Environment(**env_config)
    agent = Agent(agent_config, env)
    bus = SocketBus(sock, agent.data_broker)
    agent.data_broker.attach_bus(bus)
    bus.start()
    agent.start()
    try:
        if barrier is not None:
            barrier.wait(timeout=600.0)
        env.run(until=until)
        results = {}
        for module_id, module in agent.modules.items():
            res = module.results()
            if res is not None:
                results[module_id] = res
        result_queue.put((agent.id, results))
    finally:
        bus.stop()


class MultiProcessingMAS:
    """Process-per-agent runner (reference ``MultiProcessingMAS``).

    env defaults to real time with a fast-forward factor — cross-process
    sync has no shared simulated clock, exactly like the reference, which
    is real-time-locked in this mode."""

    def __init__(self, agent_configs: list[dict],
                 env: Optional[dict] = None, host: str = "127.0.0.1",
                 bootstrap=None):
        self.agent_configs = list(agent_configs)
        self.bootstrap = bootstrap
        self.env_config = {"rt": True, "factor": 1.0, **(env or {})}
        if not self.env_config.get("rt", True):
            raise ValueError(
                "MultiProcessingMAS requires a real-time environment "
                "(rt=True, optionally factor<1 for fast-forward); use "
                "LocalMAS for fast simulation")
        self.broker = MultiProcessingBroker(host=host)
        self._results: dict = {}

    def run(self, until: float, join_timeout: Optional[float] = None) -> None:
        ctx = mp.get_context("spawn")
        queue: mp.Queue = ctx.Queue()
        barrier = ctx.Barrier(len(self.agent_configs))
        procs = []
        for cfg in self.agent_configs:
            p = ctx.Process(
                target=_agent_process_main,
                args=(cfg, self.env_config, self.broker.host,
                      self.broker.port, until, queue, self.bootstrap,
                      barrier),
                daemon=True)
            p.start()
            procs.append(p)
        if join_timeout is None:
            join_timeout = until * self.env_config.get("factor", 1.0) + 60.0
        deadline = _time.monotonic() + join_timeout
        for _ in procs:
            remaining = max(deadline - _time.monotonic(), 0.1)
            try:
                agent_id, results = queue.get(timeout=remaining)
                self._results[agent_id] = results
            except Exception:  # queue.Empty
                logger.warning("an agent process missed the deadline")
                break
        for p in procs:
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
        self.broker.close()

    def get_results(self) -> dict:
        return dict(self._results)
