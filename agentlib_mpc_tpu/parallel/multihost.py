"""Multi-host execution of the fused data plane.

The reference scales out by running one OS process per agent and wiring
them over MQTT or cloneMAP containers (SURVEY §2.2/§2.9; reference
``modules/dmpc/admm/admm.py``, ``DockerfileMPC``) — the *control plane*
and the *data plane* are the same fabric. Here they are deliberately
split:

* **Control plane** (slow, robust): broker / TCP / MQTT messaging between
  agent processes — ``runtime/broker.py``, ``runtime/multiprocessing_mas.py``,
  ``runtime/mqtt.py``. Latency-tolerant, schema-stable JSON.
* **Data plane** (fast): the fused ADMM round as ONE SPMD program over a
  ``jax.sharding.Mesh`` (``parallel/fused_admm.py``). Consensus means
  lower to XLA all-reduces that ride ICI within a host and DCN across
  hosts — the TPU-native replacement for per-agent NCCL/MPI traffic.

This module provides the two pieces a multi-host deployment needs on top
of the single-controller API:

* :func:`initialize_multihost` — env-var-aware wrapper over
  ``jax.distributed.initialize`` (the JAX multi-controller runtime). A
  no-op for single-process runs, so the same launch script works from a
  laptop to a pod slice.
* :func:`fleet_mesh` — the 1-D "agents" mesh over all global devices.
  ``jax.devices()`` orders devices process-major, so consecutive mesh
  positions sit on the same host wherever possible: XLA's hierarchical
  all-reduce then reduces over ICI first and crosses DCN once per host
  pair, not once per chip pair (the "ride ICI, not DCN" rule of the
  scaling playbook).

Typical multi-host launch (same script on every host; the "Scaling out"
recipe in docs/API.md)::

    from agentlib_mpc_tpu.parallel import multihost

    multihost.initialize_multihost()          # reads JAX_COORDINATOR etc.
    mesh = multihost.fleet_mesh()
    # groups padded to the shard multiple (pad_group_to_devices) so the
    # agent axis divides the mesh — mesh engines REQUIRE divisibility
    engine = FusedADMM(groups, options, active=masks, mesh=mesh)
    state, thetas = engine.shard_args(mesh, engine.init_state(thetas),
                                      thetas)
    state, trajs, stats = engine.step(state, thetas)

Every process executes the same jitted step. With ``mesh=`` the step is
an explicit ``shard_map`` over the agent axis: the per-group vmapped
augmented solves run shard-local and the ADMM consensus/exchange means
lower to ``lax.psum`` over the mesh axis — one all-reduce family per
ADMM iteration, an invariant that is statically PROVED (not assumed) at
engine build: on a multi-process mesh a fused round whose collective
schedule refutes — a shard-varying exit predicate over a psum is a
silent cross-host hang no process can observe — refuses to dispatch
(:mod:`agentlib_mpc_tpu.lint.jaxpr.collectives`; docs/DISTRIBUTED.md
"Certify before you pod"). Without ``mesh=``, ``shard_args`` placement leaves the
partitioning to XLA's GSPMD propagation. Either way there is no
coordinator process in the data plane — the ADMM "coordinator" of the
reference's star topology becomes a mean (all-reduce) inside the
program.

**The shard-multiple contract**: every per-agent batch a sharded engine
touches (group agent axes, serving slot capacities) must be a multiple
of :func:`shard_multiple` (= the mesh device count). Pad uneven fleets
with :func:`~agentlib_mpc_tpu.parallel.fused_admm.pad_group_to_devices`
— padded lanes ride the masks and are dead weight, never wrong answers
— and build serving capacities at :func:`serving_slot_multiple`
granularity so a serving bucket can sit on a sharded engine unchanged.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import NamedTuple

import jax
from jax.sharding import Mesh

#: bound on the post-condemnation per-device re-probe: diagnostic only,
#: must not extend a stalled round's blocking time by another watchdog
#: budget (the serving dispatch watchdog's PROBE_TIMEOUT_S rule)
MESH_PROBE_TIMEOUT_S = 2.0

# set after a successful jax.distributed.initialize in THIS process, so
# repeated initialize_multihost calls are idempotent without depending on
# the wording of JAX's already-initialized error message
_initialized = False


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize the JAX multi-controller runtime if configured.

    Resolution order: explicit arguments, then the standard environment
    (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``, as set by most TPU pod launchers). When neither
    is present this is a single-process run and the call is a no-op —
    the same entry point works unmodified on one host.

    Returns True when the distributed runtime was (already) initialized.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])

    if coordinator_address is None and num_processes in (None, 1):
        return False  # single-process: nothing to initialize

    global _initialized
    if _initialized:
        return True

    # NOTE: nothing here may touch the backend (jax.devices(),
    # jax.process_count(), ...) before initialize() — that would
    # initialize XLA and make distributed init impossible. The flag above
    # handles idempotence within this process; the message sniff below is
    # only a fallback for an initialize() done outside this module. A
    # "must be called before any JAX calls" error is a real caller bug
    # and propagates.
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as exc:
        msg = str(exc).lower()
        if "once" not in msg and "already" not in msg:
            raise
    _initialized = True
    return True


def fleet_mesh(axis: str = "agents", devices=None) -> Mesh:
    """1-D mesh over all global devices for agent-axis sharding.

    ``jax.devices()`` is process-major (all of host 0's chips, then host
    1's, ...), so sharding a contiguous agent batch over this mesh keeps
    each host's shard local and lets XLA's hierarchical collectives
    reduce over ICI before touching DCN. Pass ``devices`` to sub-select
    (e.g. an 8-device virtual CPU mesh in tests).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    return Mesh(devices, (axis,))


def scenario_mesh(n_scenario_shards: int, devices=None) -> Mesh:
    """2-D (agents × scenarios) mesh for the scenario fleet
    (:class:`agentlib_mpc_tpu.scenario.fleet.ScenarioFleet`): the
    process-major device list folded into an ``(agents, scenarios)``
    grid with ``n_scenario_shards`` inner columns — scenarios of one
    agent shard stay as close (ICI-adjacent) as the device order
    allows, so the per-iteration non-anticipativity psum rides the
    cheap axis while the agent consensus spans the long one (the
    ISSUE 12 second mesh dimension; SNIPPETS.md [1]'s multi-process
    pjit mesh shape, explicit)."""
    import numpy as np

    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    k = int(n_scenario_shards)
    if k < 1 or n % k:
        raise ValueError(
            f"{n} devices do not fold into {k} scenario shard(s)")
    grid = np.array(devices).reshape(n // k, k)
    return Mesh(grid, ("agents", "scenarios"))


def collective_probe(mesh: Mesh, horizon: int):
    """(compiled pmean, input) — one consensus-shaped collective over
    ``mesh``: a (T,)-trajectory ``pmean`` across the mesh axis, the
    exact cross-agent dependency of one fused ADMM iteration. ONE
    builder shared by the engine's per-round ``admm_collective_seconds``
    probe (``FusedADMM``) and ``bench.py --emit-metrics``'s ``mesh``
    section, so the two numbers can never drift apart structurally.
    The returned callable is compiled AND warmed — timing a call never
    includes a trace."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    import jax.numpy as jnp

    axis = mesh.axis_names[0]
    probe = jax.jit(shard_map(
        lambda x: jax.lax.pmean(x, axis), mesh=mesh,
        in_specs=P(axis), out_specs=P(), check_rep=False))
    x = jnp.zeros((int(mesh.devices.size), max(int(horizon), 1)))
    jax.block_until_ready(probe(x))
    return probe, x


class ShardProbeReport(NamedTuple):
    """Which mesh devices answered a bounded per-device round-trip —
    the record a condemned collective leaves behind (ISSUE 10: "records
    which shards answered")."""

    #: device ids that completed the probe inside the bound, mesh order
    answered: tuple
    #: device ids that did not answer (the suspect shards)
    dead: tuple
    #: device id -> probe round-trip seconds (answered devices only)
    latency_s: dict

    @property
    def all_answered(self) -> bool:
        return not self.dead


class MeshRoundTimeout(RuntimeError):
    """A mesh-dispatched fused round blew its collective-watchdog
    budget. Carries the post-condemnation :class:`ShardProbeReport` so
    the degraded-mesh fallback can rebuild on exactly the shards that
    still answer. ``probe`` is None when the engine had no mesh to
    probe (single-device watchdog timeout)."""

    def __init__(self, message: str,
                 probe: "ShardProbeReport | None" = None):
        super().__init__(message)
        self.probe = probe


def probe_mesh_devices(mesh: Mesh,
                       timeout_s: float = MESH_PROBE_TIMEOUT_S,
                       ) -> ShardProbeReport:
    """Bounded per-device liveness probe over a mesh.

    One daemon thread per device runs a trivial host→device transfer
    and blocks on its completion; every thread gets the SAME wall-clock
    deadline (a dead device costs ``timeout_s`` once, not per device).
    Unanswered devices are the wedged-tunnel signature at device
    granularity — the serving layer's ``probe_device_bounded`` asked
    "is the backend alive?"; this asks "WHICH shards are alive?", which
    is what the degraded-mesh rebuild needs.
    """
    import numpy as np

    devices = list(mesh.devices.flat)
    results: dict = {}

    def probe_one(dev) -> None:
        t0 = time.perf_counter()
        jax.device_put(np.zeros((1,)), dev).block_until_ready()
        results[dev.id] = time.perf_counter() - t0

    threads = [threading.Thread(target=probe_one, args=(d,), daemon=True,
                                name=f"mesh-probe-{d.id}")
               for d in devices]
    deadline = time.monotonic() + float(timeout_s)
    for t in threads:
        t.start()
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    answered = tuple(d.id for d in devices if d.id in results)
    dead = tuple(d.id for d in devices if d.id not in results)
    return ShardProbeReport(answered=answered, dead=dead,
                            latency_s=dict(results))


def surviving_mesh(mesh: Mesh, answered_ids) -> Mesh:
    """The degraded 1-D mesh over the devices that still answer, in the
    original mesh order (shard row ranges of surviving devices keep
    their relative order, so carried state rows stay aligned)."""
    import numpy as np

    keep = set(answered_ids)
    devices = [d for d in mesh.devices.flat if d.id in keep]
    if not devices:
        raise ValueError(
            "no surviving devices to build a degraded mesh from — the "
            "whole mesh is unreachable (escalate to checkpoint restore)")
    return Mesh(np.array(devices), mesh.axis_names)


def surviving_mesh_2d(mesh: Mesh, rows, cols) -> Mesh:
    """The degraded 2-D mesh over surviving ROW and COLUMN indices of
    an (agents × scenarios) grid, original order preserved on both
    axes. A 2-D mesh must stay rectangular, so a single dead device
    costs its whole row (agents-axis degrade) or its whole column
    (scenarios-axis degrade) — the axis classification is the
    supervisor's call (:class:`~agentlib_mpc_tpu.parallel.survival.
    ScenarioFleetSupervisor`); this only builds the rectangle."""
    import numpy as np

    grid = np.asarray(mesh.devices)
    if grid.ndim != 2:
        raise ValueError(
            f"surviving_mesh_2d needs a 2-D mesh, got axes "
            f"{mesh.axis_names}")
    rows = tuple(int(r) for r in rows)
    cols = tuple(int(c) for c in cols)
    if not rows or not cols:
        raise ValueError(
            "no surviving rows/columns to build a degraded 2-D mesh "
            "from — the whole mesh is unreachable (escalate to "
            "checkpoint restore)")
    return Mesh(grid[np.ix_(rows, cols)], mesh.axis_names)


def shard_multiple(mesh: "Mesh | None" = None) -> int:
    """Agent-axis granularity a sharded engine requires.

    A ``FusedADMM(..., mesh=mesh)`` engine splits every per-agent batch
    into equal per-device shards, so group sizes must be a multiple of
    the mesh device count (``pad_group_to_devices`` pads uneven fleets).
    Without a mesh this is the global device count — the divisibility
    rule :meth:`FusedADMM.shard_args` and :func:`host_local_batch` apply
    to GSPMD placement.
    """
    if mesh is not None:
        return max(1, int(mesh.devices.size))
    return max(1, len(jax.devices()))


def serving_slot_multiple(mesh: "Mesh | None" = None) -> int:
    """Slot-count granularity for the serving plane's padded groups.

    Capacities that are a multiple of the global device count let
    :meth:`FusedADMM.shard_args` shard the agent axis instead of
    replicating it (the :func:`host_local_batch` divisibility rule), so
    the serving plane rounds every bucket's capacity up to this. On a
    single-device host this is 1 and the rounding is a no-op.

    With ``mesh`` the multiple is ``lcm(device count, mesh size)``: a
    serving bucket built at this granularity is splice-compatible with a
    sharded engine (every capacity divides the mesh) AND with GSPMD
    placement over the full device set — mesh-backed serving planes
    (``ServingPlane(mesh=...)``) size their buckets with this.
    """
    base = max(1, len(jax.devices()))
    if mesh is None:
        return base
    return math.lcm(base, shard_multiple(mesh))


def host_local_batch(n_agents_global: int) -> tuple[int, int]:
    """(start, count) of this process's slice of a global agent batch.

    For data loading in multi-controller runs: each process materializes
    only its own shard of the per-agent parameter batch
    (``jax.make_array_from_process_local_data`` with a :func:`fleet_mesh`
    sharding then forms the global array from the per-host pieces).

    The agent axis must divide the global device count — that is the
    layout a 1-D ``NamedSharding`` accepts (uneven axes are rejected by
    JAX). Pad uneven fleets first
    (:func:`agentlib_mpc_tpu.parallel.fused_admm.pad_group_to_devices`);
    the slice is then device-granular and exactly matches where
    :func:`fleet_mesh` places the rows.
    """
    n_dev = len(jax.devices())
    if n_agents_global % n_dev:
        raise ValueError(
            f"n_agents={n_agents_global} does not divide the "
            f"{n_dev}-device fleet mesh; pad the batch first "
            f"(parallel.fused_admm.pad_group_to_devices)")
    per_dev = n_agents_global // n_dev
    local = jax.local_device_count() * per_dev
    # jax.devices() is process-major, so this process's rows start after
    # the devices of all lower process ids
    start = sum(
        per_dev for d in jax.devices() if d.process_index <
        jax.process_index())
    return start, local
