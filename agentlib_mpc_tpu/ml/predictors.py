"""Pure-JAX evaluation of serialized ML models.

Counterpart of the reference's ``models/casadi_predictor.py`` (CasadiANN
:197-536, CasadiGPR :113-189, CasadiLinReg :87-110): there, each trained
model is re-implemented *symbolically in CasADi* so it can sit inside an
NLP. Here each becomes a pure function ``apply(params, x) -> y`` — jit,
grad and vmap safe, so the same evaluator serves the plant simulator, the
NARX transcription inside the OCP (where `jax.grad` differentiates through
it for the KKT system), and batched training-data sweeps.

The params pytree is an explicit argument: hot-swapping a retrained model
(§3.5 trainer → controller loop) replaces leaves of identical shape, so
nothing recompiles — the reference instead rebuilds its CasADi graph on
every swap (``casadi_ml_model.py:205-231``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu.ml.serialized import (
    SerializedANN,
    SerializedGPR,
    SerializedGraphANN,
    SerializedKerasANN,
    SerializedLinReg,
    SerializedMLModel,
)

_ACT = {
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softplus": jax.nn.softplus,
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
}

# one function table governs trainer + predictor; the declarative name list
# in serialized.py must match it exactly
from agentlib_mpc_tpu.ml.serialized import ACTIVATIONS as _DECLARED  # noqa: E402

assert set(_ACT) == set(_DECLARED), (
    "activation registries diverged: predictors._ACT vs "
    "serialized.ACTIVATIONS")


class Predictor(NamedTuple):
    """apply(params, x: (n_in,)) → (n_out,); params is a pytree whose
    leaves may be swapped (same shapes) without recompiling."""

    apply: Callable[[Any, jnp.ndarray], jnp.ndarray]
    params: Any
    n_inputs: int
    n_outputs: int
    input_columns: tuple[str, ...]
    output_names: tuple[str, ...]


def _ann_predictor(m: SerializedANN) -> Predictor:
    params = {
        "W": [jnp.asarray(np.asarray(w, dtype=float)) for w in m.weights],
        "b": [jnp.asarray(np.asarray(b, dtype=float)) for b in m.biases],
    }
    acts = tuple(m.activations)

    def apply(p, x):
        h = x
        for W, b, a in zip(p["W"], p["b"], acts):
            h = _ACT[a](h @ W + b)
        return jnp.atleast_1d(h)

    n_out = int(np.asarray(m.biases[-1]).size) if m.biases else 0
    return Predictor(apply, params, m.n_inputs, n_out,
                     tuple(m.input_columns), tuple(m.output_names))


def _gpr_predictor(m: SerializedGPR) -> Predictor:
    x_train = np.asarray(m.x_train, dtype=float)
    d = x_train.shape[1] if x_train.ndim == 2 else 1
    ls = np.broadcast_to(np.asarray(m.length_scale, dtype=float), (d,))
    params = {
        "x_train": jnp.asarray(x_train),
        "alpha": jnp.asarray(np.asarray(m.alpha, dtype=float)),
        "constant_value": jnp.asarray(float(m.constant_value)),
        "length_scale": jnp.asarray(ls),
        "mean": jnp.asarray(np.asarray(
            m.mean if m.mean is not None else np.zeros(d), dtype=float)),
        "std": jnp.asarray(np.asarray(
            m.std if m.std is not None else np.ones(d), dtype=float)),
        "scale": jnp.asarray(float(m.scale)),
    }
    normalize = bool(m.normalize)

    def apply(p, x):
        if normalize:
            x = (x - p["mean"]) / p["std"]
        # k(x, X) = cv * exp(-0.5 * sum_j ((x_j - X_ij)/l_j)^2); the White
        # term has zero cross-covariance, so the posterior mean is k @ alpha
        diff = (x[None, :] - p["x_train"]) / p["length_scale"][None, :]
        k = p["constant_value"] * jnp.exp(-0.5 * jnp.sum(diff * diff,
                                                         axis=1))
        return jnp.atleast_1d(k @ p["alpha"] * p["scale"])

    return Predictor(apply, params, m.n_inputs, len(m.output),
                     tuple(m.input_columns), tuple(m.output_names))


def _linreg_predictor(m: SerializedLinReg) -> Predictor:
    coef = np.atleast_2d(np.asarray(m.coef, dtype=float))  # (n_out, n_in)
    params = {
        "coef": jnp.asarray(coef),
        "intercept": jnp.atleast_1d(
            jnp.asarray(np.asarray(m.intercept, dtype=float))),
    }

    def apply(p, x):
        return p["coef"] @ x + p["intercept"]

    return Predictor(apply, params, m.n_inputs, coef.shape[0],
                     tuple(m.input_columns), tuple(m.output_names))


def _graph_predictor(m: SerializedGraphANN) -> Predictor:
    from agentlib_mpc_tpu.ml.keras_graph import (
        build_graph_apply,
        spec_from_jsonable,
    )

    spec, params = spec_from_jsonable(m.graph)
    apply = build_graph_apply(spec)
    return Predictor(apply, params, m.n_inputs, len(m.output),
                     tuple(m.input_columns), tuple(m.output_names))


def _keras_predictor(m: SerializedKerasANN) -> Predictor:
    # load the .keras artifact, convert once, evaluate as a graph
    return _graph_predictor(m.to_graph())


_MAKERS = {
    SerializedANN: _ann_predictor,
    SerializedGPR: _gpr_predictor,
    SerializedLinReg: _linreg_predictor,
    SerializedGraphANN: _graph_predictor,
    SerializedKerasANN: _keras_predictor,
}


def make_predictor(m: SerializedMLModel) -> Predictor:
    """Build the JAX evaluator for a serialized model (registry mirroring
    the reference's ``casadi_predictor.py:742-747``)."""
    for cls, maker in _MAKERS.items():
        if isinstance(m, cls):
            return maker(m)
    raise TypeError(f"no predictor for {type(m).__name__}")
