"""MQTT communicator: cross-machine interop path.

Counterpart of the reference's MQTT communicator (SURVEY.md §2.9: topics
``/agentlib/<agent_id>``, ``docs/source/tutorials/ADMM.md:69-97``).
paho-mqtt is used when installed (full interop with external brokers,
auth, TLS via paho configuration); without it the bus falls back to the
first-party MQTT 3.1.1 subset client
(:mod:`agentlib_mpc_tpu.runtime.mqtt_native`) — real TCP sockets,
wildcard subscriptions, automatic reconnect — so the MQTT transport
works out of the box with zero optional dependencies (against
:class:`~agentlib_mpc_tpu.runtime.mqtt_native.MiniBroker` or any
standard broker speaking MQTT 3.1.1).
"""

from __future__ import annotations

import logging
from typing import Optional

from agentlib_mpc_tpu.runtime.wire import var_from_wire, var_to_wire

logger = logging.getLogger(__name__)

TOPIC_PREFIX = "/agentlib_mpc_tpu"


class MqttBus:
    """BroadcastBus-compatible bridge publishing shared variables to
    ``<prefix>/<agent_id>`` and subscribing to ``<prefix>/#``."""

    def __init__(self, agent_id: str, broker_host: str = "localhost",
                 broker_port: int = 1883, prefix: str = TOPIC_PREFIX,
                 username: Optional[str] = None,
                 password: Optional[str] = None,
                 reconnect_base: float = 0.05,
                 reconnect_max_delay: float = 1.0):
        """``reconnect_base`` / ``reconnect_max_delay`` bound the native
        client's decorrelated-jitter redial backoff (a fleet must not
        thundering-herd a restarting broker); with paho installed they
        map onto ``reconnect_delay_set(min_delay, max_delay)``."""
        self.agent_id = agent_id
        self.prefix = prefix.rstrip("/")
        self._broker = None
        try:
            import paho.mqtt.client as mqtt
        except ImportError:
            from agentlib_mpc_tpu.runtime.mqtt_native import MiniMqttClient

            logger.info("paho-mqtt not installed; using the first-party "
                        "MQTT 3.1.1 subset client")
            self.client_impl = "native"
            self._client = MiniMqttClient(
                client_id=agent_id, reconnect_base=reconnect_base,
                reconnect_max_delay=reconnect_max_delay)
        else:
            self.client_impl = "paho"
            try:  # paho-mqtt >= 2.0 requires an explicit callback version
                self._client = mqtt.Client(mqtt.CallbackAPIVersion.VERSION1)
            except AttributeError:  # paho-mqtt 1.x
                self._client = mqtt.Client()
            try:
                self._client.reconnect_delay_set(
                    min_delay=max(reconnect_base, 1e-3),
                    max_delay=reconnect_max_delay)
            except AttributeError:   # stub/exotic client without the knob
                pass
        if username:
            self._client.username_pw_set(username, password)
        self._client.on_message = self._on_message
        self._client.connect(broker_host, broker_port)
        self._client.subscribe(f"{self.prefix}/#")
        self._client.loop_start()

    def attach(self, data_broker) -> None:
        self._broker = data_broker
        data_broker.attach_bus(self)

    # BroadcastBus seam -------------------------------------------------------
    def broadcast(self, from_agent: str, var) -> None:
        self._client.publish(f"{self.prefix}/{from_agent}",
                             var_to_wire(var))

    def _on_message(self, client, userdata, msg) -> None:
        if msg.topic == f"{self.prefix}/{self.agent_id}":
            return  # own echo
        if self._broker is None:
            return
        try:
            var = var_from_wire(msg.payload)
        except (ValueError, KeyError) as exc:
            logger.warning("dropping malformed MQTT payload: %s", exc)
            return
        self._broker.send_variable(var, from_external=True)

    def close(self) -> None:
        self._client.loop_stop()
        self._client.disconnect()
