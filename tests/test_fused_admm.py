"""Tests for the fused single-program ADMM engine (parallel/fused_admm.py).

Covers the reference's distributed-MPC semantics end to end
(``modules/dmpc/admm/*``) in the fused path: consensus agreement between a
heterogeneous room/cooler pair, exchange (resource-balance) coupling,
shift-by-one warm starts, residual histories and mesh sharding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.objective import SubObjective
from agentlib_mpc_tpu.models.variables import control_input, parameter
from agentlib_mpc_tpu.models.zoo import CooledRoom, Cooler, ZoneWithSupply
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    stack_params,
)

N = 5
DT = 300.0
SOLVER = SolverOptions(tol=1e-8, max_iter=40)


from conftest import make_tracker_model  # noqa: E402

#: stateless agent min (u - a)^2 — analytic ADMM fixed points
Tracker = make_tracker_model(lb=-5.0, ub=5.0)


@pytest.fixture(scope="module")
def tracker_ocp():
    return transcribe(Tracker(), ["u"], N=N, dt=DT,
                      method="multiple_shooting")


class TestConsensusTrackers:
    """Two trackers with different targets must agree on the mean."""

    def test_agreement(self, tracker_ocp):
        group = AgentGroup(
            name="trackers", ocp=tracker_ocp, n_agents=2,
            couplings={"shared_u": "u"}, solver_options=SOLVER)
        engine = FusedADMM(
            [group],
            FusedADMMOptions(max_iterations=40, rho=2.0, abs_tol=1e-6,
                             rel_tol=1e-5))
        thetas = stack_params([
            tracker_ocp.default_params(p=jnp.array([1.0])),
            tracker_ocp.default_params(p=jnp.array([3.0])),
        ])
        state = engine.init_state([thetas])
        state, trajs, stats = engine.step(state, [thetas])
        assert bool(stats.converged)
        # consensus: both settle on mean target = 2.0
        np.testing.assert_allclose(
            np.asarray(state.zbar["shared_u"]), 2.0, atol=1e-3)
        u0 = np.asarray(trajs[0]["u"])  # (2, N, 1)
        np.testing.assert_allclose(u0[0], u0[1], atol=5e-3)

    def test_explicit_warm_options_fallback_path(self, tracker_ocp):
        """warm_solver_options differing beyond (max_iter, mu_init) forces
        the static two-phase path (shared_trace=False) — pin it to the same
        fixed point as the shared-trace default."""
        group = AgentGroup(
            name="trackers", ocp=tracker_ocp, n_agents=2,
            couplings={"shared_u": "u"}, solver_options=SOLVER,
            warm_solver_options=SOLVER._replace(tol=1e-6, max_iter=6))
        engine = FusedADMM(
            [group],
            FusedADMMOptions(max_iterations=40, rho=2.0, abs_tol=1e-6,
                             rel_tol=1e-5))
        thetas = stack_params([
            tracker_ocp.default_params(p=jnp.array([1.0])),
            tracker_ocp.default_params(p=jnp.array([3.0])),
        ])
        state = engine.init_state([thetas])
        state, trajs, stats = engine.step(state, [thetas])
        assert bool(stats.converged)
        np.testing.assert_allclose(
            np.asarray(state.zbar["shared_u"]), 2.0, atol=1e-3)

    def test_lq_group_auto_routes_to_qp_path(self, tracker_ocp):
        """The Tracker OCP is LQ, and its quadratic ADMM augmentation
        keeps it LQ — the group probe must certify it and the QP-path
        round must land on the same consensus fixed point as the forced
        NLP path."""
        def build(mode):
            group = AgentGroup(
                name="trackers", ocp=tracker_ocp, n_agents=2,
                couplings={"shared_u": "u"}, solver_options=SOLVER,
                qp_fast_path=mode)
            return FusedADMM(
                [group],
                FusedADMMOptions(max_iterations=40, rho=2.0,
                                 abs_tol=1e-6, rel_tol=1e-5))

        auto, off = build("auto"), build("off")
        assert auto.group_uses_qp == (True,)
        assert off.group_uses_qp == (False,)
        thetas = stack_params([
            tracker_ocp.default_params(p=jnp.array([1.0])),
            tracker_ocp.default_params(p=jnp.array([3.0])),
        ])
        for engine in (auto, off):
            state = engine.init_state([thetas])
            state, _trajs, stats = engine.step(state, [thetas])
            assert bool(stats.converged)
            np.testing.assert_allclose(
                np.asarray(state.zbar["shared_u"]), 2.0, atol=1e-3)
        with pytest.raises(ValueError, match="qp_fast_path"):
            build("maybe")

    def test_alias_in_both_coupling_kinds_rejected(self, tracker_ocp):
        """One alias as consensus in one group and exchange in another
        would collide in the per-alias penalty state — rejected at
        engine build."""
        g1 = AgentGroup(name="a", ocp=tracker_ocp, n_agents=1,
                        couplings={"shared_u": "u"},
                        solver_options=SOLVER)
        g2 = AgentGroup(name="b", ocp=tracker_ocp, n_agents=1,
                        exchanges={"shared_u": "u"},
                        solver_options=SOLVER)
        with pytest.raises(ValueError, match="both consensus"):
            FusedADMM([g1, g2], FusedADMMOptions())

    def test_residual_history_monotone_tail(self, tracker_ocp):
        group = AgentGroup(
            name="trackers", ocp=tracker_ocp, n_agents=3,
            couplings={"shared_u": "u"}, solver_options=SOLVER)
        engine = FusedADMM(
            [group], FusedADMMOptions(max_iterations=15, rho=2.0,
                                      abs_tol=1e-9, rel_tol=1e-9))
        thetas = stack_params([
            tracker_ocp.default_params(p=jnp.array([float(a)]))
            for a in (0.0, 1.0, 5.0)])
        state = engine.init_state([thetas])
        _state, _trajs, stats = engine.step(state, [thetas])
        prim = np.asarray(stats.primal_residuals)
        ran = int(stats.iterations)
        assert ran == 15  # tolerance unreachably tight -> runs out
        assert np.all(np.isfinite(prim[:ran]))
        # residuals decay overall
        assert prim[ran - 1] < prim[0]


class TestExchangeTrackers:
    """Exchange coupling: sum_i u_i = 0; optimum is u_i = a_i - mean(a)."""

    def test_resource_balance(self, tracker_ocp):
        group = AgentGroup(
            name="trackers", ocp=tracker_ocp, n_agents=2,
            exchanges={"power": "u"}, solver_options=SOLVER)
        engine = FusedADMM(
            [group],
            FusedADMMOptions(max_iterations=50, rho=1.0, abs_tol=1e-6,
                             rel_tol=1e-5))
        thetas = stack_params([
            tracker_ocp.default_params(p=jnp.array([2.0])),
            tracker_ocp.default_params(p=jnp.array([-1.0])),
        ])
        state = engine.init_state([thetas])
        state, trajs, stats = engine.step(state, [thetas])
        assert bool(stats.converged)
        u = np.asarray(trajs[0]["u"])[:, :, 0]  # (2, N)
        np.testing.assert_allclose(u.sum(axis=0), 0.0, atol=5e-3)
        np.testing.assert_allclose(u[0], 1.5, atol=5e-3)
        np.testing.assert_allclose(u[1], -1.5, atol=5e-3)


class TestRoomCoolerPair:
    """The reference's admm example topology: a cooled room and a cooler
    agree on the air mass flow (``examples/admm/models/*``)."""

    @pytest.fixture(scope="class")
    def engine_and_thetas(self):
        room_model = CooledRoom(overrides={"s_T": 0.1})
        cooler_model = Cooler(overrides={"r_mDot": 0.01})
        room_ocp = transcribe(room_model, ["mDot"], N=N, dt=DT,
                              method="collocation", collocation_degree=2)
        cooler_ocp = transcribe(cooler_model, ["mDot"], N=N, dt=DT,
                                method="multiple_shooting")
        room = AgentGroup(
            name="room", ocp=room_ocp, n_agents=1,
            couplings={"mDot": "mDot"}, solver_options=SOLVER)
        cooler = AgentGroup(
            name="cooler", ocp=cooler_ocp, n_agents=1,
            couplings={"mDot": "mDot"}, solver_options=SOLVER)
        engine = FusedADMM(
            [room, cooler],
            FusedADMMOptions(max_iterations=30, rho=50.0, abs_tol=1e-5,
                             rel_tol=1e-4))
        room_theta = stack_params([room_ocp.default_params(
            x0=jnp.array([298.15]),
            d_traj=jnp.broadcast_to(jnp.array([150.0, 290.15, 295.15]),
                                    (N, 3)))])
        cooler_theta = stack_params([cooler_ocp.default_params()])
        return engine, (room_theta, cooler_theta)

    def test_pair_agrees_and_cools(self, engine_and_thetas):
        engine, thetas = engine_and_thetas
        state = engine.init_state(thetas)
        state, trajs, stats = engine.step(state, thetas)
        u_room = np.asarray(trajs[0]["u"])[0, :, 0]
        u_cooler = np.asarray(trajs[1]["u"])[0, :, 0]
        # agreement on the coupling
        np.testing.assert_allclose(u_room, u_cooler, atol=1e-3)
        # the room is warm: it must request cooling air
        assert u_room[0] > 1e-3
        # room temperature trajectory decreases toward comfort
        T = np.asarray(trajs[0]["x"])[0, :, 0]
        assert T[-1] < T[0]

    def test_warm_start_shift_speeds_convergence(self, engine_and_thetas):
        engine, thetas = engine_and_thetas
        state = engine.init_state(thetas)
        state, _trajs, stats_cold = engine.step(state, thetas)
        # second control step warm-started from the shifted state
        state = engine.shift_state(state)
        _state2, _trajs2, stats_warm = engine.step(state, thetas)
        assert int(stats_warm.iterations) <= int(stats_cold.iterations)


class TestMeshSharding:
    @pytest.mark.slow
    def test_sharded_step_matches_single_device(self, eight_devices):
        from jax.sharding import Mesh

        ocp = transcribe(ZoneWithSupply(), ["mDot"], N=3, dt=DT,
                         method="collocation", collocation_degree=2)
        group = AgentGroup(
            name="zones", ocp=ocp, n_agents=8,
            couplings={"mDot": "mDot"},
            solver_options=SolverOptions(tol=1e-8, max_iter=25))
        engine = FusedADMM(
            [group], FusedADMMOptions(max_iterations=5, rho=20.0))
        thetas = stack_params([
            ocp.default_params(
                x0=jnp.array([294.0 + 0.5 * i]),
                d_traj=jnp.broadcast_to(
                    jnp.array([100.0 + 10 * i, 290.15, 294.15]), (3, 3)))
            for i in range(8)])

        state0 = engine.init_state([thetas])
        _, trajs_ref, stats_ref = engine.step(state0, [thetas])

        mesh = Mesh(np.array(eight_devices), axis_names=("agents",))
        state_sh, thetas_sh = engine.shard_args(mesh, state0, [thetas])
        _, trajs_sh, stats_sh = engine.step(state_sh, thetas_sh)

        np.testing.assert_allclose(
            np.asarray(trajs_ref[0]["u"]), np.asarray(trajs_sh[0]["u"]),
            rtol=1e-5, atol=1e-7)
        assert int(stats_ref.iterations) == int(stats_sh.iterations)


class TestHeterogeneousFleet:
    """Pad/bucket strategy (module docstring): mixed fleets bucket into
    minimal structure groups; padding to the mesh does not change results."""

    def test_bucket_agents_partitions_by_structure(self, tracker_ocp):
        from agentlib_mpc_tpu.parallel.fused_admm import bucket_agents

        other_ocp = transcribe(Tracker(), ["u"], N=N, dt=DT,
                               method="multiple_shooting")
        specs = [
            {"name": "a", "ocp": tracker_ocp, "couplings": {"c": "u"},
             "theta": tracker_ocp.default_params(p=jnp.array([1.0])),
             "solver_options": SOLVER},
            {"name": "b", "ocp": tracker_ocp, "couplings": {"c": "u"},
             "theta": tracker_ocp.default_params(p=jnp.array([2.0])),
             "solver_options": SOLVER},
            {"name": "c", "ocp": other_ocp, "couplings": {"c": "u"},
             "theta": other_ocp.default_params(p=jnp.array([3.0])),
             "solver_options": SOLVER},
        ]
        groups, thetas, index_map = bucket_agents(specs)
        assert [g.n_agents for g in groups] == [2, 1]
        assert index_map == [[0, 1], [2]]
        np.testing.assert_allclose(np.asarray(thetas[0].p)[:, 0],
                                   [1.0, 2.0])

    def test_padded_fleet_matches_unpadded(self, tracker_ocp):
        """Two unequal groups (3 + 1 agents) padded to a 4-lane batch:
        consensus results equal the unpadded fleet."""
        from agentlib_mpc_tpu.parallel.fused_admm import (
            pad_group_to_devices,
        )

        opts = FusedADMMOptions(max_iterations=30, rho=2.0, abs_tol=1e-6,
                                rel_tol=1e-5)
        targets_a, targets_b = (0.0, 1.0, 2.0), (5.0,)
        group_a = AgentGroup(name="a", ocp=tracker_ocp, n_agents=3,
                             couplings={"c": "u"}, solver_options=SOLVER)
        group_b = AgentGroup(name="b", ocp=tracker_ocp, n_agents=1,
                             couplings={"c": "u"}, solver_options=SOLVER)
        theta_a = stack_params([tracker_ocp.default_params(
            p=jnp.array([t])) for t in targets_a])
        theta_b = stack_params([tracker_ocp.default_params(
            p=jnp.array([t])) for t in targets_b])

        engine = FusedADMM([group_a, group_b], opts)
        state = engine.init_state([theta_a, theta_b])
        state, _trajs, stats = engine.step(state, [theta_a, theta_b])
        assert bool(stats.converged)
        zbar_ref = np.asarray(state.zbar["c"])

        pad_a, theta_a_p, mask_a = pad_group_to_devices(group_a, theta_a, 4)
        pad_b, theta_b_p, mask_b = pad_group_to_devices(group_b, theta_b, 4)
        assert pad_a.n_agents == 4 and pad_b.n_agents == 4
        assert mask_a.tolist() == [True, True, True, False]
        assert mask_b.tolist() == [True, False, False, False]
        engine_p = FusedADMM([pad_a, pad_b], opts,
                             active=[mask_a, mask_b])
        state_p = engine_p.init_state([theta_a_p, theta_b_p])
        state_p, trajs_p, stats_p = engine_p.step(
            state_p, [theta_a_p, theta_b_p])
        assert bool(stats_p.converged)
        np.testing.assert_allclose(np.asarray(state_p.zbar["c"]), zbar_ref,
                                   atol=1e-4)
        # real lanes' trajectories finite; mean = mean of the 4 real agents
        np.testing.assert_allclose(
            float(np.mean(np.asarray(state_p.zbar["c"]))),
            np.mean(np.concatenate([targets_a, targets_b])), atol=1e-2)

    @pytest.mark.slow
    def test_padded_unequal_groups_shard_on_mesh(self, eight_devices,
                                                 tracker_ocp):
        """Two unequal groups (5 + 3 agents) padded to a device mesh: the
        agent axis shards (no replication fallback) and the result matches
        the unpadded single-device run.

        Uses a 4-device mesh: two differently-sharded groups concatenate
        into the consensus mean, which lowers to cross-module all-gathers
        needing every device thread at one rendezvous — on this 1-core VM
        an 8-way rendezvous intermittently starves and XLA aborts the
        process (rendezvous.cc termination timeout). 4 devices exercise
        the same sharding semantics without the starvation flake; the
        8-device single-group path is covered by TestMeshSharding and the
        driver dryrun."""
        from jax.sharding import Mesh
        from agentlib_mpc_tpu.parallel.fused_admm import (
            pad_group_to_devices,
        )

        opts = FusedADMMOptions(max_iterations=25, rho=2.0, abs_tol=1e-6,
                                rel_tol=1e-5)
        targets_a = (0.0, 1.0, 2.0, 3.0, 4.0)
        targets_b = (5.0, 6.0, 7.0)
        group_a = AgentGroup(name="a", ocp=tracker_ocp, n_agents=5,
                             couplings={"c": "u"}, solver_options=SOLVER)
        group_b = AgentGroup(name="b", ocp=tracker_ocp, n_agents=3,
                             couplings={"c": "u"}, solver_options=SOLVER)
        theta_a = stack_params([tracker_ocp.default_params(
            p=jnp.array([t])) for t in targets_a])
        theta_b = stack_params([tracker_ocp.default_params(
            p=jnp.array([t])) for t in targets_b])

        engine = FusedADMM([group_a, group_b], opts)
        state = engine.init_state([theta_a, theta_b])
        state, _t, stats = engine.step(state, [theta_a, theta_b])
        zbar_ref = np.asarray(state.zbar["c"])

        pad_a, theta_a_p, mask_a = pad_group_to_devices(group_a, theta_a, 4)
        pad_b, theta_b_p, mask_b = pad_group_to_devices(group_b, theta_b, 4)
        engine_p = FusedADMM([pad_a, pad_b], opts,
                             active=[mask_a, mask_b])
        mesh = Mesh(np.array(eight_devices[:4]), axis_names=("agents",))
        state_p = engine_p.init_state([theta_a_p, theta_b_p])
        state_p, thetas_p = engine_p.shard_args(
            mesh, state_p, [theta_a_p, theta_b_p])
        # padded groups divide the mesh -> warm starts actually sharded
        sharding = state_p.w[0].sharding
        assert not sharding.is_fully_replicated
        state_p, _tp, stats_p = engine_p.step(state_p, thetas_p)
        assert bool(stats_p.converged)
        np.testing.assert_allclose(np.asarray(state_p.zbar["c"]), zbar_ref,
                                   atol=1e-4)


class TwoChannelTracker(Model):
    """Two independent controls: consensus on one, exchange on the other."""

    inputs = [control_input("u1", 0.0, lb=-5.0, ub=5.0),
              control_input("u2", 0.0, lb=-5.0, ub=5.0)]
    parameters = [parameter("a", 1.0), parameter("b", 0.0)]

    def setup(self, v):
        eq = ModelEquations()
        eq.objective = (SubObjective((v.u1 - v.a) ** 2, name="track1")
                        + SubObjective((v.u2 - v.b) ** 2, name="track2"))
        return eq


class TestMixedCouplings:
    """Consensus and exchange couplings active simultaneously in one
    engine (the reference supports both per agent,
    ``admm_datatypes.py:26-77``)."""

    def test_consensus_and_exchange_together(self):

        ocp = transcribe(TwoChannelTracker(), ["u1", "u2"], N=N, dt=DT,
                         method="multiple_shooting")
        group = AgentGroup(
            name="duo", ocp=ocp, n_agents=2,
            couplings={"shared": "u1"}, exchanges={"balance": "u2"},
            solver_options=SOLVER)
        engine = FusedADMM(
            [group],
            FusedADMMOptions(max_iterations=60, rho=1.5, abs_tol=1e-6,
                             rel_tol=1e-5))
        thetas = stack_params([
            ocp.default_params(p=jnp.array([1.0, 2.0])),
            ocp.default_params(p=jnp.array([3.0, -1.0])),
        ])
        state = engine.init_state([thetas])
        state, trajs, stats = engine.step(state, [thetas])
        assert bool(stats.converged)
        # consensus channel agrees on the mean of the a-targets
        np.testing.assert_allclose(np.asarray(state.zbar["shared"]), 2.0,
                                   atol=5e-3)
        u = np.asarray(trajs[0]["u"])          # (2, N, 2)
        np.testing.assert_allclose(u[0, :, 0], u[1, :, 0], atol=1e-2)
        # exchange channel balances: sum u2 = 0, split b_i - mean(b)
        np.testing.assert_allclose(u[:, :, 1].sum(axis=0), 0.0, atol=1e-2)
        np.testing.assert_allclose(u[0, :, 1], 1.5, atol=1e-2)
