"""Agent: a named bundle of modules sharing a data broker.

Replaces agentlib's Agent (``modules/mpc/mpc.py:9``): holds the per-agent
DataBroker, instantiates modules from config dicts, and wires their
processes into the environment.
"""

from __future__ import annotations

import logging
from typing import Iterable

from agentlib_mpc_tpu.runtime.broker import DataBroker
from agentlib_mpc_tpu.runtime.environment import Environment
from agentlib_mpc_tpu.runtime.module import BaseModule, create_module

logger = logging.getLogger(__name__)


class Agent:
    def __init__(self, config: dict, env: Environment):
        self.id = config["id"]
        self.env = env
        self.config = config
        self.data_broker = DataBroker(self.id)
        self.modules: dict[str, BaseModule] = {}
        for mod_cfg in config.get("modules", []):
            # communicator entries of the reference configs ("local",
            # "local_broadcast", ...) are subsumed by the LocalMAS bus; accept
            # and skip them for config compatibility
            if mod_cfg.get("type") in ("local", "local_broadcast",
                                       "multiprocessing_broadcast", "mqtt"):
                continue
            module = create_module(mod_cfg, self)
            if module.id in self.modules:
                raise ValueError(
                    f"duplicate module_id {module.id!r} in agent {self.id}")
            self.modules[module.id] = module

    def start(self) -> None:
        for module in self.modules.values():
            module.register_callbacks()
        for module in self.modules.values():
            gen = module.process()
            if gen is not None:
                self.env.process(gen)

    def get_module(self, module_id: str) -> BaseModule:
        return self.modules[module_id]

    def terminate(self) -> None:
        """Shut down every module's background resources (reverse order).
        A failing terminate() is logged, not raised — but never silent: a
        skipped module's worker thread resurfaces as an interpreter-exit
        crash, and the log line is the only clue connecting the two."""
        import logging

        for module in reversed(list(self.modules.values())):
            try:
                module.terminate()
            except Exception:  # noqa: BLE001 - shutdown must not raise
                logging.getLogger(__name__).exception(
                    "terminate() of module %r failed",
                    getattr(module, "module_id", module))
