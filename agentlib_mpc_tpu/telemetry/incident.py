"""Incident reconstruction: causal timelines from the flight recorder.

``python -m agentlib_mpc_tpu.telemetry --incident <journal>`` turns a
journal (:mod:`agentlib_mpc_tpu.telemetry.journal`) into the artifact an
on-call engineer actually wants: a windowed event timeline (markdown +
JSON bundle), the correlation keys implicated in it (tenants, buckets,
devices, engine/schedule digests), and — when the journal carries chaos
injections — the **injection → symptom → recovery chains** that join
each injected fault to the failure it caused and the transition that
healed it. Chaos runs thereby become a test of *observability*: the
three ``bench.py --chaos-*`` benches assert their full injected
schedule is reconstructible from the journal alone.

Chain matching is typed, not fuzzy: each chaos rule kind names the
event types that count as its symptom and its recovery
(:data:`CHAIN_RULES`), and candidates must agree on the correlation
keys both sides carry (same tenant, same device, same bucket). A chain
with no observed symptom is reported ``contained`` when the rule is one
the engine quarantine absorbs silently, ``incomplete`` otherwise —
missing observability is a finding, not a formatting problem.
"""

from __future__ import annotations

import json
import re

from agentlib_mpc_tpu.telemetry.journal import read_events

#: event types that anchor an incident window when --around is omitted
FAULT_EVENTS = (
    "chaos.injected", "watchdog.condemned", "serve.stall",
    "mesh.degrade", "serve.eviction", "checkpoint.rejected",
    "certifier.refused", "perf.regression",
)

#: chaos rule kind -> (symptom event types, recovery event types,
#: containment). Symptom/recovery candidates must be correlation-
#: compatible with the injection (shared tenant/device/bucket keys
#: agree). ``contained=True`` marks rules the engine-level quarantine
#: is EXPECTED to absorb without a fleet-visible symptom.
CHAIN_RULES = {
    "serve_nan_theta": (("admission.shed", "serve.eviction",
                         "health.transition"),
                        ("serve.readmission",), False),
    "serve_nan_result": (("health.transition", "serve.eviction",
                          "guard.transition"),
                         ("serve.readmission", "guard.transition"),
                         False),
    "serve_stall": (("serve.stall",), ("serve.round",), False),
    "serve_build_fail": (("cache.engine",), ("cache.engine",), False),
    "mesh_stall": (("watchdog.condemned",), ("fleet.round",), False),
    "mesh_device_hang": (("watchdog.condemned", "mesh.degrade"),
                         ("mesh.readmit",), False),
    "mesh_probe_dead": (("mesh.degrade",), ("mesh.readmit",), False),
    "mesh_nan_theta": (("fleet.round",), ("fleet.round",), True),
    "solver_fail": (("guard.transition",), ("guard.transition",), False),
    "solver_nan": (("guard.transition",), ("guard.transition",), False),
    "solver_huge": (("guard.transition",), ("guard.transition",), False),
    # the autopilot chain (ISSUE 17): an injected overload storm burns
    # the fast window -> the controller moves DOWN the quality ladder
    # (symptom: a policy action, deliberately beside the fault
    # reactions above) -> burn recedes -> the controller spends the
    # budget back (recovery: the matching up-move)
    "serve_overload": (("autopilot.move",), ("autopilot.move",), False),
}

#: correlation keys a symptom/recovery candidate must agree on with the
#: injection WHEN both sides carry them
_CORRELATION_KEYS = ("tenant", "bucket", "device", "axis")


def _injection_keys(inj: dict) -> dict:
    """Correlation keys of a ``chaos.injected`` event. The injector's
    ``target`` string encodes them positionally (``tenant:roundN``,
    ``deviceK:roundN``, ``devices[6, 7]``, ``roundN:[6]``) — parse, do
    not guess. ``devices`` (device IDS, the space degrade/probe events
    report their dead lists in) and ``device`` (a mesh POSITION from
    NaN-storm targets — a different space, kept for scalar-key matches
    only) are deliberately separate keys."""
    out = {k: inj[k] for k in _CORRELATION_KEYS if k in inj}
    target = str(inj.get("target") or "")
    head = target.split(":", 1)[0]
    rule = str(inj.get("rule") or "")
    if rule.startswith("serve_nan") and head and "tenant" not in out:
        out["tenant"] = head
    m = re.fullmatch(r"(agents|scenarios|device)(\d+)", head)
    if m:
        out.setdefault("axis", m.group(1))
        out.setdefault("device", int(m.group(2)))
    # device-ID lists: "devices[6, 7]" (probe-dead notes) and
    # "round4:[6]" (device-hang notes) carry the ACTUAL dead ids
    m = re.search(r"\[([0-9,\s]+)\]", target)
    if m:
        out["devices"] = [int(x) for x in m.group(1).split(",")
                          if x.strip()]
    return out


def _compatible(keys: dict, ev: dict) -> bool:
    for k, v in keys.items():
        if k == "devices":
            # the injection names dead device IDS; a symptom/recovery
            # that carries its own dead list must OVERLAP it — without
            # this, two different devices' loss chains would claim each
            # other's symptoms. Events with no device attribution (a
            # condemned round is fleet-wide) stay compatible.
            dead = ev.get("dead") or ev.get("dead_devices")
            if isinstance(dead, (list, tuple)) and dead:
                if not {str(d) for d in dead} & {str(d) for d in v}:
                    return False
            continue
        if k in ev and str(ev[k]) != str(v):
            return False
    return True


def _symptom_matches(rule: str, keys: dict, ev: dict) -> bool:
    if not _compatible(keys, ev):
        return False
    if rule == "mesh_nan_theta":
        # the quarantine containing the storm IS the symptom: a round
        # that reports quarantined iterations
        return bool(ev.get("quarantined"))
    if ev.get("etype") == "health.transition":
        return ev.get("state") in ("quarantined", "evicted")
    if ev.get("etype") == "autopilot.move":
        # only a DEGRADE is a symptom of the injected overload
        return ev.get("direction") == "down"
    if ev.get("etype") == "cache.engine" and rule == "serve_build_fail":
        return ev.get("outcome") == "build_failed"
    return True


def _recovery_matches(rule: str, keys: dict, ev: dict,
                      symptom: "dict | None") -> bool:
    if not _compatible(keys, ev):
        return False
    et = ev.get("etype")
    if et == "health.transition":
        return ev.get("state") in ("probation", "healthy")
    if et == "autopilot.move":
        # recovery = the controller spending budget BACK (an up-move
        # after the burn receded)
        return ev.get("direction") == "up"
    if et == "guard.transition":
        return ev.get("level") == "mpc"
    if et == "cache.engine":
        return ev.get("outcome") in ("miss", "hit", "restored")
    if et == "fleet.round":
        # recovery = the first round COMPLETED after the symptom (for a
        # contained storm: the first clean round after the poisoned one)
        if rule == "mesh_nan_theta":
            return not ev.get("quarantined")
        return True
    return True


def build_chains(events: list) -> list:
    """One chain record per ``chaos.injected`` event: the injection,
    the first correlated symptom after it, the first correlated
    recovery after the symptom, and a status (``complete`` /
    ``contained`` / ``incomplete``)."""
    chains = []
    for inj in events:
        if inj.get("etype") != "chaos.injected":
            continue
        rule = str(inj.get("rule") or "")
        symptom_types, recovery_types, contained_ok = CHAIN_RULES.get(
            rule, ((), (), False))
        keys = _injection_keys(inj)
        seq0 = int(inj.get("seq", 0))
        symptom = next(
            (e for e in events
             if int(e.get("seq", 0)) > seq0
             and e.get("etype") in symptom_types
             and _symptom_matches(rule, keys, e)), None)
        recovery = None
        if symptom is not None:
            seq1 = int(symptom.get("seq", 0))
            recovery = next(
                (e for e in events
                 if int(e.get("seq", 0)) > seq1
                 and e.get("etype") in recovery_types
                 and _recovery_matches(rule, keys, e, symptom)), None)
        status = ("complete" if symptom is not None
                  and recovery is not None
                  else "contained" if symptom is None and contained_ok
                  else "incomplete")
        chains.append({
            "injection": inj,
            "keys": keys,
            "symptom": symptom,
            "recovery": recovery,
            "status": status,
        })
    return chains


def _anchor_events(events: list, around: "str | int | None",
                   window: int) -> list:
    if not events:
        return []
    if around is None:
        anchor = next((e for e in events
                       if e.get("etype") in FAULT_EVENTS), events[0])
        pivot = int(anchor.get("seq", 0))
        by = "seq"
    else:
        text = str(around)
        if text.startswith("round:"):
            pivot, by = int(text.split(":", 1)[1]), "round"
        else:
            pivot, by = int(text), "seq"
    if by == "round":
        return [e for e in events
                if e.get("round") is not None
                and abs(int(e["round"]) - pivot) <= window]
    return [e for e in events
            if abs(int(e.get("seq", 0)) - pivot) <= window]


def _implicated(events: list) -> dict:
    """The correlation keys and certificate digests the window touches
    — what an operator pivots on next."""
    out: dict = {"tenants": set(), "buckets": set(), "devices": set(),
                 "digests": set(), "chaos_seeds": set()}
    for ev in events:
        if "tenant" in ev:
            out["tenants"].add(str(ev["tenant"]))
        if "bucket" in ev:
            out["buckets"].add(str(ev["bucket"]))
        for key in ("dead", "dead_devices"):
            val = ev.get(key)
            if isinstance(val, (list, tuple)):
                out["devices"].update(str(d) for d in val)
        for key in ("collective_digest", "memory_digest", "digest"):
            if ev.get(key):
                out["digests"].add(str(ev[key]))
        if ev.get("etype") == "chaos.injected" and "seed" in ev:
            out["chaos_seeds"].add(int(ev["seed"]))
    return {k: sorted(v) for k, v in out.items()}


def build_incident(journal_path_or_events,
                   around: "str | int | None" = None,
                   window: int = 500,
                   metrics: "dict | None" = None) -> dict:
    """The incident bundle: windowed timeline, chains, implicated keys,
    journal-wide event counts, and (when supplied) a metrics snapshot.
    ``journal_path_or_events`` is a journal path or a pre-read event
    list; ``around`` anchors the window at a sequence number or
    ``"round:N"`` (default: the first fault-class event)."""
    if isinstance(journal_path_or_events, str):
        events = read_events(journal_path_or_events)
        source = journal_path_or_events
    else:
        events = list(journal_path_or_events)
        source = None
    windowed = _anchor_events(events, around, window)
    counts: dict = {}
    for ev in events:
        et = str(ev.get("etype"))
        counts[et] = counts.get(et, 0) + 1
    chains = build_chains(events)
    return {
        "journal": source,
        "events_total": len(events),
        "events_by_type": dict(sorted(counts.items())),
        "window": {"around": around, "size": window,
                   "events": windowed},
        "chains": chains,
        "complete_chains": sum(1 for c in chains
                               if c["status"] == "complete"),
        "implicated": _implicated(windowed),
        "metrics": metrics,
    }


def _fmt_event(ev: dict) -> str:
    skip = {"seq", "t", "round", "etype"}
    if ev.get("etype") == "perf.regression":
        # perf-gate violation: show the drift arithmetic, not raw kv
        detail = (f"phase={ev.get('phase')} "
                  f"{ev.get('measured_ms')} ms vs baseline "
                  f"{ev.get('baseline_ms')}±{ev.get('band_ms')} ms "
                  f"(+{ev.get('excess_ms')} ms over band, "
                  f"key={ev.get('metric_key')})")
    elif ev.get("etype") == "autopilot.move":
        # a policy move: render the ladder transition, not raw kv
        trig = ("forced" if ev.get("trigger") == "forced"
                else f"burn={ev.get('burn')} over "
                     f"{ev.get('window')}-round window")
        detail = (f"tenant={ev.get('tenant')} "
                  f"L{ev.get('level_from')}→L{ev.get('level_to')} "
                  f"({ev.get('direction')}, lever={ev.get('lever')}, "
                  f"{trig})")
    else:
        detail = ", ".join(f"{k}={ev[k]}" for k in sorted(ev)
                           if k not in skip)
    rnd = ev.get("round")
    return (f"| {ev.get('seq', '?')} | "
            f"{'-' if rnd is None else rnd} | "
            f"`{ev.get('etype')}` | {detail or '—'} |")


def render_markdown(report: dict) -> str:
    """The human half of the bundle: a timeline table + one section per
    causal chain — what the robustness runbooks now open with."""
    lines = ["# Incident report", ""]
    if report.get("journal"):
        lines.append(f"Journal: `{report['journal']}` "
                     f"({report['events_total']} events)")
    lines += ["", "## Causal chains", ""]
    chains = report.get("chains") or []
    if not chains:
        lines.append("No chaos injections recorded in this journal.")
    for i, chain in enumerate(chains):
        inj = chain["injection"]
        lines.append(
            f"### Chain {i + 1}: `{inj.get('rule')}` @ "
            f"{inj.get('target')} (round {inj.get('round')}) — "
            f"**{chain['status']}**")
        lines.append(f"- injected: seq {inj.get('seq')} "
                     f"(keys: {chain['keys'] or '—'})")
        for role in ("symptom", "recovery"):
            ev = chain.get(role)
            if ev is None:
                lines.append(f"- {role}: none observed")
            else:
                extra = ""
                if ev.get("etype") == "autopilot.move":
                    # the ladder level IS the story of a policy chain
                    extra = (f" (L{ev.get('level_from')}→"
                             f"L{ev.get('level_to')}, "
                             f"lever={ev.get('lever')})")
                lines.append(
                    f"- {role}: `{ev.get('etype')}` seq "
                    f"{ev.get('seq')} round {ev.get('round')}{extra}")
        lines.append("")
    imp = report.get("implicated") or {}
    lines += ["## Implicated", ""]
    for key in ("tenants", "buckets", "devices", "digests",
                "chaos_seeds"):
        vals = imp.get(key) or []
        if vals:
            lines.append(f"- {key}: "
                         + ", ".join(str(v) for v in vals))
    lines += ["", "## Timeline", "",
              "| seq | round | event | detail |",
              "|---|---|---|---|"]
    for ev in (report.get("window") or {}).get("events", []):
        lines.append(_fmt_event(ev))
    lines += ["", "## Event counts", ""]
    for et, n in (report.get("events_by_type") or {}).items():
        lines.append(f"- `{et}`: {n}")
    return "\n".join(lines) + "\n"


def write_bundle(report: dict, json_path: str) -> None:
    with open(json_path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=1, default=str)
