"""Orchestration: walk the package, run every static pass, apply
suppressions, number duplicate fingerprints, split against the baseline."""

from __future__ import annotations

import os
from collections import Counter

from agentlib_mpc_tpu.lint import jit_hygiene, thread_discipline
from agentlib_mpc_tpu.lint.callgraph import PackageIndex
from agentlib_mpc_tpu.lint.findings import (
    Finding,
    SourceAnnotations,
    number_occurrences,
)

#: directories (package-relative) the jit-hygiene passes cover — the
#: jit-bearing subsystems (ISSUE scope: ops/backends/parallel/resilience,
#: widened to every dir whose functions are traced into an OCP); the
#: thread-discipline pass self-scopes via annotations and runs everywhere
JIT_SCOPE = ("ops", "backends", "parallel", "resilience", "ml", "models",
             "modules")


def package_root() -> str:
    import agentlib_mpc_tpu

    return os.path.dirname(os.path.abspath(agentlib_mpc_tpu.__file__))


def repo_root() -> "str | None":
    """Checkout root (parent of the package holding pyproject.toml), or
    None for an installed site-packages tree."""
    root = os.path.dirname(package_root())
    if os.path.isfile(os.path.join(root, "pyproject.toml")):
        return root
    return None


def _walk_sources(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                yield rel, full


def build_index(root: "str | None" = None,
                extra_files: "dict[str, str] | None" = None
                ) -> PackageIndex:
    """Parse every package module (plus ``extra_files``: relpath ->
    source, used by the golden-file tests) into one index."""
    index = PackageIndex()
    if root is None:
        root = package_root()
    for rel, full in _walk_sources(root):
        try:
            with open(full, encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        index.add_module(rel, source)
    for rel, source in (extra_files or {}).items():
        index.add_module(rel, source)
    return index


def collect_findings(root: "str | None" = None,
                     extra_files: "dict[str, str] | None" = None,
                     jit_scope: "tuple[str, ...] | None" = JIT_SCOPE,
                     ) -> "list[Finding]":
    """``jit_scope=None`` scans every module (the golden-file fixture
    tests point ``root`` at a directory of bad snippets)."""
    index = build_index(root, extra_files)
    scope = None if jit_scope is None else tuple(jit_scope)
    if extra_files and scope is not None:
        # golden-file fixtures live outside the package layout: put their
        # top-level dirs in scope too
        scope = scope + tuple({rel.split("/")[0] for rel in extra_files})
    findings = list(jit_hygiene.run(index, scope_dirs=scope))
    for info in index.modules.values():
        findings.extend(thread_discipline.run_module(
            info.path, info.tree, info.source))
    # suppression comments apply to every rule (annotations tokenized
    # once per file, not once per finding)
    ann_cache: dict[str, SourceAnnotations] = {}
    out = []
    for f in findings:
        if f.path in index.modules:
            ann = ann_cache.get(f.path)
            if ann is None:
                ann = SourceAnnotations(index.modules[f.path].source)
                ann_cache[f.path] = ann
            if ann.suppressed(f.rule, f.line):
                continue
        out.append(f)
    return number_occurrences(out)


def collect_stats(root: "str | None" = None) -> dict:
    """Findings per rule per module — the lint-debt trend line that rides
    along in ``bench.py --emit-metrics`` artifacts."""
    findings = collect_findings(root)
    per_rule: Counter = Counter(f.rule for f in findings)
    per_module: dict = {}
    for f in findings:
        per_module.setdefault(f.path, Counter())[f.rule] += 1
    return {
        "total": len(findings),
        "per_rule": dict(sorted(per_rule.items())),
        "per_module": {m: dict(sorted(c.items()))
                       for m, c in sorted(per_module.items())},
    }
