"""Dispatch certifier (ISSUE 18): the adversarial corpus.

The dispatch pass must prove that a warm fused round is ONE device
program (the jit entry the only host↔device boundary), schedule and
charge *planned* host syncs without ever executing them, refute any
unplanned ``pure_callback``-class sync naming the offending eqn by
source, multiply loop-carried syncs by scan lengths and while-trip
budgets, divide program-boundary bytes by the shard spec, report
donated carry buffers as reuse rather than transfer — and the engine
seam must stamp the mesh-size-independent ``dispatch_digest`` at build
and refuse the mutation direction: a host peek smuggled into the
consensus update (the static analogue of PR 3's source-surgery tests)
fails the build under ``dispatch_certify="require"`` and the checked-in
``[jaxpr.dispatch]`` pin either way.

Small programs trace in milliseconds; the engine-backed classes share
module fixtures the way every mesh test module does.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from agentlib_mpc_tpu.lint.jaxpr.dispatch import (
    DispatchCertificate,
    certify_dispatch,
    check_dispatch_budget,
)
from agentlib_mpc_tpu.ops import admm as admm_ops
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.parallel import fleet_mesh
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
)

from conftest import make_tracker_model  # noqa: E402


def _mesh(n=4, axis="a"):
    return Mesh(np.array(jax.devices("cpu")[:n]), (axis,))


def _never_run(*_a):
    raise AssertionError("host callback executed during certification")


def _scalar_cb(dtype):
    """A pure_callback issuing a scalar host round-trip that must NEVER
    actually run (certification is static)."""
    return lambda v: jax.pure_callback(
        _never_run, jax.ShapeDtypeStruct((), dtype), v)


class TestCertifierCorpus:
    """Hand-written programs: the schedule walk, byte model, loop
    charging and refusal direction."""

    def test_pure_device_program_is_one_dispatch(self):
        def fn(x):
            return jnp.sum(x * 2.0)

        cert = certify_dispatch(fn, jnp.ones((8, 3), jnp.float32))
        assert cert.proved
        assert cert.dispatch_count() == 1
        assert cert.host_syncs == ()
        assert cert.dispatch_digest is not None
        entry = cert.boundaries[0]
        assert entry.kind == "program" and entry.primitive == "jit"
        assert entry.in_bytes == 8 * 3 * 4      # f32 operand lands once
        assert entry.out_bytes == 4             # scalar result back
        assert cert.transfer_bytes() == 8 * 3 * 4 + 4

    def test_unplanned_callback_refuted_naming_source(self):
        def fn(x):
            s = jnp.sum(x)
            peek = _scalar_cb(x.dtype)(s)       # the smuggled host sync
            return s + 0.0 * peek

        cert = certify_dispatch(fn, jnp.ones((4,), jnp.float32))
        assert cert.status == "refuted"
        assert cert.dispatch_digest is None
        msg = " ".join(cert.refutations)
        assert "pure_callback" in msg
        # the offending eqn is named by source position — and the
        # callback body was never executed (it raises if run)
        assert "test_jaxpr_dispatch" in msg

    def test_planned_sync_scheduled_and_charged(self):
        def fn(x):
            s = jnp.sum(x)
            peek = _scalar_cb(x.dtype)(s)
            return s + 0.0 * peek

        cert = certify_dispatch(fn, jnp.ones((4,), jnp.float32),
                                allowed_sync_prims=("pure_callback",))
        assert cert.proved
        syncs = cert.host_syncs
        assert len(syncs) == 1
        # every sync splits the program: entry + one resume
        assert cert.dispatch_count() == 2
        assert "pure_callback" in cert.opaque
        # honesty: the host-side cost is noted unknown, never measured
        assert any("unknown" in n for n in cert.notes)
        # the round-trip ships the scalar both ways (f32: 4 B each)
        assert syncs[0].out_bytes == 4 and syncs[0].in_bytes == 4

    def test_scan_multiplies_sync_issues(self):
        def fn(x):
            def body(c, _):
                c = c + _scalar_cb(x.dtype)(c)
                return c, None

            out, _ = lax.scan(body, jnp.float32(0.0), None, length=5)
            return out + jnp.sum(x)

        cert = certify_dispatch(fn, jnp.ones((4,), jnp.float32),
                                allowed_sync_prims=("pure_callback",))
        assert cert.proved
        (sync,) = cert.host_syncs
        assert sync.loop_path == ("scan[5]",)
        assert sync.multiplicity == 5 and sync.bounded
        assert cert.dispatch_count() == 1 + 5

    def test_while_sync_charged_per_trip_budget(self):
        def fn(x):
            def cond(c):
                return c < 10.0

            def body(c):
                return c + 1.0 + _scalar_cb(x.dtype)(c)

            return lax.while_loop(cond, body, jnp.sum(x))

        cert = certify_dispatch(fn, jnp.ones((4,), jnp.float32),
                                allowed_sync_prims=("pure_callback",))
        assert cert.proved
        (sync,) = cert.host_syncs
        assert sync.loop_path == ("while",) and not sync.bounded
        # data-dependent trip count: charged × the caller's budget
        assert sync.issues(while_trips=8) == 8
        assert cert.dispatch_count(while_trips=8) == 1 + 8
        assert cert.dispatch_count() == 2       # 1-trip floor

    def test_donated_carry_is_reuse_not_transfer(self):
        def step(state, inc):
            return state + inc, jnp.sum(inc)

        closed = jax.make_jaxpr(step)(jnp.ones((16,), jnp.float32),
                                      jnp.ones((16,), jnp.float32))
        plain = certify_dispatch(closed)
        donated = certify_dispatch(closed, donated_invars=(True, False))
        ep, ed = plain.boundaries[0], donated.boundaries[0]
        assert ep.donated_bytes == 0
        assert ed.donated_bytes == 64           # the carry, reused
        assert ed.in_bytes == ep.in_bytes - 64
        assert donated.transfer_bytes() == plain.transfer_bytes() - 64
        # donation changes payload accounting, never the schedule
        assert donated.dispatch_digest == plain.dispatch_digest

    def test_shard_spec_divides_bytes_digest_mesh_size_free(self):
        def body(x):
            return lax.psum(jnp.sum(x), "a")

        certs = {}
        for n in (2, 4):
            sm = shard_map(body, mesh=_mesh(n), in_specs=P("a"),
                           out_specs=P(), check_rep=False)
            certs[n] = certify_dispatch(sm, jnp.ones((8, 4), jnp.float32))
        for n, cert in certs.items():
            assert cert.proved
            # the sharded operand lands global_bytes / axis_size per dev
            assert cert.boundaries[0].in_bytes == 8 * 4 * 4 // n
        assert certs[4].axis_sizes == {"a": 4}
        # payload scales with the mesh; the schedule identity must not
        assert certs[2].dispatch_digest == certs[4].dispatch_digest

    def test_budget_pins(self):
        def fn(x):
            s = jnp.sum(x)
            return s + 0.0 * _scalar_cb(x.dtype)(s)

        planned = certify_dispatch(fn, jnp.ones((4,), jnp.float32),
                                   allowed_sync_prims=("pure_callback",))
        v = check_dispatch_budget(
            planned, {"dispatches_per_round": 1, "max_host_syncs": 0})
        assert len(v) == 2
        assert "budget pins 1" in v[0]
        assert "host sync" in v[1]
        refuted = certify_dispatch(fn, jnp.ones((4,), jnp.float32))
        v = check_dispatch_budget(refuted, {"dispatches_per_round": 1})
        assert len(v) == 1 and "not proved" in v[0]


OPTS = FusedADMMOptions(max_iterations=8, rho=2.0)
SOLVER = SolverOptions(max_iter=25)

Tracker = make_tracker_model()


def _tracker_group(n_agents):
    ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                     method="multiple_shooting")
    return AgentGroup(name="fleet", ocp=ocp, n_agents=n_agents,
                      couplings={"shared_u": "u"},
                      solver_options=SOLVER,
                      # solver-routing certification is irrelevant to
                      # the dispatch schedule — keep builds cheap
                      qp_fast_path="off")


def _tracker_fleet(n_agents, mesh, **engine_kw):
    return FusedADMM([_tracker_group(n_agents)], OPTS, mesh=mesh,
                     **engine_kw)


class TestFusedRoundDispatch:
    """The engine seam: the warm round certifies as ONE dispatch at
    build, the checked-in pin holds, and the digest is an identity of
    the schedule, not of the mesh size."""

    @pytest.fixture(scope="class")
    def fleet(self, eight_devices):
        return _tracker_fleet(8, fleet_mesh(devices=eight_devices))

    def test_mesh_engine_certifies_at_build(self, fleet):
        cert = fleet.dispatch_certificate
        assert isinstance(cert, DispatchCertificate)
        assert cert.proved, cert.refutations
        # the ISSUE headline: eval+jac -> assemble -> factor -> line
        # search all live inside ONE device program per round
        assert cert.dispatch_count() == 1
        assert cert.host_syncs == ()
        assert fleet.dispatch_digest == cert.dispatch_digest
        assert fleet.dispatch_digest is not None

    def test_gate_matches_checked_in_budget(self, fleet):
        from agentlib_mpc_tpu.lint.retrace_budget import load_budgets

        cfg = load_budgets().get("jaxpr", {}).get("dispatch", {})
        assert cfg, "[jaxpr.dispatch] missing from lint_budgets.toml"
        assert check_dispatch_budget(fleet.dispatch_certificate,
                                     cfg) == []

    def test_digest_is_mesh_size_independent(self, fleet,
                                             eight_devices):
        """The same fleet structure on a half-size mesh: per-device
        boundary payload doubles (two agents per lane), the schedule
        digest must not move — it stamps the store meta across
        degrades and topology changes."""
        half = _tracker_fleet(8, fleet_mesh(devices=eight_devices[:4]))
        assert half.dispatch_digest == fleet.dispatch_digest
        b8 = fleet.dispatch_certificate.transfer_bytes()
        b4 = half.dispatch_certificate.transfer_bytes()
        assert b4 > b8


class TestMutationDirection:
    """PR 3's source-surgery pattern, static edition: sabotage the real
    consensus update / the donation contract and the gate must refuse,
    naming the injected eqn."""

    def _sabotaged_consensus(self):
        real = admm_ops.consensus_update

        def sabotaged(locals_, state, active=None, axis_name=None):
            new_state, res = real(locals_, state, active=active,
                                  axis_name=axis_name)
            # the regression: a host peek at the residual, folded back
            # in so it cannot be DCE'd — one round-trip per ADMM trip
            peek = jax.pure_callback(
                _never_run,
                jax.ShapeDtypeStruct((), res.primal.dtype), res.primal)
            return new_state, res._replace(
                primal=res.primal + 0.0 * peek)

        return sabotaged

    def test_injected_callback_refused_under_require(self, monkeypatch):
        monkeypatch.setattr(admm_ops, "consensus_update",
                            self._sabotaged_consensus())
        with pytest.raises(ValueError) as ei:
            FusedADMM([_tracker_group(2)], OPTS,
                      dispatch_certify="require")
        msg = str(ei.value)
        assert "REFUTED" in msg and "pure_callback" in msg
        # the refusal names the injected eqn's source — THIS file
        assert "test_jaxpr_dispatch" in msg
        assert "while" in msg        # and locates it in the ADMM loop

    def test_injected_callback_warns_on_single_host_mesh(
            self, eight_devices, monkeypatch, caplog):
        """Single-host ``"auto"`` policy: warn loudly, proceed (debug
        latitude) — but the certificate is refuted, the digest gone,
        and the checked-in pin fails the tree in CI."""
        from agentlib_mpc_tpu.lint.retrace_budget import load_budgets

        monkeypatch.setattr(admm_ops, "consensus_update",
                            self._sabotaged_consensus())
        with caplog.at_level(
                logging.WARNING,
                logger="agentlib_mpc_tpu.parallel.fused_admm"):
            engine = _tracker_fleet(8, fleet_mesh(devices=eight_devices))
        cert = engine.dispatch_certificate
        assert cert is not None and cert.status == "refuted"
        assert engine.dispatch_digest is None
        assert any("dispatch schedule REFUTED" in rec.message
                   for rec in caplog.records)
        cfg = load_budgets().get("jaxpr", {}).get("dispatch", {})
        violations = check_dispatch_budget(cert, cfg)
        assert violations and "not proved" in " ".join(violations)

    def test_undonated_round_trip_fails_transfer_pin(self):
        """The other mutation direction: dropping ``donate_state``
        re-charges the carry as fresh host↔device transfer every round
        — same schedule (digest equal), bigger bill, and a transfer pin
        calibrated on the donated engine refutes it."""
        donated = FusedADMM([_tracker_group(2)], OPTS,
                            donate_state=True,
                            dispatch_certify="require")
        undonated = FusedADMM([_tracker_group(2)], OPTS,
                              donate_state=False,
                              dispatch_certify="require")
        cd = donated.dispatch_certificate
        cu = undonated.dispatch_certificate
        assert cd.proved and cu.proved
        assert cd.boundaries[0].donated_bytes > 0
        assert cu.boundaries[0].donated_bytes == 0
        assert cu.transfer_bytes() > cd.transfer_bytes()
        assert cd.dispatch_digest == cu.dispatch_digest
        cap = {"max_transfer_bytes_per_round": cd.transfer_bytes()}
        assert check_dispatch_budget(cd, cap) == []
        violations = check_dispatch_budget(cu, cap)
        assert violations and "un-donated" in violations[0]
