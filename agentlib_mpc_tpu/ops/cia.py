"""Combinatorial integer approximation (CIA) of relaxed binary schedules.

Counterpart of the reference's pycombina bridge
(``optimization_backends/casadi_/minlp_cia.py:124-150``): after a relaxed
NLP solve produces fractional binary controls ``b_rel ∈ [0,1]^(N×nb)``,
find a true binary schedule ``B`` minimizing the accumulated-deviation
objective

    η = max_{t,i} | Σ_{τ≤t} (b_rel[τ,i] − B[τ,i]) · dt[τ] |

subject to per-control switch limits and optionally a SOS1 (one-hot per
step) constraint — the schedule the second, binary-fixed NLP solve then
tracks. The branch-and-bound runs in C++ (``native/cia.cpp``) with an
identical pure-Python fallback; both are host-side by design (tiny,
sequential, branchy — the opposite of MXU work), matching the reference's
host-side pycombina call between two device solves.
"""

from __future__ import annotations

import ctypes
import itertools
import math

import numpy as np

from agentlib_mpc_tpu import native

_MAX_NB = 16


def sum_up_rounding(b_rel: np.ndarray, dt: np.ndarray,
                    sos1: bool = False) -> np.ndarray:
    """Classic sum-up rounding (Sager 2009): greedy one-pass schedule.
    Used as a fast approximation and as the B&B's conceptual first leaf."""
    b_rel = np.asarray(b_rel, dtype=float)
    N, nb = b_rel.shape
    out = np.zeros((N, nb))
    dev = np.zeros(nb)
    for t in range(N):
        dev += b_rel[t] * dt[t]
        if sos1 and nb > 1:
            i = int(np.argmax(dev))
            out[t, i] = 1.0
            dev[i] -= dt[t]
        else:
            on = dev >= 0.5 * dt[t]
            out[t, on] = 1.0
            dev[on] -= dt[t]
    return out


def cia_objective(b_rel: np.ndarray, b_bin: np.ndarray,
                  dt: np.ndarray) -> float:
    acc = np.cumsum((np.asarray(b_rel) - np.asarray(b_bin))
                    * np.asarray(dt)[:, None], axis=0)
    return float(np.max(np.abs(acc))) if acc.size else 0.0


def _solve_python(b_rel, dt, max_switches, sos1, max_nodes):
    """Pure-Python mirror of native/cia.cpp (same DFS + greedy ordering)."""
    N, nb = b_rel.shape
    if sos1 and nb > 1:
        choices = [tuple(1 if j == i else 0 for j in range(nb))
                   for i in range(nb)]
    else:
        choices = list(itertools.product((0, 1), repeat=nb))
    best = {"obj": math.inf, "B": np.zeros((N, nb))}
    current = np.zeros((N, nb))
    nodes = [0]

    def dfs(t, dev, switches, last, partial):
        if partial >= best["obj"]:
            return
        if t == N:
            best["obj"] = partial
            best["B"] = current.copy()
            return
        nodes[0] += 1
        if nodes[0] > max_nodes:
            return
        scored = []
        for choice in choices:
            nd = dev + (b_rel[t] - choice) * dt[t]
            scored.append((float(np.max(np.abs(nd))), choice, nd))
        scored.sort(key=lambda s: s[0])
        for d, choice, nd in scored:
            child = max(partial, d)
            if child >= best["obj"]:
                break
            sw = [switches[i] + (last[i] is not None and choice[i] != last[i])
                  for i in range(nb)]
            if max_switches is not None and any(
                    sw[i] > max_switches[i] for i in range(nb)):
                continue
            current[t] = choice
            dfs(t + 1, nd, sw, list(choice), child)
            if nodes[0] > max_nodes:
                return

    dfs(0, np.zeros(nb), [0] * nb, [None] * nb, 0.0)
    return best["B"], best["obj"]


def solve_cia(
    b_rel: np.ndarray,
    dt: float | np.ndarray,
    max_switches: list[int] | None = None,
    sos1: bool = False,
    max_nodes: int = 2_000_000,
) -> tuple[np.ndarray, float]:
    """Solve the CIA problem. Returns (B, η).

    b_rel: (N, nb) relaxed binaries; dt: scalar or (N,) interval lengths;
    max_switches: per-control change budget (None = unbounded);
    sos1: require exactly one active control per step (nb ≥ 2).
    """
    b_rel = np.ascontiguousarray(np.clip(np.asarray(b_rel, dtype=float),
                                         0.0, 1.0))
    if b_rel.ndim != 2:
        raise ValueError("b_rel must be (N, nb)")
    N, nb = b_rel.shape
    if nb > _MAX_NB:
        raise ValueError(f"at most {_MAX_NB} binary controls supported")
    dt_arr = np.ascontiguousarray(
        np.broadcast_to(np.asarray(dt, dtype=float), (N,)))
    if max_switches is not None and len(max_switches) != nb:
        raise ValueError(
            f"max_switches has {len(max_switches)} entries for {nb} binary "
            f"controls")

    lib = native.load("cia")
    if lib is not None:
        fn = lib.cia_solve
        fn.restype = ctypes.c_int
        b_out = np.zeros((N, nb))
        obj = ctypes.c_double(0.0)
        ms = (np.ascontiguousarray(np.asarray(max_switches, dtype=np.int32))
              if max_switches is not None else None)
        status = fn(
            b_rel.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_int(N), ctypes.c_int(nb),
            dt_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ms.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
            if ms is not None else None,
            ctypes.c_int(1 if sos1 else 0),
            b_out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.byref(obj),
            ctypes.c_longlong(max_nodes),
        )
        if status >= 0 and np.isfinite(obj.value) and obj.value < 1e299:
            return b_out, float(obj.value)

    return _solve_python(b_rel, dt_arr, max_switches, sos1,
                         max_nodes=min(max_nodes, 200_000))
