"""f32 tier: the TPU-native precision, exercised explicitly on CPU.

The suite runs in f64 (conftest enables x64 for tight tolerances); the
TPU data plane runs f32. These tests re-trace the hot paths under
``jax.experimental.enable_x64(False)`` and pin the f32-specific behavior the solver
was engineered for (scaling, stall acceptance, barrier floor —
``ops/solver.py`` docstring): solves still succeed and land on the f64
answer to f32-appropriate tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.models.zoo import OneRoom
from agentlib_mpc_tpu.ops.solver import (
    NLPFunctions,
    SolverOptions,
    solve_nlp,
)
from agentlib_mpc_tpu.ops.transcription import transcribe


@pytest.fixture()
def f32():
    # jax >= 0.4.3x removed the jax.enable_x64 alias; the context manager
    # lives in jax.experimental (this fixture errored on every tier-1 run
    # since the image's jax moved — fixed in the jaxlint PR)
    from jax.experimental import enable_x64

    with enable_x64(False):
        yield


class TestSolverF32:
    def test_hs071_f32(self, f32):
        nlp = NLPFunctions(
            f=lambda w, t: w[0] * w[3] * (w[0] + w[1] + w[2]) + w[2],
            g=lambda w, t: jnp.array([jnp.sum(w**2) - 40.0]),
            h=lambda w, t: jnp.array([w[0] * w[1] * w[2] * w[3] - 25.0]),
        )
        res = solve_nlp(nlp, jnp.array([1.0, 5.0, 5.0, 1.0]), None,
                        jnp.ones(4), 5.0 * jnp.ones(4),
                        SolverOptions(tol=1e-4, max_iter=60))
        assert res.w.dtype == jnp.float32
        assert bool(res.stats.success)
        np.testing.assert_allclose(
            np.asarray(res.w), [1.0, 4.743, 3.8211, 1.3794], atol=2e-3)

    @pytest.mark.slow
    def test_one_room_ocp_f32_matches_f64_objective(self, f32):
        """The benchmark-shaped OCP: f32 solve succeeds and the optimal
        cost matches the f64 solve to well under a percent (the
        closed-loop-cost parity claim of BASELINE.md rests on this)."""
        model = OneRoom(overrides={"s_T": 0.001, "r_mDot": 0.01})
        ocp = transcribe(model, ["mDot"], N=8, dt=300.0,
                         method="collocation", collocation_degree=2)
        theta = ocp.default_params(x0=jnp.array([298.16]))
        lb, ub = ocp.bounds(theta)
        res32 = solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta, lb,
                          ub, SolverOptions(tol=1e-4, max_iter=60))
        assert res32.w.dtype == jnp.float32
        assert bool(res32.stats.success)
        obj32 = float(res32.stats.objective)

        from jax.experimental import enable_x64

        with enable_x64(True):
            ocp64 = transcribe(model, ["mDot"], N=8, dt=300.0,
                               method="collocation", collocation_degree=2)
            theta64 = ocp64.default_params(x0=jnp.array([298.16]))
            lb64, ub64 = ocp64.bounds(theta64)
            res64 = solve_nlp(ocp64.nlp, ocp64.initial_guess(theta64),
                              theta64, lb64, ub64,
                              SolverOptions(tol=1e-7, max_iter=80))
        assert bool(res64.stats.success)
        obj64 = float(res64.stats.objective)
        assert obj32 == pytest.approx(obj64, rel=5e-3)


class TestFusedEngineF32:
    def test_consensus_fixed_point_f32(self, f32):
        from conftest import make_tracker_model

        from agentlib_mpc_tpu.parallel.fused_admm import (
            AgentGroup,
            FusedADMM,
            FusedADMMOptions,
            stack_params,
        )

        Tracker = make_tracker_model()
        ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                         method="multiple_shooting")
        group = AgentGroup(
            name="trackers", ocp=ocp, n_agents=3,
            couplings={"shared": "u"},
            solver_options=SolverOptions(tol=1e-5, max_iter=30))
        engine = FusedADMM(
            [group], FusedADMMOptions(max_iterations=40, rho=2.0,
                                      abs_tol=1e-4, rel_tol=1e-3))
        thetas = stack_params([
            ocp.default_params(p=jnp.array([float(a)]))
            for a in (0.0, 2.0, 4.0)])
        state = engine.init_state([thetas])
        state, _trajs, stats = engine.step(state, [thetas])
        assert state.zbar["shared"].dtype == jnp.float32
        assert bool(stats.converged)
        np.testing.assert_allclose(
            np.asarray(state.zbar["shared"]), 2.0, atol=5e-3)


class TestRobustnessCorpusF32:
    """VERDICT r5 #4: the degenerate/adversarial corpus of
    ``test_solver_robustness.py`` re-run in f32 through the general IPM
    (``qp_fast_path="off"`` semantics — ``solve_nlp`` directly) and the
    QP path. Parity means the same honest verdicts as f64: solvable
    degenerate programs succeed (at f32-appropriate tolerance, carried
    by the dtype-aware convergence targets), infeasible ones still
    honestly fail. The corpus OPTS request tol=1e-8 — unreachable in
    f32, so every success here exercises the acceptance machinery."""

    @pytest.fixture(params=["ipm", "qp"])
    def solver(self, request):
        from agentlib_mpc_tpu.ops.qp import solve_qp
        from agentlib_mpc_tpu.ops.solver import solve_nlp

        return solve_nlp if request.param == "ipm" else solve_qp

    def _opts(self):
        return SolverOptions(tol=1e-8, max_iter=120)

    def test_licq_failure_duplicated_constraints(self, f32, solver):
        from test_solver_robustness import _qp_nlp

        n = 6
        rng = np.random.default_rng(0)
        M = rng.normal(size=(n, n))
        Q = M @ M.T + n * np.eye(n)
        c = rng.normal(size=n)
        a = rng.normal(size=(1, n))
        nlp = _qp_nlp(Q, c, np.vstack([a, a, a]), np.array([1.0] * 3))
        res = solver(nlp, jnp.zeros(n), None, jnp.full(n, -10.0),
                     jnp.full(n, 10.0), self._opts())
        assert res.w.dtype == jnp.float32
        assert bool(res.stats.success)
        w = np.asarray(res.w)
        assert abs(float((a @ w)[0]) - 1.0) < 1e-4
        grad = Q @ w + c + np.vstack([a, a, a]).T @ np.asarray(res.y)
        assert np.max(np.abs(grad)) < 1e-2

    def test_weakly_active_bound(self, f32, solver):
        from test_solver_robustness import _qp_nlp

        nlp = _qp_nlp(np.eye(3), np.zeros(3))
        res = solver(nlp, jnp.full(3, 0.5), None,
                     jnp.asarray([0.0, -1.0, -1.0]), jnp.full(3, 1.0),
                     self._opts())
        assert bool(res.stats.success)
        # f32 barrier floor parks the weakly-active coordinate at
        # O(sqrt(mu_floor)) ~ 3e-3
        np.testing.assert_allclose(np.asarray(res.w), np.zeros(3),
                                   atol=1e-2)

    def test_pinned_at_bound(self, f32, solver):
        nlp = NLPFunctions(f=lambda w, t: -w[0] + 0.5 * w[1] ** 2,
                           g=lambda w, t: jnp.zeros((0,)),
                           h=lambda w, t: jnp.zeros((0,)))
        res = solver(nlp, jnp.asarray([0.5, 0.5]), None,
                     jnp.zeros(2), jnp.ones(2), self._opts())
        assert bool(res.stats.success)
        assert abs(float(res.w[0]) - 1.0) < 1e-3

    def test_brutal_scaling(self, f32, solver):
        from test_solver_robustness import _qp_nlp

        scales = np.array([1e-4, 1.0, 1e4])
        Q = np.diag(scales)
        c = -scales * np.array([1.0, 2.0, 3.0])
        nlp = _qp_nlp(Q, c)
        res = solver(nlp, jnp.asarray([0.1, 0.1, 0.1]), None,
                     jnp.full(3, -10.0), jnp.full(3, 10.0), self._opts())
        assert bool(res.stats.success)
        w = np.asarray(res.w)
        w_star = np.array([1.0, 2.0, 3.0])
        # in f32 only the stiffest coordinate is position-determined;
        # the flatter ones are judged by the objective (the corpus's own
        # rule for the 1e-4-curvature coordinate, one decade further)
        np.testing.assert_allclose(w[2], w_star[2], rtol=1e-3)
        f = 0.5 * w @ (Q @ w) + c @ w
        f_star = 0.5 * w_star @ (Q @ w_star) + c @ w_star
        assert f - f_star < 1e-2

    def test_contradictory_equalities_not_a_success(self, f32, solver):
        from test_solver_robustness import _qp_nlp

        Aeq = np.array([[1.0, 1.0], [1.0, 1.0]])
        nlp = _qp_nlp(np.eye(2), np.zeros(2), Aeq, np.array([0.0, 1.0]))
        res = solver(nlp, jnp.zeros(2), None, jnp.full(2, -5.0),
                     jnp.full(2, 5.0), self._opts())
        assert not bool(res.stats.success)
        assert float(res.stats.constraint_violation) > 0.05

    def test_equality_outside_box_not_a_success(self, f32, solver):
        from test_solver_robustness import _qp_nlp

        nlp = _qp_nlp(np.eye(2), np.zeros(2), np.array([[1.0, 0.0]]),
                      np.array([3.0]))
        res = solver(nlp, jnp.zeros(2), None, jnp.full(2, -1.0),
                     jnp.ones(2), self._opts())
        assert not bool(res.stats.success)
        assert float(res.stats.constraint_violation) > 0.5


class TestMixedPrecisionParity:
    """ISSUE 20: the certificate-gated mixed routing held to the f32
    tier's own bar. ``precision="mixed"`` rounds the eval_jac/assemble
    stores through bf16 (f32 accumulation, the MXU regime) and leans on
    the refined-residual compensator + the certified-full factor — on
    the corpus shapes above it must keep the f32 class's honest
    verdicts: solvable programs land f32-class answers, infeasible ones
    still honestly fail, and the stats label names the routing."""

    @pytest.fixture(params=["ipm", "qp"])
    def solver(self, request):
        from agentlib_mpc_tpu.ops.qp import solve_qp

        return solve_nlp if request.param == "ipm" else solve_qp

    def _opts(self, **kw):
        kw.setdefault("tol", 1e-8)
        kw.setdefault("max_iter", 120)
        return SolverOptions(precision="mixed", **kw)

    def test_stats_label_names_the_mixed_routing(self, f32, solver):
        from test_solver_robustness import _qp_nlp

        from agentlib_mpc_tpu.ops.solver import precision_path_name

        nlp = _qp_nlp(np.eye(3), -np.ones(3))
        res = solver(nlp, jnp.zeros(3), None, jnp.full(3, -10.0),
                     jnp.full(3, 10.0), self._opts())
        assert precision_path_name(res.stats.precision_path) == "mixed"
        assert bool(res.stats.success)
        np.testing.assert_allclose(np.asarray(res.w), np.ones(3),
                                   atol=1e-2)

    def test_hs071_mixed_matches_f32_class(self, f32):
        """The nonconvex benchmark through the mixed IPM: bf16-rounded
        derivative stores may cost iterations, not the answer class."""
        nlp = NLPFunctions(
            f=lambda w, t: w[0] * w[3] * (w[0] + w[1] + w[2]) + w[2],
            g=lambda w, t: jnp.array([jnp.sum(w**2) - 40.0]),
            h=lambda w, t: jnp.array([w[0] * w[1] * w[2] * w[3] - 25.0]),
        )
        res = solve_nlp(nlp, jnp.array([1.0, 5.0, 5.0, 1.0]), None,
                        jnp.ones(4), 5.0 * jnp.ones(4),
                        self._opts(tol=1e-4))
        assert bool(res.stats.success)
        np.testing.assert_allclose(
            np.asarray(res.w), [1.0, 4.743, 3.8211, 1.3794], atol=1e-2)

    def test_licq_failure_duplicated_constraints_mixed(self, f32,
                                                       solver):
        from test_solver_robustness import _qp_nlp

        n = 6
        rng = np.random.default_rng(0)
        M = rng.normal(size=(n, n))
        Q = M @ M.T + n * np.eye(n)
        c = rng.normal(size=n)
        a = rng.normal(size=(1, n))
        nlp = _qp_nlp(Q, c, np.vstack([a, a, a]), np.array([1.0] * 3))
        res = solver(nlp, jnp.zeros(n), None, jnp.full(n, -10.0),
                     jnp.full(n, 10.0), self._opts())
        assert res.w.dtype == jnp.float32
        assert bool(res.stats.success)
        assert abs(float((a @ np.asarray(res.w))[0]) - 1.0) < 1e-3

    def test_contradictory_equalities_still_honest_mixed(self, f32,
                                                         solver):
        """The routing must not buy speed with a silent wrong answer:
        the infeasible program still reports failure."""
        from test_solver_robustness import _qp_nlp

        Aeq = np.array([[1.0, 1.0], [1.0, 1.0]])
        nlp = _qp_nlp(np.eye(2), np.zeros(2), Aeq, np.array([0.0, 1.0]))
        res = solver(nlp, jnp.zeros(2), None, jnp.full(2, -5.0),
                     jnp.full(2, 5.0), self._opts())
        assert not bool(res.stats.success)
        assert float(res.stats.constraint_violation) > 0.05

    def test_mixed_vs_full_objective_parity_ocp(self, f32):
        """The benchmark-shaped OCP: the mixed solve's optimal cost
        matches the full-f32 solve's to well under a percent — the
        projected-HBM-halving claim rides on this parity."""
        from agentlib_mpc_tpu.models.zoo import LinearRCZone

        ocp = transcribe(LinearRCZone(), ["Q"], N=6, dt=300.0,
                         method="collocation", collocation_degree=2)
        theta = ocp.default_params()
        lb, ub = ocp.bounds(theta)
        w0 = ocp.initial_guess(theta)
        res_full = solve_nlp(ocp.nlp, w0, theta, lb, ub,
                             SolverOptions(max_iter=80))
        res_mixed = solve_nlp(ocp.nlp, w0, theta, lb, ub,
                              SolverOptions(max_iter=80,
                                            precision="mixed"))
        assert bool(res_full.stats.success)
        assert bool(res_mixed.stats.success)
        obj_full = float(res_full.stats.objective)
        obj_mixed = float(res_mixed.stats.objective)
        assert obj_mixed == pytest.approx(obj_full, rel=5e-3)


class TestF32ClosedLoopBudget:
    """The VERDICT r5 #4 repro, pinned: the f32 linear closed loop
    (LinearRCZone, 13 warm-chained solves, default tolerances) through
    the GENERAL IPM — the configuration PERF.md round 5 recorded 2/13
    budget-outs on. The dtype-aware convergence targets + the wedged-mu
    escape must yield zero budget-outs: every solve succeeds well inside
    the default budget."""

    def test_linear_closed_loop_ipm_no_budget_outs(self, f32):
        from agentlib_mpc_tpu.models.zoo import LinearRCZone

        ocp = transcribe(LinearRCZone(), ["Q"], N=6, dt=300.0,
                         method="collocation", collocation_degree=2)
        theta0 = ocp.default_params()
        lb, ub = ocp.bounds(theta0)
        opts = SolverOptions()          # defaults: tol 1e-6, budget 100
        w = ocp.initial_guess(theta0)
        y = jnp.zeros((ocp.n_g,))
        z = jnp.full((ocp.n_h,), 0.1)
        x0 = jnp.array([293.15])
        iterations = []
        for k in range(13):
            th = ocp.default_params(x0=x0)
            res = solve_nlp(ocp.nlp, w, th, lb, ub, opts, y0=y, z0=z,
                            mu0=jnp.asarray(1e-2) if k else None)
            assert bool(res.stats.success), \
                f"solve {k} failed: {res.stats}"
            iterations.append(int(res.stats.iterations))
            w, y, z = res.w, res.y, res.z
            x0 = jnp.asarray(ocp.trajectories(res.w, th)["x"][1])
        assert max(iterations) < opts.max_iter, \
            f"budget-out: per-solve iterations {iterations}"

    def test_forced_stage_qp_terminates_f32(self, f32):
        """The CHANGES.md PR 6 known stall, in the dtype it bites in:
        forced ``kkt_method="stage"`` on the tiny N=8 LinearRCZone QP.
        At f32 precision the pivot-free stage factor genuinely cannot
        deliver usable directions at the near-convergence conditioning
        (even fully Levenberg-regularized), so the honest contract is:
        the direction-health guard holds the iterate (no runaway — the
        old bug reported kkt_error 36 after burning the whole budget),
        the wedge exit bounds the burn well under the budget, the held
        iterate stays finite and near-feasible, and the verdict is an
        HONEST failure — never a silent wrong answer. (The f64 variant
        in test_qp.py::TestForcedStageTinySizes converges and matches
        LU; f64 is what the suite runs.)"""
        from agentlib_mpc_tpu.models.zoo import LinearRCZone
        from agentlib_mpc_tpu.ops.qp import solve_qp

        ocp = transcribe(LinearRCZone(), ["Q"], N=8, dt=300.0,
                         method="collocation", collocation_degree=2)
        theta = ocp.default_params()
        lb, ub = ocp.bounds(theta)
        opts = SolverOptions(tol=1e-6, max_iter=60, kkt_method="stage",
                             stage_partition=ocp.stage_partition)
        res = solve_qp(ocp.nlp, ocp.initial_guess(theta), theta, lb, ub,
                       opts)
        assert int(res.stats.iterations) < 30      # wedge exit, not budget
        assert bool(jnp.all(jnp.isfinite(res.w)))
        assert float(res.stats.kkt_error) < 1.0    # held, no runaway
        # constraint_violation is RAW (unscaled) units on an O(500 W)
        # dynamics scale: ~0.02 here is ~5e-5 relative — near-feasible
        assert float(res.stats.constraint_violation) < 0.1
