"""Retrace-budget gate: the runtime complement of the static passes.

Static analysis catches the *shape* of compile-cache bugs; this gate
catches their *effect*: it builds the 4-agent fused-ADMM consensus fleet
(the bench workload), runs ``step()`` for ``warmup_rounds`` rounds, then
measures ``rounds`` more with the PR 1 ``jax.monitoring`` hooks
(:func:`agentlib_mpc_tpu.utils.jax_setup.enable_compile_profiling`)
installed, and fails when any entry point traces or compiles more than
``lint_budgets.toml`` allows.  A weak-typed carry leaf, a shape-unstable
static arg, a host-rebuilt options object — anything that silently
retraces a warm path — trips this gate even if no static rule names it.

Budgets file (checked in at the repo root)::

    [retrace]
    warmup_rounds = 2
    rounds = 3
    n_agents = 4

    [retrace.budgets]
    default = 0
    "admm.fused_step" = 0

``default`` applies to entry points without their own key. Budget = max
allowed (traces + compiles) DELTA per entry point across the measured
rounds; 0 is the steady-state contract the whole performance story rests
on.
"""

from __future__ import annotations

import os
import re


def load_budgets(path: "str | None" = None) -> dict:
    """Parse lint_budgets.toml (tomllib on 3.11+, tomli when present, and
    a minimal built-in parser for the flat subset this file uses — the
    image constraint is no new deps, not no config)."""
    if path is None:
        from agentlib_mpc_tpu.lint.runner import repo_root

        root = repo_root()
        path = os.path.join(root or ".", "lint_budgets.toml")
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except FileNotFoundError:
        return {"retrace": {"warmup_rounds": 2, "rounds": 3, "n_agents": 4,
                            "budgets": {"default": 0}}}
    try:
        import tomllib as toml_mod              # 3.11+
    except ModuleNotFoundError:
        try:
            import tomli as toml_mod            # common in test images
        except ModuleNotFoundError:
            toml_mod = None
    if toml_mod is not None:
        return toml_mod.loads(raw.decode("utf-8"))
    return _mini_toml(raw.decode("utf-8"))


def _mini_toml(text: str) -> dict:
    """Tables + string/int/float/bool scalars — the subset
    lint_budgets.toml uses. Not a general TOML parser."""
    out: dict = {}
    table = out
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        m = re.fullmatch(r"\[([^\]]+)\]", line)
        if m:
            table = out
            for part in m.group(1).split("."):
                table = table.setdefault(part.strip().strip('"'), {})
            continue
        if "=" not in line:
            continue
        key, val = line.split("=", 1)
        key = key.strip().strip('"').strip("'")
        val = val.strip()
        table[key] = _mini_toml_value(val)
    return out


def _mini_toml_value(val: str):
    if val.startswith("[") and val.endswith("]"):
        # flat arrays of scalars (e.g. the [jaxpr.collectives] axes
        # list) — no nesting, which is all this file uses
        inner = val[1:-1].strip()
        if not inner:
            return []
        return [_mini_toml_value(p.strip()) for p in inner.split(",")]
    if val in ("true", "false"):
        return val == "true"
    if re.fullmatch(r"-?\d+", val):
        return int(val)
    if re.fullmatch(r"-?\d*\.\d+(e-?\d+)?", val):
        return float(val)
    return val.strip('"').strip("'")


def tracker_ocp():
    """The gate workload's transcribed OCP: a 1-control tracker
    (min (u - a)^2) on a 4-interval shooting grid — compiles in seconds
    on CPU, structurally identical to the consensus bench agents.
    Shared by the fused-engine retrace gate and the serving churn gate."""
    from agentlib_mpc_tpu.models.model import Model, ModelEquations
    from agentlib_mpc_tpu.models.objective import SubObjective
    from agentlib_mpc_tpu.models.variables import control_input, parameter
    from agentlib_mpc_tpu.ops.transcription import transcribe

    class _Tracker(Model):
        inputs = [control_input("u", 0.0, lb=-5.0, ub=5.0)]
        parameters = [parameter("a", 1.0)]

        def setup(self, v):
            eq = ModelEquations()
            eq.objective = SubObjective((v.u - v.a) ** 2, name="track")
            return eq

    return transcribe(_Tracker(), ["u"], N=4, dt=0.5,
                      method="multiple_shooting")


def tracker_tenant_spec(ocp, tenant_id: str, a: float):
    """One tracker tenant of the churn workload — the SINGLE definition
    of the TenantSpec that ``run_serving_gate``, ``run_mesh_gate`` and
    the serving-churn tests all script against (a drift here would let
    the gates and the tests silently measure different workloads)."""
    import jax.numpy as jnp

    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.serving import TenantSpec

    return TenantSpec(
        tenant_id=tenant_id, ocp=ocp,
        theta=ocp.default_params(p=jnp.array([a])),
        couplings={"shared_u": "u"},
        solver_options=SolverOptions(max_iter=30))


def serve_tenants(plane, *tenants, rounds: int = 1) -> dict:
    """One churn beat: submit for each tenant, serve ``rounds`` rounds,
    flush the pipeline; returns the merged per-tenant results."""
    for t in tenants:
        plane.submit(t)
    results: dict = {}
    for _ in range(max(rounds, 1)):
        results.update(plane.serve_round())
    results.update(plane.flush())
    return results


def _compile_snapshot(reg) -> dict:
    """Per-entry-point (traces + compiles) totals — the quantity both
    gates budget."""
    totals: dict = {}
    for name in ("jax_traces_total", "jax_compiles_total"):
        for sample in reg.counter(name).samples():
            entry = sample["labels"].get("entry_point", "(unscoped)")
            totals[entry] = totals.get(entry, 0) + int(sample["value"])
    return totals


def build_bench_engine(n_agents: int = 4, kkt_method: str = "auto",
                       jacobian: str = "auto"):
    """The gate's workload: one consensus group of ``n_agents`` trackers
    (min (u - a)^2 coupled on a shared control) — small enough to compile
    in seconds on CPU, structurally identical to the 4-agent bench step.
    ``kkt_method``/``jacobian`` feed the group's solver options (the
    checked-in budgets pin ``"stage"``/``"sparse"`` so the structured
    stage factorization AND the stage-sparse derivative pipeline run
    warm under the same zero-recompile contract as the dense paths).
    Returns (engine, state, theta_batches)."""
    import jax.numpy as jnp

    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import (
        AgentGroup,
        FusedADMM,
        FusedADMMOptions,
        stack_params,
    )

    ocp = tracker_ocp()
    group = AgentGroup(
        name="retrace-gate", ocp=ocp, n_agents=n_agents,
        couplings={"shared_u": "u"},
        solver_options=SolverOptions(max_iter=30, kkt_method=kkt_method,
                                     jacobian=jacobian))
    engine = FusedADMM([group], FusedADMMOptions(max_iterations=8, rho=2.0))
    thetas = stack_params([
        ocp.default_params(p=jnp.array([float(i + 1)]))
        for i in range(n_agents)])
    state = engine.init_state([thetas])
    return engine, state, [thetas]


def run_gate(budgets: "dict | None" = None, verbose: bool = True) -> dict:
    """Run the gate; returns a report dict with ``violations``.

    Steps alternate ``shift_state`` between rounds the way the production
    control loop does — state *values* change every round while avals
    must not, which is precisely the regression surface (weak types,
    dtype drift) this gate pins.
    """
    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.telemetry import jax_events
    from agentlib_mpc_tpu.utils.jax_setup import enable_compile_profiling

    cfg = (budgets or load_budgets()).get("retrace", {})
    warmup = int(cfg.get("warmup_rounds", 2))
    rounds = int(cfg.get("rounds", 3))
    n_agents = int(cfg.get("n_agents", 4))
    kkt_method = str(cfg.get("kkt_method", "auto"))
    jacobian = str(cfg.get("jacobian", "auto"))
    per_entry = dict(cfg.get("budgets", {}) or {})
    default_budget = int(per_entry.pop("default", 0))

    was_enabled = telemetry.enabled()
    telemetry.configure(enabled=True)
    reg = enable_compile_profiling()
    jax_events.reset_scopes()

    def snapshot() -> dict:
        return _compile_snapshot(reg)

    try:
        engine, state, thetas = build_bench_engine(n_agents, kkt_method,
                                                   jacobian)
        for _ in range(max(warmup, 1)):
            state, _trajs, _stats = engine.step(state, thetas)
            state = engine.shift_state(state)

        before = snapshot()
        for _ in range(rounds):
            state, _trajs, _stats = engine.step(state, thetas)
            state = engine.shift_state(state)
        after = snapshot()
    finally:
        # the gate must not leave process-global telemetry flipped on for
        # whoever embeds it (the pytest run, a bench process)
        telemetry.configure(enabled=was_enabled)

    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in set(before) | set(after)}
    violations = []
    for entry, delta in sorted(deltas.items()):
        budget = int(per_entry.get(entry, default_budget))
        if delta > budget:
            violations.append({
                "entry_point": entry,
                "observed": delta,
                "budget": budget,
            })
    report = {
        "warmup_rounds": warmup,
        "rounds": rounds,
        "n_agents": n_agents,
        "kkt_method": kkt_method,
        "jacobian": jacobian,
        "deltas": dict(sorted(deltas.items())),
        "violations": violations,
    }
    if verbose:
        for v in violations:
            print(f"retrace-budget: {v['entry_point']!r} compiled/traced "
                  f"{v['observed']}x in {rounds} post-warmup rounds "
                  f"(budget {v['budget']}) — a warm path is recompiling")
        if not violations:
            print(f"retrace-budget: OK — zero excess compiles across "
                  f"{rounds} rounds ({n_agents} agents)")
    return report


def run_journal_gate(budgets: "dict | None" = None,
                     verbose: bool = True) -> dict:
    """``[telemetry.journal]`` budget gate (ISSUE 15): journaling never
    enters the jit graph.

    The flight recorder is pure host-side Python by construction, but
    the construction is exactly what a careless emit site could break —
    a journal write inside a traced function would either retrace every
    round (the cost this gate pins at zero) or silently bake one
    event's values into the executable. The gate runs the [retrace]
    fleet with the journal ENABLED and production-shaped events
    recorded around every round (set_round + a fleet.round record,
    what the supervisors emit), and holds the per-entry-point
    (traces + compiles) delta to the ``[telemetry.journal.budgets]``
    allowance (default 0). It additionally asserts the journal really
    recorded (no no-op A/A) and that round stamps landed."""
    import os as _os
    import shutil as _shutil
    import tempfile as _tempfile

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.telemetry import jax_events
    from agentlib_mpc_tpu.telemetry import journal as journal_mod
    from agentlib_mpc_tpu.utils.jax_setup import enable_compile_profiling

    all_cfg = budgets or load_budgets()
    cfg = (all_cfg.get("telemetry", {}) or {}).get("journal", {}) or {}
    warmup = int(cfg.get("warmup_rounds", 2))
    rounds = int(cfg.get("rounds", 3))
    n_agents = int(cfg.get("n_agents", 4))
    per_entry = dict(cfg.get("budgets", {}) or {})
    default_budget = int(per_entry.pop("default", 0))

    was_enabled = telemetry.enabled()
    telemetry.configure(enabled=True)
    reg = enable_compile_profiling()
    jax_events.reset_scopes()
    tmp = _tempfile.mkdtemp(prefix="journal-gate-")
    path = _os.path.join(tmp, "journal.jsonl")
    failures: list = []
    try:
        engine, state, thetas = build_bench_engine(n_agents)
        for _ in range(max(warmup, 1)):
            state, _trajs, _stats = engine.step(state, thetas)
            state = engine.shift_state(state)

        telemetry.enable_journal(path)
        before = _compile_snapshot(reg)
        for r in range(rounds):
            telemetry.journal_set_round(r)
            state, _trajs, _stats = engine.step(state, thetas)
            telemetry.journal_event(
                "fleet.round", degraded=False, devices=1,
                quarantined=0)
            state = engine.shift_state(state)
        after = _compile_snapshot(reg)
        telemetry.disable_journal()
        events = journal_mod.read_events(path)
        if len(events) < rounds:
            failures.append(
                f"journal recorded {len(events)} events across "
                f"{rounds} journaled rounds — the gate measured a "
                f"no-op, not journaling")
        elif any(e.get("round") is None for e in events):
            failures.append("journaled rounds carry no round stamp")
    finally:
        telemetry.disable_journal()
        telemetry.configure(enabled=was_enabled)
        _shutil.rmtree(tmp, ignore_errors=True)

    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in set(before) | set(after)}
    violations = []
    for entry, delta in sorted(deltas.items()):
        budget = int(per_entry.get(entry, default_budget))
        if delta > budget:
            violations.append({"entry_point": entry,
                               "observed": delta, "budget": budget})
    report = {
        "warmup_rounds": warmup,
        "rounds": rounds,
        "n_agents": n_agents,
        "deltas": dict(sorted(deltas.items())),
        "violations": violations,
        "failures": failures,
    }
    if verbose:
        for v in violations:
            print(f"journal-budget: {v['entry_point']!r} compiled/"
                  f"traced {v['observed']}x across {rounds} journaled "
                  f"rounds (budget {v['budget']}) — journaling is "
                  f"entering the jit graph")
        for f in failures:
            print(f"journal-budget: FAILED — {f}")
        if not violations and not failures:
            print(f"journal-budget: OK — journaling active, zero "
                  f"excess compiles across {rounds} rounds "
                  f"({n_agents} agents)")
    return report


def run_profiler_gate(budgets: "dict | None" = None,
                      verbose: bool = True) -> dict:
    """``[telemetry.profiler]`` budget gate (ISSUE 16): phase capture
    never enters the jit graph.

    The performance observatory promises that ``phase_scope`` is
    trace-time metadata (free at runtime) and that wrapping a warm round
    in ``jax.profiler.trace`` costs no recompiles — a phase annotation
    that closed over a traced value, or a capture path that rebuilt the
    step, would turn the observatory into the perturbation it is meant
    to measure. The gate warms the [retrace] fleet, extracts the step's
    HLO once (the one sanctioned retrace, paid before the measured
    window — exactly how ``bench.py``/``ServingPlane`` stage it), then
    holds the per-entry-point (traces + compiles) delta across
    ``rounds`` *captured* rounds to the ``[telemetry.profiler.budgets]``
    allowance (default 0). It additionally asserts the capture really
    recorded (device-op events joined against named phases — no no-op
    A/A)."""
    import jax

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.telemetry import jax_events
    from agentlib_mpc_tpu.telemetry import profiler as profiler_mod
    from agentlib_mpc_tpu.utils.jax_setup import enable_compile_profiling

    all_cfg = budgets or load_budgets()
    cfg = (all_cfg.get("telemetry", {}) or {}).get("profiler", {}) or {}
    warmup = int(cfg.get("warmup_rounds", 2))
    rounds = int(cfg.get("rounds", 3))
    n_agents = int(cfg.get("n_agents", 4))
    min_coverage = float(cfg.get("min_coverage", 0.5))
    per_entry = dict(cfg.get("budgets", {}) or {})
    default_budget = int(per_entry.pop("default", 0))

    was_enabled = telemetry.enabled()
    telemetry.configure(enabled=True)
    reg = enable_compile_profiling()
    jax_events.reset_scopes()
    failures: list = []
    prof = None
    try:
        engine, state, thetas = build_bench_engine(n_agents)
        for _ in range(max(warmup, 1)):
            state, _trajs, _stats = engine.step(state, thetas)
            state = engine.shift_state(state)

        # the one sanctioned retrace: HLO text for the phase join,
        # extracted BEFORE the measured window (never per capture)
        hlo = profiler_mod.hlo_text_for(engine._step,
                                        *engine._step_templates())

        def run_round():
            nonlocal state
            state, _trajs, _stats = engine.step(state, thetas)
            state = engine.shift_state(state)
            jax.block_until_ready(state)

        before = _compile_snapshot(reg)
        prof = profiler_mod.capture_phase_profile(
            run_round, rounds=rounds, hlo_text=hlo,
            platform=jax.default_backend(), n_devices=1,
            journal=False)
        after = _compile_snapshot(reg)

        n_events = sum(prof.op_events.values())
        if n_events <= 0:
            failures.append(
                "capture joined zero device-op events — the gate "
                "measured a no-op, not a phase capture")
        elif prof.coverage < min_coverage:
            failures.append(
                f"phase coverage {prof.coverage:.3f} below the gate "
                f"floor {min_coverage} — named scopes are not reaching "
                f"the executed HLO")
    finally:
        telemetry.configure(enabled=was_enabled)

    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in set(before) | set(after)}
    violations = []
    for entry, delta in sorted(deltas.items()):
        budget = int(per_entry.get(entry, default_budget))
        if delta > budget:
            violations.append({"entry_point": entry,
                               "observed": delta, "budget": budget})
    report = {
        "warmup_rounds": warmup,
        "rounds": rounds,
        "n_agents": n_agents,
        "coverage": None if prof is None else prof.coverage,
        "op_events": 0 if prof is None else sum(prof.op_events.values()),
        "deltas": dict(sorted(deltas.items())),
        "violations": violations,
        "failures": failures,
    }
    if verbose:
        for v in violations:
            print(f"profiler-budget: {v['entry_point']!r} compiled/"
                  f"traced {v['observed']}x across {rounds} captured "
                  f"rounds (budget {v['budget']}) — phase capture is "
                  f"entering the jit graph")
        for f in failures:
            print(f"profiler-budget: FAILED — {f}")
        if not violations and not failures:
            print(f"profiler-budget: OK — capture live (coverage "
                  f"{report['coverage']:.3f}, {report['op_events']} "
                  f"device-op events), zero excess compiles across "
                  f"{rounds} captured rounds ({n_agents} agents)")
    return report


class _MeshGateSkipped(Exception):
    """Internal control flow: the mesh gate's measurement legs were
    skipped (single-device backend — the failure is already recorded)."""


def run_mesh_gate(budgets: "dict | None" = None,
                  verbose: bool = True) -> dict:
    """``[mesh]`` budget gate: the sharded step's zero-retrace contract.

    Builds the gate fleet SHARDED over the fleet mesh
    (``FusedADMM(mesh=fleet_mesh())`` — the explicit ``shard_map`` path
    with ``psum`` consensus), warms it, and holds the per-entry-point
    (traces + compiles) delta across ``rounds`` further control steps to
    the ``[mesh.budgets]`` allowance (default 0): the collectives, the
    shard-local solves and the per-round ``admm_collective_seconds``
    probe must all hold the same warm steady state as the single-device
    step. A second measured leg churns a mesh-backed
    ``ServingPlane(mesh=...)`` through join → serve → join → serve →
    leave → serve (the ``[mesh.serving]`` budgets): membership on a
    SHARDED engine is still data, never structure.

    A third measured leg (the ``[mesh.survive]`` budgets, ISSUE 10)
    scripts the survivability churn on a
    :class:`~agentlib_mpc_tpu.parallel.survival.FleetSupervisor`:
    after a warmup cycle that builds BOTH layouts (full mesh and the
    one-device-down degraded mesh — the one legitimate degraded-mesh
    rebuild), a full degrade → serve → re-admit → serve cycle is held
    to ZERO traces/compiles: layouts are cached per surviving-device
    set, state pad/slice/placement are shape-stable data movement, and
    re-admission reinstates the cached full-mesh engine — shard loss
    must never reintroduce retrace churn beyond that first rebuild.

    With no real multi-device backend, the gate requests 8 virtual CPU
    devices — effective only before backend init, which is how both the
    CLI (fresh process) and CI run it.
    """
    from agentlib_mpc_tpu.utils.jax_setup import request_virtual_devices

    cfg = (budgets or load_budgets()).get("mesh", {})
    # must precede any backend init to be honored (no-op afterwards)
    request_virtual_devices(int(cfg.get("devices", 8)))

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.telemetry import jax_events
    from agentlib_mpc_tpu.utils.jax_setup import enable_compile_profiling

    warmup = int(cfg.get("warmup_rounds", 2))
    rounds = int(cfg.get("rounds", 3))
    per_entry = dict(cfg.get("budgets", {}) or {})
    default_budget = int(per_entry.pop("default", 0))
    serving_cfg = dict(cfg.get("serving", {}) or {})
    serving_budgets = dict(serving_cfg.get("budgets", {}) or {})
    serving_default = int(serving_budgets.pop("default", 0))
    survive_cfg = dict(cfg.get("survive", {}) or {})
    survive_budgets = dict(survive_cfg.get("budgets", {}) or {})
    survive_default = int(survive_budgets.pop("default", 0))

    was_enabled = telemetry.enabled()
    telemetry.configure(enabled=True)
    reg = enable_compile_profiling()
    jax_events.reset_scopes()

    failures: list = []
    before = after = s_before = s_after = {}
    v_before = v_after = {}
    try:
        import jax
        import jax.numpy as jnp

        from agentlib_mpc_tpu.ops.solver import SolverOptions
        from agentlib_mpc_tpu.parallel import fleet_mesh
        from agentlib_mpc_tpu.parallel.fused_admm import (
            AgentGroup,
            FusedADMM,
            FusedADMMOptions,
            stack_params,
        )
        from agentlib_mpc_tpu.serving import ServingPlane

        mesh = fleet_mesh()
        n_dev = max(1, int(mesh.devices.size))
        want = int(cfg.get("n_agents", 8))
        n_agents = n_dev * max(1, -(-want // n_dev))
        if n_dev < 2:
            # a foregone exit-1: running the (minutes-long) legs over an
            # unsharded path would prove nothing — report and stop
            failures.append(
                f"mesh gate ran on a single-device backend ({n_dev} "
                f"device) — the sharded path was NOT exercised; set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=8 "
                f"(or run the gate in a fresh process)")
            raise _MeshGateSkipped

        ocp = tracker_ocp()
        group = AgentGroup(
            name="mesh-gate", ocp=ocp, n_agents=n_agents,
            couplings={"shared_u": "u"},
            solver_options=SolverOptions(max_iter=30))
        engine = FusedADMM([group],
                           FusedADMMOptions(max_iterations=8, rho=2.0),
                           mesh=mesh)
        thetas = [stack_params([
            ocp.default_params(p=jnp.array([float(i + 1)]))
            for i in range(n_agents)])]
        state = engine.init_state(thetas)
        for _ in range(max(warmup, 1)):
            state, _trajs, _stats = engine.step(state, thetas)
            state = engine.shift_state(state)

        before = _compile_snapshot(reg)
        for _ in range(rounds):
            state, _trajs, _stats = engine.step(state, thetas)
            state = engine.shift_state(state)
        after = _compile_snapshot(reg)

        # -- mesh serving leg: churn on a SHARDED bucket engine --------
        plane = ServingPlane(
            FusedADMMOptions(max_iterations=6, rho=2.0), mesh=mesh,
            pipelined=False, donate=False)

        def spec(tid, a):
            return tracker_tenant_spec(ocp, tid, a)

        def serve(*tenants):
            serve_tenants(plane, *tenants)

        plane.join(spec("w0", 1.0))      # warmup: cold build + splices
        serve("w0")
        plane.leave("w0")
        plane.join(spec("w0", 1.0))
        serve("w0")
        plane.leave("w0")
        s_before = _compile_snapshot(reg)
        plane.join(spec("m0", 1.0))
        serve("m0")
        plane.join(spec("m1", 2.0))
        serve("m0", "m1")
        plane.leave("m0")
        serve("m1")
        plane.leave("m1")
        s_after = _compile_snapshot(reg)

        # -- survive leg: degrade -> serve -> readmit at 0 retraces ----
        from agentlib_mpc_tpu.parallel.survival import FleetSupervisor

        sup = FleetSupervisor(
            [group], FusedADMMOptions(max_iterations=8, rho=2.0),
            mesh=mesh, watchdog_timeout_s=120.0, readmit_after=1,
            probation_rounds=1)
        sv_state = sup.init_state(thetas)
        dead = sup.full_mesh.devices.flat[-1].id
        # warmup cycle: builds the full AND the degraded layout (the
        # one legitimate degraded-mesh rebuild) and exercises every
        # pad/slice/placement shape the measured cycle repeats
        sv_state, _t, _s = sup.step(sv_state, thetas)
        sup.force_degrade([dead])
        sv_state, _t, _s = sup.step(sv_state, thetas)
        sup.force_readmit()
        sv_state, _t, _s = sup.step(sv_state, thetas)

        v_before = _compile_snapshot(reg)
        sup.force_degrade([dead])
        sv_state, _t, _s = sup.step(sv_state, thetas)
        sv_state, _t, _s = sup.step(sv_state, thetas)
        sup.force_readmit()
        sv_state, _t, _s = sup.step(sv_state, thetas)
        v_after = _compile_snapshot(reg)
        if sup.stats()["layouts_built"] != 2:
            failures.append(
                f"survive leg built {sup.stats()['layouts_built']} "
                f"layouts — the repeat degrade/readmit cycle must reuse "
                f"the 2 warmed engines, not rebuild")
    except _MeshGateSkipped:
        pass
    finally:
        telemetry.configure(enabled=was_enabled)

    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in set(before) | set(after)}
    violations = []
    for entry, delta in sorted(deltas.items()):
        budget = int(per_entry.get(entry, default_budget))
        if delta > budget:
            violations.append({"entry_point": entry, "observed": delta,
                               "budget": budget})
    serving_deltas = {k: s_after.get(k, 0) - s_before.get(k, 0)
                      for k in set(s_before) | set(s_after)}
    for entry, delta in sorted(serving_deltas.items()):
        budget = int(serving_budgets.get(entry, serving_default))
        if delta > budget:
            violations.append({"entry_point": f"serving:{entry}",
                               "observed": delta, "budget": budget})
    survive_deltas = {k: v_after.get(k, 0) - v_before.get(k, 0)
                      for k in set(v_before) | set(v_after)}
    for entry, delta in sorted(survive_deltas.items()):
        budget = int(survive_budgets.get(entry, survive_default))
        if delta > budget:
            violations.append({"entry_point": f"survive:{entry}",
                               "observed": delta, "budget": budget})
    report = {
        "devices": len(jax.devices()),
        "mesh_devices": n_dev,
        "warmup_rounds": warmup,
        "rounds": rounds,
        "n_agents": n_agents,
        "deltas": dict(sorted(deltas.items())),
        "serving_deltas": dict(sorted(serving_deltas.items())),
        "survive_deltas": dict(sorted(survive_deltas.items())),
        "violations": violations,
        "failures": failures,
    }
    if verbose:
        for v in violations:
            print(f"mesh-budget: {v['entry_point']!r} compiled/traced "
                  f"{v['observed']}x warm (budget {v['budget']}) — the "
                  f"sharded step is recompiling")
        for f in failures:
            print(f"mesh-budget: {f}")
        if not violations and not failures:
            print(f"mesh-budget: OK — zero excess compiles across "
                  f"{rounds} sharded rounds ({n_agents} agents / "
                  f"{n_dev} devices), the mesh serving churn and the "
                  f"degrade -> serve -> re-admit survive cycle")
    return report


def run_scenario_gate(budgets: "dict | None" = None,
                      verbose: bool = True) -> dict:
    """``[scenario]`` budget gate: the scenario fleet's zero-retrace
    contract (ISSUE 12 CI satellite).

    Builds the tracker workload as a :class:`~agentlib_mpc_tpu.
    scenario.fleet.ScenarioFleet` SHARDED over the 2-D
    (agents × scenarios) mesh, warms it, then holds the per-entry-point
    (traces + compiles) delta across ``rounds`` further
    scenario-count-stable control steps to the ``[scenario.budgets]``
    allowance (default 0): the vmapped branch solves, the
    non-anticipativity psums and the per-round telemetry must hold the
    same warm steady state as every other fused path — batching a
    third axis must never reintroduce retrace churn. Like the mesh
    gate, the 8 virtual CPU devices must be requested before backend
    init (fresh process: the CLI and CI both do).

    A second measured leg (the ``[scenario.survive]`` budgets, ISSUE
    14) scripts the 2-D survivability churn on a
    :class:`~agentlib_mpc_tpu.parallel.survival.
    ScenarioFleetSupervisor`: after a warmup cycle that builds BOTH
    layouts (the full grid and the scenarios-axis-degraded one — the
    one legitimate degraded rebuild), a repeat degrade → serve →
    re-admit → serve cycle is held to ZERO traces/compiles — layouts
    are cached per surviving rectangle, the scenario-column selection
    / probability renormalization / multiplier re-centering are
    shape-stable data movement, and re-admission reinstates the cached
    full-grid engine."""
    from agentlib_mpc_tpu.utils.jax_setup import request_virtual_devices

    cfg = (budgets or load_budgets()).get("scenario", {})
    request_virtual_devices(int(cfg.get("devices", 8)))

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.telemetry import jax_events
    from agentlib_mpc_tpu.utils.jax_setup import enable_compile_profiling

    warmup = int(cfg.get("warmup_rounds", 2))
    rounds = int(cfg.get("rounds", 3))
    per_entry = dict(cfg.get("budgets", {}) or {})
    default_budget = int(per_entry.pop("default", 0))
    survive_cfg = dict(cfg.get("survive", {}) or {})
    survive_budgets = dict(survive_cfg.get("budgets", {}) or {})
    survive_default = int(survive_budgets.pop("default", 0))

    was_enabled = telemetry.enabled()
    telemetry.configure(enabled=True)
    reg = enable_compile_profiling()
    jax_events.reset_scopes()

    failures: list = []
    before = after = {}
    v_before = v_after = {}
    n_scenarios = 0
    try:
        import jax
        import jax.numpy as jnp

        from agentlib_mpc_tpu.ops.solver import SolverOptions
        from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup
        from agentlib_mpc_tpu.parallel.multihost import scenario_mesh
        from agentlib_mpc_tpu.scenario import (
            ScenarioFleet,
            ScenarioFleetOptions,
            ensemble_thetas,
            fan_tree,
        )

        n_dev = len(jax.devices())
        n_shards = int(cfg.get("scenario_shards", 2))
        if n_dev < 2 * n_shards or n_dev % n_shards:
            failures.append(
                f"scenario gate ran on {n_dev} device(s) — the 2-D "
                f"sharded path was NOT exercised; set XLA_FLAGS="
                f"--xla_force_host_platform_device_count=8 (or run the "
                f"gate in a fresh process)")
            raise _MeshGateSkipped
        mesh = scenario_mesh(n_shards)
        n_agents = int(mesh.shape["agents"]) * max(
            1, int(cfg.get("n_agents", 4)) // int(mesh.shape["agents"]))
        n_scenarios = n_shards * max(
            1, int(cfg.get("n_scenarios", 4)) // n_shards)

        ocp = tracker_ocp()
        tree = fan_tree(n_scenarios, robust_horizon=1)
        group = AgentGroup(
            name="scenario-gate", ocp=ocp, n_agents=n_agents,
            couplings={"shared_u": "u"},
            solver_options=SolverOptions(max_iter=30))
        fleet = ScenarioFleet(
            group, tree,
            ScenarioFleetOptions(max_iterations=8, rho=2.0, rho_na=2.0),
            mesh=mesh)
        thetas = jax.tree.map(lambda *xs: jnp.stack(xs), *[
            ensemble_thetas(ocp.default_params(p=jnp.array([float(i + 1)])),
                            tree, seed=i)
            for i in range(n_agents)])
        state = fleet.init_state(thetas)
        state, thetas = fleet.shard_args(mesh, state, thetas)
        for _ in range(max(warmup, 1)):
            state, _trajs, _stats = fleet.step(state, thetas)
            state = fleet.shift_state(state)

        before = _compile_snapshot(reg)
        for _ in range(rounds):
            state, _trajs, _stats = fleet.step(state, thetas)
            state = fleet.shift_state(state)
        after = _compile_snapshot(reg)

        # -- survive leg (ISSUE 14): 2-D degrade -> serve -> readmit --
        from agentlib_mpc_tpu.parallel.survival import (
            ScenarioFleetSupervisor,
        )

        sup = ScenarioFleetSupervisor(
            group, tree,
            ScenarioFleetOptions(max_iterations=8, rho=2.0,
                                 rho_na=2.0),
            mesh=mesh, watchdog_timeout_s=120.0,
            readmit_after=1, probation_rounds=1)
        # fresh (unplaced) theta: the supervisor places per layout
        sv_thetas = jax.tree.map(lambda *xs: jnp.stack(xs), *[
            ensemble_thetas(
                ocp.default_params(p=jnp.array([float(i + 1)])),
                tree, seed=i)
            for i in range(n_agents)])
        sv_state = sup.init_state(sv_thetas)
        victim = int(sup.grid_ids[0, -1])
        # warmup cycle: builds the full AND the scenarios-degraded
        # layout (the one legitimate degraded rebuild) and exercises
        # every selection/pad/re-center/placement shape the measured
        # cycle repeats
        sv_state, _t, _s = sup.step(sv_state, sv_thetas)
        sup.force_degrade([victim], axis="scenarios")
        sv_state, _t, _s = sup.step(sv_state, sv_thetas)
        sup.force_readmit()
        sv_state, _t, _s = sup.step(sv_state, sv_thetas)

        v_before = _compile_snapshot(reg)
        sup.force_degrade([victim], axis="scenarios")
        sv_state, _t, _s = sup.step(sv_state, sv_thetas)
        sv_state, _t, _s = sup.step(sv_state, sv_thetas)
        sup.force_readmit()
        sv_state, _t, _s = sup.step(sv_state, sv_thetas)
        v_after = _compile_snapshot(reg)
        if sup.stats()["layouts_built"] != 2:
            failures.append(
                f"scenario survive leg built "
                f"{sup.stats()['layouts_built']} layouts — the repeat "
                f"degrade/readmit cycle must reuse the 2 warmed "
                f"engines, not rebuild")
    except _MeshGateSkipped:
        pass
    finally:
        telemetry.configure(enabled=was_enabled)

    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in set(before) | set(after)}
    violations = []
    for entry, delta in sorted(deltas.items()):
        budget = int(per_entry.get(entry, default_budget))
        if delta > budget:
            violations.append({"entry_point": entry, "observed": delta,
                               "budget": budget})
    survive_deltas = {k: v_after.get(k, 0) - v_before.get(k, 0)
                      for k in set(v_before) | set(v_after)}
    for entry, delta in sorted(survive_deltas.items()):
        budget = int(survive_budgets.get(entry, survive_default))
        if delta > budget:
            violations.append({"entry_point": f"survive:{entry}",
                               "observed": delta, "budget": budget})
    report = {
        "warmup_rounds": warmup,
        "rounds": rounds,
        "n_scenarios": n_scenarios,
        "deltas": dict(sorted(deltas.items())),
        "survive_deltas": dict(sorted(survive_deltas.items())),
        "violations": violations,
        "failures": failures,
    }
    if verbose:
        for v in violations:
            print(f"scenario-budget: {v['entry_point']!r} "
                  f"compiled/traced {v['observed']}x warm (budget "
                  f"{v['budget']}) — the scenario round is recompiling")
        for f in failures:
            print(f"scenario-budget: {f}")
        if not violations and not failures:
            print(f"scenario-budget: OK — zero excess compiles across "
                  f"{rounds} scenario-count-stable rounds "
                  f"({n_scenarios} scenarios) and the 2-D degrade -> "
                  f"serve -> re-admit survive cycle")
    return report


def run_serving_gate(budgets: "dict | None" = None,
                     verbose: bool = True) -> dict:
    """``[serving]`` budget gate: the serving plane's churn contract.

    Scripted sequence on the tracker workload:

    1. **warmup** — first tenant joins (cold engine build + warmed
       step), serves, leaves to an EMPTY bucket (retiring it) and
       rejoins — so every program the churn can run (fused step, lane
       splices, state init, bucket re-creation) has traced once;
    2. **measured churn** — join → serve → join → serve → leave →
       serve → leave-all (bucket retires) → REJOIN → serve → flush,
       with the per-entry-point (traces + compiles) delta held to the
       ``[serving.budgets]`` allowance (default 0: membership is data,
       never structure);
    3. **cache assertion** — the rejoin after retirement must come out
       of the compile cache (``engine_cached`` on the receipt AND a
       cache-dict hit), or the gate fails regardless of the compile
       counters;
    4. **health churn** (the ``[serving.health]`` budget) — evict →
       serve → readmit → serve on a live tenant: an eviction is a mask
       flip and a re-admission is a fresh-warm-start lane splice, both
       DATA — the per-entry-point (traces + compiles) delta across the
       health churn is held to the ``[serving.health.budgets]``
       allowance (default 0), so the survivability ladder can never
       reintroduce retrace churn;
    5. **autopilot ladder cycle** (the ``[serving.autopilot]`` budget,
       ISSUE 17) — a fresh autopilot-armed plane joins ONE robust
       (2-branch fan) tenant and force-walks the full quality ladder
       down and back (L0 → L1 → L2 → L3 → L2 → L1 → L0, serving at
       every rung) twice: the FIRST cycle pays each quality level's
       cold build once (L1's warm-capped robust bucket, L3's
       subtree-collapsed flat bucket), the SECOND — measured — cycle
       must come entirely out of the compile cache, with the
       per-entry-point (traces + compiles) delta held to the
       ``[serving.autopilot.budgets]`` allowance (default 0): a
       quality move is a re-bucket through the cache, never a
       recompile, or the controller would pay a cold build at the
       exact moment the plane is drowning;
    6. **warm-start flip** (the ``[serving.warmstart]`` budget,
       ISSUE 19) — a fresh plane with a learned warm-start predictor
       installed runs join-with-predictor → serve → predictor-off →
       join → serve → predictor-on → serve after a warmup that traces
       both reset flavors: the predicted and plain cold starts share
       ONE splice executable (the enable flag is traced data), so the
       whole flip cycle is held to the ``[serving.warmstart.budgets]``
       allowance (default 0). The gate also asserts at least one
       admission ran the predictor and one took the plain path — no
       no-op A/A.
    """
    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.telemetry import jax_events
    from agentlib_mpc_tpu.utils.jax_setup import enable_compile_profiling

    cfg = (budgets or load_budgets()).get("serving", {})
    serve_rounds = int(cfg.get("serve_rounds", 1))
    capacity = int(cfg.get("capacity", 4))
    per_entry = dict(cfg.get("budgets", {}) or {})
    default_budget = int(per_entry.pop("default", 0))

    was_enabled = telemetry.enabled()
    telemetry.configure(enabled=True)
    reg = enable_compile_profiling()
    jax_events.reset_scopes()

    failures: list = []
    try:
        from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
        from agentlib_mpc_tpu.serving import ServingPlane

        ocp = tracker_ocp()
        plane = ServingPlane(
            FusedADMMOptions(max_iterations=6, rho=2.0),
            slot_multiple=1, initial_capacity=capacity,
            pipelined=True, donate=True)

        def spec(tid, a):
            return tracker_tenant_spec(ocp, tid, a)

        def serve(*tenants):
            serve_tenants(plane, *tenants, rounds=serve_rounds)

        # -- warmup: cover every program shape, including retirement --
        plane.join(spec("w0", 1.0))
        serve("w0")
        plane.leave("w0")
        rec = plane.join(spec("w0", 1.0))
        if not rec.engine_cached:
            failures.append("warmup rejoin missed the compile cache")
        serve("w0")
        plane.leave("w0")

        before = _compile_snapshot(reg)
        hits_before = plane.cache.hits

        # -- measured churn: join -> serve -> leave -> rejoin ----------
        plane.join(spec("t0", 1.0))
        serve("t0")
        plane.join(spec("t1", 2.0))
        serve("t0", "t1")
        plane.join(spec("t2", 3.0))
        serve("t0", "t1", "t2")
        plane.leave("t1")
        serve("t0", "t2")
        plane.leave("t0")
        plane.leave("t2")                 # bucket retires
        rejoin = plane.join(spec("t1", 2.0))
        serve("t1")
        after = _compile_snapshot(reg)

        if not rejoin.engine_cached:
            failures.append(
                "rejoin after bucket retirement was NOT a compile-cache "
                "hit — the engine was rebuilt")
        if plane.cache.hits <= hits_before:
            failures.append("cache hit counter did not advance across "
                            "the churn sequence")

        # -- health churn: evict -> serve -> readmit -> serve ----------
        health_cfg = dict(cfg.get("health", {}) or {})
        health_budgets = dict(health_cfg.get("budgets", {}) or {})
        health_default = int(health_budgets.pop("default", 0))
        plane.join(spec("h0", 1.5))
        serve("t1", "h0")                 # cover shapes pre-measurement
        h_before = _compile_snapshot(reg)
        plane.evict_tenant("h0", reason="gate")
        serve("t1")                       # bucket serves without h0
        if not plane.readmit_tenant("h0"):
            failures.append("health-churn readmission found no free "
                            "slot — eviction did not release one")
        serve("t1", "h0")
        h_after = _compile_snapshot(reg)
        plane.leave("h0")
        plane.leave("t1")

        # -- autopilot ladder cycle (ISSUE 17): quality moves are ------
        # -- re-buckets through the cache, never recompiles ------------
        import numpy as np

        import jax
        import jax.numpy as jnp

        from agentlib_mpc_tpu.scenario.tree import fan_tree
        from agentlib_mpc_tpu.serving import AutopilotPolicy, TenantSpec

        auto_cfg = dict(cfg.get("autopilot", {}) or {})
        auto_budgets = dict(auto_cfg.get("budgets", {}) or {})
        auto_default = int(auto_budgets.pop("default", 0))
        plane2 = ServingPlane(
            FusedADMMOptions(max_iterations=6, rho=2.0),
            slot_multiple=1, initial_capacity=capacity,
            pipelined=True, donate=True, autopilot=AutopilotPolicy())
        # one ROBUST tenant (2-branch fan, skewed probabilities) so the
        # cycle exercises every lever class: L1 re-buckets into the
        # warm-capped robust bucket, L3 shrinks the tree to its
        # highest-probability branch — which normalizes into a FLAT
        # capped bucket — and the way back up restores both
        tree = fan_tree(2, probabilities=(0.7, 0.3))
        from agentlib_mpc_tpu.ops.solver import SolverOptions

        theta = jax.tree.map(
            lambda leaf: jnp.broadcast_to(jnp.asarray(leaf),
                                          (2,) + np.shape(leaf)),
            ocp.default_params())
        theta = theta._replace(
            p=jnp.stack([jnp.array([1.0]), jnp.array([2.0])]))
        plane2.join(TenantSpec(
            tenant_id="r0", ocp=ocp, theta=theta,
            couplings={"shared_u": "u"},
            solver_options=SolverOptions(max_iter=30),
            scenario_tree=tree))

        def ladder_cycle():
            for lvl in (1, 2, 3, 2, 1, 0):
                if not plane2.autopilot.force_level(plane2, "r0", lvl):
                    failures.append(
                        f"autopilot force_level({lvl}) was refused "
                        f"mid-cycle")
                serve_tenants(plane2, "r0", rounds=serve_rounds)

        ladder_cycle()                # warmup: pays each level's build
        a_hits_before = plane2.cache.hits
        a_before = _compile_snapshot(reg)
        ladder_cycle()                # measured: cache hits only
        a_after = _compile_snapshot(reg)
        if plane2.cache.hits <= a_hits_before:
            failures.append(
                "autopilot ladder cycle did not advance the compile-"
                "cache hit counter — the quality moves bypassed the "
                "cache")
        plane2.leave("r0")

        # -- learned warm-start flip (ISSUE 19): the predicted and ----
        # -- plain cold starts share ONE splice executable ------------
        from agentlib_mpc_tpu.ml.training import fit_warmstart
        from agentlib_mpc_tpu.ml.warmstart import theta_flat_size
        from agentlib_mpc_tpu.serving.fingerprint import (
            tenant_fingerprint,
        )

        ws_cfg = dict(cfg.get("warmstart", {}) or {})
        ws_budgets = dict(ws_cfg.get("budgets", {}) or {})
        ws_default = int(ws_budgets.pop("default", 0))
        plane3 = ServingPlane(
            FusedADMMOptions(max_iterations=6, rho=2.0),
            slot_multiple=1, initial_capacity=capacity,
            pipelined=True, donate=True)
        # probe join: the live engine tells us the head widths the
        # artifact must carry (the gate never hardcodes a transcription
        # detail the workload owns)
        plane3.join(spec("p0", 1.0))
        (_k3, bucket3), = plane3._buckets.items()
        eng3 = bucket3.engine
        n_w = int(eng3.groups[0].ocp.n_w)
        n_lam = len(eng3._aliases) * int(eng3.T)
        plane3.leave("p0")
        # untrained synthetic weights: the quality gate will REJECT the
        # prediction — irrelevant here, the reset executable is shared
        # and only its trace count is under test
        rng = np.random.default_rng(0)
        n_rows, n_theta = 8, theta_flat_size(ocp)
        ds = {"theta": rng.normal(size=(n_rows, n_theta)),
              "w": rng.normal(size=(n_rows, n_w)),
              "lam": rng.normal(size=(n_rows, n_lam)),
              "iterations": np.full(n_rows, 3)}
        ws_model = fit_warmstart(
            ds, fingerprint=tenant_fingerprint(ocp).digest,
            aliases=list(eng3._aliases),
            trainer_config={"hidden": (4,), "epochs": 2, "seed": 0})
        plane3.install_warmstart(ws_model)

        # warmup: both reset flavors (predictor on + off) trace once
        plane3.join(spec("ws0", 1.0))
        serve_tenants(plane3, "ws0", rounds=serve_rounds)
        plane3.set_warmstart(False)
        plane3.join(spec("ws1", 2.0))
        serve_tenants(plane3, "ws0", "ws1", rounds=serve_rounds)
        plane3.set_warmstart(True)
        plane3.leave("ws0")
        plane3.leave("ws1")

        # measured flip: join-with-predictor -> serve -> predictor-off
        # -> join -> serve -> back on -> serve, all at ZERO compiles —
        # the enable flag is traced data, never structure
        w_before = _compile_snapshot(reg)
        plane3.join(spec("m0", 1.0))
        serve_tenants(plane3, "m0", rounds=serve_rounds)
        plane3.set_warmstart(False)
        plane3.join(spec("m1", 2.0))
        serve_tenants(plane3, "m0", "m1", rounds=serve_rounds)
        plane3.set_warmstart(True)
        serve_tenants(plane3, "m0", "m1", rounds=serve_rounds)
        w_after = _compile_snapshot(reg)
        ws_stats = plane3.stats()["warmstart"]["buckets"]
        adm = next(iter(ws_stats.values()))["admissions"] if ws_stats \
            else {}
        if not (adm.get("predicted", 0) + adm.get("predicted_rejected",
                                                  0)):
            failures.append(
                "warmstart leg: no admission ran the predictor — the "
                "flip cycle measured plain starts twice")
        if not adm.get("plain", 0):
            failures.append(
                "warmstart leg: predictor-off admission did not take "
                "the plain path")
        plane3.leave("m0")
        plane3.leave("m1")
    finally:
        telemetry.configure(enabled=was_enabled)

    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in set(before) | set(after)}
    violations = []
    for entry, delta in sorted(deltas.items()):
        budget = int(per_entry.get(entry, default_budget))
        if delta > budget:
            violations.append({"entry_point": entry, "observed": delta,
                               "budget": budget})
    health_deltas = {k: h_after.get(k, 0) - h_before.get(k, 0)
                     for k in set(h_before) | set(h_after)}
    for entry, delta in sorted(health_deltas.items()):
        budget = int(health_budgets.get(entry, health_default))
        if delta > budget:
            violations.append({"entry_point": f"health:{entry}",
                               "observed": delta, "budget": budget})
    autopilot_deltas = {k: a_after.get(k, 0) - a_before.get(k, 0)
                        for k in set(a_before) | set(a_after)}
    for entry, delta in sorted(autopilot_deltas.items()):
        budget = int(auto_budgets.get(entry, auto_default))
        if delta > budget:
            violations.append({"entry_point": f"autopilot:{entry}",
                               "observed": delta, "budget": budget})
    warmstart_deltas = {k: w_after.get(k, 0) - w_before.get(k, 0)
                        for k in set(w_before) | set(w_after)}
    for entry, delta in sorted(warmstart_deltas.items()):
        budget = int(ws_budgets.get(entry, ws_default))
        if delta > budget:
            violations.append({"entry_point": f"warmstart:{entry}",
                               "observed": delta, "budget": budget})
    report = {
        "serve_rounds": serve_rounds,
        "capacity": capacity,
        "deltas": dict(sorted(deltas.items())),
        "health_deltas": dict(sorted(health_deltas.items())),
        "autopilot_deltas": dict(sorted(autopilot_deltas.items())),
        "warmstart_deltas": dict(sorted(warmstart_deltas.items())),
        "violations": violations,
        "failures": failures,
        "cache": {"hits": plane.cache.hits,
                  "misses": plane.cache.misses},
        "autopilot_cache": {"hits": plane2.cache.hits,
                            "misses": plane2.cache.misses},
    }
    if verbose:
        for v in violations:
            print(f"serving-budget: {v['entry_point']!r} compiled/traced "
                  f"{v['observed']}x across the churn sequence "
                  f"(budget {v['budget']}) — membership changes are "
                  f"retracing")
        for f in failures:
            print(f"serving-budget: {f}")
        if not violations and not failures:
            print("serving-budget: OK — zero excess compiles across "
                  "join/serve/leave/rejoin churn (evict/readmit "
                  "included), across the warm autopilot quality-"
                  "ladder cycle AND across the warm-start predictor "
                  "on/off flip; rejoin was a compile-cache hit")
    return report
