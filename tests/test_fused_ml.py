"""ML-surrogate agents on the fused data plane.

The fused engine consumes any object with the TranscribedOCP surface —
including NARX ML OCPs from `ops/ml_transcription.transcribe_ml`. This
pins the combination the reference runs as its 3-zone data-driven ADMM
benchmark (`examples/three_zone_datadriven_admm/`): learned dynamics per
agent, consensus coupling on the shared control, one jitted program.
"""

import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu.ml import Feature, OutputFeature, SerializedLinReg
from agentlib_mpc_tpu.models.ml_model import MLModel
from agentlib_mpc_tpu.models.model import ModelEquations
from agentlib_mpc_tpu.models.objective import SubObjective
from agentlib_mpc_tpu.models.variables import control_input, parameter, state
from agentlib_mpc_tpu.ops.ml_transcription import transcribe_ml
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    stack_params,
)

DT = 300.0
C = 100000.0


def _surrogate():
    """Exact discrete law: T_next = T + dt/C * (load − Q)."""
    return SerializedLinReg(
        dt=DT,
        inputs={"Q": Feature(name="Q", lag=1),
                "load": Feature(name="load", lag=1)},
        output={"T": OutputFeature(name="T", lag=1,
                                   output_type="difference",
                                   recursive=True)},
        coef=[[-DT / C, DT / C, 0.0]], intercept=[0.0])


class NarxRoom(MLModel):
    inputs = [
        control_input("Q", 0.0, lb=0.0, ub=1000.0, unit="W"),
        control_input("load", 180.0, unit="W"),
    ]
    states = [state("T", 294.15, lb=285.15, ub=310.15, unit="K")]
    parameters = [parameter("r_Q", 1e-4), parameter("T_ref", 293.15)]
    dt = DT
    ml_model_sources = [_surrogate()]

    def setup(self, v):
        eq = ModelEquations()
        eq.objective = SubObjective((v.T - v.T_ref) ** 2, name="track") + \
            SubObjective(v.r_Q * v.Q, name="energy")
        return eq


class TestFusedMLGroup:
    def test_narx_agents_reach_consensus_and_cool(self):
        """Two learned-dynamics rooms agree on a shared cooling power and
        their NARX-predicted temperatures head toward the setpoint."""
        ocp = transcribe_ml(NarxRoom(), ["Q"], N=6, dt=DT)
        group = AgentGroup(
            name="narx_rooms", ocp=ocp, n_agents=2,
            couplings={"Q_shared": "Q"},
            solver_options=SolverOptions(tol=1e-6, max_iter=40))
        engine = FusedADMM(
            [group], FusedADMMOptions(max_iterations=25, rho=1e-3,
                                      abs_tol=1e-3, rel_tol=1e-3))
        thetas = stack_params([
            ocp.default_params(x0=jnp.array([296.15])),
            ocp.default_params(x0=jnp.array([297.15])),
        ])
        state0 = engine.init_state([thetas])
        state1, trajs, stats = engine.step(state0, [thetas])
        assert bool(np.all(np.asarray(stats.local_solves_ok)))
        q = np.asarray(trajs[0]["u"])[:, :, 0]      # (2, N)
        # consensus on the shared cooling power
        np.testing.assert_allclose(q[0], q[1], atol=2.0)
        # warm rooms above T_ref must request cooling
        assert q.mean() > 10.0
        # NARX-predicted temperatures decrease toward the setpoint
        T = np.asarray(trajs[0]["x"])[:, :, 0]      # (2, N+1)
        assert T[0, -1] < T[0, 0] and T[1, -1] < T[1, 0]

    def test_shift_warm_start_works_on_ml_ocp(self):
        ocp = transcribe_ml(NarxRoom(), ["Q"], N=5, dt=DT)
        group = AgentGroup(
            name="narx", ocp=ocp, n_agents=2,
            couplings={"Q_shared": "Q"},
            solver_options=SolverOptions(tol=1e-6, max_iter=30))
        engine = FusedADMM(
            [group], FusedADMMOptions(max_iterations=15, rho=1e-3,
                                      abs_tol=1e-3, rel_tol=1e-3))
        thetas = stack_params([
            ocp.default_params(x0=jnp.array([296.15])),
            ocp.default_params(x0=jnp.array([296.65])),
        ])
        state = engine.init_state([thetas])
        state, _trajs, stats_cold = engine.step(state, [thetas])
        state = engine.shift_state(state)
        _state2, _t2, stats_warm = engine.step(state, [thetas])
        assert int(stats_warm.iterations) <= int(stats_cold.iterations)


class TestMLConfigBridge:
    def test_ml_configs_ride_the_bridge(self):
        """A config whose model block carries ml_model_sources transcribes
        through the NARX path and runs fused — the 3-zone data-driven
        topology as one program."""
        from agentlib_mpc_tpu.parallel.config_bridge import FusedFleet

        def cfg(i, t0):
            return {"id": f"Zone_{i}", "modules": [
                {"module_id": "admm", "type": "admm_local",
                 "optimization_backend": {
                     "type": "jax_admm_ml",
                     "model": {"class": NarxRoom,
                               "ml_model_sources": [_surrogate()]},
                     "solver": {"max_iter": 40, "tol": 1e-6},
                 },
                 "time_step": DT, "prediction_horizon": 6,
                 "max_iterations": 25, "penalty_factor": 1e-3,
                 "states": [{"name": "T", "value": t0}],
                 "couplings": [{"name": "Q", "alias": "Q_shared"}]}]}

        fleet = FusedFleet.from_configs([cfg(0, 296.15), cfg(1, 297.15)])
        assert len(fleet.engine.groups) == 1  # same structure: one group
        out = fleet.step()
        q0 = out["Zone_0"]["u"]["Q"]
        q1 = out["Zone_1"]["u"]["Q"]
        np.testing.assert_allclose(q0, q1, atol=2.0)
        assert q0.mean() > 10.0
        # reference-layout results work for ML agents too
        fleet.advance()
        df = fleet.results("Zone_1")
        assert ("variable", "T") in df.columns
        assert ("variable", "Q") in df.columns
        assert float(df[("variable", "T")].iloc[0]) > 290.0

    def test_per_agent_surrogate_weights_flow_through_theta(self):
        """Same MLModel class, DIFFERENT trained weights per agent: each
        agent must optimize against its OWN surrogate (weights ride
        theta.ml_params; the shared transcription carries structure
        only)."""
        from agentlib_mpc_tpu.parallel.config_bridge import FusedFleet

        def surrogate(c):
            return SerializedLinReg(
                dt=DT,
                inputs={"Q": Feature(name="Q", lag=1),
                        "load": Feature(name="load", lag=1)},
                output={"T": OutputFeature(name="T", lag=1,
                                           output_type="difference",
                                           recursive=True)},
                coef=[[-DT / c, DT / c, 0.0]], intercept=[0.0])

        def cfg(i, c):
            return {"id": f"Z_{i}", "modules": [
                {"module_id": "admm", "type": "admm_local",
                 "optimization_backend": {
                     "type": "jax_admm_ml",
                     "model": {"class": NarxRoom,
                               "ml_model_sources": [surrogate(c)]},
                     "solver": {"max_iter": 40, "tol": 1e-6},
                 },
                 "time_step": DT, "prediction_horizon": 6,
                 "max_iterations": 20, "penalty_factor": 1e-3,
                 "states": [{"name": "T", "value": 297.15}],
                 "couplings": [{"name": "Q", "alias": "Q_shared"}]}]}

        # agent 1's plant has twice the thermal mass: same cooling power
        # moves its temperature half as much
        fleet = FusedFleet.from_configs([cfg(0, C), cfg(1, 2 * C)])
        assert len(fleet.engine.groups) == 1  # same STRUCTURE: one group
        out = fleet.step()
        dT0 = out["Z_0"]["x"][0, 0] - out["Z_0"]["x"][-1, 0]
        dT1 = out["Z_1"]["x"][0, 0] - out["Z_1"]["x"][-1, 0]
        # both consensus-coupled to one Q, so the stiffer plant must cool
        # distinctly less — fails if both agents shared agent 0's weights
        assert dT0 > 1.5 * dT1 > 0.0
