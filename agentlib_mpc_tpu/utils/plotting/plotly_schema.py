"""Structural validator for the plotly figures this package emits.

This environment (and many headless deployments) has no plotly installed,
so the dash/plotly layer (``dashboard.py``) cannot be smoke-tested against
the real library — yet an attribute typo (``line={"colour": ...}``,
``mode="line"``, ``yaxis="y-2"``) would only surface at the user's first
``show_dashboard`` call. This module vendors the *relevant subset* of the
public plotly.js figure schema — attribute names, enum values and value
shapes for the scatter traces and layout keys the builders actually use —
and validates figure structures against it, the same contract
``plotly.graph_objects`` enforces with ``validate=True``.

Scope is deliberately the package's own figure vocabulary (scatter traces,
cartesian axes, margins): it is a golden-structure gate for
``dashboard.py``/``interactive.py`` (reference surface:
``utils/plotting/mpc_dashboard.py``, ``admm_dashboard.py``,
``interactive.py``), not a general plotly replacement. Unknown attributes
FAIL — exactly how an API typo is caught.
"""

from __future__ import annotations

import numbers
import re

__all__ = [
    "SchemaError",
    "validate_trace",
    "validate_layout",
    "validate_figure",
]


class SchemaError(ValueError):
    """A figure structure that plotly would reject (or silently drop)."""


# -- value validators --------------------------------------------------------

_NAMED_COLORS = {
    "black", "white", "red", "green", "blue", "gray", "grey", "orange",
    "purple", "cyan", "magenta", "yellow", "lightgray", "lightgrey",
    "darkgray", "darkgrey", "steelblue", "firebrick", "seagreen",
}
_COLOR_RE = re.compile(
    r"^(#[0-9a-fA-F]{3}|#[0-9a-fA-F]{6}|#[0-9a-fA-F]{8}"
    r"|rgb\(\s*\d{1,3}\s*,\s*\d{1,3}\s*,\s*\d{1,3}\s*\)"
    r"|rgba\(\s*\d{1,3}\s*,\s*\d{1,3}\s*,\s*\d{1,3}\s*,"
    r"\s*(0|1|0?\.\d+|1\.0+)\s*\))$")
# trace-side axis references: "y", "y2", "y3", ... (plotly.js: /^y([2-9]|
# [1-9][0-9]+)?$/ — "y1" is not a valid subplot ref, the first axis is "y")
_TRACE_AXIS_RE = {"x": re.compile(r"^x([2-9]|[1-9]\d+)?$"),
                  "y": re.compile(r"^y([2-9]|[1-9]\d+)?$")}
# layout-side axis container keys: "yaxis", "yaxis2", ...
_LAYOUT_AXIS_RE = re.compile(r"^([xy])axis([2-9]|[1-9]\d+)?$")

_SCATTER_MODE_FLAGS = {"lines", "markers", "text"}
_DASH_STYLES = {"solid", "dot", "dash", "longdash", "dashdot",
                "longdashdot"}


def _is_color(v) -> bool:
    return isinstance(v, str) and (
        v.lower() in _NAMED_COLORS or bool(_COLOR_RE.match(v)))


def _is_num(v) -> bool:
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def _is_array(v) -> bool:
    return hasattr(v, "__len__") and not isinstance(v, (str, dict))


def _check(cond: bool, path: str, msg: str) -> None:
    if not cond:
        raise SchemaError(f"{path}: {msg}")


def _check_mode(v, path):
    _check(isinstance(v, str), path, f"mode must be a string, got {v!r}")
    parts = v.split("+")
    bad = [p for p in parts if p not in _SCATTER_MODE_FLAGS]
    _check(not bad and len(parts) == len(set(parts)), path,
           f"invalid scatter mode {v!r} (flaglist over "
           f"{sorted(_SCATTER_MODE_FLAGS)})")


def _check_enum(allowed):
    def check(v, path):
        _check(v in allowed, path, f"{v!r} not one of {sorted(allowed)}")
    return check


def _check_color(v, path):
    _check(_is_color(v), path, f"{v!r} is not a CSS color plotly accepts "
                               f"(hex / rgb() / rgba() / named)")


def _check_num(v, path):
    _check(_is_num(v), path, f"expected a number, got {v!r}")


def _check_str(v, path):
    _check(isinstance(v, str), path, f"expected a string, got {v!r}")


def _check_bool(v, path):
    _check(isinstance(v, bool), path, f"expected a bool, got {v!r}")


def _check_array(v, path):
    _check(_is_array(v), path, f"expected an array-like, got {type(v)}")


def _check_title(v, path):
    # plotly accepts a plain string (auto-wrapped) or {"text": ...}
    if isinstance(v, str):
        return
    _check(isinstance(v, dict) and set(v) <= {"text", "font", "x", "y"},
           path, f"title must be a string or {{'text': ...}}, got {v!r}")


def _axis_ref_checker(letter):
    def check(v, path):
        _check(isinstance(v, str) and
               bool(_TRACE_AXIS_RE[letter].match(v)), path,
               f"{v!r} is not a valid {letter}-axis reference "
               f"('{letter}', '{letter}2', ...)")
    return check


# -- vendored schema subset --------------------------------------------------

_LINE_SCHEMA = {"color": _check_color, "width": _check_num,
                "dash": _check_enum(_DASH_STYLES), "shape": _check_enum(
                    {"linear", "spline", "hv", "vh", "hvh", "vhv"})}
_MARKER_SCHEMA = {"color": _check_color, "size": _check_num,
                  "symbol": _check_str, "opacity": _check_num}


def _check_nested(schema):
    def check(v, path):
        _check(isinstance(v, dict), path, f"expected a dict, got {v!r}")
        for k, val in v.items():
            _check(k in schema, f"{path}.{k}", "unknown attribute")
            schema[k](val, f"{path}.{k}")
    return check


SCATTER_SCHEMA = {
    "x": _check_array,
    "y": _check_array,
    "mode": _check_mode,
    "name": _check_str,
    "text": lambda v, p: None,
    "showlegend": _check_bool,
    "legendgroup": _check_str,
    "hovertemplate": _check_str,
    "hoverinfo": _check_str,
    "opacity": _check_num,
    "visible": _check_enum({True, False, "legendonly"}),
    "xaxis": _axis_ref_checker("x"),
    "yaxis": _axis_ref_checker("y"),
    "line": _check_nested(_LINE_SCHEMA),
    "marker": _check_nested(_MARKER_SCHEMA),
    "fill": _check_enum({"none", "tozeroy", "tozerox", "tonexty",
                         "tonextx", "toself", "tonext"}),
    "fillcolor": _check_color,
}

def _check_overlaying(v, path):
    ok = isinstance(v, str) and (
        v == "free"
        or (v[:1] in _TRACE_AXIS_RE and
            bool(_TRACE_AXIS_RE[v[0]].match(v))))
    _check(ok, path, f"{v!r} is not a valid overlaying target "
                     f"('free', 'x', 'y', 'y2', ...)")


_AXIS_SCHEMA = {
    "title": _check_title,
    "type": _check_enum({"-", "linear", "log", "date", "category"}),
    "range": _check_array,
    "overlaying": _check_overlaying,
    "side": _check_enum({"left", "right", "top", "bottom"}),
    "showgrid": _check_bool,
    "zeroline": _check_bool,
    "autorange": _check_enum({True, False, "reversed"}),
}

_MARGIN_SCHEMA = {"l": _check_num, "r": _check_num, "t": _check_num,
                  "b": _check_num, "pad": _check_num,
                  "autoexpand": _check_bool}

LAYOUT_SCHEMA = {
    "title": _check_title,
    "height": _check_num,
    "width": _check_num,
    "margin": _check_nested(_MARGIN_SCHEMA),
    "showlegend": _check_bool,
    "hovermode": _check_enum({"x", "y", "closest", False, "x unified",
                              "y unified"}),
    "template": lambda v, p: None,
    "legend": lambda v, p: _check(isinstance(v, dict), p,
                                  f"expected a dict, got {v!r}"),
    "xaxis_title": _check_title,   # magic-underscore shorthands plotly
    "yaxis_title": _check_title,   # expands to <axis>.title
}

TRACE_SCHEMAS = {"scatter": SCATTER_SCHEMA}


# -- public API --------------------------------------------------------------

def validate_trace(trace_type: str, attrs: dict) -> None:
    """Validate one trace's attributes; raises :class:`SchemaError` on an
    attribute plotly's scatter schema does not define or a value outside
    its enum/shape."""
    _check(trace_type in TRACE_SCHEMAS, trace_type,
           f"unsupported trace type (validator covers "
           f"{sorted(TRACE_SCHEMAS)})")
    schema = TRACE_SCHEMAS[trace_type]
    for k, v in attrs.items():
        _check(k in schema, f"{trace_type}.{k}", "unknown attribute")
        schema[k](v, f"{trace_type}.{k}")


def validate_layout(attrs: dict) -> None:
    """Validate layout attributes, including ``xaxis``/``yaxisN`` axis
    containers and plotly's ``xaxis_title``-style magic underscores."""
    for k, v in attrs.items():
        if _LAYOUT_AXIS_RE.match(k):
            _check_nested(_AXIS_SCHEMA)(v, f"layout.{k}")
            continue
        _check(k in LAYOUT_SCHEMA, f"layout.{k}", "unknown attribute")
        LAYOUT_SCHEMA[k](v, f"layout.{k}")


def validate_figure(fig: dict) -> None:
    """Validate a whole figure dict ``{"data": [...], "layout": {...}}``:
    every trace, the layout, and the cross-references — a trace pointing
    at ``yaxis="y2"`` requires a ``layout.yaxis2`` definition (plotly
    silently renders such traces on a missing axis; here it fails)."""
    _check(isinstance(fig, dict) and set(fig) <= {"data", "layout"},
           "figure", f"expected {{'data', 'layout'}}, got {sorted(fig)}")
    layout = fig.get("layout", {})
    validate_layout(layout)
    for i, trace in enumerate(fig.get("data", [])):
        trace = dict(trace)
        ttype = trace.pop("type", "scatter")
        validate_trace(ttype, trace)
        for letter in ("x", "y"):
            ref = trace.get(f"{letter}axis")
            if ref and ref != letter:  # non-default axis must exist
                key = f"{letter}axis{ref[1:]}"
                _check(key in layout, f"data[{i}].{letter}axis",
                       f"references {ref!r} but layout has no {key!r}")
