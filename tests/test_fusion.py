"""Analytic fusion planner + the fused-IPM knob (ISSUE 18).

Three layers under test. (1) The planner: :func:`plan_fusion` must rank
every contiguous merge of the observed stage pipeline by modeled
dispatch-overhead savings, charge loop-carried boundaries by the trip
budget, refuse candidates the memory certifier proves over capacity,
and stay honest on unannotated/untraceable programs. (2) The solver
knob: ``SolverOptions.fusion="off"`` materializes the staged reference
program via ``stage_boundary`` — and the ISSUE acceptance row:
fixed-iteration results are **bitwise identical** fused vs staged, for
the tracker and the LinearRCZone menu QP, single-device and on the
8-virtual-device mesh. (3) ``fusion="require"``: the engine refuses to
build unless the fused program is certified equivalent to its staged
twin (identical collective-schedule digest, memory certificate within
the plan's projected peak-HBM bound), landing the proved
:class:`FusionPlan` on the engine.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.lint.jaxpr import fusion as fusion_mod
from agentlib_mpc_tpu.lint.jaxpr.fusion import (
    DISPATCH_OVERHEAD_US,
    FusionCandidate,
    FusionPlan,
    plan_fusion,
)
from agentlib_mpc_tpu.lint.jaxpr.memory import MemoryBudgetExceeded
from agentlib_mpc_tpu.ops.solver import SolverOptions, solve_nlp
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.parallel import fleet_mesh
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    stack_params,
)
from agentlib_mpc_tpu.telemetry import profiler

from conftest import make_tracker_model  # noqa: E402


def _staged_two_phase(a):
    with profiler.phase_scope("factor"):
        b = a @ a
    with profiler.phase_scope("resolve"):
        c = b @ a
    return jnp.sum(c)


class TestPlannerUnits:
    def test_two_phase_merge_planned_and_charged_by_trips(self):
        x = jnp.ones((32, 32))
        plan = plan_fusion(_staged_two_phase, x, while_trips=4)
        assert plan.status == "planned"
        (cand,) = plan.candidates
        assert cand.phases == ("factor", "resolve")
        assert cand.dispatches_saved_per_iteration == 1
        assert cand.dispatches_saved_per_round == 4
        assert cand.savings_us == 4 * DISPATCH_OVERHEAD_US
        # the boundary's HBM round-trip is kept on-chip every trip
        assert cand.savings_bytes > 0
        # the fused trace's live-range peak bounds the merge from above
        assert plan.projected_peak_bytes == plan.certified_peak_bytes
        assert plan.top is cand

    def test_full_pipeline_merge_outranks_pairs(self):
        def staged3(a):
            with profiler.phase_scope("eval_jac"):
                j = (a * 2.0) @ a
            with profiler.phase_scope("factor"):
                b = j @ a
            with profiler.phase_scope("resolve"):
                c = b @ a
            return jnp.sum(c)

        plan = plan_fusion(staged3, jnp.ones((32, 32)), while_trips=2)
        assert plan.status == "planned"
        # every contiguous run of the 3 observed stages is a candidate
        assert len(plan.candidates) == 3
        assert plan.top.phases == ("eval_jac", "factor", "resolve")
        assert plan.top.dispatches_saved_per_round == 2 * 2

    def test_missing_trip_budget_noted_and_guessed(self):
        plan = plan_fusion(_staged_two_phase, jnp.ones((8, 8)))
        assert plan.status == "planned"
        assert any("unbounded" in n for n in plan.notes)
        assert plan.while_trips >= 1

    def test_unannotated_program_is_empty_not_planned(self):
        plan = plan_fusion(lambda x: jnp.sum(x * 2.0), jnp.ones((4,)))
        assert plan.status == "empty"
        assert plan.top is None and plan.savings_bytes == 0
        assert any("nothing to merge" in n for n in plan.notes)

    def test_untraceable_program_is_unknown(self):
        def broken(x):
            raise RuntimeError("untraceable")

        plan = plan_fusion(broken, jnp.ones((3,)))
        assert plan.status == "unknown"
        assert any("planner error" in n for n in plan.notes)

    def test_over_capacity_candidates_refused(self):
        plan = plan_fusion(_staged_two_phase, jnp.ones((32, 32)),
                           while_trips=4, hbm_bytes=16)
        assert plan.status == "refused"
        assert plan.top is None
        assert all(c.refused for c in plan.candidates)
        assert all("over" in c.reason for c in plan.candidates)
        assert any("over capacity" in n for n in plan.notes)
        # nothing admissible: the bound falls back to the staged peak
        assert plan.projected_peak_bytes == plan.certified_peak_bytes

    def test_plan_artifact_is_json_serializable(self):
        plan = plan_fusion(_staged_two_phase, jnp.ones((8, 8)),
                           while_trips=2)
        d = plan.as_dict()
        assert d["status"] == "planned"
        assert d["top"] == "factor+resolve"
        assert d["while_trips"] == 2
        json.dumps(d)      # the --emit-metrics embedding must not choke


OPTS = FusedADMMOptions(max_iterations=8, rho=2.0)

Tracker = make_tracker_model()


def _tracker_ocp():
    return transcribe(Tracker(), ["u"], N=4, dt=300.0,
                      method="multiple_shooting")


def _menu_ocp():
    from agentlib_mpc_tpu.lint.jaxpr.examples import build_example

    return build_example("LinearRCZone/colloc-d1")


def _engine(ocp, couplings, n_agents, mesh, fusion):
    group = AgentGroup(
        name="fusion-fleet", ocp=ocp, n_agents=n_agents,
        couplings=couplings,
        solver_options=SolverOptions(max_iter=25, fusion=fusion),
        # solver routing is orthogonal to stage fusion — skip the LQ
        # probe so the builds stay cheap
        qp_fast_path="off")
    thetas = stack_params([ocp.default_params()
                           for _ in range(n_agents)])
    return FusedADMM([group], OPTS, mesh=mesh), thetas


def _assert_trees_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestSolverFusionKnob:
    def test_bogus_mode_rejected_with_the_strings_hint(self):
        ocp = _tracker_ocp()
        theta = ocp.default_params()
        lb, ub = ocp.bounds(theta)
        with pytest.raises(ValueError, match="fusion must be"):
            solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta, lb, ub,
                      SolverOptions(max_iter=5, fusion=True))

    def test_staged_solve_is_bitwise_identical_to_fused(self):
        """The solver-level half of the acceptance row: the staged
        program differs from the fused one ONLY by optimization
        barriers, so fixed-iteration results agree bit for bit."""
        ocp = _tracker_ocp()
        theta = ocp.default_params(p=jnp.array([2.0]))
        lb, ub = ocp.bounds(theta)
        w0 = ocp.initial_guess(theta)
        res = {}
        for mode in ("auto", "off"):
            res[mode] = solve_nlp(
                ocp.nlp, w0, theta, lb, ub,
                SolverOptions(max_iter=25, fusion=mode))
        _assert_trees_identical(res["auto"], res["off"])
        assert int(res["auto"].stats.iterations) == \
            int(res["off"].stats.iterations)


class TestFusedUnfusedIdentity:
    """The engine-level acceptance row: fixed-iteration rounds of the
    fused engine and its staged twin are numerically identical — for
    both gate workloads, single-device and on the virtual mesh."""

    @pytest.mark.parametrize("workload", ["tracker", "menu"])
    @pytest.mark.parametrize("on_mesh", [False, True],
                             ids=["single-device", "mesh8"])
    def test_two_rounds_identical(self, workload, on_mesh,
                                  eight_devices):
        if workload == "tracker":
            ocp, couplings = _tracker_ocp(), {"shared_u": "u"}
        else:
            ocp, couplings = _menu_ocp(), {"Q_shared": "Q"}
        mesh = fleet_mesh(devices=eight_devices) if on_mesh else None
        n_agents = 8 if on_mesh else 2
        outs = {}
        for mode in ("auto", "off"):
            engine, thetas = _engine(ocp, couplings, n_agents, mesh,
                                     mode)
            state = engine.init_state([thetas])
            state, trajs1, stats1 = engine.step(state, [thetas])
            state, trajs2, stats2 = engine.step(state, [thetas])
            outs[mode] = (state, trajs1, stats1, trajs2, stats2)
        _assert_trees_identical(outs["auto"], outs["off"])


class TestRequireMode:
    """``fusion="require"``: build-time staged-twin equivalence proof,
    the proved plan on the engine, and both refusal seams."""

    def test_mesh_build_proves_equivalence_and_lands_plan(
            self, eight_devices):
        engine, _ = _engine(_tracker_ocp(), {"shared_u": "u"}, 4,
                            fleet_mesh(devices=eight_devices[:4]),
                            "require")
        plan = engine.fusion_plan
        assert isinstance(plan, FusionPlan)
        assert plan.status == "planned"
        # the headline merge: the whole IPM stage pipeline, one program
        assert plan.top is not None
        assert len(plan.top.phases) >= 2
        assert plan.savings_bytes > 0
        assert plan.while_trips == OPTS.max_iterations
        # the digest identity held (a mismatch would have raised) ...
        assert engine.collective_schedule_digest is not None
        # ... and the build-time memory certificate sits within the
        # plan's projected peak-HBM bound
        mem = engine.memory_certificate
        assert mem is not None and mem.status == "proved"
        assert mem.peak_bytes <= plan.projected_peak_bytes

    def test_single_device_build_lands_plan_too(self):
        engine, _ = _engine(_tracker_ocp(), {"shared_u": "u"}, 2, None,
                            "require")
        assert engine.fusion_plan is not None
        assert engine.fusion_plan.status == "planned"

    def test_unmodelable_round_refuses_the_build(self, monkeypatch):
        monkeypatch.setattr(
            fusion_mod, "plan_fusion",
            lambda *a, **k: FusionPlan(status="unknown",
                                       notes=("stubbed",)))
        with pytest.raises(ValueError, match="could not model"):
            _engine(_tracker_ocp(), {"shared_u": "u"}, 2, None,
                    "require")

    def test_peak_over_projected_bound_refuses_the_build(
            self, monkeypatch):
        """A fused step whose certified peak exceeds the plan's
        projection must not build — the certificate, not the model,
        has the last word."""
        tiny = FusionCandidate(
            name="stub", phases=("factor", "resolve"),
            dispatches_saved_per_iteration=1,
            dispatches_saved_per_round=8, savings_us=560.0,
            savings_bytes=100, projected_peak_bytes=1)
        monkeypatch.setattr(
            fusion_mod, "plan_fusion",
            lambda *a, **k: FusionPlan(status="planned",
                                       candidates=(tiny,)))
        with pytest.raises(MemoryBudgetExceeded,
                           match="projected peak-HBM bound"):
            _engine(_tracker_ocp(), {"shared_u": "u"}, 2, None,
                    "require")
