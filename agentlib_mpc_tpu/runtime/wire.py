"""Wire serialization of AgentVariables for cross-process/network comms.

Counterpart of the reference's orjson-serialized payloads
(``data_structures/admm_datatypes.py:334-363``; AgentVariable JSON in the
multiprocessing/MQTT communicators): numpy-aware JSON with a 4-byte
length-prefixed framing for stream transports. JSON stays at the MAS
boundary only — on-device data never crosses it (SURVEY.md §2.8).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Optional

import numpy as np

from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source

_LEN = struct.Struct("!I")


class FramedSocket:
    """Socket wrapper serializing sends: ``sendall`` is not atomic for
    payloads beyond the send buffer, so concurrent writers (relay threads,
    env thread + reader-thread callbacks) would interleave bytes and
    desync the length-prefixed stream."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = threading.Lock()

    def send_frame(self, payload: bytes) -> None:
        with self._send_lock:
            send_frame(self.sock, payload)

    def recv_frame(self) -> Optional[bytes]:
        # single reader per socket by design; no lock needed
        return recv_frame(self.sock)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _jsonable(value):
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, (np.floating, np.integer)):
        return value.item()
    if hasattr(value, "tolist"):  # jax arrays
        return np.asarray(value).tolist()
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return value


def var_to_wire(var: AgentVariable) -> bytes:
    doc = {
        "name": var.name,
        "value": _jsonable(var.value),
        "alias": var.alias,
        "timestamp": var.timestamp,
        "shared": var.shared,
        "source": {"agent_id": var.source.agent_id,
                   "module_id": var.source.module_id},
    }
    return json.dumps(doc).encode()


def var_from_wire(payload: bytes) -> AgentVariable:
    doc = json.loads(payload.decode())
    src = doc.get("source") or {}
    var = AgentVariable(
        name=doc["name"], value=doc.get("value"),
        alias=doc.get("alias", doc["name"]),
        shared=bool(doc.get("shared", True)),
        source=Source(agent_id=src.get("agent_id"),
                      module_id=src.get("module_id")))
    var.timestamp = doc.get("timestamp", 0.0)
    return var


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """One length-prefixed frame; None on EOF."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
