"""Padded tenant slots over one fused engine.

A :class:`SlotPlane` owns ONE single-group
:class:`~agentlib_mpc_tpu.parallel.fused_admm.FusedADMM` engine built at
a fixed, pre-padded capacity (``pad_group_to_devices`` rounding: a
multiple of the device count so the agent axis shards instead of
replicating). Tenants occupy slots; free slots are padding lanes — they
solve the uniform dense math but are masked out of every consensus
mean, multiplier update, residual norm and health flag (the
``pad_group_to_devices`` contract, now DYNAMIC):

* **join** — take a free slot, splice the tenant's parameters and a
  fresh warm start into that lane (one jitted lane-splice with a TRACED
  lane index — no retrace per slot), flip the slot's mask bit on;
* **leave** — flip the bit off. The lane keeps solving its last
  parameters as padding; nothing changes shape;
* **serve** — one fused ADMM round over the whole batch with the
  current mask as a traced input.

Because capacity, shapes and dtypes never change across join/leave, the
warm executable serves every membership state of the bucket — the
``[serving]`` retrace budget pins this at zero warm retraces across a
scripted join→serve→leave→rejoin churn sequence.

The same contract holds on a device mesh: a ``ServingPlane(mesh=...)``
builds its bucket engines sharded (``FusedADMM(mesh=...)``) at
capacities rounded to ``multihost.serving_slot_multiple(mesh)`` — every
capacity divides the mesh, so the slot plane's lane splices and mask
flips land on a shard_map'ed step without any shape change, and churn
stays zero-retrace on the sharded engine too (the ``[mesh]`` budget's
serving leg pins it).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def tree_repeat(tree, n: int):
    """Stack one agent row into an (n, ...) batch — the padding
    semantics of ``pad_group_to_devices``: every lane starts as a copy
    of the seed tenant. ONE definition, shared by the slot plane's
    theta batch and the plane's engine-warmup batch so the two can
    never diverge."""
    return jax.tree.map(
        lambda leaf: jnp.repeat(jnp.asarray(leaf)[None], n, axis=0), tree)


def tree_row(batch, i: int):
    """Extract agent row ``i`` from a batched pytree (the inverse seam:
    tenant migration during capacity growth)."""
    return jax.tree.map(lambda leaf: leaf[i], batch)


class RoundHandle(NamedTuple):
    """An in-flight (possibly not yet materialized) served round."""

    trajs: object            # per-group trajectory pytrees (device)
    stats: object            # IterationStats (device)
    #: (tenant_id, slot) snapshot at launch — results are decoded
    #: against THIS membership, not the one at materialize time
    served: tuple
    #: robust rounds only (ISSUE 14): the non-anticipativity
    #: projection's actuated controls, (capacity, S, n_u) on device —
    #: group-identical across a node group's branches by construction
    u0: object = None


class _SlotBookkeeping:
    """The occupancy surface BOTH slot planes share (ISSUE 14 review:
    one definition — a slot-semantics fix must never apply to flat
    buckets but miss robust ones, or vice versa). Subclasses own
    ``capacity``, ``slots``, ``_slot_of`` and ``mask``."""

    @property
    def n_active(self) -> int:
        return int(self.mask.sum())

    @property
    def free_slots(self) -> int:
        return self.capacity - self.n_active

    def slot_of(self, tenant_id: str) -> "int | None":
        return self._slot_of.get(tenant_id)

    @property
    def tenants(self) -> tuple:
        return tuple(t for t in self.slots if t is not None)

    def _alloc_slot(self, tenant_id: str) -> int:
        """Find a free slot for a new tenant (duplicate ids and full
        planes raise — the plane grows capacity on full)."""
        if tenant_id in self._slot_of:
            raise ValueError(f"tenant {tenant_id!r} already admitted")
        try:
            return self.slots.index(None)
        except ValueError:
            raise ValueError(
                f"no free slot (capacity {self.capacity})") from None

    def _bind_slot(self, slot: int, tenant_id: str) -> None:
        self.slots[slot] = tenant_id
        self._slot_of[tenant_id] = slot
        self.mask[slot] = True

    def evict(self, tenant_id: str) -> int:
        """Free a tenant's slot (mask off; the lane becomes padding,
        keeping its last parameters — shapes never change)."""
        slot = self._slot_of.pop(tenant_id)
        self.slots[slot] = None
        self.mask[slot] = False
        return slot

    def restore_occupancy(self, slots: "list[str | None]") -> None:
        """Overwrite the occupancy bookkeeping wholesale — the
        checkpoint-restore seam. A restored plane must reproduce the
        SAVED slot layout (gaps included) because the per-lane state
        arrays restored next to it are indexed by those exact slots;
        sequential :meth:`admit` calls would compact the gaps away."""
        if len(slots) != self.capacity:
            raise ValueError(
                f"occupancy snapshot has {len(slots)} slots for a "
                f"capacity-{self.capacity} plane")
        self.slots = list(slots)
        self._slot_of = {t: s for s, t in enumerate(slots)
                         if t is not None}
        self.mask = np.asarray([t is not None for t in slots],
                               dtype=bool)


class SlotPlane(_SlotBookkeeping):
    """Slot bookkeeping + lane splicing for one bucket's fused engine.

    ``engine`` must be a single-group :class:`FusedADMM` (the serving
    plane builds one engine per structure bucket); ``theta0`` seeds the
    padding lanes' parameters.
    """

    def __init__(self, engine, ocp, theta0, shift_between_rounds=True):
        if len(engine.groups) != 1:
            raise ValueError(
                "SlotPlane serves single-group engines (one structure "
                f"bucket per plane); got {len(engine.groups)} groups")
        self.engine = engine
        self.ocp = ocp
        self.capacity = engine.groups[0].n_agents
        self.shift_between_rounds = bool(shift_between_rounds)
        #: slot -> tenant_id or None
        self.slots: list = [None] * self.capacity
        self._slot_of: dict = {}
        self.mask = np.zeros((self.capacity,), dtype=bool)
        # padding lanes repeat the seed tenant's parameters (the
        # pad_group_to_devices recipe: uniform dense math, masked out)
        self.theta_batch = tree_repeat(theta0, self.capacity)
        self.rounds_served = 0

        # jitted lane splices with a TRACED lane index: one trace serves
        # every slot, so admissions never retrace. The compiled helpers
        # are cached ON the engine object — a retired bucket's engine
        # comes back from the compile cache with its warm splice traces,
        # so a rejoin-after-retirement is trace-free end to end.
        helpers = engine.__dict__.get("_serving_helpers")
        if helpers is None:
            ocp_ = ocp

            def reset_lane(state, lane, theta_row):
                """Fresh warm start for a newly-admitted tenant's lane:
                the OCP initial guess, zero equality duals, centered
                inequality duals, zero multipliers — a recycled slot
                must not leak the previous tenant's iterate."""
                w = (state.w[0].at[lane].set(
                    ocp_.initial_guess(theta_row)),)
                y = (state.y[0].at[lane].set(0.0),)
                z = (state.z[0].at[lane].set(0.1),)
                lam = {a: (pieces[0].at[lane].set(0.0),)
                       for a, pieces in state.lam.items()}
                ex_diff = {a: (pieces[0].at[lane].set(0.0),)
                           for a, pieces in state.ex_diff.items()}
                return state._replace(w=w, y=y, z=z, lam=lam,
                                      ex_diff=ex_diff)

            helpers = {
                "splice_theta": jax.jit(
                    lambda batch, lane, row: jax.tree.map(
                        lambda b, r: b.at[lane].set(r), batch, row)),
                "reset_lane": jax.jit(reset_lane),
                # the fresh-state TEMPLATE, built once per engine (the
                # eager init_state cost is paid at the cold build, not
                # per slot-plane). Later slot planes copy it: every
                # admitted lane is re-spliced by reset_lane anyway, so
                # the template's padding values are immaterial — it only
                # has to be finite and shape-true.
                "state_template": engine.init_state([self.theta_batch]),
            }
            engine.__dict__["_serving_helpers"] = helpers
        self._splice_theta = helpers["splice_theta"]
        self._reset_lane = helpers["reset_lane"]
        # per-plane COPY: with a donated engine the first step consumes
        # its input state's buffers — the cached template must never be
        # the object handed to step
        state = jax.tree.map(jnp.copy, helpers["state_template"])
        if getattr(engine, "mesh", None) is not None:
            # pre-place state and thetas on the engine's mesh so the
            # FIRST served round already runs the sharded-input
            # executable — without this the bucket would compile (and
            # keep) two step variants, one for the unsharded template
            # inputs and one for everything after round 1
            state, (self.theta_batch,) = engine.shard_args(
                engine.mesh, state, [self.theta_batch])
        self.state = state

    # -- membership (occupancy surface shared via _SlotBookkeeping) -----------

    def admit(self, tenant_id: str, theta_row) -> int:
        """Place a tenant into a free slot; returns the slot index.
        Raises ``ValueError`` when full (the plane grows capacity) or on
        a duplicate id."""
        slot = self._alloc_slot(tenant_id)
        lane = jnp.asarray(slot, jnp.int32)
        self.theta_batch = self._splice_theta(self.theta_batch, lane,
                                              theta_row)
        self.state = self._reset_lane(self.state, lane, theta_row)
        self._bind_slot(slot, tenant_id)
        return slot

    def update_theta(self, tenant_id: str, theta_row) -> None:
        """Splice a tenant's fresh parameters (its per-request state /
        disturbance data) into its lane."""
        slot = self._slot_of[tenant_id]
        self.theta_batch = self._splice_theta(
            self.theta_batch, jnp.asarray(slot, jnp.int32), theta_row)

    # -- serving --------------------------------------------------------------

    def launch_round(self) -> RoundHandle:
        """Enqueue one fused ADMM round for the current membership and
        return immediately (JAX dispatch is asynchronous; materialize
        the handle to read results). The state threads linearly through
        here — with a donated engine the previous state's buffers are
        consumed by the step, which is why no other reference to it may
        survive."""
        served = tuple((t, s) for s, t in enumerate(self.slots)
                       if t is not None)
        state, trajs, stats = self.engine.step(
            self.state, [self.theta_batch],
            active=[jnp.asarray(self.mask)])
        self.state = self.engine.shift_state(state) \
            if self.shift_between_rounds else state
        self.rounds_served += 1
        return RoundHandle(trajs=trajs, stats=stats, served=served)

    def materialize(self, handle: RoundHandle) -> dict:
        """Block on a round's outputs and decode per-tenant results:
        ``tenant_id -> {"u0": {name: float}, "traj": {"u": row},
        "stats": {...}}`` — the result-dict shape
        :func:`~agentlib_mpc_tpu.resilience.guard.check_result`
        consumes."""
        u = np.asarray(handle.trajs[0]["u"])      # (capacity, N, n_u)
        stats = handle.stats
        converged = bool(stats.converged)
        iterations = int(stats.iterations)
        # per-lane quarantine attribution: the engine substitutes a sick
        # lane's iterate, so its decoded u comes back FINITE — without
        # this column a persistently-NaN tenant looks healthy forever
        # (the serving health ledger consumes it)
        lane_q = None
        if stats.lane_quarantined is not None:
            lane_q = np.asarray(stats.lane_quarantined[0])
        names = list(self.ocp.control_names)
        out = {}
        for tenant_id, slot in handle.served:
            u_row = u[slot]
            out[tenant_id] = {
                "u0": {nm: float(u_row[0, k])
                       for k, nm in enumerate(names)},
                "traj": {"u": u_row},
                "stats": {
                    # per-tenant success = this lane produced a finite
                    # plan (engine-level quarantine substitutes diverged
                    # lanes); fleet-level convergence rides along for
                    # observability and the round artifact
                    "success": bool(np.isfinite(u_row).all()),
                    "round_converged": converged,
                    "iterations": iterations,
                    "quarantined_iters": (int(lane_q[slot])
                                          if lane_q is not None else 0),
                },
            }
        return out


class ScenarioSlotPlane(_SlotBookkeeping):
    """Padded tenant slots over one :class:`~agentlib_mpc_tpu.scenario.
    fleet.ScenarioFleet` engine — the scenario-lifted sibling of
    :class:`SlotPlane` (ISSUE 14: "scenario buckets get slots/health/
    checkpoint").

    Same contract, one axis wider: a lane is one ROBUST tenant whose
    per-round data is an (S, ...)-leading per-branch parameter stack
    (``scenario.generate`` builds it), solved as S disturbance branches
    inside the fused robust round. Join/leave/update are the same
    traced lane splices and mask flips — membership is data, never
    structure, so churn on a scenario bucket is zero-retrace exactly
    like the flat plane (the ``[scenario.survive]`` budget's serving
    sibling is pinned by the ``[serving]`` gate family).

    Decoded results: ``u0`` is the non-anticipativity projection's
    first-interval command for branch 0 (the nominal-branch convention
    of ``ensemble_thetas`` — for a fan tree every branch of the root
    group carries the identical row by construction); ``traj`` carries
    all S branch trajectories; ``stats.quarantined_iters`` is the
    worst branch's per-lane quarantine attribution (one persistently
    sick branch marks the tenant sick — the health ledger's third
    sickness signal on robust tenants) with the full per-branch
    breakdown in ``stats.branch_quarantined``."""

    def __init__(self, engine, ocp, theta0, shift_between_rounds=True):
        self.engine = engine
        self.ocp = ocp
        self.capacity = engine.group.n_agents
        self.n_scenarios = engine.S
        self.shift_between_rounds = bool(shift_between_rounds)
        self.slots: list = [None] * self.capacity
        self._slot_of: dict = {}
        self.mask = np.zeros((self.capacity,), dtype=bool)
        self.theta_batch = tree_repeat(theta0, self.capacity)
        self.rounds_served = 0

        helpers = engine.__dict__.get("_serving_helpers")
        if helpers is None:
            ocp_ = ocp

            def reset_lane(state, lane, theta_row):
                """Fresh warm start for a newly-admitted robust
                tenant's lane: per-branch OCP initial guesses, zeroed
                multipliers on BOTH coupling families — a recycled slot
                must not leak the previous tenant's iterates on any
                branch."""
                w = state.w.at[lane].set(
                    jax.vmap(ocp_.initial_guess)(theta_row))
                y = state.y.at[lane].set(0.0)
                z = state.z.at[lane].set(0.1)
                nu = state.nu.at[lane].set(0.0)
                na = state.na_target.at[lane].set(0.0)
                lam = {a: leaf.at[lane].set(0.0)
                       for a, leaf in state.lam.items()}
                return state._replace(w=w, y=y, z=z, nu=nu,
                                      na_target=na, lam=lam)

            helpers = {
                "splice_theta": jax.jit(
                    lambda batch, lane, row: jax.tree.map(
                        lambda b, r: b.at[lane].set(r), batch, row)),
                "reset_lane": jax.jit(reset_lane),
                "state_template": engine.init_state(self.theta_batch),
            }
            engine.__dict__["_serving_helpers"] = helpers
        self._splice_theta = helpers["splice_theta"]
        self._reset_lane = helpers["reset_lane"]
        state = jax.tree.map(jnp.copy, helpers["state_template"])
        if getattr(engine, "mesh", None) is not None:
            state, self.theta_batch = engine.shard_args(
                engine.mesh, state, self.theta_batch)
        self.state = state

    # -- membership (occupancy surface shared via _SlotBookkeeping) -----------

    def _check_branch_stack(self, tenant_id: str, theta_row) -> None:
        s_lead = int(jnp.asarray(
            jax.tree.leaves(theta_row)[0]).shape[0])
        if s_lead != self.n_scenarios:
            raise ValueError(
                f"robust tenant {tenant_id!r} submitted a "
                f"{s_lead}-branch theta stack for a "
                f"{self.n_scenarios}-scenario bucket — build it with "
                f"scenario.generate for the bucket's tree")

    def admit(self, tenant_id: str, theta_row) -> int:
        self._check_branch_stack(tenant_id, theta_row)
        slot = self._alloc_slot(tenant_id)
        lane = jnp.asarray(slot, jnp.int32)
        self.theta_batch = self._splice_theta(self.theta_batch, lane,
                                              theta_row)
        self.state = self._reset_lane(self.state, lane, theta_row)
        self._bind_slot(slot, tenant_id)
        return slot

    def update_theta(self, tenant_id: str, theta_row) -> None:
        slot = self._slot_of[tenant_id]
        self._check_branch_stack(tenant_id, theta_row)
        self.theta_batch = self._splice_theta(
            self.theta_batch, jnp.asarray(slot, jnp.int32), theta_row)

    # -- serving --------------------------------------------------------------

    def launch_round(self) -> RoundHandle:
        served = tuple((t, s) for s, t in enumerate(self.slots)
                       if t is not None)
        state, trajs, stats = self.engine.step(
            self.state, self.theta_batch,
            active=jnp.asarray(self.mask))
        u0 = self.engine.actuated_u0(state)
        self.state = self.engine.shift_state(state) \
            if self.shift_between_rounds else state
        self.rounds_served += 1
        return RoundHandle(trajs=trajs, stats=stats, served=served,
                           u0=u0)

    def materialize(self, handle: RoundHandle) -> dict:
        u = np.asarray(handle.trajs["u"])     # (capacity, S, N, n_u)
        u0 = np.asarray(handle.u0)            # (capacity, S, n_u)
        stats = handle.stats
        converged = bool(stats.converged)
        iterations = int(stats.iterations)
        na_spread = float(stats.na_spread)
        lane_q = None
        if stats.lane_quarantined is not None:
            lane_q = np.asarray(stats.lane_quarantined)  # (cap, S)
        names = list(self.ocp.control_names)
        out = {}
        for tenant_id, slot in handle.served:
            u_lane = u[slot]                  # (S, N, n_u)
            u0_row = u0[slot, 0]              # nominal-branch command
            branch_q = (lane_q[slot].tolist() if lane_q is not None
                        else [0] * self.n_scenarios)
            out[tenant_id] = {
                "u0": {nm: float(u0_row[k])
                       for k, nm in enumerate(names)},
                "traj": {"u": u_lane},
                "stats": {
                    "success": bool(np.isfinite(u_lane).all()
                                    and np.isfinite(u0_row).all()),
                    "round_converged": converged,
                    "iterations": iterations,
                    "na_spread": na_spread,
                    # worst branch: ONE persistently-quarantined
                    # branch marks the robust tenant sick (the health
                    # ladder's is_sick_result consumes this), with the
                    # per-branch attribution alongside
                    "quarantined_iters": int(max(branch_q)),
                    "branch_quarantined": branch_q,
                },
            }
        return out
