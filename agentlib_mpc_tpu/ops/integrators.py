"""Fixed-step ODE integrators as jit-friendly scans.

TPU-native replacement for the CVODES/IDAS integrators the reference drives
through ``ca.integrator`` (``agentlib_mpc/models/casadi_model.py:402-447``;
multiple-shooting integrator choice euler/rk/cvodes at
``optimization_backends/casadi_/basic.py:450-476``). Explicit euler and RK4
cover the reference's fast paths; an implicit-midpoint method with a fixed
Newton iteration covers moderately stiff plants while staying
shape-static and differentiable (no adaptive step control inside jit).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

ODE = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]  # f(x, t) -> dx/dt


def euler_step(f: ODE, x, t, h):
    return x + h * f(x, t)


def rk4_step(f: ODE, x, t, h):
    k1 = f(x, t)
    k2 = f(x + 0.5 * h * k1, t + 0.5 * h)
    k3 = f(x + 0.5 * h * k2, t + 0.5 * h)
    k4 = f(x + h * k3, t + h)
    return x + (h / 6.0) * (k1 + 2.0 * k2 + 2.0 * k3 + k4)


def implicit_midpoint_step(f: ODE, x, t, h, newton_iters: int = 5):
    """Implicit midpoint rule, solved with a fixed number of Newton steps.

    A-stable: suitable for the stiff building-physics plants the reference
    hands to CVODES. The Newton loop is a lax.fori_loop with a dense linear
    solve on the (small) state dimension.
    """

    def residual(x_next):
        xm = 0.5 * (x + x_next)
        return x_next - x - h * f(xm, t + 0.5 * h)

    return _newton_solve(residual, x + h * f(x, t), newton_iters, reg=1e-10)


def _newton_solve(residual, x_guess, iters: int = 6, reg: float = 1e-12):
    """Fixed-iteration Newton on a small dense system (shape-static)."""
    n = x_guess.shape[0]
    eye = jnp.eye(n, dtype=x_guess.dtype)
    jac = jax.jacfwd(residual)

    def body(_, xk):
        r = residual(xk)
        J = jac(xk)
        dx = jnp.linalg.solve(J + reg * eye, -r)
        return xk + dx

    return jax.lax.fori_loop(0, iters, body, x_guess)


# TR-BDF2 constants (Bank et al.; error pair per Hosea & Shampine 1996).
_TRBDF2_GAMMA = 2.0 - 2.0 ** 0.5          # γ = 2 - √2
_TRBDF2_W = 2.0 ** 0.5 / 4.0              # w = √2 / 4
_TRBDF2_D = _TRBDF2_GAMMA / 2.0           # diagonal DIRK coefficient γ/2
#: 2nd-order weights b and embedded 3rd-order weights b̂ of the DIRK tableau
_TRBDF2_B = (_TRBDF2_W, _TRBDF2_W, _TRBDF2_D)
_TRBDF2_BHAT = ((1.0 - _TRBDF2_W) / 3.0, (3.0 * _TRBDF2_W + 1.0) / 3.0,
                _TRBDF2_D / 3.0)


def trbdf2_step(f: ODE, x, t, h, newton_iters: int = 6):
    """One TR-BDF2 step; returns (x_next, embedded error estimate).

    TR-BDF2 is the one-step L-stable composite of a trapezoidal half-stage
    to t+γh and a BDF2 closure to t+h — the workhorse implicit method for
    stiff plant simulation (the role CVODES plays for the reference,
    ``agentlib_mpc/models/casadi_model.py:402-447``). The embedded
    3rd-order companion weights give a per-step local error estimate,
    stiffly filtered through (I - γ/2 h J)⁻¹ so the controller is not
    fooled by fast transients (Hosea & Shampine 1996).
    """
    g, d = _TRBDF2_GAMMA, _TRBDF2_D
    k1 = f(x, t)

    # stage 2: trapezoidal to t + γh
    def res_tr(xg):
        return xg - x - d * h * (k1 + f(xg, t + g * h))

    xg = _newton_solve(res_tr, x + g * h * k1, newton_iters)
    k2 = f(xg, t + g * h)

    # stage 3: BDF2 closure to t + h
    w = _TRBDF2_W

    def res_bdf(xn):
        return xn - x - h * (w * k1 + w * k2 + d * f(xn, t + h))

    xn = _newton_solve(res_bdf, xg + (1.0 - g) * h * k2, newton_iters)
    k3 = f(xn, t + h)

    b, bh = _TRBDF2_B, _TRBDF2_BHAT
    est = h * ((b[0] - bh[0]) * k1 + (b[1] - bh[1]) * k2
               + (b[2] - bh[2]) * k3)
    # stiff filter: est ← (I - d h J)⁻¹ est
    n = x.shape[0]
    eye = jnp.eye(n, dtype=x.dtype)
    J = jax.jacfwd(lambda xx: f(xx, t + h))(xn)
    est = jnp.linalg.solve(eye - d * h * J, est)
    return xn, est


def integrate_adaptive(f: ODE, x0, t0, dt, rtol: float = 1e-6,
                       atol: float = 1e-8, h0: float | None = None,
                       max_steps: int = 10_000, newton_iters: int = 6):
    """Adaptive TR-BDF2 integration of x' = f(x, t) over [t0, t0+dt].

    Embedded-error step control inside one ``lax.while_loop`` (shape-static,
    jit/vmap-safe): a step is accepted when the weighted RMS of the local
    error estimate is ≤ 1, and the next step size follows the standard
    third-order controller ``h ← h · clip(0.9 · err^(-1/3), 0.2, 5)``.
    This is the framework's CVODES-fidelity plant integrator; the fixed-step
    methods in :func:`integrate` remain the in-OCP fast paths.

    Returns ``(x_final, stats)`` with ``stats = (n_accepted, n_rejected)``.
    If the step budget is exhausted before reaching ``t0+dt`` the returned
    state is NaN-poisoned — a silently wrong plant state must never be
    indistinguishable from a successful integration.
    """
    dtype = x0.dtype
    t_end = t0 + dt
    h_init = jnp.asarray(dt / 16.0 if h0 is None else h0, dtype)

    def err_norm(est, x_new, x_old):
        scale = atol + rtol * jnp.maximum(jnp.abs(x_new), jnp.abs(x_old))
        return jnp.sqrt(jnp.mean((est / scale) ** 2))

    def cond(carry):
        t, _x, _h, _acc, _rej, k = carry
        return (t < t_end - 1e-12 * jnp.abs(t_end)) & (k < max_steps)

    def body(carry):
        t, x, h, acc, rej, k = carry
        h_eff = jnp.minimum(h, t_end - t)
        x_new, est = trbdf2_step(f, x, t, h_eff, newton_iters)
        err = err_norm(est, x_new, x)
        ok = (err <= 1.0) & jnp.all(jnp.isfinite(x_new))
        # 3rd-order embedded → exponent -1/3; safety 0.9; bounded factor.
        # A non-finite estimate (Newton blow-up) must SHRINK the step, not
        # ride the err>0 branch to the 5x growth clip.
        fac = jnp.where(
            jnp.isfinite(err),
            jnp.clip(0.9 * jnp.maximum(err, 1e-10) ** (-1.0 / 3.0), 0.2, 5.0),
            0.2)
        t_n = jnp.where(ok, t + h_eff, t)
        x_n = jnp.where(ok, x_new, x)
        h_n = h_eff * fac
        return (t_n, x_n, h_n, acc + ok.astype(jnp.int32),
                rej + (~ok).astype(jnp.int32), k + 1)

    t_f, x_f, _h, acc, rej, _k = jax.lax.while_loop(
        cond, body,
        (jnp.asarray(t0, dtype), x0, h_init, jnp.asarray(0), jnp.asarray(0),
         jnp.asarray(0)))
    reached = t_f >= t_end - 1e-12 * jnp.abs(t_end)
    x_f = jnp.where(reached, x_f, jnp.nan)
    return x_f, (acc, rej)


_STEPPERS = {
    "euler": euler_step,
    "rk4": rk4_step,
    "implicit_midpoint": implicit_midpoint_step,
    "trbdf2": lambda f, x, t, h: trbdf2_step(f, x, t, h)[0],
}


def integrate(f: ODE, x0, t0, dt, substeps: int = 1, method: str = "rk4"):
    """Integrate x' = f(x, t) from t0 over dt with `substeps` fixed steps.

    ``method="adaptive"`` dispatches to :func:`integrate_adaptive`
    (embedded-error TR-BDF2) and ignores ``substeps``.
    """
    if method == "adaptive":
        return integrate_adaptive(f, x0, t0, dt)[0]
    stepper = _STEPPERS[method]
    h = dt / substeps

    def body(x, i):
        return stepper(f, x, t0 + i * h, h), None

    x_final, _ = jax.lax.scan(body, x0, jnp.arange(substeps))
    return x_final
