"""Resilience subsystem: guarded actuation, degradation ladder, chaos.

The reference tolerates failed solves by logging and carrying on
(``modules/mpc/mpc.py:389-404``) and ships a hand-operated
``fallback_pid`` escape hatch; a failed or NaN solve still actuates
``u[0]`` from the garbage trajectory. This package gives the framework
reflexes instead of hope:

- :mod:`.guard` — per-solve health checks and the configurable
  degradation cascade (shift-and-replay → hold-last-control →
  FallbackPID hand-over, with hysteresis before MPC re-engages), driven
  from :class:`~agentlib_mpc_tpu.modules.mpc.BaseMPC`.
- :mod:`.chaos` — deterministic, seeded fault injectors for the
  DataBroker (drop/delay/duplicate/reorder), the backend solve seam
  (forced failure / NaN poisoning), ADMM participants (silent
  mid-round death) and the serving plane (per-tenant NaN storms,
  dispatcher stalls, engine-build failures, checkpoint corruption —
  ``install_serving_chaos``), so the unhappy paths are *tested*, not
  hoped for.

The fused-ADMM quarantine (non-finite local solutions substituted with
the agent's previous iterate inside the jitted step) lives with the
engine in :mod:`agentlib_mpc_tpu.parallel.fused_admm`; its knobs are
``FusedADMMOptions.quarantine`` / ``quarantine_reset_after``.

See ``docs/robustness.md`` for the full degradation-ladder and
chaos-config reference.
"""

from agentlib_mpc_tpu.resilience.guard import (
    LEVEL_FALLBACK,
    LEVEL_HOLD,
    LEVEL_MPC,
    LEVEL_REPLAY,
    ActuationGuard,
    DegradationPolicy,
    GuardDecision,
    check_result,
)
from agentlib_mpc_tpu.resilience.chaos import (
    AdmmDeathRule,
    BrokerRule,
    ChaosBuildError,
    ChaosConfig,
    ChaosController,
    ServeBuildFailRule,
    ServeChaosConfig,
    ServeNaNStormRule,
    ServeStallRule,
    SolverRule,
    WarmstartPoisonRule,
    corrupt_checkpoint,
    disturbance_model,
    install_chaos,
    install_serving_chaos,
)

__all__ = [
    "ActuationGuard", "DegradationPolicy", "GuardDecision", "check_result",
    "LEVEL_MPC", "LEVEL_REPLAY", "LEVEL_HOLD", "LEVEL_FALLBACK",
    "ChaosConfig", "ChaosController", "BrokerRule", "SolverRule",
    "AdmmDeathRule", "install_chaos",
    "ServeChaosConfig", "ServeNaNStormRule", "ServeStallRule",
    "ServeBuildFailRule", "WarmstartPoisonRule", "ChaosBuildError",
    "install_serving_chaos",
    "corrupt_checkpoint", "disturbance_model",
]
