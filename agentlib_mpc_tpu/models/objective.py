"""Composable objective algebra.

Re-design of the reference's objective system
(``agentlib_mpc/data_structures/objective.py``: SubObjective :74-134,
ChangePenaltyObjective :239-294, CombinedObjective :297-453,
ConditionalObjective :456-621, CompositeWeight :10-71) for JAX tracing.

Key difference from the reference: there, objective terms wrap *symbolic
CasADi expressions* built once; here, ``Model.setup`` is re-executed inside
every trace, so a term simply holds the *traced scalar value* of its
expression at the current stage, plus metadata (name, weight). Because
weights can themselves be model parameters in the reference, a weight here
is whatever value you pass — a Python float or a traced parameter value from
the namespace; both compose identically.

Per-term bookkeeping is preserved: every term has a ``name`` and
``term_values()`` so the transcription can record per-term stage costs,
matching the reference's post-hoc per-term objective evaluation
(``casadi_backend.py:309-323``, ``objective.py:342-395``).
"""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp

Scalar = Union[float, jnp.ndarray]


class Objective:
    """Base class: supports ``+`` and ``*`` composition like the reference
    (``objective.py:110-134``)."""

    name: str = "objective"

    def value(self) -> Scalar:
        raise NotImplementedError

    def term_values(self) -> dict[str, Scalar]:
        """name → weighted term value at the current stage."""
        return {self.name: self.value()}

    def __add__(self, other):
        return CombinedObjective(self, _as_objective(other))

    def __radd__(self, other):
        if other == 0:  # support sum([...])
            return self
        return CombinedObjective(_as_objective(other), self)

    def __mul__(self, factor):
        return _Scaled(self, factor)

    __rmul__ = __mul__


class _Wrapped(Objective):
    """A bare scalar expression used as an objective (reference wraps legacy
    scalar objectives the same way, ``casadi_model.py:332-344``)."""

    def __init__(self, expr: Scalar, name: str = "objective"):
        self.expr = expr
        self.name = name

    def value(self) -> Scalar:
        return jnp.asarray(self.expr)


class _Scaled(Objective):
    def __init__(self, inner: Objective, factor: Scalar):
        self.inner = inner
        self.factor = factor
        self.name = inner.name

    def value(self) -> Scalar:
        return self.inner.value() * self.factor

    def term_values(self) -> dict[str, Scalar]:
        return {k: v * self.factor for k, v in self.inner.term_values().items()}


def _as_objective(x) -> Objective:
    if isinstance(x, Objective):
        return x
    return _Wrapped(x)


class SubObjective(Objective):
    """``weight * sum(expressions)`` — reference ``objective.py:74-134``.

    ``expressions`` may be a single traced scalar or a list; ``weight`` a
    float or a traced parameter value (parameter weights supported like the
    reference's CompositeWeight, ``objective.py:10-71``).
    """

    def __init__(self, expressions, weight: Scalar = 1.0, name: str = "sub_objective"):
        if not isinstance(expressions, (list, tuple)):
            expressions = [expressions]
        self.expressions = list(expressions)
        self.weight = weight
        self.name = name

    def value(self) -> Scalar:
        total = jnp.asarray(0.0)
        for e in self.expressions:
            total = total + jnp.asarray(e)
        return self.weight * total


class ChangePenaltyObjective(Objective):
    """Penalty on control moves Δu (reference ``objective.py:239-294``).

    ``du`` must come from the namespace's ``v.du("<control>")`` which the
    transcription wires to u_k − u_{k−1} (with u_{−1} = the live previous
    control, reference FullSystem ``casadi_/full.py:18-33``).
    """

    def __init__(self, du: Scalar, weight: Scalar = 1.0,
                 name: str = "change_penalty", quadratic: bool = True):
        self.du = du
        self.weight = weight
        self.name = name
        self.quadratic = quadratic

    def value(self) -> Scalar:
        du = jnp.asarray(self.du)
        penalty = du * du if self.quadratic else jnp.abs(du)
        return self.weight * penalty


class ConditionalObjective(Objective):
    """Objective switched by a traced boolean condition (reference
    ``objective.py:456-621`` uses ``ca.if_else``; here ``jnp.where``)."""

    def __init__(self, condition, if_true: Objective, if_false: Objective,
                 name: str = "conditional"):
        self.condition = condition
        self.if_true = _as_objective(if_true)
        self.if_false = _as_objective(if_false)
        self.name = name

    def value(self) -> Scalar:
        return jnp.where(self.condition, self.if_true.value(),
                         self.if_false.value())


class CombinedObjective(Objective):
    """Sum of terms with optional normalization (reference
    ``objective.py:297-453``)."""

    def __init__(self, *terms, normalization: Scalar = 1.0, name: str = "combined"):
        self.terms: list[Objective] = [_as_objective(t) for t in terms]
        self.normalization = normalization
        self.name = name

    def value(self) -> Scalar:
        total = jnp.asarray(0.0)
        for t in self.terms:
            total = total + t.value()
        return total / self.normalization

    def term_values(self) -> dict[str, Scalar]:
        out: dict[str, Scalar] = {}
        for i, t in enumerate(self.terms):
            for k, v in t.term_values().items():
                key = k if k not in out else f"{k}_{i}"
                out[key] = v / self.normalization
        return out

    def __add__(self, other):
        other = _as_objective(other)
        if isinstance(other, CombinedObjective) and \
                other.normalization == self.normalization:
            return CombinedObjective(*self.terms, *other.terms,
                                     normalization=self.normalization)
        return CombinedObjective(self, other)
