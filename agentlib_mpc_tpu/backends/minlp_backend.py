"""Mixed-integer MPC backends: relaxed NLP + rounding / CIA + fixed re-solve.

Counterparts of the reference's MINLP backends:
- ``jax_minlp`` ↔ ``casadi_minlp`` (``optimization_backends/casadi_/
  minlp.py:16-199``): there, binary controls are flagged ``discrete`` and a
  Bonmin/Gurobi branch-and-bound solves the true MINLP. Here the schedule
  is obtained by rounding the relaxed optimum and re-solving with the
  binaries fixed.
- ``jax_cia`` ↔ ``casadi_cia`` (``casadi_/minlp_cia.py:75-171``): the
  3-phase combinatorial-integer-approximation scheme — relaxed NLP →
  branch-and-bound CIA (native C++, ``ops/cia.py`` replacing pycombina) →
  NLP with the binary schedule fixed (the reference pins binaries via
  bounds, ``constrain_binary_inputs``, ``minlp_cia.py:152-171``).

Two compiled programs, not one with degenerate bounds: the relaxed phase
transcribes binaries as ordinary [0,1] controls; the fixed phase is a
*separate* transcription in which the binaries are exogenous inputs — the
schedule rides the ``d_traj`` parameter, so the log-barrier never sees a
(near-)zero-width box. Both programs compile once at setup and stay hot
across the closed loop.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.backends.backend import (
    VariableReference,
    register_backend,
)
from agentlib_mpc_tpu.backends.mpc_backend import (
    JAXBackend,
    attach_stage_partition,
)
from agentlib_mpc_tpu.ops.cia import cia_objective, solve_cia, sum_up_rounding
from agentlib_mpc_tpu.ops.solver import solve_nlp
from agentlib_mpc_tpu.ops.transcription import transcribe


@register_backend("jax_minlp", "casadi_minlp")
class MINLPBackend(JAXBackend):
    """Relaxed solve + binary schedule + fixed solve.

    Config additions:
        binary_method: "rounding" (default) | "sur" | "cia"
        cia_options: {"max_switches": int | [int...], "sos1": bool,
                      "max_nodes": int}
    """

    default_binary_method = "rounding"

    def setup_optimization(self, var_ref: VariableReference,
                           time_step: float, prediction_horizon: int) -> None:
        self.binary_names = list(var_ref.binary_controls)
        if not self.binary_names:
            raise ValueError(
                "MINLP backend configured without binary_controls; use the "
                "'jax' backend for purely continuous problems")
        merged = dataclasses.replace(
            var_ref,
            controls=list(var_ref.controls) + self.binary_names,
            binary_controls=[],
        )
        super().setup_optimization(merged, time_step, prediction_horizon)
        self._bin_idx = np.array(
            [merged.controls.index(n) for n in self.binary_names])
        self._cont_names = list(var_ref.controls)
        self._method = self.config.get(
            "binary_method", self.default_binary_method)
        self._cia_options = dict(self.config.get("cia_options", {}))
        self._build_fixed_program(var_ref)

    def _build_fixed_program(self, var_ref: VariableReference) -> None:
        """Second transcription: binaries as exogenous inputs."""
        from agentlib_mpc_tpu.backends.mpc_backend import \
            transcription_kwargs_from_config

        kw = transcription_kwargs_from_config(
            self.config.get("discretization_options"))
        self.ocp_fixed = transcribe(self.model, self._cont_names, N=self.N,
                                    dt=self.time_step, **kw)
        # schedule-tracking phase: binaries are data, so what matters is
        # feasibility + complementarity; the f32 stationarity floor scales
        # with the (large) comfort-slack gradient when the fixed schedule
        # forces a violation, so the stall-acceptance dual tolerance is wide
        from agentlib_mpc_tpu.backends.mpc_backend import \
            solver_options_from_config

        fixed_solver_cfg = {"dual_inf_tol": 100.0, "compl_inf_tol": 1e-2,
                            **dict(self.config.get("solver", {}) or {}),
                            **dict(self.config.get("fixed_solver", {}) or {})}
        from agentlib_mpc_tpu.backends.mpc_backend import \
            attach_derivative_plan

        self._fixed_options = attach_derivative_plan(
            attach_stage_partition(
                solver_options_from_config(fixed_solver_cfg),
                self.ocp_fixed),
            self.ocp_fixed, logger=self.logger,
            label="the fixed-binaries MINLP OCP")
        # exo vector of the fixed program = binaries ∪ relaxed program's exo;
        # map both into its declaration order
        fixed_exo = list(self.ocp_fixed.exo_names)
        self._fixed_bin_cols = np.array(
            [fixed_exo.index(n) for n in self.binary_names])
        self._fixed_exo_cols = np.array(
            [fixed_exo.index(n) for n in self._exo_names], dtype=int) \
            if self._exo_names else np.zeros(0, dtype=int)
        self._cont_idx = np.array(
            [self.var_ref.controls.index(n) for n in self._cont_names],
            dtype=int)
        ocp = self.ocp_fixed
        opts = self._fixed_options

        @jax.jit
        def step_fixed(x0, u_prev_c, d_traj_fixed, p, x_lb, x_ub,
                       u_lb_c, u_ub_c, mu0, t0):
            theta = ocp.default_params(
                x0=x0, u_prev=u_prev_c, d_traj=d_traj_fixed, p=p,
                x_lb=x_lb, x_ub=x_ub, u_lb=u_lb_c, u_ub=u_ub_c, t0=t0)
            lb, ub = ocp.bounds(theta)
            # fresh guess every solve: the schedule changes step to step, and
            # empirically the program's own guess (x ≡ x0) converges in a few
            # iterations where a rebased relaxed optimum stalls in f32
            res = solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta, lb, ub,
                            opts, mu0=mu0)
            traj = ocp.trajectories(res.w, theta)
            u0_c = (jnp.clip(traj["u"][0], theta.u_lb[0], theta.u_ub[0])
                    if len(self._cont_names) else jnp.zeros((0,)))
            return u0_c, traj, res.stats

        self._step_fixed = step_fixed

    def trajectory_layout(self) -> dict[str, list[str]]:
        """The returned ``traj`` comes from the *fixed* phase-3 program, so
        its "u" columns are the continuous controls only (binaries ride in
        ``binary_schedule``)."""
        layout = super().trajectory_layout()
        layout["u"] = list(self.ocp_fixed.control_names)
        return layout

    # -- binary scheduling (host side, between the two device solves) ---------

    def _binary_schedule(self, b_rel: np.ndarray) -> tuple[np.ndarray, float]:
        dt = np.full(len(b_rel), self.time_step)
        if self._method == "rounding":
            B = np.round(np.clip(b_rel, 0.0, 1.0))
            return B, cia_objective(b_rel, B, dt)
        if self._method == "sur":
            B = sum_up_rounding(b_rel, dt,
                                sos1=bool(self._cia_options.get("sos1")))
            return B, cia_objective(b_rel, B, dt)
        if self._method == "cia":
            ms = self._cia_options.get("max_switches")
            if isinstance(ms, int):
                ms = [ms] * len(self.binary_names)
            return solve_cia(
                b_rel, self.time_step, max_switches=ms,
                sos1=bool(self._cia_options.get("sos1")),
                max_nodes=int(self._cia_options.get("max_nodes", 2_000_000)))
        raise ValueError(f"unknown binary_method {self._method!r}")

    # -- three-phase solve ----------------------------------------------------

    def _solve_fixed(self, B: np.ndarray, ctx: dict) -> tuple:
        """Phase-3 solve for one binary schedule ``B`` (N, n_bin): binaries
        ride as exogenous data of the fixed program. Returns
        ``(u0_c, traj, stats)``; ``stats.objective`` is the TRUE objective
        of the schedule (no relaxation box involved), which is what the
        branch-and-bound backend uses to score incumbents."""
        ci = self._cont_idx
        n_fixed_exo = len(self.ocp_fixed.exo_names)
        d_fixed = np.zeros((self.N, n_fixed_exo))
        d_fixed[:, self._fixed_bin_cols] = B
        if len(self._fixed_exo_cols):
            d_fixed[:, self._fixed_exo_cols] = ctx["d_traj"]
        u0_c, traj, stats = self._step_fixed(
            ctx["x0"],
            ctx["u_prev"][ci] if len(ci) else np.zeros(0), d_fixed,
            ctx["p"], ctx["x_lb"], ctx["x_ub"],
            ctx["u_lb"][:, ci], ctx["u_ub"][:, ci],
            jnp.asarray(self.solver_options.mu_init, dtype=ctx["dtype"]),
            ctx["t_now"])
        return u0_c, traj, stats

    def _schedule(self, b_rel: np.ndarray, ctx: dict) -> tuple:
        """Phase 2: turn the relaxed binary trajectories into a {0,1}
        schedule. The base class runs the configured combinatorial
        heuristic; :class:`BranchAndBoundBackend` overrides this with an
        exact tree search. Must respect ``ctx['b_min']``/``ctx['b_max']``
        (bound lock-outs)."""
        B, eta = self._binary_schedule(b_rel)
        return np.clip(B, ctx["b_min"], ctx["b_max"]), eta

    def solve(self, now: float, variables: dict[str, Any]) -> dict:
        x0, u_prev, d_traj, p, x_lb, x_ub, u_lb, u_ub = \
            self._collect(now, variables)
        bi = self._bin_idx
        # relaxed box = externally supplied bound trajectories intersected
        # with [0,1] — a published ``on__ub = 0`` (lock-out) must carry
        # through to the schedule (reference pins binaries via bounds,
        # ``minlp_cia.py:152-171``)
        u_lb = u_lb.copy()
        u_ub = u_ub.copy()
        u_lb[:, bi] = np.clip(u_lb[:, bi], 0.0, 1.0)
        u_ub[:, bi] = np.clip(u_ub[:, bi], 0.0, 1.0)
        dtype = self._w_guess.dtype
        mu0 = jnp.asarray(self.solver_options.mu_init if self._cold else 1e-2,
                          dtype=dtype)
        t_now = jnp.asarray(float(now))
        t_start = _time.perf_counter()

        # phase 1: relaxed NLP
        with telemetry.span("backend.solve", backend=type(self).__name__,
                            instance=f"{id(self):x}",
                            phase="relaxed"):
            _, traj_rel, w_next, y_next, z_next, stats_rel = self._step(
                x0, u_prev, d_traj, p, x_lb, x_ub, u_lb, u_ub,
                self._w_guess, self._y_guess, self._z_guess, mu0, t_now)
            b_rel = np.asarray(traj_rel["u"])[:, bi]

        # phase 2: binary schedule, clamped to the binary values the bound
        # trajectories actually admit (an interval with ub < 1 cannot
        # switch on; lb > 0 cannot switch off)
        eps = 1e-9
        ctx = {
            "x0": x0, "u_prev": u_prev, "d_traj": d_traj, "p": p,
            "x_lb": x_lb, "x_ub": x_ub, "u_lb": u_lb, "u_ub": u_ub,
            "t_now": t_now, "dtype": dtype,
            "b_min": (u_lb[:, bi] > eps).astype(float),
            "b_max": (u_ub[:, bi] >= 1.0 - eps).astype(float),
            "root_objective": float(stats_rel.objective),
            "root_success": bool(stats_rel.success),
            "root_kkt": float(stats_rel.kkt_error),
        }
        self._schedule_stats = {}
        B, eta = self._schedule(b_rel, ctx)

        # phase 3: binaries enter as exogenous data of the fixed program
        ci = self._cont_idx
        with telemetry.span("backend.solve", backend=type(self).__name__,
                            instance=f"{id(self):x}",
                            phase="fixed"):
            u0_c, traj, stats = self._solve_fixed(B, ctx)
            jax.block_until_ready(traj)
        wall = _time.perf_counter() - t_start

        # warm-start bookkeeping rides the relaxed program; the shared
        # guard resets on non-finite iterates (duals included) instead
        # of poisoning the next step
        self._carry_warm_start(w_next, y_next, z_next, now=now)

        # assemble the actuation vector in merged-control order
        u0 = np.zeros(len(self.var_ref.controls))
        if len(ci):
            u0[ci] = np.asarray(u0_c)
        u0[bi] = B[0]
        stats_row = self.solver_stats_row(
            stats, now, wall,
            iterations=int(stats_rel.iterations) + int(stats.iterations),
            cia_objective=float(eta),
            relaxed_objective=float(stats_rel.objective),
            relaxed_success=bool(stats_rel.success),
            **self._schedule_stats,
        )
        self._record_solve(stats_row)
        return {
            "u0": {n: float(u0[i])
                   for i, n in enumerate(self.var_ref.controls)},
            "traj": {k: np.asarray(v) for k, v in traj.items()},
            "traj_relaxed": {k: np.asarray(v) for k, v in traj_rel.items()},
            "binary_schedule": B,
            "stats": stats_row,
        }


@register_backend("jax_cia", "casadi_cia")
class CIABackend(MINLPBackend):
    """MINLP backend defaulting to the branch-and-bound CIA schedule."""

    default_binary_method = "cia"


@register_backend("jax_minlp_bb")
class BranchAndBoundBackend(MINLPBackend):
    """Exact MINLP via best-first branch-and-bound over binary fixings —
    the TPU-idiomatic equivalent of the reference's Bonmin solve
    (``data_structures/casadi_utils.py:264-280``).

    Where Bonmin walks the tree sequentially with one NLP per node, here
    the frontier's children are relaxed in ONE vmapped interior-point
    call per sweep (``batch_pairs`` nodes → ``2·batch_pairs`` child
    relaxations, one XLA dispatch). Node fixings enter as narrow bound
    boxes on the relaxed program — fixed-to-1 means ``[1−δ, 1]``,
    fixed-to-0 means ``[0, δ]`` — so the log-barrier always has an
    interior and every node reuses the SAME compiled program. Because a
    binary point of the subtree lies inside its δ-box, each node's
    relaxation objective is a valid lower bound for the subtree — up to
    the error the node solve actually achieved: an inexactly-converged
    interior-point objective can sit above the true relaxation optimum
    by roughly its residual KKT error (far above the nominal ``tol``
    when the solver exits through its "acceptable" criteria), so every
    node bound is deflated by its own achieved KKT error, floored at
    ``tol``, before it is used for pruning. ``bb_proven_optimal`` is
    therefore rigorous relative to the deflated bounds; the certified
    gap is ``gap_tol`` *plus* the per-node achieved errors, never
    tighter than what the node relaxations actually resolved.
    Incumbents are scored EXACTLY by the phase-3 fixed program (binaries
    as data, no box), so the returned schedule's objective is the true
    mixed-integer objective.

    The search starts from the configured combinatorial heuristic
    (``binary_method``: rounding/sur/cia) as the initial incumbent, so it
    can only improve on the heuristic backends. The node budget
    (``bb_options.max_nodes``) bounds wall time; on exhaustion the best
    incumbent so far is returned (anytime behaviour, like Bonmin's
    iteration limits).

    Config additions::

        bb_options: {
          "max_nodes": 256,     # explored-node budget (anytime cutoff)
          "batch_pairs": 8,     # frontier nodes expanded per vmapped sweep
          "box_width": 1e-3,    # δ of the fixing boxes
          "gap_tol": 1e-6,      # absolute optimality gap for pruning
          "int_tol": 1e-3,      # integrality tolerance on relaxed binaries
        }
    """

    def setup_optimization(self, var_ref: VariableReference,
                           time_step: float, prediction_horizon: int) -> None:
        super().setup_optimization(var_ref, time_step, prediction_horizon)
        self._bb = dict(self.config.get("bb_options", {}))
        self._batch_pairs = int(self._bb.get("batch_pairs", 8))
        self._build_node_program()

    def _build_node_program(self) -> None:
        """One compiled program for a fixed-size batch of node
        relaxations (padded; fixed shape → compiled once)."""
        ocp = self.ocp
        opts = self.solver_options

        def one(theta, mu0):
            lb, ub = ocp.bounds(theta)
            res = solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta,
                            lb, ub, opts, mu0=mu0)
            traj = ocp.trajectories(res.w, theta)
            return traj["u"], res.stats

        self._solve_nodes = jax.jit(jax.vmap(one, in_axes=(0, None)))

    # -- tree search ----------------------------------------------------------

    def _node_bounds(self, lo: np.ndarray, hi: np.ndarray,
                     ctx: dict, delta: float):
        """Control-bound trajectories for a node fixing. ``lo``/``hi`` are
        (N, n_bin) in {0,1}: (0,1)=free, (0,0)=fixed 0, (1,1)=fixed 1.
        Returns (u_lb, u_ub) or None when the box is empty (a fixing that
        contradicts an external lock-out)."""
        bi = self._bin_idx
        u_lb = ctx["u_lb"].copy()
        u_ub = ctx["u_ub"].copy()
        u_lb[:, bi] = np.maximum(u_lb[:, bi],
                                 np.where(lo == 1, 1.0 - delta, 0.0))
        u_ub[:, bi] = np.minimum(u_ub[:, bi],
                                 np.where(hi == 0, delta, 1.0))
        if np.any(u_lb[:, bi] > u_ub[:, bi] + 1e-12):
            return None
        return u_lb, u_ub

    def _exact_objective(self, B: np.ndarray, ctx: dict) -> float:
        _, _, stats = self._solve_fixed(B, ctx)
        return (float(stats.objective) if bool(stats.success)
                else float("inf"))

    def _schedule(self, b_rel: np.ndarray, ctx: dict) -> tuple:
        import heapq
        import itertools

        delta = float(self._bb.get("box_width", 1e-3))
        gap = float(self._bb.get("gap_tol", 1e-6))
        int_tol = float(self._bb.get("int_tol", 1e-3))
        # an inexactly-converged node objective is only a lower bound up
        # to the error the node ACHIEVED — which under the solver's
        # "acceptable" exit can sit far above the nominal tol. Deflate
        # every bound by its own achieved KKT error (floored at tol) so
        # pruning and the optimality certificate never rest on unearned
        # digits.
        tol = float(self.solver_options.tol)

        def node_slack(kkt: float) -> float:
            return max(tol, kkt) if np.isfinite(kkt) else np.inf
        max_nodes = int(self._bb.get("max_nodes", 256))
        dt_vec = np.full(len(b_rel), self.time_step)
        counter = itertools.count()

        # exact incumbent scoring is one phase-3 device solve per DISTINCT
        # schedule: many near-integral nodes round to the same B, so a
        # memo keeps the per-sweep device traffic bounded, and every
        # unique exact solve counts toward the node budget (the class
        # docstring's anytime guarantee)
        exact_memo: dict[bytes, float] = {}

        def exact(B: np.ndarray) -> float:
            nonlocal explored
            key = np.ascontiguousarray(B).tobytes()
            if key not in exact_memo:
                exact_memo[key] = self._exact_objective(B, ctx)
                explored += 1
            return exact_memo[key]

        # initial incumbent: the heuristic schedule, scored exactly — the
        # search can only improve on the rounding/SUR/CIA backends
        explored = 1          # the root relaxation (phase 1) counts
        B_heur, _ = self._binary_schedule(b_rel)
        B_heur = np.clip(B_heur, ctx["b_min"], ctx["b_max"])
        inc_obj = exact(B_heur)
        heur_obj = inc_obj
        inc_B = B_heur

        def sanitize(brel, lo, hi):
            """A diverged relaxation can carry NaN trajectories; NaN
            defeats the leaf check AND the free-entry mask (NaN·0 = NaN),
            which would let argmax branch on an already-fixed entry.
            Replace non-finite entries by a neutral fractional guess on
            free entries and by the fixing elsewhere."""
            if np.all(np.isfinite(brel)):
                return brel
            free = (lo == 0) & (hi == 1)
            return np.where(np.isfinite(brel), brel,
                            np.where(free, 0.5, lo))

        lo0 = np.zeros_like(b_rel)
        hi0 = np.ones_like(b_rel)
        root_bound = (ctx["root_objective"] - node_slack(ctx["root_kkt"])
                      if ctx["root_success"] else -np.inf)
        heap = [(root_bound, next(counter), lo0, hi0,
                 sanitize(b_rel, lo0, hi0))]
        best_open = root_bound

        def try_incumbent(brel_node, lo, hi):
            nonlocal inc_obj, inc_B
            B = np.round(np.clip(brel_node, 0.0, 1.0))
            B = np.clip(np.clip(B, lo, hi), ctx["b_min"], ctx["b_max"])
            obj = exact(B)
            if obj < inc_obj:
                inc_obj, inc_B = obj, B

        while heap and explored < max_nodes:
            best_open = heap[0][0]
            if best_open >= inc_obj - gap:
                break  # optimality proven within gap
            # pop a frontier batch, branch each node on its most
            # fractional free entry
            children = []
            while heap and len(children) < 2 * self._batch_pairs:
                bound, _, lo, hi, brel = heapq.heappop(heap)
                if bound >= inc_obj - gap:
                    continue
                free = (lo == 0) & (hi == 1)
                frac = np.abs(brel - np.round(brel)) * free
                if frac.max() <= int_tol:
                    # relaxation optimum is (essentially) binary → the
                    # bound is attained by a feasible point: leaf
                    try_incumbent(brel, lo, hi)
                    continue
                k, j = np.unravel_index(np.argmax(frac), frac.shape)
                for fix in (0.0, 1.0):
                    lo_c, hi_c = lo.copy(), hi.copy()
                    lo_c[k, j] = hi_c[k, j] = fix
                    children.append((bound, lo_c, hi_c))
            if not children:
                continue

            # batched child relaxations: pad to the compiled batch size
            thetas, meta = [], []
            for parent_bound, lo_c, hi_c in children:
                bounds = self._node_bounds(lo_c, hi_c, ctx, delta)
                if bounds is None:
                    continue  # fixing contradicts a lock-out
                u_lb_c, u_ub_c = bounds
                thetas.append(self.ocp.default_params(
                    x0=ctx["x0"], u_prev=ctx["u_prev"],
                    d_traj=ctx["d_traj"], p=ctx["p"],
                    x_lb=ctx["x_lb"], x_ub=ctx["x_ub"],
                    u_lb=u_lb_c, u_ub=u_ub_c, t0=ctx["t_now"]))
                meta.append((parent_bound, lo_c, hi_c))
            if not thetas:
                continue
            n_real = len(thetas)
            pad = 2 * self._batch_pairs - n_real
            thetas += [thetas[0]] * pad
            theta_batch = jax.tree.map(lambda *xs: jnp.stack(xs), *thetas)
            # sequential by construction: each B&B wave's nodes depend
            # on the previous wave's bounds, and the wave itself is
            # already one batched dispatch
            u_batch, stats = self._solve_nodes(  # lint: ignore[jit-dispatch-in-loop]
                theta_batch,
                jnp.asarray(self.solver_options.mu_init,
                            dtype=ctx["dtype"]))
            u_host = np.asarray(u_batch)[:n_real]
            objs = np.asarray(stats.objective)[:n_real]
            oks = np.asarray(stats.success)[:n_real]
            kkts = np.asarray(stats.kkt_error)[:n_real]
            explored += n_real

            for i, (parent_bound, lo_c, hi_c) in enumerate(meta):
                brel_c = sanitize(u_host[i][:, self._bin_idx], lo_c, hi_c)
                # bounds are monotone down the tree; a failed child solve
                # cannot tighten the parent's bound
                bound_c = (max(parent_bound,
                               float(objs[i]) - node_slack(float(kkts[i])))
                           if oks[i] else parent_bound)
                if bound_c >= inc_obj - gap:
                    continue  # prune
                free = (lo_c == 0) & (hi_c == 1)
                frac = np.abs(brel_c - np.round(brel_c)) * free
                if frac.max() <= int_tol:
                    try_incumbent(brel_c, lo_c, hi_c)
                    continue
                heapq.heappush(
                    heap, (bound_c, next(counter), lo_c, hi_c, brel_c))

        best_open = heap[0][0] if heap else inc_obj
        self._schedule_stats = {
            "bb_nodes": explored,
            "bb_incumbent": inc_obj,
            "bb_bound": min(best_open, inc_obj),
            "bb_gap": max(0.0, inc_obj - best_open) if heap else 0.0,
            "bb_proven_optimal": not heap or best_open >= inc_obj - gap,
            "bb_improved_on_heuristic": inc_obj < heur_obj - gap,
        }
        return inc_B, cia_objective(b_rel, inc_B, dt_vec)
