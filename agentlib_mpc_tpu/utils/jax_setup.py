"""JAX process-level setup helpers.

Two recurring ergonomics problems this module solves (VERDICT.md round 1,
"What's weak" #3/#7):

* **Compile latency.** Every (model, horizon, options) shape recompiles the
  interior-point solver from scratch (~20-40 s cold on TPU, similar on the
  CPU backend the tests use). ``enable_persistent_cache`` turns on JAX's
  persistent compilation cache so repeated test runs / bench runs /
  deployments reuse compiled executables across processes. The XLA
  replacement for the reference's CasADi C-codegen + DLL batch compile
  (``data_structures/casadi_utils.py:313-369``) — except it is
  platform-portable and automatic.

* **Platform bring-up.** This environment's sitecustomize force-registers
  the experimental ``axon`` TPU platform; a process that only needs the
  host CPU (tests, dry runs, baseline probes) can block on the TPU tunnel.
  ``force_cpu`` pins the process to the CPU backend before any backend
  initialization.

* **Compile observability.** ``enable_compile_profiling`` installs
  ``jax.monitoring`` listeners that surface compiles, retraces and compile
  latency as telemetry metrics (:mod:`agentlib_mpc_tpu.telemetry`) — cache
  misses become numbers instead of mystery latency.
"""

from __future__ import annotations

import os

def _default_cache_dir() -> str:
    """Repo-root ``.jax_cache`` in a source checkout; user cache dir when
    the package is installed into site-packages."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if os.path.isfile(os.path.join(root, "pyproject.toml")):
        return os.path.join(root, ".jax_cache")
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "agentlib_mpc_tpu", "jax")


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Enable JAX's persistent compilation cache (idempotent).

    Safe to call before or after backend initialization; entries are keyed
    by platform so CPU-test and TPU-bench executables coexist.
    """
    import jax

    path = cache_dir or os.environ.get("AGENTLIB_MPC_TPU_CACHE") or \
        _default_cache_dir()
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every compile that takes noticeable time, regardless of size
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return path


def enable_compile_profiling(registry=None):
    """Install JAX compile/retrace telemetry hooks (idempotent).

    Registers ``jax.monitoring`` listeners that mirror every jaxpr trace,
    XLA backend compile and persistent-cache event into the telemetry
    registry (``jax_traces_total``, ``jax_retraces_total``,
    ``jax_compiles_total``, ``jax_compile_seconds_total``,
    ``jax_cache_events_total`` — see ``docs/telemetry.md``).  Compile
    latency is attributed to the innermost active telemetry span, so the
    instrumented entry points (``backend.solve``, ``admm.fused_step``,
    ``solver.solve_nlp``, the bench phases) each own their compile cost —
    an unexpected ``jax_retraces_total`` increment on a warm path is the
    "what config change just recompiled my solver" alarm that previously
    required print-debugging.

    Safe to call before or after backend initialization and with telemetry
    disabled (listeners no-op until enabled). Returns the registry the
    hooks write into.
    """
    from agentlib_mpc_tpu.telemetry import jax_events

    return jax_events.install(registry)


def request_virtual_devices(n: int) -> None:
    """Set ``--xla_force_host_platform_device_count=n`` in XLA_FLAGS,
    replacing any existing count (idempotent — a blind append would
    leave two copies with unspecified precedence). Only honored if it
    runs before the backend comes up."""
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    want = f"--xla_force_host_platform_device_count={n}"
    os.environ["XLA_FLAGS"] = f"{flags} {want}".strip()


def force_cpu(n_virtual_devices: int | None = None) -> None:
    """Pin this process to the host-CPU backend.

    Must run before any JAX backend initialization. ``n_virtual_devices``
    additionally requests a virtual multi-device CPU (only honored if set
    before the backend comes up — i.e. call this first thing).
    """
    if n_virtual_devices is not None:
        request_virtual_devices(n_virtual_devices)

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backends already initialized
        pass


def cpu_subprocess_env(base: "dict | None" = None) -> dict:
    """Environment for a CPU-only child process that must NEVER touch the
    TPU tunnel.

    The image's axon ``sitecustomize`` gates its relay dial (which hangs
    the interpreter when the tunnel is wedged) on ``PALLAS_AXON_POOL_IPS``
    — scrubbing it means the axon platform is never registered and a
    launch-time ``JAX_PLATFORMS=cpu`` pin is safe. Single definition of
    the scrub set, used by ``bench.py`` (CPU baseline probe) and
    ``__graft_entry__.py`` (multichip dry-run child).
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


#: known-noise XLA warning markers filtered from forwarded child output:
#: the XLA:CPU "machine type ... doesn't match ... Compile machine
#: features: [+64bit,+adx,...] ... may cause SIGILL" blob is a
#: multi-kilobyte per-child emission on this VM that dominated the
#: driver-stored BENCH_r05 stderr AND MULTICHIP_r0x output tails and
#: buried the actual result lines. Harmless (the persistent compile
#: cache crosses machine generations by design), known, and useless in
#: an artifact. One definition, used by every child-spawning entry
#: point (``bench.py`` workers, ``__graft_entry__`` dryrun).
XLA_NOISE_MARKERS = (
    "Machine type used for XLA:CPU compilation",
    "Compile machine features:",
    "may cause SIGILL",
    "+prefer-no-gather",
)


def filter_xla_noise(text: str) -> str:
    """Drop known-noise XLA machine-feature warning lines from captured
    child output before forwarding/storing it; appends one summary line
    so the filtering itself is on record."""
    kept, dropped = [], 0
    for ln in (text or "").splitlines(keepends=True):
        if any(marker in ln for marker in XLA_NOISE_MARKERS):
            dropped += 1
            continue
        kept.append(ln)
    out = "".join(kept)
    if dropped:
        if out and not out.endswith("\n"):
            out += "\n"
        out += (f"[filtered {dropped} known-noise XLA machine-feature "
                f"warning line(s)]\n")
    return out
