"""Static memory certification: prove per-device peak HBM before dispatch.

The reference stack discovers memory exhaustion at runtime — IPOPT and
CasADi malloc until the OS objects — but on a TPU pod an OOM is a fatal,
whole-mesh dispatch failure, and every capacity question the scale-out
work asks (*how many agents / scenarios / tenant slots fit on one
device?*) needs an answer BEFORE the program touches silicon.

This is the sixth certifier pass on the PR 5 interpreter stack: a
**live-range abstract interpretation over the closed jaxpr** that
computes peak bytes-resident per device and emits a
:class:`MemoryCertificate` —

* per-buffer live intervals from one linear walk of the eqn schedule:
  a value is resident from the eqn that defines it to its last use
  (jaxpr outputs live to the end); the peak is the largest sum of
  simultaneously-live buffers. Arguments are owned by the caller and
  stay resident for the whole execution (exactly XLA's contract);
* **donation-aware** — donated invars alias their dtype/shape-matching
  outvals (XLA input-output aliasing), so ``donate_state=True``
  provably saves one full :class:`~agentlib_mpc_tpu.parallel.
  fused_admm.FusedState` copy and the certificate shows the exact
  delta;
* **sharding-aware** — inside a ``shard_map`` eqn the body avals are
  already shard-local, and the eqn's operands/results divide by the
  mesh axis sizes their in/out-specs shard over (the PR 11
  ``in_names`` plumbing), so the certificate answers per-*device*, not
  per-host;
* control flow charged honestly: ``scan``/``while`` bodies at
  body-peak + carry (NOT × trips — the loop reuses its body buffers),
  ``cond`` at max-of-branches;
* opaque primitives (``pure_callback`` & friends — never executed)
  degrade the verdict to an honest ``"lower_bound"``: the reported
  peak is still a floor, but no longer a proved ceiling.

Calibration closes the loop: :func:`xla_memory_analysis` compiles the
same program and reads XLA's own buffer-assignment numbers
(``argument + output − alias + temp``); the certifier must bound XLA
from above within the ``[jaxpr.memory]`` ``max_xla_ratio`` pin on the
whole example menu (:func:`memory_gate_summary`, run by
``python -m agentlib_mpc_tpu.lint --memory-budget`` and ``--jaxpr``),
so the static proof is anchored to ground truth.

On top of the certificate, :func:`plan_capacity` inverts the per-lane
marginal cost into the three capacity answers the scale-out roadmap
needs — max agents per device, max scenario branches per device, max
serving-slot multiple — and the build seams consume it:
``FusedADMM``/``ScenarioFleet`` attach the certificate and refuse
(``memory_certify="auto"|"require"|"off"``) programs whose projected
peak exceeds the backend device's reported capacity, and the
``ServingPlane`` consults the projection before capacity growth so a
join that would OOM a bucket is shed into the PR 2 guard ladder
instead of killing the round.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging

import numpy as np

from agentlib_mpc_tpu.lint.jaxpr.interp import CALLBACK_PRIMS

logger = logging.getLogger(__name__)

__all__ = [
    "CapacityPlan",
    "MemoryBudgetExceeded",
    "MemoryCertificate",
    "certify_memory",
    "check_memory_budget",
    "device_hbm_bytes",
    "engine_memory_certificate",
    "memory_gate_summary",
    "modeled_buffer_bytes",
    "plan_capacity",
    "xla_memory_analysis",
]

#: call-like primitives whose single sub-jaxpr is inlined transparently
#: (the collectives walker's table — kept in sync by the shared tests)
_CALL_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "remat": "jaxpr",
    "checkpoint": "jaxpr",
    "remat2": "jaxpr",
}

#: how many top live buffers a certificate records for attribution
_TOP_BUFFERS = 8

#: per-buffer allocation granularity of the model: XLA's buffer
#: assignment aligns every allocation (64 B on CPU/TPU), so a program
#: of many small temps occupies far more than its logical bytes —
#: without this the certifier UNDERCOUNTS exactly the programs whose
#: footprint is allocation-dominated (measured on the fused tracker
#: round: hundreds of scalar residual/penalty temps)
_ALIGN = 64


def modeled_buffer_bytes(shape, dtype) -> int:
    """Bytes the model charges one buffer: logical size rounded up to
    the :data:`_ALIGN` allocation granularity (public so identity tests
    can compute e.g. the exact FusedState footprint the way the
    certificate does)."""
    n = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    if n <= 0:
        return 0
    return -(-n // _ALIGN) * _ALIGN


class MemoryBudgetExceeded(ValueError):
    """A certified program's projected per-device peak exceeds the
    available (or budgeted) device memory. Raised by the engine build
    seams under ``memory_certify`` and consumed by the serving plane's
    capacity-shed path."""


def _source_of(eqn) -> str:
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:  # noqa: BLE001 — diagnostics only
        return "<unknown>"


def _as_jaxpr(obj):
    """(jaxpr, const_avals) from a ClosedJaxpr or an open Jaxpr."""
    if hasattr(obj, "jaxpr"):                     # ClosedJaxpr
        return obj.jaxpr, [np.asarray(c) for c in obj.consts]
    return obj, []


def _aval_bytes(aval) -> int:
    if aval is None or not hasattr(aval, "shape") \
            or not hasattr(aval, "dtype"):
        return 0
    try:
        return modeled_buffer_bytes(aval.shape, aval.dtype)
    except Exception:  # noqa: BLE001 — token/opaque avals
        return 0


def _var_bytes(v) -> int:
    return _aval_bytes(getattr(v, "aval", None))


def _spec_factor(names, mesh_sizes: dict) -> int:
    """Division factor a shard_map in/out-spec buys: the product of the
    mesh axis sizes the spec shards over (1 = replicated)."""
    f = 1
    vals = names.values() if hasattr(names, "values") else names
    for axes in vals:
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        for a in axes:
            f *= int(mesh_sizes.get(str(a), 1))
    return max(int(f), 1)


@dataclasses.dataclass(frozen=True)
class _SubResult:
    """One sub-jaxpr's walk outcome, as its caller accounts for it.

    ``interior_peak`` is the peak bytes of values INTERIOR to the
    jaxpr — everything except its invars and outvars, which the caller
    already counts as the call eqn's operands/results (that exclusion
    is what lets call-like primitives inline without double counting).
    """

    interior_peak: int
    in_factors: tuple          # per-invar sharding divisor
    out_factors: tuple         # per-outvar sharding divisor
    buffers: tuple             # (bytes, primitive, source) at the peak
    per_prim: dict             # primitive -> live bytes at the peak


_EMPTY_SUB = _SubResult(0, (), (), (), {})


class _MemWalker:
    def __init__(self):
        self.opaque: list = []
        self.notes: list = []
        self.axis_sizes: dict = {}

    def _note(self, msg: str) -> None:
        if msg not in self.notes:
            self.notes.append(msg)

    # -- the walk -------------------------------------------------------------

    def walk(self, obj, in_sizes: "list[int] | None" = None) -> _SubResult:
        jaxpr, consts = _as_jaxpr(obj)
        n_eqns = len(jaxpr.eqns)
        if in_sizes is None:
            in_sizes = [_var_bytes(v) for v in jaxpr.invars]

        # -- pass 1: per-eqn extras, sub recursion, sharding factors ---
        extra = [0] * n_eqns
        extra_sub: "list[_SubResult | None]" = [None] * n_eqns
        # candidate division factors per var; plain uses contribute 1 so
        # a value consumed anywhere outside a sharded seam stays charged
        # at full (conservative) size
        use_factors: dict = {}
        def_factors: dict = {}

        def use(v, factor: int = 1):
            if type(v).__name__ == "Literal":
                return
            use_factors.setdefault(v, []).append(int(factor))

        for i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                sizes = {}
                try:
                    sizes = {str(k): int(s)
                             for k, s in dict(mesh.shape).items()}
                except Exception:  # noqa: BLE001 — AbstractMesh variants
                    pass
                self.axis_sizes.update(sizes)
                body = eqn.params["jaxpr"]
                sub = self.walk(body)          # body avals are shard-local
                extra[i], extra_sub[i] = sub.interior_peak, sub
                for v, names in zip(eqn.invars, eqn.params["in_names"]):
                    use(v, _spec_factor(names, sizes))
                for v, names in zip(eqn.outvars, eqn.params["out_names"]):
                    def_factors[v] = _spec_factor(names, sizes)
                continue
            if name in _CALL_PRIMS:
                sub_obj = eqn.params.get(_CALL_PRIMS[name])
                sub_jaxpr, _ = _as_jaxpr(sub_obj) if sub_obj is not None \
                    else (None, [])
                if sub_jaxpr is not None and \
                        len(sub_jaxpr.invars) == len(eqn.invars):
                    sub = self.walk(sub_obj,
                                    [_var_bytes(v) for v in eqn.invars])
                    extra[i], extra_sub[i] = sub.interior_peak, sub
                    for v, f in zip(eqn.invars, sub.in_factors):
                        use(v, f)
                    for v, f in zip(eqn.outvars, sub.out_factors):
                        def_factors[v] = f
                    continue
                # arity mismatch (wrapper consts): fall through to the
                # generic rule — operands/outputs still counted
            elif name == "scan":
                body = eqn.params["jaxpr"]
                body_jaxpr, _ = _as_jaxpr(body)
                sub = self.walk(body)
                n_const = eqn.params["num_consts"]
                # per-iteration xs slices and the in-flight body outputs
                # (new carry + the ys slice being stacked) materialize
                # beside the stacked operands; the body peak itself is
                # NOT multiplied by the trip count — the loop reuses its
                # body buffers
                slices = sum(_var_bytes(v)
                             for v in body_jaxpr.invars[n_const:])
                in_flight = sum(_var_bytes(v)
                                for v in body_jaxpr.outvars)
                extra[i] = sub.interior_peak + slices + in_flight
                extra_sub[i] = sub
            elif name == "while":
                sub_c = self.walk(eqn.params["cond_jaxpr"])
                sub_b = self.walk(eqn.params["body_jaxpr"])
                body_jaxpr, _ = _as_jaxpr(eqn.params["body_jaxpr"])
                best = sub_b if sub_b.interior_peak >= sub_c.interior_peak \
                    else sub_c
                # XLA assigns the cond's and the body's temp arenas in
                # one allocation, and the new carry is computed while
                # the old one is live — charge all three
                in_flight = sum(_var_bytes(v)
                                for v in body_jaxpr.outvars)
                extra[i] = (sub_c.interior_peak + sub_b.interior_peak
                            + in_flight)
                extra_sub[i] = best
            elif name == "cond":
                subs = [self.walk(br) for br in eqn.params["branches"]]
                best = max(subs, key=lambda s: s.interior_peak,
                           default=_EMPTY_SUB)
                extra[i], extra_sub[i] = best.interior_peak, best
            elif name in CALLBACK_PRIMS:
                # never executed; whatever the host (or foreign call)
                # allocates is outside the proof — the verdict degrades
                # to "lower_bound"
                self.opaque.append(name)
            else:
                # any other primitive's working set is its operands +
                # outputs (both counted by the timeline); sub-jaxprs it
                # hides (custom_linear_solve etc.) are charged as extra
                for val in eqn.params.values():
                    for s in (val if isinstance(val, (tuple, list))
                              else (val,)):
                        if hasattr(s, "eqns") or hasattr(s, "jaxpr"):
                            sub = self.walk(s)
                            if sub.interior_peak > extra[i]:
                                extra[i], extra_sub[i] = \
                                    sub.interior_peak, sub
            for v in eqn.invars:
                use(v)

        # -- pass 2: per-value sizes (sharding divisors applied) -------
        invar_set = set(jaxpr.invars)
        out_vars = [v for v in jaxpr.outvars
                    if type(v).__name__ != "Literal"]
        outvar_set = set(out_vars)

        def factor_of(v) -> int:
            # the most conservative (smallest) divisor any consumer
            # demands; a value with no uses (a jaxpr output) keeps the
            # divisor its defining seam provides
            fs = use_factors.get(v)
            if fs:
                return max(min(fs), 1)
            return max(def_factors.get(v, 1), 1)

        size: dict = {}
        in_factors = []
        for v, s in zip(jaxpr.invars, in_sizes):
            f = factor_of(v)
            in_factors.append(f)
            size[v] = -(-int(s) // f)
        const_base = sum(_aval_bytes(c) for c in consts)
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                size[v] = -(-_var_bytes(v) // factor_of(v))
        out_factors = tuple(
            1 if type(v).__name__ == "Literal" or v not in size
            else factor_of(v) for v in jaxpr.outvars)

        # -- pass 3: live-interval sweep over interior values ----------
        defs: dict = {}
        last: dict = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for v in eqn.invars:
                if type(v).__name__ != "Literal":
                    last[v] = i
            for v in eqn.outvars:
                defs[v] = i
        for v in out_vars:
            last[v] = n_eqns               # sentinel: live to the end

        interior = [v for v in defs
                    if v not in outvar_set and v not in invar_set]
        delta = [0] * (n_eqns + 1)
        for v in interior:
            delta[defs[v]] += size[v]
            end = last.get(v, defs[v])
            if end + 1 <= n_eqns:
                delta[min(end + 1, n_eqns)] -= size[v]
        cur, peak, peak_t = 0, const_base, -1
        for t in range(n_eqns):
            cur += delta[t]
            live = const_base + cur + extra[t]
            if live > peak:
                peak, peak_t = live, t
        if n_eqns == 0:
            return _SubResult(const_base, tuple(in_factors),
                              out_factors, (), {})

        # -- attribution at the peak instant ---------------------------
        buffers: list = []
        per_prim: dict = {}
        if peak_t >= 0:
            for v in interior:
                if defs[v] <= peak_t <= last.get(v, defs[v]) and size[v]:
                    eqn = jaxpr.eqns[defs[v]]
                    buffers.append((size[v], eqn.primitive.name,
                                    _source_of(eqn)))
                    per_prim[eqn.primitive.name] = \
                        per_prim.get(eqn.primitive.name, 0) + size[v]
            sub = extra_sub[peak_t]
            if sub is not None:
                buffers.extend(sub.buffers)
                for k, b in sub.per_prim.items():
                    per_prim[k] = per_prim.get(k, 0) + b
        buffers.sort(key=lambda b: -b[0])
        return _SubResult(int(peak), tuple(in_factors), out_factors,
                          tuple(buffers[:_TOP_BUFFERS]), per_prim)


@dataclasses.dataclass(frozen=True)
class MemoryCertificate:
    """Outcome of :func:`certify_memory`.

    ``status``:

    * ``"proved"`` — ``peak_bytes`` is a proved per-device upper bound
      on bytes-resident (validated against XLA's own
      ``memory_analysis`` by the ``[jaxpr.memory]`` gate);
    * ``"lower_bound"`` — an opaque primitive (``pure_callback`` &
      friends, never executed) hides allocations: ``peak_bytes`` is
      still a floor, no longer a proved ceiling;
    * ``"unknown"`` — the walk failed; no number is claimed.
    """

    status: str
    peak_bytes: int = 0            # per-device, arguments included
    argument_bytes: int = 0        # caller-owned, resident throughout
    output_bytes: int = 0          # after donation aliasing
    temp_peak_bytes: int = 0       # interior live-range peak
    donated_aliased_bytes: int = 0
    per_primitive_peak_bytes: dict = dataclasses.field(
        default_factory=dict)
    #: the largest live buffers at the peak instant:
    #: (bytes, primitive, source) descending — what a budget violation
    #: names
    top_buffers: tuple = ()
    opaque: tuple = ()
    notes: tuple = ()
    axis_sizes: "dict | None" = None   # mesh axis name -> size (sharded)

    @property
    def proved(self) -> bool:
        return self.status == "proved"

    @property
    def sharded(self) -> bool:
        return bool(self.axis_sizes)

    @property
    def memory_digest(self) -> "str | None":
        """Identity of the certified footprint — rides the engine-store
        meta next to the collective-schedule digest so a restore into a
        process whose fresh build would certify a DIFFERENT footprint
        is visible. None unless proved."""
        if self.status != "proved":
            return None
        ident = "|".join([
            str(self.peak_bytes), str(self.argument_bytes),
            str(self.output_bytes), str(self.temp_peak_bytes),
            str(self.donated_aliased_bytes),
            ";".join(f"{b}:{p}" for b, p, _s in self.top_buffers),
        ])
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def per_lane_bytes(self, lanes: int) -> int:
        """Average resident bytes per batched lane — the coarse
        (base-inclusive) marginal; :func:`plan_capacity` computes the
        true marginal from two certificates."""
        return -(-self.peak_bytes // max(int(lanes), 1))

    def describe(self) -> str:
        mib = self.peak_bytes / 2**20
        shard = ""
        if self.axis_sizes:
            shard = " per-device over " + "x".join(
                f"{k}={v}" for k, v in sorted(self.axis_sizes.items()))
        if self.status == "proved":
            return (f"proved: peak {mib:.2f} MiB{shard} "
                    f"(args {self.argument_bytes / 2**20:.2f} + outs "
                    f"{self.output_bytes / 2**20:.2f} + temps "
                    f"{self.temp_peak_bytes / 2**20:.2f} MiB)")
        if self.status == "lower_bound":
            return (f"lower bound: peak >= {mib:.2f} MiB{shard} — "
                    f"opaque primitive(s) "
                    f"{','.join(sorted(set(self.opaque)))} hide "
                    f"allocations")
        return "unknown: " + "; ".join(self.notes[:2])

    def as_dict(self) -> dict:
        return {
            "status": self.status,
            "peak_bytes": self.peak_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_peak_bytes": self.temp_peak_bytes,
            "donated_aliased_bytes": self.donated_aliased_bytes,
            "per_primitive_peak_bytes": dict(sorted(
                self.per_primitive_peak_bytes.items(),
                key=lambda kv: -kv[1])),
            "top_buffers": [
                {"bytes": b, "primitive": p, "source": s}
                for b, p, s in self.top_buffers],
            "digest": self.memory_digest,
            "opaque": sorted(set(self.opaque)),
            "notes": list(self.notes),
            "axis_sizes": dict(self.axis_sizes or {}),
        }


def _donated_mask(closed, donate_argnums, args) -> "tuple | None":
    """Flat per-invar donation flags from jit-style ``donate_argnums``
    (the flat order of ``make_jaxpr`` invars is the leaf order of the
    positional args)."""
    if not donate_argnums:
        return None
    import jax

    donate = set(int(i) for i in donate_argnums)
    flags: list = []
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        flags.extend([i in donate] * n)
    if len(flags) != len(closed.jaxpr.invars):
        return None
    return tuple(flags)


def certify_memory(fn_or_jaxpr, *args, donate_argnums=(),
                   donated_invars=None) -> MemoryCertificate:
    """Certify the per-device peak bytes-resident of a traced program.

    ``fn_or_jaxpr``: a ``ClosedJaxpr`` (pass no ``args``) or a callable
    traced as ``jax.make_jaxpr(fn)(*args)`` — shape templates suffice.
    ``donate_argnums`` mirrors ``jax.jit``'s (positional args whose
    buffers the caller donates); ``donated_invars`` is the already-flat
    per-invar alternative for pre-closed jaxprs. Never executes user
    code: callbacks degrade the verdict to ``"lower_bound"``."""
    if hasattr(fn_or_jaxpr, "jaxpr") and not args:
        closed = fn_or_jaxpr
    else:
        import jax

        closed = jax.make_jaxpr(fn_or_jaxpr)(*args)
        if donated_invars is None:
            donated_invars = _donated_mask(closed, donate_argnums, args)
    walker = _MemWalker()
    try:
        res = walker.walk(closed)
    except Exception as exc:  # noqa: BLE001 — certification must not
        # kill an engine build; an uninterpretable program is "unknown"
        return MemoryCertificate(
            status="unknown", opaque=("interpreter-error",),
            notes=(f"interpreter error: {exc!r}",))
    jaxpr = closed.jaxpr

    in_sizes = [-(-_var_bytes(v) // f)
                for v, f in zip(jaxpr.invars, res.in_factors)]
    argument_bytes = sum(in_sizes)
    out_entries = []
    for v, f in zip(jaxpr.outvars, res.out_factors):
        if type(v).__name__ == "Literal":
            continue
        aval = getattr(v, "aval", None)
        out_entries.append((tuple(getattr(aval, "shape", ())),
                            str(getattr(aval, "dtype", "?")),
                            -(-_var_bytes(v) // f)))
    # donation: each donated invar's buffer can back one dtype/shape-
    # matching output (XLA input-output aliasing) — that output then
    # costs nothing beyond the argument already counted
    pool: list = []
    if donated_invars:
        for v, flag, s in zip(jaxpr.invars, donated_invars, in_sizes):
            if flag:
                aval = getattr(v, "aval", None)
                pool.append([tuple(getattr(aval, "shape", ())),
                             str(getattr(aval, "dtype", "?")), s])
    output_bytes = 0
    donated_aliased = 0
    for shape, dtype, s in out_entries:
        hit = next((p for p in pool
                    if p[0] == shape and p[1] == dtype and p[2] == s),
                   None)
        if hit is not None:
            pool.remove(hit)
            donated_aliased += s
        else:
            output_bytes += s
    peak = argument_bytes + output_bytes + res.interior_peak
    if donated_aliased:
        # honesty marker: aliasing models XLA input-output donation,
        # which backends without buffer-donation support (CPU) do NOT
        # perform — there the true residency is peak + the aliased
        # bytes. The accelerator answer is the certificate's job; the
        # note keeps a CPU cross-check of a donated program from
        # reading as an upper-bound violation of the model itself.
        walker._note(
            f"donation aliasing modeled ({donated_aliased} B): on "
            f"backends without buffer donation (CPU) add "
            f"donated_aliased_bytes to peak_bytes for the true "
            f"residency")
    per_prim = dict(res.per_prim)
    if argument_bytes:
        per_prim["(arguments)"] = argument_bytes
    if output_bytes:
        per_prim["(outputs)"] = output_bytes
    status = "lower_bound" if walker.opaque else "proved"
    return MemoryCertificate(
        status=status,
        peak_bytes=int(peak),
        argument_bytes=int(argument_bytes),
        output_bytes=int(output_bytes),
        temp_peak_bytes=int(res.interior_peak),
        donated_aliased_bytes=int(donated_aliased),
        per_primitive_peak_bytes=per_prim,
        top_buffers=res.buffers,
        opaque=tuple(walker.opaque),
        notes=tuple(walker.notes),
        axis_sizes=dict(walker.axis_sizes) or None,
    )


# --------------------------------------------------------------------------
# XLA cross-check (calibration to ground truth)
# --------------------------------------------------------------------------

def xla_memory_analysis(fn, *args, donate_argnums=()) -> "dict | None":
    """Compile ``fn(*args)`` and read XLA's own buffer-assignment stats.

    Returns ``{argument, output, temp, alias, total}`` bytes (per
    device for SPMD programs — verified against the sharded exemplar),
    where ``total = argument + output − alias + temp`` is the resident
    footprint the static certificate must bound from above. None when
    the backend reports no analysis."""
    import jax

    compiled = jax.jit(fn, donate_argnums=donate_argnums
                       ).lower(*args).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        return None
    arg = int(ma.argument_size_in_bytes)
    out = int(ma.output_size_in_bytes)
    temp = int(ma.temp_size_in_bytes)
    alias = int(ma.alias_size_in_bytes)
    return {"argument": arg, "output": out, "temp": temp, "alias": alias,
            "total": arg + out - alias + temp}


def crosscheck_ratio(cert: MemoryCertificate,
                     xla: "dict | None") -> "float | None":
    """static / XLA resident-bytes ratio (must be ≥ 1 for a sound upper
    bound; the ``[jaxpr.memory]`` gate pins its ceiling)."""
    if xla is None or not xla.get("total"):
        return None
    return cert.peak_bytes / float(xla["total"])


# --------------------------------------------------------------------------
# budgets
# --------------------------------------------------------------------------

def check_memory_budget(cert: MemoryCertificate, cfg: dict,
                        lanes: "int | None" = None) -> "list[str]":
    """Compare a certificate against the ``[jaxpr.memory]`` budget.

    Keys (all optional):

    * ``max_peak_bytes`` — absolute per-device ceiling;
    * ``max_step_bytes_per_lane`` — ceiling on peak ÷ shard-local lane
      count (requires ``lanes``): the fused round's per-agent-lane
      footprint pin. A regression that parks a new full-horizon buffer
      in the round breaches this and the violation NAMES the offending
      equations (top live buffers with their source lines).

    Returns violation strings (empty = within budget)."""
    out: list = []
    if cert.status == "unknown":
        out.append(f"memory not certified: {cert.describe()}")
        return out

    def name_buffers() -> str:
        rows = [f"{b / 2**20:.2f} MiB {p} at {s}"
                for b, p, s in cert.top_buffers[:4]]
        return "\n  ".join(rows) if rows else "(no interior buffers)"

    cap = cfg.get("max_peak_bytes")
    if cap is not None and cert.peak_bytes > int(cap):
        out.append(
            f"certified peak {cert.peak_bytes} B exceeds the "
            f"max_peak_bytes budget {int(cap)} B. Largest live buffers:"
            f"\n  {name_buffers()}")
    per_lane_cap = cfg.get("max_step_bytes_per_lane")
    if per_lane_cap is not None and lanes:
        per_lane = cert.per_lane_bytes(lanes)
        if per_lane > int(per_lane_cap):
            out.append(
                f"certified peak is {per_lane} B per agent lane "
                f"({lanes} shard-local lane(s)), budget pins "
                f"{int(per_lane_cap)} B/lane — a buffer was added to "
                f"(or grew inside) the fused round. Largest live "
                f"buffers:\n  {name_buffers()}")
    return out


def device_hbm_bytes(device=None) -> "int | None":
    """The backend device's reported memory capacity, or None where the
    backend does not report one (CPU returns no memory_stats)."""
    try:
        import jax

        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats()
    except Exception:  # noqa: BLE001 — absent backends, init races
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit") or stats.get(
        "bytes_reservable_limit")
    return int(limit) if limit else None


# --------------------------------------------------------------------------
# capacity planning
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CapacityPlan:
    """What fits on one device — :func:`plan_capacity`'s answer.

    ``base_bytes`` is the lane-independent resident floor (replicated
    means, schedules, the program's own temps at one lane);
    ``per_lane_bytes`` the certified marginal cost of one more agent
    lane on a device. ``max_agents_per_device`` inverts them against
    the HBM budget; the mesh-level fields scale by the device count."""

    hbm_bytes: int
    base_bytes: int
    per_lane_bytes: int
    max_agents_per_device: int
    max_agents: "int | None" = None           # with a mesh
    max_slot_multiple: "int | None" = None    # serving-plane capacity
    per_scenario_bytes: "int | None" = None
    max_scenarios_per_device: "int | None" = None
    notes: tuple = ()

    def as_dict(self) -> dict:
        return {k: (list(v) if isinstance(v, tuple) else v)
                for k, v in dataclasses.asdict(self).items()}

    def describe(self) -> str:
        out = (f"{self.max_agents_per_device} agent lane(s)/device "
               f"(base {self.base_bytes / 2**20:.2f} MiB + "
               f"{self.per_lane_bytes / 2**20:.2f} MiB/lane vs "
               f"{self.hbm_bytes / 2**20:.0f} MiB HBM)")
        if self.max_agents is not None:
            out += (f"; {self.max_agents} agents / slot multiple "
                    f"{self.max_slot_multiple} on the mesh")
        if self.max_scenarios_per_device is not None:
            out += (f"; {self.max_scenarios_per_device} scenario "
                    f"branch(es)/device")
        return out


def engine_memory_certificate(engine) -> MemoryCertificate:
    """Certify a built engine's step WITHOUT the build-time capacity
    enforcement — the planner's seam (a probe larger than the current
    device must still report its honest number, not raise) and a
    debugging convenience for engines built with
    ``memory_certify="off"``. Returns the engine's attached certificate
    when one exists."""
    if getattr(engine, "memory_certificate", None) is not None:
        return engine.memory_certificate
    import jax

    tmpl = engine._step_templates()
    closed = jax.make_jaxpr(engine._step_fn)(*tmpl)
    donated = None
    if getattr(engine, "donate_state", False):
        n_state = len(jax.tree_util.tree_leaves(tmpl[0]))
        donated = tuple(i < n_state
                        for i in range(len(closed.jaxpr.invars)))
    return certify_memory(closed, donated_invars=donated)


def _fleet_certificate(ocp, options, n_agents: int, couplings: dict,
                       solver_options=None, mesh=None,
                       qp_routing: "list | None" = None
                       ) -> MemoryCertificate:
    """Certificate of a consensus-fleet probe build at ``n_agents``
    lanes (both certifications off — the planner proves bytes, without
    the build-time capacity enforcement). ``qp_routing`` is a 1-cell
    mutable memo: the first probe resolves the group's QP routing,
    later probes force it so repeat builds never re-certify."""
    from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup, FusedADMM

    kwargs = {} if solver_options is None else {
        "solver_options": solver_options}
    if qp_routing and qp_routing[0] is not None:
        kwargs["qp_fast_path"] = qp_routing[0]
    group = AgentGroup(name="plan-probe", ocp=ocp, n_agents=n_agents,
                       couplings=dict(couplings), **kwargs)
    engine = FusedADMM([group], options, memory_certify="off",
                       collective_certify="off", mesh=mesh)
    if qp_routing is not None and qp_routing[0] is None:
        qp_routing[0] = "on" if engine.group_uses_qp[0] else "off"
    return engine_memory_certificate(engine)


def plan_capacity(ocp, options, hbm_bytes: int, mesh=None,
                  couplings: "dict | None" = None,
                  solver_options=None,
                  scenario_tree=None, refine: bool = True,
                  max_probe_builds: int = 8) -> CapacityPlan:
    """Invert the certified per-lane marginal memory cost into device
    capacity: max agents per device, max scenario branches per device,
    and the largest serving-slot multiple that fits ``hbm_bytes``.

    Two probe builds (2 and 4 agent lanes — every carried and history
    buffer is lane-batched, so the footprint is near-linear in the lane
    count) give the affine model; with ``refine=True`` the candidate is
    then verified against REAL probe certificates — built on ``mesh``
    when one is given, per-device otherwise — and walked until
    ``peak(planned) ≤ hbm < peak(planned + 1 lane)`` holds by
    construction (allocation-granularity stepping makes a pure affine
    inversion over-promise by a lane or two). Runs anywhere: a laptop
    can plan a pod, because the single-device certificate at the
    shard-local lane count upper-bounds the sharded round's per-device
    footprint. ``scenario_tree`` adds two
    :class:`~agentlib_mpc_tpu.scenario.ScenarioFleet` probes for the
    scenario-axis marginal."""
    from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions

    if options is None:
        options = FusedADMMOptions()
    if couplings is None:
        # default: consensus on the first control — structurally the
        # worst case the serving plane hosts (every lane carries
        # multipliers + histories for the alias)
        name = ocp.control_names[0]
        couplings = {f"__plan_{name}": name}
    notes: list = []
    qp_memo: list = [None]
    n_dev = 1 if mesh is None else max(1, int(mesh.devices.size))

    probes: dict = {}

    def peak_at(lanes_per_device: int) -> int:
        """Certified per-device peak at ``lanes_per_device`` — a mesh
        probe when a mesh is given (the real sharded program), a
        single-device fleet otherwise."""
        if lanes_per_device not in probes:
            cert = _fleet_certificate(
                ocp, options, lanes_per_device * n_dev, couplings,
                solver_options, mesh=mesh, qp_routing=qp_memo)
            if not cert.proved:
                notes.append(f"probe at {lanes_per_device} lane(s) not "
                             f"proved ({cert.status})")
            probes[lanes_per_device] = int(cert.peak_bytes)
        return probes[lanes_per_device]

    p2, p4 = peak_at(2), peak_at(4)
    per_lane = max((p4 - p2) // 2, 1)
    base = max(p2 - 2 * per_lane, 0)
    hbm = int(hbm_bytes)
    max_per_dev = max(int((hbm - base) // per_lane), 0)
    if refine and max_per_dev >= 1:
        budget = max(int(max_probe_builds) - len(probes), 1)
        while budget > 0 and max_per_dev >= 1 \
                and peak_at(max_per_dev) > hbm:
            max_per_dev -= 1
            budget -= 1
        while budget > 0 and peak_at(max_per_dev + 1) <= hbm:
            max_per_dev += 1
            budget -= 1
        if probes.get(max_per_dev, 0) > hbm or max_per_dev not in probes:
            # the probe-build budget ran out mid-walk: the refined
            # claim "peak(planned) <= hbm" must never be returned
            # unverified — clamp to the largest probe that PROVABLY
            # fits (the affine candidate was over-promising)
            fitting = [k for k, v in probes.items() if v <= hbm]
            max_per_dev = max(fitting, default=0)
            notes.append(
                f"probe-build budget exhausted refining the affine "
                f"candidate — clamped to the largest VERIFIED fit "
                f"(max_agents_per_device={max_per_dev}); raise "
                f"max_probe_builds for a tighter answer")

    max_agents = max_slot = None
    if mesh is not None:
        from agentlib_mpc_tpu.parallel.multihost import (
            serving_slot_multiple,
        )

        max_agents = max_per_dev * n_dev
        sm = serving_slot_multiple(mesh)
        max_slot = (max_agents // sm) * sm

    per_scen = max_scen = None
    if scenario_tree is not None:
        try:
            from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup
            from agentlib_mpc_tpu.scenario import ScenarioFleet
            from agentlib_mpc_tpu.scenario.fleet import (
                ScenarioFleetOptions,
            )
            from agentlib_mpc_tpu.scenario.tree import (
                fan_tree,
                single_scenario,
            )

            scen_opts = ScenarioFleetOptions(
                max_iterations=options.max_iterations)
            kwargs = {} if solver_options is None else {
                "solver_options": solver_options}
            group = AgentGroup(name="plan-scen", ocp=ocp, n_agents=1,
                               couplings=dict(couplings), **kwargs)
            certs = {}
            for s in (1, 2):
                tree = fan_tree(s, robust_horizon=1) if s > 1 \
                    else single_scenario()
                fleet = ScenarioFleet(group, tree, scen_opts,
                                      memory_certify="off",
                                      collective_certify="off")
                certs[s] = engine_memory_certificate(fleet)
            per_scen = max(
                int(certs[2].peak_bytes - certs[1].peak_bytes), 1)
            scen_base = max(int(certs[1].peak_bytes - per_scen), 0)
            max_scen = max(int((hbm - scen_base) // per_scen), 0)
        except Exception as exc:  # noqa: BLE001 — planning stays usable
            notes.append(f"scenario probe failed: {exc!r}")
    plan = CapacityPlan(
        hbm_bytes=hbm, base_bytes=base, per_lane_bytes=per_lane,
        max_agents_per_device=max_per_dev, max_agents=max_agents,
        max_slot_multiple=max_slot, per_scenario_bytes=per_scen,
        max_scenarios_per_device=max_scen, notes=tuple(notes))
    logger.info("capacity plan: %s", plan.describe())
    return plan


# --------------------------------------------------------------------------
# the CI gate
# --------------------------------------------------------------------------

def memory_gate_summary(budgets: "dict | None" = None) -> dict:
    """The ``--memory-budget`` CLI gate (also a ``--jaxpr`` leg and the
    ``memory_certificates`` section of ``bench.py --emit-metrics``):

    1. **menu sweep** — certify f/g/h of every example OCP and
       cross-check against XLA's ``memory_analysis``: the static peak
       must bound XLA's resident total from above within the
       ``[jaxpr.memory]`` ``max_xla_ratio`` pin — the proof stays
       anchored to ground truth;
    2. **fused tracker fleet** — the mesh gate fleet's step certified
       per device, held to ``max_step_bytes_per_lane``, and
       cross-checked against the compiled step's own XLA numbers.
       Needs ≥ 2 devices (CI pins 8 virtual); skipped with a note
       otherwise."""
    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.lint.jaxpr.examples import EXAMPLE_OCPS
    from agentlib_mpc_tpu.lint.retrace_budget import load_budgets

    cfg = (budgets if budgets is not None else load_budgets()).get(
        "jaxpr", {}).get("memory", {})
    max_ratio = float(cfg.get("max_xla_ratio", 16.0))
    # the ratio ceiling only signals slackness when XLA kept real
    # buffers: a function XLA constant-folds to a handful of bytes
    # makes any static estimate look "23x" while the absolute gap is a
    # few hundred bytes — below the slack floor only the lower bound
    # (static >= XLA) is enforced
    ratio_slack = int(cfg.get("xla_ratio_slack_bytes", 4096))
    rows: list = []
    failures = 0

    for ex in EXAMPLE_OCPS:
        ocp = ex.build()
        theta = ocp.default_params()
        w0 = jnp.zeros((ocp.n_w,))
        entry = {"name": ex.name, "functions": {}}
        for fname, fn in (("f", ocp.nlp.f), ("g", ocp.nlp.g),
                          ("h", ocp.nlp.h)):
            cert = certify_memory(fn, w0, theta)
            try:
                xla = xla_memory_analysis(fn, w0, theta)
            except Exception as exc:  # noqa: BLE001 — report, not crash
                xla = None
                entry.setdefault("errors", []).append(
                    f"{fname}: {exc!r}")
            ratio = crosscheck_ratio(cert, xla)
            fail = None
            if not cert.proved:
                fail = f"{fname}: {cert.describe()}"
            elif ratio is None:
                # the gate's whole claim is the XLA anchor — a backend
                # that stops reporting memory_analysis must FAIL the
                # gate loudly, not pass it with zero comparisons made
                fail = (f"{fname}: XLA cross-check unavailable "
                        f"(memory_analysis returned nothing) — the "
                        f"static bound is unanchored")
            elif ratio < 1.0:
                fail = (f"{fname}: certified peak {cert.peak_bytes} B "
                        f"does NOT bound XLA's {xla['total']} B — the "
                        f"certifier undercounts")
            elif ratio > max_ratio and cert.peak_bytes > ratio_slack:
                fail = (f"{fname}: certified peak is {ratio:.1f}x "
                        f"XLA's {xla['total']} B (pin {max_ratio}x) — "
                        f"the bound went slack")
            entry["functions"][fname] = {
                "peak_bytes": cert.peak_bytes,
                "xla_total_bytes": None if xla is None else xla["total"],
                "xla_ratio": None if ratio is None else round(ratio, 2),
                "status": cert.status,
                "failure": fail,
            }
            if fail:
                failures += 1
        rows.append(entry)

    fleet_row: dict = {"name": "tracker-consensus-fleet"}
    n_dev = len(jax.devices())
    if n_dev >= 2:
        try:
            from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
            from agentlib_mpc_tpu.ops.solver import SolverOptions
            from agentlib_mpc_tpu.parallel import multihost
            from agentlib_mpc_tpu.parallel.fused_admm import (
                AgentGroup,
                FusedADMM,
                FusedADMMOptions,
            )

            ocp = tracker_ocp()
            mesh = multihost.fleet_mesh()
            group = AgentGroup(
                name="memory-gate", ocp=ocp, n_agents=n_dev,
                couplings={"shared_u": "u"},
                solver_options=SolverOptions(max_iter=30))
            engine = FusedADMM(
                [group], FusedADMMOptions(max_iterations=8, rho=2.0),
                mesh=mesh, memory_certify="require")
            cert = engine.memory_certificate
            lanes = max(n_dev // int(mesh.devices.size), 1)
            violations = check_memory_budget(cert, cfg, lanes=lanes)
            xla = None
            try:
                tmpl = engine._step_templates()
                compiled = engine._step.lower(*tmpl).compile()
                ma = compiled.memory_analysis()
                if ma is not None:
                    xla = {"argument": int(ma.argument_size_in_bytes),
                           "output": int(ma.output_size_in_bytes),
                           "temp": int(ma.temp_size_in_bytes),
                           "alias": int(ma.alias_size_in_bytes)}
                    xla["total"] = (xla["argument"] + xla["output"]
                                    - xla["alias"] + xla["temp"])
            except Exception as exc:  # noqa: BLE001 — AOT quirks
                fleet_row["xla_error"] = repr(exc)
            ratio = crosscheck_ratio(cert, xla)
            if ratio is None:
                violations.append(
                    "fused-step XLA cross-check unavailable — the "
                    "per-lane pin still holds, but the bound is "
                    "unanchored: " + fleet_row.get("xla_error",
                                                   "no memory_analysis"))
            elif ratio < 1.0:
                violations.append(
                    f"fused-step certificate {cert.peak_bytes} B does "
                    f"NOT bound XLA's {xla['total']} B per device")
            elif ratio is not None and ratio > max_ratio \
                    and cert.peak_bytes > ratio_slack:
                violations.append(
                    f"fused-step certificate is {ratio:.1f}x XLA's "
                    f"{xla['total']} B (pin {max_ratio}x)")
            failures += len(violations)
            fleet_row.update({
                "certificate": cert.as_dict(),
                "peak_bytes": cert.peak_bytes,
                "bytes_per_lane": cert.per_lane_bytes(lanes),
                "lanes_per_device": lanes,
                "xla": xla,
                "xla_ratio": None if ratio is None else round(ratio, 2),
                "violations": violations,
            })
        except Exception as exc:  # noqa: BLE001 — report, not crash
            fleet_row["error"] = repr(exc)
            failures += 1
    else:
        fleet_row["skipped"] = (
            f"needs a multi-device mesh; {n_dev} device(s) visible — "
            f"set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            f"like CI does")
    return {"examples": rows, "fleet": fleet_row, "failures": failures,
            "devices": n_dev, "budget": dict(cfg)}
