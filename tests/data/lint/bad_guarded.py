"""Golden-file fixture: guarded-field mutation outside its lock and
callback registration under the dispatch lock."""

import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()  # lint: dispatch-lock
        self._subs = []  # guarded-by: self._lock
        self._warned = set()  # guarded-by: self._lock

    def good_add(self, item):
        with self._lock:
            self._subs.append(item)

    def bad_add(self, item):
        self._subs.append(item)          # mutation without the lock

    def bad_replace(self, items):
        self._subs = list(items)         # rebind without the lock

    def bad_reentry(self, broker, cb):
        with self._lock:
            self._warned.add("x")        # fine: lock held
            broker.register_callback("a", None, cb)   # deadlock shape
