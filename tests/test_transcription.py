"""Tests for collocation matrices and OCP transcription.

Covers the same ground as the reference's backend-construction tests
(tests/test_casadi_backend.py: shapes, grids, system setup) plus direct
verification of the collocation math and a full OCP solve on a problem with
a known analytic solution (double integrator).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.objective import SubObjective
from agentlib_mpc_tpu.models.variables import control_input, parameter, state
from agentlib_mpc_tpu.ops.collocation import collocation_matrices, collocation_points
from agentlib_mpc_tpu.ops.solver import SolverOptions, solve_nlp
from agentlib_mpc_tpu.ops.transcription import transcribe


class DoubleIntegrator(Model):
    inputs = [control_input("u", 0.0, lb=-2.0, ub=2.0)]
    states = [state("pos", 0.0), state("vel", 0.0)]
    parameters = [parameter("r", 0.01)]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("pos", v.vel)
        eq.ode("vel", v.u)
        eq.objective = SubObjective(
            (v.pos - 1.0) ** 2 + 0.1 * v.vel**2 + v.r * v.u**2, name="track")
        return eq


# ---- collocation matrices ----------------------------------------------------


@pytest.mark.parametrize("method", ["radau", "legendre"])
@pytest.mark.parametrize("degree", [1, 2, 3, 4])
def test_quadrature_weights_integrate_polynomials(degree, method):
    """B must integrate polynomials up to the scheme's degree exactly."""
    taus, C, D, B = collocation_matrices(degree, method)
    for k in range(degree + 1):
        exact = 1.0 / (k + 1)
        approx = float(np.sum(B * taus**k))
        np.testing.assert_allclose(approx, exact, rtol=1e-10)


@pytest.mark.parametrize("method", ["radau", "legendre"])
@pytest.mark.parametrize("degree", [1, 2, 3])
def test_derivative_matrix_differentiates_polynomials(degree, method):
    taus, C, D, B = collocation_matrices(degree, method)
    for k in range(degree + 1):
        vals = taus**k
        deriv_exact = k * taus ** max(k - 1, 0) if k > 0 else np.zeros_like(taus)
        for col in range(1, degree + 1):
            approx = float(np.sum(C[:, col] * vals))
            np.testing.assert_allclose(approx, deriv_exact[col], atol=1e-9)


def test_continuity_vector_extrapolates(capsys):
    taus, C, D, B = collocation_matrices(3, "radau")
    # D evaluates the interpolating polynomial at tau=1
    for k in range(4):
        np.testing.assert_allclose(float(np.sum(D * taus**k)), 1.0, atol=1e-9)


def test_radau_includes_endpoint():
    pts = collocation_points(3, "radau")
    np.testing.assert_allclose(pts[-1], 1.0, atol=1e-12)


def test_radau_iia_node_values():
    """Pin the canonical Radau IIA nodes (not their left-Radau mirror)."""
    np.testing.assert_allclose(collocation_points(1, "radau"), [1.0],
                               atol=1e-12)
    np.testing.assert_allclose(collocation_points(2, "radau"),
                               [1.0 / 3.0, 1.0], atol=1e-12)
    np.testing.assert_allclose(
        collocation_points(3, "radau"),
        [(4 - np.sqrt(6)) / 10, (4 + np.sqrt(6)) / 10, 1.0], atol=1e-9)


# ---- transcription shapes ----------------------------------------------------


@pytest.mark.parametrize("method", ["collocation", "multiple_shooting"])
def test_sizes_and_bounds(method):
    m = DoubleIntegrator()
    ocp = transcribe(m, ["u"], N=5, dt=0.2, method=method,
                     collocation_degree=2)
    assert ocp.n_w > 0
    theta = ocp.default_params()
    lb, ub = ocp.bounds(theta)
    assert lb.shape == (ocp.n_w,) and ub.shape == (ocp.n_w,)
    w0 = ocp.initial_guess(theta)
    assert w0.shape == (ocp.n_w,)
    assert ocp.nlp.g(w0, theta).shape == (ocp.n_g,)
    assert ocp.nlp.h(w0, theta).shape == (ocp.n_h,)
    # control bounds from the Var declaration survive into the NLP bounds
    w = ocp.unflatten(lb)
    np.testing.assert_allclose(w["u"], -2.0 * np.ones((5, 1)))


def test_collocation_equality_count():
    m = DoubleIntegrator()
    N, d, nx = 4, 3, 2
    ocp = transcribe(m, ["u"], N=N, dt=0.1, collocation_degree=d)
    # initial condition + defects (N*d*nx) + continuity (N*nx)
    assert ocp.n_g == nx + N * d * nx + N * nx


@pytest.mark.parametrize("method", ["collocation", "multiple_shooting"])
def test_dynamics_feasibility_is_satisfiable(method):
    """g(w)=0 must hold when w is filled from an exact simulation of the
    dynamics under zero control (pos stays, vel stays)."""
    m = DoubleIntegrator()
    ocp = transcribe(m, ["u"], N=3, dt=0.1, method=method)
    theta = ocp.default_params(x0=jnp.array([1.0, 0.0]))
    w = ocp.unflatten(ocp.initial_guess(theta))
    # constant state [1, 0], u = 0 is an exact trajectory
    w["x"] = jnp.tile(jnp.array([1.0, 0.0]), (ocp.N + 1, 1))
    w["u"] = jnp.zeros_like(w["u"])
    if "xc" in w:
        w["xc"] = jnp.tile(jnp.array([1.0, 0.0]), (ocp.N, w["xc"].shape[1], 1))
    g = ocp.nlp.g(ocp.flatten(w), theta)
    np.testing.assert_allclose(g, np.zeros_like(g), atol=1e-10)


# ---- end-to-end OCP solves ---------------------------------------------------


@pytest.mark.parametrize("method", ["collocation", "multiple_shooting"])
def test_double_integrator_reaches_target(method):
    m = DoubleIntegrator()
    ocp = transcribe(m, ["u"], N=20, dt=0.25, method=method,
                     collocation_degree=2)
    theta = ocp.default_params(x0=jnp.array([0.0, 0.0]))
    lb, ub = ocp.bounds(theta)
    res = solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta, lb, ub,
                    SolverOptions(tol=1e-7, max_iter=150))
    assert res.stats.success
    traj = ocp.trajectories(res.w, theta)
    # position must approach the target 1.0 by the end of the horizon
    assert abs(float(traj["x"][-1, 0]) - 1.0) < 0.05
    # control bound respected
    assert float(jnp.max(jnp.abs(traj["u"]))) <= 2.0 + 1e-6


def test_shift_guess_pins_new_state():
    m = DoubleIntegrator()
    ocp = transcribe(m, ["u"], N=4, dt=0.1)
    theta = ocp.default_params(x0=jnp.array([0.5, 0.5]))
    w = ocp.initial_guess(ocp.default_params())
    shifted = ocp.unflatten(ocp.shift_guess(w, theta))
    np.testing.assert_allclose(shifted["x"][0], [0.5, 0.5])


def test_solve_is_vmappable():
    """Batch of OCPs with different initial states — one compiled solve."""
    m = DoubleIntegrator()
    ocp = transcribe(m, ["u"], N=10, dt=0.25, collocation_degree=2)
    x0s = jnp.array([[0.0, 0.0], [0.5, -0.5], [-0.3, 0.2]])

    def solve_one(x0):
        theta = ocp.default_params(x0=x0)
        lb, ub = ocp.bounds(theta)
        return solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta, lb, ub,
                         SolverOptions(tol=1e-6, max_iter=120))

    res = jax.vmap(solve_one)(x0s)
    assert bool(jnp.all(res.stats.success))
