"""Per-tenant SLO / error-budget accounting for the serving fleet.

The serving plane already *reacts* to failures (guard ladders, health
evictions, watchdogs); this module makes them *accountable*: each
tenant carries objectives — availability (actuated ÷ delivered results)
and deadline adherence — tracked cumulatively and over sliding round
windows, with multi-window **error-budget burn rates** (the
Google-SRE alerting shape: a fast window catches a cliff, a slow window
catches a leak; burn rate 1.0 = consuming exactly the budget the target
allows, >1 = on track to violate).

Fed purely from the per-round results the plane already produces
(``ServingPlane._assess_bucket`` verdicts + shed decisions), so the
whole report is **recomputable offline** from the journal's
``serve.round`` events (:func:`slo_from_events`) — the number the bench
publishes, the number ``slo_report()`` returns and the number an
auditor recomputes from the flight recorder must all agree.

Availability counts exactly what ``bench.py --chaos-serve`` counts: a
delivered result is *available* only when the guard actuated the fresh
solve (``action == "actuate"``); replay/hold/fallback rounds and every
shed (overload, deadline, eviction, poisoned theta) are delivered but
unavailable.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Iterable

from agentlib_mpc_tpu.telemetry import registry as _registry_mod


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Objectives and windows (plane config key ``slo_policy``)."""

    #: target fraction of delivered results that actuate a fresh solve
    availability_target: float = 0.99
    #: target fraction of submissions that meet their deadline
    deadline_target: float = 0.99
    #: sliding windows, in served rounds (fast, slow) — burn rates are
    #: reported per window
    windows: tuple = (8, 32)

    def __post_init__(self):
        for t in (self.availability_target, self.deadline_target):
            if not (0.0 < t < 1.0):
                raise ValueError(f"SLO targets must sit in (0, 1), "
                                 f"got {t}")
        if not self.windows or any(int(w) < 1 for w in self.windows):
            raise ValueError(f"windows must be >= 1 round each, "
                             f"got {self.windows}")

    @classmethod
    def from_config(cls, cfg: dict) -> "SLOPolicy":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown slo option(s) {sorted(unknown)}; "
                             f"known: {sorted(known)}")
        if "windows" in cfg:
            cfg = dict(cfg, windows=tuple(int(w)
                                          for w in cfg["windows"]))
        return cls(**cfg)


class _TenantLedger:
    """One tenant's tallies: cumulative + a per-round ring of
    (delivered, actuated, deadline_missed) triples."""

    __slots__ = ("delivered", "actuated", "deadline_missed",
                 "cur", "recent")

    def __init__(self, max_window: int):
        self.delivered = 0
        self.actuated = 0
        self.deadline_missed = 0
        self.cur = [0, 0, 0]
        self.recent = deque(maxlen=max_window)


class SLOTracker:
    """Accumulates per-tenant verdicts and renders the SLO report.

    Wire-up (the plane does all of this): ``record_result`` per
    delivered result or shed decision, ``tick_round`` once per served
    round (returns that round's tally — what the plane journals as the
    ``serve.round`` event, making the report offline-recomputable).
    """

    def __init__(self, policy: SLOPolicy = SLOPolicy()):
        self.policy = policy
        self._max_window = max(int(w) for w in policy.windows)
        self._rows: dict = {}
        #: tenants that left: tenant_id -> rounds of tombstone left.
        #: ``forget`` no longer drops the ledger — a tenant that
        #: leaves and rejoins inside ``max_window`` rounds resumes its
        #: burn windows where it left them (a flapping tenant cannot
        #: launder its burn by churning membership); after
        #: ``max_window`` tombstoned rounds the windows have fully
        #: aged out and the row really goes
        self._tombstones: dict = {}
        self.rounds = 0
        #: the caller's round clock at the last tick (drift check
        #: against the journal's serve.round stamps)
        self.last_round_index: "int | None" = None

    def _row(self, tenant_id: str) -> _TenantLedger:
        # any access revives a tombstoned row: the rejoining tenant
        # resumes its windows (the whole point of the tombstone)
        self._tombstones.pop(tenant_id, None)
        row = self._rows.get(tenant_id)
        if row is None:
            row = self._rows[tenant_id] = _TenantLedger(self._max_window)
        return row

    # -- feed -----------------------------------------------------------------

    def record_result(self, tenant_id: str, action: str,
                      deadline_missed: bool = False) -> None:
        """One delivered verdict: a guard action (actuate / replay /
        hold / fallback) from a served result OR a shed decision."""
        row = self._row(tenant_id)
        ok = action == "actuate"
        row.delivered += 1
        row.actuated += int(ok)
        row.deadline_missed += int(bool(deadline_missed))
        row.cur[0] += 1
        row.cur[1] += int(ok)
        row.cur[2] += int(bool(deadline_missed))

    def forget(self, tenant_id: str) -> None:
        """Tombstone a departed tenant's ledger for ``max_window``
        rounds instead of dropping it: the row keeps aging through
        the sliding windows (and keeps counting in the fleet roll-up —
        budgets are an accounting record) but leaves the per-tenant
        report; a rejoin inside the window resumes the burn exactly
        where it stood. Dropping immediately let a flapping tenant
        restart its windows from zero each rejoin — burn laundering."""
        if tenant_id in self._rows:
            self._tombstones[tenant_id] = self._max_window

    def tick_round(self, round_index: "int | None" = None) -> dict:
        """Close the current round: push each tenant's tally into the
        sliding windows and return ``{tenant: [delivered, actuated,
        deadline_missed]}`` — the journal payload. ``round_index`` is
        the caller's round clock, kept on ``last_round_index`` so a
        drift between the tracker and the journal's ``serve.round``
        stamps is observable. Exports the ``serving_slo_*`` gauges for
        the tenants with traffic this round (the others' numbers did
        not move — at 10k tenants a full re-export per round would be
        the serving loop's dominant host cost)."""
        self.rounds += 1
        if round_index is not None:
            self.last_round_index = int(round_index)
        tally = {}
        for tid, row in self._rows.items():
            if row.cur != [0, 0, 0]:
                tally[tid] = list(row.cur)
            row.recent.append(tuple(row.cur))
            row.cur = [0, 0, 0]
        # tombstoned rows age like every other idle tenant above; once
        # the windows have fully cycled the ledger really goes
        for tid in list(self._tombstones):
            self._tombstones[tid] -= 1
            if self._tombstones[tid] <= 0:
                del self._tombstones[tid]
                self._rows.pop(tid, None)
        self._export_gauges(tally.keys())
        return tally

    # -- report ---------------------------------------------------------------

    @staticmethod
    def _rate(num: int, den: int) -> "float | None":
        return None if den <= 0 else num / den

    def _window_stats(self, row: _TenantLedger, window: int) -> dict:
        recent = list(row.recent)[-int(window):]
        delivered = sum(r[0] for r in recent)
        actuated = sum(r[1] for r in recent)
        avail = self._rate(actuated, delivered)
        # burn rate: observed miss fraction over the window, in units of
        # the budgeted miss fraction (1 - target); 1.0 = burning exactly
        # the allowed budget, >1 = violating if sustained
        budget = 1.0 - self.policy.availability_target
        burn = None if avail is None else (1.0 - avail) / budget
        return {
            "delivered": delivered,
            "availability_pct": (None if avail is None
                                 else round(100.0 * avail, 3)),
            "burn_rate": None if burn is None else round(burn, 3),
        }

    def burn_rates(self) -> dict:
        """Per-tenant windowed burn rates, ``{tenant: {window: burn}}``
        (``None`` for windows with no delivered traffic) — the SLO
        autopilot's controller input, public so policy code never
        reaches into the ledger rows."""
        return {
            tid: {int(w): self._window_stats(row, w)["burn_rate"]
                  for w in self.policy.windows}
            for tid, row in self._rows.items()}

    def _tenant_report(self, row: _TenantLedger) -> dict:
        avail = self._rate(row.actuated, row.delivered)
        deadline_hit = self._rate(row.delivered - row.deadline_missed,
                                  row.delivered)
        # error budget: the miss allowance the availability target
        # grants over everything delivered so far; remaining < 0 means
        # the objective is already violated for this horizon
        allowed = (1.0 - self.policy.availability_target) * row.delivered
        consumed = row.delivered - row.actuated
        remaining = None if row.delivered == 0 else \
            1.0 - (consumed / allowed if allowed > 0 else float(consumed))
        return {
            "delivered": row.delivered,
            "actuated": row.actuated,
            "availability_pct": (None if avail is None
                                 else round(100.0 * avail, 3)),
            "deadline_hit_pct": (None if deadline_hit is None
                                 else round(100.0 * deadline_hit, 3)),
            "error_budget_remaining": (None if remaining is None
                                       else round(remaining, 4)),
            "slo_met": (None if avail is None else
                        avail >= self.policy.availability_target),
            "windows": {str(w): self._window_stats(row, w)
                        for w in self.policy.windows},
        }

    def report(self) -> dict:
        """The full SLO report: per-tenant objectives + a fleet roll-up
        (what ``ServingPlane.slo_report()`` returns and the chaos bench
        publishes)."""
        # tombstoned (departed) tenants leave the per-tenant section
        # but keep counting in the fleet sums: the roll-up is an
        # accounting record, not a membership list
        tenants = {tid: self._tenant_report(row)
                   for tid, row in sorted(self._rows.items())
                   if tid not in self._tombstones}
        delivered = sum(r.delivered for r in self._rows.values())
        actuated = sum(r.actuated for r in self._rows.values())
        missed = sum(r.deadline_missed for r in self._rows.values())
        avail = self._rate(actuated, delivered)
        return {
            "policy": {
                "availability_target": self.policy.availability_target,
                "deadline_target": self.policy.deadline_target,
                "windows": list(self.policy.windows),
            },
            "rounds": self.rounds,
            "fleet": {
                "delivered": delivered,
                "actuated": actuated,
                "availability_pct": (None if avail is None
                                     else round(100.0 * avail, 3)),
                "deadline_missed": missed,
                "tenants_in_violation": sum(
                    1 for t in tenants.values()
                    if t["slo_met"] is False),
            },
            "tenants": tenants,
        }

    def _export_gauges(self, tenant_ids=None) -> None:
        reg = _registry_mod.DEFAULT
        if not reg._enabled:
            return
        avail_g = reg.gauge(
            "serving_slo_availability_pct",
            "per-tenant cumulative availability (actuated / delivered)")
        budget_g = reg.gauge(
            "serving_slo_error_budget_remaining",
            "fraction of the tenant's availability error budget left "
            "(1 = untouched, <= 0 = objective violated)")
        burn_g = reg.gauge(
            "serving_slo_burn_rate",
            "windowed error-budget burn rate (1 = exactly the budgeted "
            "miss rate)")
        ids = (self._rows.keys() if tenant_ids is None
               else tenant_ids)
        for tid in ids:
            row = self._rows.get(tid)
            if row is None:
                continue
            rep = self._tenant_report(row)
            if rep["availability_pct"] is not None:
                avail_g.set(rep["availability_pct"], tenant=tid)
            if rep["error_budget_remaining"] is not None:
                budget_g.set(rep["error_budget_remaining"], tenant=tid)
            for w, ws in rep["windows"].items():
                if ws["burn_rate"] is not None:
                    burn_g.set(ws["burn_rate"], tenant=tid, window=w)

    # -- checkpoint seam ------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able state for the plane checkpoint (crash/restart must
        not reset error budgets — a restore that forgot the burn would
        report a fresh 100% budget mid-incident)."""
        return {
            "rounds": int(self.rounds),
            "tenants": {
                tid: {"delivered": row.delivered,
                      "actuated": row.actuated,
                      "deadline_missed": row.deadline_missed,
                      "recent": [list(r) for r in row.recent]}
                for tid, row in self._rows.items()},
            "tombstones": {tid: int(n)
                           for tid, n in self._tombstones.items()},
        }

    def restore(self, snap: "dict | None") -> None:
        if not snap:
            return
        self.rounds = int(snap.get("rounds") or 0)
        for tid, s in (snap.get("tenants") or {}).items():
            row = self._row(tid)
            row.delivered = int(s.get("delivered") or 0)
            row.actuated = int(s.get("actuated") or 0)
            row.deadline_missed = int(s.get("deadline_missed") or 0)
            row.recent.clear()
            for r in s.get("recent") or []:
                row.recent.append(tuple(int(x) for x in r))
        for tid, n in (snap.get("tombstones") or {}).items():
            if tid in self._rows:
                self._tombstones[tid] = int(n)


def slo_from_events(events: Iterable,
                    policy: "SLOPolicy | None" = None) -> dict:
    """Recompute the SLO report offline from journal ``serve.round``
    events (each carries the round's ``{tenant: [delivered, actuated,
    deadline_missed]}`` tally) — byte-for-byte the same report shape as
    :meth:`SLOTracker.report`, from the flight recorder alone.

    ``policy=None`` reads the plane's OWN policy from the journal's
    ``slo.policy`` event (the plane journals it once per process, so an
    auditor with only the tape recomputes against the same targets and
    windows the live report used); an explicit policy overrides, and
    the default applies only to a tape that predates policy stamping."""
    events = list(events)
    if policy is None:
        stamped = [e for e in events if e.get("etype") == "slo.policy"]
        if stamped:
            last = stamped[-1]
            policy = SLOPolicy(
                availability_target=float(
                    last.get("availability_target", 0.99)),
                deadline_target=float(
                    last.get("deadline_target", 0.99)),
                windows=tuple(int(w)
                              for w in last.get("windows") or (8, 32)))
        else:
            policy = SLOPolicy()
    tracker = SLOTracker(policy)
    for ev in events:
        if ev.get("etype") != "serve.round":
            continue
        tally = ev.get("tally") or {}
        for tid, counts in tally.items():
            d, a, m = (int(x) for x in counts)
            row = tracker._row(tid)
            row.delivered += d
            row.actuated += a
            row.deadline_missed += m
            row.recent.append((d, a, m))
        # idle-but-known tenants age through the sliding windows
        # exactly like the online tracker's tick_round
        for tid, row in tracker._rows.items():
            if tid not in tally:
                row.recent.append((0, 0, 0))
        tracker.rounds += 1
    return tracker.report()
