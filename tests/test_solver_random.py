"""Randomized cross-validation of the interior-point solver.

Property-style hardening beyond the named problems in test_solver.py:
strictly convex random QPs with boxes and equality constraints have a
unique optimum that an independent solver (SciPy SLSQP) can certify —
5 seeded instances per shape class (20 across the classes), exact
agreement required. The
reference leans on IPOPT's decades of hardening here; this is the
analogous evidence for the native solver.
"""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import minimize

from agentlib_mpc_tpu.ops.solver import (
    NLPFunctions,
    SolverOptions,
    solve_nlp,
)

OPTS = SolverOptions(tol=1e-8, max_iter=120)


def _random_qp(rng, n, m_eq):
    A = rng.normal(size=(n, n))
    Q = A @ A.T + n * np.eye(n)          # strictly convex
    c = rng.normal(size=n) * 2.0
    lb = -1.0 - rng.random(n)
    ub = 1.0 + rng.random(n)
    Aeq = rng.normal(size=(m_eq, n)) if m_eq else np.zeros((0, n))
    # a feasible interior point guarantees a consistent system
    x_feas = lb + (ub - lb) * rng.random(n)
    beq = Aeq @ x_feas
    return Q, c, lb, ub, Aeq, beq


def _scipy_solution(Q, c, lb, ub, Aeq, beq):
    cons = []
    if Aeq.shape[0]:
        cons.append({"type": "eq", "fun": lambda x: Aeq @ x - beq,
                     "jac": lambda x: Aeq})
    res = minimize(
        lambda x: 0.5 * x @ Q @ x + c @ x,
        jac=lambda x: Q @ x + c,
        x0=np.clip(np.zeros_like(c), lb, ub),
        bounds=list(zip(lb, ub)), constraints=cons, method="SLSQP",
        options={"maxiter": 500, "ftol": 1e-12})
    assert res.success, res.message
    return res.x


@pytest.mark.parametrize("n,m_eq", [
    (4, 0), (8, 0),
    pytest.param(8, 3, marks=pytest.mark.slow),
    pytest.param(12, 5, marks=pytest.mark.slow),
])
def test_random_qps_match_scipy(n, m_eq):
    rng = np.random.default_rng(n * 100 + m_eq)
    for trial in range(5):
        Q, c, lb, ub, Aeq, beq = _random_qp(rng, n, m_eq)
        Qj, cj = jnp.asarray(Q), jnp.asarray(c)
        Aj, bj = jnp.asarray(Aeq), jnp.asarray(beq)
        nlp = NLPFunctions(
            f=lambda w, t: 0.5 * w @ Qj @ w + cj @ w,
            g=(lambda w, t: Aj @ w - bj) if m_eq else
            (lambda w, t: jnp.zeros((0,))),
            h=lambda w, t: jnp.zeros((0,)),
        )
        res = solve_nlp(nlp, jnp.zeros(n), None, jnp.asarray(lb),
                        jnp.asarray(ub), OPTS)
        assert bool(res.stats.success), f"trial {trial} failed to converge"
        x_ref = _scipy_solution(Q, c, lb, ub, Aeq, beq)
        np.testing.assert_allclose(
            np.asarray(res.w), x_ref, atol=2e-5,
            err_msg=f"trial {trial} (n={n}, m_eq={m_eq})")


@pytest.mark.slow
def test_random_qp_with_inequalities_matches_scipy():
    """General linear inequalities Gx >= h exercised through the slack
    path (s, z duals) as well."""
    rng = np.random.default_rng(7)
    n, m_in = 8, 4
    for trial in range(5):
        Q, c, lb, ub, _A, _b = _random_qp(rng, n, 0)
        G = rng.normal(size=(m_in, n))
        x_feas = lb + (ub - lb) * rng.random(n)
        h = G @ x_feas - rng.random(m_in)      # strictly feasible point
        Qj, cj = jnp.asarray(Q), jnp.asarray(c)
        Gj, hj = jnp.asarray(G), jnp.asarray(h)
        nlp = NLPFunctions(
            f=lambda w, t: 0.5 * w @ Qj @ w + cj @ w,
            g=lambda w, t: jnp.zeros((0,)),
            h=lambda w, t: Gj @ w - hj,
        )
        res = solve_nlp(nlp, jnp.asarray(x_feas), None, jnp.asarray(lb),
                        jnp.asarray(ub), OPTS)
        assert bool(res.stats.success)
        ref = minimize(
            lambda x: 0.5 * x @ Q @ x + c @ x,
            jac=lambda x: Q @ x + c, x0=x_feas,
            bounds=list(zip(lb, ub)),
            constraints=[{"type": "ineq", "fun": lambda x: G @ x - h,
                          "jac": lambda x: G}],
            method="SLSQP", options={"maxiter": 500, "ftol": 1e-12})
        assert ref.success, ref.message
        np.testing.assert_allclose(np.asarray(res.w), ref.x, atol=2e-5,
                                   err_msg=f"trial {trial}")
