"""Multi-agent system runners.

`LocalMAS` replaces the reference's LocalMASAgency
(``examples/one_room_mpc/physical/simple_mpc.py:16,223-227``): build agents
from config dicts, link their brokers over an in-process broadcast bus, run
the shared environment, collect per-module results.

Process-parallel execution (the reference's MultiProcessingMAS) is
intentionally NOT a process-per-agent fork here: structure-identical agents
batch into single jitted computations on one device mesh (see
parallel/admm.py), which is the TPU-native answer to that scaling axis. A
broker-based real-time mode (rt=True) remains for heterogeneous/interop
deployments.
"""

from __future__ import annotations

import logging
from typing import Optional

from agentlib_mpc_tpu.runtime.agent import Agent
from agentlib_mpc_tpu.runtime.broker import BroadcastBus
from agentlib_mpc_tpu.runtime.environment import Environment

logger = logging.getLogger(__name__)


class LocalMAS:
    """All agents in one process on a shared simulated/real-time clock."""

    def __init__(self, agent_configs: list[dict],
                 env: Optional[dict | Environment] = None,
                 variable_logging: bool = False):
        if isinstance(env, Environment):
            self.env = env
        else:
            env = dict(env or {})
            self.env = Environment(
                rt=bool(env.get("rt", False)),
                factor=float(env.get("factor", 1.0)),
                t_sample=float(env.get("t_sample", 0.0)),
                offset=float(env.get("offset", 0.0)),
            )
        self.bus = BroadcastBus()
        self.agents: dict[str, Agent] = {}
        for cfg in agent_configs:
            agent = Agent(cfg, self.env)
            if agent.id in self.agents:
                raise ValueError(f"duplicate agent id {agent.id!r}")
            self.agents[agent.id] = agent
            self.bus.join(agent.data_broker)
        self.variable_logging = variable_logging
        self._started = False

    def run(self, until: float) -> None:
        # start agents exactly once; later run() calls continue the clock
        # without re-registering processes/callbacks
        if not self._started:
            for agent in self.agents.values():
                agent.start()
            self._started = True
        self.env.run(until)

    def terminate(self) -> None:
        """Join background worker threads of all agents' modules. Without
        this, a realtime ADMM worker blocked in a wait can be killed
        mid-C-frame at interpreter exit ('FATAL: exception not rethrown').
        Idempotent; call after the last :meth:`run`."""
        for agent in self.agents.values():
            agent.terminate()

    def get_results(self, cleanup: bool = False) -> dict:
        """dict[agent_id][module_id] → DataFrame (reference
        ``mas.get_results()`` shape, tests/test_examples.py:39-72)."""
        out: dict[str, dict] = {}
        for agent_id, agent in self.agents.items():
            mod_results = {}
            for module_id, module in agent.modules.items():
                res = module.results()
                if res is not None:
                    mod_results[module_id] = res
                if cleanup:
                    module.cleanup_results()
            out[agent_id] = mod_results
        return out


# alias matching the reference's class name for easy migration
LocalMASAgency = LocalMAS
