"""Thread-discipline passes: guarded-field mutations and dispatch-lock
reentry.

Annotations (comment layer, parsed by
:class:`~agentlib_mpc_tpu.lint.findings.SourceAnnotations`):

* ``# guarded-by: self._lock`` on a field declaration (class-body
  ``field: T = ...`` line or the ``self.field = ...`` line in
  ``__init__``; the line above also binds). Every *mutation* of that
  field — plain/augmented assignment, subscript store/delete, or a
  mutator-method call (``append``/``pop``/``clear``/``update``/...) —
  must sit lexically inside a ``with <lock>:`` block in the enclosing
  function.  ``__init__`` is exempt (construction happens-before
  publication).  Functions only ever called with the lock held declare
  the contract with ``# lint: holds[self._lock]`` in their body.
* ``# lint: dispatch-lock`` on a lock field marks the broker
  dispatch-lock: calls to ``register_callback`` / ``deregister_callback``
  while that lock is held are flagged (``guard-dispatch-reentry``) — the
  deadlock shape where a callback fired under the dispatch lock tries to
  (de)register and the non-reentrant lock self-deadlocks, or the
  registration list mutates under the iterating dispatcher.

Scope notes, deliberately conservative: only *direct* container
mutations are checked (``self.field[...] = x`` yes,
``self.field[k].attr = x`` no — the latter mutates the contained object,
whose own discipline is its own class's business). Reads are not
checked: the project idiom is copy-under-lock then act outside it, and a
read pass would flag exactly those correct snapshot reads. Cross-object
mutations (``link.status = ...`` where ``status`` is guarded in class
``NeighborLink`` of the same module) are checked against the annotation
with ``self`` rewritten to the receiver (``with link._cv``).
"""

from __future__ import annotations

import ast

from agentlib_mpc_tpu.lint.findings import Finding, SourceAnnotations

#: method names that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "clear", "add", "discard", "update", "setdefault", "sort", "reverse",
    "popitem",
}

_REGISTRATION_CALLS = {"register_callback", "deregister_callback"}


def _norm(text: str) -> str:
    return "".join(text.split())


class _FieldGuards:
    """Per-module: guarded fields and dispatch locks, from annotations."""

    def __init__(self, tree: ast.Module, ann: SourceAnnotations):
        #: (class name, field name) -> lock expression text ("self._lock")
        self.guards: dict[tuple, str] = {}
        #: field name -> [(class, lock)] for cross-object checks
        self.by_field: dict[str, list] = {}
        #: lock field names marked as dispatch locks, with class
        self.dispatch: set[tuple] = set()
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            for node in ast.walk(cls):
                field = None
                if isinstance(node, ast.AnnAssign) and \
                        isinstance(node.target, ast.Name):
                    field = node.target.id
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name):
                        field = tgt.id
                    elif isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        field = tgt.attr
                if field is None:
                    continue
                lock = ann.guard_at(node.lineno)
                if lock is not None:
                    self.guards[(cls.name, field)] = lock
                    self.by_field.setdefault(field, []).append(
                        (cls.name, lock))
                if ann.dispatch_at(node.lineno):
                    self.dispatch.add((cls.name, field))


def _holds_for(fn_node, ann: SourceAnnotations,
               nested_spans: list) -> "set[str]":
    """holds[...] contracts declared inside fn (not in nested defs)."""
    out = set()
    for line, lock in ann.holds.items():
        if fn_node.lineno <= line <= (fn_node.end_lineno or fn_node.lineno):
            if any(lo <= line <= hi for lo, hi in nested_spans):
                continue
            out.add(_norm(lock))
    return out


def run_module(path: str, tree: ast.Module, source: str) -> "list[Finding]":
    ann = SourceAnnotations(source)
    guards = _FieldGuards(tree, ann)
    if not guards.guards and not guards.dispatch:
        return []
    findings: list[Finding] = []

    class_of_func: dict[int, str] = {}
    funcs: list = []

    def collect(node, cls=None, qual=""):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                collect(child, cls=child.name,
                        qual=f"{qual}{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                funcs.append((child, cls, f"{qual}{child.name}"))
                class_of_func[id(child)] = cls
                collect(child, cls=cls, qual=f"{qual}{child.name}.")

    collect(tree)

    for fn_node, cls, qual in funcs:
        nested_spans = [
            (n.lineno, n.end_lineno or n.lineno)
            for n in ast.walk(fn_node)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn_node]
        held_contracts = _holds_for(fn_node, ann, nested_spans)
        _check_function(path, fn_node, cls, qual, guards, ann,
                        held_contracts, findings)
    return findings


def _receiver_and_field(expr: ast.AST) -> "tuple[str, str] | None":
    """('self'|receiver-src, field) when expr is a direct field access."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name):
        return expr.value.id, expr.attr
    return None


def _mutations_in(stmt: ast.AST):
    """(node, receiver, field) direct-mutation triples in one statement
    (not descending into nested defs — caller guarantees)."""
    out = []

    def targets_of(node):
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            return [node.target]
        if isinstance(node, ast.Delete):
            return node.targets
        return []

    for node in ast.walk(stmt):
        for tgt in targets_of(node):
            rf = _receiver_and_field(tgt)
            if rf is not None:
                out.append((node, *rf))
            elif isinstance(tgt, ast.Subscript):
                rf = _receiver_and_field(tgt.value)
                if rf is not None:
                    out.append((node, *rf))
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for el in tgt.elts:
                    rf = _receiver_and_field(el)
                    if rf is not None:
                        out.append((node, *rf))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            rf = _receiver_and_field(node.func.value)
            if rf is not None:
                out.append((node, *rf))
    return out


def _check_function(path, fn_node, cls, qual, guards: _FieldGuards, ann,
                    held_contracts, findings) -> None:
    is_init = fn_node.name in ("__init__", "__post_init__")

    def lock_for(receiver: str, field: str) -> "str | None":
        if receiver == "self" and cls is not None:
            return guards.guards.get((cls, field))
        if receiver != "self":
            cands = guards.by_field.get(field, [])
            if len(cands) == 1:
                _cls, lock = cands[0]
                return lock.replace("self.", f"{receiver}.", 1) \
                    if lock.startswith("self.") else lock
        return None

    def dispatch_held(held: "set[str]") -> "str | None":
        for cls_name, lockfield in guards.dispatch:
            for h in held:
                if h.endswith("." + lockfield) or h == lockfield:
                    return lockfield
        return None

    def walk(stmts, held: "set[str]") -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue        # nested defs checked on their own
            if isinstance(stmt, ast.With):
                new_held = set(held)
                for item in stmt.items:
                    try:
                        new_held.add(_norm(ast.unparse(item.context_expr)))
                    except Exception:       # pragma: no cover
                        pass
                walk(stmt.body, new_held)
                continue
            if isinstance(stmt, (ast.If, ast.While, ast.For)):
                _check_leaf(stmt, held, header_only=True)
                walk(stmt.body, held)
                walk(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.Try):
                walk(stmt.body, held)
                for h in stmt.handlers:
                    walk(h.body, held)
                walk(stmt.orelse, held)
                walk(stmt.finalbody, held)
                continue
            _check_leaf(stmt, held, header_only=False)

    def _check_leaf(stmt, held, header_only: bool) -> None:
        nodes = [stmt] if not header_only else [
            stmt.test if isinstance(stmt, (ast.If, ast.While))
            else stmt.iter]
        for node in nodes:
            for mut, receiver, field in _mutations_in(node):
                lock = lock_for(receiver, field)
                if lock is None:
                    continue
                want = _norm(lock)
                if want in held or want in held_contracts:
                    continue
                if is_init and receiver == "self":
                    continue
                if ann.suppressed("guard-unlocked-mutation", mut.lineno):
                    continue
                findings.append(Finding(
                    rule="guard-unlocked-mutation", path=path,
                    line=mut.lineno, qualname=qual,
                    message=(f"{receiver}.{field} is guarded-by {lock} "
                             f"but mutated outside `with {lock}` (add "
                             f"the with-block, or declare the caller "
                             f"contract with `# lint: holds[{lock}]`)"),
                    snippet=ast.unparse(mut)))
            # dispatch-lock reentry
            lockfield = dispatch_held(held)
            if lockfield is not None:
                for call in ast.walk(node):
                    if isinstance(call, ast.Call) and (
                            (isinstance(call.func, ast.Attribute)
                             and call.func.attr in _REGISTRATION_CALLS)
                            or (isinstance(call.func, ast.Name)
                                and call.func.id in _REGISTRATION_CALLS)):
                        if ann.suppressed("guard-dispatch-reentry",
                                          call.lineno):
                            continue
                        findings.append(Finding(
                            rule="guard-dispatch-reentry", path=path,
                            line=call.lineno, qualname=qual,
                            message=(f"callback (de)registration under "
                                     f"the dispatch lock "
                                     f"{lockfield!r} — the classic "
                                     f"dispatch/registration deadlock; "
                                     f"snapshot under the lock, call "
                                     f"outside it"),
                            snippet=ast.unparse(call)))

    walk(fn_node.body, set())
