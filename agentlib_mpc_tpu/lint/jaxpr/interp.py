"""Shared abstract interpreter over closed jaxprs.

One walk, many domains: the LQ-degree pass and the stage-dependence pass
differ only in the per-element payload they propagate (a polynomial
degree vs a stage bitmask) and in how arithmetic combines payloads. This
module owns everything domain-independent:

* the abstract value model — :class:`AVal` couples a per-element payload
  array with an optional *concrete* value. Literals and jaxpr consts are
  concrete; any primitive whose inputs are all concrete is evaluated
  eagerly (plain ``prim.bind``), so index machinery (``iota``,
  ``arange`` consts, clamp/select index fixups) stays exact instead of
  smearing dependence through gathers;
* the per-primitive registry (:data:`RULES`) classifying every primitive
  as linear / nonlinear / structural / control-flow, with
  domain-agnostic handling of the structural ones via the *ID trick*:
  data-movement primitives (slice, reshape, gather, scatter, concat,
  pad, …) are re-executed on int32 element-id arrays, which yields the
  exact output→input element mapping for ANY dimension_numbers without
  re-implementing XLA gather semantics;
* recursion into higher-order primitives: ``pjit`` inlines, ``scan`` /
  ``while`` run their bodies to a payload fixpoint (the lattices are
  finite, so this terminates), ``cond`` joins branches under the
  predicate rule;
* the soundness fallback: an unknown or opaque primitive with
  ``w``-tainted inputs *smears* (output gets the domain's top + the
  event is recorded on the domain); with untainted inputs its output is
  provably ``w``-independent (jaxpr evaluation is a pure function of the
  inputs), so precision survives.

Domains subclass :class:`Domain` and provide the payload algebra; see
:mod:`.lq` and :mod:`.structure`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import numpy as np

__all__ = ["AVal", "CALLBACK_PRIMS", "COLLECTIVE_PRIMS", "Domain",
           "collective_axes", "interpret_closed", "run_nlp_function"]


@dataclasses.dataclass
class AVal:
    """Abstract value: per-element ``payload`` (numpy array, domain
    dtype, shaped like the value) plus the concrete value when it is
    independent of every symbolic input (``None`` otherwise)."""

    payload: np.ndarray
    const: "np.ndarray | None" = None

    @property
    def is_const(self) -> bool:
        return self.const is not None


class Domain:
    """Payload algebra one pass plugs into the shared walk.

    ``zero()`` is the payload of a value with no ``w`` dependence (also
    used for concrete values and fill/padding). ``is_zero`` must hold
    for it. The binary/unary hooks receive *broadcast* payload arrays
    (already shaped like the output) and return the output payload.
    """

    dtype: Any = object

    def __init__(self):
        self.notes: list[str] = []
        self.opaque: list[str] = []   # tainted opaque primitives seen

    # -- payload constructors ------------------------------------------------
    def zero(self):
        raise NotImplementedError

    def w_element(self, flat_index: int):
        """Payload of element ``flat_index`` of the ``w`` input."""
        raise NotImplementedError

    def zeros(self, shape) -> np.ndarray:
        out = np.empty(shape, dtype=self.dtype)
        out[...] = self.zero()
        return out

    def is_zero(self, payload_arr: np.ndarray) -> bool:
        z = self.zero()
        return bool(np.all(payload_arr == z)) if payload_arr.size else True

    # -- algebra -------------------------------------------------------------
    def join(self, args: "list[np.ndarray]") -> np.ndarray:
        """Linear combination (add/sub/sum/…): no new nonlinearity."""
        raise NotImplementedError

    def mul(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def div(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def int_pow(self, a: np.ndarray, y: int) -> np.ndarray:
        raise NotImplementedError

    def nonlinear(self, args: "list[np.ndarray]") -> np.ndarray:
        """Smooth nonlinear op (sin/exp/…, generic pow)."""
        raise NotImplementedError

    def nonsmooth(self, args: "list[np.ndarray]") -> np.ndarray:
        """Piecewise-linear / comparison ops (max, min, abs, lt, …)."""
        raise NotImplementedError

    def select(self, pred: np.ndarray, cases: "list[np.ndarray]"
               ) -> np.ndarray:
        """``select_n`` with a symbolic predicate."""
        raise NotImplementedError

    def top_like(self, shape, args: "list[np.ndarray]") -> np.ndarray:
        """Smear: conservative payload for an opaque primitive."""
        raise NotImplementedError


# --------------------------------------------------------------------------
# primitive classification
# --------------------------------------------------------------------------

#: value-preserving / linear elementwise & reduction primitives: payload =
#: elementwise join of the (broadcast) inputs; reductions join along axes
LINEAR_EW = {
    "add", "sub", "neg", "add_any", "copy", "real", "imag",
    "reduce_precision",
}
LINEAR_REDUCE = {"reduce_sum": "axes", "cumsum": None, "cumlogsumexp": None}

#: smooth nonlinear elementwise primitives (unary and binary)
NONLINEAR_EW = {
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "asinh", "acosh", "atanh", "exp", "exp2", "expm1", "log", "log1p",
    "sqrt", "rsqrt", "cbrt", "logistic", "erf", "erfc", "erf_inv",
    "pow", "atan2", "rem", "nextafter", "digamma", "lgamma",
}

#: piecewise / comparison / boolean elementwise primitives
NONSMOOTH_EW = {
    "max", "min", "abs", "sign", "floor", "ceil", "round", "clamp",
    "lt", "le", "gt", "ge", "eq", "ne", "and", "or", "xor", "not",
    "is_finite", "shift_left", "shift_right_logical",
    "shift_right_arithmetic",
}

#: nonlinear reductions
NONLINEAR_REDUCE = {"reduce_prod"}
NONSMOOTH_REDUCE = {"reduce_max", "reduce_min", "reduce_and", "reduce_or",
                    "argmax", "argmin", "reduce_xor"}

#: pure data movement: re-executed on element-id arrays (the ID trick).
#: value (non-index) operand positions per primitive; ``None`` = all.
STRUCTURAL: "dict[str, tuple | None]" = {
    "slice": None,
    "reshape": None,
    "broadcast_in_dim": None,
    "concatenate": None,
    "squeeze": None,
    "transpose": None,
    "rev": None,
    "expand_dims": None,
    "gather": (0,),
    "dynamic_slice": (0,),
    "dynamic_update_slice": (0, 1),
    "scatter": (0, 2),
    "pad": (0, 1),
    "split": None,
}


#: primitives that may run user host code — never executed during
#: certification, even on fully concrete inputs
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "custom_call", "ffi_call",
})
#: public alias (the collectives pass and the cost model share it)
CALLBACK_PRIMS = _CALLBACK_PRIMS


#: cross-shard communication primitives: the one primitive family that
#: moves data BETWEEN mesh shards. Everything else in a jaxpr is a pure
#: shard-local function of its inputs, which is what makes the
#: replication lattice of :mod:`.collectives` sound with a single
#: generic join rule. Value per name: ``(axes_param, rejoins)`` —
#: ``axes_param`` is the eqn-param key holding the named axes,
#: ``rejoins`` is True when the output is provably identical on every
#: shard of the reduced axes (an all-reduce/all-gather re-replicates;
#: a permute/scatter stays shard-varying).
COLLECTIVE_PRIMS: "dict[str, tuple[str, bool]]" = {
    "psum": ("axes", True),
    "pmax": ("axes", True),
    "pmin": ("axes", True),
    "all_gather": ("axis_name", True),
    "all_to_all": ("axis_name", False),
    "ppermute": ("axis_name", False),
    "pshuffle": ("axis_name", False),
    "psum_scatter": ("axis_name", False),
    "reduce_scatter": ("axis_name", False),
}


def collective_axes(eqn) -> tuple:
    """The NAMED axes a collective eqn communicates over (positional
    integer axes — a vmapped psum over a local batch axis — are not
    cross-shard traffic and are filtered out)."""
    param = COLLECTIVE_PRIMS[eqn.primitive.name][0]
    axes = eqn.params.get(param, ())
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _aval_shape(var) -> tuple:
    return tuple(var.aval.shape)


def _literal_value(v):
    return np.asarray(v.val)


def _broadcast_payloads(domain: Domain, args: "list[AVal]", out_shape):
    """Broadcast every arg's payload to ``out_shape`` (numpy rules; jax
    elementwise primitives follow the same ones after their explicit
    broadcast_in_dim insertions, so ranks already line up)."""
    outs = []
    for a in args:
        p = a.payload
        if p.shape != tuple(out_shape):
            p = np.broadcast_to(p, out_shape)
        outs.append(p)
    return outs


class _Interpreter:
    def __init__(self, domain: Domain):
        self.domain = domain

    # -- helpers -------------------------------------------------------------
    def _concrete_bind(self, prim, args: "list[AVal]", params) -> list:
        vals = prim.bind(*[jax.numpy.asarray(a.const) for a in args],
                         **params)
        if not prim.multiple_results:
            vals = [vals]
        return [AVal(self.domain.zeros(np.shape(v)), np.asarray(v))
                for v in vals]

    def _smear(self, prim_name: str, args: "list[AVal]", out_vars) -> list:
        """Opaque primitive with tainted inputs: domain top + a record."""
        payloads = [a.payload for a in args]
        self.domain.opaque.append(prim_name)
        return [AVal(np.broadcast_to(
            self.domain.top_like((), payloads).reshape(()),
            _aval_shape(v)).copy()) for v in out_vars]

    def _structural(self, eqn, args: "list[AVal]"):
        """ID trick: run the primitive on int32 element ids; map payloads
        through the resulting output→input element mapping. Index-like
        operands must be concrete (else: smear)."""
        data_pos = STRUCTURAL[eqn.primitive.name]
        n = len(args)
        data_pos = tuple(range(n)) if data_pos is None else data_pos
        id_args, offsets = [], {}
        next_id = 1                       # id 0 = "not from any operand"
        for i, a in enumerate(args):
            if i in data_pos:
                size = int(np.prod(np.shape(a.payload), dtype=np.int64))
                ids = (np.arange(size, dtype=np.int32) + next_id).reshape(
                    np.shape(a.payload))
                offsets[i] = next_id
                next_id += size
                id_args.append(jax.numpy.asarray(ids))
            else:
                if not a.is_const:
                    return None           # symbolic indices: caller smears
                id_args.append(jax.numpy.asarray(a.const))
        outs = eqn.primitive.bind(*id_args, **eqn.params)
        if not eqn.primitive.multiple_results:
            outs = [outs]
        flat_payloads = np.concatenate(
            [np.asarray([self.domain.zero()], dtype=self.domain.dtype)]
            + [args[i].payload.reshape(-1).astype(self.domain.dtype,
                                                  copy=False)
               for i in sorted(offsets)]) \
            if offsets else np.asarray([self.domain.zero()],
                                       dtype=self.domain.dtype)
        results = []
        for out in outs:
            src = np.asarray(out).reshape(-1)
            payload = flat_payloads[src].reshape(np.shape(out))
            results.append(AVal(payload))
        return results

    # -- the walk ------------------------------------------------------------
    def run(self, closed, in_avals: "list[AVal]") -> "list[AVal]":
        jaxpr = closed.jaxpr
        env: dict = {}

        def read(v) -> AVal:
            if isinstance(v, jax.core.Literal):
                val = _literal_value(v)
                return AVal(self.domain.zeros(val.shape), val)
            return env[v]

        def write(v, a: AVal):
            env[v] = a

        for var, const in zip(jaxpr.constvars, closed.consts):
            cval = np.asarray(const)
            write(var, AVal(self.domain.zeros(cval.shape), cval))
        if len(jaxpr.invars) != len(in_avals):
            raise ValueError(
                f"jaxpr expects {len(jaxpr.invars)} inputs, got "
                f"{len(in_avals)}")
        for var, a in zip(jaxpr.invars, in_avals):
            write(var, a)

        for eqn in jaxpr.eqns:
            args = [read(v) for v in eqn.invars]
            outs = self.eqn(eqn, args)
            for var, out in zip(eqn.outvars, outs):
                write(var, out)
        return [read(v) for v in jaxpr.outvars]

    def eqn(self, eqn, args: "list[AVal]") -> "list[AVal]":
        prim = eqn.primitive
        name = prim.name
        dom = self.domain

        # anything computable from constants stays exact — including the
        # whole index universe (iota/arange/clamp/select on indices).
        # Callbacks are excluded: certification must never execute user
        # host code; their w-independence is still proven below.
        if all(a.is_const for a in args) and name not in _CALLBACK_PRIMS:
            try:
                return self._concrete_bind(prim, args, eqn.params)
            except Exception:
                pass  # fall through to the abstract rules

        out_shapes = [_aval_shape(v) for v in eqn.outvars]

        if name in LINEAR_EW:
            ps = _broadcast_payloads(dom, args, out_shapes[0])
            return [AVal(dom.join(ps))]
        if name in LINEAR_REDUCE:
            axes_key = LINEAR_REDUCE[name]
            p = args[0].payload
            if axes_key is not None:
                axes = tuple(eqn.params[axes_key])
                out = p
                for ax in sorted(axes, reverse=True):
                    parts = [np.take(out, i, axis=ax)
                             for i in range(out.shape[ax])]
                    out = dom.join(parts) if parts else dom.zeros(
                        out_shapes[0])
                out = np.broadcast_to(out, out_shapes[0]).copy()
            else:
                # cumulative op: every element joins its whole axis
                # (prefix precision is not worth the complexity)
                ax = eqn.params.get("axis", 0)
                parts = [np.take(p, i, axis=ax) for i in range(p.shape[ax])]
                total = dom.join(parts) if parts else dom.zeros(())
                out = np.broadcast_to(
                    np.expand_dims(total, ax), out_shapes[0]).copy()
            return [AVal(out)]
        if name == "mul":
            a, b = _broadcast_payloads(dom, args, out_shapes[0])
            if args[0].is_const or args[1].is_const:
                return [AVal(dom.join([a, b]))]
            return [AVal(dom.mul(a, b))]
        if name == "div":
            a, b = _broadcast_payloads(dom, args, out_shapes[0])
            if args[1].is_const:
                return [AVal(dom.join([a, b]))]
            return [AVal(dom.div(a, b))]
        if name == "integer_pow":
            return [AVal(dom.int_pow(args[0].payload,
                                     int(eqn.params["y"])))]
        if name == "square":
            # jnp.square lowers to its own primitive on current jax —
            # it is integer_pow(y=2), NOT a transcendental, or every
            # quadratic written as jnp.square would refute its own LQ
            # certificate
            return [AVal(dom.int_pow(args[0].payload, 2))]
        if name in NONLINEAR_EW:
            ps = _broadcast_payloads(dom, args, out_shapes[0])
            return [AVal(dom.nonlinear(ps))]
        if name in NONSMOOTH_EW:
            ps = _broadcast_payloads(dom, args, out_shapes[0])
            return [AVal(dom.nonsmooth(ps))]
        if name in NONLINEAR_REDUCE or name in NONSMOOTH_REDUCE:
            p = args[0].payload
            parts = [p.reshape(-1)[i:i + 1].reshape(())
                     for i in range(p.size)]
            total = dom.join(parts) if parts else dom.zeros(())
            joined = (dom.nonlinear if name in NONLINEAR_REDUCE
                      else dom.nonsmooth)([total])
            return [AVal(np.broadcast_to(joined, out_shapes[0]).copy())]
        if name == "select_n":
            pred, cases = args[0], args[1:]
            case_ps = _broadcast_payloads(dom, cases, out_shapes[0])
            if pred.is_const:
                idx = np.broadcast_to(np.asarray(pred.const).astype(np.int64),
                                      out_shapes[0])
                stacked = np.stack(case_ps, axis=0)
                out = np.take_along_axis(
                    stacked, idx[None, ...], axis=0)[0]
                return [AVal(np.asarray(out, dtype=dom.dtype))]
            pred_p = np.broadcast_to(pred.payload, out_shapes[0])
            return [AVal(dom.select(pred_p, case_ps))]
        if name == "convert_element_type":
            # float→float / int→anything is value-preserving (linear);
            # float→int/bool truncates (nonsmooth)
            in_float = np.issubdtype(eqn.invars[0].aval.dtype, np.floating)
            out_float = np.issubdtype(np.dtype(eqn.params["new_dtype"]),
                                      np.floating)
            p = args[0].payload
            if in_float and not out_float:
                return [AVal(dom.nonsmooth([p]))]
            return [AVal(dom.join([p]))]
        if name == "stop_gradient":
            # AD sees a constant here: no w-dependence survives in any
            # gradient/Hessian the solvers extract
            return [AVal(dom.zeros(out_shapes[0]))]
        if name == "dot_general":
            return [self._dot_general(eqn, args)]
        if name == "iota":
            return self._concrete_bind(prim, args, eqn.params)
        if name in STRUCTURAL:
            res = self._structural(eqn, args)
            if res is not None:
                return res
            return self._smear(name, args, eqn.outvars)
        if name in ("pjit", "closed_call", "core_call"):
            inner = eqn.params["jaxpr"] if name == "pjit" \
                else eqn.params["call_jaxpr"]
            return self.run(inner, args)
        if name == "cond":
            return self._cond(eqn, args)
        if name == "scan":
            return self._scan(eqn, args)
        if name == "while":
            return self._while(eqn, args)

        # opaque: custom AD rules, callbacks, unknown primitives. With no
        # tainted input the output provably carries no w-dependence.
        if all(dom.is_zero(a.payload) for a in args):
            return [AVal(dom.zeros(s)) for s in out_shapes]
        return self._smear(name, args, eqn.outvars)

    # -- composite rules -----------------------------------------------------
    def _dot_general(self, eqn, args: "list[AVal]") -> AVal:
        """Generic dot_general: align both operands to
        (batch…, M, N, K) index space and fold the contraction with
        mul+join. Exact per element; the loops run on abstract payloads
        of CI-sized problems (a few thousand elements)."""
        dom = self.domain
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        a, b = args
        ap, bp = a.payload, b.payload
        la = list(range(ap.ndim))
        lbd = list(range(bp.ndim))
        l_free = [d for d in la if d not in lc and d not in lb]
        r_free = [d for d in lbd if d not in rc and d not in rb]
        # lhs to (batch, free, contract); rhs to (batch, contract, free)
        ap_t = np.transpose(ap, list(lb) + l_free + list(lc))
        bp_t = np.transpose(bp, list(rb) + list(rc) + r_free)
        Bshape = ap_t.shape[:len(lb)]
        Mshape = ap_t.shape[len(lb):len(lb) + len(l_free)]
        Nshape = bp_t.shape[len(rb) + len(rc):]
        K = int(np.prod(ap_t.shape[len(lb) + len(l_free):], dtype=np.int64))
        Bsz = int(np.prod(Bshape, dtype=np.int64))
        Msz = int(np.prod(Mshape, dtype=np.int64))
        Nsz = int(np.prod(Nshape, dtype=np.int64))
        ap2 = ap_t.reshape(Bsz, Msz, K)
        bp2 = bp_t.reshape(Bsz, K, Nsz)
        one_const = a.is_const or b.is_const
        out = np.empty((Bsz, Msz, Nsz), dtype=dom.dtype)
        for bi in range(Bsz):
            for mi in range(Msz):
                for ni in range(Nsz):
                    if K == 0:
                        out[bi, mi, ni] = dom.zero()
                        continue
                    terms = []
                    for k in range(K):
                        pa = ap2[bi, mi, k:k + 1].reshape(())
                        pb = bp2[bi, k, ni:ni + 1].reshape(())
                        if one_const:
                            terms.append(dom.join([pa, pb]))
                        else:
                            terms.append(dom.mul(pa, pb))
                    out[bi, mi, ni] = dom.join(terms).reshape(())[()]
        out = out.reshape(Bshape + Mshape + Nshape)
        return AVal(out)

    def _cond(self, eqn, args: "list[AVal]") -> "list[AVal]":
        dom = self.domain
        pred, ops = args[0], args[1:]
        branch_outs = [self.run(br, ops)
                       for br in eqn.params["branches"]]
        n_out = len(branch_outs[0])
        outs = []
        for i in range(n_out):
            cases = [bo[i].payload for bo in branch_outs]
            shape = cases[0].shape
            cases = [np.broadcast_to(c, shape) for c in cases]
            if pred.is_const:
                outs.append(AVal(cases[int(np.asarray(pred.const))].copy()))
            else:
                p = np.broadcast_to(pred.payload.reshape(
                    (1,) * len(shape)) if pred.payload.shape == ()
                    else pred.payload, shape)
                outs.append(AVal(dom.select(p, cases)))
        return outs

    def _scan(self, eqn, args: "list[AVal]") -> "list[AVal]":
        dom = self.domain
        n_const = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        body = eqn.params["jaxpr"]
        consts = args[:n_const]
        carry = args[n_const:n_const + n_carry]
        xs = args[n_const + n_carry:]
        # per-iteration xs slice: join over the scan axis (sound for any
        # iteration order); shape = xs[1:]
        x_slices = []
        for x in xs:
            p = x.payload
            if p.shape[0:1] == (0,):
                x_slices.append(AVal(dom.zeros(p.shape[1:])))
                continue
            parts = [np.take(p, i, axis=0) for i in range(p.shape[0])]
            x_slices.append(AVal(dom.join(parts)))
        carry_p = [c.payload.copy() for c in carry]
        ys_p = None
        for _ in range(64):  # finite lattices: fixpoint comes fast
            ins = (consts
                   + [AVal(p.copy()) for p in carry_p]
                   + x_slices)
            outs = self.run(body, ins)
            new_carry = [dom.join([carry_p[i], outs[i].payload])
                         for i in range(n_carry)]
            ys_p = [o.payload for o in outs[n_carry:]]
            if all(np.array_equal(new_carry[i], carry_p[i])
                   for i in range(n_carry)):
                carry_p = new_carry
                break
            carry_p = new_carry
        else:
            dom.notes.append("scan fixpoint not reached in 64 iterations")
        results = [AVal(p) for p in carry_p]
        for i, v in enumerate(eqn.outvars[n_carry:]):
            shape = _aval_shape(v)
            results.append(AVal(np.broadcast_to(ys_p[i], shape).copy()))
        return results

    def _while(self, eqn, args: "list[AVal]") -> "list[AVal]":
        dom = self.domain
        cn, bn = eqn.params["cond_nconsts"], eqn.params["body_nconsts"]
        cond_consts = args[:cn]
        body_consts = args[cn:cn + bn]
        carry = args[cn + bn:]
        carry_p = [c.payload.copy() for c in carry]
        for _ in range(64):
            outs = self.run(eqn.params["body_jaxpr"],
                            body_consts + [AVal(p.copy())
                                           for p in carry_p])
            new_carry = [dom.join([carry_p[i], outs[i].payload])
                         for i in range(len(carry_p))]
            if all(np.array_equal(new_carry[i], carry_p[i])
                   for i in range(len(carry_p))):
                carry_p = new_carry
                break
            carry_p = new_carry
        else:
            dom.notes.append("while fixpoint not reached in 64 iterations")
        # a w-dependent trip count makes every output nonsmooth in w
        cond_out = self.run(eqn.params["cond_jaxpr"],
                            cond_consts + [AVal(p.copy())
                                           for p in carry_p])
        pred_p = cond_out[0].payload
        if not dom.is_zero(pred_p):
            carry_p = [dom.select(np.broadcast_to(pred_p.reshape(
                (1,) * p.ndim) if pred_p.shape == () else pred_p,
                p.shape), [p]) for p in carry_p]
        return [AVal(p) for p in carry_p]


def interpret_closed(closed, in_avals: "list[AVal]",
                     domain: Domain) -> "list[AVal]":
    """Run ``domain`` over a :class:`jax.core.ClosedJaxpr`."""
    return _Interpreter(domain).run(closed, in_avals)


def run_nlp_function(fn, w_template, theta, domain: Domain
                     ) -> "list[AVal]":
    """Trace ``fn(w, theta)`` and interpret it with ``w`` symbolic
    (element ``i`` seeded from ``domain.w_element(i)``) and every theta
    leaf a symbolic *constant-in-w* (zero payload, unknown value) — so
    whatever the pass proves holds for ALL theta, not one sample."""
    closed = jax.make_jaxpr(fn)(w_template, theta)
    theta_leaves = jax.tree_util.tree_leaves(theta)
    n = int(np.prod(np.shape(w_template), dtype=np.int64))
    w_payload = np.empty(np.shape(w_template), dtype=domain.dtype)
    flat = w_payload.reshape(-1)
    for i in range(n):
        flat[i] = domain.w_element(i)
    in_avals = [AVal(w_payload)]
    for leaf in theta_leaves:
        in_avals.append(AVal(domain.zeros(np.shape(leaf))))
    return interpret_closed(closed, in_avals, domain)
