"""Data broker: callback pub/sub for agent variables.

Replaces agentlib's DataBroker + communicator modules (the reference's
distributed communication backend, SURVEY.md §2.9): modules register
callbacks on (alias, source) and send AgentVariables
(``modules/mpc/mpc.py:281-284``, ``modules/dmpc/admm/admm.py:605-610``);
``local_broadcast`` communicators forward shared variables between agents.

Here every agent owns a `DataBroker`; a process-wide `BroadcastBus` links
brokers in one LocalMAS (the in-process fast path). The same broker API is
the seam for cross-process/MQTT interop communicators later — exactly the
reference's layering (fast path vs interop path).
"""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Callable, Optional

from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source

logger = logging.getLogger(__name__)

Callback = Callable[[AgentVariable], None]


class DataBroker:
    """Per-agent variable router."""

    def __init__(self, agent_id: str):
        self.agent_id = agent_id
        self._subs: list[tuple[str, Source, Callback]] = []
        self._bus: Optional["BroadcastBus"] = None

    def register_callback(self, alias: str, source, callback: Callback) -> None:
        self._subs.append((alias, Source.coerce(source), callback))

    def deregister_callback(self, alias: str, source, callback: Callback) -> None:
        key = (alias, Source.coerce(source), callback)
        self._subs = [s for s in self._subs if s != key]

    def send_variable(self, var: AgentVariable, from_external: bool = False) -> None:
        """Deliver to local subscribers; forward shared vars to the bus."""
        for alias, source, cb in list(self._subs):
            if alias == var.alias and source.matches(var.source):
                cb(var)
        if var.shared and not from_external and self._bus is not None:
            self._bus.broadcast(self.agent_id, var)

    def attach_bus(self, bus: "BroadcastBus") -> None:
        self._bus = bus


class BroadcastBus:
    """In-process broadcast linking all agents of a LocalMAS — the
    replacement for the reference's `local_broadcast` communicator."""

    def __init__(self):
        self._brokers: dict[str, DataBroker] = {}

    def join(self, broker: DataBroker) -> None:
        self._brokers[broker.agent_id] = broker
        broker.attach_bus(self)

    def broadcast(self, from_agent: str, var: AgentVariable) -> None:
        for agent_id, broker in self._brokers.items():
            if agent_id != from_agent:
                broker.send_variable(var, from_external=True)
