"""Golden-file fixture: host-side dispatch storms (ISSUE 18).

A Python ``for``/``while`` over a jitted call dispatches one device
program per iteration, and a per-iteration ``.block_until_ready()``
adds a full host round-trip on top — the ``jit-dispatch-in-loop`` rule
must flag each occurrence, while the in-graph ``lax.scan`` loop (one
dispatch total) and the single post-loop sync must stay silent.
"""

from functools import partial

import jax
import jax.numpy as jnp

step = jax.jit(lambda x: x * 2.0)


@partial(jax.jit, static_argnums=(1,))
def decorated_step(x, n):
    return x + n


def dispatch_storm(x):
    for _ in range(100):
        x = step(x)                          # one dispatch per pass
    return x


def sync_storm(x):
    total = jnp.zeros(())
    while float(total) < 4.0:
        y = step(x)                          # dispatch per pass...
        total = total + y.block_until_ready().sum()   # ...plus a sync
    return total


def decorated_storm(x):
    for n in range(8):
        x = decorated_step(x, n)             # dispatch per pass
    return x


def fused_ok(x):
    # the loop lives IN the program: one dispatch covers every
    # iteration, and the single sync after it is the idiomatic exit
    def body(c, _):
        return c * 2.0, None

    y, _ = jax.lax.scan(body, x, None, length=100)
    return y.block_until_ready()
