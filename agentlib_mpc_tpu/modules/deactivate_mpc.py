"""MPC deactivation: external on/off gating with fallback control values.

Counterparts of the reference's deactivation suite:
- ``SkippableMixin`` (``modules/mpc/skippable_mixin.py:44-57``): MPC-side —
  an AgentVariable ``mpc_active`` that other modules may set to False gates
  ``do_step``.
- ``MPCOnOff`` (``modules/deactivate_mpc/deactivate_mpc.py:45-88``):
  sender side — periodically broadcasts the flag, fallback control values
  while inactive, and optional public (in)active messages.
- ``SkipMPCInIntervals`` (``deactivate_mpc.py:106-123``): deactivates
  inside configured time intervals (unit-convertible).
"""

from __future__ import annotations

import logging

from agentlib_mpc_tpu.runtime.module import BaseModule, register_module
from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source
from agentlib_mpc_tpu.utils.time_utils import (
    TIME_CONVERSION,
    is_time_in_intervals,
)

logger = logging.getLogger(__name__)

#: reserved flag name (reference ``mpc_datamodels.MPC_FLAG_ACTIVE``)
MPC_FLAG_ACTIVE = "mpc_active"


class SkippableMixin:
    """Mix into an MPC-like module: call ``init_skippable`` from
    ``__init__`` and ``check_if_should_be_skipped`` at the top of each
    step (reference ``skippable_mixin.py:44-57``)."""

    def init_skippable(self) -> None:
        config = self.config
        self.enable_deactivation = bool(
            config.get("enable_deactivation", False))
        if not self.enable_deactivation:
            return
        if MPC_FLAG_ACTIVE not in self.vars:
            var = AgentVariable(
                name=MPC_FLAG_ACTIVE, value=True, shared=False,
                description="MPC is active")
            src = config.get("deactivation_source")
            if src:
                var.source = Source.coerce(src)
            self.vars[MPC_FLAG_ACTIVE] = var
        # subscription happens in BaseModule.register_callbacks — the flag
        # is shared=False, so the default rule covers it; registering here
        # too would run duplicate callbacks per broadcast

    def check_if_should_be_skipped(self) -> bool:
        if not getattr(self, "enable_deactivation", False):
            return False
        flag = self.vars[MPC_FLAG_ACTIVE]
        if bool(flag.value):
            return False
        self.logger.info("MPC deactivated by %s at t=%s",
                         flag.source, self.env.now)
        return True


@register_module("mpc_on_off")
class MPCOnOff(BaseModule):
    """Broadcasts the active flag every ``t_sample``; while inactive, also
    re-sends the configured fallback control values. Subclasses override
    ``check_mpc_deactivation``."""

    variable_groups = ("inputs", "controls_when_deactivated")
    shared_groups = ("controls_when_deactivated",)

    def __init__(self, config: dict, agent):
        super().__init__(config, agent)
        self.t_sample = float(config.get("t_sample", 60.0))
        if MPC_FLAG_ACTIVE not in self.vars:
            self._declare(AgentVariable(name=MPC_FLAG_ACTIVE, value=True,
                                        shared=True), "outputs")
        self.public_active_message = config.get("public_active_message")
        self.public_inactive_message = config.get("public_inactive_message")

    def check_mpc_deactivation(self) -> bool:
        """Override: True → MPC should be deactivated now."""
        return False

    def process(self):
        while True:
            if self.check_mpc_deactivation():
                self.deactivate_mpc()
            else:
                self.activate_mpc()
            yield self.t_sample

    def deactivate_mpc(self) -> None:
        self.set(MPC_FLAG_ACTIVE, False)
        for var in self.variables_in_group("controls_when_deactivated"):
            self.set(var.name, var.value)
        if self.public_inactive_message:
            self.send(AgentVariable.from_config(
                self.public_inactive_message))

    def activate_mpc(self) -> None:
        self.set(MPC_FLAG_ACTIVE, True)
        if self.public_active_message:
            self.send(AgentVariable.from_config(self.public_active_message))


@register_module("skip_mpc_intervals")
class SkipMPCInIntervals(MPCOnOff):
    """Deactivates the MPC inside configured [start, end] intervals
    (reference ``SkipMPCInIntervals``, ``deactivate_mpc.py:106-123``)."""

    def __init__(self, config: dict, agent):
        super().__init__(config, agent)
        self.intervals = [tuple(map(float, iv))
                          for iv in config.get("intervals", [])]
        self.time_unit = config.get("time_unit", "seconds")
        if self.time_unit not in TIME_CONVERSION:
            raise ValueError(f"unknown time_unit {self.time_unit!r}")

    def check_mpc_deactivation(self) -> bool:
        t = float(self.env.now) / TIME_CONVERSION[self.time_unit]
        return is_time_in_intervals(t, self.intervals)
