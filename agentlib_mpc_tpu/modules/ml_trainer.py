"""ML model trainer modules: collect data → retrain → broadcast.

Counterpart of the reference's trainer modules
(``modules/ml_model_training/ml_model_trainer.py``: broker-callback data
collection :334-351, periodic retrain loop :283-288, retrain→serialize→
save→broadcast :305-332, memory/age eviction :353-374; trainer registry
:770-774). The numeric pipeline lives in
:mod:`agentlib_mpc_tpu.ml.training`; this module wires it to the runtime:
every update of a declared input/output variable is recorded with its
timestamp, and every ``retrain_delay`` the history is resampled, lagged,
split, fitted and published as a serialized model document on the
``ml_model_variable`` channel, where MLSimulator / MLBackend consumers
hot-swap it (§3.5 loop).

Config (reference ``MLModelTrainerConfig``, :42-235):
    inputs / outputs: recorded variables (outputs are the prediction
        targets; every variable may carry ``lag`` in its entry)
    step_size: resample dt == the surrogate's prediction step
    retrain_delay: seconds between retrains
    output_types: {name: "absolute" | "difference"}
    non_recursive_outputs: [names] (algebraic targets)
    train_share / validation_share / test_share: must sum to 1
    ml_model_variable: broadcast channel name (default "MLModel")
    save_directory: optional JSON dump location
    max_data_points / max_data_age: eviction policy
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Optional

import numpy as np

from agentlib_mpc_tpu.ml.serialized import (
    Feature,
    OutputFeature,
    SerializedMLModel,
)
from agentlib_mpc_tpu.ml.training import (
    ANNTrainerCore,
    create_lagged_features,
    fit_ann,
    fit_gpr,
    fit_linreg,
    resample,
    train_val_test_split,
)
from agentlib_mpc_tpu.runtime.module import BaseModule, register_module
from agentlib_mpc_tpu.runtime.variables import AgentVariable

logger = logging.getLogger(__name__)


class MLModelTrainer(BaseModule):
    """Abstract trainer; subclasses implement ``fit``."""

    variable_groups = ("inputs", "outputs")
    shared_groups = ()
    model_type = "base"

    def __init__(self, config: dict, agent):
        super().__init__(config, agent)
        self.step_size = float(config.get("step_size",
                                          config.get("time_step", 60.0)))
        self.retrain_delay = float(config.get("retrain_delay", 3600.0))
        self.train_share = float(config.get("train_share", 0.7))
        self.validation_share = float(config.get("validation_share", 0.15))
        self.test_share = float(config.get("test_share", 0.15))
        if abs(self.train_share + self.validation_share + self.test_share
               - 1.0) > 1e-9:
            raise ValueError(
                "train/validation/test shares must sum to 1 (reference "
                "validator, ml_model_trainer.py:132-150)")
        self.ml_model_variable = config.get("ml_model_variable", "MLModel")
        self.save_directory = config.get("save_directory")
        self.max_data_points = int(config.get("max_data_points", 100_000))
        self.max_data_age = config.get("max_data_age")  # seconds | None
        self.output_types = dict(config.get("output_types", {}))
        self.non_recursive = set(config.get("non_recursive_outputs", []))
        self._retrains = 0

        def lag_of(group, name):
            for entry in config.get(group, []):
                if isinstance(entry, dict) and entry.get("name") == name:
                    return int(entry.get("lag", 1))
            return 1

        self.input_features = {
            n: Feature(name=n, lag=lag_of("inputs", n))
            for n in self._groups["inputs"]}
        self.output_features = {
            n: OutputFeature(
                name=n, lag=lag_of("outputs", n),
                output_type=self.output_types.get(n, "difference"
                                                  if n not in
                                                  self.non_recursive
                                                  else "absolute"),
                recursive=n not in self.non_recursive)
            for n in self._groups["outputs"]}
        #: name → [(time, value)] raw samples
        self.time_series: dict[str, list] = {
            n: [] for n in (*self._groups["inputs"],
                            *self._groups["outputs"])}

    # -- data collection ------------------------------------------------------

    def register_callbacks(self) -> None:
        for name in self.time_series:
            var = self.vars[name]
            self.agent.data_broker.register_callback(
                var.alias, var.source, self._make_record_callback(name))

    def _make_record_callback(self, name: str):
        def _cb(incoming: AgentVariable):
            local = self.vars[name]
            local.value = incoming.value
            local.timestamp = incoming.timestamp
            try:
                self.time_series[name].append(
                    (float(incoming.timestamp), float(incoming.value)))
            except (TypeError, ValueError):
                pass
        return _cb

    def _update_time_series_data(self) -> None:
        """Eviction by count and age (reference
        ``_update_time_series_data``, ``ml_model_trainer.py:353-374``)."""
        now = float(self.env.now)
        for name, rows in self.time_series.items():
            if self.max_data_age is not None:
                cutoff = now - float(self.max_data_age)
                rows[:] = [r for r in rows if r[0] >= cutoff]
            if len(rows) > self.max_data_points:
                del rows[:len(rows) - self.max_data_points]

    def history_frame(self):
        import pandas as pd

        frames = {}
        for name, rows in self.time_series.items():
            if rows:
                s = pd.Series({t: v for t, v in rows}).sort_index()
                frames[name] = s[~s.index.duplicated(keep="last")]
        if not frames:
            return None
        # ZOH fill across columns updating at different times (broker
        # semantics: a value holds until the next publish)
        return pd.DataFrame(frames).sort_index().ffill().bfill()

    # -- retraining loop ------------------------------------------------------

    def process(self):
        while True:
            yield self.retrain_delay
            try:
                self.retrain_model()
            except ValueError as exc:
                self.logger.warning("retrain skipped: %s", exc)

    def retrain_model(self) -> Optional[SerializedMLModel]:
        """resample → lag features → split → fit → serialize → broadcast
        (reference ``retrain_model``, ``ml_model_trainer.py:305-332``)."""
        self._update_time_series_data()
        df = self.history_frame()
        if df is None or len(df) < 3:
            raise ValueError("not enough data to train")
        df = resample(df.dropna(),
                      self.step_size,
                      method=self.config.get("interpolation_method",
                                             "previous"))
        X, y = create_lagged_features(df, self.input_features,
                                      self.output_features)
        if len(X) < 3:
            raise ValueError("not enough samples after lag shifting")
        data = train_val_test_split(
            X, y, (self.train_share, self.validation_share, self.test_share),
            seed=self._retrains)
        serialized = self.fit(data)
        self._retrains += 1
        if self.save_directory:
            directory = Path(self.save_directory)
            directory.mkdir(parents=True, exist_ok=True)
            name = "_".join(self.output_features) or "model"
            serialized.save(directory /
                            f"{name}_{self._retrains:04d}.json")
        out = AgentVariable(name=self.ml_model_variable,
                            value=serialized.to_dict(), shared=True)
        self.send(out)
        return serialized

    def fit(self, data) -> SerializedMLModel:  # pragma: no cover - abstract
        raise NotImplementedError

    def results(self):
        import pandas as pd

        rows = [{"time": t, "variable": n, "value": v}
                for n, series in self.time_series.items()
                for t, v in series]
        if not rows:
            return None
        return pd.DataFrame(rows).set_index("time")


@register_module("ann_trainer")
class ANNTrainer(MLModelTrainer):
    """JAX/optax MLP trainer (reference ``ANNTrainer``,
    ``ml_model_trainer.py:617-667``)."""

    model_type = "ANN"

    def fit(self, data):
        cfg = self.config
        core = ANNTrainerCore(
            hidden=tuple(cfg.get("layers", (32, 32))),
            activation=cfg.get("activation", "tanh"),
            epochs=int(cfg.get("epochs", 400)),
            learning_rate=float(cfg.get("learning_rate", 1e-2)),
            batch_size=int(cfg.get("batch_size", 64)),
            early_stopping_patience=int(
                cfg.get("early_stopping_patience", 50)),
            seed=self._retrains)
        return fit_ann(
            data.training_inputs, data.training_outputs,
            data.validation_inputs, data.validation_outputs,
            dt=self.step_size, inputs=self.input_features,
            output=self.output_features, trainer=core,
            trainer_config={"module_id": self.id, "type": "ann_trainer"})


@register_module("gpr_trainer")
class GPRTrainer(MLModelTrainer):
    """Exact GPR trainer (reference ``GPRTrainer``,
    ``ml_model_trainer.py:673-735``)."""

    model_type = "GPR"

    def fit(self, data):
        return fit_gpr(
            data.training_inputs, data.training_outputs,
            dt=self.step_size, inputs=self.input_features,
            output=self.output_features,
            normalize=bool(self.config.get("normalize", True)),
            n_restarts_optimizer=int(
                self.config.get("n_restarts_optimizer", 0)),
            trainer_config={"module_id": self.id, "type": "gpr_trainer"})


@register_module("linreg_trainer")
class LinRegTrainer(MLModelTrainer):
    """Least-squares trainer (reference ``LinRegTrainer``,
    ``ml_model_trainer.py:744-767``)."""

    model_type = "LinReg"

    def fit(self, data):
        return fit_linreg(
            data.training_inputs, data.training_outputs,
            dt=self.step_size, inputs=self.input_features,
            output=self.output_features,
            trainer_config={"module_id": self.id, "type": "linreg_trainer"})


@register_module("keras_ann_trainer")
class KerasANNTrainer(MLModelTrainer):
    """Keras-backed ANN trainer (the reference's actual trainer stack,
    ``ml_model_trainer.py:617-667``): trains a Keras Sequential MLP and
    broadcasts a self-contained GraphANN document (keras needed at
    training time only; prediction is pure JAX via ``ml/keras_graph``)."""

    model_type = "GraphANN"

    def fit(self, data):
        from agentlib_mpc_tpu.ml.training import fit_keras_ann

        cfg = self.config
        return fit_keras_ann(
            data.training_inputs, data.training_outputs,
            data.validation_inputs, data.validation_outputs,
            dt=self.step_size, inputs=self.input_features,
            output=self.output_features,
            layers=tuple(cfg.get("layers", (32, 32))),
            activation=cfg.get("activation", "tanh"),
            epochs=int(cfg.get("epochs", 200)),
            learning_rate=float(cfg.get("learning_rate", 1e-2)),
            batch_size=int(cfg.get("batch_size", 64)),
            early_stopping_patience=int(
                cfg.get("early_stopping_patience", 30)),
            trainer_config={"module_id": self.id,
                            "type": "keras_ann_trainer"})
