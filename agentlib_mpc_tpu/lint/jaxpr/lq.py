"""Sound LQ certification: a polynomial-degree lattice over the jaxpr.

Per element, a value carries its maximum possible polynomial degree in
``w``: 0 (independent of ``w`` — including every theta input), 1
(affine), 2 (quadratic), 3 (``NONPOLY`` — degree ≥ 3, transcendental,
piecewise in a ``w``-dependent predicate, or behind an opaque
primitive). The rules are the obvious degree arithmetic — add joins,
mul adds, a smooth nonlinearity of anything ``w``-dependent is
``NONPOLY`` — with one precision saver: a ``select`` whose predicate
carries no ``w`` dependence (a *theta-gated* branch) takes the max of
its branches, because for every FIXED theta the selected branch is a
polynomial of that degree. That is exactly the case the sampled probe
``ops/qp.py:is_lq`` gets wrong: it evaluates at one theta, sees one
branch, and certifies; the lattice sees both.

An LQ program needs objective degree ≤ 2 and constraint degrees ≤ 1;
:func:`certify_lq` proves it for all theta, refutes it with the
offending degree, or returns ``"unknown"`` when an opaque primitive
(``pure_callback`` and friends, custom AD rules) blocks the proof — the
callers then fall back to the sampled probe (see
``ops/qp.py:resolve_qp_routing``).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from agentlib_mpc_tpu.lint.jaxpr.interp import Domain, run_nlp_function

__all__ = ["LQCertificate", "DegreeDomain", "certify_lq", "NONPOLY"]

#: lattice top: not a polynomial of degree ≤ 2 (or not provably one)
NONPOLY = 3


class DegreeDomain(Domain):
    """Per-element polynomial degree in ``w``: int8 in {0, 1, 2, 3}."""

    dtype = np.int8

    def zero(self):
        return np.int8(0)

    def w_element(self, flat_index: int):
        return np.int8(1)

    def join(self, args):
        out = args[0]
        for a in args[1:]:
            out = np.maximum(out, a)
        return np.asarray(out, dtype=self.dtype).copy()

    def mul(self, a, b):
        return np.minimum(a.astype(np.int16) + b.astype(np.int16),
                          NONPOLY).astype(self.dtype)

    def div(self, a, b):
        # b is symbolic here (concrete divisors take the linear path)
        return np.where(b == 0, a, NONPOLY).astype(self.dtype)

    def int_pow(self, a, y: int):
        if y == 0:
            return np.zeros_like(a)
        if y < 0:
            return np.where(a == 0, 0, NONPOLY).astype(self.dtype)
        return np.minimum(a.astype(np.int16) * y, NONPOLY).astype(self.dtype)

    def nonlinear(self, args):
        j = self.join(args)
        return np.where(j == 0, 0, NONPOLY).astype(self.dtype)

    def nonsmooth(self, args):
        # max/abs/comparisons: piecewise — degree-0 inputs stay degree 0
        # (a fixed theta picks a constant), anything else is not a
        # polynomial
        return self.nonlinear(args)

    def select(self, pred, cases):
        base = self.join(cases)
        # theta-gated select (pred degree 0): each fixed theta picks ONE
        # branch, so the result is a polynomial of at most the max branch
        # degree. A w-dependent predicate makes the value piecewise in w.
        return np.where(pred == 0, base, NONPOLY).astype(self.dtype)

    def top_like(self, shape, args):
        out = np.empty(shape, dtype=self.dtype)
        out[...] = NONPOLY
        return out


@dataclasses.dataclass(frozen=True)
class LQCertificate:
    """Outcome of :func:`certify_lq`.

    ``status``:

    * ``"lq"`` — proved linear-quadratic in ``w`` for ALL theta;
    * ``"not_lq"`` — the jaxpr contains a ``w``-path of too-high degree
      (for a gated nonlinearity this is a real refutation: some theta
      activates it);
    * ``"unknown"`` — an opaque primitive with ``w``-tainted inputs
      blocks the proof; route on the sampled probe instead.
    """

    status: str
    objective_degree: int
    eq_degree: int
    ineq_degree: int
    opaque: tuple = ()
    notes: tuple = ()

    @property
    def proved_lq(self) -> bool:
        return self.status == "lq"

    def describe(self) -> str:
        return (f"{self.status} (deg f={self.objective_degree}, "
                f"g={self.eq_degree}, h={self.ineq_degree}"
                + (f", opaque={','.join(sorted(set(self.opaque)))}"
                   if self.opaque else "") + ")")


def _max_degree(avals) -> int:
    out = 0
    for a in avals:
        if a.payload.size:
            out = max(out, int(np.max(a.payload)))
    return out


def certify_lq(nlp, theta, n: int) -> LQCertificate:
    """Prove/refute LQ structure of an :class:`ops.solver.NLPFunctions`
    triple in ``w`` for all theta. ``n`` is the primal dimension (same
    signature anchors as ``ops/qp.py:is_lq``, which this supersedes as
    the routing authority)."""
    import jax.numpy as jnp

    w0 = jnp.zeros((n,))
    degs, opaque, notes = {}, [], []
    for name, fn, in (("f", nlp.f), ("g", nlp.g), ("h", nlp.h)):
        dom = DegreeDomain()
        try:
            outs = run_nlp_function(fn, w0, theta, dom)
            degs[name] = _max_degree(outs)
        except Exception as exc:  # noqa: BLE001 — certification must not
            # kill a backend setup; an uninterpretable function is
            # "unknown", the probe still routes
            degs[name] = NONPOLY
            notes.append(f"{name}: interpreter error: {exc!r}")
            opaque.append("interpreter-error")
            continue
        opaque.extend(dom.opaque)
        notes.extend(dom.notes)
    is_lq_shape = (degs["f"] <= 2 and degs["g"] <= 1 and degs["h"] <= 1)
    if is_lq_shape:
        status = "lq"
    elif opaque:
        # the excessive degree may be an artifact of the opaque smear:
        # neither provable nor refutable
        status = "unknown"
    else:
        status = "not_lq"
    return LQCertificate(
        status=status,
        objective_degree=degs["f"],
        eq_degree=degs["g"],
        ineq_degree=degs["h"],
        opaque=tuple(opaque),
        notes=tuple(notes),
    )
