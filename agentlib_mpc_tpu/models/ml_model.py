"""Hybrid NARX model: ML surrogates + optional white-box dynamics.

Native re-design of the reference's ``CasadiMLModel``
(``models/casadi_ml_model.py``: config validation :61-149, lag bookkeeping
:261-280, recursive/non-recursive output placement :401-465, unified
predict function :496-577, hot-swap :205-231). A subclass declares
variables like any :class:`~agentlib_mpc_tpu.models.model.Model` and may
write white-box ODEs in ``setup``; serialized ML models then provide the
discrete-time dynamics of the remaining states (recursive outputs) and
algebraic relations (non-recursive outputs).

The unified step is a pure function of a *history pytree*
``hist[name] → (L,) array, newest first`` plus the parameter vector and the
ML parameter pytrees — all shapes static, jit/vmap/grad-safe. Hot-swapping
a retrained model is a leaf replacement (no recompile when shapes match).
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu.ml.predictors import Predictor, make_predictor
from agentlib_mpc_tpu.ml.serialized import (
    SerializedMLModel,
    load_serialized_model,
    name_with_lag,
)
from agentlib_mpc_tpu.models.model import Model


class MLModel(Model):
    """Model whose state evolution is (partly) learned.

    Class attribute / constructor arg ``ml_model_sources``: list of
    serialized models (instances, dicts, JSON strings or file paths).
    """

    ml_model_sources: Sequence[Union[str, dict, SerializedMLModel]] = ()

    def __init__(self, overrides: dict | None = None, dt: float | None = None,
                 ml_models: Optional[Iterable] = None):
        super().__init__(overrides=overrides, dt=dt)
        sources = list(ml_models if ml_models is not None
                       else type(self).ml_model_sources)
        self.serialized: dict[str, SerializedMLModel] = {}
        self.predictors: dict[str, Predictor] = {}
        self.ml_params: dict[str, Any] = {}
        self._model_of_output: dict[str, str] = {}
        self.register_ml_models(*[load_serialized_model(s) for s in sources])

    # default: pure black-box model (no white-box equations)
    def setup(self, v):
        from agentlib_mpc_tpu.models.model import ModelEquations

        return ModelEquations()

    # -- registration / validation (casadi_ml_model.py:61-149,374-399) -------

    def register_ml_models(self, *serialized: SerializedMLModel) -> None:
        known = {v.name for v in
                 (*self.inputs, *self.states, *self.parameters,
                  *self.outputs)}
        seen_outputs: dict[str, str] = {}
        for m in serialized:
            key = "|".join(m.output)
            if not m.output:
                raise ValueError("serialized model declares no output")
            if abs(float(m.dt) - float(self.dt)) > 1e-9:
                raise ValueError(
                    f"serialized model for {key!r} has dt={m.dt}, model "
                    f"dt={self.dt}; all must match (reference "
                    f"casadi_ml_model.py:104-121)")
            for out_name, feat in m.output.items():
                if out_name in seen_outputs:
                    raise ValueError(
                        f"output {out_name!r} provided by two ML models")
                seen_outputs[out_name] = key
                if feat.recursive:
                    if out_name not in self.state_names:
                        raise ValueError(
                            f"recursive ML output {out_name!r} must be a "
                            f"declared state")
                else:
                    if out_name not in self.output_names:
                        raise ValueError(
                            f"non-recursive ML output {out_name!r} must be "
                            f"a declared output")
            for feat_name in m.lags_per_variable():
                if feat_name not in known:
                    raise ValueError(
                        f"ML feature {feat_name!r} is not a declared model "
                        f"variable")
            predictor = make_predictor(m)
            if predictor.n_outputs != len(m.output):
                raise ValueError(
                    f"serialized model for {key!r} declares "
                    f"{len(m.output)} outputs but its parameters produce "
                    f"{predictor.n_outputs}")
            self.serialized[key] = m
            self.predictors[key] = predictor
            self.ml_params[key] = self.predictors[key].params
            for out_name in m.output:
                self._model_of_output[out_name] = key
        self._rebuild_lag_tables()

    def update_ml_models(self, *serialized: SerializedMLModel) -> None:
        """Hot-swap retrained models at runtime (reference
        ``update_ml_models``, ``casadi_ml_model.py:205-231``). Same-shape
        parameter updates keep compiled step functions valid."""
        for m in serialized:
            key = "|".join(m.output)
            if key not in self.serialized:
                self.register_ml_models(m)
                continue
            pred = make_predictor(m)
            if pred.n_outputs != len(m.output):
                raise ValueError(
                    f"serialized model for {key!r} declares "
                    f"{len(m.output)} outputs but its parameters produce "
                    f"{pred.n_outputs}")
            self.serialized[key] = m
            old = self.predictors[key]
            self.predictors[key] = pred
            self.ml_params[key] = pred.params
            if old.input_columns != pred.input_columns:
                self._rebuild_lag_tables()

    def _rebuild_lag_tables(self) -> None:
        lags: dict[str, int] = {}
        for m in self.serialized.values():
            for name, lag in m.lags_per_variable().items():
                lags[name] = max(lag, lags.get(name, 0))
        self.ml_lags = lags
        #: states whose evolution is learned (recursive outputs)
        self.narx_state_names = [
            n for n in self.state_names
            if any(n in m.output and m.output[n].recursive
                   for m in self.serialized.values())]
        #: algebraic ML outputs
        self.ml_output_names = [
            n for n in self.output_names
            if any(n in m.output and not m.output[n].recursive
                   for m in self.serialized.values())]
        #: white-box differential states keep their ODEs
        self.wb_state_names = [n for n in self.diff_state_names
                               if n not in self.narx_state_names]
        #: every variable that needs a history window (length ≥ 1)
        self.history_names = sorted(
            set(self.ml_lags)
            | set(self.input_names)
            | set(self.narx_state_names)
            | set(self.wb_state_names))

    def get_lags_per_variable(self) -> dict[str, int]:
        """name → history depth the controller must record (reference
        ``casadi_ml.py:388-397``)."""
        return {n: l for n, l in self.ml_lags.items() if l > 1}

    @property
    def max_lag(self) -> int:
        return max(self.ml_lags.values(), default=1)

    # -- history pytree -------------------------------------------------------

    def init_history(self, values: dict[str, float] | None = None) -> dict:
        """hist[name] = (L,) array, newest first, filled with the current
        (or declared default) value."""
        values = values or {}
        hist = {}
        for n in self.history_names:
            L = max(self.ml_lags.get(n, 1), 1)
            v = float(values.get(n, self.get_var(n).value))
            hist[n] = jnp.full((L,), v)
        return hist

    @staticmethod
    def advance_history(hist: dict, updates: dict[str, Any]) -> dict:
        """Shift every window one step and write the new current values."""
        out = {}
        for n, win in hist.items():
            new = updates.get(n, win[0])
            out[n] = jnp.concatenate(
                [jnp.asarray(new).reshape(1), win[:-1]]) if win.shape[0] > 1 \
                else jnp.asarray(new).reshape(1)
        return out

    # -- unified discrete step (casadi_ml_model.py:496-577) -------------------

    def _flat_input(self, key: str, hist: dict) -> jnp.ndarray:
        """Assemble the model's flat input vector from history windows."""
        m = self.serialized[key]
        cols = []
        for name, feat in m.inputs.items():
            cols.extend(hist[name][i] for i in range(feat.lag))
        for name, feat in m.output.items():
            if feat.recursive:
                cols.extend(hist[name][i] for i in range(feat.lag))
        return jnp.stack(cols)

    def ml_step(self, hist: dict, p: jnp.ndarray,
                ml_params: dict[str, Any] | None = None,
                t: float | jnp.ndarray = 0.0) -> tuple[dict, dict]:
        """One dt step of the unified dynamics.

        Returns (next_states, outputs): next_states maps every
        differential-state name to its value after dt (ML states via
        surrogate, white-box states via RK4 on their ODEs with all other
        quantities held); outputs maps non-recursive ML outputs and
        declarative algebraic outputs to current values.
        """
        if ml_params is None:
            ml_params = self.ml_params
        preds: dict[str, jnp.ndarray] = {}
        for key, predictor in self.predictors.items():
            out = predictor.apply(ml_params[key], self._flat_input(key, hist))
            m = self.serialized[key]
            for j, out_name in enumerate(m.output):
                feat = m.output[out_name]
                val = out[j]
                if feat.recursive and feat.output_type == "difference":
                    val = hist[out_name][0] + val
                preds[out_name] = val

        next_states: dict[str, jnp.ndarray] = {}
        for n in self.narx_state_names:
            next_states[n] = preds[n]

        if self.wb_state_names:
            # white-box ODE states advance by RK4 with ML states, inputs
            # and algebraic outputs held at their current values (the
            # reference fuses an integrator with the black-box passes the
            # same way, casadi_ml_model.py:496-577)
            from agentlib_mpc_tpu.ops.integrators import integrate

            wb_idx = [self.diff_state_names.index(n)
                      for n in self.wb_state_names]
            u = jnp.stack([hist[n][0] for n in self.input_names]) \
                if self.input_names else jnp.zeros((0,))
            z = jnp.stack([hist[n][0] if n in hist
                           else jnp.asarray(float(self.get_var(n).value))
                           for n in self.free_state_names]) \
                if self.free_state_names else jnp.zeros((0,))

            def f(x_wb, tt):
                x_full_list = []
                for i, n in enumerate(self.diff_state_names):
                    if n in self.narx_state_names:
                        x_full_list.append(hist[n][0])
                    else:
                        x_full_list.append(x_wb[self.wb_state_names.index(n)])
                x_full = jnp.stack(x_full_list)
                dx = self.ode(x_full, z, u, p, tt)
                return jnp.stack([dx[i] for i in wb_idx])

            x_wb0 = jnp.stack([hist[n][0] for n in self.wb_state_names])
            x_wb1 = integrate(f, x_wb0, t, float(self.dt), substeps=4,
                              method="rk4")
            for i, n in enumerate(self.wb_state_names):
                next_states[n] = x_wb1[i]

        outputs: dict[str, jnp.ndarray] = {}
        for n in self.ml_output_names:
            outputs[n] = preds[n]
        # declarative algebraic outputs at the current point
        if set(self.output_names) - set(self.ml_output_names):
            x_full = jnp.stack(
                [hist[n][0] for n in self.diff_state_names]) \
                if self.diff_state_names else jnp.zeros((0,))
            z = jnp.stack([hist[n][0] if n in hist
                           else jnp.asarray(float(self.get_var(n).value))
                           for n in self.free_state_names]) \
                if self.free_state_names else jnp.zeros((0,))
            u = jnp.stack([hist[n][0] for n in self.input_names]) \
                if self.input_names else jnp.zeros((0,))
            y = self.output(x_full, z, u, p, t)
            for i, n in enumerate(self.output_names):
                if n not in self.ml_output_names:
                    outputs[n] = y[i]
        return next_states, outputs

    def simulate_ml_step(self, hist: dict, p, inputs: dict[str, float],
                         ml_params=None, t=0.0) -> tuple[dict, dict, dict]:
        """Convenience closed-loop driver: apply `inputs`, take one step,
        advance the history. Returns (hist_next, next_states, outputs)."""
        hist = dict(hist)
        for n, v in inputs.items():
            hist[n] = hist[n].at[0].set(v)
        next_states, outputs = self.ml_step(hist, jnp.asarray(p),
                                            ml_params=ml_params, t=t)
        hist_next = self.advance_history(hist, dict(next_states))
        return hist_next, next_states, outputs
