"""physXAI bridge: convert physXAI training artifacts to the exchange format.

Counterpart of the reference's physXAI plugin
(``machine_learning_plugins/physXAI/``: config translation
``model_config_creation.py:26-150``, model generation
``model_generation.py:45-120``): physXAI preprocessing configs name
features as ``<name>_lag<k>`` and outputs as ``Change(<name>)`` for
difference targets; artifacts are joblib-dumped sklearn estimators or
layer-weight dumps. This module parses those conventions into
`Feature`/`OutputFeature` metadata and wraps the artifacts as serialized
models. The physXAI package itself is optional — running its training
scripts (`generate_physxai_models`) needs it installed, while converting
existing artifacts does not.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Optional, Union

import numpy as np

from agentlib_mpc_tpu.ml.serialized import (
    Feature,
    OutputFeature,
    SerializedANN,
    SerializedLinReg,
    SerializedMLModel,
)

#: physXAI naming conventions (reference ``model_config_creation.py:8-9``)
OUTPUT_TYPE_PATTERN = r"Change\((.*)\)"
LAG_PATTERN = r"_lag(\d+)$"


def parse_physxai_features(
        preprocessing: dict) -> tuple[float, dict, dict]:
    """(dt, inputs, output) from a physXAI preprocessing dict (reference
    ``physXAI_2_agentlib_json``, ``model_config_creation.py:26-150``)."""
    dt = float(preprocessing["time_step"])
    shift = preprocessing.get("shift", 1)
    if shift != 1:
        raise ValueError(
            f"physXAI shift must be 1 for MPC use, got {shift}")
    outputs = preprocessing.get("output")
    if not isinstance(outputs, list) or len(outputs) != 1:
        raise ValueError("physXAI output must be a list with one element")

    output_str = outputs[0]
    output_type = "absolute"
    m = re.match(OUTPUT_TYPE_PATTERN, output_str)
    out_name = output_str
    if m:
        output_type = "difference"
        out_name = m.group(1).strip()

    # group "<name>_lag<k>" features; lag depth = 1 + max k, and the lag
    # indices must be consecutive (the reference validates likewise)
    lags: dict[str, list[int]] = {}
    order: list[str] = []
    for input_str in preprocessing["inputs"]:
        lag = 0
        base = input_str
        lm = re.search(LAG_PATTERN, input_str)
        if lm:
            lag = int(lm.group(1))
            base = input_str[:lm.start()]
        if base not in lags:
            lags[base] = []
            order.append(base)
        lags[base].append(lag)
    for base, ks in lags.items():
        if sorted(ks) != list(range(len(ks))):
            raise ValueError(
                f"physXAI lags for {base!r} are not consecutive from 0: "
                f"{sorted(ks)}")

    recursive = out_name in lags
    inputs = {base: Feature(name=base, lag=len(ks))
              for base, ks in lags.items() if base != out_name}
    output = {out_name: OutputFeature(
        name=out_name, lag=len(lags.get(out_name, [0])),
        output_type=output_type, recursive=recursive)}
    if not recursive and output_type == "difference":
        raise ValueError(
            f"physXAI output {out_name!r} is a Change() target but does "
            f"not appear among the inputs — unsupported combination")
    return dt, inputs, output


def convert_physxai_model(
        preprocessing: dict,
        artifact,
        model_type: str = "LinReg",
        trainer_config: Optional[dict] = None) -> SerializedMLModel:
    """Wrap a physXAI artifact as a serialized model.

    artifact: a fitted sklearn LinearRegression (or a joblib path to one)
    for ``model_type="LinReg"``; a ``{"weights": [...], "biases": [...],
    "activations": [...]}`` layer dump (or a path to a joblib of one) for
    ``model_type="ANN"``.
    """
    dt, inputs, output = parse_physxai_features(preprocessing)
    if isinstance(artifact, (str, Path)):
        import joblib

        artifact = joblib.load(artifact)
    meta = {"source": "physXAI", **(trainer_config or {})}
    if model_type == "LinReg":
        return SerializedLinReg.from_sklearn(
            artifact, dt=dt, inputs=inputs, output=output,
            trainer_config=meta)
    if model_type == "ANN":
        return SerializedANN(
            dt=dt, inputs=inputs, output=output, trainer_config=meta,
            weights=[np.asarray(w).tolist() for w in artifact["weights"]],
            biases=[np.asarray(b).tolist() for b in artifact["biases"]],
            activations=list(artifact["activations"]))
    raise ValueError(f"unsupported physXAI model_type {model_type!r}")


def generate_physxai_models(scripts: Union[list, dict], scripts_path: str,
                            training_data_path: str, run_id: str,
                            save_path: str = "models",
                            time_step: int = 900) -> list[str]:
    """Run physXAI training scripts (requires the physXAI package — the
    reference gates identically, ``model_generation.py:9-13``)."""
    try:
        from physXAI import models  # noqa: F401 - registers model types
    except ImportError as exc:
        raise ImportError(
            "generate_physxai_models needs the physXAI package "
            "(git+https://github.com/RWTH-EBC/physXAI.git); converting "
            "existing artifacts with convert_physxai_model does not"
        ) from exc
    import importlib.util
    import os

    entries = scripts.items() if isinstance(scripts, dict) \
        else [(None, s) for s in scripts]
    out = []
    for _name, script in entries:
        if not script.endswith(".py"):
            script += ".py"
        script_path = os.path.join(scripts_path, script)
        spec = importlib.util.spec_from_file_location(
            "physxai_train", script_path)
        if spec is None or spec.loader is None:
            raise FileNotFoundError(
                f"physXAI training script not found: {script_path}")
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        # always return what train_model produced (artifact paths/names);
        # dict keys are only labels for the caller's bookkeeping
        out.append(module.train_model(
            base_path=os.path.abspath(save_path), folder_name=run_id,
            training_data_path=os.path.abspath(training_data_path),
            time_step=time_step))
    return out
