"""Decentralized broker-based ADMM: the cooled-room / cooler pair.

Mirrors the reference's local ADMM integration example
(``examples/admm/admm_example_local.py`` with ``configs/cooled_room.json``,
``cooler.json``, ``simulator.json``): the room optimizes the air flow it
*receives* (coupling on its input ``mDot``), the cooler optimizes the air
flow it *supplies* (coupling on its output ``mDot_out``, actuating its
control ``mDot``), both broadcast trajectories under the shared wire alias
and must agree; the simulator integrates the room plant with the cooler's
actuated flow. Closed-loop assertion: the room cools down (the reference's
``testing=True`` assertion, ``admm_example_local.py:99-101``).
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu.models.zoo import CooledRoom, Cooler
from agentlib_mpc_tpu.runtime.mas import LocalMAS
import agentlib_mpc_tpu.modules  # noqa: F401 - registers module types

UB = 295.15
TIME_STEP = 300.0

ROOM = {
    "id": "CooledRoom",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "admm_module",
            "type": "admm_local",
            "optimization_backend": {
                "type": "jax_admm",
                "model": {"class": CooledRoom},
                "discretization_options": {
                    "collocation_order": 2,
                    "collocation_method": "legendre",
                },
                "solver": {"max_iter": 40},
            },
            "time_step": TIME_STEP,
            "prediction_horizon": 8,
            "max_iterations": 6,
            "penalty_factor": 10.0,
            "parameters": [{"name": "s_T", "value": 1.0}],
            "inputs": [
                {"name": "load", "value": 150},
                {"name": "T_in", "value": 290.15},
                {"name": "T_upper", "value": UB},
            ],
            "controls": [],
            "states": [
                {"name": "T", "value": 298.16, "ub": 303.15, "lb": 288.15,
                 "alias": "T", "source": "Simulation"},
            ],
            "couplings": [
                {"name": "mDot", "alias": "mDotCoolAir", "value": 0.02,
                 "ub": 0.05, "lb": 0.0},
            ],
        },
    ],
}

COOLER = {
    "id": "Cooler",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "admm_module",
            "type": "admm_local",
            "optimization_backend": {
                "type": "jax_admm",
                "model": {"class": Cooler},
                "discretization_options": {
                    "collocation_order": 2,
                    "collocation_method": "legendre",
                },
                "solver": {"max_iter": 40},
            },
            "time_step": TIME_STEP,
            "prediction_horizon": 8,
            "max_iterations": 6,
            "penalty_factor": 10.0,
            "parameters": [{"name": "r_mDot", "value": 1.0}],
            "controls": [
                {"name": "mDot", "value": 0.02, "ub": 0.05, "lb": 0.0},
            ],
            "couplings": [
                {"name": "mDot_out", "alias": "mDotCoolAir", "value": 0.02},
            ],
        },
    ],
}

SIM = {
    "id": "Simulation",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "simulator",
            "type": "simulator",
            "model": {"class": CooledRoom,
                      "states": [{"name": "T", "value": 298.16}]},
            "t_sample": 60,
            "outputs": [{"name": "T_out", "value": 298.16, "alias": "T"}],
            "inputs": [{"name": "mDot", "value": 0.02, "alias": "mDot"}],
        },
    ],
}


@pytest.fixture(scope="module")
def results():
    mas = LocalMAS([ROOM, COOLER, SIM], env={"rt": False})
    mas.run(until=1800)
    return mas.get_results()


def test_room_cools_down(results):
    sim = results["Simulation"]["simulator"]
    temps = sim[("variable", "T")] if ("variable", "T") in sim else sim["T"]
    temps = np.asarray(temps, dtype=float)
    assert temps[0] > temps[-1], "room should cool towards the comfort band"
    assert temps[-1] < 297.0


def test_couplings_agree(results):
    """After the last full round, room and cooler trajectories must be
    close (consensus)."""
    room = results["CooledRoom"]["admm_module"]["admm"]
    cooler = results["Cooler"]["admm_module"]["admm"]
    t_last = room.index.get_level_values("time").max()
    it_last = room.loc[t_last].index.get_level_values("iteration").max()
    r = room.loc[(t_last, it_last)][("variable", "mDot")].to_numpy()
    c = cooler.loc[(t_last, it_last)][("variable", "mDot_out")].to_numpy()
    assert np.max(np.abs(r - c)) < 5e-3


def test_iteration_results_shape(results):
    room = results["CooledRoom"]["admm_module"]["admm"]
    assert room.index.names == ["time", "iteration", "grid"]
    n_iters = room.index.get_level_values("iteration").nunique()
    assert n_iters >= 2
