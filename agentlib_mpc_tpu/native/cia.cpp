// Combinatorial Integer Approximation (CIA) branch-and-bound solver.
//
// Native replacement for the reference's pycombina dependency (C++
// branch-and-bound driven from agentlib_mpc/optimization_backends/casadi_/
// minlp_cia.py:124-150): given a relaxed binary trajectory b_rel in [0,1]
// of shape (N, nb), find a binary schedule B in {0,1} minimizing the CIA
// objective
//
//     eta = max_{t,i} | sum_{tau<=t} (b_rel[tau,i] - B[tau,i]) * dt[tau] |
//
// subject to per-control maximum switch counts and (optionally) a SOS1
// one-hot constraint per time step. Depth-first search over time steps
// with greedy child ordering (first leaf = sum-up-rounding-like incumbent)
// and partial-objective pruning. A node budget bounds worst-case time; the
// incumbent at budget exhaustion is returned (status 1).
//
// Exported C API (ctypes-friendly):
//   int cia_solve(const double* b_rel, int N, int nb, const double* dt,
//                 const int* max_switches, int sos1,
//                 double* b_out, double* obj_out, long long max_nodes);
// Returns 0 = proven optimal, 1 = node budget hit (incumbent returned),
//         -1 = invalid arguments.

#include <algorithm>
#include <cmath>
#include <cstring>
#include <vector>

namespace {

struct Problem {
    const double* b_rel;
    int N;
    int nb;
    const double* dt;
    const int* max_switches;
    bool sos1;
    long long max_nodes;

    long long nodes = 0;
    double incumbent = 1e300;
    std::vector<signed char> best;      // N * nb
    std::vector<signed char> current;   // N * nb
    std::vector<double> dev;            // nb running deviations
    std::vector<int> switches;          // nb switch counts
    std::vector<signed char> last;      // nb last values (-1 = none yet)
    // enumerated per-step choices: sos1 -> one-hot rows, else all 2^nb rows
    std::vector<std::vector<signed char>> choices;
};

// objective contribution if at step t we pick `choice`; returns the new
// max |dev| over controls after the step (the quantity that must stay
// below the incumbent for the subtree to survive)
double step_dev(Problem& P, int t, const signed char* choice,
                std::vector<double>& new_dev) {
    double m = 0.0;
    for (int i = 0; i < P.nb; ++i) {
        new_dev[i] = P.dev[i] + (P.b_rel[t * P.nb + i] - choice[i]) * P.dt[t];
        m = std::max(m, std::fabs(new_dev[i]));
    }
    return m;
}

void dfs(Problem& P, int t, double partial_max) {
    if (partial_max >= P.incumbent) return;
    if (t == P.N) {
        P.incumbent = partial_max;
        P.best = P.current;
        return;
    }
    if (P.nodes++ > P.max_nodes) return;

    // order children by the max-deviation they produce (greedy best-first:
    // makes the first leaf a high-quality incumbent, so pruning bites early)
    int nc = (int)P.choices.size();
    std::vector<std::pair<double, int>> order(nc);
    std::vector<double> nd(P.nb);
    for (int c = 0; c < nc; ++c) {
        order[c] = {step_dev(P, t, P.choices[c].data(), nd), c};
    }
    std::sort(order.begin(), order.end());

    std::vector<double> saved_dev = P.dev;
    std::vector<int> saved_sw = P.switches;
    std::vector<signed char> saved_last = P.last;

    for (auto& [d, c] : order) {
        double child_max = std::max(partial_max, d);
        if (child_max >= P.incumbent) break;  // sorted: the rest are worse
        const signed char* choice = P.choices[c].data();
        // switch feasibility
        bool ok = true;
        for (int i = 0; i < P.nb; ++i) {
            int sw = saved_sw[i];
            if (saved_last[i] >= 0 && choice[i] != saved_last[i]) sw++;
            if (P.max_switches && sw > P.max_switches[i]) { ok = false; break; }
            P.switches[i] = sw;
        }
        if (!ok) {
            P.switches = saved_sw;
            continue;
        }
        for (int i = 0; i < P.nb; ++i) {
            P.dev[i] = saved_dev[i] + (P.b_rel[t * P.nb + i] - choice[i]) * P.dt[t];
            P.last[i] = choice[i];
            P.current[t * P.nb + i] = choice[i];
        }
        dfs(P, t + 1, child_max);
        P.dev = saved_dev;
        P.switches = saved_sw;
        P.last = saved_last;
        if (P.nodes > P.max_nodes) return;
    }
}

}  // namespace

extern "C" int cia_solve(const double* b_rel, int N, int nb, const double* dt,
                         const int* max_switches, int sos1,
                         double* b_out, double* obj_out, long long max_nodes) {
    if (N <= 0 || nb <= 0 || nb > 16) return -1;
    Problem P;
    P.b_rel = b_rel;
    P.N = N;
    P.nb = nb;
    P.dt = dt;
    P.max_switches = max_switches;
    P.sos1 = sos1 != 0 && nb > 1;
    P.max_nodes = max_nodes > 0 ? max_nodes : (1LL << 40);
    P.best.assign((size_t)N * nb, 0);
    P.current.assign((size_t)N * nb, 0);
    P.dev.assign(nb, 0.0);
    P.switches.assign(nb, 0);
    P.last.assign(nb, -1);

    if (P.sos1) {
        for (int i = 0; i < nb; ++i) {
            std::vector<signed char> row(nb, 0);
            row[i] = 1;
            P.choices.push_back(row);
        }
    } else {
        for (int m = 0; m < (1 << nb); ++m) {
            std::vector<signed char> row(nb);
            for (int i = 0; i < nb; ++i) row[i] = (m >> i) & 1;
            P.choices.push_back(row);
        }
    }

    dfs(P, 0, 0.0);

    for (int k = 0; k < N * nb; ++k) b_out[k] = (double)P.best[k];
    *obj_out = P.incumbent;
    return P.nodes > P.max_nodes ? 1 : 0;
}
