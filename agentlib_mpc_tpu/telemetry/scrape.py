"""Prometheus scrape endpoint: ``telemetry.serve_metrics(port)``.

A stdlib ``http.server`` thread serving the registry's existing text
exposition at ``/metrics`` (plus a ``/healthz`` liveness stub) — no new
dependencies, clean shutdown, so the fleet benches and long-lived
serving processes can run under a real scraper instead of exporting
JSONL artifacts by hand. One thread, ThreadingHTTPServer semantics:
each scrape renders a consistent snapshot under the registry lock.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger(__name__)


class MetricsServer:
    """Owns the listener thread; close() (or the context manager) shuts
    it down cleanly. ``port=0`` binds an ephemeral port — read the real
    one from ``.port``."""

    def __init__(self, port: int = 0, registry=None,
                 host: str = "127.0.0.1"):
        if registry is None:
            from agentlib_mpc_tpu.telemetry import registry as _reg

            registry = _reg.DEFAULT
        self.registry = registry

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.split("?", 1)[0] == "/metrics":
                    body = server.registry.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.split("?", 1)[0] == "/healthz":
                    self.send_response(200)
                    self.send_header("Content-Length", "3")
                    self.end_headers()
                    self.wfile.write(b"ok\n")
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):
                logger.debug("metrics scrape: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="telemetry-metrics-server")
        self._thread.start()
        logger.info("serving /metrics on %s:%d", host, self.port)

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}/metrics"

    def close(self) -> None:
        """Stop the listener and join the thread (idempotent)."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def serve_metrics(port: int = 0, registry=None,
                  host: str = "127.0.0.1") -> MetricsServer:
    """Start the scrape endpoint; returns the :class:`MetricsServer`
    (``.port`` for the bound port, ``.close()`` for shutdown)."""
    return MetricsServer(port=port, registry=registry, host=host)
