"""bench.py fail-soft orchestration (round-4 fix for VERDICT r3 #1a).

Round 3 lost its benchmark to a wedged TPU tunnel (BENCH_r03:
``rc=1, parsed=null``). These tests pin the contract that made that
impossible: whatever the platform probe / worker children do — hang,
crash, emit garbage — ``bench.py`` exits 0 and prints a headline JSON
line with a ``platform`` field. ``TestFailsoft`` fakes the children at
the ``_spawn`` / ``_default_platform`` seam (no JAX, no subprocesses,
no timing); ``TestArchitectureBaselines`` (slow tier) smoke-tests the
BASELINE.md instruments with real tiny solves.
"""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench


def _headline_lines(capsys) -> list[dict]:
    out = capsys.readouterr().out
    return [json.loads(ln) for ln in out.strip().splitlines()
            if ln.startswith("{")]


def _fake_measurement(step_ms=100.0, platform="cpu") -> dict:
    return {"n_agents": 256, "step_ms": step_ms, "compile_ms": 5000.0,
            "agents_per_sec": 256 / (step_ms / 1e3),
            "zone_iters_per_sec": 2560 / (step_ms / 1e3),
            "platform": platform}


@pytest.fixture(autouse=True)
def _plain_argv(monkeypatch):
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    # collapse the bounded tunnel re-probe window: these contract tests
    # fake a permanently-dead probe and must not wait out real re-probe
    # sleeps (the retry behavior itself is pinned by TestBoundedReprobe)
    monkeypatch.setattr(bench, "PROBE_RETRY_WINDOW_S", 0.0)


class TestBoundedReprobe:
    """VERDICT r5 weak #2 / task #1: the driver invocation re-runs the
    watchdogged platform probe on failure — bounded window, every attempt
    logged as ``probe_attempts`` in the final JSON line."""

    def test_late_tunnel_revival_is_caught(self, monkeypatch, capsys):
        monkeypatch.setattr(bench, "PROBE_RETRY_INTERVAL_S", 0.01)
        monkeypatch.setattr(bench, "PROBE_RETRY_WINDOW_S", 5.0)
        results = iter([None, None, "tpu"])
        monkeypatch.setattr(bench, "_default_platform",
                            lambda: next(results))

        def fake_spawn(args, env, timeout):
            if "--worker" in args:
                return [dict(_fake_measurement(50.0, "tpu"),
                             section="headline")]
            return [_fake_measurement(100.0)]   # the CPU baseline probe

        monkeypatch.setattr(bench, "_spawn", fake_spawn)
        bench.main()
        line = _headline_lines(capsys)[-1]
        assert line["platform"] == "tpu"
        assert line["tpu_fallback_to_cpu"] is False
        assert [a["platform"] for a in line["probe_attempts"]] == \
            [None, None, "tpu"]

    def test_window_exhaustion_logs_every_attempt(self, monkeypatch,
                                                  capsys):
        monkeypatch.setattr(bench, "PROBE_RETRY_INTERVAL_S", 0.01)
        monkeypatch.setattr(bench, "PROBE_RETRY_WINDOW_S", 0.05)
        monkeypatch.setattr(bench, "_default_platform", lambda: None)
        monkeypatch.setattr(bench, "_spawn",
                            lambda a, e, t: [_fake_measurement()])
        bench.main()
        line = _headline_lines(capsys)[-1]
        assert line["platform"] == "cpu"
        # >= 2 real attempts across the window, all failed
        assert len(line["probe_attempts"]) >= 2
        assert all(a["platform"] is None for a in line["probe_attempts"])

    def test_clean_cpu_answer_is_never_retried(self, monkeypatch, capsys):
        """A machine that ANSWERS "cpu" has no tunnel to wait for — one
        probe, no sleeps (tests and CPU boxes must not pay the window)."""
        monkeypatch.setattr(bench, "PROBE_RETRY_WINDOW_S", 900.0)
        calls = []

        def probe():
            calls.append(1)
            return "cpu"

        monkeypatch.setattr(bench, "_default_platform", probe)
        monkeypatch.setattr(bench, "_spawn",
                            lambda a, e, t: [_fake_measurement()])
        bench.main()
        line = _headline_lines(capsys)[-1]
        assert line["platform"] == "cpu"
        assert len(calls) == 1
        assert len(line["probe_attempts"]) == 1


class TestFailsoft:
    def test_wedged_tunnel_degrades_to_cpu(self, monkeypatch, capsys):
        """Probe times out (returns None) → CPU probe child runs, JSON
        carries platform=cpu and the fallback flag."""
        monkeypatch.setattr(bench, "_default_platform", lambda: None)
        calls = []

        def fake_spawn(args, env, timeout):
            # record only — assertions inside this fake would be
            # swallowed by main()'s catch-all and surface as a
            # misleading catastrophe JSON; assert after main() returns
            calls.append((args, env))
            return [_fake_measurement()]

        monkeypatch.setattr(bench, "_spawn", fake_spawn)
        bench.main()
        line = _headline_lines(capsys)[-1]
        # platform-qualified headline: a CPU number must never publish
        # under the TPU trajectory metric (ROADMAP item 2)
        assert line["metric"] == "admm256_step_ms_cpu"
        assert line["value"] == 100.0
        assert line["platform"] == "cpu"
        assert line["tpu_fallback_to_cpu"] is True
        assert line["vs_baseline"] == 1.0
        assert calls, "CPU child never spawned"
        args, env = calls[0]
        assert "--probe" in args, "a dead platform must go to the CPU child"
        assert env.get("JAX_PLATFORMS") == "cpu"
        assert "PALLAS_AXON_POOL_IPS" not in env

    def test_tpu_worker_crash_degrades_to_cpu(self, monkeypatch, capsys):
        """Probe says TPU, but the worker child dies → CPU fallback."""
        monkeypatch.setattr(bench, "_default_platform", lambda: "axon")

        def fake_spawn(args, env, timeout):
            if "--worker" in args:
                raise RuntimeError("bench child rc=1: tunnel reset")
            return [_fake_measurement()]

        monkeypatch.setattr(bench, "_spawn", fake_spawn)
        bench.main()
        line = _headline_lines(capsys)[-1]
        assert line["platform"] == "cpu"
        assert line["tpu_fallback_to_cpu"] is True
        assert line["value"] == 100.0

    def test_healthy_tpu_reports_vs_cpu_baseline(self, monkeypatch, capsys):
        """A healthy accelerator run spawns ONE evidence worker; the
        final line embeds every section (VERDICT r4 #1)."""
        monkeypatch.setattr(bench, "_default_platform", lambda: "axon")
        worker_args = []

        def fake_spawn(args, env, timeout):
            if "--worker" in args:
                worker_args.append(args)
                return [
                    {"section": "headline",
                     **_fake_measurement(step_ms=100.0, platform="axon")},
                    {"section": "ldl_micro", "lu_ms": 5.0, "ldl_ms": 1.0,
                     "platform": "axon"},
                    {"section": "scaling", "rows": [{"n_agents": 4}]},
                ]
            return [_fake_measurement(step_ms=1500.0)]

        monkeypatch.setattr(bench, "_spawn", fake_spawn)
        bench.main()
        line = _headline_lines(capsys)[-1]
        assert line["platform"] == "axon"
        assert line["tpu_fallback_to_cpu"] is False
        assert line["vs_baseline"] == 15.0
        assert "--evidence" in worker_args[0]
        assert line["evidence"]["ldl_micro"]["ldl_ms"] == 1.0
        assert line["evidence"]["scaling"]["rows"] == [{"n_agents": 4}]

    def test_dead_headline_section_degrades_to_cpu(self, monkeypatch,
                                                   capsys):
        """The evidence child surviving but its HEADLINE section failing
        still degrades to a CPU measurement (partial evidence must not
        masquerade as a result)."""
        monkeypatch.setattr(bench, "_default_platform", lambda: "axon")

        def fake_spawn(args, env, timeout):
            if "--worker" in args:
                return [{"section": "headline", "error": "OOM"},
                        {"section": "ldl_micro", "lu_ms": 5.0}]
            return [_fake_measurement()]

        monkeypatch.setattr(bench, "_spawn", fake_spawn)
        bench.main()
        line = _headline_lines(capsys)[-1]
        assert line["platform"] == "cpu"
        assert line["tpu_fallback_to_cpu"] is True
        assert line["value"] == 100.0

    def test_cpu_only_machine_is_not_a_fallback(self, monkeypatch, capsys):
        """A machine whose default platform IS cpu is a normal run."""
        monkeypatch.setattr(bench, "_default_platform", lambda: "cpu")
        monkeypatch.setattr(bench, "_spawn",
                            lambda *a, **k: [_fake_measurement()])
        bench.main()
        line = _headline_lines(capsys)[-1]
        assert line["platform"] == "cpu"
        assert line["tpu_fallback_to_cpu"] is False

    def test_catastrophe_still_emits_json(self, monkeypatch, capsys):
        """Even probe + both children failing must print a parsable
        headline line and exit cleanly (the round-3 lesson)."""
        monkeypatch.setattr(bench, "_default_platform", lambda: None)

        def dead_spawn(args, env, timeout):
            raise RuntimeError("everything is broken")

        monkeypatch.setattr(bench, "_spawn", dead_spawn)
        bench.main()  # must not raise
        line = _headline_lines(capsys)[-1]
        # qualified: a null datapoint must not land in the TPU series
        assert line["metric"] == "admm256_step_ms_unavailable"
        assert line["value"] is None
        assert line["platform"] == "unavailable"
        assert "error" in line

    def test_headline_metric_is_platform_qualified(self):
        """The unqualified trajectory name is reserved for TPU; every
        other platform gets a suffix so the BENCH trajectory never mixes
        platforms (r04/r05 read as a 3.6x regression when they were a
        platform change)."""
        assert bench._headline_metric("tpu") == "admm256_step_ms"
        assert bench._headline_metric("cpu") == "admm256_step_ms_cpu"
        assert bench._headline_metric("gpu") == "admm256_step_ms_gpu"

    def test_xla_noise_filter_drops_machine_feature_blob(self):
        """The multi-kB XLA:CPU machine-feature/SIGILL warning blob must
        not reach the driver-stored stderr tail; real bench lines and
        unrelated warnings survive."""
        noise = ("W0000 Machine type used for XLA:CPU compilation "
                 "doesn't match the machine type for execution. Compile "
                 "machine features: [+64bit,+adx,+avx512f] running this "
                 "code may cause SIGILL\n")
        keep = "[bench] platform=cpu step=100.0ms\nsome other warning\n"
        out = bench._filter_xla_noise(noise + keep)
        assert "Compile machine features" not in out
        assert "[bench] platform=cpu step=100.0ms" in out
        assert "some other warning" in out
        assert "filtered 1 known-noise" in out
        # clean text passes through untouched (no spurious summary line)
        assert bench._filter_xla_noise(keep) == keep

    def test_scaling_mode_always_emits_json(self, monkeypatch, capsys):
        monkeypatch.setattr(sys, "argv", ["bench.py", "--scaling"])
        monkeypatch.setattr(bench, "_default_platform", lambda: None)
        monkeypatch.setattr(
            bench, "_spawn",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("dead")))
        bench.main()  # must not raise
        line = _headline_lines(capsys)[-1]
        assert line["value"] is None
        assert line["platform"] == "unavailable"

    def test_warm_step_layout_matches_build_step(self):
        """warm_step is the ONE place that knows build_step's positional
        layout; pin the mapping with sentinels so a signature change in
        either trips here instead of silently mis-wiring the profiler
        or the measurement loop."""
        calls = {}

        def fake_step(*a):
            calls["args"] = a

        args = tuple(f"arg{i}" for i in range(8))
        out = tuple(f"out{i}" for i in range(5))
        bench.warm_step(fake_step, args, out)
        assert calls["args"] == ("arg0", "arg1", "out0", "out1", "out2",
                                 "out3", "out4", "arg7")

    def test_spawn_rejects_json_free_child(self, monkeypatch):
        class FakeProc:
            returncode = 0
            stdout = "no json here\n"
            stderr = ""

        monkeypatch.setattr(bench.subprocess, "run",
                            lambda *a, **k: FakeProc())
        with pytest.raises(RuntimeError, match="no JSON"):
            bench._spawn(["--worker"], {}, 1.0)


@pytest.mark.slow
class TestArchitectureBaselines:
    """The BASELINE.md instruments (--sequential / --conventional) keep
    working: tiny fleets, real solves, sane JSON fields."""

    def test_sequential_native_instrument(self):
        out = bench.run_sequential_native(2, admm_iters=2)
        assert out["platform"] == "cpu-sequential-native"
        assert out["value"] > 0
        assert out["nlp_calls_per_step"] == 4
        assert 0 <= out["consensus_spread"] < 1.0

    def test_conventional_slsqp_instrument(self):
        out = bench.run_conventional(2, admm_iters=2)
        assert out["platform"] == "cpu-sequential-slsqp"
        assert out["value"] > 0
        assert 0 <= out["consensus_spread"] < 1.0


@pytest.mark.slow
@pytest.mark.chaos
class TestChaosSmoke:
    """``bench.py --chaos SEED`` (ISSUE 2 satellite): the fused 4-zone
    quarantine smoke emits sane, platform-tagged JSON and upholds the
    resilience contract with real zone solves."""

    def test_chaos_mode_contract(self, capsys):
        out = bench.run_chaos(seed=3, n_agents=4)
        assert out["metric"] == "chaos_smoke"
        assert out["seed"] == 3
        assert 0 <= out["poisoned_agent"] < 4
        assert out["state_finite"] is True
        assert out["healthy_trajectories_finite"] is True
        assert out["quarantined_agent_iters"] >= 1
        assert out["extra_retraces"] == 0
        assert out["platform"]
        # the CLI contract: ONE parsable JSON line on stdout
        lines = _headline_lines(capsys)
        assert lines[-1]["metric"] == "chaos_smoke"

    def test_chaos_is_deterministic_in_the_seed(self):
        a = bench.run_chaos(seed=11, n_agents=4)
        b = bench.run_chaos(seed=11, n_agents=4)
        assert a["poisoned_agent"] == b["poisoned_agent"]
        assert a["quarantined_agent_iters"] == b["quarantined_agent_iters"]
