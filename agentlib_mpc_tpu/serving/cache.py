"""Fingerprint-keyed compile cache for fused serving engines.

The most expensive event in the serving plane is building a fused
engine: jaxpr certification, solver tracing and XLA compilation of the
whole ADMM round (seconds to tens of seconds — the "compile latency /
persistent cache" table in PERF.md). The cache makes that a
once-per-structure cost: a tenant whose problem is structurally
identical to one already compiled — including a tenant REJOINING after
an eviction — reuses the warm executable, and the join is a dictionary
lookup plus a slot splice.

Counters: ``serving_compile_cache_hits_total`` /
``serving_compile_cache_misses_total`` (labelled by bucket digest),
``serving_cache_evictions_total`` when an ``max_engines`` bound is set,
and a ``serving_join_build_seconds`` histogram labelled ``cached="yes"/"no"``
so the cached-vs-cold join-latency A/B is always measured in
production, not just in the bench.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from agentlib_mpc_tpu import telemetry


class CompileCache:
    """Maps hashable engine keys to built (and warmed) engine objects.

    An engine is a compiled executable plus static metadata — the
    artifact worth keeping for the life of the process (the persistent
    XLA cache plays the cross-process role), so by default the cache
    never evicts. A long-lived multi-structure plane can bound it with
    ``max_engines``: least-recently-USED entries (hits refresh recency)
    are dropped once the bound is exceeded, counted in
    ``serving_cache_evictions_total{bucket=}`` — a rejoin of an evicted
    structure is then a measured cache MISS (cold rebuild). Engines
    serving a LIVE bucket are referenced by the bucket itself, so LRU
    eviction only ever costs retired structures their warm rejoin.
    ``get_or_build(key, builder)`` returns ``(engine, hit, latency_s)``.
    """

    def __init__(self, max_engines: "int | None" = None):
        if max_engines is not None and int(max_engines) < 1:
            raise ValueError(f"max_engines must be >= 1 or None, "
                             f"got {max_engines}")
        self.max_engines = None if max_engines is None else int(max_engines)
        self._entries: "OrderedDict" = OrderedDict()  # key -> (engine, label)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        #: engines revived from a cross-process artifact (the on-disk
        #: engine store) instead of built — neither a hit (no warm
        #: executable existed in THIS process) nor a cold build (no
        #: certify/trace was paid)
        self.persistent_restores = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    def note_hit(self, label: str = "") -> None:
        """Count an executable reuse that never had to consult the
        entry dict — a tenant joining a LIVE bucket whose engine is
        already serving. Same counter family as lookup hits: the metric
        is "compiled engines reused", however shallow the path."""
        self.hits += 1
        telemetry.journal_event("cache.engine", outcome="hit",
                                bucket=label or "?", live_bucket=True)
        if telemetry.enabled():
            telemetry.counter(
                "serving_compile_cache_hits_total",
                "serving engine cache lookups that reused a compiled "
                "engine").inc(bucket=label or "?")

    def _evict_over_bound(self) -> None:
        while self.max_engines is not None and \
                len(self._entries) > self.max_engines:
            _key, (_engine, label) = self._entries.popitem(last=False)
            self.evictions += 1
            if telemetry.enabled():
                telemetry.counter(
                    "serving_cache_evictions_total",
                    "compiled serving engines dropped by the LRU bound "
                    "(max_engines)").inc(bucket=label or "?")

    def get_or_build(self, key, builder, label: str = "",
                     restorer=None):
        """``restorer``: optional zero-arg callable tried BEFORE
        ``builder`` on an entry miss — the cross-process warm-restore
        tier (deserialize an engine-store artifact instead of
        certify+trace+compile). Returns None to decline, in which case
        the cold ``builder`` runs and counts as a miss; a revived
        engine counts in ``persistent_restores`` and
        ``serving_compile_cache_persistent_restores_total`` instead."""
        t0 = time.perf_counter()
        entry = self._entries.get(key)
        hit = entry is not None
        restored = False
        if not hit:
            engine = None
            if restorer is not None:
                engine = restorer()
                restored = engine is not None
            if restored:
                self.persistent_restores += 1
                if telemetry.enabled():
                    telemetry.counter(
                        "serving_compile_cache_persistent_restores_total",
                        "engines revived from the on-disk export store "
                        "(no certify/trace paid)").inc(bucket=label or "?")
            else:
                try:
                    engine = builder()
                except Exception as exc:
                    # a failed cold build (compile OOM, chaos) is a
                    # first-class incident event, not just a stack trace
                    telemetry.journal_event(
                        "cache.engine", outcome="build_failed",
                        bucket=label or "?", error=repr(exc)[:300])
                    raise
                self.misses += 1
            self._entries[key] = (engine, label)
            self._evict_over_bound()
        else:
            engine = entry[0]
            self._entries.move_to_end(key)       # LRU: a hit is a use
            self.hits += 1
        latency = time.perf_counter() - t0
        telemetry.journal_event(
            "cache.engine",
            outcome=("restored" if restored else "hit" if hit
                     else "miss"),
            bucket=label or "?", latency_s=round(latency, 6),
            collective_digest=getattr(engine,
                                      "collective_schedule_digest",
                                      None),
            memory_digest=getattr(engine, "memory_digest", None),
            dispatch_digest=getattr(engine, "dispatch_digest", None))
        if telemetry.enabled():
            if not restored:
                name = ("serving_compile_cache_hits_total" if hit
                        else "serving_compile_cache_misses_total")
                telemetry.counter(
                    name, "serving engine cache lookups that "
                    + ("reused a compiled engine" if hit
                       else "had to build (certify + trace + compile)")
                    ).inc(bucket=label or "?")
            telemetry.histogram(
                "serving_join_build_seconds",
                "engine acquisition latency at tenant join, by cache "
                "outcome").observe(
                latency, cached=("restored" if restored
                                 else "yes" if hit else "no"))
        return engine, hit, latency
