"""Lagrange-polynomial collocation matrices.

Same math as the reference's direct collocation setup
(``agentlib_mpc/optimization_backends/casadi_/basic.py:344-392``, which calls
``casadi.collocation_points``): for a degree-d scheme on the unit interval,
build the derivative matrix C, the end-point continuity vector D and the
quadrature weight vector B of the Lagrange basis through the collocation
points. Everything here is *static* numpy executed once at transcription
time; the resulting matrices are baked into the jitted NLP as constants.
"""

from __future__ import annotations

import functools

import numpy as np


@functools.lru_cache(maxsize=None)
def collocation_points(degree: int, method: str = "radau") -> tuple[float, ...]:
    """Collocation points on (0, 1], excluding the left endpoint 0.

    ``legendre``: Gauss-Legendre points (roots of the shifted Legendre
    polynomial P_d). ``radau``: right Radau points (roots of
    P_d + P_{d-1} shifted, endpoint 1 included) — the stiffly-accurate
    default, matching CasADi's convention.
    """
    if degree < 1:
        raise ValueError("collocation degree must be >= 1")
    if method == "legendre":
        # roots of Legendre P_d on [-1, 1] → shift to [0, 1]
        roots = np.polynomial.legendre.legroots(
            [0.0] * degree + [1.0])
        pts = (roots + 1.0) / 2.0
    elif method == "radau":
        # right Radau (Radau IIA): the d roots of P_d(x) − P_{d-1}(x) on
        # [-1, 1], which include the right endpoint x = +1
        # (check: d=2 → roots {−1/3, 1} → taus {1/3, 1})
        coeffs = np.zeros(degree + 1)
        coeffs[degree] = 1.0
        coeffs[degree - 1] = -1.0
        roots = np.polynomial.legendre.legroots(coeffs)
        pts = np.sort((roots + 1.0) / 2.0)
        assert np.isclose(pts[-1], 1.0), "right Radau must include tau=1"
    else:
        raise ValueError(f"unknown collocation method {method!r}")
    return tuple(float(p) for p in np.sort(pts))


@functools.lru_cache(maxsize=None)
def collocation_matrices(degree: int, method: str = "radau"):
    """(taus, C, D, B) for degree-d collocation.

    ``taus``: (d+1,) grid including 0.
    ``C[j, k]``: d/dτ of Lagrange basis ℓ_j at τ_k (j = 0..d, k = 1..d).
    ``D[j]``: ℓ_j(1) — continuity to the next interval boundary.
    ``B[j]``: ∫₀¹ ℓ_j dτ — quadrature weights for the cost integral.
    """
    taus = np.array([0.0] + list(collocation_points(degree, method)))
    d = degree
    C = np.zeros((d + 1, d + 1))
    D = np.zeros(d + 1)
    B = np.zeros(d + 1)
    for j in range(d + 1):
        # Lagrange basis ℓ_j through taus
        poly = np.poly1d([1.0])
        for r in range(d + 1):
            if r != j:
                poly *= np.poly1d([1.0, -taus[r]]) / (taus[j] - taus[r])
        D[j] = poly(1.0)
        dpoly = np.polyder(poly)
        for k in range(d + 1):
            C[j, k] = dpoly(taus[k])
        ipoly = np.polyint(poly)
        B[j] = ipoly(1.0)
    return taus, C, D, B
