"""MQTT communicator loopback test with a fake in-memory paho client.

The image has no paho-mqtt and no broker; a fake ``paho.mqtt.client``
module is injected so the full publish → topic-filter → wire-decode →
broker-delivery path of :class:`runtime.mqtt.MqttBus` runs in-process
(reference MQTT communicator role: SURVEY.md §2.9)."""

import sys
import types

import pytest

from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source


class _FakeBrokerHub:
    """Shared in-memory 'broker': routes publishes to subscribed clients."""

    def __init__(self):
        self.clients = []


class _FakeMessage:
    def __init__(self, topic, payload):
        self.topic = topic
        self.payload = payload


def _install_fake_paho(monkeypatch, hub):
    class FakeClient:
        def __init__(self, *args, **kwargs):
            self.on_message = None
            self._subs = []
            self.connected = False
            self.loop_running = False
            self.credentials = None
            hub.clients.append(self)

        def username_pw_set(self, username, password=None):
            self.credentials = (username, password)

        def connect(self, host, port):
            self.connected = (host, port)

        def subscribe(self, pattern):
            self._subs.append(pattern)

        def loop_start(self):
            self.loop_running = True

        def loop_stop(self):
            self.loop_running = False

        def disconnect(self):
            self.connected = False

        def publish(self, topic, payload):
            # like a real broker: a '#' subscriber receives its OWN
            # publishes back too — that echo is what MqttBus's own-topic
            # guard must filter
            for client in hub.clients:
                if not client.loop_running:
                    continue
                for pattern in client._subs:
                    prefix = pattern[:-1] if pattern.endswith("#") \
                        else pattern
                    if topic.startswith(prefix) and client.on_message:
                        client.on_message(client, None,
                                          _FakeMessage(topic, payload))
                        break

    class CallbackAPIVersion:
        VERSION1 = 1

    mqtt_mod = types.ModuleType("paho.mqtt.client")
    mqtt_mod.Client = FakeClient
    mqtt_mod.CallbackAPIVersion = CallbackAPIVersion
    paho_mod = types.ModuleType("paho")
    paho_mqtt_mod = types.ModuleType("paho.mqtt")
    paho_mod.mqtt = paho_mqtt_mod
    paho_mqtt_mod.client = mqtt_mod
    monkeypatch.setitem(sys.modules, "paho", paho_mod)
    monkeypatch.setitem(sys.modules, "paho.mqtt", paho_mqtt_mod)
    monkeypatch.setitem(sys.modules, "paho.mqtt.client", mqtt_mod)
    return FakeClient


class _RecordingBroker:
    def __init__(self):
        self.received = []
        self.bus = None

    def attach_bus(self, bus):
        self.bus = bus

    def send_variable(self, var, from_external=False):
        self.received.append((var, from_external))


def test_mqtt_loopback_two_agents(monkeypatch):
    hub = _FakeBrokerHub()
    _install_fake_paho(monkeypatch, hub)
    from agentlib_mpc_tpu.runtime.mqtt import MqttBus

    bus_a = MqttBus("AgentA")
    bus_b = MqttBus("AgentB")
    broker_a, broker_b = _RecordingBroker(), _RecordingBroker()
    bus_a.attach(broker_a)
    bus_b.attach(broker_b)

    var = AgentVariable(name="T", alias="T_room", value=[1.0, 2.0],
                        source=Source(agent_id="AgentA", module_id="mpc"))
    bus_a.broadcast("AgentA", var)

    # B received the decoded variable, delivered as external
    assert len(broker_b.received) == 1
    got, from_external = broker_b.received[0]
    assert from_external is True
    assert got.alias == "T_room"
    assert list(got.value) == [1.0, 2.0]
    assert got.source.agent_id == "AgentA"
    # A's own echo is filtered by topic
    assert broker_a.received == []

    bus_a.close()
    bus_b.close()
    assert bus_a._client.loop_running is False


def test_mqtt_malformed_payload_dropped(monkeypatch, caplog):
    import logging

    hub = _FakeBrokerHub()
    _install_fake_paho(monkeypatch, hub)
    from agentlib_mpc_tpu.runtime.mqtt import MqttBus

    bus_a = MqttBus("AgentA")
    bus_b = MqttBus("AgentB")
    broker_b = _RecordingBroker()
    bus_b.attach(broker_b)
    with caplog.at_level(logging.WARNING):
        bus_a._client.publish("/agentlib_mpc_tpu/AgentA", b"{not json!")
    assert broker_b.received == []
    assert any("malformed" in r.message for r in caplog.records)
    bus_a.close()
    bus_b.close()


def test_mqtt_prefers_paho_when_installed(monkeypatch):
    """With paho importable the bus uses it (external-broker interop,
    auth, TLS); the paho-less fallback onto the first-party client is
    covered end-to-end in test_mqtt_native.py."""
    hub = _FakeBrokerHub()
    fake_client_cls = _install_fake_paho(monkeypatch, hub)
    from agentlib_mpc_tpu.runtime.mqtt import MqttBus

    bus = MqttBus("AgentA")
    assert bus.client_impl == "paho"
    assert isinstance(bus._client, fake_client_cls)
    bus.close()
