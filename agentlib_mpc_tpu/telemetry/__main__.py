"""``python -m agentlib_mpc_tpu.telemetry`` — the flight-recorder CLI.

Modes:

* ``--incident JOURNAL [--around SEQ | --around round:N] [--window N]``
  — reconstruct a causal incident report from a journal: markdown to
  stdout, optionally a JSON bundle (``--json PATH``) with the windowed
  events, injection→symptom→recovery chains and implicated correlation
  keys. ``--metrics METRICS_JSONL`` embeds a metrics export next to the
  timeline. Exit 1 when the journal holds no events (an empty incident
  report is itself an incident).
* ``--slo JOURNAL`` — recompute the per-tenant SLO report offline from
  the journal's ``serve.round`` events (JSON to stdout): the auditor's
  path to the same numbers ``ServingPlane.slo_report()`` serves live.

No jax import in either mode — the CLI must run on a machine that has
only the tape, not the fleet.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m agentlib_mpc_tpu.telemetry",
        description="flight-recorder incident / SLO tooling")
    parser.add_argument("--incident", metavar="JOURNAL",
                        help="build an incident report from a journal")
    parser.add_argument("--slo", metavar="JOURNAL",
                        help="recompute the SLO report offline from a "
                             "journal's serve.round events")
    parser.add_argument("--around", default=None,
                        help="window anchor: a sequence number, or "
                             "round:N (default: first fault event)")
    parser.add_argument("--window", type=int, default=500,
                        help="window half-width in sequence numbers "
                             "(or rounds with --around round:N)")
    parser.add_argument("--json", dest="json_out", default=None,
                        help="also write the JSON incident bundle here")
    parser.add_argument("--metrics", default=None,
                        help="metrics JSONL export to embed in the "
                             "bundle (bench.py --emit-metrics format)")
    args = parser.parse_args(argv)

    if args.slo:
        from agentlib_mpc_tpu.telemetry.journal import read_events
        from agentlib_mpc_tpu.telemetry.slo import slo_from_events

        events = read_events(args.slo)
        report = slo_from_events(events)
        print(json.dumps(report, indent=1))
        if not events:
            print(f"no events in journal {args.slo}", file=sys.stderr)
            return 1
        return 0

    if not args.incident:
        parser.print_help()
        return 2

    from agentlib_mpc_tpu.telemetry.incident import (
        build_incident,
        render_markdown,
        write_bundle,
    )

    metrics = None
    if args.metrics:
        # two formats in the wild: the registry's JSONL export (one
        # family per line) and the indented single-document JSON the
        # bench's --emit-metrics artifact is — accept both
        with open(args.metrics, "r", encoding="utf-8") as fh:
            text = fh.read()
        try:
            metrics = json.loads(text)
        except ValueError:
            metrics = [json.loads(line)
                       for line in text.splitlines() if line.strip()]
    report = build_incident(args.incident, around=args.around,
                            window=args.window, metrics=metrics)
    sys.stdout.write(render_markdown(report))
    if args.json_out:
        write_bundle(report, args.json_out)
    if report["events_total"] == 0:
        print(f"no events in journal {args.incident} — nothing to "
              f"reconstruct", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
