#!/usr/bin/env bash
# Degraded (no-docker) variant of docker-compose.fleet.yml: the SAME
# coordinator + room + cooler fleet as three local processes joined over
# the first-party MQTT broker on real TCP sockets. CI-runnable; the
# containerized run only swaps process boundaries for container
# boundaries (same entry points, same configs, same wire traffic).
#
#   deploy/run_fleet_local.sh [run_seconds] [results_dir]
set -euo pipefail
HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(dirname "$HERE")"
RUN_UNTIL="${1:-40}"
RESULTS_DIR="${2:-$HERE/fleet_results}"
PORT="${MQTT_PORT:-18830}"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS=cpu

mkdir -p "$RESULTS_DIR"
python -m agentlib_mpc_tpu.runtime.mqtt_native "$PORT" &
BROKER_PID=$!
trap 'kill $BROKER_PID 2>/dev/null || true' EXIT
sleep 0.5

run_agent() {
  AGENT_CONFIG="$1" MQTT_HOST=127.0.0.1 MQTT_PORT="$PORT" REALTIME=1 \
    RUN_UNTIL="$RUN_UNTIL" RESULTS_DIR="$RESULTS_DIR" \
    python -m agentlib_mpc_tpu.runtime.container &
}

run_agent "$HERE/fleet/coordinator.json"; CO_PID=$!
run_agent "$HERE/fleet/room.json";        RO_PID=$!
run_agent "$HERE/fleet/cooler.json";      CL_PID=$!

wait $CO_PID $RO_PID $CL_PID
echo "fleet run complete; results:"
ls -l "$RESULTS_DIR"
