"""Real-time threaded ADMM: two agents exchange couplings in wall-clock
mode (the reference's threaded two-agent test, ``tests/test_admm.py:26-80``:
rt env, local broadcast, asserts registration + mean computation)."""

import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu.models.zoo import CooledRoom, Cooler
from agentlib_mpc_tpu.modules.admm import ParticipantStatus
from agentlib_mpc_tpu.runtime.mas import LocalMAS
import agentlib_mpc_tpu.modules  # noqa: F401


def _agent(aid, model_cls, couplings, controls, extra):
    return {
        "id": aid,
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {
                "module_id": "admm",
                "type": "admm",
                "optimization_backend": {
                    "type": "jax_admm",
                    "model": {"class": model_cls},
                    "discretization_options": {"collocation_order": 2},
                    "solver": {"max_iter": 25},
                    "precompile": True,
                },
                "time_step": 8.0,
                "prediction_horizon": 4,
                "max_iterations": 3,
                "iteration_timeout": 5.0,
                "registration_period": 0.3,
                "penalty_factor": 10.0,
                "couplings": couplings,
                "controls": controls,
                **extra,
            },
        ],
    }


ROOM = _agent(
    "Room", CooledRoom,
    couplings=[{"name": "mDot", "alias": "air", "value": 0.02,
                "ub": 0.05, "lb": 0.0}],
    controls=[],
    extra={
        "inputs": [
            {"name": "load", "value": 150},
            {"name": "T_in", "value": 290.15},
            {"name": "T_upper", "value": 295.15},
        ],
        "states": [{"name": "T", "value": 298.16}],
    },
)

COOLER = _agent(
    "Cooler", Cooler,
    couplings=[{"name": "mDot_out", "alias": "air", "value": 0.02}],
    controls=[{"name": "mDot", "value": 0.02, "ub": 0.05, "lb": 0.0}],
    extra={"parameters": [{"name": "r_mDot", "value": 1.0}]},
)


@pytest.mark.slow
def test_realtime_admm_round():
    mas = LocalMAS([ROOM, COOLER], env={"rt": True, "factor": 1.0})
    mas.run(until=10.0)
    # let the daemon threads finish the round the last trigger started
    time.sleep(1.0)

    room = mas.agents["Room"].get_module("admm")
    cooler = mas.agents["Cooler"].get_module("admm")

    # both saw each other on the shared wire alias
    assert any(p for p in room._registered_participants["admm_coupling_air"])
    assert any(p for p in cooler._registered_participants["admm_coupling_air"])

    # at least one full iteration with mean computation ran on each side
    assert room._iter_rows, "room completed no ADMM iteration"
    assert cooler._iter_rows, "cooler completed no ADMM iteration"
    mean_room = room._admm_values["admm_coupling_mean_mDot"]
    assert np.all(np.isfinite(mean_room))
    assert mean_room.shape == (4,)
