"""Scenario-tree subsystem (ISSUE 12): tree metadata, the coupled tree
KKT solve, the scenario-batched ops paths, generation determinism.

The load-bearing contract is the DEGENERATE case: a single-scenario
tree must route through the flat single-scenario machinery bit for bit
(factor, resolve, and full solve_nlp), so the tree axis can never
silently diverge from the proven flat paths.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
from agentlib_mpc_tpu.ops.solver import SolverOptions, solve_nlp
from agentlib_mpc_tpu.ops.stagewise import (
    build_stage_partition,
    factor_kkt_scenarios,
    factor_kkt_stage,
    resolve_kkt_scenarios,
    resolve_kkt_stage,
)
from agentlib_mpc_tpu.resilience.chaos import disturbance_model
from agentlib_mpc_tpu.scenario import (
    branching_tree,
    build_tree_partition,
    certify_tree_structure,
    fan_tree,
    single_scenario,
    solve_kkt_tree,
    solve_nlp_scenarios,
    synthetic_tree_kkt,
    tree_method_available,
    tree_partition_for_ocp,
)
from agentlib_mpc_tpu.scenario.generate import (
    ensemble_thetas,
    scenario_thetas,
)
from agentlib_mpc_tpu.scenario.tree import _apply_A, _coupling_layout


@pytest.fixture(scope="module")
def partition():
    return build_stage_partition(N=4, n_x=2, n_u=1, n_z=0, d=0,
                                 method="multiple_shooting")


@pytest.fixture(scope="module")
def ocp():
    return tracker_ocp()


class TestScenarioTree:
    def test_fan_tree_groups(self):
        t = fan_tree(4, robust_horizon=2)
        assert t.n_scenarios == 4 and t.robust_horizon == 2
        assert t.groups_at(0) == ((0, 1, 2, 3),)
        assert t.groups_at(1) == ((0, 1, 2, 3),)
        assert sum(t.probabilities) == pytest.approx(1.0)

    def test_branching_tree_nodes(self):
        t = branching_tree((2, 2))
        assert t.n_scenarios == 4 and t.robust_horizon == 2
        # u_0 shared by all; u_1 shared within each first-branch pair
        assert t.groups_at(0) == ((0, 1, 2, 3),)
        assert t.groups_at(1) == ((0, 1), (2, 3))

    def test_single_scenario_degenerate(self):
        t = single_scenario()
        assert t.n_scenarios == 1 and t.robust_horizon == 0

    def test_validate_rejects_bad_probabilities(self):
        with pytest.raises(ValueError, match="sum to 1"):
            fan_tree(2, probabilities=(0.9, 0.9))

    def test_validate_rejects_deep_robust_horizon(self, ocp):
        with pytest.raises(ValueError, match="robust horizon"):
            tree_partition_for_ocp(ocp, fan_tree(2, robust_horizon=99))


class TestTreePartition:
    def test_from_ocp(self, ocp):
        tp = tree_partition_for_ocp(ocp, fan_tree(3, robust_horizon=2))
        assert tp.n_scenarios == 3
        # (3-1) scenarios pinned per stage x 1 control x 2 stages
        assert tp.n_coupling_rows == 4
        assert tp.na_indices == ((0,), (1,))

    def test_rejects_non_primal_indices(self, partition):
        with pytest.raises(ValueError, match="non-primal"):
            build_tree_partition(partition, fan_tree(2),
                                 ((partition.n_w + 1,),))

    def test_hashable_static_metadata(self, ocp):
        tp = tree_partition_for_ocp(ocp, fan_tree(2))
        assert hash(tp) == hash(
            tree_partition_for_ocp(ocp, fan_tree(2)))


class TestTreeKKT:
    def test_degenerate_routes_flat_bitwise(self, partition):
        """factor + resolve of a 1-scenario tree == the flat stage
        sweep, bit for bit (not a 1-lane vmap)."""
        tp = build_tree_partition(partition, single_scenario(), ())
        K, rhs = synthetic_tree_kkt(tp, seed=3)
        x_tree = solve_kkt_tree(jnp.asarray(K), jnp.asarray(rhs), tp)
        f = factor_kkt_stage(jnp.asarray(K[0]), partition)
        x_flat = resolve_kkt_stage(f, jnp.asarray(rhs[0]), partition)
        assert bool(jnp.all(x_tree[0] == x_flat))

    def test_coupled_solve_matches_dense_reference(self, partition):
        """The scenario-sweep + non-anticipativity-Schur factorization
        equals a dense solve of the full coupled system."""
        tree = fan_tree(3, robust_horizon=2)
        tp = build_tree_partition(partition, tree, ((0,), (1,)))
        K, rhs = synthetic_tree_kkt(tp, seed=5)
        delta = 1e-10
        x = np.asarray(solve_kkt_tree(jnp.asarray(K), jnp.asarray(rhs),
                                      tp, delta_c=delta))
        S, M = rhs.shape
        idx, s_pos, s_ref = _coupling_layout(tp)
        m = idx.shape[0]
        A = np.zeros((m, S * M))
        for r in range(m):
            A[r, s_pos[r] * M + idx[r]] = 1.0
            A[r, s_ref[r] * M + idx[r]] = -1.0
        big = np.zeros((S * M + m, S * M + m))
        for s in range(S):
            big[s * M:(s + 1) * M, s * M:(s + 1) * M] = K[s]
        big[S * M:, :S * M] = A
        big[:S * M, S * M:] = A.T
        big[S * M:, S * M:] = -delta * np.eye(m)
        ref = np.linalg.solve(big, np.concatenate(
            [rhs.reshape(-1), np.zeros(m)]))
        np.testing.assert_allclose(x.reshape(-1), ref[:S * M],
                                   rtol=1e-5, atol=1e-6)
        # non-anticipativity holds on the solution itself
        assert float(np.max(np.abs(np.asarray(
            _apply_A(jnp.asarray(x), (idx, s_pos, s_ref)))))) < 1e-6

    def test_probe_available(self, partition):
        tp = build_tree_partition(partition, fan_tree(2), ((0,),))
        assert tree_method_available(tp)


class TestScenarioBatchedSweep:
    def test_single_scenario_bitwise(self, partition):
        tp = build_tree_partition(partition, single_scenario(), ())
        K, rhs = synthetic_tree_kkt(tp, seed=11)
        f_b = factor_kkt_scenarios(jnp.asarray(K), partition)
        assert f_b[0] == "flat"
        x_b = resolve_kkt_scenarios(f_b, jnp.asarray(rhs), partition)
        f = factor_kkt_stage(jnp.asarray(K[0]), partition)
        x = resolve_kkt_stage(f, jnp.asarray(rhs[0]), partition)
        assert bool(jnp.all(x_b[0] == x))

    def test_batch_matches_per_scenario_flat(self, partition):
        tp = build_tree_partition(partition, fan_tree(3), ((0,),))
        K, rhs = synthetic_tree_kkt(tp, seed=13)
        f_b = factor_kkt_scenarios(jnp.asarray(K), partition)
        x_b = resolve_kkt_scenarios(f_b, jnp.asarray(rhs), partition)
        for s in range(3):
            f = factor_kkt_stage(jnp.asarray(K[s]), partition)
            x = resolve_kkt_stage(f, jnp.asarray(rhs[s]), partition)
            np.testing.assert_allclose(np.asarray(x_b[s]), np.asarray(x),
                                       rtol=1e-9, atol=1e-9)


class TestTreeStructureCertificate:
    def test_proved_for_transcribed_ocp(self, ocp):
        tp = tree_partition_for_ocp(ocp, fan_tree(3, robust_horizon=1))
        theta = ocp.default_params()
        cert = certify_tree_structure(ocp.nlp, theta, ocp.n_w, tp)
        assert cert.ok
        assert cert.n_scenarios == 3
        assert cert.n_coupling_rows == 2
        assert "scenario branch" in cert.describe()

    def test_tree_plan_shares_flat_seeds(self, ocp):
        from agentlib_mpc_tpu.ops.stagejac import (
            plan_from_certificate,
            tree_plan_from_certificate,
        )

        tp = tree_partition_for_ocp(ocp, fan_tree(2, robust_horizon=1))
        theta = ocp.default_params()
        plan_tree = tree_plan_from_certificate(ocp.nlp, theta, ocp.n_w,
                                               tp)
        plan_flat = plan_from_certificate(ocp.nlp, theta, ocp.n_w,
                                          tp.base)
        assert plan_tree is not None
        # one proof, one seed set: the memoized flat plan IS the tree's
        assert plan_tree is plan_flat


class TestSolveNlpScenarios:
    def _problem(self, ocp, n_scenarios):
        thetas = [ocp.default_params(p=jnp.array([float(s + 1)]))
                  for s in range(n_scenarios)]
        theta_b = jax.tree.map(lambda *xs: jnp.stack(xs), *thetas)
        w0 = jnp.stack([ocp.initial_guess(t) for t in thetas])
        lbub = [ocp.bounds(t) for t in thetas]
        lb = jnp.stack([b[0] for b in lbub])
        ub = jnp.stack([b[1] for b in lbub])
        return theta_b, w0, lb, ub

    def test_degenerate_bitwise_flat_solve(self, ocp):
        theta_b, w0, lb, ub = self._problem(ocp, 1)
        opts = SolverOptions(max_iter=25)
        res_b = solve_nlp_scenarios(ocp.nlp, w0, theta_b, lb, ub, opts,
                                    tree=single_scenario())
        res = solve_nlp(ocp.nlp, w0[0],
                        jax.tree.map(lambda l: l[0], theta_b),
                        lb[0], ub[0], opts)
        assert bool(jnp.all(res_b.w[0] == res.w))
        assert bool(jnp.all(res_b.y[0] == res.y))
        assert bool(jnp.all(res_b.z[0] == res.z))

    def test_batched_matches_serial_solves(self, ocp):
        """Acceptance: the S-scenario batched solve matches S
        independent serial solves to solver tolerance."""
        S = 3
        theta_b, w0, lb, ub = self._problem(ocp, S)
        opts = SolverOptions(max_iter=25)
        res_b = solve_nlp_scenarios(ocp.nlp, w0, theta_b, lb, ub, opts,
                                    tree=fan_tree(S, robust_horizon=0))
        for s in range(S):
            res = solve_nlp(ocp.nlp, w0[s],
                            jax.tree.map(lambda l, s=s: l[s], theta_b),
                            lb[s], ub[s], opts)
            np.testing.assert_allclose(np.asarray(res_b.w[s]),
                                       np.asarray(res.w),
                                       rtol=1e-6, atol=1e-6)

    def test_tree_size_mismatch_rejected(self, ocp):
        theta_b, w0, lb, ub = self._problem(ocp, 2)
        with pytest.raises(ValueError, match="scenarios"):
            solve_nlp_scenarios(ocp.nlp, w0, theta_b, lb, ub,
                                SolverOptions(), tree=fan_tree(3))


class TestGenerationDeterminism:
    def test_disturbance_model_deterministic(self):
        a = disturbance_model(7, 10, 4, scale=0.5)
        b = disturbance_model(7, 10, 4, scale=0.5)
        np.testing.assert_array_equal(a, b)
        c = disturbance_model(8, 10, 4, scale=0.5)
        assert np.any(a != c)
        assert a.shape == (4, 10, 1)
        np.testing.assert_array_equal(a[0], 0.0)  # nominal row

    def test_walk_kind_accumulates(self):
        g = disturbance_model(1, 50, 2, scale=1.0, kind="gaussian",
                              nominal_first=False)
        w = disturbance_model(1, 50, 2, scale=1.0, kind="walk",
                              nominal_first=False)
        np.testing.assert_allclose(np.cumsum(g, axis=1), w)

    def test_scenario_thetas_perturbs_channels(self, ocp):
        theta = ocp.default_params()
        tree = fan_tree(3)
        batched = ensemble_thetas(theta, tree, seed=3, scale=1.0)
        # tracker has no exogenous channels: pure broadcast stack
        assert batched.p.shape == (3,) + tuple(theta.p.shape)
        np.testing.assert_array_equal(np.asarray(batched.d_traj),
                                      np.broadcast_to(
                                          np.asarray(theta.d_traj),
                                          batched.d_traj.shape))

    def test_scenario_thetas_rejects_bad_channel(self, ocp):
        theta = ocp.default_params()
        draws = np.zeros((2, ocp.N, 1))
        with pytest.raises(ValueError, match="outside d_traj"):
            scenario_thetas(theta, fan_tree(2), draws, channels=(5,))

    def test_predictor_ensemble_deterministic(self):
        from agentlib_mpc_tpu.modules.input_prediction import (
            InputPredictor,
        )

        class _Host:
            """Minimal agent stand-in (the test_aux_modules pattern)."""

            id = "weather"

            class _Env:
                now = 0.0

            class _Broker:
                def register_callback(self, *a, **k):
                    pass

                def send_variable(self, v):
                    pass

            env = _Env()
            data_broker = _Broker()

        table = {"T_amb": {float(t): 280.0 + t / 100.0
                           for t in range(0, 7200, 600)}}
        mod = InputPredictor({"module_id": "weather", "data": table,
                              "t_sample": 600,
                              "prediction_horizon": 1800,
                              "prediction_sample": 600}, _Host())
        a = mod.get_prediction_ensemble_at_time(1200.0, 4, seed=5)
        b = mod.get_prediction_ensemble_at_time(1200.0, 4, seed=5)
        assert a.keys() == b.keys() == {"T_amb"}
        times_a, vals_a = a["T_amb"]
        times_b, vals_b = b["T_amb"]
        assert times_a == times_b
        np.testing.assert_array_equal(vals_a, vals_b)
        vals_a = np.asarray(vals_a)
        assert vals_a.shape == (4, 4)
        # row 0 is the nominal forecast
        nominal = np.asarray(mod.get_prediction_at_time(1200.0)
                             ["T_amb"][1])
        np.testing.assert_allclose(vals_a[0], nominal)
        # perturbed rows actually differ
        assert np.any(vals_a[1:] != vals_a[0])

    def test_try_forecast_ensemble_deterministic(self):
        pd = pytest.importorskip("pandas")
        from agentlib_mpc_tpu.utils.try_format import (
            try_forecast_ensemble,
        )

        idx = np.arange(24) * 3600.0
        df = pd.DataFrame({"T_oda": 273.15 + 10 * np.sin(idx / 7e3)},
                          index=idx)
        a = try_forecast_ensemble(df, "T_oda", 3600.0, 6, 3, seed=2)
        b = try_forecast_ensemble(df, "T_oda", 3600.0, 6, 3, seed=2)
        np.testing.assert_array_equal(a, b)
        assert a.shape == (3, 6)
        np.testing.assert_allclose(
            a[0], np.interp(3600.0 + np.arange(6) * 3600.0, idx,
                            df["T_oda"].to_numpy()))
        with pytest.raises(KeyError):
            try_forecast_ensemble(df, "nope", 0.0, 4, 2)
