"""Reference-shaped agent configs → the fused SPMD data plane.

The module world (`modules/admm.py`, reference
``modules/dmpc/admm/admm.py``) runs one agent per config over the
message broker — right for field deployment, wasteful for cluster
simulation of a large fleet. This bridge takes the SAME agent configs an
``admm_local`` MAS consumes and compiles the whole fleet into one
:class:`~agentlib_mpc_tpu.parallel.fused_admm.FusedADMM` program: every
agent's local solve, the consensus updates and the convergence test in a
single jitted step over a device mesh (docs/DISTRIBUTED.md, "data
plane").

Scope: input couplings (the coupling variable is a control input of the
agent's model — the reference 4-room topologies). Output-expression
couplings (e.g. a coupling alias bound to a model *output*) need the
expression machinery of ``backends/admm_backend.py`` and stay on the
module path; the bridge raises a pointed error for them rather than
silently mis-modelling.

Typical use::

    from agentlib_mpc_tpu.parallel.config_bridge import FusedFleet

    fleet = FusedFleet.from_configs(configs)       # admm_local configs
    out = fleet.step()                             # one coordinated round
    u0 = out["Room_3"]["u"]["mDot"][0]             # first control move
    fleet.update_agent("Room_3", x0=[296.2])       # plant feedback
    out = fleet.step()                             # warm-started next round
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu.backends.backend import load_model_for_backend
from agentlib_mpc_tpu.backends.mpc_backend import (
    solver_options_from_config,
    transcription_kwargs_from_config,
)
from agentlib_mpc_tpu.models.ml_model import MLModel
from agentlib_mpc_tpu.models.model import Model
from agentlib_mpc_tpu.ops.ml_transcription import transcribe_ml
from agentlib_mpc_tpu.ops.transcription import TranscribedOCP, transcribe
from agentlib_mpc_tpu.parallel.fused_admm import (
    FusedADMM,
    FusedADMMOptions,
    bucket_agents,
    stack_params,
)

#: module types whose config block the bridge understands
_ADMM_TYPES = ("admm_local", "admm", "admm_coordinated")


@dataclasses.dataclass
class _FleetAgent:
    agent_id: str
    model: Model
    ocp: TranscribedOCP
    couplings: dict[str, str]          # alias -> control input name
    exchanges: dict[str, str]
    solver_options: Any
    x0: np.ndarray                     # (n_diff,)
    p: np.ndarray                      # (n_params,)
    exo: dict[str, float]              # constant disturbance values
    u_bounds: dict[str, tuple[float | None, float | None]]

    def theta(self, N: int):
        ocp = self.ocp
        d = None
        if ocp.exo_names:
            d = jnp.broadcast_to(
                jnp.array([self.exo[n] for n in ocp.exo_names]),
                (N, len(ocp.exo_names)))
        kw: dict[str, Any] = {"x0": jnp.asarray(self.x0),
                              "p": jnp.asarray(self.p)}
        if d is not None:
            kw["d_traj"] = d
        if isinstance(self.model, MLModel):
            # learned weights ride theta (the hot-swap design,
            # ops/ml_transcription.py): each agent's OWN surrogate
            # parameters, even though structure-identical agents share
            # one transcription
            kw["ml_params"] = self.model.ml_params
        theta = ocp.default_params(**kw)
        # config-level lb/ub on couplings/controls override the model's
        if self.u_bounds:
            u_lb = np.asarray(theta.u_lb).copy()
            u_ub = np.asarray(theta.u_ub).copy()
            for name, (lb, ub) in self.u_bounds.items():
                j = ocp.control_names.index(name)
                if lb is not None:
                    u_lb[:, j] = lb
                if ub is not None:
                    u_ub[:, j] = ub
            theta = theta._replace(u_lb=jnp.asarray(u_lb),
                                   u_ub=jnp.asarray(u_ub))
        return theta


def _find_admm_module(agent_cfg: Mapping) -> Mapping | None:
    for m in agent_cfg.get("modules", []):
        if m.get("type") in _ADMM_TYPES:
            return m
    return None


def _values(entries) -> dict[str, float]:
    return {e["name"]: e["value"] for e in (entries or []) if "value" in e}


class FusedFleet:
    """A fleet of config-defined ADMM agents as one fused engine.

    Build with :meth:`from_configs`; drive with :meth:`step` /
    :meth:`update_agent`. State (consensus means, multipliers, warm
    starts) persists across steps and is shift-warm-started by
    :meth:`advance` between control intervals.
    """

    def __init__(self, agents: Sequence[_FleetAgent], N: int,
                 options: FusedADMMOptions, dt: float = 300.0,
                 record: bool = True):
        self._agents = list(agents)
        self.N = N
        self.dt = float(dt)
        self.time = 0.0
        #: record per-step trajectories/residuals for :meth:`results` /
        #: :meth:`iteration_stats`; disable (or call
        #: :meth:`cleanup_results` periodically) for very long runs
        self.record = record
        self._history: dict[str, list[dict]] = {
            a.agent_id: [] for a in self._agents}
        self._stats_rows: list[dict] = []
        self._admm_rows: dict[str, list[dict]] = {}
        specs = [
            {"ocp": a.ocp, "theta": a.theta(N), "couplings": a.couplings,
             "exchanges": a.exchanges, "name": a.agent_id,
             "solver_options": a.solver_options}
            for a in self._agents
        ]
        groups, theta_batches, index_map = bucket_agents(specs)
        self.engine = FusedADMM(groups, options, record_locals=record)
        self._theta_batches = list(theta_batches)
        self._index_map = index_map
        # agent_id -> (group index, position in the group batch)
        self._where: dict[str, tuple[int, int]] = {}
        for gi, members in enumerate(index_map):
            for slot, spec_idx in enumerate(members):
                self._where[self._agents[spec_idx].agent_id] = (gi, slot)
        self.state = self.engine.init_state(self._theta_batches)

    # -- construction ---------------------------------------------------------

    @classmethod
    def from_configs(cls, configs: Sequence[Mapping],
                     options: FusedADMMOptions | None = None,
                     ) -> "FusedFleet":
        """Parse ``admm_local``-style agent configs into a fused fleet.

        Agents whose configs share model class, horizon, discretization
        and solver options batch into one vmapped group automatically
        (one transcription per structure). Configs without an ADMM module
        (e.g. simulator agents) are skipped — the bridge is the optimizer
        fleet; plants stay outside, feeding back via
        :meth:`update_agent`.
        """
        agents: list[_FleetAgent] = []
        ocp_cache: dict[tuple, TranscribedOCP] = {}
        N_ref: int | None = None
        dt_ref: float | None = None
        rho = None
        max_iterations = None
        for cfg in configs:
            m = _find_admm_module(cfg)
            if m is None:
                continue
            backend = m.get("optimization_backend") or {}
            N = int(m.get("prediction_horizon", 10))
            dt = float(m.get("time_step", 300.0))
            # ML-aware loading: configs with ml_model_sources come back as
            # MLModel and transcribe through the NARX path below
            model = load_model_for_backend(backend.get("model", {}), dt=dt)
            if N_ref is None:
                N_ref = N
            elif N != N_ref:
                raise ValueError(
                    f"fused fleet needs one shared horizon: agent "
                    f"{cfg.get('id')} has N={N}, fleet has N={N_ref}")
            if dt_ref is None:
                dt_ref = dt
            elif dt != dt_ref:
                raise ValueError(
                    f"fused fleet needs one shared time_step: agent "
                    f"{cfg.get('id')} has dt={dt}, fleet has {dt_ref}")
            for attr, current in (("penalty_factor", rho),
                                  ("max_iterations", max_iterations)):
                val = m.get(attr)
                if val is not None and current is not None and \
                        val != current:
                    raise ValueError(
                        f"fused fleet needs one shared {attr}: agent "
                        f"{cfg.get('id')} has {val}, fleet has {current}")
            rho = m.get("penalty_factor", rho)
            max_iterations = m.get("max_iterations", max_iterations)

            couplings, exchanges, u_bounds = {}, {}, {}
            control_names = [e["name"] for e in m.get("controls", [])]
            def _merge_bounds(e):
                old = u_bounds.get(e["name"], (None, None))
                u_bounds[e["name"]] = (e.get("lb", old[0]),
                                       e.get("ub", old[1]))

            for e in m.get("controls", []):
                if "lb" in e or "ub" in e:
                    _merge_bounds(e)
            model_controls = {v.name for v in model.inputs}
            for kind, target in (("couplings", couplings),
                                 ("exchange", exchanges)):
                for e in m.get(kind, []):
                    name, alias = e["name"], e.get("alias", e["name"])
                    if name not in model_controls:
                        raise NotImplementedError(
                            f"agent {cfg.get('id')}: coupling '{name}' is "
                            f"not a control input of "
                            f"{type(model).__name__} — output-expression "
                            f"couplings run on the module path "
                            f"(modules/admm.py), not the fused bridge")
                    target[alias] = name
                    if name not in control_names:
                        control_names.append(name)
                    if "lb" in e or "ub" in e:
                        _merge_bounds(e)

            is_ml = isinstance(model, MLModel)
            if is_ml:
                # NARX shooting over the learned step (discretization
                # options do not apply — the surrogate IS the integrator).
                # The cache key carries the surrogate's lag STRUCTURE:
                # same-structure agents share one transcription (their
                # weights ride theta.ml_params); different lag layouts
                # need their own transcribed program.
                key = (type(model), tuple(control_names), N, dt, "ml",
                       tuple(sorted(model.ml_lags.items())))
                if key not in ocp_cache:
                    ocp_cache[key] = transcribe_ml(model, control_names,
                                                   N=N, dt=dt)
            else:
                trans_kwargs = transcription_kwargs_from_config(
                    backend.get("discretization_options"))
                key = (type(model), tuple(control_names), N, dt,
                       tuple(sorted(trans_kwargs.items())))
                if key not in ocp_cache:
                    ocp_cache[key] = transcribe(model, control_names, N=N,
                                                dt=dt, **trans_kwargs)
            ocp = ocp_cache[key]

            state_vals = _values(m.get("states"))
            # ML OCPs order their state vector by dyn_names (NARX +
            # white-box states); physical OCPs by diff_state_names
            state_names = list(getattr(ocp, "dyn_names", None)
                               or model.diff_state_names)
            x0 = np.array([
                state_vals.get(n, model.get_var(n).value)
                for n in state_names], dtype=float)
            param_vals = _values(m.get("parameters"))
            p = np.array([
                param_vals.get(v.name, v.value) for v in model.parameters],
                dtype=float)
            input_vals = _values(m.get("inputs"))
            exo = {}
            for n in ocp.exo_names:
                val = input_vals.get(n, model.get_var(n).value)
                if val is None:
                    raise ValueError(
                        f"agent {cfg.get('id', f'agent{len(agents)}')!r}: "
                        f"exogenous input {n!r} has no value in the config "
                        f"and no default in the model — add it to the "
                        f"module's 'inputs' list or give the model "
                        f"variable a default value")
                exo[n] = float(val)

            agents.append(_FleetAgent(
                agent_id=str(cfg.get("id", f"agent{len(agents)}")),
                model=model, ocp=ocp, couplings=couplings,
                exchanges=exchanges,
                solver_options=solver_options_from_config(
                    backend.get("solver")),
                x0=x0, p=p, exo=exo, u_bounds=u_bounds))

        if not agents:
            raise ValueError("no ADMM modules found in the given configs")
        if options is None:
            options = FusedADMMOptions(
                max_iterations=int(max_iterations or 10),
                rho=float(rho if rho is not None else 10.0))
        return cls(agents, N_ref, options, dt=dt_ref)

    # -- runtime --------------------------------------------------------------

    def update_agent(self, agent_id: str, x0=None, inputs=None,
                     parameters=None) -> None:
        """Feed plant state / disturbance / parameter updates back into an
        agent before the next :meth:`step` (the module path receives these
        over the broker; the bridge takes them directly)."""
        a = self._agents_by_id()[agent_id]
        if x0 is not None:
            a.x0 = np.asarray(x0, dtype=float)
        for name, val in (inputs or {}).items():
            if name not in a.exo:
                raise KeyError(
                    f"{agent_id}: '{name}' is not an exogenous input of "
                    f"its OCP (has: {sorted(a.exo)}) — controls and "
                    f"couplings are decided by the solver, not fed back")
            a.exo[name] = float(val)
        if parameters is not None:
            byname = {v.name: i for i, v in enumerate(a.model.parameters)}
            for name, val in parameters.items():
                a.p[byname[name]] = float(val)
        gi, slot = self._where[agent_id]
        theta = a.theta(self.N)
        import jax

        self._theta_batches[gi] = jax.tree.map(
            lambda batch, leaf: batch.at[slot].set(leaf),
            self._theta_batches[gi], theta)

    def step(self) -> dict[str, dict]:
        """One coordinated ADMM round for the whole fleet.

        Returns per-agent results: ``{"u": {name: (N,) array}, "x": ...,
        "converged": bool, "iterations": int}``. ``converged`` and
        ``iterations`` are **fleet-wide** values (the fused round has one
        Boyd convergence check and one iteration count for all agents,
        like the reference coordinator); they are replicated into every
        agent's dict for ergonomic per-agent consumption.
        """
        self.state, trajs, stats = self.engine.step(
            self.state, self._theta_batches)
        # one device→host transfer per group, then indexed per agent
        host = [{k: np.asarray(v) for k, v in tr.items()} for tr in trajs]
        out: dict[str, dict] = {}
        for a in self._agents:
            gi, slot = self._where[a.agent_id]
            tr = host[gi]
            u = tr["u"][slot]                      # (N, n_u)
            res = {
                "u": {n: u[:, j]
                      for j, n in enumerate(a.ocp.control_names)},
                "converged": bool(stats.converged),
                "iterations": int(stats.iterations),
            }
            if "x" in tr:
                res["x"] = tr["x"][slot]
            out[a.agent_id] = res
            if self.record:
                # reference-layout history (same record shape as the
                # module path, modules/mpc.py _record)
                self._history[a.agent_id].append({
                    "time": self.time,
                    "traj": {k: v[slot] + (self.time
                             if k in ("time_state", "time_control")
                             else 0.0)
                             for k, v in tr.items()},
                })
        if self.record:
            it = int(stats.iterations)
            self._stats_rows.append({
                "time": self.time,
                "primal": np.asarray(stats.primal_residuals)[:it],
                "dual": np.asarray(stats.dual_residuals)[:it],
                # per-alias ρ histories (the engine adapts each alias
                # independently); "rho" keeps the mean trail for
                # existing single-alias consumers
                "rho": np.mean([np.asarray(v)[:it]
                                for v in stats.penalty.values()], axis=0),
                "rho_per_alias": {a: np.asarray(v)[:it]
                                  for a, v in stats.penalty.items()},
            })
            # per-iteration local coupling trajectories per agent (the
            # reference's iteration-buffered ADMM record); one block per
            # step() call, so repeated solves at one time all survive
            per_agent: dict[str, dict[str, np.ndarray]] = {}
            for kind, hist in (("consensus", stats.coupling_locals),
                               ("exchange", stats.exchange_locals)):
                for alias, arr in (hist or {}).items():
                    arr = np.asarray(arr)[:it]       # (it, n_part, T)
                    for a in self._agents:
                        amap = (a.couplings if kind == "consensus"
                                else a.exchanges)
                        if alias not in amap:
                            continue
                        gi, slot = self._where[a.agent_id]
                        row = self.engine.participant_offset(
                            alias, kind, gi) + slot
                        per_agent.setdefault(a.agent_id, {})[alias] = \
                            arr[:, row, :]           # (it, T)
            for aid, aliases_d in per_agent.items():
                self._admm_rows.setdefault(aid, []).append(
                    {"time": self.time, "aliases": aliases_d})
        self._last_stats = stats
        return out

    def advance(self) -> None:
        """Shift-by-one warm start + clock advance between control
        intervals (``shift_state``; reference
        ``_shift_coupling_variables``)."""
        self.state = self.engine.shift_state(self.state)
        self.time += self.dt

    # -- checkpoint/resume (beyond reference: SURVEY §5 records the
    #    reference has NO process-state checkpointing) ------------------------

    def save_checkpoint(self, path: str) -> str:
        """Persist the fleet's control state — consensus means,
        multipliers, primal/dual warm starts, clock, and the current
        per-agent parameter batches — to ``path`` (orbax directory).

        A restarted process rebuilds the fleet from the SAME configs and
        calls :meth:`restore_checkpoint`; the next :meth:`step` then
        continues with warm-started iteration counts instead of paying a
        cold start under a real-time deadline. Results/stats history is
        not included (persist it via :meth:`results` /
        :meth:`iteration_stats` writers, the reference's append-only
        CSV role)."""
        from agentlib_mpc_tpu.utils.checkpoint import save_pytree

        return save_pytree(path, {
            "state": self.state,
            "time": self.time,
            "theta_batches": list(self._theta_batches),
        })

    def restore_checkpoint(self, path: str) -> None:
        """Restore state saved by :meth:`save_checkpoint` into this
        (structurally identical, freshly built) fleet."""
        from agentlib_mpc_tpu.utils.checkpoint import load_pytree

        tree = load_pytree(path, {
            "state": self.state,
            "time": self.time,
            "theta_batches": list(self._theta_batches),
        })
        self.state = tree["state"]
        self.time = float(tree["time"])
        self._theta_batches = list(tree["theta_batches"])

    # -- results (reference CSV layouts, utils/analysis-compatible) -----------

    def results(self, agent_id: str):
        """(time, grid) MultiIndex trajectory DataFrame for one agent —
        the same layout the module path records, so `utils/analysis` and
        the plotting toolkit work on fused runs unchanged."""
        from agentlib_mpc_tpu.utils.results import (
            mpc_trajectory_frame,
            trajectory_layout,
        )

        a = self._agents_by_id()[agent_id]
        return mpc_trajectory_frame(
            self._history[agent_id],
            trajectory_layout(a.model, a.ocp.control_names, ocp=a.ocp))

    def admm_results(self, agent_id: str):
        """(time, iteration, grid) MultiIndex frame of one agent's local
        coupling trajectories per fused iteration — the module path's
        ``ADMMModule.admm_results`` layout (reference iteration-buffered
        record, ``casadi_/admm.py:364-424``), so `analysis.load_admm`
        slicing, `plot_consensus_shades` and the convergence animation
        work on fused runs unchanged."""
        from agentlib_mpc_tpu.utils.results import (
            admm_iteration_frame,
            concat_admm_frames,
        )

        rows = self._admm_rows.get(agent_id)
        if not rows:
            return None
        grid = np.arange(self.N) * self.dt
        frames = []
        for row in rows:
            per_alias = row["aliases"]               # alias -> (it, T)
            # one stats object per step: every alias shares its `it`
            n_it = next(iter(per_alias.values())).shape[0]
            frames.append(admm_iteration_frame(
                row["time"], range(n_it), grid, per_alias))
        return concat_admm_frames(frames)

    def cleanup_results(self) -> None:
        """Drop recorded history (module-path parity:
        ``modules/mpc.py cleanup_results``) — bounds memory on long
        closed-loop runs."""
        for rows in self._history.values():
            rows.clear()
        self._stats_rows.clear()
        self._admm_rows.clear()

    def iteration_stats(self):
        """(time, iteration)-indexed residual/penalty trail of every
        fused round (the reference coordinator's per-iteration stats,
        ``admm_coordinator.py:396-402``)."""
        import pandas as pd

        if not self._stats_rows:
            return None
        frames = []
        for row in self._stats_rows:
            # coordinator column names (modules/coordinator.py stats rows)
            df = pd.DataFrame({"primal_residual": row["primal"],
                               "dual_residual": row["dual"],
                               "penalty_parameter": row["rho"]})
            df.index = pd.MultiIndex.from_product(
                [[row["time"]], range(len(row["primal"]))],
                names=["time", "iteration"])
            frames.append(df)
        return pd.concat(frames)

    @property
    def last_stats(self):
        return getattr(self, "_last_stats", None)

    def _agents_by_id(self) -> dict[str, _FleetAgent]:
        return {a.agent_id: a for a in self._agents}
