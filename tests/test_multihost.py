"""Multi-host plumbing (parallel/multihost.py) on the virtual CPU mesh.

True multi-process execution cannot run in CI; what can is pinned here:
the no-op single-process init, the mesh construction/layout, the
host-local batch arithmetic, and a fused-ADMM step over a fleet_mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.parallel import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    fleet_mesh,
    host_local_batch,
    initialize_multihost,
)
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.ops.transcription import transcribe


from conftest import make_tracker_model  # noqa: E402


@pytest.fixture(scope="module")
def tracker_ocp_factory():
    def make():
        Tracker = make_tracker_model(lb=-10.0, ub=10.0)
        return transcribe(Tracker(), ["u"], N=4, dt=300.0,
                          method="multiple_shooting")

    return make


def test_single_process_init_is_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize_multihost() is False


def test_fleet_mesh_covers_all_devices(eight_devices):
    mesh = fleet_mesh()
    assert mesh.axis_names == ("agents",)
    assert mesh.devices.size == len(jax.devices())


def test_host_local_batch_partitions_exactly(eight_devices):
    # single process, 8 virtual devices: the whole (divisible) batch
    start, count = host_local_batch(16)
    assert (start, count) == (0, 16)


def test_host_local_batch_rejects_uneven(eight_devices):
    with pytest.raises(ValueError, match="pad"):
        host_local_batch(11)


def test_host_local_batch_multi_process_layout(eight_devices, monkeypatch):
    """Drive the REAL function under a faked 2-process view of the
    8-device fleet: slices must be contiguous, device-granular, and
    concatenate to the full batch in process-major order."""

    class _Dev:
        def __init__(self, pid):
            self.process_index = pid

    devs = [_Dev(0)] * 4 + [_Dev(1)] * 4
    monkeypatch.setattr(jax, "devices", lambda *a: devs)
    monkeypatch.setattr(jax, "local_device_count", lambda *a: 4)

    slices = []
    for pid in (0, 1):
        monkeypatch.setattr(jax, "process_index", lambda p=pid: p)
        slices.append(host_local_batch(16))
    assert slices == [(0, 8), (8, 8)]
    covered = []
    for start, count in slices:
        covered.extend(range(start, start + count))
    assert covered == list(range(16))


@pytest.mark.slow
def test_two_process_distributed_fused_step(eight_devices,
                                            tracker_ocp_factory):
    """VERDICT r3 ask #4: the DCN path of parallel/multihost.py executed
    by a test, not just documented. Two REAL OS processes (4 virtual CPU
    devices each) join via jax.distributed and run one fused ADMM step
    over the 8-device global mesh — the consensus mean crosses the
    process boundary as a Gloo all-reduce. Both processes must agree with
    each other and with the single-process result (evidence parity with
    the reference's spawned-process ADMM test,
    ``tests/test_examples.py:170-186``)."""
    import json
    import os
    import socket
    import subprocess
    import sys as _sys

    from agentlib_mpc_tpu.parallel.fused_admm import stack_params
    from agentlib_mpc_tpu.utils.jax_setup import cpu_subprocess_env

    # single-process reference: same problem, unsharded
    ocp = tracker_ocp_factory()
    group = AgentGroup(
        name="trackers", ocp=ocp, n_agents=8,
        couplings={"shared_u": "u"},
        solver_options=SolverOptions(tol=1e-8, max_iter=30))
    engine = FusedADMM(
        [group], FusedADMMOptions(max_iterations=25, rho=2.0,
                                  abs_tol=1e-6, rel_tol=1e-5))
    thetas = stack_params([
        ocp.default_params(p=jnp.array([float(a)])) for a in range(8)])
    state_single, _t, stats_single = engine.step(
        engine.init_state([thetas]), [thetas])
    assert bool(stats_single.converged)
    zbar_single = np.asarray(state_single.zbar["shared_u"])

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    env = cpu_subprocess_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_worker.py")
    procs = [subprocess.Popen(
        [_sys.executable, worker, str(i), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, cwd=os.path.dirname(worker)) for i in range(2)]
    outs = []
    for i, p in enumerate(procs):
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, \
            f"worker {i} rc={p.returncode}:\n{err[-2000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        outs.append(json.loads(line))

    for o in outs:
        assert o["n_processes"] == 2
        assert o["n_global_devices"] == 8
        assert o["converged"]
    # both controllers computed the same SPMD program: identical results
    np.testing.assert_allclose(outs[0]["zbar"], outs[1]["zbar"],
                               rtol=1e-12)
    # and the 2-process global mesh matches the single-process run
    np.testing.assert_allclose(
        np.asarray(outs[0]["zbar"]).reshape(zbar_single.shape),
        zbar_single, atol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[0]["zbar"]), 3.5,
                               atol=1e-3)


def test_fused_step_on_fleet_mesh(eight_devices, tracker_ocp_factory):
    """A fused consensus round sharded over fleet_mesh() matches the
    unsharded result — the single-controller stand-in for a pod run."""
    ocp = tracker_ocp_factory()
    group = AgentGroup(
        name="trackers", ocp=ocp, n_agents=8,
        couplings={"shared_u": "u"},
        solver_options=SolverOptions(tol=1e-8, max_iter=30))
    engine = FusedADMM(
        [group], FusedADMMOptions(max_iterations=25, rho=2.0,
                                  abs_tol=1e-6, rel_tol=1e-5))
    from agentlib_mpc_tpu.parallel.fused_admm import stack_params
    thetas = stack_params([
        ocp.default_params(p=jnp.array([float(a)])) for a in range(8)])
    state = engine.init_state([thetas])
    state_plain, _trajs, stats_plain = engine.step(state, [thetas])

    mesh = fleet_mesh()
    state_sh, thetas_sh = engine.shard_args(
        mesh, engine.init_state([thetas]), [thetas])
    state_mesh, _t, stats_mesh = engine.step(state_sh, thetas_sh)
    assert bool(stats_plain.converged) and bool(stats_mesh.converged)
    np.testing.assert_allclose(
        np.asarray(state_mesh.zbar["shared_u"]),
        np.asarray(state_plain.zbar["shared_u"]), atol=1e-5)
    # analytic consensus fixed point: mean of targets 0..7
    np.testing.assert_allclose(
        np.asarray(state_mesh.zbar["shared_u"]), 3.5, atol=1e-3)
