"""Performance observatory (ISSUE 16): named phases → capture →
calibrate → regression gate.

Three layers under test. (1) The phase vocabulary and the HLO join:
``phase_scope`` annotations must survive into compiled ``op_name``
metadata and ``phase_map_from_hlo`` must reconstruct an
instruction→phase map — including the structural-inheritance walk that
recovers XLA's metadata-stripped loop-transform clones. (2) Capture:
``capture_phase_profile`` on the SAME 4-agent fused tracker fleet the
lint gates run must attribute ≥90% of measured warm-round device time
to named phases, with the gap as an explicit ``unattributed`` row (the
ISSUE acceptance criterion). (3) The regression plane: baselines with
noise bands, a one-sided gate that passes A/A and fails an injected
slowdown, both outcomes journaled as typed events.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp
import pytest

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.telemetry import calibration, profiler, regression
from agentlib_mpc_tpu.telemetry import journal as journal_mod


@pytest.fixture(autouse=True)
def _restore_telemetry():
    yield
    telemetry.disable_journal()
    telemetry.configure(enabled=True)
    telemetry.reset()


def _profile(device_ms, metric_key="phase_ms_cpu", rounds=3):
    """A synthetic PhaseProfile for regression-plane unit tests."""
    total = sum(device_ms.values())
    unattr = device_ms.get(profiler.UNATTRIBUTED, 0.0)
    return profiler.PhaseProfile(
        platform="cpu", rounds=rounds, device_ms=dict(device_ms),
        op_events={k: 5 for k in device_ms}, total_device_ms=total,
        host_ms=1.0, wall_ms=total + 1.0,
        coverage=(total - unattr) / total if total else 0.0,
        metric_key=metric_key)


class TestPhaseVocabulary:
    def test_phase_scope_rejects_names_outside_the_vocabulary(self):
        with pytest.raises(ValueError, match="vocabulary"):
            profiler.phase_scope("not_a_phase")

    def test_deepest_phase_wins_on_nested_scopes(self):
        path = "jit(step)/while/phase.factor/body/phase.resolve/dot"
        assert profiler.deepest_phase(path) == "resolve"
        assert profiler.deepest_phase("jit(step)/while/dot") is None

    def test_phase_map_joins_annotations_through_compiled_text(self):
        """Annotations placed with phase_scope must come back out of the
        compiled module text mapped to the right phase — including ops
        the compiler moved into metadata-less cloned computations (the
        structural-inheritance walk)."""

        @jax.jit
        def f(a):
            with profiler.phase_scope("factor"):
                l_factor = jnp.linalg.cholesky(
                    a @ a.T + 64.0 * jnp.eye(a.shape[0]))
            with profiler.phase_scope("resolve"):
                y = jax.scipy.linalg.solve_triangular(
                    l_factor, a[:, 0], lower=True)
            return y

        x = jnp.eye(64) + 0.01
        hlo = profiler.hlo_text_for(f, x)
        pmap = profiler.phase_map_from_hlo(hlo)
        assert set(pmap.values()) >= {"factor", "resolve"}
        # CPU lowers cholesky through expander computations whose
        # cloned instructions carry NO op_name — inheritance must
        # still attribute a dot/triangular op somewhere
        assert any(v == "factor" for v in pmap.values())


class TestCapture:
    def test_capture_attributes_device_time_on_a_small_step(self, tmp_path):
        @jax.jit
        def f(a):
            with profiler.phase_scope("factor"):
                b = a @ a
            with profiler.phase_scope("resolve"):
                c = b @ a
            return jnp.sum(c)

        x = jnp.ones((256, 256)) * 0.01
        jax.block_until_ready(f(x))
        hlo = profiler.hlo_text_for(f, x)

        journal = telemetry.enable_journal(str(tmp_path / "j.jsonl"))
        prof = profiler.capture_phase_profile(
            lambda: jax.block_until_ready(f(x)), rounds=2, hlo_text=hlo)
        telemetry.disable_journal()

        assert prof.rounds == 2
        assert sum(prof.op_events.values()) > 0
        assert prof.device_ms["factor"] + prof.device_ms["resolve"] > 0
        # the residual row is always present, never silently dropped
        assert profiler.UNATTRIBUTED in prof.device_ms
        assert 0.0 <= prof.coverage <= 1.0
        # platform-qualified metric key (CPU run → _cpu suffix)
        assert prof.metric_key == "phase_ms_cpu"
        # the capture journaled itself as a typed event
        events = journal_mod.read_events(str(tmp_path / "j.jsonl"))
        captured = [e for e in events if e["etype"] == "profile.captured"]
        assert captured and captured[0]["coverage"] == round(
            prof.coverage, 4)
        assert journal.stats()["events"] >= 1

    def test_fused_tracker_fleet_coverage_at_least_90_percent(self):
        """THE acceptance criterion: on the fused tracker fleet (the
        same 4-agent consensus workload every lint gate runs), named
        phases must reconstruct ≥90% of measured warm-round device
        time, the gap reported as an explicit ``unattributed`` row."""
        from agentlib_mpc_tpu.lint.retrace_budget import build_bench_engine

        engine, state, thetas = build_bench_engine(4)
        for _ in range(2):
            state, _trajs, _stats = engine.step(state, thetas)
            state = engine.shift_state(state)
        hlo = profiler.hlo_text_for(engine._step,
                                    *engine._step_templates())

        holder = {"state": state}

        def run_round():
            s, _trajs, _stats = engine.step(holder["state"], thetas)
            holder["state"] = engine.shift_state(s)
            jax.block_until_ready(holder["state"])

        prof = profiler.capture_phase_profile(
            run_round, rounds=2, hlo_text=hlo, journal=False)

        assert sum(prof.op_events.values()) > 0
        assert prof.coverage >= 0.90, prof.as_dict()
        assert profiler.UNATTRIBUTED in prof.device_ms
        # the table renders the residual row explicitly
        assert "unattributed" in prof.table()


    def test_warm_round_observes_exactly_the_certified_dispatch_count(self):
        """ISSUE 18 cross-check: the dispatch certificate's static
        claim — the warm fused round is ONE device program — against
        what the profiler actually measures. A captured warm round must
        execute exactly ``dispatch_count()`` distinct device programs;
        an extra module in the window means an uncertified dispatch
        snuck into the hot path."""
        from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
        from agentlib_mpc_tpu.ops.solver import SolverOptions
        from agentlib_mpc_tpu.parallel.fused_admm import (
            AgentGroup,
            FusedADMM,
            FusedADMMOptions,
            stack_params,
        )

        ocp = tracker_ocp()
        group = AgentGroup(
            name="dispatch-xcheck", ocp=ocp, n_agents=4,
            couplings={"shared_u": "u"},
            solver_options=SolverOptions(max_iter=30),
            qp_fast_path="off")
        engine = FusedADMM(
            [group], FusedADMMOptions(max_iterations=8, rho=2.0),
            dispatch_certify="require")
        cert = engine.dispatch_certificate
        assert cert is not None and cert.proved
        assert cert.dispatch_count() == 1

        thetas = [stack_params([
            ocp.default_params(p=jnp.array([float(i + 1)]))
            for i in range(4)])]
        state = engine.init_state(thetas)
        for _ in range(2):      # compile strictly outside the window
            state, _trajs, _stats = engine.step(state, thetas)
        jax.block_until_ready(state)
        hlo = profiler.hlo_text_for(engine._step,
                                    *engine._step_templates())

        holder = {"state": state}

        def run_round():
            # ONLY the certified step runs inside the capture window
            s, _trajs, _stats = engine.step(holder["state"], thetas)
            holder["state"] = s
            jax.block_until_ready(s)

        prof = profiler.capture_phase_profile(
            run_round, rounds=2, hlo_text=hlo, journal=False)
        assert sum(prof.op_events.values()) > 0
        # the observed program set IS the certified schedule: one
        # module — the fused mega-round — and nothing else
        assert len(prof.hlo_modules) == cert.dispatch_count(), \
            prof.hlo_modules


class TestRegressionPlane:
    PHASES_MS = {"factor": 10.0, "resolve": 40.0, "eval_jac": 20.0,
                 profiler.UNATTRIBUTED: 0.5}

    def test_qualified_metric_naming_rule(self):
        q = regression.qualified_metric
        assert q("phase_ms", "tpu") == "phase_ms"
        assert q("phase_ms", "cpu") == "phase_ms_cpu"
        assert q("phase_ms", "cpu", n_devices=4) == "phase_ms_cpu_d4"
        assert q("phase_ms", "tpu", n_devices=8,
                 mesh_shape=(4, 2)) == "phase_ms_d4x2"
        assert q("phase_ms", "cpu", degraded=True).endswith("_degraded")

    def test_update_baseline_writes_bands_from_spread_and_floors(
            self, tmp_path):
        path = str(tmp_path / "baselines.json")
        p1 = _profile(self.PHASES_MS)
        p2 = _profile({**self.PHASES_MS, "factor": 12.0})
        entry = regression.update_baseline(path, [p1, p2])
        assert entry["phases"]["factor"]["mean_ms"] == pytest.approx(11.0)
        # band = max(spread, rel_floor*mean, abs_floor): spread=2.0,
        # 0.25*11=2.75 dominates
        assert entry["phases"]["factor"]["band_ms"] == pytest.approx(2.75)
        on_disk = json.loads(Path(path).read_text())
        assert on_disk["phase_ms_cpu"] == entry

    def test_gate_passes_aa_and_fails_injected_slowdown(self, tmp_path):
        path = str(tmp_path / "baselines.json")
        regression.update_baseline(
            path, [_profile(self.PHASES_MS), _profile(self.PHASES_MS)])

        jpath = str(tmp_path / "j.jsonl")
        telemetry.enable_journal(jpath)
        aa = regression.check_regression(path, _profile(self.PHASES_MS))
        slowed = regression.check_regression(
            path, _profile({**self.PHASES_MS, "factor": 25.0}))
        telemetry.disable_journal()

        assert aa["status"] == "pass" and not aa["violations"]
        assert slowed["status"] == "fail"
        assert [v["phase"] for v in slowed["violations"]] == ["factor"]
        assert slowed["violations"][0]["excess_ms"] > 0

        # both outcomes journaled as typed events
        events = journal_mod.read_events(jpath)
        gates = [e for e in events if e["etype"] == "perf.gate"]
        assert [g["status"] for g in gates] == ["pass", "fail"]
        regs = [e for e in events if e["etype"] == "perf.regression"]
        assert len(regs) == 1 and regs[0]["phase"] == "factor"

    def test_gate_is_one_sided_improvements_are_notes_not_failures(
            self, tmp_path):
        path = str(tmp_path / "baselines.json")
        regression.update_baseline(
            path, [_profile(self.PHASES_MS), _profile(self.PHASES_MS)])
        faster = regression.check_regression(
            path, _profile({**self.PHASES_MS, "resolve": 5.0}),
            journal=False)
        assert faster["status"] == "pass"
        assert [i["phase"] for i in faster["improvements"]] == ["resolve"]

    def test_missing_baseline_key_is_an_explicit_skip(self, tmp_path):
        report = regression.check_regression(
            {}, _profile(self.PHASES_MS), journal=False)
        assert report["status"] == "skip"
        assert "no baseline" in report["notes"][0]

    def test_incident_timeline_renders_perf_regression(self):
        from agentlib_mpc_tpu.telemetry import incident

        assert "perf.regression" in incident.FAULT_EVENTS
        row = incident._fmt_event({
            "seq": 7, "round": 3, "etype": "perf.regression",
            "phase": "factor", "measured_ms": 25.0, "baseline_ms": 11.0,
            "band_ms": 2.75, "excess_ms": 11.25,
            "metric_key": "phase_ms_cpu"})
        assert "phase=factor" in row and "25.0" in row \
            and "phase_ms_cpu" in row


class TestCalibration:
    def test_costs_join_measurement_into_roofline_report(self):
        @jax.jit
        def f(a):
            with profiler.phase_scope("factor"):
                b = a @ a
            return jnp.sum(b)

        x = jnp.ones((128, 128))
        costs = calibration.phase_costs(f, x)
        assert costs["factor"]["flops"] > 0

        prof = _profile({"factor": 2.0, profiler.UNATTRIBUTED: 0.1})
        report = calibration.calibrate(prof, costs)
        d = report.as_dict()
        assert "factor" in d["phases"]
        ph = d["phases"]["factor"]
        assert ph["achieved_gflops_per_s"] > 0
        assert ph["bound"] in ("compute", "memory")
        assert "factor" in report.table()
