"""PID controller and the MPC-fallback variant.

The reference leans on agentlib's PID module and subclasses it
(``modules/deactivate_mpc/fallback_pid.py:40-97``); since the runtime here
replaces agentlib (SURVEY.md §1 L0), the PID itself is part of the
framework. Event-driven SISO loop: every arriving measurement triggers one
controller step

    u = Kp · (e + 1/Ti ∫e dt + Td de/dt),  clamped to [lb, ub]

with conditional anti-windup (the integrator freezes while the output
saturates). ``FallbackPID`` runs only while the MPC flag is False and
resets its integrator and timing on every hand-over, so control resumes
bumplessly after MPC outages.
"""

from __future__ import annotations

import logging
import math

import numpy as np

from agentlib_mpc_tpu.modules.deactivate_mpc import MPC_FLAG_ACTIVE
from agentlib_mpc_tpu.runtime.module import BaseModule, register_module
from agentlib_mpc_tpu.runtime.variables import AgentVariable

logger = logging.getLogger(__name__)


@register_module("pid")
class PID(BaseModule):
    """Config: ``input`` (measured variable, usually with alias/source),
    ``output`` (actuation variable, shared), ``setpoint`` (value or
    variable entry), ``Kp``, ``Ti`` (s, 0 = no integral action), ``Td``
    (s), ``ub``/``lb`` saturation, ``reverse_acting``."""

    variable_groups = ("inputs", "outputs")
    shared_groups = ("outputs",)

    def __init__(self, config: dict, agent):
        # copy the variable-group lists too: appending into a caller-owned
        # list would leak the singular entries into reused config templates
        config = dict(config)
        if "input" in config:
            config["inputs"] = [*config.get("inputs", []),
                                config.pop("input")]
        if "output" in config:
            config["outputs"] = [*config.get("outputs", []),
                                 config.pop("output")]
        super().__init__(config, agent)
        if not self._groups["inputs"] or not self._groups["outputs"]:
            raise ValueError("PID needs an input and an output variable")
        self.input_name = self._groups["inputs"][0]
        self.output_name = self._groups["outputs"][0]
        sp = config.get("setpoint", 0.0)
        if isinstance(sp, dict):
            var = AgentVariable.from_config(sp)
            self._declare(var, "inputs")
            self._groups["inputs"].append(var.name)
            self.setpoint_name = var.name
        else:
            self.setpoint_name = None
            self.setpoint_value = float(sp)
        self.Kp = float(config.get("Kp", 1.0))
        self.Ti = float(config.get("Ti", 0.0))
        self.Td = float(config.get("Td", 0.0))
        self.ub = float(config.get("ub", math.inf))
        self.lb = float(config.get("lb", -math.inf))
        self.reverse_acting = bool(config.get("reverse_acting", False))
        self.integral = 0.0
        self.e_last = 0.0
        self.last_time: float | None = None

    @property
    def setpoint(self) -> float:
        if self.setpoint_name is not None:
            return float(self.vars[self.setpoint_name].value)
        return self.setpoint_value

    def register_callbacks(self) -> None:
        super().register_callbacks()
        var = self.vars[self.input_name]
        self.agent.data_broker.register_callback(
            var.alias, var.source, self._siso_callback)

    def reset(self, at_time: float | None = None) -> None:
        self.integral = 0.0
        self.e_last = 0.0
        self.last_time = at_time

    def _siso_callback(self, incoming: AgentVariable) -> None:
        self.vars[self.input_name].value = incoming.value
        self.vars[self.input_name].timestamp = incoming.timestamp
        out = self.do_step(float(incoming.value),
                           float(incoming.timestamp))
        if out is not None:
            self.set(self.output_name, out)

    def do_step(self, measurement: float, t: float) -> float | None:
        e = self.setpoint - measurement
        if self.reverse_acting:
            e = -e
        if self.last_time is None:
            self.last_time = t
            self.e_last = e
            return None
        dt = t - self.last_time
        if dt <= 0:
            return None
        d_term = self.Td * (e - self.e_last) / dt
        i_term = (self.integral + e * dt) / self.Ti if self.Ti > 0 else 0.0
        u = self.Kp * (e + i_term + d_term)
        u_sat = float(np.clip(u, self.lb, self.ub))
        # conditional anti-windup: integrate only when not pushing further
        # into saturation
        if self.Ti > 0 and (u == u_sat or (u > u_sat) == (e < 0)):
            self.integral += e * dt
        self.e_last = e
        self.last_time = t
        return u_sat


@register_module("fallback_pid")
class FallbackPID(PID):
    """PID active only while the MPC flag is False (reference
    ``FallbackPID._siso_callback``, ``fallback_pid.py:40-97``)."""

    def __init__(self, config: dict, agent):
        super().__init__(config, agent)
        if MPC_FLAG_ACTIVE not in self.vars:
            self._declare(AgentVariable(name=MPC_FLAG_ACTIVE, value=True,
                                        shared=False), "inputs")
            self._groups["inputs"].append(MPC_FLAG_ACTIVE)
        self._mpc_was_active: bool | None = None

    def _siso_callback(self, incoming: AgentVariable) -> None:
        mpc_active = bool(self.vars[MPC_FLAG_ACTIVE].value)
        if self._mpc_was_active is None:
            self._mpc_was_active = mpc_active
            if not mpc_active:
                self.reset(at_time=float(incoming.timestamp))
        elif mpc_active != self._mpc_was_active:
            # hand-over in either direction resets integrator and timing
            self.logger.info(
                "MPC flag became %s; %s FallbackPID", mpc_active,
                "deactivating" if mpc_active else "activating")
            self.reset(at_time=None if mpc_active
                       else float(incoming.timestamp))
            self._mpc_was_active = mpc_active
        if not mpc_active:
            super()._siso_callback(incoming)
