"""Durable warm-start checkpointing of the module-path backends
(utils/checkpoint.py + OptimizationBackend.warm_state).

The fused-fleet checkpoint equivalence is pinned in
test_config_bridge.py::TestCheckpointResume; this covers the central-MPC
backend path: a restarted backend restored from the checkpoint must
produce the SAME next solve (trajectory and iteration count) as the
uninterrupted one, and warm solves must actually be cheaper than cold.
"""

import numpy as np
import pytest

from agentlib_mpc_tpu.backends.backend import (
    VariableReference,
    create_backend,
)
from agentlib_mpc_tpu.models.zoo import CooledRoom
from agentlib_mpc_tpu.utils.checkpoint import load_pytree, save_pytree


def _backend():
    backend = create_backend({
        "type": "jax",
        "model": {"class": CooledRoom},
        "discretization_options": {"collocation_order": 2},
        "solver": {"max_iter": 60},
    })
    backend.setup_optimization(
        VariableReference(
            states=["T", "T_slack"], controls=["mDot"],
            inputs=["load", "T_in", "T_upper"],
            parameters=["cp", "C", "s_T", "r_mDot"],
        ),
        time_step=300.0, prediction_horizon=6)
    return backend


class TestBackendWarmState:
    def test_restored_backend_matches_uninterrupted_solve(self, tmp_path):
        backend = _backend()
        backend.solve(0.0, {"T": 297.15})
        path = save_pytree(str(tmp_path / "warm"), backend.warm_state())

        res_continued = backend.solve(300.0, {"T": 296.9})

        fresh = _backend()                     # "restarted process"
        fresh.set_warm_state(load_pytree(path, fresh.warm_state()))
        res_resumed = fresh.solve(300.0, {"T": 296.9})

        np.testing.assert_array_equal(
            np.asarray(res_continued["traj"]["u"]),
            np.asarray(res_resumed["traj"]["u"]))
        assert res_continued["stats"]["iterations"] == \
            res_resumed["stats"]["iterations"]

    def test_warm_restore_beats_cold_start(self, tmp_path):
        backend = _backend()
        cold_iters = backend.solve(0.0, {"T": 297.15})["stats"]["iterations"]
        backend.solve(300.0, {"T": 296.9})
        path = save_pytree(str(tmp_path / "warm"), backend.warm_state())

        fresh = _backend()
        fresh.set_warm_state(load_pytree(path, fresh.warm_state()))
        warm_iters = fresh.solve(600.0, {"T": 296.7})["stats"]["iterations"]
        # <= like the repo's other warm-vs-cold pins (the two solves see
        # different data, so strict inequality would be flaky by design)
        assert warm_iters <= cold_iters

    def test_shape_mismatch_rejected(self, tmp_path):
        backend = _backend()
        other = create_backend({
            "type": "jax",
            "model": {"class": CooledRoom},
            "discretization_options": {"collocation_order": 2},
            "solver": {"max_iter": 60},
        })
        other.setup_optimization(
            VariableReference(
                states=["T", "T_slack"], controls=["mDot"],
                inputs=["load", "T_in", "T_upper"],
                parameters=["cp", "C", "s_T", "r_mDot"],
            ),
            time_step=300.0, prediction_horizon=9)   # different horizon
        with pytest.raises(ValueError, match="same config"):
            other.set_warm_state(backend.warm_state())

    def test_ml_backend_warm_state_roundtrips(self, tmp_path):
        """The warm-state contract is generic over backend subclasses:
        the NARX ML backend (its own _reset_warm_start) checkpoints and
        resumes identically too."""
        from test_ml_backend import _backend as ml_backend

        backend = ml_backend()
        backend.solve(0.0, {"T": 297.15})
        path = save_pytree(str(tmp_path / "ml_warm"),
                           backend.warm_state())
        res_continued = backend.solve(300.0, {"T": 296.9})

        fresh = ml_backend()
        fresh.set_warm_state(load_pytree(path, fresh.warm_state()))
        res_resumed = fresh.solve(300.0, {"T": 296.9})
        np.testing.assert_array_equal(
            np.asarray(res_continued["traj"]["u"]),
            np.asarray(res_resumed["traj"]["u"]))

    def test_partial_tmp_does_not_shadow_complete_old(self, tmp_path):
        """Crash scenario: a save killed *during* the orbax write leaves
        an incomplete (newer) ``.tmp-*`` next to the complete ``.old-*``
        the swap parked. Restore must fall through the garbage tmp to
        the old checkpoint instead of failing on exactly the crash the
        feature exists for."""
        import os
        import shutil
        import time

        tree = {"a": np.arange(4.0), "b": np.float64(2.5)}
        path = save_pytree(str(tmp_path / "state"), tree)
        # simulate the mid-swap kill: real checkpoint parked at .old-*,
        # primary gone, then a NEWER partial .tmp-* from the next save
        shutil.move(path, f"{path}.old-123")
        time.sleep(0.02)
        os.makedirs(f"{path}.tmp-123")
        (tmp_path / "state.tmp-123" / "junk").write_text("not orbax")

        restored = load_pytree(path, tree)
        np.testing.assert_array_equal(restored["a"], tree["a"])
        assert restored["b"] == tree["b"]

    def test_truncated_checkpoint_rejected_not_restored(self, tmp_path):
        """Satellite: a torn checkpoint (every ocdbt data block
        truncated — the crash-mid-write / bit-rot model) must be
        REJECTED by load_pytree, never silently restored as garbage."""
        from agentlib_mpc_tpu.resilience.chaos import corrupt_checkpoint

        tree = {"a": np.arange(64.0), "b": np.float64(2.5)}
        path = save_pytree(str(tmp_path / "state"), tree)
        corrupt_checkpoint(path, mode="truncate")
        with pytest.raises((ValueError, RuntimeError)):
            load_pytree(path, tree)

    def test_half_written_tmp_is_not_a_checkpoint(self, tmp_path):
        """A save killed during the very first orbax write leaves only
        a marker-less temp dir: has_checkpoint must answer False (cold
        start), not steer the module into a doomed restore."""
        import os

        from agentlib_mpc_tpu.utils.checkpoint import has_checkpoint

        path = str(tmp_path / "state")
        os.makedirs(f"{path}.tmp-1")
        (tmp_path / "state.tmp-1" / "junk").write_text("not orbax")
        assert not has_checkpoint(path)
        # ... while a COMPLETE checkpoint (commit marker present) next
        # to the same junk tmp still answers True
        save_pytree(path, {"a": np.arange(3.0)})
        assert has_checkpoint(path)

    def test_primary_without_commit_marker_is_not_a_checkpoint(
            self, tmp_path):
        import os

        from agentlib_mpc_tpu.utils.checkpoint import has_checkpoint

        path = str(tmp_path / "state")
        os.makedirs(path)
        (tmp_path / "state" / "partial").write_text("x")
        assert not has_checkpoint(path)

    def test_missing_checkpoint_reports_all_failed_siblings(self, tmp_path):
        """Truly absent -> FileNotFoundError (cold start is correct);
        present-but-unrestorable -> RuntimeError (cold start would
        silently discard potentially recoverable state)."""
        import os

        path = str(tmp_path / "absent")
        with pytest.raises(FileNotFoundError, match="no checkpoint"):
            load_pytree(path, {"a": np.zeros(2)})
        os.makedirs(f"{path}.tmp-1")
        with pytest.raises(RuntimeError, match="sibling"):
            load_pytree(path, {"a": np.zeros(2)})

    def test_pid_reuse_old_dir_does_not_abort_save(self, tmp_path):
        """A container controller is always the same pid: a leftover
        ``.old-<pid>`` from a crashed earlier save must not make the
        next save's swap rename fail with ENOTEMPTY."""
        import os

        tree = {"a": np.arange(3.0)}
        path = save_pytree(str(tmp_path / "state"), tree)
        stale = f"{path}.old-{os.getpid()}"
        os.makedirs(stale)
        (tmp_path / f"state.old-{os.getpid()}" / "junk").write_text("x")
        path = save_pytree(str(tmp_path / "state"),
                           {"a": np.arange(3.0) + 1})
        restored = load_pytree(path, tree)
        np.testing.assert_array_equal(restored["a"], np.arange(3.0) + 1)
        assert not os.path.isdir(stale)

    def test_unset_backend_raises_lifecycle_error(self):
        backend = create_backend({"type": "jax",
                                  "model": {"class": CooledRoom}})
        with pytest.raises(RuntimeError, match="setup_optimization"):
            backend.warm_state()
        with pytest.raises(RuntimeError, match="setup_optimization"):
            backend.set_warm_state({})
