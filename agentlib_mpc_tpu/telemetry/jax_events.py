"""JAX compile/retrace profiling hooks (``jax.monitoring`` listeners).

Cache misses are the dominant mystery latency of a jitted MPC stack: a
config change that perturbs a static argument (solver options, horizon,
shapes) silently retraces and recompiles the whole interior-point program
(tens of seconds), and nothing in the old ``stats_history`` could say so.
These hooks turn JAX's internal monitoring events into registry metrics:

- ``jax_traces_total{entry_point=...}`` — jaxpr traces (every ``jit``
  cache miss traces; inner jits of one entry point each count)
- ``jax_retraces_total{entry_point=...}`` — traces for an entry point that
  had already traced in an *earlier* instrumented call: the "why is this
  warm call slow" alarm
- ``jax_compiles_total{entry_point=...}`` / ``jax_compile_seconds_total``
  — XLA backend compiles and their latency
- ``jax_trace_seconds_total{entry_point=...}`` — Python tracing latency
- ``jax_lower_seconds_total{entry_point=...}`` — jaxpr→MLIR lowering
  latency (the third cold-start phase besides trace and compile)
- ``jax_cache_events_total{event=...}`` — persistent-compilation-cache
  activity (hits/misses/requests)

``entry_point`` is the innermost active telemetry span
(:func:`agentlib_mpc_tpu.telemetry.spans.current_span`) at the moment the
event fires — the instrumented call sites (solver, backends, fused ADMM,
bench) each wrap their jit dispatch in a span, so compile time lands on the
call that paid it.  Events outside any span are attributed to
``"(unscoped)"``.

Retrace classification needs a call boundary (one trace batch fires several
events): events within the *same span instance* as the scope's previous
trace batch belong to that batch; a trace event from a *new* span instance
of an already-traced scope is a retrace.  The scope identity is the span's
``(name, labels)`` — two first-time traces under the same span *name* but
different labels (``backend.solve{backend=JAXBackend}`` vs
``{backend=MHEBackend}``, or the MINLP relaxed/fixed phases) are distinct
programs and must not read as retraces of each other.  Unscoped events
cannot be batch-separated and are never classified as retraces (documented
in ``docs/telemetry.md``).

Install once per process via
:func:`agentlib_mpc_tpu.utils.jax_setup.enable_compile_profiling` (or
:func:`install` directly); listeners respect the registry's enabled flag,
so installing is safe even when telemetry is off.
"""

from __future__ import annotations

import threading

from agentlib_mpc_tpu.telemetry import registry as _registry_mod
from agentlib_mpc_tpu.telemetry import spans as _spans

TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
LOWER_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
CACHE_EVENT_PREFIX = "/jax/compilation_cache/"

UNSCOPED = "(unscoped)"

_lock = threading.Lock()
_installed = False
_registry: "_registry_mod.MetricsRegistry | None" = None

#: (span name, canonical labels) -> span seq of the most recent trace
#: batch (retrace detection); grows one entry per distinct traced scope
_last_trace_span: dict[tuple, "int | None"] = {}


def _declare(reg: _registry_mod.MetricsRegistry) -> dict:
    return {
        "traces": reg.counter(
            "jax_traces_total", "jaxpr traces (jit cache misses)"),
        "retraces": reg.counter(
            "jax_retraces_total",
            "traces of an entry point that had already traced once"),
        "compiles": reg.counter(
            "jax_compiles_total", "XLA backend compiles"),
        "compile_seconds": reg.counter(
            "jax_compile_seconds_total", "XLA backend compile latency"),
        "trace_seconds": reg.counter(
            "jax_trace_seconds_total", "Python jaxpr tracing latency"),
        "lower_seconds": reg.counter(
            "jax_lower_seconds_total", "jaxpr->MLIR lowering latency"),
        "cache_events": reg.counter(
            "jax_cache_events_total",
            "persistent compilation cache activity"),
    }


def _scope() -> "tuple[str, tuple, int | None]":
    """(entry-point name for metric labels, full scope key for retrace
    detection, span instance id)."""
    sp = _spans.current_span()
    if sp is None:
        return UNSCOPED, (UNSCOPED,), None
    key = (sp.name, tuple(sorted((str(k), str(v))
                                 for k, v in sp.labels.items())))
    return sp.name, key, sp.seq


def _on_duration(name: str, secs: float, **kwargs) -> None:
    reg = _registry
    if reg is None or not reg._enabled:
        return
    # one atomic read of the binding: install() swaps the whole dict, so a
    # concurrent re-install can never expose a half-built mapping here
    m = _metrics
    if not m:
        return
    if name == TRACE_EVENT:
        scope, key, sid = _scope()
        m["traces"].inc(entry_point=scope)
        m["trace_seconds"].inc(secs, entry_point=scope)
        with _lock:
            if key not in _last_trace_span:
                _last_trace_span[key] = sid
            elif sid is not None and _last_trace_span[key] != sid:
                _last_trace_span[key] = sid
                m["retraces"].inc(entry_point=scope)
    elif name == COMPILE_EVENT:
        scope, _key, _sid = _scope()
        m["compiles"].inc(entry_point=scope)
        m["compile_seconds"].inc(secs, entry_point=scope)
    elif name == LOWER_EVENT:
        scope, _key, _sid = _scope()
        m["lower_seconds"].inc(secs, entry_point=scope)


def _on_event(name: str, **kwargs) -> None:
    reg = _registry
    if reg is None or not reg._enabled:
        return
    m = _metrics
    if m and name.startswith(CACHE_EVENT_PREFIX):
        m["cache_events"].inc(event=name[len(CACHE_EVENT_PREFIX):])


_metrics: dict = {}


def install(registry: "_registry_mod.MetricsRegistry | None" = None
            ) -> _registry_mod.MetricsRegistry:
    """Register the ``jax.monitoring`` listeners (idempotent). Returns the
    registry the hooks write into. Imports jax lazily so the telemetry
    package stays importable in jax-free tooling contexts."""
    global _installed, _registry, _metrics
    reg = registry or _registry_mod.DEFAULT
    with _lock:
        # build the family dict fully, then swap the binding in one
        # assignment — listeners on other threads read the binding once
        # and never see a half-built mapping
        new_metrics = _declare(reg)
        _registry = reg
        _metrics = new_metrics
        if _installed:
            return reg
        import jax.monitoring as mon

        mon.register_event_duration_secs_listener(_on_duration)
        mon.register_event_listener(_on_event)
        _installed = True
    return reg


def installed() -> bool:
    return _installed


def reset_scopes() -> None:
    """Forget which entry points have traced (so the next trace counts as a
    first trace, not a retrace) — test isolation helper."""
    with _lock:
        _last_trace_span.clear()
