"""The serving front door: join / leave / submit / serve_round.

One :class:`ServingPlane` hosts many structure buckets, each a
:class:`~agentlib_mpc_tpu.serving.slots.SlotPlane` over a fused engine
acquired through the fingerprint-keyed
:class:`~agentlib_mpc_tpu.serving.cache.CompileCache`. The request path:

1. ``join(spec)`` — fingerprint the tenant's problem, find (cache hit)
   or build (miss: certify + trace + compile + warm) the bucket engine,
   splice the tenant into a padded slot. A structurally-identical
   rejoin is a measured cache hit: join latency is the splice, not the
   compile.
2. ``submit(tenant_id, theta)`` — enqueue one solve request (bounded
   queue, per-tenant deadline, coalescing). A shed request walks the
   tenant's PR 2 degradation ladder immediately and returns the
   resulting :class:`~agentlib_mpc_tpu.resilience.guard.GuardDecision`.
3. ``serve_round()`` — drain the queue, splice fresh parameters, run
   one fused round per touched bucket through the (donated, pipelined)
   dispatcher, assess every delivered result against the tenant's
   guard, return per-tenant :class:`RoundResult`\\ s.

Capacity: a full bucket grows to the next
:func:`~agentlib_mpc_tpu.parallel.multihost.serving_slot_multiple`
multiple — a new (cached-by-capacity) engine, with sitting tenants
migrated; their warm starts reset (documented cost of growth, amortized
by sizing ``initial_capacity``).

Survivability (the PR 8 layer, ``docs/serving.md`` "Surviving
failures"):

* ``health_policy=`` arms the per-tenant
  :class:`~agentlib_mpc_tpu.serving.health.HealthLedger`: a
  persistently sick tenant (guard-rejected results OR a lane the fused
  quarantine carries round after round) walks quarantine → eviction
  (lane masked out; its submissions shed into its guard ladder) →
  probation re-admission (fresh-warm-start splice, zero retraces).
* ``watchdog_timeout_s=`` arms the dispatch watchdog: a hung in-flight
  round times out, its tenants shed into their ladders, and the
  dispatcher permanently falls back to synchronous dispatch — no
  exception escapes ``serve_round``.
* ``save_checkpoint``/``restore_checkpoint`` persist the whole plane
  (occupancy, warm starts, ladders, queue carryover); restore
  reconstructs buckets through the compile cache, so crash recovery is
  cached-join splices, not cold compiles.
"""

from __future__ import annotations

import logging
import math
import time
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.ops.solver import INIT_POINT_SOURCES
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
)
from agentlib_mpc_tpu.resilience.guard import (
    ActuationGuard,
    DegradationPolicy,
)
from agentlib_mpc_tpu.serving.admission import AdmissionQueue, SolveRequest
from agentlib_mpc_tpu.serving.cache import CompileCache
from agentlib_mpc_tpu.serving.dispatch import PipelinedDispatcher, RoundTimeout
from agentlib_mpc_tpu.serving.fingerprint import TenantSpec, bucket_key
from agentlib_mpc_tpu.serving.health import HealthLedger, HealthPolicy
from agentlib_mpc_tpu.serving.slots import SlotPlane, tree_repeat, tree_row
from agentlib_mpc_tpu.telemetry.slo import SLOPolicy, SLOTracker

logger = logging.getLogger(__name__)


class JoinReceipt(NamedTuple):
    tenant_id: str
    bucket: str              # bucket digest (artifact/log key)
    #: slot index, or -1 for a CAPACITY-SHED join: the bucket growth
    #: (or initial build) that would have admitted this tenant was
    #: refused by the memory certificate — the tenant is registered and
    #: its submissions shed into its guard ladder until capacity frees
    #: (``readmit_tenant``), exactly like a health eviction
    slot: int
    capacity: int
    #: the engine came out of the compile cache (a structurally
    #: identical problem — e.g. this tenant rejoining — was served
    #: before); False = certify + trace + compile were paid
    engine_cached: bool
    #: wall seconds of the whole join (engine acquisition + splice)
    latency_s: float


class RoundResult(NamedTuple):
    """What the plane tells a tenant's actuator after a round."""

    #: actuate | replay | hold | fallback (guard ladder vocabulary)
    action: str
    #: controls to apply (the solve's u0 for ``actuate``, the guard's
    #: degraded controls otherwise; None = nothing to actuate)
    controls: "dict | None"
    healthy: bool
    reasons: tuple = ()
    #: raw per-tenant solve stats (None for shed requests)
    stats: "dict | None" = None


class ServingPlane:
    def __init__(self,
                 admm_options: FusedADMMOptions = FusedADMMOptions(),
                 slot_multiple: "int | None" = None,
                 initial_capacity: "int | None" = None,
                 pipelined: "bool | str" = "auto",
                 donate: "bool | str" = "auto",
                 queue_limit: int = 1024,
                 default_deadline_s: "float | None" = None,
                 guard_policy: DegradationPolicy = DegradationPolicy(),
                 warm_on_build: bool = True,
                 health_policy: "HealthPolicy | None" = None,
                 watchdog_timeout_s: "float | None" = None,
                 max_engines: "int | None" = None,
                 cache: "CompileCache | None" = None,
                 mesh=None,
                 engine_store=None,
                 memory_certify: str = "auto",
                 hbm_bytes: "int | str | None" = "auto",
                 slo_policy: "SLOPolicy | None" = None,
                 profile_every: "int | None" = None,
                 autopilot=None,
                 warmstart: "bool | str" = "auto",
                 warmstart_tape: bool = False):
        #: a 1-D agent mesh (``multihost.fleet_mesh``): every bucket
        #: engine is built sharded over it (``FusedADMM(mesh=...)``) and
        #: slot capacities are rounded to the mesh-aware
        #: ``serving_slot_multiple(mesh)`` so joins/leaves stay lane
        #: splices on the sharded engine — a serving bucket sits on a
        #: sharded engine unchanged
        self.mesh = mesh
        if slot_multiple is None:
            from agentlib_mpc_tpu.parallel.multihost import (
                serving_slot_multiple,
            )

            slot_multiple = serving_slot_multiple(mesh)
        elif mesh is not None and \
                int(slot_multiple) % max(1, int(mesh.devices.size)):
            raise ValueError(
                f"slot_multiple={slot_multiple} is not a multiple of "
                f"the {int(mesh.devices.size)}-device mesh — sharded "
                f"bucket capacities must divide the mesh "
                f"(multihost.serving_slot_multiple(mesh))")
        # "auto" resolves by backend (the fused_ls_jacobian pattern): the
        # depth-1 pipeline + donated carry pay off where the device
        # executes while the host decodes (accelerators); on CPU the
        # measured A/B is parity-to-negative — two rounds in flight
        # double the live state working set while donation is a no-op
        # (PERF.md round 9) — so the synchronous loop is the default
        import jax

        on_accel = jax.default_backend() != "cpu"
        if pipelined == "auto":
            pipelined = on_accel
        if donate == "auto":
            donate = on_accel
        self.admm_options = admm_options
        self.slot_multiple = max(1, int(slot_multiple))
        # every capacity is a slot-multiple so the agent axis can shard
        # (the serving_slot_multiple contract) — a user-supplied
        # initial_capacity is rounded UP, never taken verbatim
        want = (self.slot_multiple if initial_capacity is None
                else int(initial_capacity))
        self.initial_capacity = self.slot_multiple * math.ceil(
            max(want, 1) / self.slot_multiple)
        self.donate = bool(donate)
        self.warm_on_build = bool(warm_on_build)
        self.guard_policy = guard_policy
        #: pass a shared cache to model a supervisor restart (the
        #: crash-recovery bench); cross-process the persistent XLA
        #: cache plays this role
        self.cache = cache if cache is not None \
            else CompileCache(max_engines=max_engines)
        #: cross-process warm-restore tier: ``True`` enables the
        #: default on-disk store (next to the persistent XLA cache), a
        #: path/EngineStore selects one explicitly. Cold builds export
        #: their compiled step (portable StableHLO) into the store; a
        #: FRESH process's engine acquisition then revives the engine —
        #: no certification, no solver tracing, one persistent-cache-
        #: covered XLA compile — so ``restore_checkpoint`` after real
        #: process death is cache-hit splices, not cold builds
        #: (docs/serving.md "Cross-process restore")
        from agentlib_mpc_tpu.serving.store import EngineStore

        if engine_store is None or engine_store is False:
            self.engine_store = None
        elif isinstance(engine_store, EngineStore):
            self.engine_store = engine_store
        elif engine_store is True or engine_store == "auto":
            self.engine_store = EngineStore()
        else:
            self.engine_store = EngineStore(str(engine_store))
        #: memory-capacity consult (ISSUE 13): bucket engines carry the
        #: static per-device peak-bytes certificate
        #: (``lint/jaxpr/memory.py``) and the plane projects it before
        #: GROWING a bucket — a join whose grown engine would exceed
        #: ``hbm_bytes`` is shed into the tenant's PR 2 guard ladder
        #: (JoinReceipt.slot == -1) instead of OOMing the round.
        #: ``hbm_bytes="auto"`` reads the backend device's reported
        #: capacity (None on CPU → consult disabled); an int forces a
        #: budget (tests, planned deployments below the physical HBM).
        if memory_certify not in ("auto", "require", "off"):
            raise ValueError(
                f"memory_certify must be 'auto', 'require' or 'off', "
                f"got {memory_certify!r}")
        self.memory_certify = memory_certify
        if hbm_bytes == "auto":
            from agentlib_mpc_tpu.lint.jaxpr.memory import (
                device_hbm_bytes,
            )

            hbm_bytes = device_hbm_bytes() \
                if memory_certify != "off" else None
        self.hbm_bytes = int(hbm_bytes) if hbm_bytes else None
        self.dispatcher = PipelinedDispatcher(pipelined,
                                              timeout_s=watchdog_timeout_s)
        self.queue = AdmissionQueue(queue_limit, default_deadline_s)
        self._health = None if health_policy is None \
            else HealthLedger(health_policy)
        self._buckets: dict = {}          # BucketKey -> SlotPlane
        self._tenant_bucket: dict = {}    # tenant_id -> BucketKey
        self._specs: dict = {}            # tenant_id -> TenantSpec
        self._guards: dict = {}           # tenant_id -> ActuationGuard
        #: health-evicted tenants: registered (spec + guard + ladder)
        #: but occupying no slot; tenant_id -> BucketKey
        self._evicted: dict = {}
        #: results decoded outside serve_round (growth/leave flushes),
        #: merged into the next serve_round return
        self._carryover: dict = {}
        #: tenants whose submission was rejected at the door this round
        #: (non-finite theta) — consumed into the health ledger at the
        #: next assessment so a healthy stale-theta lane result cannot
        #: mask a persistently poisoned feed
        self._sick_marks: set = set()
        self.rounds = 0
        #: serve_round() calls — the flight recorder's round stamp and
        #: the SLO plane's window clock (``rounds`` above counts fused
        #: dispatches, one per TOUCHED bucket)
        self.served_rounds = 0
        #: per-tenant SLO / error-budget accounting (ISSUE 15), fed
        #: purely from the results this plane already produces; the
        #: report is recomputable offline from the journal's
        #: ``serve.round`` events (telemetry.slo.slo_from_events)
        self.slo = SLOTracker(slo_policy if slo_policy is not None
                              else SLOPolicy())
        self._slo_policy_journaled = False
        #: SLO autopilot (ISSUE 17): a hysteretic feedback controller
        #: that spends the error budget deliberately — reads the
        #: tracker's fast-window burn each serve_round and walks
        #: tenants up/down the quality ladder (warm-iteration caps,
        #: deadline relaxation, scenario-subtree shrink, mesh
        #: pre-degrade). Accepts an AutopilotPolicy or a pre-built
        #: SLOAutopilot (the latter to attach mesh hooks); None
        #: disables the controller entirely.
        from agentlib_mpc_tpu.serving.autopilot import (
            AutopilotPolicy,
            SLOAutopilot,
        )

        if autopilot is None:
            self.autopilot = None
        elif isinstance(autopilot, SLOAutopilot):
            self.autopilot = autopilot
        elif isinstance(autopilot, AutopilotPolicy):
            self.autopilot = SLOAutopilot(autopilot)
        else:
            raise TypeError(
                f"autopilot must be an AutopilotPolicy, an SLOAutopilot "
                f"or None, got {type(autopilot).__name__}")
        #: periodic phase-profile capture (ISSUE 16): every K-th bucket
        #: dispatch runs under ``jax.profiler.trace`` and lands its
        #: per-phase device times in the ``phase_device_ms`` histogram
        #: (scraped like every other family) plus a ``profile.captured``
        #: journal event. The off-capture path is one modulo check; the
        #: per-executable HLO join is cached after the first capture.
        #: None (the default) disables the hook entirely.
        from agentlib_mpc_tpu.telemetry.profiler import PeriodicCapture

        n_dev = 1 if mesh is None else max(1, int(mesh.devices.size))
        self.profiler = PeriodicCapture(
            profile_every, rounds=1, n_devices=n_dev,
            mesh_shape=None if mesh is None
            else tuple(mesh.devices.shape))
        #: learned warm starts (ISSUE 19): "auto"/True looks up a
        #: fingerprint-stamped warm-start document beside the engine
        #: blobs at bucket acquisition; False never does. Documents
        #: installed directly via :meth:`install_warmstart` are used
        #: either way. ``warmstart_tape=True`` journals a
        #: ``warmstart.tape`` event per served tenant per round — the
        #: offline training set (telemetry --dataset extracts it).
        if warmstart not in (True, False, "auto"):
            raise ValueError(
                f"warmstart must be True, False or 'auto', "
                f"got {warmstart!r}")
        self._warmstart_lookup = warmstart in (True, "auto")
        self.warmstart_tape = bool(warmstart_tape)
        self._warmstarts: dict = {}       # fingerprint -> document
        self._ws_reject_streak: dict = {} # BucketKey -> consecutive
        #: consecutive rejected predicted admissions per bucket before
        #: the plane turns the predictor off (journal: warmstart.disabled)
        self.warmstart_disable_streak = 3
        # events emitted between rounds (submissions, sheds, chaos
        # injections at the submit seam) belong to the UPCOMING round
        telemetry.journal_set_round(self.served_rounds)

    # -- membership -----------------------------------------------------------

    def _register_tenant(self, tenant_id: str, key, spec: TenantSpec,
                         ) -> None:
        self._tenant_bucket[tenant_id] = key
        self._specs[tenant_id] = spec
        self._guards[tenant_id] = ActuationGuard(
            self.guard_policy, logger_=logger,
            tenant=tenant_id, bucket=key.digest)

    def join(self, spec: TenantSpec) -> JoinReceipt:
        if spec.tenant_id in self._tenant_bucket:
            raise ValueError(f"tenant {spec.tenant_id!r} already joined")
        t0 = time.perf_counter()
        spec = self._normalize_robust_spec(spec)
        key = bucket_key(spec)
        from agentlib_mpc_tpu.lint.jaxpr.memory import (
            MemoryBudgetExceeded,
        )

        bucket = self._buckets.get(key)
        cached = True
        try:
            if bucket is None:
                bucket, cached = self._acquire_bucket(key, spec,
                                                      n_needed=1)
            elif bucket.free_slots == 0:
                bucket, cached = self._acquire_bucket(
                    key, spec, n_needed=bucket.n_active + 1,
                    migrate_from=bucket)
            else:
                # joining a LIVE bucket: the compiled engine is reused
                # without even a cache lookup — still a hit in the metric
                self.cache.note_hit(label=key.digest)
        except MemoryBudgetExceeded as exc:
            # the grown (or initial) engine would exceed the device's
            # memory: shed the JOIN into the guard ladder — sitting
            # tenants keep their round; this one degrades until
            # capacity frees (readmit_tenant / the health re-admission
            # window picks it back up)
            return self._capacity_shed_join(spec, key, t0, exc)
        slot = bucket.admit(spec.tenant_id, spec.theta)
        self._note_warmstart_admission(key, bucket, spec.tenant_id, slot)
        self._register_tenant(spec.tenant_id, key, spec)
        if telemetry.enabled():
            telemetry.serving_metrics()["active"].set(
                float(bucket.n_active), bucket=key.digest)
        latency = time.perf_counter() - t0
        logger.info(
            "tenant %s joined bucket %s slot %d (%s, %.1f ms)",
            spec.tenant_id, key.digest, slot,
            "cached engine" if cached else "cold build", 1e3 * latency)
        return JoinReceipt(spec.tenant_id, key.digest, slot,
                           bucket.capacity, cached, latency)

    @staticmethod
    def _normalize_robust_spec(spec: TenantSpec) -> TenantSpec:
        """Validate a robust tenant's spec at the door (ISSUE 14).

        The degenerate single-scenario tree normalizes into a FLAT
        tenant (theta's branch axis squeezed — the S=1 path must never
        fork a second compiled program for the same structure); a real
        tree requires an (S, ...)-leading theta stack and no exchange
        couplings (``ScenarioFleet`` lifts consensus only)."""
        import dataclasses as _dc

        import jax
        import numpy as _np

        from agentlib_mpc_tpu.serving.slots import tree_row

        tree = spec.scenario_tree
        if tree is None:
            return spec
        if tree.n_scenarios == 1:
            return _dc.replace(spec, theta=tree_row(spec.theta, 0),
                               scenario_tree=None,
                               scenario_options=None)
        if spec.exchanges:
            raise ValueError(
                f"robust tenant {spec.tenant_id!r} declares exchange "
                f"couplings {sorted(spec.exchanges)} — scenario "
                f"buckets lift consensus couplings only")
        lead = _np.shape(jax.tree.leaves(spec.theta)[0])[0] \
            if jax.tree.leaves(spec.theta) else 0
        if lead != tree.n_scenarios:
            raise ValueError(
                f"robust tenant {spec.tenant_id!r} carries a "
                f"{lead}-branch theta stack for a "
                f"{tree.n_scenarios}-scenario tree — build it with "
                f"scenario.generate (scenario_thetas/ensemble_thetas)")
        return spec

    def _capacity_shed_join(self, spec: TenantSpec, key, t0: float,
                            exc) -> JoinReceipt:
        """A join the memory certificate refused: register the tenant
        (spec + guard + ladder) WITHOUT a slot — the evicted-tenant
        machinery then sheds every submission into its PR 2 guard
        ladder, and :meth:`readmit_tenant` splices it in when capacity
        frees. The sitting tenants' round is never touched."""
        self._register_tenant(spec.tenant_id, key, spec)
        self._evicted[spec.tenant_id] = key
        telemetry.journal_event(
            "certifier.refused", kind="memory", tenant=spec.tenant_id,
            bucket=key.digest, hbm_bytes=self.hbm_bytes,
            detail=str(exc)[:300])
        if telemetry.enabled():
            telemetry.counter(
                "serving_capacity_shed_joins_total",
                "joins refused by the bucket memory certificate "
                "(growth would exceed the device's HBM) and shed into "
                "the guard ladder").inc(bucket=key.digest)
        logger.warning(
            "tenant %s join shed into its guard ladder — bucket %s "
            "cannot grow within the %s-byte device memory budget: %s",
            spec.tenant_id, key.digest, self.hbm_bytes, exc)
        return JoinReceipt(spec.tenant_id, key.digest, -1, 0, False,
                           time.perf_counter() - t0)

    def leave(self, tenant_id: str) -> None:
        key = self._tenant_bucket.pop(tenant_id)
        # an evicted tenant holds no slot, and (after a checkpoint
        # restore) possibly no live bucket either — nothing to evict
        bucket = self._buckets.get(key)
        if tenant_id not in self._evicted and bucket is not None:
            bucket.evict(tenant_id)
        self._evicted.pop(tenant_id, None)
        self._specs.pop(tenant_id, None)
        self._guards.pop(tenant_id, None)
        # the SLO ledger deliberately KEEPS the departed tenant's rows:
        # error budgets are an accounting record, and dropping them
        # would make the live report diverge from the offline recompute
        # over the journal's serve.round events (the documented
        # live == offline parity). Operators can slo.forget() explicitly.
        if self._health is not None:
            self._health.forget(tenant_id)
        if bucket is None:
            return
        if telemetry.enabled():
            telemetry.serving_metrics()["active"].set(
                float(bucket.n_active), bucket=key.digest)
        if bucket.n_active == 0 and \
                key not in self._evicted.values():
            # drain the pipeline, then retire the slot plane — the
            # ENGINE stays in the compile cache, so a rejoin is a hit
            self._stash_flush(key)
            del self._buckets[key]

    def _acquire_bucket(self, key, spec: TenantSpec, n_needed: int,
                        migrate_from: "SlotPlane | None" = None,
                        capacity: "int | None" = None):
        """Find-or-build an engine with capacity for ``n_needed`` active
        tenants (rounded up to the slot multiple; an explicit
        ``capacity`` — the checkpoint-restore path — is taken verbatim);
        optionally migrate an existing full bucket's tenants into it."""
        if capacity is None:
            capacity = max(self.initial_capacity,
                           self.slot_multiple
                           * math.ceil(n_needed / self.slot_multiple))
        engine_key = (key, capacity, self._options_key(), self.donate,
                      self._mesh_key())
        # consult the sitting engine's memory certificate BEFORE paying
        # the grown build: its per-lane share projects the new capacity
        # linearly (lane-batched buffers dominate), so a doomed growth
        # sheds without tracing anything (the post-build certificate
        # check below is the exact backstop)
        if self.hbm_bytes is not None and migrate_from is not None \
                and self.memory_certify != "off":
            from agentlib_mpc_tpu.lint.jaxpr.memory import (
                MemoryBudgetExceeded,
            )

            cert = getattr(migrate_from.engine, "memory_certificate",
                           None)
            if cert is not None and cert.status != "unknown":
                projected = -(-cert.peak_bytes * int(capacity)
                              // max(migrate_from.capacity, 1))
                if projected > self.hbm_bytes:
                    raise MemoryBudgetExceeded(
                        f"growing bucket {key.digest} "
                        f"{migrate_from.capacity} → {capacity} slots "
                        f"projects ≈{projected} B peak per device "
                        f"(certified {cert.peak_bytes} B at "
                        f"{migrate_from.capacity}) against the "
                        f"{self.hbm_bytes} B budget")

        # a plane with a known memory budget needs certificates to
        # consult — "auto" engines would skip the trace on CPU
        engine_memory_certify = self.memory_certify
        if self.hbm_bytes is not None and engine_memory_certify == "auto":
            engine_memory_certify = "require"
        scen_tree = key.scenario_tree

        def make_engine(qp_fast_path: str,
                        collective_certify: str = "auto",
                        memory_certify: "str | None" = None,
                        dispatch_certify: str = "auto",
                        precision_certify: str = "auto"):
            group = AgentGroup(
                name=f"bucket-{key.digest}",
                ocp=spec.ocp, n_agents=capacity,
                couplings=dict(key.couplings),
                exchanges=dict(key.exchanges),
                solver_options=key.solver_options,
                warm_solver_options=key.warm_solver_options,
                qp_fast_path=qp_fast_path)
            resolved_memory = (engine_memory_certify
                               if memory_certify is None
                               else memory_certify)
            if scen_tree is not None:
                # robust bucket (ISSUE 14): one ScenarioFleet per
                # (structure, tree) — each lane solves the tenant's S
                # disturbance branches inside the fused robust round
                from agentlib_mpc_tpu.scenario.fleet import (
                    ScenarioFleet,
                    ScenarioFleetOptions,
                )

                return ScenarioFleet(
                    group, scen_tree,
                    (key.scenario_options
                     if key.scenario_options is not None
                     else ScenarioFleetOptions()),
                    active=jnp.zeros((capacity,), bool),
                    mesh=self.mesh,
                    collective_certify=collective_certify,
                    memory_certify=resolved_memory,
                    dispatch_certify=dispatch_certify,
                    precision_certify=precision_certify)
            return FusedADMM(
                [group], self.admm_options,
                active=[jnp.zeros((capacity,), bool)],
                donate_state=self.donate, mesh=self.mesh,
                collective_certify=collective_certify,
                memory_certify=resolved_memory,
                dispatch_certify=dispatch_certify,
                precision_certify=precision_certify)

        def warm_args(engine):
            # throwaway template inputs, mesh-placed for sharded
            # engines so the warmed executable is the serving one
            theta_b = tree_repeat(spec.theta, capacity)
            if scen_tree is not None:
                state = engine.init_state(theta_b)
                if self.mesh is not None:
                    state, theta_b = engine.shard_args(
                        self.mesh, state, theta_b)
                return state, theta_b, jnp.zeros((capacity,), bool)
            state = engine.init_state([theta_b])
            if self.mesh is not None:
                state, (theta_b,) = engine.shard_args(
                    self.mesh, state, [theta_b])
            return state, [theta_b], [jnp.zeros((capacity,), bool)]

        def build():
            engine = make_engine(key.qp_fast_path)
            if self.warm_on_build or (self.engine_store is not None
                                      and scen_tree is None):
                # pay trace+compile NOW so the cold/cached join-latency
                # split is honest and the first served round is warm.
                # Throwaway state: with donation its buffers are
                # consumed by this very step — nothing else holds them.
                state, thetas, masks = warm_args(engine)
                engine.step(state, thetas, active=masks)
            if self.engine_store is not None and scen_tree is not None:
                # the StableHLO export path is FusedADMM-shaped; robust
                # buckets rebuild warm through the in-process cache and
                # the persistent XLA cache instead (an accelerator, not
                # a dependency — same contract as a failed export)
                logger.info(
                    "bucket %s is a scenario bucket — engine-store "
                    "export skipped (persistent XLA cache still "
                    "covers crash-restart compiles)", key.digest)
            if self.engine_store is not None and scen_tree is None:
                # persist the compiled step for cross-process revival;
                # export failure must never fail a join (the store is
                # an accelerator, not a dependency)
                try:
                    from agentlib_mpc_tpu.parallel.export import (
                        export_fused_step,
                        prewarm_exported,
                    )

                    state, thetas, masks = warm_args(engine)
                    blob = export_fused_step(engine, state, thetas,
                                             active=masks)
                    # seed the persistent XLA cache with the exported
                    # twin's program: the first crash restart then
                    # compiles from disk instead of from scratch
                    prewarm_exported(blob, state, thetas, masks)
                    self.engine_store.save(store_digest, blob, {
                        "bucket": key.digest,
                        "capacity": int(capacity),
                        "donate": bool(self.donate),
                        "mesh_devices": (None if self.mesh is None else
                                         int(self.mesh.devices.size)),
                        "qp_fast_path": ("on" if engine.group_uses_qp[0]
                                         else "off"),
                        # the certified collective schedule this blob's
                        # program issues — the revival path trusts it
                        # (no re-trace) and a restore into a process
                        # whose fresh build would certify DIFFERENTLY
                        # is refused (a schedule drift across processes
                        # is the pod-hang class, ISSUE 11)
                        "collective_digest":
                            engine.collective_schedule_digest,
                        # the certified memory footprint's identity —
                        # a restore into a process whose fresh build
                        # would certify a DIFFERENT footprint (other
                        # dtypes, other capacity math) is visible the
                        # same way a schedule drift is
                        "memory_digest": engine.memory_digest,
                        # the certified dispatch schedule's identity
                        # (ISSUE 18) — a revival whose fresh build
                        # would stage the round differently (extra
                        # boundaries, a host sync) is visible the
                        # same way
                        "dispatch_digest": engine.dispatch_digest,
                        # the certified phase→dtype routing table's
                        # identity (ISSUE 20) — a revival whose fresh
                        # build would prove DIFFERENT precision
                        # routing (other phases certified narrow) is
                        # visible the same way
                        "precision_digest": engine.precision_digest,
                    })
                except Exception:  # noqa: BLE001 - store is best-effort
                    logger.warning(
                        "engine export to the store failed for bucket "
                        "%s; crash restarts will rebuild cold",
                        key.digest, exc_info=True)
            return engine

        def restore_from_store():
            loaded = self.engine_store.load(store_digest)
            if loaded is None:
                return None
            blob, meta = loaded
            try:
                from agentlib_mpc_tpu.parallel.export import (
                    install_exported_step,
                )

                # certification off: revival must stay trace-free. The
                # artifact records the schedule its program was
                # certified with at export; the engine carries that
                # digest so checkpoint/supervisor identity checks keep
                # working against revived engines.
                # revival must stay trace-free: both certifications off;
                # the artifact's recorded digests carry the identities
                engine = make_engine(meta.get("qp_fast_path", "off"),
                                     collective_certify="off",
                                     memory_certify="off",
                                     dispatch_certify="off",
                                     precision_certify="off")
                engine.collective_schedule_digest = \
                    meta.get("collective_digest")
                engine.memory_digest = meta.get("memory_digest")
                engine.dispatch_digest = meta.get("dispatch_digest")
                engine.precision_digest = meta.get("precision_digest")
                install_exported_step(
                    engine, blob,
                    warm_args=warm_args(engine) if self.warm_on_build
                    else None)
                logger.info(
                    "bucket %s revived from the engine store "
                    "(no certify/trace paid)", key.digest)
                return engine
            except Exception:  # noqa: BLE001 - fall back to cold build
                logger.warning(
                    "engine-store revival failed for bucket %s; "
                    "building cold", key.digest, exc_info=True)
                return None

        store_digest = None
        restorer = None
        if self.engine_store is not None and scen_tree is None:
            from agentlib_mpc_tpu.serving.store import EngineStore

            store_digest = EngineStore.digest(engine_key)
            restorer = restore_from_store
        engine, hit, _latency = self.cache.get_or_build(
            engine_key, build, label=key.digest, restorer=restorer)
        if self.hbm_bytes is not None:
            # exact backstop for FORCED budgets the device itself does
            # not report (the engine's own build check covers reported
            # capacities): refuse the certified-over-budget engine —
            # it stays cached, so a later retry at freed capacity is
            # still a hit
            cert = getattr(engine, "memory_certificate", None)
            if cert is not None and cert.status != "unknown" \
                    and cert.peak_bytes > self.hbm_bytes:
                from agentlib_mpc_tpu.lint.jaxpr.memory import (
                    MemoryBudgetExceeded,
                )

                raise MemoryBudgetExceeded(
                    f"bucket {key.digest} at capacity {capacity} "
                    f"certifies {cert.peak_bytes} B peak per device "
                    f"against the {self.hbm_bytes} B budget "
                    f"({cert.describe()})")
        self._attach_warmstart(key, engine)
        if scen_tree is not None:
            from agentlib_mpc_tpu.serving.slots import ScenarioSlotPlane

            bucket = ScenarioSlotPlane(engine, spec.ocp, spec.theta)
        else:
            bucket = SlotPlane(engine, spec.ocp, spec.theta)
        bucket.tape_enabled = self.warmstart_tape and scen_tree is None
        if migrate_from is not None:
            self._stash_flush(key)       # deliver the old plane's round
            for tenant_id in migrate_from.tenants:
                slot = migrate_from.slot_of(tenant_id)
                row = tree_row(migrate_from.theta_batch, slot)
                bucket.admit(tenant_id, row)
            logger.info(
                "bucket %s grew %d -> %d slots (%d tenants migrated, "
                "warm starts reset)", key.digest, migrate_from.capacity,
                capacity, len(migrate_from.tenants))
        self._buckets[key] = bucket
        return bucket, hit

    # -- learned warm starts (ISSUE 19) ---------------------------------------

    def _warmstart_for(self, key):
        """The warm-start document for a bucket's structure, if any:
        explicitly installed documents first, then the content-addressed
        artifact beside the engine blobs (``<fingerprint>.warmstart
        .json`` in the engine store)."""
        doc = self._warmstarts.get(key.structure_digest)
        if doc is not None:
            return doc
        if self._warmstart_lookup and self.engine_store is not None:
            from agentlib_mpc_tpu.ml.warmstart import load_warmstart

            doc = load_warmstart(self.engine_store,
                                 key.structure_digest)
            if doc is not None:
                # register the revived artifact so stats()["warmstart"]
                # ["installed"] reports it and later acquisitions skip
                # the store read
                self._warmstarts[doc.fingerprint] = doc
            return doc
        return None

    def _attach_warmstart(self, key, engine) -> None:
        """Install the bucket's warm-start document on a (fresh or
        cache-revived) engine. Drift — the stamp not matching the
        engine's structure — journals a refusal and serves plain
        starts; a sick artifact must degrade latency, never a join."""
        doc = self._warmstart_for(key)
        if doc is None or getattr(engine, "warmstart", None) is not None:
            return
        from agentlib_mpc_tpu.ml.warmstart import WarmstartDriftError

        try:
            engine._install_warmstart(doc)
            telemetry.journal_event(
                "warmstart.installed", bucket=key.digest,
                fingerprint=doc.fingerprint)
        except (WarmstartDriftError, ValueError) as exc:
            telemetry.journal_event(
                "warmstart.refused", bucket=key.digest,
                fingerprint=doc.fingerprint, reason=str(exc))
            logger.warning(
                "warm-start artifact refused for bucket %s: %s",
                key.digest, exc)

    def install_warmstart(self, model) -> int:
        """Register a trained warm-start document (keyed by its
        fingerprint stamp) and attach it to every live bucket of that
        structure; future bucket acquisitions pick it up too. Persists
        it beside the engine blobs when the plane has a store. Returns
        the number of live buckets it attached to."""
        from agentlib_mpc_tpu.ml.warmstart import (
            WarmstartDriftError,
            save_warmstart,
        )

        if not model.fingerprint:
            raise WarmstartDriftError(
                "refusing to install an unstamped warm-start document")
        self._warmstarts[model.fingerprint] = model
        if self.engine_store is not None:
            save_warmstart(self.engine_store, model)
        attached = 0
        for key, bucket in self._buckets.items():
            if key.structure_digest != model.fingerprint:
                continue
            engine = bucket.engine
            engine.warmstart = None          # allow re-install
            self._attach_warmstart(key, engine)
            if getattr(engine, "warmstart", None) is not None:
                bucket.refresh_warmstart()
                attached += 1
        return attached

    def set_warmstart(self, enabled: bool) -> None:
        """Flip the learned predictor on/off for every bucket — traced
        data at the next admission, never a retrace."""
        enabled = bool(enabled)
        for key, bucket in self._buckets.items():
            if getattr(bucket, "warmstart_bundle", None) is None:
                continue
            bucket.warmstart_enabled = enabled
            bucket.engine.warmstart_enabled = enabled
        if enabled:
            self._ws_reject_streak.clear()
        telemetry.journal_event("warmstart.toggled", enabled=enabled)

    def _note_warmstart_admission(self, key, bucket, tenant_id: str,
                                  slot: int) -> None:
        """Per-admission provenance bookkeeping: journal the source and
        walk the rejection streak — a predictor whose points keep
        failing the in-graph quality gate is turned OFF for the bucket
        (``warmstart.disabled``), degrading cold-start latency back to
        plain while actuation stays untouched."""
        if getattr(bucket, "warmstart_bundle", None) is None:
            return
        src = int(bucket.init_sources[slot])
        telemetry.journal_event(
            "warmstart.admission", tenant=tenant_id, bucket=key.digest,
            source=INIT_POINT_SOURCES[src])
        if src == 2 and bucket.warmstart_enabled:
            streak = self._ws_reject_streak.get(key, 0) + 1
            self._ws_reject_streak[key] = streak
            if streak >= self.warmstart_disable_streak:
                bucket.warmstart_enabled = False
                bucket.engine.warmstart_enabled = False
                telemetry.journal_event(
                    "warmstart.disabled", bucket=key.digest,
                    tenant=tenant_id, streak=streak,
                    reason="rejection_streak")
                logger.warning(
                    "bucket %s: %d consecutive predicted starts "
                    "rejected by the quality gate — predictor disabled "
                    "(plain starts)", key.digest, streak)
        elif src == 1:
            self._ws_reject_streak[key] = 0

    def _emit_warmstart_tape(self, key, bucket) -> None:
        """Journal one ``warmstart.tape`` row per tenant the bucket's
        last round served: (theta, accepted solution, iterations) —
        the offline training set (``python -m agentlib_mpc_tpu.
        telemetry --dataset`` extracts it; ``ml.training.
        fit_warmstart`` consumes it). Replay-only: training never
        hooks the live path."""
        tape = getattr(bucket, "last_round_tape", None)
        if tape is None:
            return
        bucket.last_round_tape = None
        from agentlib_mpc_tpu.ml.warmstart import flatten_theta

        state, stats = tape["state"], tape["stats"]
        iterations = int(np.asarray(stats.iterations))
        converged = bool(np.asarray(stats.converged))
        aliases = sorted(getattr(bucket.engine, "_aliases", ()))
        w = np.asarray(state.w[0])
        y = np.asarray(state.y[0])
        z = np.asarray(state.z[0])
        lam = {a: np.asarray(state.lam[a][0]) for a in aliases}
        for tenant_id, slot in tape["served"]:
            theta_row = tree_row(tape["theta"], slot)
            lam_row = (np.concatenate([lam[a][slot] for a in aliases])
                       if aliases else np.zeros((0,)))
            telemetry.journal_event(
                "warmstart.tape", tenant=tenant_id, bucket=key.digest,
                fingerprint=key.structure_digest,
                theta=np.asarray(flatten_theta(theta_row)).tolist(),
                w=w[slot].tolist(), y=y[slot].tolist(),
                z=z[slot].tolist(), lam=lam_row.tolist(),
                aliases=aliases, iterations=iterations,
                converged=converged)

    def _options_key(self):
        """Hashable identity of the engine-level options (rho may be a
        dict)."""
        opts = self.admm_options
        rho = opts.rho
        rho_key = tuple(sorted(rho.items())) if isinstance(rho, dict) \
            else float(rho)
        return opts._replace(rho=rho_key)

    def _mesh_key(self):
        """Hashable mesh identity for the engine cache: a sharded and an
        unsharded engine of the same structure are DIFFERENT compiled
        programs and must never alias in the cache."""
        if self.mesh is None:
            return None
        return (self.mesh.axis_names,
                tuple(d.id for d in self.mesh.devices.flat))

    # -- tenant health: evict / readmit ---------------------------------------

    def evict_tenant(self, tenant_id: str, reason: str = "manual") -> None:
        """Mask a tenant's lane out of its bucket WITHOUT deregistering
        it: the spec, guard ladder and health row stay, its submissions
        shed into the ladder, and :meth:`readmit_tenant` (or the health
        ledger's re-admission window) splices it back fresh. The health
        ledger calls this on its evict transition; it is public for
        operator intervention and the ``[serving.health]`` gate."""
        if tenant_id not in self._tenant_bucket:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        if tenant_id in self._evicted:
            return
        key = self._tenant_bucket[tenant_id]
        bucket = self._buckets[key]
        bucket.evict(tenant_id)
        self._evicted[tenant_id] = key
        if self._health is not None:
            self._health.force_evict(tenant_id)
        if telemetry.enabled():
            telemetry.counter(
                "serving_evictions_total",
                "tenants masked out of their bucket by the health "
                "ladder (or operator)").inc(bucket=key.digest,
                                            reason=reason)
            telemetry.serving_metrics()["active"].set(
                float(bucket.n_active), bucket=key.digest)
        telemetry.journal_event("serve.eviction", tenant=tenant_id,
                                bucket=key.digest, reason=reason)
        logger.warning("tenant %s evicted from bucket %s (%s); "
                       "submissions now shed into its guard ladder",
                       tenant_id, key.digest, reason)

    def readmit_tenant(self, tenant_id: str) -> bool:
        """Splice an evicted tenant back into its bucket with a FRESH
        warm start (the recycled-slot contract — a sick iterate must not
        come back with it). Returns False when its bucket is full (the
        caller retries later); the engine comes from the live bucket or
        the compile cache, never a rebuild."""
        key = self._evicted.get(tenant_id)
        if key is None:
            raise KeyError(f"tenant {tenant_id!r} is not evicted")
        spec = self._specs[tenant_id]
        bucket = self._buckets.get(key)
        if bucket is None:
            # every member was evicted and the last active one left:
            # the slot plane retired but the ENGINE is cached — this
            # acquisition is the measured cache-hit rejoin
            bucket, _hit = self._acquire_bucket(key, spec, n_needed=1)
        if bucket.free_slots == 0:
            return False
        slot = bucket.admit(tenant_id, spec.theta)
        self._note_warmstart_admission(key, bucket, tenant_id, slot)
        del self._evicted[tenant_id]
        if self._health is not None:
            self._health.readmitted(tenant_id)
        if telemetry.enabled():
            telemetry.counter(
                "serving_readmissions_total",
                "evicted tenants spliced back on probation").inc(
                bucket=key.digest)
            telemetry.serving_metrics()["active"].set(
                float(bucket.n_active), bucket=key.digest)
        telemetry.journal_event("serve.readmission", tenant=tenant_id,
                                bucket=key.digest, slot=slot)
        logger.info("tenant %s readmitted to bucket %s slot %d "
                    "(probation)", tenant_id, key.digest, slot)
        return True

    def _readmit_due(self) -> None:
        if self._health is None:
            return
        for tenant_id in self._health.tick_evicted():
            if tenant_id in self._evicted:
                self.readmit_tenant(tenant_id)

    # -- quality ladder (ISSUE 17) --------------------------------------------

    def _rebucket_tenant(self, tenant_id: str, spec: TenantSpec) -> bool:
        """Move a registered tenant onto a new spec — the autopilot's
        lever executor. When the new spec fingerprints into the SAME
        bucket (an L2 move, or an L1 cap equal to the current warm
        budget) this is pure bookkeeping; otherwise the tenant's lane
        is evicted from its old bucket and spliced into the new one
        through the ordinary ``_acquire_bucket``/compile-cache path —
        a cache hit after first use (the ``[serving.autopilot]`` gate
        pins the warm cycle at zero retraces). The splice resets the
        tenant's warm start (the documented cost of every migration).
        Guard/health/SLO rows are keyed by tenant id and ride along
        untouched. Returns False — with nothing changed — when the
        memory certificate refuses the target bucket."""
        if tenant_id not in self._tenant_bucket:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        spec = self._normalize_robust_spec(spec)
        old_key = self._tenant_bucket[tenant_id]
        new_key = bucket_key(spec)
        if new_key == old_key:
            self._specs[tenant_id] = spec
            return True
        if tenant_id in self._evicted:
            # no lane to move: re-key the registration so the eventual
            # re-admission splices into the NEW bucket
            self._tenant_bucket[tenant_id] = new_key
            self._specs[tenant_id] = spec
            self._evicted[tenant_id] = new_key
            return True
        from agentlib_mpc_tpu.lint.jaxpr.memory import (
            MemoryBudgetExceeded,
        )

        target = self._buckets.get(new_key)
        try:
            if target is None:
                target, _hit = self._acquire_bucket(new_key, spec,
                                                    n_needed=1)
            elif target.free_slots == 0:
                target, _hit = self._acquire_bucket(
                    new_key, spec, n_needed=target.n_active + 1,
                    migrate_from=target)
            else:
                self.cache.note_hit(label=new_key.digest)
        except MemoryBudgetExceeded as exc:
            logger.warning(
                "tenant %s re-bucket %s -> %s refused by the memory "
                "certificate (%s) — keeping the current bucket",
                tenant_id, old_key.digest, new_key.digest, exc)
            return False
        old_bucket = self._buckets.get(old_key)
        if old_bucket is not None:
            old_bucket.evict(tenant_id)
        slot = target.admit(tenant_id, spec.theta)
        self._tenant_bucket[tenant_id] = new_key
        self._specs[tenant_id] = spec
        if telemetry.enabled():
            gauge = telemetry.serving_metrics()["active"]
            gauge.set(float(target.n_active), bucket=new_key.digest)
            if old_bucket is not None:
                gauge.set(float(old_bucket.n_active),
                          bucket=old_key.digest)
        if old_bucket is not None and old_bucket.n_active == 0 \
                and old_key not in self._evicted.values():
            # retire the empty slot plane; the ENGINE stays cached, so
            # the up-move back is a hit (the zero-cold-build contract)
            self._stash_flush(old_key)
            del self._buckets[old_key]
        logger.info("tenant %s re-bucketed %s -> %s slot %d (fresh "
                    "warm start)", tenant_id, old_key.digest,
                    new_key.digest, slot)
        return True

    # -- request path ---------------------------------------------------------

    @staticmethod
    def _theta_valid(theta) -> bool:
        """NaN-free, not finite: parameter trees legitimately carry
        ±inf (unbounded state/control bounds ride in theta), so only
        NaN marks a poisoned feed."""
        import jax
        import numpy as np

        try:
            return not any(
                bool(np.any(np.isnan(np.asarray(leaf, dtype=float))))
                for leaf in jax.tree.leaves(theta))
        except (TypeError, ValueError):
            return False

    def submit(self, tenant_id: str, theta=None,
               deadline_s: "float | None" = None,
               now: "float | None" = None):
        """Enqueue one solve request. Returns None when queued; when the
        queue sheds it (overload, non-finite parameters, or the tenant
        is health-evicted), the tenant's guard ladder is walked
        immediately and the resulting degraded
        :class:`~agentlib_mpc_tpu.resilience.guard.GuardDecision`
        is returned (replay/hold controls, or fallback hand-over)."""
        if tenant_id not in self._tenant_bucket:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        if deadline_s is None:
            deadline_s = self._specs[tenant_id].deadline_s
        if self.autopilot is not None:
            # the L2 lever: relax the deadline — EXPLICIT deadlines
            # included, so an overload storm forcing tight deadlines
            # is counterable, not just the spec defaults
            deadline_s = self.autopilot.relaxed_deadline(tenant_id,
                                                         deadline_s)
        if telemetry.enabled():
            telemetry.serving_metrics()["requests"].inc()
        if tenant_id in self._evicted:
            if telemetry.enabled():
                telemetry.counter(
                    "serving_shed_total",
                    "solve requests shed to the degradation ladder"
                    ).inc(reason="evicted")
            return self._shed(tenant_id, "shed_evicted")
        if theta is not None and not self._theta_valid(theta):
            # validate at the door: a NaN/Inf parameter tree must never
            # reach a lane splice — quarantine would carry the lane, but
            # the bad data would sit in theta_batch poisoning every
            # subsequent round (and on some workloads the solve stays
            # finite, hiding the fault entirely). Counts as a sick round
            # on the health ladder: a persistently NaN-ing feed walks
            # quarantine → evict exactly like an in-solve divergence.
            if telemetry.enabled():
                telemetry.counter(
                    "serving_shed_total",
                    "solve requests shed to the degradation ladder"
                    ).inc(reason="nonfinite_theta")
            if self._health is not None:
                self._sick_marks.add(tenant_id)
            return self._shed(tenant_id, "nonfinite_theta")
        ok = self.queue.submit(SolveRequest(
            tenant_id=tenant_id, theta=theta,
            submitted_at=time.monotonic() if now is None else now,
            deadline_s=deadline_s))
        if ok:
            return None
        return self._shed(tenant_id, "shed_overload")

    def _shed(self, tenant_id: str, reason: str):
        """Walk a shed request through the tenant's degradation ladder
        (the PR 2 wiring: overload and solver failure degrade through
        one path)."""
        guard = self._guards.get(tenant_id)
        if guard is None:
            return None
        decision = guard.assess({"stats": {"success": True}},
                                precheck=(False, (reason,)))
        key = self._tenant_bucket.get(tenant_id)
        telemetry.journal_event(
            "admission.shed", tenant=tenant_id, reason=reason,
            action=decision.action,
            bucket=key.digest if key is not None else None)
        self.slo.record_result(
            tenant_id, decision.action,
            deadline_missed=(reason == "shed_deadline"))
        return decision

    def serve_round(self, now: "float | None" = None) -> dict:
        """Drain the queue and run one fused round per touched bucket.
        Returns ``{tenant_id: RoundResult}`` — in pipelined mode these
        are the results of each bucket's PREVIOUS round (plus any
        deadline-shed verdicts of this one); call :meth:`flush` to drain
        the pipeline. Never raises for a watchdogged (hung) round: the
        affected tenants shed into their ladders and the dispatcher
        falls back to synchronous dispatch."""
        t0 = time.perf_counter()
        now = time.monotonic() if now is None else now
        # stamp every event this round emits (sheds, evictions, stalls,
        # chaos injections …) with the serve-round clock
        telemetry.journal_set_round(self.served_rounds)
        if not self._slo_policy_journaled \
                and telemetry.journal_active() is not None:
            # stamp the plane's SLO policy onto the tape once, so the
            # offline recompute audits against the SAME targets and
            # windows the live report uses
            telemetry.journal_event(
                "slo.policy",
                availability_target=self.slo.policy.availability_target,
                deadline_target=self.slo.policy.deadline_target,
                windows=list(self.slo.policy.windows))
            self._slo_policy_journaled = True
        self._readmit_due()
        ready, expired = self.queue.drain(now)
        results: dict = {}
        for key, res in self._carryover.items():
            results.update(self._assess_bucket(res))
        self._carryover.clear()
        for req in expired:
            decision = self._shed(req.tenant_id, "shed_deadline")
            if decision is not None:
                results[req.tenant_id] = RoundResult(
                    action=decision.action, controls=decision.controls,
                    healthy=False, reasons=decision.reasons)
        touched = []
        for req in ready:
            if req.tenant_id in self._evicted:
                # evicted after submitting (or a restored carryover
                # request): walk the ladder instead of solving
                decision = self._shed(req.tenant_id, "shed_evicted")
                if decision is not None:
                    results[req.tenant_id] = RoundResult(
                        action=decision.action,
                        controls=decision.controls,
                        healthy=False, reasons=decision.reasons)
                continue
            key = self._tenant_bucket.get(req.tenant_id)
            if key is None:
                continue                  # left after submitting
            bucket = self._buckets[key]
            if req.theta is not None:
                bucket.update_theta(req.tenant_id, req.theta)
            if key not in touched:
                touched.append(key)
        m = telemetry.serving_metrics() if telemetry.enabled() else None
        for key in touched:
            res = self._dispatch_profiled(key, self._buckets[key])
            self.rounds += 1
            if m is not None:
                m["rounds"].inc(bucket=key.digest)
            if res is not None:
                results.update(self._assess_bucket(res))
            if self.warmstart_tape:
                self._emit_warmstart_tape(key, self._buckets[key])
        # rounds condemned by a stall in another bucket: assess as
        # failures NOW (their tenants shed into their ladders) instead
        # of leaving stale results to surface out of order at a flush
        for res in self.dispatcher.drain_failed().values():
            results.update(self._assess_bucket(res))
        if self._health is not None and self._sick_marks:
            # tenants whose only traffic this round was a rejected
            # (non-finite) submission: score the strike even though no
            # lane result carried it (a solo sick tenant must still
            # walk quarantine → evict)
            for tenant_id in tuple(self._sick_marks):
                self._sick_marks.discard(tenant_id)
                if tenant_id not in self._tenant_bucket \
                        or tenant_id in self._evicted:
                    continue
                if self._health.observe(tenant_id, True) == "evict":
                    self.evict_tenant(tenant_id, reason="health")
        if m is not None:
            m["queue_depth"].set(float(len(self.queue)))
            m["round_seconds"].observe(time.perf_counter() - t0)
        # close the SLO round and journal its tally: the serve.round
        # event is what makes slo_report() recomputable offline from
        # the flight recorder alone
        tally = self.slo.tick_round(self.served_rounds)
        if self.autopilot is not None:
            # controller step AFTER the windows advance (it reads this
            # round's burn) and BEFORE the round stamp moves forward
            # (its autopilot.move events belong to this round)
            self.autopilot.tick(self, tally)
        telemetry.journal_event(
            "serve.round", round=self.served_rounds, tally=tally,
            buckets_touched=len(touched),
            actions={tid: r.action for tid, r in results.items()})
        self.served_rounds += 1
        # between-round events (next round's submissions) stamp forward
        telemetry.journal_set_round(self.served_rounds)
        return results

    def _dispatch_profiled(self, key, bucket):
        """One bucket dispatch, routed through the periodic profiler.
        The common path (``profile_every=None`` or a non-due round) is
        the plain dispatch plus at most one integer modulo; a due round
        runs the SAME dispatch inside ``jax.profiler.trace`` and
        attributes its device time by named phase. Capture failures
        never fail the round — serving traffic outranks observability."""
        if self.profiler.every is None:
            return self.dispatcher.dispatch(key, bucket)
        hlo = None
        if self.profiler.due():
            eng = getattr(bucket, "engine", None)
            if eng is not None:
                try:
                    hlo = self.profiler.hlo_for(
                        key, eng._step, *eng._step_templates())
                except Exception:  # noqa: BLE001 — join is best-effort
                    hlo = None
        holder = {}

        def run_round():
            holder["res"] = self.dispatcher.dispatch(key, bucket)

        try:
            self.profiler.tick(run_round, hlo_text=hlo,
                               label=key.digest)
        except Exception:  # noqa: BLE001 — capture must not shed a round
            if "res" not in holder:
                holder["res"] = self.dispatcher.dispatch(key, bucket)
        return holder.get("res")

    def flush(self) -> dict:
        """Drain the dispatch pipeline: assess and return every
        in-flight round's results (empty dict when none)."""
        results: dict = {}
        for res in self.dispatcher.flush().values():
            results.update(self._assess_bucket(res))
        for res in self._carryover.values():
            results.update(self._assess_bucket(res))
        self._carryover.clear()
        return results

    def _stash_flush(self, key) -> None:
        flushed = self.dispatcher.flush(key)
        if key in flushed:
            self._carryover[key] = flushed[key]

    def _assess_bucket(self, decoded) -> dict:
        """Run each delivered result through its tenant's guard and
        shape the per-tenant verdicts. A :class:`RoundTimeout` marker
        (the watchdog declared the round dead) becomes a failed solve
        for every tenant the round served."""
        if isinstance(decoded, RoundTimeout):
            decoded = {
                tenant_id: {
                    "u0": {}, "traj": {},
                    "stats": {"success": False,
                              "watchdog_timeout": True},
                } for tenant_id, _slot in decoded.served}
        out = {}
        evictions = []
        m = telemetry.serving_metrics() if telemetry.enabled() else None
        for tenant_id, result in decoded.items():
            guard = self._guards.get(tenant_id)
            if guard is None:
                continue                  # tenant left while in flight
            spec = self._specs.get(tenant_id)
            bounds = None
            if spec is not None:
                bounds = getattr(spec.ocp, "control_bounds", None)
            stats = result.get("stats") or {}
            precheck = ((False, ("watchdog_timeout",))
                        if stats.get("watchdog_timeout") else None)
            decision = guard.assess(result, bounds, precheck=precheck)
            controls = result["u0"] if decision.action == "actuate" \
                else decision.controls
            out[tenant_id] = RoundResult(
                action=decision.action, controls=controls,
                healthy=decision.healthy, reasons=decision.reasons,
                stats=result.get("stats"))
            if m is not None:
                # labelled by guard action so availability (actuated /
                # delivered) is computable from telemetry alone
                m["solves"].inc(action=decision.action)
            self.slo.record_result(tenant_id, decision.action)
            if self._health is not None:
                sick = self._health.is_sick_result(decision.healthy,
                                                   stats)
                if tenant_id in self._sick_marks:
                    # a rejected (non-finite) submission this round: the
                    # lane's healthy stale-theta result must not mask it
                    sick = True
                    self._sick_marks.discard(tenant_id)
                if self._health.observe(tenant_id, sick) == "evict":
                    evictions.append(tenant_id)
        for tenant_id in evictions:
            if tenant_id in self._tenant_bucket \
                    and tenant_id not in self._evicted:
                self.evict_tenant(tenant_id, reason="health")
        return out

    # -- durability -----------------------------------------------------------

    def save_checkpoint(self, path: str) -> str:
        """Durable snapshot of the whole plane (crash-safe swap); see
        :func:`agentlib_mpc_tpu.serving.checkpoint.save_plane`."""
        from agentlib_mpc_tpu.serving.checkpoint import save_plane

        return save_plane(self, path)

    def restore_checkpoint(self, path: str, specs):
        """Rebuild a checkpointed plane into this (empty) one through
        the compile-cache path; returns a
        :class:`~agentlib_mpc_tpu.serving.checkpoint.RestoreReport`
        whose ``total_s`` is the measured recovery time (MTTR)."""
        from agentlib_mpc_tpu.serving.checkpoint import restore_plane

        return restore_plane(self, path, specs)

    def _export_active(self) -> None:
        if telemetry.enabled():
            gauge = telemetry.serving_metrics()["active"]
            for key, bucket in self._buckets.items():
                gauge.set(float(bucket.n_active), bucket=key.digest)

    # -- introspection --------------------------------------------------------

    @property
    def tenants(self) -> tuple:
        """Currently admitted tenant ids (health-evicted ones included —
        they are still the plane's responsibility)."""
        return tuple(self._tenant_bucket)

    @property
    def evicted_tenants(self) -> tuple:
        return tuple(self._evicted)

    def health_state(self, tenant_id: str) -> "str | None":
        """The tenant's health-ladder state, or None when the ledger is
        disabled."""
        if self._health is None:
            return None
        return self._health.state(tenant_id)

    def slo_report(self) -> dict:
        """Per-tenant SLO / error-budget report
        (:meth:`~agentlib_mpc_tpu.telemetry.slo.SLOTracker.report`):
        availability and deadline objectives, multi-window burn rates,
        a fleet roll-up. Fed purely from the per-round results, so the
        identical report is recomputable offline from the journal
        (``telemetry.slo.slo_from_events`` /
        ``python -m agentlib_mpc_tpu.telemetry --slo <journal>``)."""
        return self.slo.report()

    def stats(self) -> dict:
        return {
            "tenants": len(self._tenant_bucket),
            "evicted": len(self._evicted),
            "buckets": {
                key.digest: {"capacity": b.capacity,
                             "active": b.n_active,
                             "rounds": b.rounds_served}
                for key, b in self._buckets.items()},
            "cache": {"engines": len(self.cache),
                      "hits": self.cache.hits,
                      "misses": self.cache.misses,
                      "evictions": self.cache.evictions},
            "queue": {"pending": len(self.queue),
                      "submitted": self.queue.submitted,
                      "shed_overload": self.queue.shed_overload,
                      "shed_deadline": self.queue.shed_deadline},
            "watchdog": {"stalls": self.dispatcher.stalls,
                         "sync_fallback": self.dispatcher.sync_fallback},
            "warmstart": {
                "installed": sorted(self._warmstarts),
                "buckets": {
                    key.digest: {
                        "enabled": bool(b.warmstart_enabled),
                        "reject_streak":
                            self._ws_reject_streak.get(key, 0),
                        "admissions": {
                            name: int((b.init_sources[
                                np.asarray(b.mask)] == code).sum())
                            for code, name in
                            enumerate(INIT_POINT_SOURCES)},
                    }
                    for key, b in self._buckets.items()
                    if getattr(b, "warmstart_bundle", None) is not None},
            },
            "memory": {
                "hbm_bytes": self.hbm_bytes,
                "certified_peak_bytes": {
                    key.digest: getattr(
                        b.engine, "memory_certificate", None)
                    and b.engine.memory_certificate.peak_bytes
                    for key, b in self._buckets.items()},
            },
            "rounds": self.rounds,
        }
