"""ML surrogate core: serialization round-trips, predictor equivalence
against the originating training stacks (sklearn, torch), and hybrid NARX
model semantics.

Mirrors the reference's serialization tests
(``tests/test_serialized_{ann,gpr,linreg}.py``: serialize → JSON →
deserialize → compare predictions; CasADi-predictor vs native equivalence)
with JAX predictors in place of CasADi graphs.
"""

import numpy as np
import pytest

from agentlib_mpc_tpu.ml import (
    Feature,
    OutputFeature,
    SerializedANN,
    SerializedGPR,
    SerializedLinReg,
    SerializedMLModel,
    column_order,
    load_serialized_model,
    make_predictor,
)
from agentlib_mpc_tpu.models.ml_model import MLModel
from agentlib_mpc_tpu.models.model import ModelEquations
from agentlib_mpc_tpu.models.variables import control_input, parameter, state


def _features():
    return ({"u": Feature(name="u", lag=2)},
            {"x": OutputFeature(name="x", lag=2, output_type="difference",
                                recursive=True)})


class TestSchema:
    def test_column_order(self):
        inputs, output = _features()
        assert column_order(inputs, output) == ["u", "u_1", "x", "x_1"]

    def test_non_recursive_difference_rejected(self):
        with pytest.raises(ValueError, match="absolute"):
            OutputFeature(name="y", output_type="difference",
                          recursive=False)

    def test_lags_per_variable(self):
        inputs, output = _features()
        m = SerializedLinReg(dt=10.0, inputs=inputs, output=output,
                             coef=[[1.0, 0.0, 1.0, 0.0]], intercept=[0.0])
        assert m.lags_per_variable() == {"u": 2, "x": 2}


class TestRoundTrips:
    def test_linreg_roundtrip(self):
        inputs, output = _features()
        m = SerializedLinReg(dt=10.0, inputs=inputs, output=output,
                             coef=[[0.5, -0.25, 1.5, 0.75]], intercept=[0.1])
        m2 = SerializedMLModel.from_json(m.to_json())
        assert isinstance(m2, SerializedLinReg)
        assert m2.dt == 10.0
        assert m2.output["x"].output_type == "difference"
        x = np.array([1.0, 2.0, 3.0, 4.0])
        p1, p2 = make_predictor(m), make_predictor(m2)
        np.testing.assert_allclose(p1.apply(p1.params, x),
                                   p2.apply(p2.params, x))

    def test_ann_roundtrip_file(self, tmp_path):
        rng = np.random.default_rng(0)
        inputs, output = _features()
        m = SerializedANN(
            dt=10.0, inputs=inputs, output=output,
            weights=[rng.normal(size=(4, 8)).tolist(),
                     rng.normal(size=(8, 1)).tolist()],
            biases=[rng.normal(size=8).tolist(),
                    rng.normal(size=1).tolist()],
            activations=["tanh", "linear"])
        path = tmp_path / "ann.json"
        m.save(path)
        m2 = load_serialized_model(path)
        x = rng.normal(size=4)
        p1, p2 = make_predictor(m), make_predictor(m2)
        np.testing.assert_allclose(np.asarray(p1.apply(p1.params, x)),
                                   np.asarray(p2.apply(p2.params, x)),
                                   rtol=1e-6)

    def test_gpr_roundtrip(self):
        rng = np.random.default_rng(1)
        inputs, output = _features()
        m = SerializedGPR(dt=10.0, inputs=inputs, output=output,
                          x_train=rng.normal(size=(20, 4)).tolist(),
                          alpha=rng.normal(size=20).tolist(),
                          constant_value=2.0, length_scale=[1.0, 2., 3., 4.],
                          normalize=True,
                          mean=[0.1] * 4, std=[1.1] * 4, scale=2.5)
        m2 = SerializedMLModel.from_dict(m.to_dict())
        x = rng.normal(size=4)
        p1, p2 = make_predictor(m), make_predictor(m2)
        np.testing.assert_allclose(np.asarray(p1.apply(p1.params, x)),
                                   np.asarray(p2.apply(p2.params, x)),
                                   rtol=1e-6)


class TestSklearnEquivalence:
    """Predictor must reproduce the originating sklearn model — the
    reference's CasADi-vs-native equivalence tests."""

    @pytest.mark.filterwarnings(
        "ignore::sklearn.exceptions.ConvergenceWarning")
    def test_gpr_matches_sklearn(self):
        # sklearn's own hyperparameter optimizer grumbles on this tiny
        # fixture; the equivalence assertion below is what matters
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import RBF, ConstantKernel, \
            WhiteKernel

        rng = np.random.default_rng(2)
        X = rng.uniform(-2, 2, size=(30, 3))
        y = np.sin(X[:, 0]) + 0.5 * X[:, 1] ** 2 - X[:, 2]
        kernel = ConstantKernel() * RBF(length_scale=[1.0] * 3) \
            + WhiteKernel(noise_level=1e-4)
        gpr = GaussianProcessRegressor(kernel=kernel).fit(X, y)
        m = SerializedGPR.from_sklearn(
            gpr, dt=1.0,
            inputs={"a": Feature(name="a"), "b": Feature(name="b"),
                    "c": Feature(name="c")},
            output={"x": OutputFeature(name="x", output_type="absolute")})
        pred = make_predictor(m)
        Xq = rng.uniform(-2, 2, size=(10, 3))
        want = gpr.predict(Xq)
        got = np.array([np.asarray(pred.apply(pred.params, x))[0]
                        for x in Xq])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_linreg_matches_sklearn(self):
        from sklearn.linear_model import LinearRegression

        rng = np.random.default_rng(3)
        X = rng.normal(size=(40, 4))
        y = X @ np.array([1.0, -2.0, 0.5, 3.0]) + 0.7
        lr = LinearRegression().fit(X, y)
        inputs, output = _features()
        m = SerializedLinReg.from_sklearn(lr, dt=1.0, inputs=inputs,
                                          output=output)
        pred = make_predictor(m)
        for x in rng.normal(size=(5, 4)):
            np.testing.assert_allclose(
                np.asarray(pred.apply(pred.params, x))[0],
                lr.predict(x[None, :])[0], rtol=1e-6)


class TestTorchEquivalence:
    def test_ann_matches_torch(self):
        torch = pytest.importorskip("torch")
        import torch.nn as nn

        torch.manual_seed(0)
        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(),
                            nn.Linear(16, 8), nn.Tanh(),
                            nn.Linear(8, 1))
        inputs, output = _features()
        m = SerializedANN.from_torch(net, dt=1.0, inputs=inputs,
                                     output=output)
        pred = make_predictor(m)
        x = np.linspace(-1, 1, 4)
        want = net(torch.tensor(x, dtype=torch.float32)).detach().numpy()
        got = np.asarray(pred.apply(pred.params, x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# -- hybrid NARX model --------------------------------------------------------

def _exact_linreg():
    """Surrogate encoding exactly x_next = x + 0.5*u(t) + 0.25*u(t-1)."""
    return SerializedLinReg(
        dt=10.0,
        inputs={"u": Feature(name="u", lag=2)},
        output={"x": OutputFeature(name="x", lag=1,
                                   output_type="difference",
                                   recursive=True)},
        coef=[[0.5, 0.25, 0.0]], intercept=[0.0])


class TwoStateHybrid(MLModel):
    """x learned (NARX), w white-box ODE dw/dt = -k*w + u."""

    inputs = [control_input("u", 0.0, lb=-1.0, ub=1.0)]
    states = [state("x", 1.0), state("w", 2.0)]
    parameters = [parameter("k", 0.1)]
    dt = 10.0
    ml_model_sources = [_exact_linreg()]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("w", -v.k * v.w + v.u)
        return eq


class TestMLModel:
    def test_classification(self):
        m = TwoStateHybrid()
        assert m.narx_state_names == ["x"]
        assert m.wb_state_names == ["w"]
        assert m.get_lags_per_variable() == {"u": 2}
        assert m.max_lag == 2

    def test_exact_narx_step(self):
        m = TwoStateHybrid()
        hist = m.init_history({"x": 1.0, "w": 2.0, "u": 0.0})
        hist, nxt, _ = m.simulate_ml_step(hist, [0.1], {"u": 1.0})
        # x: 1 + 0.5*1 + 0.25*0 = 1.5
        assert float(nxt["x"]) == pytest.approx(1.5)
        # w: dw/dt = -0.1*w + 1 from w=2 over 10s (RK4 ≈ exact)
        want_w = (2.0 - 10.0) * np.exp(-0.1 * 10.0) + 10.0
        assert float(nxt["w"]) == pytest.approx(want_w, rel=1e-3)
        # second step uses the lagged u
        _, nxt2, _ = m.simulate_ml_step(hist, [0.1], {"u": 0.0})
        # x: 1.5 + 0.5*0 + 0.25*1 = 1.75
        assert float(nxt2["x"]) == pytest.approx(1.75)

    def test_dt_mismatch_rejected(self):
        bad = _exact_linreg()
        bad.dt = 42.0
        with pytest.raises(ValueError, match="dt"):
            TwoStateHybrid(ml_models=[bad])

    def test_duplicate_output_rejected(self):
        with pytest.raises(ValueError, match="two ML models"):
            TwoStateHybrid(ml_models=[_exact_linreg(), _exact_linreg()])

    def test_recursive_output_must_be_state(self):
        m = _exact_linreg()
        m.output = {"nope": OutputFeature(name="nope", output_type="difference",
                                          recursive=True)}
        with pytest.raises(ValueError, match="declared state"):
            TwoStateHybrid(ml_models=[m])

    def test_hot_swap_changes_prediction(self):
        m = TwoStateHybrid()
        hist = m.init_history({"x": 1.0, "u": 1.0})
        _, n1, _ = m.simulate_ml_step(hist, [0.1], {"u": 1.0})
        new = _exact_linreg()
        new.coef = [[1.0, 0.0, 0.0]]  # x_next = x + u
        m.update_ml_models(new)
        _, n2, _ = m.simulate_ml_step(hist, [0.1], {"u": 1.0})
        assert float(n2["x"]) == pytest.approx(2.0)
        assert float(n1["x"]) != float(n2["x"])

    def test_jit_and_grad_through_step(self):
        import jax
        import jax.numpy as jnp

        m = TwoStateHybrid()

        @jax.jit
        def rollout(u_seq, p, ml_params):
            hist = m.init_history({"x": 1.0, "w": 2.0})

            def body(h, u):
                h = dict(h)
                h["u"] = h["u"].at[0].set(u)
                nxt, _ = m.ml_step(h, p, ml_params=ml_params)
                return m.advance_history(h, dict(nxt)), nxt["x"]

            _, xs = jax.lax.scan(body, hist, u_seq)
            return xs[-1]

        u = jnp.ones(5)
        p = jnp.asarray([0.1])
        val = rollout(u, p, m.ml_params)
        g = jax.grad(rollout)(u, p, m.ml_params)
        assert np.isfinite(float(val))
        # last u affects x through lag-0 coefficient 0.5 at the final step
        assert float(g[-1]) == pytest.approx(0.5)
