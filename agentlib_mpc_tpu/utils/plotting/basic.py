"""Shared plot style (reference ``utils/plotting/basic.py:27-58``)."""

from __future__ import annotations

import dataclasses
from typing import Optional

#: palette in the spirit of the reference's EBC colors
COLORS = {
    "blue": "#00549f",
    "light_blue": "#8ebae5",
    "red": "#cc071e",
    "green": "#57ab27",
    "orange": "#f6a800",
    "grey": "#646567",
    "black": "#000000",
}


@dataclasses.dataclass
class Style:
    color_cycle: tuple = tuple(COLORS.values())
    grid: bool = True
    figsize: tuple = (8.0, 4.5)
    dpi: int = 120
    font_size: int = 10


def _use_agg():
    import matplotlib

    if matplotlib.get_backend().lower() not in ("agg",):
        try:  # headless environments
            matplotlib.use("Agg", force=False)
        except Exception:  # pragma: no cover
            pass


def make_fig(style: Optional[Style] = None, rows: int = 1, cols: int = 1):
    """(fig, axes) with the shared style applied (reference ``make_fig``)."""
    _use_agg()
    import matplotlib.pyplot as plt
    from cycler import cycler

    style = style or Style()
    fig, axes = plt.subplots(rows, cols, figsize=style.figsize,
                             dpi=style.dpi, squeeze=False)
    for ax in axes.ravel():
        ax.set_prop_cycle(cycler(color=list(style.color_cycle)))
        if style.grid:
            ax.grid(True, alpha=0.3)
        ax.tick_params(labelsize=style.font_size)
    return fig, axes


def make_grid(ax):
    ax.grid(True, alpha=0.3)
    return ax
