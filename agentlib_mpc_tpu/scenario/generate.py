"""Scenario generation: disturbance draws → scenario-stacked OCP data.

Scenarios are DATA, never structure: every branch of a scenario tree
evaluates the same transcribed OCP with a different exogenous-input
trajectory (``OCPParams.d_traj``), so generating scenarios is stacking
perturbed parameter pytrees along a new leading axis — the axis
:class:`~agentlib_mpc_tpu.scenario.fleet.ScenarioFleet` vmaps and
shards. Two seeded sources feed it:

* the chaos harness's deterministic sampler
  (:func:`agentlib_mpc_tpu.resilience.chaos.disturbance_model`) —
  scenario generation and chaos injection share one seeded stream, so
  a robust-MPC run and the chaos replay that attacks it can draw the
  SAME disturbance realizations;
* the weather/TRY forecast-ensemble hooks
  (:meth:`~agentlib_mpc_tpu.modules.input_prediction.InputPredictor.
  get_prediction_ensemble_at_time`,
  :func:`agentlib_mpc_tpu.utils.try_format.try_forecast_ensemble`) —
  nominal forecast + seeded random-walk perturbations per column.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "ensemble_thetas",
    "scenario_thetas",
    "stack_scenario_params",
]


def stack_scenario_params(thetas):
    """Stack per-scenario OCPParams into one batched pytree (scenario
    axis 0) — the scenario-axis sibling of
    :func:`agentlib_mpc_tpu.parallel.fused_admm.stack_params`."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *thetas)


def scenario_thetas(theta, tree, draws, channels=None):
    """Stack one agent's ``theta`` into an (S, ...) scenario batch with
    ``d_traj`` perturbed per branch.

    ``draws``: additive disturbances, shape ``(S, N, len(channels))``
    (or ``(S, N)`` for one channel); ``channels`` indexes the exogenous
    columns of ``d_traj`` they perturb (default: the leading columns).
    Rows beyond the perturbed channels replicate the nominal data, so
    a single-scenario tree returns an exact 1-stack of ``theta``."""
    S = tree.n_scenarios
    draws = np.asarray(draws, dtype=float)
    if draws.ndim == 2:
        draws = draws[:, :, None]
    if draws.shape[0] != S:
        raise ValueError(
            f"draws carry {draws.shape[0]} scenarios, tree has {S}")
    d = np.asarray(theta.d_traj, dtype=float)
    if d.ndim != 2:
        raise ValueError(f"theta.d_traj must be (N, n_d), got {d.shape}")
    N, n_d = d.shape
    if draws.shape[1] != N:
        raise ValueError(
            f"draws cover {draws.shape[1]} intervals, horizon has {N}")
    channels = tuple(range(draws.shape[2])) if channels is None \
        else tuple(int(c) for c in channels)
    if len(channels) != draws.shape[2]:
        raise ValueError(
            f"{len(channels)} channel indices for "
            f"{draws.shape[2]}-channel draws")
    bad = [c for c in channels if not 0 <= c < n_d]
    if bad:
        raise ValueError(f"channel index(es) {bad} outside d_traj's "
                         f"{n_d} columns")
    d_batch = np.broadcast_to(d, (S, N, n_d)).copy()
    for k, c in enumerate(channels):
        d_batch[:, :, c] += draws[:, :, k]
    batched = jax.tree.map(
        lambda leaf: jnp.broadcast_to(
            jnp.asarray(leaf), (S,) + tuple(np.shape(leaf))), theta)
    return batched._replace(d_traj=jnp.asarray(d_batch))


def ensemble_thetas(theta, tree, seed: int = 0, scale: float = 1.0,
                    channels=(0,), kind: str = "walk"):
    """Scenario batch straight from the chaos sampler: seeded
    ``disturbance_model`` draws (scenario 0 nominal) added onto the
    selected ``d_traj`` channels — the one-call path ``bench.py
    --scenario-ab`` and the tests use. Deterministic in ``seed``.
    Models without exogenous inputs (0-column ``d_traj``) stack the
    nominal data S times unperturbed — the branches then differ only
    through whatever the caller varies by hand."""
    from agentlib_mpc_tpu.resilience.chaos import disturbance_model

    N, n_d = (int(v) for v in np.shape(theta.d_traj))
    channels = tuple(c for c in channels if c < n_d)
    draws = disturbance_model(seed=seed, horizon=N,
                              n_scenarios=tree.n_scenarios,
                              n_channels=len(channels),
                              scale=scale, kind=kind)
    return scenario_thetas(theta, tree, draws, channels=channels)
