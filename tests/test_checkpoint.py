"""Durable warm-start checkpointing of the module-path backends
(utils/checkpoint.py + OptimizationBackend.warm_state).

The fused-fleet checkpoint equivalence is pinned in
test_config_bridge.py::TestCheckpointResume; this covers the central-MPC
backend path: a restarted backend restored from the checkpoint must
produce the SAME next solve (trajectory and iteration count) as the
uninterrupted one, and warm solves must actually be cheaper than cold.
"""

import numpy as np
import pytest

from agentlib_mpc_tpu.backends.backend import (
    VariableReference,
    create_backend,
)
from agentlib_mpc_tpu.models.zoo import CooledRoom
from agentlib_mpc_tpu.utils.checkpoint import load_pytree, save_pytree


def _backend():
    backend = create_backend({
        "type": "jax",
        "model": {"class": CooledRoom},
        "discretization_options": {"collocation_order": 2},
        "solver": {"max_iter": 60},
    })
    backend.setup_optimization(
        VariableReference(
            states=["T", "T_slack"], controls=["mDot"],
            inputs=["load", "T_in", "T_upper"],
            parameters=["cp", "C", "s_T", "r_mDot"],
        ),
        time_step=300.0, prediction_horizon=6)
    return backend


class TestBackendWarmState:
    def test_restored_backend_matches_uninterrupted_solve(self, tmp_path):
        backend = _backend()
        backend.solve(0.0, {"T": 297.15})
        path = save_pytree(str(tmp_path / "warm"), backend.warm_state())

        res_continued = backend.solve(300.0, {"T": 296.9})

        fresh = _backend()                     # "restarted process"
        fresh.set_warm_state(load_pytree(path, fresh.warm_state()))
        res_resumed = fresh.solve(300.0, {"T": 296.9})

        np.testing.assert_array_equal(
            np.asarray(res_continued["traj"]["u"]),
            np.asarray(res_resumed["traj"]["u"]))
        assert res_continued["stats"]["iterations"] == \
            res_resumed["stats"]["iterations"]

    def test_warm_restore_beats_cold_start(self, tmp_path):
        backend = _backend()
        cold_iters = backend.solve(0.0, {"T": 297.15})["stats"]["iterations"]
        backend.solve(300.0, {"T": 296.9})
        path = save_pytree(str(tmp_path / "warm"), backend.warm_state())

        fresh = _backend()
        fresh.set_warm_state(load_pytree(path, fresh.warm_state()))
        warm_iters = fresh.solve(600.0, {"T": 296.7})["stats"]["iterations"]
        # <= like the repo's other warm-vs-cold pins (the two solves see
        # different data, so strict inequality would be flaky by design)
        assert warm_iters <= cold_iters

    def test_shape_mismatch_rejected(self, tmp_path):
        backend = _backend()
        other = create_backend({
            "type": "jax",
            "model": {"class": CooledRoom},
            "discretization_options": {"collocation_order": 2},
            "solver": {"max_iter": 60},
        })
        other.setup_optimization(
            VariableReference(
                states=["T", "T_slack"], controls=["mDot"],
                inputs=["load", "T_in", "T_upper"],
                parameters=["cp", "C", "s_T", "r_mDot"],
            ),
            time_step=300.0, prediction_horizon=9)   # different horizon
        with pytest.raises(ValueError, match="same config"):
            other.set_warm_state(backend.warm_state())

    def test_ml_backend_warm_state_roundtrips(self, tmp_path):
        """The warm-state contract is generic over backend subclasses:
        the NARX ML backend (its own _reset_warm_start) checkpoints and
        resumes identically too."""
        from test_ml_backend import _backend as ml_backend

        backend = ml_backend()
        backend.solve(0.0, {"T": 297.15})
        path = save_pytree(str(tmp_path / "ml_warm"),
                           backend.warm_state())
        res_continued = backend.solve(300.0, {"T": 296.9})

        fresh = ml_backend()
        fresh.set_warm_state(load_pytree(path, fresh.warm_state()))
        res_resumed = fresh.solve(300.0, {"T": 296.9})
        np.testing.assert_array_equal(
            np.asarray(res_continued["traj"]["u"]),
            np.asarray(res_resumed["traj"]["u"]))

    def test_unset_backend_raises_lifecycle_error(self):
        backend = create_backend({"type": "jax",
                                  "model": {"class": CooledRoom}})
        with pytest.raises(RuntimeError, match="setup_optimization"):
            backend.warm_state()
        with pytest.raises(RuntimeError, match="setup_optimization"):
            backend.set_warm_state({})
