"""Benchmark: consensus-ADMM MPC fleets, wall-clock per control step.

The BASELINE.json north-star metric: "ADMM-MPC wall-clock per control step;
agents/sec scaling 4->256 zones". One control step = `ADMM_ITERS` fused
consensus-ADMM iterations, each iteration = vmapped per-zone interior-point
NLP solves + consensus mean + scaled-dual update, all inside one jitted XLA
computation (the TPU-native replacement for the reference's coordinator
round driving one IPOPT process per zone, ``admm_coordinator.py:259-321``).
On TPU the per-iteration KKT systems factor in the lanes-batched Pallas
LDLᵀ kernel (``agentlib_mpc_tpu/ops/kkt.py``).

The reference itself cannot run here (CasADi/IPOPT not installed, zero
egress) and publishes no numbers (BASELINE.md), so ``vs_baseline`` is the
measured speedup of the default platform (TPU under the driver) over the
same workload forced onto host CPU — a conservative stand-in: the CPU run
uses the same fused XLA path, which is already far faster than 256
sequential CasADi+IPOPT processes.

Modes:
    python bench.py             # headline: 256 zones + CPU baseline probe,
                                # prints ONE JSON line
    python bench.py --scaling   # 4/16/64/256-zone curve (BASELINE.md rows),
                                # prints one JSON line per size + a table

Headline JSON:
    {"metric": "admm256_step_ms", "value": <ms>, "unit": "ms",
     "vs_baseline": <cpu_ms / this_ms>}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

N_AGENTS = 256
HORIZON = 10
ADMM_ITERS = 10
DT = 300.0
SCALING_SIZES = (4, 16, 64, 256)


def build_step(n_agents: int = N_AGENTS, solver_overrides: dict | None = None,
               warm_budget: int = 1):
    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()

    from agentlib_mpc_tpu.models.zoo import ZoneWithSupply
    from agentlib_mpc_tpu.ops.solver import (
        NLPFunctions,
        SolverOptions,
        solve_nlp,
    )
    from agentlib_mpc_tpu.ops.transcription import transcribe

    model = ZoneWithSupply()
    ocp = transcribe(model, ["mDot"], N=HORIZON, dt=DT,
                     method="collocation", collocation_degree=2)

    def f_aug(w, theta):
        ocp_theta, zbar, lam, rho = theta
        u = ocp.unflatten(w)["u"]
        return ocp.nlp.f(w, ocp_theta) + \
            0.5 * rho * jnp.sum((u - zbar + lam) ** 2)

    nlp = NLPFunctions(f=f_aug, g=lambda w, th: ocp.nlp.g(w, th[0]),
                       h=lambda w, th: ocp.nlp.h(w, th[0]))

    # two-phase inexact ADMM: the first (cold) iteration gets the full
    # interior-point budget; subsequent iterations are warm-started in
    # primal, duals AND barrier, so a short budget suffices — in a vmapped
    # while_loop wall time is the slowest lane's iteration count, so the
    # budget is the lever (measured 2.4x on this workload at equal final
    # consensus error). The budget is a TRACED scalar (solve_nlp max_iter
    # override), so the cold and warm phases share one solver trace — the
    # Python-tracing floor of this program was 2 solver traces ≈ 7 s.
    # The Mehrotra corrector is ON for this workload (round-4 A/B,
    # PERF.md "Corrector in the warm phase"): its second back-substitution
    # per iteration buys warm budget 1 at equal-or-better consensus
    # spread — a 32% cut in sequential inner iterations per control step.
    base_opts = {"tol": 1e-4, "max_iter": 10, "corrector": True}
    base_opts.update(solver_overrides or {})
    opts = SolverOptions(**base_opts)

    def local_solve(x0, load, w_guess, y_guess, z_guess, mu0, budget,
                    zbar, lam, rho):
        theta = ocp.default_params(
            x0=x0, d_traj=jnp.broadcast_to(
                jnp.array([load, 290.15, 294.15]), (HORIZON, 3)))
        lb, ub = ocp.bounds(theta)
        res = solve_nlp(nlp, w_guess, (theta, zbar, lam, rho), lb, ub,
                        opts, y0=y_guess, z0=z_guess, mu0=mu0,
                        max_iter=budget)
        return res.w, res.y, res.z, ocp.unflatten(res.w)["u"]

    vsolve = jax.vmap(local_solve,
                      in_axes=(0, 0, 0, 0, 0, None, None, None, 0, None))

    # budgets swept on this workload (warm steady state, final consensus
    # spread max|u - zbar| as the equal-quality gate). r3 (no corrector):
    #   10/3: 37 inner iters, spread 0.01147   10/2: 28, 0.01137
    #    8/2: 26, 0.01136                      12/1: 21, 0.01171
    # r4 (64 zones): corrector+10/1: 19 iters, spread 0.00873 beats
    # plain 10/2 (28 iters, 0.00902); plain 10/1 degrades (0.01059).
    # → cold=10 / warm=1 with the corrector (see PERF.md).
    # All ADMM_ITERS iterations run in ONE scan whose per-iteration
    # (budget, mu0) are scanned-over values — a single solver call site
    # means a single solver trace (the jit trace cache is trace-context-
    # sensitive, so a separate cold call outside the loop would trace the
    # whole interior-point method twice).
    budgets = jnp.full((ADMM_ITERS,), warm_budget).at[0].set(10)
    mu0s = jnp.full((ADMM_ITERS,), 1e-2).at[0].set(0.1)

    def control_step(x0s, loads, w_gs, y_gs, z_gs, zbar, lams, rho):
        def admm_iter(carry, x):
            budget, mu0 = x
            w_gs, y_gs, z_gs, zbar, lams = carry
            w_gs, y_gs, z_gs, u = vsolve(x0s, loads, w_gs, y_gs, z_gs,
                                         mu0, budget, zbar, lams, rho)
            zbar_new = jnp.mean(u, axis=0)
            lams_new = lams + (u - zbar_new)
            return (w_gs, y_gs, z_gs, zbar_new, lams_new), None

        carry, _ = jax.lax.scan(admm_iter, (w_gs, y_gs, z_gs, zbar, lams),
                                (budgets, mu0s))
        return carry

    theta0 = ocp.default_params()
    x0s = jnp.linspace(294.0, 300.0, n_agents).reshape(n_agents, 1)
    loads = jnp.linspace(80.0, 250.0, n_agents)
    w_gs = jnp.broadcast_to(ocp.initial_guess(theta0), (n_agents, ocp.n_w))
    y_gs = jnp.zeros((n_agents, ocp.n_g))
    z_gs = jnp.full((n_agents, ocp.n_h), 0.1)
    zbar = jnp.full((HORIZON, 1), 0.02)
    lams = jnp.zeros((n_agents, HORIZON, 1))
    rho = jnp.asarray(20.0)
    args = (x0s, loads, w_gs, y_gs, z_gs, zbar, lams, rho)
    return jax.jit(control_step), args


def measure(n_agents: int = N_AGENTS,
            solver_overrides: dict | None = None,
            warm_budget: int = 1) -> dict:
    import jax

    step, args = build_step(n_agents, solver_overrides, warm_budget)
    t0 = time.perf_counter()
    out = step(*args)
    jax.block_until_ready(out)
    compile_ms = 1e3 * (time.perf_counter() - t0)
    # steady state: warm-started repeat (the closed-loop regime)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = step(args[0], args[1], out[0], out[1], out[2], out[3],
                   out[4], args[7])
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    step_ms = 1e3 * min(times)
    return {
        "n_agents": n_agents,
        "step_ms": step_ms,
        "compile_ms": compile_ms,
        # agents served per second of wall clock (one control step serves
        # every agent once) — the north-star "agents/sec" definition
        "agents_per_sec": n_agents / (step_ms / 1e3),
        # per-zone ADMM iterations per second (each step runs ADMM_ITERS)
        "zone_iters_per_sec": n_agents * ADMM_ITERS / (step_ms / 1e3),
        "platform": jax.devices()[0].platform,
    }


def run_scaling() -> list[dict]:
    """The 4→256-zone curve (BASELINE.md scaling rows)."""
    rows = []
    for n in SCALING_SIZES:
        res = measure(n)
        rows.append(res)
        print(f"[bench] n={n:4d}  step={res['step_ms']:8.1f}ms  "
              f"agents/s={res['agents_per_sec']:8.0f}  "
              f"compile={res['compile_ms']:.0f}ms", file=sys.stderr)
    for res in rows:
        print(json.dumps({
            "metric": f"admm{res['n_agents']}_step_ms",
            "value": round(res["step_ms"], 2),
            "unit": "ms",
            "agents_per_sec": round(res["agents_per_sec"], 1),
            "zone_iters_per_sec": round(res["zone_iters_per_sec"], 1),
            "platform": res["platform"],
        }))
    return rows


def run_ab() -> None:
    """A/B the per-iteration latency knobs on the current backend
    (used to validate SolverOptions defaults on real TPU hardware)."""
    for label, ov, wb in (
            ("fused_ls=off", {"fused_ls_jacobian": "off"}, 1),
            ("fused_ls=on", {"fused_ls_jacobian": "on"}, 1),
            ("corrector=off,warm=2", {"corrector": False}, 2),
            ("corrector=on,warm=1", {}, 1)):
        res = measure(N_AGENTS, ov, warm_budget=wb)
        print(json.dumps({
            "metric": f"admm256_step_ms[{label}]",
            "value": round(res["step_ms"], 2), "unit": "ms",
            "compile_ms": round(res["compile_ms"]),
            "platform": res["platform"]}))


# --- fail-soft orchestration (round-3 lesson: a wedged TPU tunnel hangs
# jax backend init *forever* inside the axon sitecustomize, before any of
# our code runs, and the round's BENCH came back `rc=1, parsed=null`).
# The parent process below never initializes JAX itself: every measurement
# runs in a watchdogged child, and a dead/wedged tunnel degrades to a CPU
# measurement with the platform recorded in the JSON — a JSON line is
# emitted on EVERY path.

_HERE = os.path.dirname(os.path.abspath(__file__))
PROBE_TIMEOUT_S = 240.0    # tunnel init is ~30 s when healthy
WORKER_TIMEOUT_S = 2400.0  # compile (~40 s/size on TPU) + measurement


def _child_main() -> None:
    """Measurement child. ``--probe`` pins to host CPU (the launcher also
    hands us a scrubbed env so the axon sitecustomize never dials the
    tunnel; the in-process override is belt-and-braces for direct
    invocations from an unscrubbed shell); ``--worker`` runs on whatever
    the default platform is (TPU under the driver)."""
    if "--probe" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if "--scaling" in sys.argv:
        run_scaling()
    elif "--ab" in sys.argv:
        run_ab()
    else:
        print(json.dumps(measure()))


def _spawn(args: list, env: dict, timeout: float) -> list:
    """Run this script as a child, forward its stderr, return its parsed
    JSON stdout lines. Raises on rc != 0, timeout, or no JSON output."""
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + args,
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_HERE)
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench child rc={proc.returncode}: {proc.stderr[-500:]}")
    lines = [json.loads(line)
             for line in proc.stdout.strip().splitlines()
             if line.strip().startswith("{")]
    if not lines:
        raise RuntimeError("bench child emitted no JSON")
    return lines


def _default_platform() -> "str | None":
    """Initialize JAX in a tiny watchdogged child; return its default
    platform name, or None if init fails/hangs (wedged tunnel)."""
    code = ("import jax, json; "
            "print(json.dumps({'p': jax.devices()[0].platform}))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=PROBE_TIMEOUT_S, env=dict(os.environ), cwd=_HERE)
        if proc.returncode != 0:
            return None
        return json.loads(proc.stdout.strip().splitlines()[-1])["p"]
    except Exception:  # noqa: BLE001 - any failure means "unavailable"
        return None


def _measure_failsoft(mode_args: list) -> "tuple[list, str, bool]":
    """(json_lines, platform, fell_back). Tries the default platform
    first; degrades to a tunnel-free CPU child on any failure.
    ``fell_back`` is True only when an accelerator was expected but the
    measurement degraded to CPU — a machine whose default platform IS the
    CPU is a normal run, not a fallback."""
    platform = _default_platform()
    if platform is not None and platform != "cpu":
        try:
            lines = _spawn(["--worker"] + mode_args, dict(os.environ),
                           WORKER_TIMEOUT_S)
            return lines, platform, False
        except Exception as exc:  # noqa: BLE001 - degrade, never die
            print(f"[bench] {platform} worker failed ({exc}); "
                  f"falling back to CPU", file=sys.stderr)
        fell_back = True
    elif platform is None:
        print("[bench] default platform unavailable (backend init failed "
              "or timed out — wedged TPU tunnel?); measuring on CPU",
              file=sys.stderr)
        fell_back = True
    else:
        print("[bench] default platform is CPU (no accelerator "
              "registered); measuring on CPU", file=sys.stderr)
        fell_back = False
    from agentlib_mpc_tpu.utils.jax_setup import cpu_subprocess_env

    lines = _spawn(["--probe"] + mode_args, cpu_subprocess_env(),
                   WORKER_TIMEOUT_S)
    return lines, "cpu", fell_back


def main() -> None:
    if "--probe" in sys.argv or "--worker" in sys.argv:
        _child_main()
        return

    if "--scaling" in sys.argv or "--ab" in sys.argv:
        mode = "--scaling" if "--scaling" in sys.argv else "--ab"
        try:
            lines, _, _ = _measure_failsoft([mode])
            for line in lines:
                print(json.dumps(line))
        except Exception as exc:  # noqa: BLE001 - the line must always emit
            print(f"[bench] catastrophic failure: {exc}", file=sys.stderr)
            print(json.dumps({
                "metric": f"bench[{mode.lstrip('-')}]",
                "value": None, "unit": "ms",
                "platform": "unavailable", "error": str(exc)[:300]}))
        return

    try:
        lines, platform, fell_back = _measure_failsoft([])
        res = lines[-1]
        print(f"[bench] platform={platform} "
              f"step={res['step_ms']:.1f}ms "
              f"compile={res['compile_ms']:.0f}ms "
              f"agents/s={res['agents_per_sec']:.0f}", file=sys.stderr)

        if fell_back or platform == "cpu":
            # the headline IS the CPU number; the ratio vs itself is 1
            vs_baseline = 1.0
        else:
            vs_baseline = 0.0
            try:
                from agentlib_mpc_tpu.utils.jax_setup import (
                    cpu_subprocess_env,
                )

                cpu = _spawn(["--probe"], cpu_subprocess_env(),
                             WORKER_TIMEOUT_S)[-1]
                print(f"[bench] cpu baseline step={cpu['step_ms']:.1f}ms",
                      file=sys.stderr)
                vs_baseline = cpu["step_ms"] / res["step_ms"]
            except Exception as exc:  # noqa: BLE001 - best-effort
                print(f"[bench] cpu baseline unavailable: {exc}",
                      file=sys.stderr)

        print(json.dumps({
            "metric": "admm256_step_ms",
            "value": round(res["step_ms"], 2),
            "unit": "ms",
            "vs_baseline": round(vs_baseline, 2),
            "platform": platform,
            "tpu_fallback_to_cpu": fell_back,
        }))
    except Exception as exc:  # noqa: BLE001 - the line must always emit
        print(f"[bench] catastrophic failure: {exc}", file=sys.stderr)
        print(json.dumps({
            "metric": "admm256_step_ms",
            "value": None,
            "unit": "ms",
            "vs_baseline": 0.0,
            "platform": "unavailable",
            "error": str(exc)[:300],
        }))


if __name__ == "__main__":
    main()
