"""Semantic proofs over closed jaxprs: the layer below the AST passes.

PR 3's ``jaxlint`` analyzes Python source; the questions the framework's
two riskiest auto-routing decisions hang on — *is this function linear in
``w``?* (the QP fast path), *does the KKT dependence structure really
match the attached* :class:`~agentlib_mpc_tpu.ops.stagewise.StagePartition`?
(the block-tridiagonal sweep) — are not syntactic. They are decidable
exactly one level down, in the jaxpr, where JAX's tracing design (Frostig
et al.) gives a complete dataflow IR of the traced function: every
primitive application, every constant, no Python control flow left.

Nine passes over one shared per-primitive interpreter (:mod:`.interp`):

* :func:`certify_lq` (:mod:`.lq`) — a polynomial-degree lattice
  {const, affine, quadratic, nonpoly} propagated per element through
  every primitive. Proves LQ structure *for all theta* (theta inputs are
  symbolic degree-0 values, so a theta-gated nonlinearity keeps both
  branches in the abstraction) — the sound replacement for the sampled
  probe ``ops/qp.py:is_lq``, which only sees the default-theta branch.
* :func:`certify_stage_structure` (:mod:`.structure`) — exact
  w→(g, h) dependence propagation at stage granularity plus Hessian
  interaction tracking, checked against the partition's
  block-tridiagonal band: the transcribe-time *layout* assertion becomes
  a proof against the actual traced functions.
* :func:`check_dtypes` (:mod:`.dtypes`) — dtype/weak-type propagation:
  f64 promotions, weak-type leaks into jaxpr outputs and loop carries,
  x64-flag-dependent constants. The semantic complement of the AST
  ``jit-weak-type`` pass.
* :func:`op_cost` (:mod:`.cost`) — a per-primitive FLOP/bytes cost
  model for ``bench.py --emit-metrics`` and PERF.md attribution tables,
  with a comm column (``collective_bytes`` = payload × axis size ×
  loop trips) for the mesh program's cross-device traffic.
* :func:`certify_collectives` (:mod:`.collectives`) — a replication
  lattice (replicated ⊑ shard-varying, seeded by ``shard_map``
  in-specs, collectives rejoining replicated) proving every ``psum``
  of a mesh program sits on shard-uniform control flow, and emitting
  the ordered collective schedule whose digest the engine store, the
  plane checkpoint and the degraded-mesh rebuild assert against. A
  shard-varying ``while`` predicate over a collective — the silent
  cross-host pod hang — is refuted at build time, naming the eqn.
* :func:`certify_memory` (:mod:`.memory`) — a live-range walk over the
  eqn schedule computing peak bytes-resident PER DEVICE
  (donation-aware: donated invals alias matching outvals;
  sharding-aware: ``shard_map`` operands divide by their spec'd mesh
  axis sizes; loops at body-peak + carry, never × trips), anchored to
  XLA's own ``memory_analysis`` by the ``[jaxpr.memory]`` gate. Both
  fleet engines attach the certificate at build and refuse programs
  whose certified peak exceeds the device's reported capacity;
  :func:`plan_capacity` inverts the per-lane marginal into "how many
  agents / scenarios / tenant slots fit on one device".

* :func:`certify_dispatch` (:mod:`.dispatch`) — the warm round's
  host↔device schedule proved static: ordered dispatch boundaries with
  shard-divided, donation-aware transfer bytes, every
  ``pure_callback``-class host sync located by source and charged ×
  loop trips, an unplanned sync inside the round refuted by name, and
  a mesh-size-independent ``dispatch_digest`` riding the engine-store
  and checkpoint stamps next to the collective and memory digests.
* :func:`certify_precision` (:mod:`.precision`) — a forward
  error-propagation lattice (per-value magnitude interval + accumulated
  relative-error bound, condition-number-aware cancellation checks for
  sub/sum, operand-rounding + log-depth accumulation charges for
  contractions, loop fixpoints with honest widening) proving, per
  ``phase_scope`` phase, the narrowest dtype regime whose error stays
  under the phase tolerance — the :class:`PrecisionCertificate` behind
  ``SolverOptions.precision`` ("mixed" routes certified phases to
  bf16-input/f32-accumulate, "require" refuses an unproved build) and
  the ``precision_digest`` on engine-store and checkpoint stamps. A
  refuting phase names the dominating hazard by eqn source (the KKT
  residual subtraction, a μ-floor division).
* :func:`plan_fusion` (:mod:`.fusion`) — the analytic fusion planner:
  per-phase op-cost × collective-bytes × live-range peaks joined
  across candidate stage merges, ranked by modeled dispatch-overhead
  savings vs projected peak-HBM growth, over-capacity plans refused —
  the :class:`FusionPlan` artifact behind ``SolverOptions.fusion`` and
  ``bench.py --emit-metrics``.

Soundness boundary: primitives the interpreter cannot see through
(``pure_callback``, custom AD rules, foreign calls) make a *tainted*
result opaque — :func:`certify_lq` then returns ``"unknown"`` instead of
a verdict and the callers fall back to the sampled probe, with the
fallback recorded as a finding. An opaque primitive whose inputs carry
no ``w`` dependence is harmless (its output provably does not depend on
``w`` either, by purity of jaxpr evaluation) and does not degrade the
certificate.

CLI: ``python -m agentlib_mpc_tpu.lint --jaxpr`` runs all passes over
the example-OCP menu (:mod:`.examples`) against the expectations in
``lint_budgets.toml``. See ``docs/static_analysis.md``.
"""

from __future__ import annotations

from agentlib_mpc_tpu.lint.jaxpr.collectives import (  # noqa: F401
    CollectiveCertificate,
    CollectiveOp,
    certify_collectives,
    check_collective_budget,
)
from agentlib_mpc_tpu.lint.jaxpr.cost import (  # noqa: F401
    CostEstimate,
    compare_eval_jac_cost,
    op_cost,
)
from agentlib_mpc_tpu.lint.jaxpr.dispatch import (  # noqa: F401
    DispatchBoundary,
    DispatchCertificate,
    certify_dispatch,
    check_dispatch_budget,
)
from agentlib_mpc_tpu.lint.jaxpr.dtypes import check_dtypes  # noqa: F401
from agentlib_mpc_tpu.lint.jaxpr.fusion import (  # noqa: F401
    FusionCandidate,
    FusionPlan,
    plan_fusion,
)
from agentlib_mpc_tpu.lint.jaxpr.fingerprint import (  # noqa: F401
    StructuralFingerprint,
    jaxpr_digest,
    structural_fingerprint,
)
from agentlib_mpc_tpu.lint.jaxpr.precision import (  # noqa: F401
    CANDIDATE_DTYPES,
    MIXED_FULL_PHASES,
    MIXED_NARROW_PHASES,
    PHASE_TOLS,
    PhaseVerdict,
    PrecisionCertificate,
    certify_precision,
    certify_solver_precision,
    check_precision_budget,
    precision_gate_summary,
)
from agentlib_mpc_tpu.lint.jaxpr.lq import (  # noqa: F401
    LQCertificate,
    certify_lq,
)
from agentlib_mpc_tpu.lint.jaxpr.memory import (  # noqa: F401
    CapacityPlan,
    MemoryBudgetExceeded,
    MemoryCertificate,
    certify_memory,
    check_memory_budget,
    device_hbm_bytes,
    engine_memory_certificate,
    modeled_buffer_bytes,
    plan_capacity,
    xla_memory_analysis,
)
from agentlib_mpc_tpu.lint.jaxpr.structure import (  # noqa: F401
    StructureCertificate,
    certify_stage_structure,
)
