"""Interactive dashboard: pure data layer + schema-validated figure layer.

The reference ships ~1.9 kLoC of dash dashboards
(``utils/plotting/{mpc_dashboard,admm_dashboard,interactive}.py``); this
environment has no dash/plotly, so the data layer is tested directly and
the dash/plotly layer is exercised against stand-ins that VALIDATE every
trace and layout attribute against the vendored plotly schema subset
(``utils/plotting/plotly_schema.py``) — an attribute typo, a bad enum
value, a malformed color, or a dangling ``yaxis="y2"`` reference fails
here the same way real plotly's ``validate=True`` would reject it
(VERDICT r3 ask #5: the figure layer must not be verifiable only against
permissive stubs).
"""

import sys
import types
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu.utils.plotting import dashboard as db
from agentlib_mpc_tpu.utils.plotting.dashboard import show_dashboard
from agentlib_mpc_tpu.utils.plotting.plotly_schema import (
    SchemaError,
    validate_figure,
    validate_layout,
    validate_trace,
)


def _mpc_frame():
    frames = []
    for t in (0.0, 300.0, 600.0):
        df = pd.DataFrame({
            ("variable", "T"): [295.0 + t / 300, 294.0, 293.0],
            ("variable", "mDot"): [0.01, 0.02, np.nan],
        })
        df.index = pd.MultiIndex.from_product(
            [[t], [0.0, 100.0, 200.0]], names=["time", "grid"])
        frames.append(df)
    out = pd.concat(frames)
    out.columns = pd.MultiIndex.from_tuples(out.columns)
    return out


def _admm_frame():
    frames = []
    for t in (0.0, 300.0):
        for it in (0, 1, 2):
            df = pd.DataFrame({"mDot": [0.01 * (it + 1)] * 3})
            df.index = pd.MultiIndex.from_product(
                [[t], [it], [0.0, 100.0, 200.0]],
                names=["time", "iteration", "grid"])
            frames.append(df)
    return pd.concat(frames)


def _mhe_frame():
    """Backward-horizon estimation frame: grid offsets [-200 .. 0]."""
    frames = []
    for t in (600.0, 900.0):
        df = pd.DataFrame({
            ("variable", "T"): [294.0, 294.5, 295.0 + t / 300],
        })
        df.index = pd.MultiIndex.from_product(
            [[t], [-200.0, -100.0, 0.0]], names=["time", "grid"])
        frames.append(df)
    out = pd.concat(frames)
    out.columns = pd.MultiIndex.from_tuples(out.columns)
    return out


def _measurements():
    return pd.DataFrame(
        {"measured_T": [294.1, 294.6, 297.2, 298.1]},
        index=pd.Index([400.0, 500.0, 600.0, 900.0], name="time"))


def _residual_stats():
    rows = []
    for t in (0.0, 300.0):
        for it in (0, 1, 2):
            rows.append((t, it, 10.0 ** -it, 5.0 * 10.0 ** -it, 10.0))
    df = pd.DataFrame(rows, columns=["time", "iteration", "primal_residual",
                                     "dual_residual", "rho"])
    return df.set_index(["time", "iteration"])


class TestDataLayer:
    def test_discover_and_kind(self):
        res = {"A": {"mpc": _mpc_frame(), "meta": None},
               "B": {"admm": _admm_frame()},
               "junk": "not-a-dict"}
        frames = db.discover_frames(res)
        assert set(frames) == {("A", "mpc"), ("B", "admm")}
        assert db.frame_kind(frames[("A", "mpc")]) == "mpc"
        assert db.frame_kind(frames[("B", "admm")]) == "admm"

    def test_variables_and_steps(self):
        df = _mpc_frame()
        assert db.variables_of(df) == ["T", "mDot"]
        np.testing.assert_allclose(db.time_steps_of(df), [0.0, 300.0, 600.0])

    def test_prediction_traces_and_fade_subsample(self):
        df = _mpc_frame()
        traces = db.prediction_traces(df, "T")
        assert len(traces) == 3
        t0, abs_t, vals = traces[0]
        assert t0 == 0.0
        np.testing.assert_allclose(abs_t, [0.0, 100.0, 200.0])
        np.testing.assert_allclose(vals, [295.0, 294.0, 293.0])
        # nan tail dropped for control-grid vars
        _, _, mdot = db.prediction_traces(df, "mDot")[0]
        assert len(mdot) == 2
        # subsampling cap
        assert len(db.prediction_traces(df, "T", max_steps=2)) == 2

    def test_actual_series(self):
        ts, vs = db.actual_series(_mpc_frame(), "T")
        np.testing.assert_allclose(ts, [0.0, 300.0, 600.0])
        np.testing.assert_allclose(vs, [295.0, 296.0, 297.0])

    def test_admm_iteration_traces(self):
        df = _admm_frame()
        traces = db.admm_iteration_traces(df, "mDot", 300.0)
        assert [it for it, _, _ in traces] == [0, 1, 2]
        np.testing.assert_allclose(traces[2][2], [0.03] * 3)
        # prediction_traces uses the LAST iteration for admm frames
        last = db.prediction_traces(df, "mDot")[-1]
        np.testing.assert_allclose(last[2], [0.03] * 3)

    def test_mhe_frame_kind_and_series(self):
        df = _mhe_frame()
        assert db.frame_kind(df) == "mhe"
        ts, vs = db.estimate_series(df, "T")
        np.testing.assert_allclose(ts, [600.0, 900.0])
        np.testing.assert_allclose(vs, [297.0, 298.0])  # offset-0 nodes
        mt, mv = db.measurement_points(_measurements(), "T")
        np.testing.assert_allclose(mt, [400.0, 500.0, 600.0, 900.0])
        # unprefixed column name resolves too; absent variable -> empty
        meas2 = _measurements().rename(columns={"measured_T": "T"})
        assert len(db.measurement_points(meas2, "T")[0]) == 4
        assert len(db.measurement_points(None, "T")[0]) == 0
        assert len(db.measurement_points(_measurements(), "Q")[0]) == 0

    def test_residual_and_solver_tables(self):
        stats = _residual_stats()
        table = db.residual_table(stats)
        assert list(table.columns) == ["primal_residual", "dual_residual",
                                       "rho"]
        assert db.residual_table(None) is None
        solver = pd.DataFrame({
            "iterations": [10, 8], "success": [True, True],
            "solve_wall_time": [0.1, 0.05]}, index=[0.0, 300.0])
        st = db.solver_table(solver)
        assert "iterations" in st.columns


class _StubComponent:
    def __init__(self, *children, **kwargs):
        self.children = children
        self.kwargs = kwargs


class _SchemaScatter:
    """go.Scatter stand-in that rejects what plotly would reject."""

    trace_type = "scatter"

    def __init__(self, **kwargs):
        validate_trace(self.trace_type, kwargs)
        self.kwargs = kwargs


class _SchemaFig:
    """go.Figure stand-in: every mutation is schema-validated, and
    :meth:`to_dict` yields the plotly figure dict for whole-figure
    validation (axis cross-references included)."""

    def __init__(self, *a, **k):
        self.traces = []
        self.layout = {}

    def add_trace(self, tr):
        assert isinstance(tr, _SchemaScatter)
        self.traces.append(tr)

    def update_layout(self, *a, **k):
        validate_layout(k)
        self.layout.update(k)

    def update_yaxes(self, *a, **k):
        ax = dict(self.layout.get("yaxis", {}))
        ax.update(k)
        validate_layout({"yaxis": ax})
        self.layout["yaxis"] = ax

    def to_dict(self):
        return {
            "data": [{**tr.kwargs, "type": tr.trace_type}
                     for tr in self.traces],
            "layout": dict(self.layout),
        }


class _StubDash:
    def __init__(self, name=None, **kw):
        self.name = name
        self.layout = None
        self.callbacks = []

    def callback(self, *deps):
        def deco(fn):
            self.callbacks.append((deps, fn))
            return fn
        return deco


def _install_stub_dash(monkeypatch):
    dash_mod = types.ModuleType("dash")
    dash_mod.Dash = _StubDash
    html_mod = types.ModuleType("dash.html")
    dcc_mod = types.ModuleType("dash.dcc")
    for name in ("Div", "H2", "Label"):
        setattr(html_mod, name, _StubComponent)
    for name in ("Dropdown", "Slider", "Graph", "Store"):
        setattr(dcc_mod, name, _StubComponent)
    deps_mod = types.ModuleType("dash.dependencies")
    deps_mod.Input = lambda *a, **k: ("input", a)
    deps_mod.Output = lambda *a, **k: ("output", a)
    dash_mod.html = html_mod
    dash_mod.dcc = dcc_mod
    dash_mod.dependencies = deps_mod
    monkeypatch.setitem(sys.modules, "dash", dash_mod)
    monkeypatch.setitem(sys.modules, "dash.html", html_mod)
    monkeypatch.setitem(sys.modules, "dash.dcc", dcc_mod)
    monkeypatch.setitem(sys.modules, "dash.dependencies", deps_mod)

    plotly_mod = types.ModuleType("plotly")
    go_mod = types.ModuleType("plotly.graph_objects")
    go_mod.Figure = _SchemaFig
    go_mod.Scatter = _SchemaScatter
    plotly_mod.graph_objects = go_mod
    monkeypatch.setitem(sys.modules, "plotly", plotly_mod)
    monkeypatch.setitem(sys.modules, "plotly.graph_objects", go_mod)


class TestDashLayer:
    def test_build_app_smoke(self, monkeypatch):
        _install_stub_dash(monkeypatch)
        results = {"A": {"mpc": _mpc_frame()}, "B": {"admm": _admm_frame()}}
        app = db.build_app(results, stats=_residual_stats())
        assert app.layout is not None
        assert len(app.callbacks) == 2
        # drive the callbacks as dash would
        for _, fn in app.callbacks:
            out_mpc = fn("A/mpc")
            out_admm = fn("B/admm")
            assert out_mpc is not None and out_admm is not None

    def test_show_dashboard_never_raises_with_dash(self, monkeypatch):
        """VERDICT r1 weak #6: installing dash must not make behavior
        worse. With (stub) dash importable, show_dashboard builds the app
        instead of raising NotImplementedError."""
        _install_stub_dash(monkeypatch)
        results = {"A": {"mpc": _mpc_frame()}}
        app = show_dashboard(results, block=False)
        assert isinstance(app, _StubDash)

    def test_empty_results_error_contract(self):
        with pytest.raises(ValueError):
            show_dashboard({"A": {"none": None}})

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            show_dashboard({"A": {"mpc": _mpc_frame()}}, mode="png")

    def test_mhe_frames_routed_in_app(self, monkeypatch):
        _install_stub_dash(monkeypatch)
        results = {"E": {"mhe": _mhe_frame()}}
        app = db.build_app(results, measurements=_measurements())
        graphs_cb = app.callbacks[-1][1]
        assert graphs_cb("E/mhe") is not None

    def test_static_mode_renders_admm_frame(self, tmp_path):
        """3-level ADMM frames must render in static mode too (review
        regression: the rewrite initially fed them to plot_mpc)."""
        import matplotlib

        matplotlib.use("Agg")
        out = tmp_path / "admm.png"
        fig = show_dashboard({"B": {"admm": _admm_frame()}}, mode="static",
                             save_path=str(out))
        assert out.exists()
        import matplotlib.pyplot as plt

        plt.close(fig)

    def test_static_mode_renders_mhe_overview(self, tmp_path):
        """mode='static' is the export path (VERDICT r4 #8): no dash
        required, measurement overlay included, file written."""
        import matplotlib

        matplotlib.use("Agg")
        out = tmp_path / "mhe.png"
        fig = show_dashboard({"E": {"mhe": _mhe_frame()}}, mode="static",
                             save_path=str(out),
                             measurements=_measurements())
        assert out.exists()
        import matplotlib.pyplot as plt

        plt.close(fig)

    def test_figure_builders_with_stub_plotly(self, monkeypatch):
        _install_stub_dash(monkeypatch)
        fig = db.prediction_figure(_mpc_frame(), "T")
        assert len(fig.traces) == 4  # 3 predictions + closed loop
        fig2 = db.admm_iteration_figure(_admm_frame(), "mDot", 300.0)
        assert len(fig2.traces) == 3
        fig3 = db.residual_figure(_residual_stats(), 0.0)
        assert len(fig3.traces) == 2


class TestFigureSchema:
    """Golden-structure gate: every figure the builders emit must be a
    valid plotly figure dict (trace attributes, enums, colors, axis
    references), and the validator itself must catch the typo classes
    real plotly rejects."""

    def test_every_builder_emits_schema_valid_figures(self, monkeypatch):
        _install_stub_dash(monkeypatch)
        solver = pd.DataFrame({
            "iterations": [10, 8], "success": [True, True],
            "solve_wall_time": [0.1, 0.05]}, index=[0.0, 300.0])
        figs = [
            db.prediction_figure(_mpc_frame(), "T"),
            db.prediction_figure(_mpc_frame(), "mDot"),
            db.admm_iteration_figure(_admm_frame(), "mDot", 300.0),
            db.admm_iteration_figure(_admm_frame(), "mDot", 0.0,
                                     iteration=1),
            db.mhe_figure(_mhe_frame(), "T",
                          measurements=_measurements()),
            db.residual_figure(_residual_stats(), 0.0),
            db.residual_figure(_residual_stats()),
            db.solver_figure(solver),
        ]
        for fig in figs:
            validate_figure(fig.to_dict())
        # the two-axis solver panel really exercises the cross-reference
        # rule: a trace on y2 and a layout.yaxis2 with overlaying
        solver_dict = figs[-1].to_dict()
        assert any(t.get("yaxis") == "y2" for t in solver_dict["data"])
        assert solver_dict["layout"]["yaxis2"]["overlaying"] == "y"

    def test_unknown_trace_attribute_fails(self):
        with pytest.raises(SchemaError, match="unknown attribute"):
            validate_trace("scatter", {"lnie": {"color": "red"}})

    def test_unknown_nested_attribute_fails(self):
        with pytest.raises(SchemaError, match="unknown attribute"):
            validate_trace("scatter", {"line": {"colour": "red"}})

    def test_bad_mode_flag_fails(self):
        with pytest.raises(SchemaError, match="mode"):
            validate_trace("scatter", {"mode": "line"})

    def test_bad_color_fails(self):
        with pytest.raises(SchemaError, match="color"):
            validate_trace("scatter",
                           {"line": {"color": "rgba(0, 84, 159)"}})

    def test_bad_axis_reference_fails(self):
        with pytest.raises(SchemaError, match="axis reference"):
            validate_trace("scatter", {"yaxis": "y-2"})

    def test_unknown_layout_attribute_fails(self):
        with pytest.raises(SchemaError, match="unknown attribute"):
            validate_layout({"heigth": 320})

    def test_bad_axis_type_enum_fails(self):
        with pytest.raises(SchemaError, match="not one of"):
            validate_layout({"yaxis": {"type": "logarithmic"}})

    def test_dangling_axis_reference_fails(self):
        fig = {"data": [{"type": "scatter", "x": [0], "y": [1],
                         "yaxis": "y2"}],
               "layout": {"height": 320}}
        with pytest.raises(SchemaError, match="yaxis2"):
            validate_figure(fig)
