"""Unit tests for the interior-point NLP solver.

The reference has no direct solver tests (it trusts IPOPT); these cover the
replacement on problems with known optima, including vmap batching — the
property the whole multi-agent design rests on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.ops.solver import (
    NLPFunctions,
    SolverOptions,
    solve_nlp,
)

BIG = 1e6
OPTS = SolverOptions(tol=1e-6)


def _no_g(w, t):
    return jnp.zeros((0,))


def _no_h(w, t):
    return jnp.zeros((0,))


def test_active_box_bound():
    nlp = NLPFunctions(f=lambda w, t: jnp.sum((w - 1.0) ** 2), g=_no_g, h=_no_h)
    res = solve_nlp(nlp, jnp.array([5.0]), None, jnp.array([2.0]),
                    jnp.array([BIG]), OPTS)
    assert res.stats.success
    np.testing.assert_allclose(res.w, [2.0], atol=1e-6)


def test_equality_constrained_qp():
    nlp = NLPFunctions(
        f=lambda w, t: jnp.sum(w**2),
        g=lambda w, t: jnp.array([w[0] + w[1] - 1.0]),
        h=_no_h,
    )
    res = solve_nlp(nlp, jnp.array([3.0, -2.0]), None, -BIG * jnp.ones(2),
                    BIG * jnp.ones(2), OPTS)
    assert res.stats.success
    np.testing.assert_allclose(res.w, [0.5, 0.5], atol=1e-6)
    # KKT: gradient 2w = -y * [1,1] → y = -1
    np.testing.assert_allclose(res.y, [-1.0], atol=1e-5)


def test_hs071():
    """Hock-Schittkowski 71 — the canonical IPOPT example problem."""
    nlp = NLPFunctions(
        f=lambda w, t: w[0] * w[3] * (w[0] + w[1] + w[2]) + w[2],
        g=lambda w, t: jnp.array([jnp.sum(w**2) - 40.0]),
        h=lambda w, t: jnp.array([w[0] * w[1] * w[2] * w[3] - 25.0]),
    )
    res = solve_nlp(nlp, jnp.array([1.0, 5.0, 5.0, 1.0]), None,
                    jnp.ones(4), 5.0 * jnp.ones(4), OPTS)
    assert res.stats.success
    np.testing.assert_allclose(
        res.w, [1.0, 4.7429994, 3.8211503, 1.3794082], atol=1e-4)
    np.testing.assert_allclose(res.stats.objective, 17.0140173, atol=1e-4)


def test_inequality_constrained_rosenbrock():
    nlp = NLPFunctions(
        f=lambda w, t: (1 - w[0]) ** 2 + 100 * (w[1] - w[0] ** 2) ** 2,
        g=_no_g,
        h=lambda w, t: jnp.array([1.5 - w[0] ** 2 - w[1] ** 2]),
    )
    res = solve_nlp(nlp, jnp.array([-1.0, 1.0]), None, -BIG * jnp.ones(2),
                    BIG * jnp.ones(2), OPTS)
    assert res.stats.success
    # constraint active at optimum
    np.testing.assert_allclose(res.w[0] ** 2 + res.w[1] ** 2, 1.5, atol=1e-5)


def test_theta_parameterization():
    """The same compiled solver re-solves for new parameters without retrace."""
    nlp = NLPFunctions(
        f=lambda w, t: jnp.sum((w - t) ** 2), g=_no_g, h=_no_h)
    lb, ub = -BIG * jnp.ones(2), BIG * jnp.ones(2)
    r1 = solve_nlp(nlp, jnp.zeros(2), jnp.array([1.0, 2.0]), lb, ub, OPTS)
    r2 = solve_nlp(nlp, jnp.zeros(2), jnp.array([-3.0, 4.0]), lb, ub, OPTS)
    np.testing.assert_allclose(r1.w, [1.0, 2.0], atol=1e-6)
    np.testing.assert_allclose(r2.w, [-3.0, 4.0], atol=1e-6)


def test_vmap_batched_solve():
    """A batch of hs071 instances from different starts must all converge —
    the foundation of the vmapped per-agent ADMM solves."""
    nlp = NLPFunctions(
        f=lambda w, t: w[0] * w[3] * (w[0] + w[1] + w[2]) + w[2],
        g=lambda w, t: jnp.array([jnp.sum(w**2) - 40.0]),
        h=lambda w, t: jnp.array([w[0] * w[1] * w[2] * w[3] - 25.0]),
    )
    w0s = jnp.array([[1.0, 5.0, 5.0, 1.0], [2.0, 4.0, 4.0, 2.0],
                     [1.5, 4.5, 4.0, 1.2]])
    res = jax.vmap(
        lambda w0: solve_nlp(nlp, w0, None, jnp.ones(4), 5.0 * jnp.ones(4),
                             OPTS)
    )(w0s)
    assert bool(jnp.all(res.stats.success))
    np.testing.assert_allclose(res.stats.objective,
                               17.0140173 * jnp.ones(3), atol=1e-4)


def test_infeasible_start_recovers():
    nlp = NLPFunctions(
        f=lambda w, t: jnp.sum(w**2),
        g=_no_g,
        h=lambda w, t: jnp.array([w[0] + w[1] - 2.0]),  # w0+w1 >= 2
    )
    res = solve_nlp(nlp, jnp.array([-5.0, -5.0]), None, -BIG * jnp.ones(2),
                    BIG * jnp.ones(2), OPTS)
    assert res.stats.success
    np.testing.assert_allclose(res.w, [1.0, 1.0], atol=1e-5)


def test_stats_fields():
    nlp = NLPFunctions(f=lambda w, t: jnp.sum(w**2), g=_no_g, h=_no_h)
    res = solve_nlp(nlp, jnp.ones(3), None, -BIG * jnp.ones(3),
                    BIG * jnp.ones(3), OPTS)
    assert res.stats.iterations < OPTS.max_iter
    assert float(res.stats.kkt_error) <= OPTS.tol
    assert float(res.stats.constraint_violation) <= 1e-8


def test_corrector_option_converges_to_same_solution():
    """Mehrotra-style corrector (SolverOptions.corrector): same optimum,
    tighter feasibility, factorization reused for the second solve."""
    from agentlib_mpc_tpu.models.zoo import OneRoom
    from agentlib_mpc_tpu.ops.transcription import transcribe

    model = OneRoom(overrides={"s_T": 0.001, "r_mDot": 0.01})
    ocp = transcribe(model, ["mDot"], N=6, dt=300.0,
                     method="collocation", collocation_degree=2)
    theta = ocp.default_params(x0=jnp.array([297.8]))
    lb, ub = ocp.bounds(theta)
    w0 = ocp.initial_guess(theta)
    objs = {}
    for corr in (False, True):
        res = solve_nlp(ocp.nlp, w0, theta, lb, ub,
                        SolverOptions(tol=1e-6, max_iter=80,
                                      corrector=corr))
        assert bool(res.stats.success)
        objs[corr] = float(res.stats.objective)
    assert abs(objs[False] - objs[True]) <= 1e-4 * (1 + abs(objs[False]))


def test_traced_max_iter_matches_static_budget():
    """The traced max_iter override (the shared-trace budget knob used by
    the two-phase ADMM schemes) must behave exactly like the same static
    options.max_iter: identical iterate after an identical number of
    interior-point iterations."""
    nlp = NLPFunctions(
        f=lambda w, t: (1 - w[0]) ** 2 + 100 * (w[1] - w[0] ** 2) ** 2,
        g=_no_g, h=_no_h)
    w0 = jnp.array([-1.2, 1.0])
    lb, ub = -BIG * jnp.ones(2), BIG * jnp.ones(2)
    for budget in (3, 8):
        res_static = solve_nlp(nlp, w0, None, lb, ub,
                               OPTS._replace(max_iter=budget))
        res_traced = solve_nlp(nlp, w0, None, lb, ub, OPTS,
                               max_iter=jnp.asarray(budget))
        assert int(res_static.stats.iterations) == \
            int(res_traced.stats.iterations) == budget
        np.testing.assert_allclose(res_static.w, res_traced.w, rtol=0,
                                   atol=0)


def test_fused_linesearch_jacobian_matches_default():
    """fused_ls_jacobian="on" (the TPU latency path: Jacobians of all
    line-search candidates in the one batched call) must walk the exact
    same iterate sequence as the separate accepted-point evaluation."""
    nlp = NLPFunctions(
        f=lambda w, t: w[0] * w[3] * (w[0] + w[1] + w[2]) + w[2],
        g=lambda w, t: jnp.array([jnp.sum(w**2) - 40.0]),
        h=lambda w, t: jnp.array([w[0] * w[1] * w[2] * w[3] - 25.0]),
    )
    w0 = jnp.array([1.0, 5.0, 5.0, 1.0])
    lb, ub = jnp.ones(4), 5.0 * jnp.ones(4)
    res_off = solve_nlp(nlp, w0, None, lb, ub,
                        OPTS._replace(fused_ls_jacobian="off"))
    res_on = solve_nlp(nlp, w0, None, lb, ub,
                       OPTS._replace(fused_ls_jacobian="on"))
    assert bool(res_off.stats.success) and bool(res_on.stats.success)
    assert int(res_off.stats.iterations) == int(res_on.stats.iterations)
    np.testing.assert_allclose(res_off.w, res_on.w, atol=1e-9)
