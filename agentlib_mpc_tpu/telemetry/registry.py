"""Process-global metrics registry: counters, gauges, fixed-bucket histograms.

The observability spine of the framework (ISSUE 1): every layer — solver,
ADMM engines, backends, runtime broker, JAX compile hooks — writes into one
:class:`MetricsRegistry` instead of keeping private stats lists.  Design
constraints, in order:

1. **Near-zero disabled cost.**  Every write path starts with one attribute
   check (``registry._enabled``) and returns immediately when telemetry is
   off — no locks, no allocation.  Hot paths (broker message dispatch,
   per-solve recording) stay safe to instrument unconditionally.
2. **Label support without label explosions.**  Instruments are *families*
   (one name, one kind); samples are keyed by their label sets
   (``solver_failures_total{backend="JAXBackend"}``), Prometheus style.
3. **Exportable.**  :meth:`MetricsRegistry.prometheus_text` renders the
   Prometheus text exposition format (scrape-able / pushable);
   :meth:`MetricsRegistry.write_jsonl` writes one JSON document per family
   (the format ``bench.py --emit-metrics`` embeds into BENCH artifacts).

Per-process: agents running under ``MultiProcessingMAS`` each own their
process's default registry (export per process, aggregate downstream —
exactly how Prometheus treats multi-process targets).
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import IO, Iterable, Optional

#: default histogram buckets for latencies in seconds (power-of-~2.5 ladder
#: from 1 ms to 60 s — solver solves, ADMM rounds, broker dispatch all fit)
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: default buckets for iteration counts (interior-point iterations per
#: solve, ADMM iterations per round)
ITERATION_BUCKETS = (1.0, 2.0, 3.0, 5.0, 8.0, 12.0, 20.0, 30.0, 50.0, 100.0)


def _label_key(labels: dict) -> tuple:
    """Canonical hashable identity of a label set."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _format_value(value: float) -> str:
    """Prometheus sample rendering: integers without a trailing ``.0``."""
    if value != value:  # NaN
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _render_labels(key: tuple, extra: "tuple | None" = None) -> str:
    pairs = list(key) + list(extra or ())
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(str(v))}"'
                     for k, v in pairs)
    return "{" + inner + "}"


class _Bound:
    """A family bound to one label set — resolve labels once, write many."""

    __slots__ = ("_family", "_key")

    def __init__(self, family: "_MetricFamily", key: tuple):
        self._family = family
        self._key = key

    def inc(self, value: float = 1.0) -> None:
        self._family._write(self._key, value, mode="inc")

    def set(self, value: float) -> None:
        self._family._write(self._key, value, mode="set")

    def observe(self, value: float) -> None:
        self._family._write(self._key, value, mode="observe")


class _MetricFamily:
    kind = "untyped"
    #: write modes this kind accepts — a bound child calling a
    #: kind-inappropriate method (e.g. .set() on a Counter) must raise,
    #: not silently do the wrong thing
    _modes: frozenset = frozenset()

    def __init__(self, registry: "MetricsRegistry", name: str, help: str):
        self._registry = registry
        self.name = name
        self.help = help
        self._values: dict = {}

    # -- binding ---------------------------------------------------------------

    def labels(self, **labels) -> _Bound:
        return _Bound(self, _label_key(labels))

    # -- writes ----------------------------------------------------------------

    def _write(self, key: tuple, value: float, mode: str) -> None:
        reg = self._registry
        if not reg._enabled:          # the disabled-mode fast path
            return
        if mode not in self._modes:
            raise ValueError(
                f"metric {self.name!r} is a {self.kind}; it does not "
                f"support .{mode}()")
        with reg._lock:
            self._write_locked(key, float(value), mode)

    def _write_locked(self, key, value, mode):  # pragma: no cover - abstract
        raise NotImplementedError

    # -- reads -----------------------------------------------------------------

    def samples(self) -> list[dict]:
        """[{'labels': {...}, ...kind-specific fields}] snapshot."""
        with self._registry._lock:
            return [self._sample_dict(key, val)
                    for key, val in sorted(self._values.items())]

    def _sample_dict(self, key, val) -> dict:
        return {"labels": dict(key), "value": val}

    def remove(self, **labels) -> None:
        """Drop the sample for one label set (no-op when absent) — for
        families whose label sets can go stale, e.g. per-iteration gauges
        of a round that ran shorter than the previous one. Cleanup runs
        regardless of the enabled flag."""
        with self._registry._lock:
            self._values.pop(_label_key(labels), None)

    def value(self, **labels) -> Optional[float]:
        """Current scalar value for one label set (None if never written).
        Histograms return their observation count."""
        with self._registry._lock:
            val = self._values.get(_label_key(labels))
        if val is None:
            return None
        if isinstance(val, _HistogramState):
            return float(val.count)
        return float(val)

    def total(self) -> float:
        """Sum over all label sets (histograms: total observation count)."""
        with self._registry._lock:
            vals = list(self._values.values())
        return float(sum(v.count if isinstance(v, _HistogramState) else v
                         for v in vals))


class Counter(_MetricFamily):
    """Monotone counter (``*_total`` naming convention)."""

    kind = "counter"
    _modes = frozenset({"inc"})

    def inc(self, value: float = 1.0, **labels) -> None:
        self._write(_label_key(labels), value, "inc")

    def _write_locked(self, key, value, mode):
        if value < 0:
            raise ValueError(
                f"counter {self.name!r} cannot decrease (got {value})")
        self._values[key] = self._values.get(key, 0.0) + value


class Gauge(_MetricFamily):
    """Set-to-current-value instrument (residuals, queue depths, ρ)."""

    kind = "gauge"
    _modes = frozenset({"inc", "set"})

    def set(self, value: float, **labels) -> None:
        self._write(_label_key(labels), value, "set")

    def inc(self, value: float = 1.0, **labels) -> None:
        self._write(_label_key(labels), value, "inc")

    def _write_locked(self, key, value, mode):
        if mode == "inc":
            self._values[key] = self._values.get(key, 0.0) + value
        else:
            self._values[key] = value


class _HistogramState:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)   # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_MetricFamily):
    """Fixed-bucket histogram (cumulative buckets in exports, Prometheus
    semantics: ``le`` upper bounds, implicit ``+Inf``)."""

    kind = "histogram"
    _modes = frozenset({"observe"})

    def __init__(self, registry, name, help,
                 buckets: Iterable[float] = LATENCY_BUCKETS_S):
        super().__init__(registry, name, help)
        bks = tuple(sorted(float(b) for b in buckets))
        if not bks:
            raise ValueError(f"histogram {name!r} needs >= 1 bucket")
        self.buckets = bks

    def observe(self, value: float, **labels) -> None:
        self._write(_label_key(labels), value, "observe")

    def _write_locked(self, key, value, mode):
        st = self._values.get(key)
        if st is None:
            st = self._values[key] = _HistogramState(len(self.buckets))
        st.counts[bisect.bisect_left(self.buckets, value)] += 1
        st.sum += value
        st.count += 1

    def _sample_dict(self, key, st: _HistogramState) -> dict:
        cum, cumulative = 0, {}
        for b, c in zip(self.buckets, st.counts):
            cum += c
            cumulative[_format_value(b)] = cum
        cumulative["+Inf"] = st.count
        return {"labels": dict(key), "count": st.count, "sum": st.sum,
                "buckets": cumulative}


class MetricsRegistry:
    """A set of metric families. Most code uses the process-global
    :data:`DEFAULT` through :mod:`agentlib_mpc_tpu.telemetry`; tests and
    embedders can carry private instances."""

    def __init__(self, enabled: bool = True):
        self._lock = threading.RLock()
        self._families: dict[str, _MetricFamily] = {}
        self._enabled = bool(enabled)

    # -- enablement ------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: bool) -> None:
        self._enabled = bool(enabled)

    # -- declaration (idempotent) ----------------------------------------------

    def _declare(self, cls, name: str, help: str, **kwargs) -> _MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}, "
                        f"cannot re-register as {cls.kind}")
                # idempotent re-declaration: first declaration wins
                # (help text and histogram buckets included)
                return fam
            fam = cls(self, name, help or "", **kwargs)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "") -> Counter:
        return self._declare(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._declare(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = LATENCY_BUCKETS_S) -> Histogram:
        return self._declare(Histogram, name, help, buckets=buckets)

    # -- reads -----------------------------------------------------------------

    def get(self, name: str, **labels) -> Optional[float]:
        """Scalar lookup convenience (None for unknown metric / label set)."""
        fam = self._families.get(name)
        return None if fam is None else fam.value(**labels)

    def families(self) -> list[_MetricFamily]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def snapshot(self) -> list[dict]:
        """JSON-ready export: one dict per family, samples sorted by label
        set — the payload of ``bench.py --emit-metrics``."""
        return [{"name": fam.name, "kind": fam.kind, "help": fam.help,
                 "samples": fam.samples(), "total": fam.total()}
                for fam in self.families()]

    # -- exports ---------------------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (v0.0.4), deterministically
        ordered (family name, then label set) so it can be golden-tested."""
        out: list[str] = []
        for fam in self.families():
            out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for sample in fam.samples():
                key = _label_key(sample["labels"])
                if fam.kind == "histogram":
                    for le, cum in sample["buckets"].items():
                        out.append(
                            f"{fam.name}_bucket"
                            f"{_render_labels(key, (('le', le),))} {cum}")
                    out.append(f"{fam.name}_sum{_render_labels(key)} "
                               f"{_format_value(sample['sum'])}")
                    out.append(f"{fam.name}_count{_render_labels(key)} "
                               f"{sample['count']}")
                else:
                    out.append(f"{fam.name}{_render_labels(key)} "
                               f"{_format_value(sample['value'])}")
        return "\n".join(out) + ("\n" if out else "")

    def write_jsonl(self, path_or_file: "str | IO[str]") -> None:
        """One JSON document per family, one per line (append-friendly,
        ``jq``-friendly)."""
        if hasattr(path_or_file, "write"):
            for fam_dict in self.snapshot():
                path_or_file.write(json.dumps(fam_dict) + "\n")
            return
        with open(path_or_file, "w", encoding="utf-8") as fh:
            self.write_jsonl(fh)

    # -- lifecycle -------------------------------------------------------------

    def reset(self) -> None:
        """Clear all sample values; declared families survive (so exports
        keep showing zero-valued families — dashboards and the bench
        artifact rely on presence, not just non-zero values)."""
        with self._lock:
            for fam in self._families.values():
                fam._values.clear()


#: the process-global registry every built-in instrumentation site uses
DEFAULT = MetricsRegistry(enabled=True)
