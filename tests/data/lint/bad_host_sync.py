"""Golden-file fixture: host syncs and tracer branches inside jit.

Every construct below is a known-bad pattern the jit-hygiene passes must
flag — the test asserts the exact finding fingerprints.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_step(x, y):
    s = jnp.sum(x)
    v = float(s)                  # host sync: float() on a tracer
    print("solving")              # trace-time print
    w = s.item()                  # host sync: .item()
    arr = np.asarray(s)           # numpy pulls the tracer to host
    if s > 0:                     # Python branch on a tracer
        y = y + 1.0
    t0 = time.time()              # baked in as a trace-time constant
    return y + v + w + arr.sum() + t0


def helper(a):
    # reachable from bad_step? no — but reachable from jitted caller below
    return float(jnp.max(a))      # host sync in a jit-reachable helper


@jax.jit
def calls_helper(x):
    return helper(x * 2.0)
