"""Durable control-state checkpointing (orbax).

The reference has NO checkpoint/resume for process state — warm starts
live in memory and die with the process (SURVEY §5: "Checkpoint/resume:
none for process state"; its only durable artifacts are results CSVs
and serialized ML models). For long-running building fleets that is a
real gap: a controller restart loses every warm start, dual variable
and consensus state, and the next control step pays cold-start
iteration counts under a real-time deadline.

Here the whole control state is a pytree by construction (JAX), so
checkpointing is one orbax call. :class:`~agentlib_mpc_tpu.parallel.
config_bridge.FusedFleet` wires these into ``save_checkpoint`` /
``restore_checkpoint``; for hand-built :class:`FusedADMM` states (also
NamedTuple pytrees) call :func:`save_pytree` / :func:`load_pytree`
directly with the state as its own template.
"""

from __future__ import annotations

import os
import shutil
from typing import Any

__all__ = ["save_pytree", "load_pytree", "has_checkpoint"]


def _stale_siblings(path: str) -> list:
    import glob

    return sorted(glob.glob(f"{path}.tmp-*") + glob.glob(f"{path}.old-*"),
                  key=os.path.getmtime)


#: files orbax writes only once a checkpoint is fully committed — their
#: presence separates a complete checkpoint directory from the husk a
#: save killed mid-write leaves behind
_COMMIT_MARKERS = ("_CHECKPOINT_METADATA", "_METADATA", "checkpoint")


def _looks_complete(path: str) -> bool:
    return any(os.path.exists(os.path.join(path, m))
               for m in _COMMIT_MARKERS)


def has_checkpoint(path: str) -> bool:
    """True when :func:`load_pytree` has something COMPLETE to try at
    ``path``: the primary checkpoint directory or a crash-recovery
    sibling (``.old-*`` / ``.tmp-*``) carrying orbax's commit marker.
    The restore-on-construct guard used by ``BaseMPC``'s
    auto-checkpointing (``checkpoint_path`` config) — a fresh
    deployment with no checkpoint yet, or one whose ONLY artifact is a
    half-written temp dir from a save killed mid-write, must start cold
    instead of raising."""
    path = os.path.abspath(path)
    if os.path.isdir(path) and _looks_complete(path):
        return True
    return any(_looks_complete(s) for s in _stale_siblings(path))


def save_pytree(path: str, tree: Any) -> str:
    """Write a pytree of arrays/scalars to ``path`` (a directory),
    replacing any existing checkpoint crash-safely: the new checkpoint
    is fully written to a sibling temp directory first, then swapped in
    via the previous one being parked at ``<path>.old-*``. POSIX cannot
    atomically replace directories, so a kill in the tiny window between
    the two renames leaves the previous checkpoint at ``.old-*`` —
    :func:`load_pytree` falls back to the newest such sibling, so SOME
    valid checkpoint is always recoverable (that is the feature's whole
    purpose). Stale siblings from earlier crashed saves (any pid) are
    cleaned up on the next successful save.

    Returns the absolute path."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(tmp, tree)
    ckptr.wait_until_finished()
    if os.path.isdir(path):
        old = f"{path}.old-{os.getpid()}"
        if os.path.isdir(old):
            # leftover from an earlier save of this same pid that crashed
            # between the swap renames (pid reuse is the norm in
            # containers, where the controller is always e.g. pid 1)
            shutil.rmtree(old)
        os.rename(path, old)
        os.rename(tmp, path)
    else:
        os.rename(tmp, path)
    # the new checkpoint is in place: drop every leftover sibling,
    # including tmp/old dirs leaked by crashed saves under other pids
    for stale in _stale_siblings(path):
        shutil.rmtree(stale, ignore_errors=True)
    return path


def _leaf_signature(tree) -> list:
    """Order-insensitive (shape, dtype) multiset of a pytree's leaves —
    comparable between a template and orbax's stored ArrayMetadata tree
    even though the two flatten in different container orders."""
    import jax
    import numpy as np

    sig = []
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(leaf.shape), str(np.dtype(leaf.dtype))))
        else:
            arr = np.asarray(leaf)
            sig.append((tuple(arr.shape), str(arr.dtype)))
    return sorted(sig)


def _assert_compatible(ckptr, path: str, template) -> None:
    """Reject a structurally mismatched restore BEFORE orbax touches it:
    newer orbax versions (>= 0.7) silently RESHAPE stored arrays into
    the requested abstract shapes, so restoring e.g. a 3-agent fleet's
    checkpoint into a 4-agent fleet would fabricate state instead of
    failing — the exact corruption a checkpoint exists to prevent."""
    try:
        meta = ckptr.metadata(path)
    except Exception:  # noqa: BLE001 - no metadata (older orbax):
        return         # the restore itself validates structure then
    stored = _leaf_signature(meta)
    expected = _leaf_signature(template)
    if stored != expected:
        raise ValueError(
            f"checkpoint at {path} is not compatible with the template: "
            f"stored leaves {stored} != template leaves {expected} — "
            f"restore into a fleet/backend built from the same config")


def load_pytree(path: str, template: Any) -> Any:
    """Restore a pytree written by :func:`save_pytree`.

    ``template`` supplies the tree structure, container types (incl.
    NamedTuples) and array shapes/dtypes — pass a freshly-initialized
    state of the same problem; its VALUES are ignored. A checkpoint
    whose stored leaves do not match the template's shapes/dtypes is
    rejected with ``ValueError`` (see :func:`_assert_compatible`).

    If ``path`` is missing (a save was killed between its two swap
    renames), the ``<path>.old-*``/``.tmp-*`` siblings are tried newest
    first — a ``.tmp-*`` from a save killed *during* the orbax write is
    incomplete and must not shadow the complete ``.old-*`` next to it,
    so a sibling that fails to restore falls through to the next."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    ckptr = ocp.StandardCheckpointer()
    if os.path.isdir(path):
        _assert_compatible(ckptr, path, abstract)
        return ckptr.restore(path, abstract)
    candidates = _stale_siblings(path)
    if not candidates:
        raise FileNotFoundError(f"no checkpoint at {path}")
    errors = []
    last_exc = None
    for candidate in reversed(candidates):
        try:
            _assert_compatible(ckptr, candidate, abstract)
            return ckptr.restore(candidate, abstract)
        except Exception as exc:  # partial .tmp-* etc. — try the next
            errors.append(f"{candidate}: {exc}")
            last_exc = exc
    # NOT FileNotFoundError: checkpoint data exists but none of it
    # restored (corruption, or e.g. a template mismatch after a config
    # change) — a caller treating "no checkpoint" as cold-start must not
    # silently discard recoverable state
    raise RuntimeError(
        f"checkpoint at {path} is missing its primary directory and "
        f"every crash-recovery sibling failed to restore: "
        f"{'; '.join(errors)}") from last_exc
